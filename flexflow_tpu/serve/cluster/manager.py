"""ClusterManager — one process driving N engine replicas.

The cluster front-end: RequestManager-shaped API (``submit`` /
``step`` / ``drain`` / ``generate`` / ``generate_stream`` / ``result``)
over a pool of :class:`Replica` (each its own engine, mesh and KV pool)
behind a :class:`Router`. The manager owns cluster-level request
identity (cluster ids are independent of any replica's guids), the
per-step drive loop over every replica's scheduler, and — under
disaggregation — the prefill→decode page migrations.

Request lifecycle::

    submit ──router──┬── shed / all DOWN ──→ ERROR (terminal, PR-2 contract)
                     ├── mixed replica ─────→ prefill+decode there ("single")
                     └── prefill replica ───→ prefill, max_new_tokens=1
                             │ held slot        ("prefill")
                             └─ COMPLETED → migration queue → decode replica
                                             adopts into DECODING ("decode")

Sheds come from SLO admission (``ServingConfig.slo_queue_delay_s``):
they surface as ``GenerationResult.error`` exactly like the PR-2
unservable-request path — a shed request is terminal the moment it is
submitted and can never hang a ``generate()``/stream/C-host loop.

**Fault tolerance** (serve/cluster/health.py): every replica step runs
under the health monitor — a step exception or sustained latency spike
demotes the replica (HEALTHY → SUSPECT → DOWN), and a DOWN replica's
circuit opens: it leaves ``Router.route`` scoring, its session
affinities drop (they re-pin on survivors, which also re-seeds its
prefix families there), and every request it held is RE-ADMITTED to a
healthy replica through the recompute path — prompt + tokens generated
so far resubmit as a prompt, exactly the vLLM-style preemption recompute
the scheduler already runs, so greedy generations stay bitwise the
fault-free run's. Retries are bounded (``ServingConfig.failover_retries``
with exponential cluster-step backoff); when they exhaust, or no healthy
replica remains, the request turns into a terminal
``GenerationResult.error`` — never a hang. After an exponential backoff
the breaker half-opens (PROBING) and routed traffic is the probe.

**Migration back-pressure** (``ServingConfig.migration_queue_budget``):
finished prefills waiting for decode-pool capacity sit in a bounded
FIFO. Within budget they wait holding their pages (the cheap page
hand-off); past it they release the pages immediately and drain through
recompute re-admission on the decode pool's own pending queue — a full
decode pool costs recompute, not unbounded held slots on the prefill
pool. Degraded pools fall back: a dead decode pool means the surviving
pool serves both phases (recompute re-admission in place of page
migration); a dead prefill pool routes new requests single-phase onto
the decode pool.

With ``replicas=1`` and no faults the manager routes everything to
replica 0 and the replica runs the bit-for-bit single-engine scheduler —
the router adds bookkeeping, never a different step sequence (asserted
bitwise in tests/test_cluster.py).
"""
from __future__ import annotations

import dataclasses
import time
from typing import Any, Dict, Iterator, List, Optional, Sequence, Set, Union

from ...logging_utils import get_logger
from ...metrics import ClusterStats
from ...obs.tracer import NULL_TRACER
from ..batch_config import (
    GenerationConfig,
    GenerationResult,
    ProfileInfo,
    StreamEvent,
)
from ..engine import ServingConfig
from ..request_manager import TERMINAL_STATUSES, RequestStatus
from .health import HealthConfig, HealthMonitor, HealthState, ReplicaHealth
from .migration import migrate_request
from .remote import HeartbeatGap, RemoteReplica
from .replica import Replica
from .router import Router
from .transport import LoopbackTransport, SocketTransport


@dataclasses.dataclass
class ClusterRequest:
    """Cluster-level view of one request: where it lives now (replica
    position + replica-local rid) and which phase of the disaggregated
    lifecycle it is in. ``rid is None`` means the request is not on any
    replica right now: shed / terminally failed (``error`` set) or
    between homes awaiting a failover re-admission (``error`` None)."""

    cluster_id: int
    tokens: List[int]
    prompt_text: str
    gen: GenerationConfig
    session_id: Optional[object] = None
    replica: Optional[int] = None       # position into manager.replicas
    rid: Optional[int] = None           # replica-local request id
    phase: str = "single"               # "single" | "prefill" | "decode"
    error: Optional[str] = None         # terminal failure (shed/failover)
    profile: ProfileInfo = dataclasses.field(default_factory=ProfileInfo)
    # ORIGINAL prompt length (the output-token baseline): a failover
    # re-admission's home sees prompt+generated as its prompt, so the
    # home's prompt_len stops being the boundary — this one always is.
    prompt_len: int = 0
    retries: int = 0                    # re-admissions so far
    mig_attempts: int = 0               # failed page-migration attempts

    _manager: Any = dataclasses.field(default=None, repr=False)
    # prompt + flushed generated tokens captured when the home replica
    # went DOWN — the recompute re-admission's submission (and the
    # partial output while between homes)
    _known: Optional[List[int]] = dataclasses.field(default=None, repr=False)
    _retry_at_step: int = 0             # failover/migration backoff gate

    @property
    def status(self) -> RequestStatus:
        """RequestStatus-shaped view (c_backend drives clusters through
        the same loop it drives a bare RequestManager with)."""
        if self.rid is None:
            # shed / failed = terminal; between homes (failover pending)
            # = PENDING, so nothing treats an in-flight recovery as done
            return RequestStatus.ERROR if self.error else RequestStatus.PENDING
        home = self._manager.replicas[self.replica].rm
        st = home.requests[self.rid].status
        if self.phase == "prefill" and st in TERMINAL_STATUSES:
            # completed ON THE PREFILL POOL means "awaiting migration",
            # not done — unless the manager decided it finished there
            return (
                st if st is RequestStatus.ERROR
                else RequestStatus.DECODING
            )
        return st

    @property
    def output_tokens(self) -> List[int]:
        if self.rid is None:
            if self._known:
                return list(self._known[self.prompt_len:])
            return []
        home = self._manager.replicas[self.replica].rm
        # slice at the ORIGINAL prompt boundary: a failover home's own
        # prompt_len includes carried-over generated tokens
        return home.requests[self.rid].tokens[self.prompt_len:]


class ClusterManager:
    """Drive ``replicas`` behind a router (see module docstring)."""

    def __init__(
        self,
        replicas: Sequence[Replica],
        serving: ServingConfig,
        *,
        router: Optional[Router] = None,
        tokenizer: Any = None,
        eos_token_id: Optional[int] = None,
        health_config: Optional[HealthConfig] = None,
        standbys: Sequence[Replica] = (),
    ):
        serving.validate_cluster()
        if len(replicas) != serving.replicas:
            raise ValueError(
                f"ServingConfig.replicas={serving.replicas} but "
                f"{len(replicas)} replicas were built"
            )
        if len(standbys) != serving.standby_replicas:
            raise ValueError(
                f"ServingConfig.standby_replicas="
                f"{serving.standby_replicas} but {len(standbys)} "
                "standbys were built"
            )
        self.serving = serving
        self.replicas = list(replicas)
        # warm standbys: pre-built engines OUTSIDE routing; on a DOWN
        # transition one adopts the dead replica's position (+ its
        # prefix families over the transport) — see _adopt_standby
        self.standbys = list(standbys)
        self._retired: List[Replica] = []   # replaced dead replicas
        self.tokenizer = tokenizer
        self.eos_token_id = eos_token_id
        if eos_token_id is None and tokenizer is not None:
            self.eos_token_id = getattr(tokenizer, "eos_token_id", None)
        self.stats = ClusterStats()
        for rep in list(self.replicas) + self.standbys:
            if getattr(rep, "is_remote", False):
                rep.bind_stats(lambda: self.stats)
        self.health = HealthMonitor(len(self.replicas), health_config)
        self.fault_injector = None
        # replica positions already observed failing THIS cluster step
        # (the one-SUSPECT-observation-per-step guard: a replica that is
        # simultaneously in a heartbeat gap and returning RPC errors is
        # observed once, preserving the PR-9 threshold arithmetic)
        self._failed_obs: Set[int] = set()
        self.prefill_pool = [r for r in self.replicas if r.role == "prefill"]
        self.decode_pool = [r for r in self.replicas if r.role == "decode"]
        self.disaggregated = bool(self.prefill_pool)
        if self.disaggregated and not self.decode_pool:
            raise ValueError("prefill pool without a decode pool")
        routing = self.prefill_pool if self.disaggregated else self.replicas
        # router positions index the ROUTING pool; map back to cluster
        # positions so ClusterRequest.replica is always cluster-wide
        self._routing_pos = [self.replicas.index(r) for r in routing]
        health_cb = (
            lambda pos: self.health[self._routing_pos[pos]].routable
        )
        self.router = router or Router(
            routing,
            serving.router_policy,
            slo_queue_delay_s=serving.slo_queue_delay_s,
            stats=lambda: self.stats,
            health=health_cb,
        )
        if router is not None and self.router.health is None:
            self.router.health = health_cb
        self.requests: Dict[int, ClusterRequest] = {}
        self._next_cid = 1
        self._step_counter = 0
        # failover re-admissions pending their backoff (cluster ids)
        self._failovers: List[int] = []
        # finished prefills awaiting decode-pool capacity (cluster ids,
        # FIFO; bounded by ServingConfig.migration_queue_budget)
        self._migration_queue: List[int] = []
        self._mig_queued: Set[int] = set()
        self._log = get_logger("serve")
        # Observability (flexflow_tpu/obs): the router/manager lane of
        # the cluster timeline (placements, migrations, failovers,
        # health transitions, heartbeat gaps) plus the failure flight
        # recorder's dump triggers. NULL_TRACER/None by default — the
        # drive loop pays one attribute read per guarded site;
        # obs.attach_observability wires live ones in.
        self.tracer = NULL_TRACER
        self.flight_recorder = None

    # ------------------------------------------------------------------
    # construction

    @classmethod
    def build(
        cls,
        model: Any,
        cfg: Any,
        params: Any,
        serving: Optional[ServingConfig] = None,
        *,
        tokenizer: Any = None,
        eos_token_id: Optional[int] = None,
        seed: int = 0,
        devices: Optional[Sequence[Any]] = None,
        health_config: Optional[HealthConfig] = None,
        ssms: Sequence[Any] = (),
        spec: Any = None,
    ) -> "ClusterManager":
        """Build ``serving.replicas`` in-process replicas — params
        shared by reference, each replica with its own mesh over a
        device picked round-robin from ``devices`` (all of them on a
        1-device host: independent engines on one chip is the
        in-process cluster this PR ships; per-host processes slot in
        behind the same Replica surface later).

        ``ssms`` ((model, cfg, params) triples) + ``spec`` turn every
        replica into a SpecInfer pair: per-replica SSM MIRRORS — each
        replica builds its own draft engines on its own mesh (draft
        params shared by reference, like the target's), so speculation
        scales out with the pool. Disaggregated prefill/decode pools
        reject the combination at ``validate_cluster``."""
        serving = serving or ServingConfig()
        serving.validate_cluster(
            specinfer=bool(ssms)
            or getattr(spec, "draft", "ssm") == "early_exit"
        )
        import jax

        devs = list(devices or jax.devices())
        roles = ["mixed"] * serving.replicas
        if serving.prefill_replicas:
            roles = (
                ["prefill"] * serving.prefill_replicas
                + ["decode"] * serving.decode_replicas
            )
        roles += ["mixed"] * serving.standby_replicas

        def make(i):
            """One replica (or standby) behind the configured
            transport. "loopback" wraps the SAME in-process build in a
            RemoteReplica whose every call round-trips the wire codec
            against a ReplicaServerCore; "socket" dials a subprocess
            replica server instead of building anything locally."""
            if serving.replica_transport == "socket":
                host, _, port = serving.replica_endpoints[i].rpartition(":")
                return RemoteReplica(
                    i, SocketTransport(host or "127.0.0.1", int(port)),
                    serving, role=roles[i],
                )
            local = Replica.build(
                i, model, cfg, params, serving,
                role=roles[i],
                devices=[devs[i % len(devs)]],
                tokenizer=tokenizer,
                eos_token_id=eos_token_id,
                seed=seed,
                ssms=ssms,
                spec=spec,
            )
            if serving.replica_transport == "inproc":
                return local
            from .server import ReplicaServerCore

            return RemoteReplica(
                i, LoopbackTransport(ReplicaServerCore(local).dispatch),
                serving, role=roles[i], local=local,
            )

        replicas = [make(i) for i in range(serving.replicas)]
        standbys = [
            make(serving.replicas + j)
            for j in range(serving.standby_replicas)
        ]
        return cls(
            replicas, serving, tokenizer=tokenizer,
            eos_token_id=eos_token_id, health_config=health_config,
            standbys=standbys,
        )

    def attach_faults(self, plan):
        """Wire a :class:`~.faults.FaultPlan` (or a prebuilt injector,
        or its JSON) into every replica (standbys included) and the
        migration path. Transport fault kinds (drop/delay/disconnect/
        partition) are injected AT the RPC transport, which in-process
        replicas do not have — aiming them at an ``inproc`` cluster is
        a loud error, not a silent no-op. Returns the
        :class:`~.faults.FaultInjector` for ``fired``/``release_all``."""
        from .faults import TRANSPORT_KINDS, FaultInjector, FaultPlan

        if isinstance(plan, str):
            plan = FaultPlan.from_json(plan)
        injector = plan if isinstance(plan, FaultInjector) else (
            FaultInjector(plan)
        )
        transport_faults = [
            f.kind for f in injector.plan if f.kind in TRANSPORT_KINDS
        ]
        if transport_faults and self.serving.replica_transport == "inproc":
            raise ValueError(
                f"fault plan contains transport kinds {transport_faults} "
                "but this cluster drives IN-PROCESS replicas "
                "(replica_transport='inproc') — transport faults are "
                "injected at the RPC layer; run with "
                "replica_transport='loopback' (or 'socket') to exercise "
                "them"
            )
        if self.serving.replica_transport == "socket" and any(
            f.kind == "oom" for f in injector.plan
        ):
            raise ValueError(
                "the 'oom' fault kind squeezes the replica's page pool "
                "in-process, which a socket-backed replica does not "
                "expose — use loopback replicas for oom scenarios"
            )
        self.fault_injector = injector
        for rep in list(self.replicas) + self.standbys:
            rep.fault_injector = injector
        return injector

    # ------------------------------------------------------------------
    # submission + placement

    def _tokenize(self, prompt: Union[str, Sequence[int]]):
        if isinstance(prompt, str):
            if self.tokenizer is None:
                raise ValueError("string prompt requires a tokenizer")
            return list(self.tokenizer.encode(prompt)), prompt
        return [int(t) for t in prompt], ""

    def _routable_rep(self, rep: Replica) -> bool:
        return self.health[self.replicas.index(rep)].routable

    def submit(
        self,
        prompt: Union[str, Sequence[int]],
        gen: Optional[GenerationConfig] = None,
        max_new_tokens: Optional[int] = None,
        session_id: Optional[object] = None,
    ) -> int:
        """Route + queue one request; returns its CLUSTER id
        immediately (non-blocking — drive with :meth:`step` or a
        concurrent :meth:`generate`/:meth:`generate_stream`). A shed
        (or no-healthy-replica) request is terminal on return
        (``result`` carries the error)."""
        gen = gen or GenerationConfig()
        if max_new_tokens is not None:
            gen = dataclasses.replace(gen, max_new_tokens=max_new_tokens)
        tokens, text = self._tokenize(prompt)
        cid = self._next_cid
        self._next_cid += 1
        self.stats.submitted += 1
        cr = ClusterRequest(
            cluster_id=cid, tokens=tokens, prompt_text=text, gen=gen,
            session_id=session_id, prompt_len=len(tokens), _manager=self,
        )
        self.requests[cid] = cr
        self._place(cr, tokens)
        return cid

    def _place_failed(self, cr: ClusterRequest, how: str) -> bool:
        cr.rid = None
        cr.replica = None
        if how == "shed":
            cr.error = (
                "shed by SLO admission: every replica's queue-delay "
                f"estimate exceeds slo_queue_delay_s="
                f"{self.serving.slo_queue_delay_s}"
            )
        else:  # "down"
            cr.error = (
                "no healthy replica: every replica is circuit-broken "
                "(DOWN) — the request fails terminally instead of "
                "waiting for a probe that may never succeed"
            )
        tr = self.tracer
        if tr.enabled:
            tr.event("place_failed", trace_id=cr.cluster_id, how=how)
        if self.flight_recorder is not None:
            self.flight_recorder.dump(
                self.tracer.lane or "router", "request_error",
                step=self._step_counter,
                extra={"cluster_id": cr.cluster_id, "how": how},
            )
        return False

    def _place(
        self,
        cr: ClusterRequest,
        known: Sequence[int],
        *,
        ignore_slo: bool = False,
    ) -> bool:
        """Route ``known`` (the prompt, or prompt + tokens generated so
        far on a failover re-admission) and submit it to the chosen
        replica. Returns True when placed; False means TERMINAL — shed,
        or no healthy replica (``cr.error`` set). Failover
        re-admissions pass ``ignore_slo=True``: a request admitted once
        is never shed on its second landing."""
        produced = max(0, len(known) - cr.prompt_len)
        remaining = cr.gen.max_new_tokens - produced
        gen_home = (
            cr.gen if produced == 0
            else dataclasses.replace(cr.gen, max_new_tokens=remaining)
        )
        first = cr.retries == 0
        phase = "single"
        if self.disaggregated and any(
            self._routable_rep(r) for r in self.prefill_pool
        ):
            pos, how = self.router.route(
                known, cr.session_id, ignore_slo=ignore_slo
            )
            if pos is None:
                return self._place_failed(cr, how)
            rep = self.replicas[self._routing_pos[pos]]
            if any(self._routable_rep(r) for r in self.decode_pool):
                phase = "prefill"
            else:
                # decode pool entirely DOWN: non-disaggregated serving
                # on the surviving prefill pool — the chosen replica
                # runs BOTH phases (no hold, no doomed migration)
                self._log.warning(
                    "decode pool is DOWN — request %d served "
                    "single-phase on prefill replica %d",
                    cr.cluster_id, rep.index,
                )
        elif self.disaggregated:
            # prefill pool entirely DOWN: fall back to non-disaggregated
            # serving on the surviving decode pool (ROADMAP'd degrade —
            # the decode replicas prefill too rather than refuse traffic)
            cands = [r for r in self.decode_pool if self._routable_rep(r)]
            if not cands:
                return self._place_failed(cr, "down")
            rep = min(
                cands,
                key=lambda r: (r.queue_delay_s(), r.load(), r.index),
            )
            self.stats.record_placement("pool_fallback")
            self._log.warning(
                "prefill pool is DOWN — request %d served single-phase "
                "on decode replica %d", cr.cluster_id, rep.index,
            )
        else:
            pos, how = self.router.route(
                known, cr.session_id, ignore_slo=ignore_slo
            )
            if pos is None:
                return self._place_failed(cr, how)
            rep = self.replicas[self._routing_pos[pos]]
        delay = rep.queue_delay_s()
        cr.replica = self.replicas.index(rep)
        cr.phase = phase
        if phase == "prefill":
            # prefill pass only: max_new_tokens=1 makes the prefill-final
            # dispatch (which samples the first output token on device)
            # the request's LAST step there — the chunked-prefill
            # boundary — and the held slot keeps its pages alive for
            # the migration that follows
            cr.rid = rep.rm.submit(
                known, dataclasses.replace(gen_home, max_new_tokens=1),
                trace_id=cr.cluster_id,
            )
            rep.rm.hold_on_finish(cr.rid)
        else:
            cr.rid = rep.rm.submit(known, gen_home,
                                   trace_id=cr.cluster_id)
        req = rep.rm.requests[cr.rid]
        if first:
            req.profile.replica_id = rep.index
            req.profile.router_queue_delay_s = delay
            cr.profile = req.profile
            # the home may have truncated an over-long prompt — its
            # prompt_len is the authoritative output boundary
            cr.prompt_len = req.prompt_len
        else:
            # re-admission: keep the ORIGINAL profile (start time, TTFT)
            # on the new home and record the move on it
            req.profile = cr.profile
            cr.profile.retries = cr.retries
            cr.profile.failover_replica_id = rep.index
            cr.profile.replica_id = rep.index
            cr.profile.router_queue_delay_s = delay
        cr._known = None
        tr = self.tracer
        if tr.enabled:
            tr.event(
                "place", trace_id=cr.cluster_id, replica=rep.index,
                phase=phase, retries=cr.retries,
            )
        return True

    # convenience alias (c_backend drives both manager kinds identically)
    def register_request(
        self,
        prompt: Union[str, Sequence[int]],
        gen: Optional[GenerationConfig] = None,
    ) -> int:
        return self.submit(prompt, gen)

    # ------------------------------------------------------------------
    # fault handling: health transitions + failover re-admission

    def _note_transition(self, pos: int, transition: Optional[str],
                         exc: Optional[BaseException] = None) -> None:
        if transition is None:
            return
        rep = self.replicas[pos]
        tr = self.tracer
        if tr.enabled:
            # health transitions land on the AFFECTED replica's lane so
            # a flight-recorder dump of that lane ends with them
            tr.event(
                "health", lane=f"replica{rep.index}", replica=rep.index,
                state=transition,
                error=str(self.health[pos].last_error or "")[:200],
            )
        if transition == "suspect":
            self.stats.replica_suspect += 1
            self._log.warning(
                "replica %d SUSPECT: %s", rep.index,
                self.health[pos].last_error,
            )
        elif transition == "recovered":
            self.stats.replica_recoveries += 1
            self._log.warning("replica %d recovered (circuit closed)",
                              rep.index)
        elif transition == "down":
            self.stats.replica_down += 1
            # capture the machine's recorded trip BEFORE failover runs
            # (_adopt_standby may replace the health record)
            down_at = self.health[pos].down_at_step
            self._on_replica_down(pos, exc)
            if self.flight_recorder is not None:
                self.flight_recorder.dump(
                    f"replica{rep.index}", "replica_down",
                    step=self._step_counter,
                    extra={
                        "replica_index": rep.index,
                        "health_state": HealthState.DOWN.value,
                        "down_at_step": down_at,
                    },
                )

    def _on_replica_down(self, pos: int,
                         exc: Optional[BaseException]) -> None:
        """The breaker opened: fail every request on the replica over
        to survivors (recompute re-admission), drop its session pins
        (they re-pin — which also re-seeds its prefix families on
        survivors), and tear its scheduler state down so a later probe
        re-admission starts clean."""
        rep = self.replicas[pos]
        self._log.warning(
            "replica %d DOWN (%s) — failing over its requests",
            rep.index, exc if exc is not None else
            self.health[pos].last_error,
        )
        try:
            rpos = self.router.replicas.index(rep)
        except ValueError:
            rpos = None  # decode-pool replica: not in the routing pool
        if rpos is not None:
            dropped = self.router.drop_replica_sessions(rpos)
            if dropped:
                self._log.debug(
                    "replica %d: %d session affinities dropped "
                    "(re-pin on survivors)", rep.index, dropped,
                )
        victims = [
            cr for cr in self.requests.values()
            if cr.rid is not None and cr.replica == pos
            and cr.status not in TERMINAL_STATUSES
        ]
        for cr in victims:
            req = rep.rm.requests[cr.rid]
            # the host token list only ever holds FLUSHED truth — the
            # recompute re-admission regenerates anything in flight
            cr._known = list(req.tokens)
            cr.rid = None
            cr.replica = None
            cr.phase = "single"
            self._schedule_failover(cr)
        # queued migrations whose source died are failover victims now
        self._migration_queue = [
            c for c in self._migration_queue
            if self.requests[c].rid is not None
        ]
        self._mig_queued = set(self._migration_queue)
        try:
            rep.abandon()
        except Exception as abandon_exc:  # the pool may be torn mid-step
            self._log.warning(
                "replica %d abandon() failed (%s) — its pool is "
                "excluded from audits until it recovers",
                rep.index, abandon_exc,
            )
        if self.standbys:
            self._adopt_standby(pos)

    def _adopt_standby(self, pos: int) -> None:
        """A warm standby takes the dead replica's routing position:
        the dead replica's prefix radix tree — block keys + page bytes,
        host-spilled pages included — ships over the transport and
        re-admits on the standby (best-effort: an unreachable process
        means a COLD join, capacity is still replaced), then the
        standby enters routing at ``pos``. The dead replica retires
        permanently (its health record is replaced by the standby's
        fresh one, so it never probes back) — failover re-admissions
        and re-pinned sessions land on a warm tree instead of survivors
        re-seeding the families cold."""
        dead = self.replicas[pos]
        standby = self.standbys.pop(0)
        blocks = 0
        try:
            entries = dead.export_prefix_tree()
            if entries:
                blocks = standby.import_prefix_tree(entries)
        except Exception as exc:
            self._log.warning(
                "standby adoption: prefix-tree export from dead replica "
                "%d failed (%s) — standby %d joins COLD",
                dead.index, exc, standby.index,
            )
        self.replicas[pos] = standby
        try:
            rpos = self._routing_pos.index(pos)
        except ValueError:
            rpos = None
        if rpos is not None:
            self.router.replicas[rpos] = standby
        # a fresh health record: the standby starts HEALTHY and the
        # retired replica can never probe back into this position
        self.health.replicas[pos] = ReplicaHealth(pos, self.health.cfg)
        self._retired.append(dead)
        self.stats.standby_adoptions += 1
        self._log.warning(
            "standby replica %d adopted position %d (%d prefix blocks "
            "warm; %d standbys remain)",
            standby.index, pos, blocks, len(self.standbys),
        )

    def _schedule_failover(self, cr: ClusterRequest) -> None:
        """Bounded retries with exponential (cluster-step) backoff; past
        the bound the request fails terminally — never a hang."""
        cr.retries += 1
        self.stats.retries += 1
        if cr.retries > self.serving.failover_retries:
            cr.error = (
                f"replica failed and failover retries exhausted "
                f"({cr.retries - 1} re-admissions, failover_retries="
                f"{self.serving.failover_retries})"
            )
            self.stats.failover_errors += 1
            tr = self.tracer
            if tr.enabled:
                tr.event("request_error", trace_id=cr.cluster_id,
                         reason="failover_exhausted")
            if self.flight_recorder is not None:
                self.flight_recorder.dump(
                    self.tracer.lane or "router", "request_error",
                    step=self._step_counter,
                    extra={"cluster_id": cr.cluster_id,
                           "error": cr.error[:500]},
                )
            return
        backoff = (
            0 if cr.retries == 1
            else self.serving.failover_backoff_steps
            * (2 ** (cr.retries - 2))
        )
        cr._retry_at_step = self._step_counter + backoff
        self._failovers.append(cr.cluster_id)

    def _run_failovers(self) -> bool:
        """Re-admit requests whose backoff expired. A request that
        cannot be placed (no healthy replica) fails terminally."""
        if not self._failovers:
            return False
        progressed = False
        still: List[int] = []
        for cid in self._failovers:
            cr = self.requests[cid]
            if cr.error is not None or cr.rid is not None:
                continue
            if self._step_counter < cr._retry_at_step:
                still.append(cid)
                continue
            if self._place(cr, cr._known, ignore_slo=True):
                self.stats.failovers += 1
                progressed = True
                tr = self.tracer
                if tr.enabled:
                    tr.event(
                        "failover", trace_id=cid,
                        replica=cr.profile.failover_replica_id,
                        retry=cr.retries,
                    )
                self._log.warning(
                    "failover: request %d re-admitted on replica %d "
                    "(retry %d, %d tokens recomputed)",
                    cid, cr.profile.failover_replica_id, cr.retries,
                    len(cr.tokens),
                )
            else:
                self.stats.failover_errors += 1
                progressed = True
        self._failovers = still
        return progressed

    # ------------------------------------------------------------------
    # prefill→decode migration (bounded queue + back-pressure)

    def _queue_migrations(self) -> None:
        """Move newly completed held prefills into the migration FIFO
        (finishing the ones that owe no decode phase), then apply the
        back-pressure budget: entries past it release their held pages
        and drain through recompute re-admission instead of parking."""
        for cid, cr in list(self.requests.items()):
            if (
                cr.phase != "prefill" or cr.rid is None
                or cid in self._mig_queued
            ):
                continue
            src = self.replicas[cr.replica]
            req = src.rm.requests[cr.rid]
            if req.status not in TERMINAL_STATUSES or req.pipeline_refs:
                continue
            if req.status is RequestStatus.ERROR:
                # unservable on the prefill pool (PR-2 ERROR path) — the
                # cluster request is terminal with that error
                src.rm.release_held(cr.rid)
                cr.phase = "single"
                continue
            done = len(req.tokens) >= self.serving.max_sequence_length
            if req.tokens[req.prompt_len:]:
                last = req.tokens[-1]
                stops = set(cr.gen.stop_token_ids)
                if self.eos_token_id is not None:
                    stops.add(self.eos_token_id)
                remaining = cr.gen.max_new_tokens - (
                    len(req.tokens) - cr.prompt_len
                )
                done = done or last in stops or remaining <= 0
            if done:
                # 1-token budget, a stop token, or max length — no
                # decode phase owed: it finished on the prefill replica
                src.rm.release_held(cr.rid)
                cr.phase = "single"
                continue
            self._migration_queue.append(cid)
            self._mig_queued.add(cid)
        budget = self.serving.migration_queue_budget
        if budget is not None:
            while len(self._migration_queue) > budget:
                # newest entries overflow (FIFO heads keep their pages —
                # they hand off next); the overflow recomputes instead
                cid = self._migration_queue.pop()
                self._mig_queued.discard(cid)
                self.stats.migration_queue_overflows += 1
                self._recompute_readmit(cid)
        depth = len(self._migration_queue)
        self.stats.migration_queue_depth = depth
        self.stats.migration_queue_peak = max(
            self.stats.migration_queue_peak, depth
        )

    def _drain_migration_queue(self) -> bool:
        """Hand queued prefills to the decode pool: page migration when
        a healthy decode replica has capacity; recompute re-admission
        when the decode pool is gone or a migration keeps failing."""
        if not self._migration_queue:
            return False
        progressed = False
        remaining_q: List[int] = []
        for cid in self._migration_queue:
            cr = self.requests[cid]
            if cr.rid is None or cr.error is not None:
                continue  # source died — the failover path owns it now
            if self._step_counter < cr._retry_at_step:
                remaining_q.append(cid)  # migration-failure backoff
                continue
            src = self.replicas[cr.replica]
            req = src.rm.requests[cr.rid]
            dsts = [r for r in self.decode_pool if self._routable_rep(r)]
            if not dsts:
                # decode pool entirely DOWN: fall back to
                # non-disaggregated serving on the surviving pool —
                # recompute re-admission frees the held pages and the
                # prefill replica (or any survivor) serves the decode
                # phase itself
                self._recompute_readmit(cid)
                progressed = True
                continue
            dst = min(
                dsts,
                key=lambda r: (r.queue_delay_s(), r.load(), r.index),
            )
            # the decode side runs the REMAINING budget: after a
            # failover the home's prompt already carries generated
            # tokens, and the dst counts generation from its own
            # adopted baseline (= the home's prompt_len)
            gen_dst = dataclasses.replace(
                cr.gen,
                max_new_tokens=cr.gen.max_new_tokens
                - (req.prompt_len - cr.prompt_len),
            )
            try:
                rid_dst = migrate_request(
                    src, dst, cr.rid, gen_dst,
                    stats=self.stats, injector=self.fault_injector,
                    trace_id=cr.cluster_id, tracer=self.tracer,
                )
            except Exception as exc:
                self.stats.migration_failures += 1
                cr.mig_attempts += 1
                self._log.warning(
                    "migration of request %d -> replica %d failed "
                    "(attempt %d): %s", cid, dst.index,
                    cr.mig_attempts, exc,
                )
                if cr.mig_attempts > self.serving.failover_retries:
                    self._recompute_readmit(cid)
                else:
                    cr._retry_at_step = self._step_counter + (
                        self.serving.failover_backoff_steps
                        * (2 ** (cr.mig_attempts - 1))
                    )
                    remaining_q.append(cid)
                progressed = True
                continue
            if rid_dst is None:
                remaining_q.append(cid)  # dst full right now — waits
                continue
            src.rm.release_held(cr.rid)
            cr.replica = self.replicas.index(dst)
            cr.rid = rid_dst
            cr.phase = "decode"
            cr.profile.replica_id = dst.index
            progressed = True
        self._migration_queue = remaining_q
        self._mig_queued = set(remaining_q)
        self.stats.migration_queue_depth = len(remaining_q)
        return progressed

    def _recompute_readmit(self, cid: int) -> None:
        """Drain one held prefill WITHOUT moving pages: release the
        hold (its pages free immediately) and resubmit prompt + first
        token through the recompute path on the best surviving replica
        — the decode pool when any of it is healthy, else any healthy
        replica. The re-prefill is the back-pressure price (warm where
        prefix caching holds the prompt); greedy outputs stay bitwise."""
        cr = self.requests[cid]
        src = self.replicas[cr.replica]
        req = src.rm.requests[cr.rid]
        known = list(req.tokens)
        src.rm.release_held(cr.rid)
        cr.rid = None
        cr.replica = None
        cr.phase = "single"
        cr.retries += 1
        self.stats.retries += 1
        cands = [r for r in self.decode_pool if self._routable_rep(r)] or [
            r for r in self.replicas if self._routable_rep(r)
        ]
        if not cands:
            cr._known = known
            cr.error = (
                "no healthy replica to drain the held prefill to — "
                "the request fails terminally instead of parking"
            )
            self.stats.failover_errors += 1
            return
        rep = min(
            cands, key=lambda r: (r.queue_delay_s(), r.load(), r.index)
        )
        produced = len(known) - cr.prompt_len
        gen_home = dataclasses.replace(
            cr.gen, max_new_tokens=cr.gen.max_new_tokens - produced
        )
        cr.rid = rep.rm.submit(known, gen_home, trace_id=cr.cluster_id)
        cr.replica = self.replicas.index(rep)
        rep.rm.requests[cr.rid].profile = cr.profile
        cr.profile.retries = cr.retries
        cr.profile.failover_replica_id = rep.index
        cr.profile.replica_id = rep.index
        tr = self.tracer
        if tr.enabled:
            tr.event("recompute_readmit", trace_id=cid,
                     replica=rep.index, n_tokens=len(known))
        self._log.debug(
            "migration back-pressure: request %d drained to replica %d "
            "via recompute (%d tokens re-prefill)",
            cid, rep.index, len(known),
        )

    # ------------------------------------------------------------------
    # the drive loop

    def _observe_failure(self, pos: int, exc: BaseException,
                         step_no: int) -> None:
        """ONE health failure observation per replica per cluster step
        — an RPC-erroring replica that is also inside a heartbeat gap
        must not burn through ``failure_threshold`` twice as fast as a
        plain crashing one (the PR-9 arithmetic is the contract)."""
        if pos in self._failed_obs:
            return
        self._failed_obs.add(pos)
        self._note_transition(
            pos, self.health[pos].record_failure(exc, step_no), exc
        )

    def _check_gap(self, pos: int, rep, step_no: int) -> None:
        """Heartbeat-gap detection, in deterministic CLUSTER steps: no
        successful exchange for ``heartbeat_gap_steps`` steps is a
        SUSPECT observation each step until contact resumes (or the
        breaker trips)."""
        gap = step_no - rep.last_contact_step
        if gap >= self.serving.heartbeat_gap_steps:
            self.stats.heartbeat_gaps += 1
            tr = self.tracer
            if tr.enabled:
                tr.event("heartbeat_gap", replica=rep.index, gap=gap)
            self._observe_failure(
                pos,
                HeartbeatGap(
                    f"replica {rep.index}: no successful exchange for "
                    f"{gap} cluster steps"
                ),
                step_no,
            )

    def _heartbeat_remote(self, pos: int, rep, step_no: int) -> None:
        """Idle remote replicas stay observable: a heartbeat every
        ``heartbeat_interval_steps`` refreshes the telemetry mirror
        (SchedulerStats + the queue-delay inputs the router reads) and
        stamps contact; a FAILED heartbeat is silent on its own (the
        loss is retried/absorbed at the transport) — sustained loss
        surfaces through :meth:`_check_gap`."""
        due = (
            step_no - rep.last_contact_step
            >= self.serving.heartbeat_interval_steps
        )
        if due and rep.heartbeat():
            rep.last_contact_step = step_no
            return
        self._check_gap(pos, rep, step_no)

    def step(self) -> bool:
        """One cluster step: advance every steppable replica under the
        health monitor (remote replicas additionally heartbeat when
        idle, with gap detection in cluster steps), settle
        prefill→decode migrations, then run any due failover
        re-admissions. Returns False when no replica has work left and
        nothing is pending recovery."""
        self._step_counter += 1
        step_no = self._step_counter
        self._failed_obs = set()
        progressed = False
        for pos in range(len(self.replicas)):
            rep = self.replicas[pos]
            h = self.health[pos]
            if h.state is HealthState.DOWN:
                if h.maybe_probe(step_no):
                    self.stats.probes += 1
                    if self.tracer.enabled:
                        self.tracer.event("probe", replica=rep.index,
                                          backoff=h.backoff_steps)
                    self._log.warning(
                        "replica %d probing (circuit half-open after "
                        "%d-step backoff)", rep.index, h.backoff_steps,
                    )
                    progressed = True
                else:
                    continue
            remote = getattr(rep, "is_remote", False)
            if not rep.has_work():
                if remote:
                    self._heartbeat_remote(pos, rep, step_no)
                continue
            t0 = time.perf_counter()
            try:
                stepped = rep.step()
            except Exception as exc:
                self.stats.step_faults += 1
                self._observe_failure(pos, exc, step_no)
                if (
                    remote and rep is self.replicas[pos]
                    and self.health[pos].state is not HealthState.DOWN
                ):
                    self._check_gap(pos, rep, step_no)
                progressed = True
                continue
            if remote:
                rep.last_contact_step = step_no
            latency = (time.perf_counter() - t0) + rep.injected_latency_s
            self._note_transition(
                pos, h.record_success(latency, step_no, had_work=True)
            )
            progressed = stepped or progressed
        if self.disaggregated:
            self._queue_migrations()
            progressed = self._drain_migration_queue() or progressed
        progressed = self._run_failovers() or progressed
        if self._failovers or self._migration_queue:
            # pending recoveries keep the drive loop alive through their
            # backoff windows — a generate() must never break out and
            # strand a request between homes
            progressed = True
        if step_no % 200 == 0:
            self._log.debug(
                "%s", self.stats.report([r.rm.stats for r in self.replicas])
            )
        return progressed

    def drain(self) -> None:
        """Flush every healthy replica's pipeline, then settle any
        migrations those flushes unblocked (a prefill pass whose
        completion was still in the pipeline hands its pages off here;
        the adopted decode work itself is driven by later :meth:`step`
        calls, same as RequestManager.drain never runs new steps). A
        flush failure is a replica failure — same health path as a
        step exception."""
        for pos, rep in enumerate(self.replicas):
            if self.health[pos].state is HealthState.DOWN:
                continue
            try:
                rep.drain()
            except Exception as exc:
                self.stats.step_faults += 1
                self._note_transition(
                    pos,
                    self.health[pos].record_failure(exc, self._step_counter),
                    exc,
                )
        if self.disaggregated:
            self._queue_migrations()
            self._drain_migration_queue()
        self._run_failovers()

    # ------------------------------------------------------------------
    # results

    def cluster_stats(self) -> Dict[str, object]:
        """ClusterStats snapshot over the live per-replica stats."""
        return self.stats.snapshot([r.rm.stats for r in self.replicas])

    def health_snapshot(self) -> List[str]:
        return self.health.snapshot()

    def check_no_leaks(self) -> None:
        """Page-pool audits on every replica that is NOT circuit-broken
        — a DOWN replica's pool is unreachable (on multi-host it is
        gone with the process), not leaked; it re-enters the audit set
        the moment it probes back."""
        for pos, rep in enumerate(self.replicas):
            if self.health[pos].state is HealthState.DOWN:
                continue
            rep.check_no_leaks()

    def result(self, cid: int) -> GenerationResult:
        cr = self.requests[cid]
        out = cr.output_tokens
        text = (
            self.tokenizer.decode(out) if self.tokenizer is not None else ""
        )
        error = cr.error
        if error is None and cr.rid is not None:
            error = self.replicas[cr.replica].rm.requests[cr.rid].error
        return GenerationResult(
            request_id=cid,
            prompt=cr.prompt_text,
            input_tokens=list(cr.tokens),
            output_tokens=list(out),
            output_text=text,
            profile=cr.profile,
            error=error,
        )

    def _terminal(self, cid: int) -> bool:
        return self.requests[cid].status in TERMINAL_STATUSES

    def generate(
        self,
        prompts: Union[str, Sequence[Union[str, Sequence[int]]]],
        gen: Optional[GenerationConfig] = None,
        max_new_tokens: Optional[int] = None,
        session_ids: Optional[Sequence[object]] = None,
    ) -> List[GenerationResult]:
        """Blocking generate across the cluster (router-placed)."""
        if isinstance(prompts, str):
            prompts = [prompts]
        cids = [
            self.submit(
                p, gen, max_new_tokens,
                session_id=session_ids[i] if session_ids else None,
            )
            for i, p in enumerate(prompts)
        ]
        while any(not self._terminal(c) for c in cids):
            if not self.step():
                break
        self.drain()
        return [self.result(c) for c in cids]

    def generate_stream(
        self,
        prompts: Union[str, Sequence[Union[str, Sequence[int]]]],
        gen: Optional[GenerationConfig] = None,
        max_new_tokens: Optional[int] = None,
        session_ids: Optional[Sequence[object]] = None,
    ) -> Iterator[StreamEvent]:
        """Streaming generate across the cluster: one StreamEvent per
        drained token (``request_id`` is the CLUSTER id) + a terminal
        event per request (``error`` set for sheds/failures). Token
        counts are monotone across a migration — the first output token
        is visible on both sides of the hand-off, so nothing is dropped
        or re-sent — and across a failover: the re-admission's known
        tokens are exactly the flushed (= streamed) prefix, so the
        stream resumes where it stopped."""
        if isinstance(prompts, str):
            prompts = [prompts]
        cids = [
            self.submit(
                p, gen, max_new_tokens,
                session_id=session_ids[i] if session_ids else None,
            )
            for i, p in enumerate(prompts)
        ]
        sent = {c: 0 for c in cids}
        finished: set = set()

        def drain_events():
            for c in cids:
                if c in finished:
                    continue
                cr = self.requests[c]
                out = cr.output_tokens
                while sent[c] < len(out):
                    tok = out[sent[c]]
                    sent[c] += 1
                    yield StreamEvent(c, int(tok))
                if self._terminal(c):
                    finished.add(c)
                    err = cr.error
                    if err is None and cr.rid is not None:
                        home = self.replicas[cr.replica].rm
                        err = home.requests[cr.rid].error
                    yield StreamEvent(c, None, done=True, error=err)

        while len(finished) < len(cids):
            progressed = self.step()
            yield from drain_events()
            if not progressed and len(finished) < len(cids):
                self.drain()
                yield from drain_events()
                if len(finished) < len(cids):
                    break  # nothing schedulable remains — avoid spinning
        self.drain()
        yield from drain_events()
