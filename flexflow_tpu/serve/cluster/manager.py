"""ClusterManager — one process driving N engine replicas.

The cluster front-end: RequestManager-shaped API (``submit`` /
``step`` / ``drain`` / ``generate`` / ``generate_stream`` / ``result``)
over a pool of :class:`Replica` (each its own engine, mesh and KV pool)
behind a :class:`Router`. The manager owns cluster-level request
identity (cluster ids are independent of any replica's guids), the
per-step drive loop over every replica's scheduler, and — under
disaggregation — the prefill→decode page migrations.

Request lifecycle::

    submit ──router──┬── shed / all DOWN ──→ ERROR (terminal, PR-2 contract)
                     ├── mixed replica ─────→ prefill+decode there ("single")
                     └── prefill replica ───→ prefill, max_new_tokens=1
                             │ held slot        ("prefill")
                             └─ COMPLETED → migration queue → decode replica
                                             adopts into DECODING ("decode")

Sheds come from SLO admission (``ServingConfig.slo_queue_delay_s``):
they surface as ``GenerationResult.error`` exactly like the PR-2
unservable-request path — a shed request is terminal the moment it is
submitted and can never hang a ``generate()``/stream/C-host loop.

**Fault tolerance** (serve/cluster/health.py): every replica step runs
under the health monitor — a step exception or sustained latency spike
demotes the replica (HEALTHY → SUSPECT → DOWN), and a DOWN replica's
circuit opens: it leaves ``Router.route`` scoring, its session
affinities drop (they re-pin on survivors, which also re-seeds its
prefix families there), and every request it held is RE-ADMITTED to a
healthy replica through the recompute path — prompt + tokens generated
so far resubmit as a prompt, exactly the vLLM-style preemption recompute
the scheduler already runs, so greedy generations stay bitwise the
fault-free run's. Retries are bounded (``ServingConfig.failover_retries``
with exponential cluster-step backoff); when they exhaust, or no healthy
replica remains, the request turns into a terminal
``GenerationResult.error`` — never a hang. After an exponential backoff
the breaker half-opens (PROBING) and routed traffic is the probe.

**Migration back-pressure** (``ServingConfig.migration_queue_budget``):
finished prefills waiting for decode-pool capacity sit in a bounded
FIFO. Within budget they wait holding their pages (the cheap page
hand-off); past it they release the pages immediately and drain through
recompute re-admission on the decode pool's own pending queue — a full
decode pool costs recompute, not unbounded held slots on the prefill
pool. Degraded pools fall back: a dead decode pool means the surviving
pool serves both phases (recompute re-admission in place of page
migration); a dead prefill pool routes new requests single-phase onto
the decode pool.

With ``replicas=1`` and no faults the manager routes everything to
replica 0 and the replica runs the bit-for-bit single-engine scheduler —
the router adds bookkeeping, never a different step sequence (asserted
bitwise in tests/test_cluster.py).
"""
from __future__ import annotations

import dataclasses
import os
import time
from typing import (
    Any, Dict, Iterator, List, Optional, Sequence, Set, Tuple, Union,
)

from ...logging_utils import get_logger
from ...metrics import ClusterStats
from ...obs.tracer import NULL_TRACER
from ..batch_config import (
    GenerationConfig,
    GenerationResult,
    ProfileInfo,
    StreamEvent,
)
from ..engine import ServingConfig
from ..request_manager import TERMINAL_STATUSES, RequestStatus
from .health import HealthConfig, HealthMonitor, HealthState, ReplicaHealth
from .journal import RequestJournal, replay_journal
from .migration import migrate_request
from .reconfigure import (
    begin_scale_in as _begin_scale_in,
    maybe_retire as _maybe_retire,
    scale_in as _scale_in,
    scale_out as _scale_out,
    set_pools as _set_pools,
)
from .remote import HeartbeatGap, RemoteReplica
from .replica import Replica
from .router import Router
from .transport import LoopbackTransport, SocketTransport


def _wire_session(session_id: Optional[object]):
    """Session ids ride the journal as codec-safe primitives; anything
    richer journals as its string form (affinity pins do not survive a
    restart anyway — the journaled id only re-keys future turns)."""
    if session_id is None or isinstance(session_id, (int, str, float, bool)):
        return session_id
    return str(session_id)


def _build_member(serving, ctx, index: int, role: str,
                  endpoint: Optional[str] = None):
    """One replica (or standby) behind the configured transport —
    shared by :meth:`ClusterManager.build`, :meth:`ClusterManager.
    recover` and ``reconfigure.scale_out``. "loopback" wraps the SAME
    in-process build in a RemoteReplica whose every call round-trips
    the wire codec against a ReplicaServerCore; "socket" dials a
    subprocess replica server (``endpoint``, falling back to the
    config's positional entry) instead of building anything locally."""
    if serving.replica_transport == "socket":
        ep = endpoint
        if ep is None:
            if index >= len(serving.replica_endpoints):
                raise ValueError(
                    f"no endpoint for socket replica {index} — pass "
                    "scale_out(endpoint=...) or extend replica_endpoints"
                )
            ep = serving.replica_endpoints[index]
        host, _, port = ep.rpartition(":")
        return RemoteReplica(
            index, SocketTransport(host or "127.0.0.1", int(port)),
            serving, role=role,
        )
    devs = ctx["devices"]
    local = Replica.build(
        index, ctx["model"], ctx["cfg"], ctx["params"], serving,
        role=role,
        devices=[devs[index % len(devs)]],
        tokenizer=ctx["tokenizer"],
        eos_token_id=ctx["eos_token_id"],
        seed=ctx["seed"],
        ssms=ctx["ssms"],
        spec=ctx["spec"],
    )
    if serving.replica_transport == "inproc":
        return local
    from .server import ReplicaServerCore

    return RemoteReplica(
        index, LoopbackTransport(ReplicaServerCore(local).dispatch),
        serving, role=role, local=local,
    )


@dataclasses.dataclass
class ClusterRequest:
    """Cluster-level view of one request: where it lives now (replica
    position + replica-local rid) and which phase of the disaggregated
    lifecycle it is in. ``rid is None`` means the request is not on any
    replica right now: shed / terminally failed (``error`` set) or
    between homes awaiting a failover re-admission (``error`` None)."""

    cluster_id: int
    tokens: List[int]
    prompt_text: str
    gen: GenerationConfig
    session_id: Optional[object] = None
    replica: Optional[int] = None       # position into manager.replicas
    rid: Optional[int] = None           # replica-local request id
    phase: str = "single"               # "single" | "prefill" | "decode"
    error: Optional[str] = None         # terminal failure (shed/failover)
    # terminal-success WITHOUT a live home: set when the request's home
    # retired (scale_in) or when a recovered manager rehydrated its
    # journaled terminal record — ``_known`` holds the full transcript
    finished: bool = False
    profile: ProfileInfo = dataclasses.field(default_factory=ProfileInfo)
    # ORIGINAL prompt length (the output-token baseline): a failover
    # re-admission's home sees prompt+generated as its prompt, so the
    # home's prompt_len stops being the boundary — this one always is.
    prompt_len: int = 0
    retries: int = 0                    # re-admissions so far
    mig_attempts: int = 0               # failed page-migration attempts

    _manager: Any = dataclasses.field(default=None, repr=False)
    # prompt + flushed generated tokens captured when the home replica
    # went DOWN — the recompute re-admission's submission (and the
    # partial output while between homes)
    _known: Optional[List[int]] = dataclasses.field(default=None, repr=False)
    _retry_at_step: int = 0             # failover/migration backoff gate

    @property
    def status(self) -> RequestStatus:
        """RequestStatus-shaped view (c_backend drives clusters through
        the same loop it drives a bare RequestManager with)."""
        if self.rid is None:
            # shed / failed = terminal; retired-home / recovered
            # completions = COMPLETED; between homes (failover pending)
            # = PENDING, so nothing treats an in-flight recovery as done
            if self.error:
                return RequestStatus.ERROR
            if self.finished:
                return RequestStatus.COMPLETED
            return RequestStatus.PENDING
        home = self._manager.replicas[self.replica].rm
        st = home.requests[self.rid].status
        if self.phase == "prefill" and st in TERMINAL_STATUSES:
            # completed ON THE PREFILL POOL means "awaiting migration",
            # not done — unless the manager decided it finished there
            return (
                st if st is RequestStatus.ERROR
                else RequestStatus.DECODING
            )
        return st

    @property
    def output_tokens(self) -> List[int]:
        if self.rid is None:
            if self._known:
                return list(self._known[self.prompt_len:])
            return []
        home = self._manager.replicas[self.replica].rm
        # slice at the ORIGINAL prompt boundary: a failover home's own
        # prompt_len includes carried-over generated tokens
        return home.requests[self.rid].tokens[self.prompt_len:]


class ClusterManager:
    """Drive ``replicas`` behind a router (see module docstring)."""

    def __init__(
        self,
        replicas: Sequence[Replica],
        serving: ServingConfig,
        *,
        router: Optional[Router] = None,
        tokenizer: Any = None,
        eos_token_id: Optional[int] = None,
        health_config: Optional[HealthConfig] = None,
        standbys: Sequence[Replica] = (),
    ):
        serving.validate_cluster()
        if len(replicas) != serving.replicas:
            raise ValueError(
                f"ServingConfig.replicas={serving.replicas} but "
                f"{len(replicas)} replicas were built"
            )
        if len(standbys) != serving.standby_replicas:
            raise ValueError(
                f"ServingConfig.standby_replicas="
                f"{serving.standby_replicas} but {len(standbys)} "
                "standbys were built"
            )
        self.serving = serving
        self.replicas = list(replicas)
        # warm standbys: pre-built engines OUTSIDE routing; on a DOWN
        # transition one adopts the dead replica's position (+ its
        # prefix families over the transport) — see _adopt_standby
        self.standbys = list(standbys)
        self._retired: List[Replica] = []   # replaced dead replicas
        self.tokenizer = tokenizer
        self.eos_token_id = eos_token_id
        if eos_token_id is None and tokenizer is not None:
            self.eos_token_id = getattr(tokenizer, "eos_token_id", None)
        self.stats = ClusterStats()
        for rep in list(self.replicas) + self.standbys:
            if getattr(rep, "is_remote", False):
                rep.bind_stats(lambda: self.stats)
        self.health = HealthMonitor(len(self.replicas), health_config)
        self.fault_injector = None
        # replica positions already observed failing THIS cluster step
        # (the one-SUSPECT-observation-per-step guard: a replica that is
        # simultaneously in a heartbeat gap and returning RPC errors is
        # observed once, preserving the PR-9 threshold arithmetic)
        self._failed_obs: Set[int] = set()
        self.prefill_pool = [r for r in self.replicas if r.role == "prefill"]
        self.decode_pool = [r for r in self.replicas if r.role == "decode"]
        self.disaggregated = bool(self.prefill_pool)
        if self.disaggregated and not self.decode_pool:
            raise ValueError("prefill pool without a decode pool")
        # Live reconfiguration (serve/cluster/reconfigure.py): replica
        # INDICES currently draining toward retirement — excluded from
        # every placement exactly like DOWN replicas, but still stepped
        # (their in-flight work finishes or migrates; maybe_retire
        # removes them once idle). Keyed by index, not position, so
        # membership surgery never invalidates the set.
        self._draining: Set[int] = set()
        routing = self.prefill_pool if self.disaggregated else self.replicas
        # router positions index the ROUTING pool; map back to cluster
        # positions so ClusterRequest.replica is always cluster-wide
        self._routing_pos = [self.replicas.index(r) for r in routing]
        health_cb = (
            lambda pos: self._routable_pos(self._routing_pos[pos])
        )
        self.router = router or Router(
            routing,
            serving.router_policy,
            slo_queue_delay_s=serving.slo_queue_delay_s,
            stats=lambda: self.stats,
            health=health_cb,
        )
        if router is not None and self.router.health is None:
            self.router.health = health_cb
        self.requests: Dict[int, ClusterRequest] = {}
        self._next_cid = 1
        self._step_counter = 0
        # failover re-admissions pending their backoff (cluster ids)
        self._failovers: List[int] = []
        # finished prefills awaiting decode-pool capacity (cluster ids,
        # FIFO; bounded by ServingConfig.migration_queue_budget)
        self._migration_queue: List[int] = []
        self._mig_queued: Set[int] = set()
        self._log = get_logger("serve")
        # Observability (flexflow_tpu/obs): the router/manager lane of
        # the cluster timeline (placements, migrations, failovers,
        # health transitions, heartbeat gaps) plus the failure flight
        # recorder's dump triggers. NULL_TRACER/None by default — the
        # drive loop pays one attribute read per guarded site;
        # obs.attach_observability wires live ones in.
        self.tracer = NULL_TRACER
        self.flight_recorder = None
        # events recorded before a tracer could attach (recovery runs
        # before obs wiring) — flushed on the first traced step
        self._pending_trace: List[tuple] = []
        # Elastic control plane (journal.py + reconfigure.py): the
        # durable request journal (opened by build/recover — see
        # _open_journal), per-request flushed-token high-water marks,
        # terminal records already written, the replica factory context
        # scale_out/recover rebuild members from, and the index→endpoint
        # map the members snapshot journals for socket clusters.
        self.journal: Optional[RequestJournal] = None
        self._journal_flushed: Dict[int, int] = {}
        self._journal_done: Set[int] = set()
        self._build_ctx: Optional[Dict[str, Any]] = None
        self._endpoints: Dict[int, str] = {}
        self._next_replica_index = 1 + max(
            (r.index for r in list(self.replicas) + self.standbys),
            default=-1,
        )
        # Self-driving serving (serve/autotune): the optional policy
        # loop hooked into step() — attached by build()/recover() when
        # ServingConfig.autoscale is set, or injected by tests. The
        # completion window feeds its TrafficEstimator: cluster ids
        # still awaiting their terminal sweep, plus this-window
        # (prompt_len, output_len) pairs for newly finished requests,
        # drained by drain_completion_window() once per observation.
        self.autoscaler = None
        self._open_cids: Set[int] = set()
        self._completion_window: List[Tuple[int, int]] = []

    # ------------------------------------------------------------------
    # construction

    @classmethod
    def build(
        cls,
        model: Any,
        cfg: Any,
        params: Any,
        serving: Optional[ServingConfig] = None,
        *,
        tokenizer: Any = None,
        eos_token_id: Optional[int] = None,
        seed: int = 0,
        devices: Optional[Sequence[Any]] = None,
        health_config: Optional[HealthConfig] = None,
        ssms: Sequence[Any] = (),
        spec: Any = None,
    ) -> "ClusterManager":
        """Build ``serving.replicas`` in-process replicas — params
        shared by reference, each replica with its own mesh over a
        device picked round-robin from ``devices`` (all of them on a
        1-device host: independent engines on one chip is the
        in-process cluster this PR ships; per-host processes slot in
        behind the same Replica surface later).

        ``ssms`` ((model, cfg, params) triples) + ``spec`` turn every
        replica into a SpecInfer pair: per-replica SSM MIRRORS — each
        replica builds its own draft engines on its own mesh (draft
        params shared by reference, like the target's), so speculation
        scales out with the pool. Disaggregated prefill/decode pools
        reject the combination at ``validate_cluster``."""
        serving = serving or ServingConfig()
        serving.validate_cluster(
            specinfer=bool(ssms)
            or getattr(spec, "draft", "ssm") == "early_exit"
        )
        import jax

        devs = list(devices or jax.devices())
        roles = ["mixed"] * serving.replicas
        if serving.prefill_replicas:
            roles = (
                ["prefill"] * serving.prefill_replicas
                + ["decode"] * serving.decode_replicas
            )
        ctx = dict(
            model=model, cfg=cfg, params=params, devices=devs,
            tokenizer=tokenizer, eos_token_id=eos_token_id, seed=seed,
            ssms=ssms, spec=spec,
        )
        replicas = [
            _build_member(serving, ctx, i, roles[i])
            for i in range(serving.replicas)
        ]
        standbys = [
            _build_member(serving, ctx, serving.replicas + j, "mixed")
            for j in range(serving.standby_replicas)
        ]
        cm = cls(
            replicas, serving, tokenizer=tokenizer,
            eos_token_id=eos_token_id, health_config=health_config,
            standbys=standbys,
        )
        cm._build_ctx = ctx
        if serving.replica_transport == "socket":
            cm._endpoints = {
                i: serving.replica_endpoints[i]
                for i in range(serving.replicas)
            }
        # build() starts a FRESH log (use recover() to resume one): a
        # stale journal replaying into a new cluster would resurrect a
        # previous run's requests
        cm._open_journal(resume=False)
        if serving.autoscale:
            cm._attach_autoscaler()
        return cm

    @classmethod
    def recover(
        cls,
        model: Any,
        cfg: Any,
        params: Any,
        serving: Optional[ServingConfig] = None,
        *,
        tokenizer: Any = None,
        eos_token_id: Optional[int] = None,
        seed: int = 0,
        devices: Optional[Sequence[Any]] = None,
        health_config: Optional[HealthConfig] = None,
        ssms: Sequence[Any] = (),
        spec: Any = None,
    ) -> "ClusterManager":
        """Rebuild a crashed manager from ``serving.journal_dir``.

        The journal replays first (a torn tail truncates — never
        corrupts), yielding the last COMMITTED membership (scale_out /
        scale_in / set_pools survive the crash; an uncommitted begin
        recovers as "never happened") and every journaled request with
        its flushed-token prefix. Replicas rebuild per that membership:
        still-running subprocess servers are RECONNECTED — a heartbeat
        rebuilds the client mirror from its envelope, then ``abandon``
        clears the orphaned scheduler state (the PR-12 seq cache keeps
        the replayed RPCs at-most-once; the server's prefix tree
        survives, so it rejoins WARM) — while in-process/loopback
        replicas, which died with the manager, rebuild fresh. Every
        unfinished request then re-admits through the PR-9 recompute
        path with its journaled prompt + flushed prefix, so greedy
        outputs are BITWISE the uninterrupted run's and already-
        delivered tokens are regenerated identically, never duplicated
        (stream-monotone across the restart). Terminal entries
        rehydrate so ``result`` still answers for them."""
        serving = serving or ServingConfig()
        if not serving.journal_dir:
            raise ValueError(
                "ClusterManager.recover needs ServingConfig.journal_dir "
                "(there is no journal to recover from)"
            )
        serving.validate_cluster(
            specinfer=bool(ssms)
            or getattr(spec, "draft", "ssm") == "early_exit"
        )
        state = replay_journal(cls._journal_path(serving))
        import jax

        devs = list(devices or jax.devices())
        roles = ["mixed"] * serving.replicas
        if serving.prefill_replicas:
            roles = (
                ["prefill"] * serving.prefill_replicas
                + ["decode"] * serving.decode_replicas
            )
        is_socket = serving.replica_transport == "socket"
        members = state.members or [
            {"index": i, "role": roles[i],
             "endpoint": (serving.replica_endpoints[i] if is_socket
                          else "")}
            for i in range(serving.replicas)
        ]
        # standby endpoints stay config-positional (the tail entries);
        # the MEMBER endpoints come from the journaled snapshot, which
        # survives scale_out/scale_in having changed them
        standby_eps = (
            serving.replica_endpoints[len(serving.replica_endpoints)
                                      - serving.standby_replicas:]
            if is_socket and serving.standby_replicas else ()
        )
        n_prefill = sum(1 for m in members if m["role"] == "prefill")
        n_decode = sum(1 for m in members if m["role"] == "decode")
        serving = dataclasses.replace(
            serving,
            replicas=len(members),
            prefill_replicas=n_prefill,
            decode_replicas=n_decode,
            replica_endpoints=(
                tuple(str(m.get("endpoint", "")) for m in members)
                + tuple(standby_eps)
            ) if is_socket else serving.replica_endpoints,
        )
        ctx = dict(
            model=model, cfg=cfg, params=params, devices=devs,
            tokenizer=tokenizer, eos_token_id=eos_token_id, seed=seed,
            ssms=ssms, spec=spec,
        )
        replicas = [
            _build_member(serving, ctx, int(m["index"]), str(m["role"]),
                          str(m.get("endpoint") or "") or None)
            for m in members
        ]
        max_idx = max((int(m["index"]) for m in members), default=-1)
        standbys = [
            _build_member(serving, ctx, max_idx + 1 + j, "mixed",
                          standby_eps[j] if standby_eps else None)
            for j in range(serving.standby_replicas)
        ]
        cm = cls(
            replicas, serving, tokenizer=tokenizer,
            eos_token_id=eos_token_id, health_config=health_config,
            standbys=standbys,
        )
        cm._build_ctx = ctx
        cm._endpoints = {
            int(m["index"]): str(m.get("endpoint", ""))
            for m in members if m.get("endpoint")
        }
        cm._next_replica_index = max_idx + 1 + serving.standby_replicas
        # reconnect still-running subprocess servers (see docstring);
        # loopback/inproc replicas were just rebuilt and need neither
        for rep in cm.replicas:
            if getattr(rep, "is_remote", False) and rep.local is None:
                if rep.heartbeat():
                    rep.abandon()
        # rehydrate the journaled requests
        cm._next_cid = state.next_cid
        replayed = 0
        now = time.perf_counter()
        for e in state.entries.values():
            cr = ClusterRequest(
                cluster_id=e.cid, tokens=list(e.tokens),
                prompt_text=e.prompt_text, gen=e.gen,
                session_id=e.session, prompt_len=e.prompt_len,
                _manager=cm,
            )
            cr._known = list(e.tokens) + list(e.flushed)
            cm.requests[e.cid] = cr
            cm._journal_flushed[e.cid] = len(e.flushed)
            if e.terminal:
                cr.error = e.error
                cr.finished = e.error is None
                cm._journal_done.add(e.cid)
            else:
                # recompute re-admission with the journaled prompt +
                # flushed prefix: retries=1 marks it a re-admission, so
                # _place keeps the ORIGINAL prompt_len boundary and the
                # carried profile (fresh clock — recovery restarts it)
                cr.profile.start_time = now
                cr.retries = 1
                cm._failovers.append(e.cid)
                replayed += 1
        cm.stats.submitted += len(state.entries)
        cm.stats.manager_recoveries += 1
        cm.stats.journal_replayed += replayed
        cm._pending_trace.append(("recover", dict(
            replicas=len(members), replayed=replayed,
            records=state.records,
        )))
        cm._pending_trace.append(("replay", dict(
            requests=len(state.entries), records=state.records,
            truncated_bytes=state.truncated_bytes,
        )))
        # resume the SAME log, compacted to the recovered state (the
        # full history was just replayed — rewriting it keeps replay
        # idempotent and the file bounded)
        cm._open_journal(resume=True)
        cm._journal_checkpoint(include_finished=True)
        # unfinished rehydrated requests re-enter the completion sweep;
        # a fresh autoscaler (cooldown re-armed from the current step)
        # resumes the policy loop over the recovered membership
        cm._open_cids = {
            cid for cid, cr in cm.requests.items()
            if cr.status not in TERMINAL_STATUSES
        }
        if serving.autoscale:
            cm._attach_autoscaler()
        cm._log.warning(
            "manager recovered from %s: %d replicas, %d requests "
            "rehydrated (%d re-admitted, %d already terminal)%s",
            cls._journal_path(serving), len(members), len(state.entries),
            replayed, len(state.entries) - replayed,
            f", {state.truncated_bytes}B torn tail truncated"
            if state.truncated_bytes else "",
        )
        return cm

    # ------------------------------------------------------------------
    # durable request journal (serve/cluster/journal.py)

    @staticmethod
    def _journal_path(serving: ServingConfig) -> str:
        return os.path.join(serving.journal_dir, "requests.journal")

    def _open_journal(self, resume: bool) -> None:
        if not self.serving.journal_dir:
            return
        path = self._journal_path(self.serving)
        if not resume and os.path.exists(path):
            self._log.warning(
                "journal %s exists — build() starts a FRESH log over "
                "it (use ClusterManager.recover to resume a crashed "
                "manager's journal)", path,
            )
            os.remove(path)
        self.journal = RequestJournal(path, stats=lambda: self.stats)

    def _journal_sync(self) -> None:
        """Batch-write flushed-token deltas + newly terminal records —
        called at the drive loop's flush sync points (end of step/
        drain/submit): one buffered write + one file flush, never a
        per-token write and never a device sync."""
        j = self.journal
        if j is None:
            return
        for cid, cr in self.requests.items():
            if cid in self._journal_done:
                continue
            out = cr.output_tokens
            sent = self._journal_flushed.get(cid, 0)
            if len(out) > sent:
                j.append({
                    "type": "tokens", "cid": cid,
                    "toks": [int(t) for t in out[sent:]],
                })
                self._journal_flushed[cid] = len(out)
            if cr.status in TERMINAL_STATUSES:
                err = cr.error
                if err is None and cr.rid is not None:
                    err = self.replicas[cr.replica].rm.requests[
                        cr.rid].error
                j.append({"type": "terminal", "cid": cid, "error": err})
                self._journal_done.add(cid)
                j.note_finished()
        j.flush()
        if j.should_compact():
            self._journal_checkpoint(include_finished=False)

    def _journal_checkpoint(self, include_finished: bool) -> None:
        """Rewrite the journal to the current live state (compaction —
        finished entries retire unless ``include_finished``, which the
        recovery checkpoint uses so results survive one more restart)."""
        j = self.journal
        if j is None:
            return
        from .server import gen_to_wire

        recs: List[Dict[str, Any]] = [
            {"type": "members", "members": self.members_snapshot()}
        ]
        for cid in sorted(self.requests):
            cr = self.requests[cid]
            done = cid in self._journal_done
            if done and not include_finished:
                continue
            out = cr.output_tokens
            recs.append({
                "type": "submit", "cid": cid,
                "tokens": [int(t) for t in cr.tokens[:cr.prompt_len]],
                "prompt_len": int(cr.prompt_len),
                "gen": gen_to_wire(cr.gen),
                "session": _wire_session(cr.session_id),
                "prompt": cr.prompt_text,
            })
            if out:
                recs.append({
                    "type": "tokens", "cid": cid,
                    "toks": [int(t) for t in out],
                })
                self._journal_flushed[cid] = len(out)
            if done:
                err = cr.error
                recs.append({"type": "terminal", "cid": cid, "error": err})
        j.compact(recs)

    def _make_member(self, index: int, role: str,
                     endpoint: Optional[str] = None):
        """Build (or dial) one more replica through the same factory
        construction used — scale_out's replica source."""
        if self._build_ctx is None:
            raise RuntimeError(
                "this cluster was constructed from prebuilt replicas "
                "(no build context) — pass scale_out(replica=...) a "
                "prebuilt one"
            )
        return _build_member(self.serving, self._build_ctx, index, role,
                             endpoint)

    def members_snapshot(self) -> List[Dict[str, Any]]:
        """The journaled membership: index/role/endpoint per replica —
        what :meth:`recover` rebuilds after reconfigurations moved the
        cluster away from the config's static shape."""
        return [
            {"index": r.index, "role": r.role,
             "endpoint": self._endpoints.get(r.index, "")}
            for r in self.replicas
        ]

    def close(self) -> None:
        """Flush + close the journal and every remote transport (the
        orderly shutdown; crash recovery never needs it)."""
        if self.journal is not None:
            self._journal_sync()
            self.journal.close()
        for rep in list(self.replicas) + self.standbys + self._retired:
            close_fn = getattr(rep, "close", None)
            if close_fn is not None:
                close_fn()

    # ------------------------------------------------------------------
    # live reconfiguration (serve/cluster/reconfigure.py)

    def scale_out(self, **kw) -> int:
        """Grow the cluster by one replica (warm by default) — see
        :func:`~.reconfigure.scale_out`."""
        return _scale_out(self, **kw)

    def begin_scale_in(self, pos: int) -> None:
        """Start draining the replica at ``pos`` (non-blocking) — see
        :func:`~.reconfigure.begin_scale_in`."""
        _begin_scale_in(self, pos)

    def scale_in(self, pos: int, **kw) -> None:
        """Drain + retire the replica at ``pos`` (blocking, bounded) —
        see :func:`~.reconfigure.scale_in`."""
        _scale_in(self, pos, **kw)

    def set_pools(self, roles: Dict[int, str]) -> None:
        """Flip replicas between prefill/decode pools under traffic —
        see :func:`~.reconfigure.set_pools`."""
        _set_pools(self, roles)

    def attach_faults(self, plan):
        """Wire a :class:`~.faults.FaultPlan` (or a prebuilt injector,
        or its JSON) into every replica (standbys included) and the
        migration path. Transport fault kinds (drop/delay/disconnect/
        partition) are injected AT the RPC transport, which in-process
        replicas do not have — aiming them at an ``inproc`` cluster is
        a loud error, not a silent no-op. Returns the
        :class:`~.faults.FaultInjector` for ``fired``/``release_all``."""
        from .faults import TRANSPORT_KINDS, FaultInjector, FaultPlan

        if isinstance(plan, str):
            plan = FaultPlan.from_json(plan)
        injector = plan if isinstance(plan, FaultInjector) else (
            FaultInjector(plan)
        )
        if any(f.kind == "sigkill" for f in injector.plan) and (
            self.serving.replica_transport != "socket"
        ):
            raise ValueError(
                "the 'sigkill' fault kind kills a real subprocess "
                "replica server — it needs replica_transport='socket' "
                "(and FaultInjector.register_process per target); use "
                "'crash' to script surface-level death elsewhere"
            )
        transport_faults = [
            f.kind for f in injector.plan if f.kind in TRANSPORT_KINDS
        ]
        if transport_faults and self.serving.replica_transport == "inproc":
            raise ValueError(
                f"fault plan contains transport kinds {transport_faults} "
                "but this cluster drives IN-PROCESS replicas "
                "(replica_transport='inproc') — transport faults are "
                "injected at the RPC layer; run with "
                "replica_transport='loopback' (or 'socket') to exercise "
                "them"
            )
        if self.serving.replica_transport == "socket" and any(
            f.kind == "oom" for f in injector.plan
        ):
            raise ValueError(
                "the 'oom' fault kind squeezes the replica's page pool "
                "in-process, which a socket-backed replica does not "
                "expose — use loopback replicas for oom scenarios"
            )
        self.fault_injector = injector
        for rep in list(self.replicas) + self.standbys:
            rep.fault_injector = injector
        return injector

    # ------------------------------------------------------------------
    # submission + placement

    def _tokenize(self, prompt: Union[str, Sequence[int]]):
        if isinstance(prompt, str):
            if self.tokenizer is None:
                raise ValueError("string prompt requires a tokenizer")
            return list(self.tokenizer.encode(prompt)), prompt
        return [int(t) for t in prompt], ""

    def _routable_pos(self, pos: int) -> bool:
        """May the router/failover/migration paths place work at this
        cluster position? DOWN (circuit open) and DRAINING (scale_in in
        progress) are both excluded — the one router-exclusion flow."""
        return (
            self.health[pos].routable
            and self.replicas[pos].index not in self._draining
        )

    def _routable_rep(self, rep: Replica) -> bool:
        return self._routable_pos(self.replicas.index(rep))

    def _drop_sessions(self, pos: int) -> int:
        """Re-home the sessions pinned to the replica at ``pos`` —
        the ONE flow both the DOWN path and the drain path use: each
        session re-pins on its next turn (which also re-seeds, or
        re-homes, the replica's prefix families on survivors)."""
        rep = self.replicas[pos]
        try:
            rpos = self.router.replicas.index(rep)
        except ValueError:
            return 0  # not in the routing pool (e.g. a decode replica)
        dropped = self.router.drop_replica_sessions(rpos)
        if dropped:
            self._log.debug(
                "replica %d: %d session affinities dropped (re-pin on "
                "survivors)", rep.index, dropped,
            )
        return dropped

    def submit(
        self,
        prompt: Union[str, Sequence[int]],
        gen: Optional[GenerationConfig] = None,
        max_new_tokens: Optional[int] = None,
        session_id: Optional[object] = None,
    ) -> int:
        """Route + queue one request; returns its CLUSTER id
        immediately (non-blocking — drive with :meth:`step` or a
        concurrent :meth:`generate`/:meth:`generate_stream`). A shed
        (or no-healthy-replica) request is terminal on return
        (``result`` carries the error)."""
        gen = gen or GenerationConfig()
        if max_new_tokens is not None:
            gen = dataclasses.replace(gen, max_new_tokens=max_new_tokens)
        tokens, text = self._tokenize(prompt)
        cid = self._next_cid
        self._next_cid += 1
        self.stats.submitted += 1
        cr = ClusterRequest(
            cluster_id=cid, tokens=tokens, prompt_text=text, gen=gen,
            session_id=session_id, prompt_len=len(tokens), _manager=self,
        )
        self.requests[cid] = cr
        self._open_cids.add(cid)
        self._place(cr, tokens)
        if self.journal is not None:
            # durable the moment submit returns: the journaled prompt
            # (post-placement — prompt_len is the home's authoritative,
            # possibly truncated, boundary) + GenerationConfig is what a
            # recovered manager re-promises. One record + one flush per
            # SUBMISSION, not per step — then the terminal sweep covers
            # the shed-on-arrival case.
            from .server import gen_to_wire

            self.journal.append({
                "type": "submit", "cid": cid,
                "tokens": [int(t) for t in cr.tokens[:cr.prompt_len]],
                "prompt_len": int(cr.prompt_len),
                "gen": gen_to_wire(gen),
                "session": _wire_session(session_id),
                "prompt": text,
            })
            self._journal_sync()
        return cid

    def _place_failed(self, cr: ClusterRequest, how: str) -> bool:
        cr.rid = None
        cr.replica = None
        if how == "shed":
            cr.error = (
                "shed by SLO admission: every replica's queue-delay "
                f"estimate exceeds slo_queue_delay_s="
                f"{self.serving.slo_queue_delay_s}"
            )
        else:  # "down"
            cr.error = (
                "no healthy replica: every replica is circuit-broken "
                "(DOWN) — the request fails terminally instead of "
                "waiting for a probe that may never succeed"
            )
        tr = self.tracer
        if tr.enabled:
            tr.event("place_failed", trace_id=cr.cluster_id, how=how)
        if self.flight_recorder is not None:
            self.flight_recorder.dump(
                self.tracer.lane or "router", "request_error",
                step=self._step_counter,
                extra={"cluster_id": cr.cluster_id, "how": how},
            )
        return False

    def _place(
        self,
        cr: ClusterRequest,
        known: Sequence[int],
        *,
        ignore_slo: bool = False,
    ) -> bool:
        """Route ``known`` (the prompt, or prompt + tokens generated so
        far on a failover re-admission) and submit it to the chosen
        replica. Returns True when placed; False means TERMINAL — shed,
        or no healthy replica (``cr.error`` set). Failover
        re-admissions pass ``ignore_slo=True``: a request admitted once
        is never shed on its second landing."""
        produced = max(0, len(known) - cr.prompt_len)
        remaining = cr.gen.max_new_tokens - produced
        gen_home = (
            cr.gen if produced == 0
            else dataclasses.replace(cr.gen, max_new_tokens=remaining)
        )
        first = cr.retries == 0
        phase = "single"
        if self.disaggregated and any(
            self._routable_rep(r) for r in self.prefill_pool
        ):
            pos, how = self.router.route(
                known, cr.session_id, ignore_slo=ignore_slo
            )
            if pos is None:
                return self._place_failed(cr, how)
            rep = self.replicas[self._routing_pos[pos]]
            if any(self._routable_rep(r) for r in self.decode_pool):
                phase = "prefill"
            else:
                # decode pool entirely DOWN: non-disaggregated serving
                # on the surviving prefill pool — the chosen replica
                # runs BOTH phases (no hold, no doomed migration)
                self._log.warning(
                    "decode pool is DOWN — request %d served "
                    "single-phase on prefill replica %d",
                    cr.cluster_id, rep.index,
                )
        elif self.disaggregated:
            # prefill pool entirely DOWN: fall back to non-disaggregated
            # serving on the surviving decode pool (ROADMAP'd degrade —
            # the decode replicas prefill too rather than refuse traffic)
            cands = [r for r in self.decode_pool if self._routable_rep(r)]
            if not cands:
                return self._place_failed(cr, "down")
            rep = min(
                cands,
                key=lambda r: (r.queue_delay_s(), r.load(), r.index),
            )
            self.stats.record_placement("pool_fallback")
            self._log.warning(
                "prefill pool is DOWN — request %d served single-phase "
                "on decode replica %d", cr.cluster_id, rep.index,
            )
        else:
            pos, how = self.router.route(
                known, cr.session_id, ignore_slo=ignore_slo
            )
            if pos is None:
                return self._place_failed(cr, how)
            rep = self.replicas[self._routing_pos[pos]]
        delay = rep.queue_delay_s()
        if first:
            # per-replica arrival accounting + the admission-time
            # queue-delay sample (what the router saw, not a later
            # re-read) — the autotune TrafficEstimator's raw inputs
            self.stats.note_arrival(rep.index)
            self.stats.note_queue_delay_s(delay)
        cr.replica = self.replicas.index(rep)
        cr.phase = phase
        if phase == "prefill":
            # prefill pass only: max_new_tokens=1 makes the prefill-final
            # dispatch (which samples the first output token on device)
            # the request's LAST step there — the chunked-prefill
            # boundary — and the held slot keeps its pages alive for
            # the migration that follows
            cr.rid = rep.rm.submit(
                known, dataclasses.replace(gen_home, max_new_tokens=1),
                trace_id=cr.cluster_id,
            )
            rep.rm.hold_on_finish(cr.rid)
        else:
            cr.rid = rep.rm.submit(known, gen_home,
                                   trace_id=cr.cluster_id)
        req = rep.rm.requests[cr.rid]
        if first:
            req.profile.replica_id = rep.index
            req.profile.router_queue_delay_s = delay
            cr.profile = req.profile
            # the home may have truncated an over-long prompt — its
            # prompt_len is the authoritative output boundary
            cr.prompt_len = req.prompt_len
        else:
            # re-admission: keep the ORIGINAL profile (start time, TTFT)
            # on the new home and record the move on it
            req.profile = cr.profile
            cr.profile.retries = cr.retries
            cr.profile.failover_replica_id = rep.index
            cr.profile.replica_id = rep.index
            cr.profile.router_queue_delay_s = delay
        cr._known = None
        tr = self.tracer
        if tr.enabled:
            tr.event(
                "place", trace_id=cr.cluster_id, replica=rep.index,
                phase=phase, retries=cr.retries,
            )
        return True

    # convenience alias (c_backend drives both manager kinds identically)
    def register_request(
        self,
        prompt: Union[str, Sequence[int]],
        gen: Optional[GenerationConfig] = None,
    ) -> int:
        return self.submit(prompt, gen)

    # ------------------------------------------------------------------
    # fault handling: health transitions + failover re-admission

    def _note_transition(self, pos: int, transition: Optional[str],
                         exc: Optional[BaseException] = None) -> None:
        if transition is None:
            return
        rep = self.replicas[pos]
        tr = self.tracer
        if tr.enabled:
            # health transitions land on the AFFECTED replica's lane so
            # a flight-recorder dump of that lane ends with them
            tr.event(
                "health", lane=f"replica{rep.index}", replica=rep.index,
                state=transition,
                error=str(self.health[pos].last_error or "")[:200],
            )
        if transition == "suspect":
            self.stats.replica_suspect += 1
            self._log.warning(
                "replica %d SUSPECT: %s", rep.index,
                self.health[pos].last_error,
            )
        elif transition == "recovered":
            self.stats.replica_recoveries += 1
            self._log.warning("replica %d recovered (circuit closed)",
                              rep.index)
        elif transition == "down":
            self.stats.replica_down += 1
            # capture the machine's recorded trip BEFORE failover runs
            # (_adopt_standby may replace the health record)
            down_at = self.health[pos].down_at_step
            self._on_replica_down(pos, exc)
            if self.flight_recorder is not None:
                self.flight_recorder.dump(
                    f"replica{rep.index}", "replica_down",
                    step=self._step_counter,
                    extra={
                        "replica_index": rep.index,
                        "health_state": HealthState.DOWN.value,
                        "down_at_step": down_at,
                    },
                )

    def _on_replica_down(self, pos: int,
                         exc: Optional[BaseException]) -> None:
        """The breaker opened: fail every request on the replica over
        to survivors (recompute re-admission), drop its session pins
        (they re-pin — which also re-seeds its prefix families on
        survivors), and tear its scheduler state down so a later probe
        re-admission starts clean."""
        rep = self.replicas[pos]
        self._log.warning(
            "replica %d DOWN (%s) — failing over its requests",
            rep.index, exc if exc is not None else
            self.health[pos].last_error,
        )
        if rep.index in self._draining:
            # died mid-drain: the DOWN path owns it now (failover +
            # standby adoption); the scale_in never commits and its
            # journaled begin recovers as "never happened"
            self._draining.discard(rep.index)
        self._drop_sessions(pos)
        victims = [
            cr for cr in self.requests.values()
            if cr.rid is not None and cr.replica == pos
            and cr.status not in TERMINAL_STATUSES
        ]
        for cr in victims:
            req = rep.rm.requests[cr.rid]
            # the host token list only ever holds FLUSHED truth — the
            # recompute re-admission regenerates anything in flight
            cr._known = list(req.tokens)
            cr.rid = None
            cr.replica = None
            cr.phase = "single"
            self._schedule_failover(cr)
        # queued migrations whose source died are failover victims now
        self._migration_queue = [
            c for c in self._migration_queue
            if self.requests[c].rid is not None
        ]
        self._mig_queued = set(self._migration_queue)
        try:
            rep.abandon()
        except Exception as abandon_exc:  # the pool may be torn mid-step
            self._log.warning(
                "replica %d abandon() failed (%s) — its pool is "
                "excluded from audits until it recovers",
                rep.index, abandon_exc,
            )
        if self.standbys:
            self._adopt_standby(pos)

    def _adopt_standby(self, pos: int) -> None:
        """A warm standby takes the dead replica's routing position:
        the dead replica's prefix radix tree — block keys + page bytes,
        host-spilled pages included — ships over the transport and
        re-admits on the standby (best-effort: an unreachable process
        means a COLD join, capacity is still replaced), then the
        standby enters routing at ``pos``. The dead replica retires
        permanently (its health record is replaced by the standby's
        fresh one, so it never probes back) — failover re-admissions
        and re-pinned sessions land on a warm tree instead of survivors
        re-seeding the families cold."""
        dead = self.replicas[pos]
        standby = self.standbys.pop(0)
        blocks = 0
        try:
            entries = dead.export_prefix_tree()
            if entries:
                blocks = standby.import_prefix_tree(entries)
        except Exception as exc:
            self._log.warning(
                "standby adoption: prefix-tree export from dead replica "
                "%d failed (%s) — standby %d joins COLD",
                dead.index, exc, standby.index,
            )
        self.replicas[pos] = standby
        try:
            rpos = self._routing_pos.index(pos)
        except ValueError:
            rpos = None
        if rpos is not None:
            self.router.replicas[rpos] = standby
        # a fresh health record: the standby starts HEALTHY and the
        # retired replica can never probe back into this position
        self.health.replicas[pos] = ReplicaHealth(pos, self.health.cfg)
        self._retired.append(dead)
        self.stats.standby_adoptions += 1
        self._log.warning(
            "standby replica %d adopted position %d (%d prefix blocks "
            "warm; %d standbys remain)",
            standby.index, pos, blocks, len(self.standbys),
        )

    def _schedule_failover(self, cr: ClusterRequest) -> None:
        """Bounded retries with exponential (cluster-step) backoff; past
        the bound the request fails terminally — never a hang."""
        cr.retries += 1
        self.stats.retries += 1
        if cr.retries > self.serving.failover_retries:
            cr.error = (
                f"replica failed and failover retries exhausted "
                f"({cr.retries - 1} re-admissions, failover_retries="
                f"{self.serving.failover_retries})"
            )
            self.stats.failover_errors += 1
            tr = self.tracer
            if tr.enabled:
                tr.event("request_error", trace_id=cr.cluster_id,
                         reason="failover_exhausted")
            if self.flight_recorder is not None:
                self.flight_recorder.dump(
                    self.tracer.lane or "router", "request_error",
                    step=self._step_counter,
                    extra={"cluster_id": cr.cluster_id,
                           "error": cr.error[:500]},
                )
            return
        backoff = (
            0 if cr.retries == 1
            else self.serving.failover_backoff_steps
            * (2 ** (cr.retries - 2))
        )
        cr._retry_at_step = self._step_counter + backoff
        self._failovers.append(cr.cluster_id)

    def _run_failovers(self) -> bool:
        """Re-admit requests whose backoff expired. A request that
        cannot be placed (no healthy replica) fails terminally."""
        if not self._failovers:
            return False
        progressed = False
        still: List[int] = []
        for cid in self._failovers:
            cr = self.requests[cid]
            if cr.error is not None or cr.rid is not None:
                continue
            if self._step_counter < cr._retry_at_step:
                still.append(cid)
                continue
            try:
                placed = self._place(cr, cr._known, ignore_slo=True)
            except Exception as exc:
                # the chosen home refused the submission (e.g. a
                # recovered manager re-admitting onto a replica whose
                # server died with the old manager, before the gap
                # detector trips it) — a health observation + another
                # bounded retry, never an exception out of the drive
                # loop
                pos = cr.replica
                cr.rid = None
                cr.replica = None
                if pos is not None:
                    self._observe_failure(pos, exc, self._step_counter)
                self._schedule_failover(cr)
                progressed = True
                continue
            if placed:
                self.stats.failovers += 1
                progressed = True
                tr = self.tracer
                if tr.enabled:
                    tr.event(
                        "failover", trace_id=cid,
                        replica=cr.profile.failover_replica_id,
                        retry=cr.retries,
                    )
                self._log.warning(
                    "failover: request %d re-admitted on replica %d "
                    "(retry %d, %d tokens recomputed)",
                    cid, cr.profile.failover_replica_id, cr.retries,
                    len(cr.tokens),
                )
            else:
                self.stats.failover_errors += 1
                progressed = True
        self._failovers = still
        return progressed

    # ------------------------------------------------------------------
    # prefill→decode migration (bounded queue + back-pressure)

    def _queue_migrations(self) -> None:
        """Move newly completed held prefills into the migration FIFO
        (finishing the ones that owe no decode phase), then apply the
        back-pressure budget: entries past it release their held pages
        and drain through recompute re-admission instead of parking."""
        for cid, cr in list(self.requests.items()):
            if (
                cr.phase != "prefill" or cr.rid is None
                or cid in self._mig_queued
            ):
                continue
            src = self.replicas[cr.replica]
            req = src.rm.requests[cr.rid]
            if req.status not in TERMINAL_STATUSES or req.pipeline_refs:
                continue
            if req.status is RequestStatus.ERROR:
                # unservable on the prefill pool (PR-2 ERROR path) — the
                # cluster request is terminal with that error
                src.rm.release_held(cr.rid)
                cr.phase = "single"
                continue
            done = len(req.tokens) >= self.serving.max_sequence_length
            if req.tokens[req.prompt_len:]:
                last = req.tokens[-1]
                stops = set(cr.gen.stop_token_ids)
                if self.eos_token_id is not None:
                    stops.add(self.eos_token_id)
                remaining = cr.gen.max_new_tokens - (
                    len(req.tokens) - cr.prompt_len
                )
                done = done or last in stops or remaining <= 0
            if done:
                # 1-token budget, a stop token, or max length — no
                # decode phase owed: it finished on the prefill replica
                src.rm.release_held(cr.rid)
                cr.phase = "single"
                continue
            self._migration_queue.append(cid)
            self._mig_queued.add(cid)
        budget = self.serving.migration_queue_budget
        if budget is not None:
            while len(self._migration_queue) > budget:
                # newest entries overflow (FIFO heads keep their pages —
                # they hand off next); the overflow recomputes instead
                cid = self._migration_queue.pop()
                self._mig_queued.discard(cid)
                self.stats.migration_queue_overflows += 1
                self._recompute_readmit(cid)
        depth = len(self._migration_queue)
        self.stats.migration_queue_depth = depth
        self.stats.migration_queue_peak = max(
            self.stats.migration_queue_peak, depth
        )

    def _drain_migration_queue(self) -> bool:
        """Hand queued prefills to the decode pool: page migration when
        a healthy decode replica has capacity; recompute re-admission
        when the decode pool is gone or a migration keeps failing."""
        if not self._migration_queue:
            return False
        progressed = False
        remaining_q: List[int] = []
        for cid in self._migration_queue:
            cr = self.requests[cid]
            if cr.rid is None or cr.error is not None:
                continue  # source died — the failover path owns it now
            if self._step_counter < cr._retry_at_step:
                remaining_q.append(cid)  # migration-failure backoff
                continue
            src = self.replicas[cr.replica]
            req = src.rm.requests[cr.rid]
            dsts = [r for r in self.decode_pool if self._routable_rep(r)]
            if not dsts:
                # decode pool entirely DOWN: fall back to
                # non-disaggregated serving on the surviving pool —
                # recompute re-admission frees the held pages and the
                # prefill replica (or any survivor) serves the decode
                # phase itself
                self._recompute_readmit(cid)
                progressed = True
                continue
            dst = min(
                dsts,
                key=lambda r: (r.queue_delay_s(), r.load(), r.index),
            )
            # the decode side runs the REMAINING budget: after a
            # failover the home's prompt already carries generated
            # tokens, and the dst counts generation from its own
            # adopted baseline (= the home's prompt_len)
            gen_dst = dataclasses.replace(
                cr.gen,
                max_new_tokens=cr.gen.max_new_tokens
                - (req.prompt_len - cr.prompt_len),
            )
            try:
                rid_dst = migrate_request(
                    src, dst, cr.rid, gen_dst,
                    stats=self.stats, injector=self.fault_injector,
                    trace_id=cr.cluster_id, tracer=self.tracer,
                )
            except Exception as exc:
                self.stats.migration_failures += 1
                cr.mig_attempts += 1
                self._log.warning(
                    "migration of request %d -> replica %d failed "
                    "(attempt %d): %s", cid, dst.index,
                    cr.mig_attempts, exc,
                )
                if cr.mig_attempts > self.serving.failover_retries:
                    self._recompute_readmit(cid)
                else:
                    cr._retry_at_step = self._step_counter + (
                        self.serving.failover_backoff_steps
                        * (2 ** (cr.mig_attempts - 1))
                    )
                    remaining_q.append(cid)
                progressed = True
                continue
            if rid_dst is None:
                remaining_q.append(cid)  # dst full right now — waits
                continue
            src.rm.release_held(cr.rid)
            cr.replica = self.replicas.index(dst)
            cr.rid = rid_dst
            cr.phase = "decode"
            cr.profile.replica_id = dst.index
            progressed = True
        self._migration_queue = remaining_q
        self._mig_queued = set(remaining_q)
        self.stats.migration_queue_depth = len(remaining_q)
        return progressed

    def _recompute_readmit(self, cid: int) -> None:
        """Drain one held prefill WITHOUT moving pages: release the
        hold (its pages free immediately) and resubmit prompt + first
        token through the recompute path on the best surviving replica
        — the decode pool when any of it is healthy, else any healthy
        replica. The re-prefill is the back-pressure price (warm where
        prefix caching holds the prompt); greedy outputs stay bitwise."""
        cr = self.requests[cid]
        src = self.replicas[cr.replica]
        req = src.rm.requests[cr.rid]
        known = list(req.tokens)
        src.rm.release_held(cr.rid)
        cr.rid = None
        cr.replica = None
        cr.phase = "single"
        cr.retries += 1
        self.stats.retries += 1
        cands = [r for r in self.decode_pool if self._routable_rep(r)] or [
            r for r in self.replicas if self._routable_rep(r)
        ]
        if not cands:
            cr._known = known
            cr.error = (
                "no healthy replica to drain the held prefill to — "
                "the request fails terminally instead of parking"
            )
            self.stats.failover_errors += 1
            return
        rep = min(
            cands, key=lambda r: (r.queue_delay_s(), r.load(), r.index)
        )
        produced = len(known) - cr.prompt_len
        gen_home = dataclasses.replace(
            cr.gen, max_new_tokens=cr.gen.max_new_tokens - produced
        )
        cr.rid = rep.rm.submit(known, gen_home, trace_id=cr.cluster_id)
        cr.replica = self.replicas.index(rep)
        rep.rm.requests[cr.rid].profile = cr.profile
        cr.profile.retries = cr.retries
        cr.profile.failover_replica_id = rep.index
        cr.profile.replica_id = rep.index
        tr = self.tracer
        if tr.enabled:
            tr.event("recompute_readmit", trace_id=cid,
                     replica=rep.index, n_tokens=len(known))
        self._log.debug(
            "migration back-pressure: request %d drained to replica %d "
            "via recompute (%d tokens re-prefill)",
            cid, rep.index, len(known),
        )

    # ------------------------------------------------------------------
    # the drive loop

    def _observe_failure(self, pos: int, exc: BaseException,
                         step_no: int) -> None:
        """ONE health failure observation per replica per cluster step
        — an RPC-erroring replica that is also inside a heartbeat gap
        must not burn through ``failure_threshold`` twice as fast as a
        plain crashing one (the PR-9 arithmetic is the contract)."""
        if pos in self._failed_obs:
            return
        self._failed_obs.add(pos)
        self._note_transition(
            pos, self.health[pos].record_failure(exc, step_no), exc
        )

    def _check_gap(self, pos: int, rep, step_no: int) -> None:
        """Heartbeat-gap detection, in deterministic CLUSTER steps: no
        successful exchange for ``heartbeat_gap_steps`` steps is a
        SUSPECT observation each step until contact resumes (or the
        breaker trips)."""
        gap = step_no - rep.last_contact_step
        if gap >= self.serving.heartbeat_gap_steps:
            self.stats.heartbeat_gaps += 1
            tr = self.tracer
            if tr.enabled:
                tr.event("heartbeat_gap", replica=rep.index, gap=gap)
            self._observe_failure(
                pos,
                HeartbeatGap(
                    f"replica {rep.index}: no successful exchange for "
                    f"{gap} cluster steps"
                ),
                step_no,
            )

    def _heartbeat_remote(self, pos: int, rep, step_no: int) -> None:
        """Idle remote replicas stay observable: a heartbeat every
        ``heartbeat_interval_steps`` refreshes the telemetry mirror
        (SchedulerStats + the queue-delay inputs the router reads) and
        stamps contact; a FAILED heartbeat is silent on its own (the
        loss is retried/absorbed at the transport) — sustained loss
        surfaces through :meth:`_check_gap`."""
        due = (
            step_no - rep.last_contact_step
            >= self.serving.heartbeat_interval_steps
        )
        if due and rep.heartbeat():
            rep.last_contact_step = step_no
            return
        self._check_gap(pos, rep, step_no)

    def _step_replicas_serial(self, step_no: int) -> bool:
        """The original one-RPC-at-a-time drive loop — kept verbatim as
        the reference arm (``ServingConfig.concurrent_stepping=False``,
        and what the in-process cluster runs): the concurrent loop's
        contract is to be indistinguishable from THIS, and the
        ``serve_cluster_async`` bench measures the two against each
        other."""
        progressed = False
        for pos in range(len(self.replicas)):
            rep = self.replicas[pos]
            h = self.health[pos]
            if h.state is HealthState.DOWN:
                if h.maybe_probe(step_no):
                    self.stats.probes += 1
                    if self.tracer.enabled:
                        self.tracer.event("probe", replica=rep.index,
                                          backoff=h.backoff_steps)
                    self._log.warning(
                        "replica %d probing (circuit half-open after "
                        "%d-step backoff)", rep.index, h.backoff_steps,
                    )
                    progressed = True
                else:
                    continue
            remote = getattr(rep, "is_remote", False)
            if not rep.has_work():
                if remote:
                    self._heartbeat_remote(pos, rep, step_no)
                continue
            t0 = time.perf_counter()
            try:
                stepped = rep.step()
            except Exception as exc:
                self.stats.step_faults += 1
                self._observe_failure(pos, exc, step_no)
                if (
                    remote and rep is self.replicas[pos]
                    and self.health[pos].state is not HealthState.DOWN
                ):
                    self._check_gap(pos, rep, step_no)
                progressed = True
                continue
            if remote:
                rep.last_contact_step = step_no
                self.stats.note_rpc_rtt_ms(
                    rep.index, (time.perf_counter() - t0) * 1000.0
                )
            latency = (time.perf_counter() - t0) + rep.injected_latency_s
            self._note_transition(
                pos, h.record_success(latency, step_no, had_work=True)
            )
            progressed = stepped or progressed
        return progressed

    def _step_replicas_concurrent(self, step_no: int) -> bool:
        """Fan-out drive loop: ISSUE every routable replica's step RPC
        (and every due idle-replica heartbeat) without blocking, then
        HARVEST and apply results in replica-index order — N wire
        round-trips overlap into one (O(RTT), not O(N·RTT)).

        Determinism contract: completion order NEVER changes cluster
        behavior. Issue runs in replica-index order and only touches
        per-replica state (fault kinds fire at the serial loop's call
        site; ``has_work``/heartbeat-due reads are position-local, and
        nothing the apply phase mutates — health transitions, failover
        enqueues, migration queues — feeds back into another position's
        issue decision inside the same step; those all settle AFTER the
        loop, exactly as in the serial arm). Apply runs in
        replica-index order on the manager's thread, so the PR-9 health
        machine, the one-observation-per-step guard, failover order and
        journal semantics see the SAME sequence of observations the
        serial loop produced, no matter how responses interleaved on
        the wire."""
        progressed = False
        plan: list = []  # (pos, rep, kind, payload) in replica order
        inflight = 0
        for pos in range(len(self.replicas)):
            rep = self.replicas[pos]
            h = self.health[pos]
            if h.state is HealthState.DOWN:
                if h.maybe_probe(step_no):
                    self.stats.probes += 1
                    if self.tracer.enabled:
                        self.tracer.event("probe", replica=rep.index,
                                          backoff=h.backoff_steps)
                    self._log.warning(
                        "replica %d probing (circuit half-open after "
                        "%d-step backoff)", rep.index, h.backoff_steps,
                    )
                    progressed = True
                else:
                    continue
            remote = getattr(rep, "is_remote", False)
            if not rep.has_work():
                if remote:
                    due = (
                        step_no - rep.last_contact_step
                        >= self.serving.heartbeat_interval_steps
                    )
                    if due:
                        plan.append(
                            (pos, rep, "hb", rep.heartbeat_async())
                        )
                        inflight += 1
                    else:
                        plan.append((pos, rep, "gap", None))
                continue
            t0 = time.perf_counter()
            if not remote:
                # no wire to overlap — the local step runs where the
                # serial loop ran it, its outcome applies in order
                try:
                    stepped = rep.step()
                except Exception as exc:
                    plan.append((pos, rep, "step_fail", exc))
                else:
                    lat = (
                        (time.perf_counter() - t0)
                        + rep.injected_latency_s
                    )
                    plan.append((pos, rep, "step_done", (stepped, lat)))
                continue
            try:
                call = rep.step_async()
            except Exception as exc:
                # replica-kind fault / abandon replay failed at issue —
                # the serial loop's step() raised at the same point
                plan.append((pos, rep, "step_fail", exc))
            else:
                plan.append((pos, rep, "step", (t0, call)))
                inflight += 1
        if inflight > self.stats.rpc_inflight_peak:
            self.stats.rpc_inflight_peak = inflight
        for pos, rep, kind, payload in plan:
            if kind == "gap":
                self._check_gap(pos, rep, step_no)
            elif kind == "hb":
                if rep.finish_heartbeat(payload):
                    rep.last_contact_step = step_no
                else:
                    self._check_gap(pos, rep, step_no)
            elif kind == "step_fail":
                progressed = self._apply_step_failure(
                    pos, rep, payload, step_no
                ) or progressed
            elif kind == "step_done":
                stepped, latency = payload
                self._note_transition(
                    pos,
                    self.health[pos].record_success(
                        latency, step_no, had_work=True
                    ),
                )
                progressed = stepped or progressed
            else:  # "step" — harvest the remote ticket
                t0, call = payload
                try:
                    stepped = rep.finish_step(call)
                except Exception as exc:
                    progressed = self._apply_step_failure(
                        pos, rep, exc, step_no
                    ) or progressed
                    continue
                rep.last_contact_step = step_no
                done = (
                    call.completed_at if call.completed_at is not None
                    else time.perf_counter()
                )
                self.stats.note_rpc_rtt_ms(
                    rep.index, max(0.0, done - t0) * 1000.0
                )
                latency = max(0.0, done - t0) + rep.injected_latency_s
                self._note_transition(
                    pos,
                    self.health[pos].record_success(
                        latency, step_no, had_work=True
                    ),
                )
                progressed = stepped or progressed
        return progressed

    def _apply_step_failure(self, pos: int, rep, exc: BaseException,
                            step_no: int) -> bool:
        """The serial loop's step-exception arm, shared by the
        concurrent loop's issue and harvest phases — one failure
        observation (guarded per step), plus the gap check for a
        still-installed remote that is not yet DOWN."""
        self.stats.step_faults += 1
        self._observe_failure(pos, exc, step_no)
        if (
            getattr(rep, "is_remote", False)
            and rep is self.replicas[pos]
            and self.health[pos].state is not HealthState.DOWN
        ):
            self._check_gap(pos, rep, step_no)
        return True

    def step(self) -> bool:
        """One cluster step: advance every steppable replica under the
        health monitor (remote replicas additionally heartbeat when
        idle, with gap detection in cluster steps), settle
        prefill→decode migrations, then run any due failover
        re-admissions. Returns False when no replica has work left and
        nothing is pending recovery.

        With ``ServingConfig.concurrent_stepping`` (the default) and
        any remote members, the per-replica RPCs fan out concurrently
        and the step costs ~one round-trip; results still apply in
        replica-index order (see :meth:`_step_replicas_concurrent` for
        the determinism contract)."""
        t_step = time.perf_counter()
        self._step_counter += 1
        step_no = self._step_counter
        if self.fault_injector is not None:
            # scripted manager death (FaultPlan "manager_crash"): the
            # checkpoint-kill raises HERE, before any replica steps —
            # the test/bench recovers from the journal where a real
            # SIGKILL would restart the process
            self.fault_injector.on_cluster_step(self)
        tr = self.tracer
        if tr.enabled and self._pending_trace:
            # recovery ran before a tracer could attach — its
            # recover/replay events flush on the first traced step
            for name, kw in self._pending_trace:
                tr.event(name, **kw)
            self._pending_trace = []
        self._failed_obs = set()
        concurrent = (
            getattr(self.serving, "concurrent_stepping", True)
            and any(getattr(r, "is_remote", False) for r in self.replicas)
        )
        if concurrent:
            progressed = self._step_replicas_concurrent(step_no)
        else:
            progressed = self._step_replicas_serial(step_no)
        if self.disaggregated:
            self._queue_migrations()
            progressed = self._drain_migration_queue() or progressed
        progressed = self._run_failovers() or progressed
        progressed = _maybe_retire(self) or progressed
        if self._failovers or self._migration_queue:
            # pending recoveries keep the drive loop alive through their
            # backoff windows — a generate() must never break out and
            # strand a request between homes
            progressed = True
        # completion sweep + autoscale BEFORE the journal sync: a
        # policy decision's records (and the scale ops' begin records)
        # batch into the same durable flush as the step that made them
        self._sweep_completions()
        if self.autoscaler is not None:
            self.autoscaler.on_step(step_no)
        # journal sync point: flushed-token deltas + newly terminal
        # records batch into ONE buffered write + file flush per step
        self._journal_sync()
        self.stats.note_cluster_step_ms(
            (time.perf_counter() - t_step) * 1000.0
        )
        if step_no % 200 == 0:
            self._log.debug(
                "%s", self.stats.report([r.rm.stats for r in self.replicas])
            )
        return progressed

    def drain(self) -> None:
        """Flush every healthy replica's pipeline, then settle any
        migrations those flushes unblocked (a prefill pass whose
        completion was still in the pipeline hands its pages off here;
        the adopted decode work itself is driven by later :meth:`step`
        calls, same as RequestManager.drain never runs new steps). A
        flush failure is a replica failure — same health path as a
        step exception."""
        for pos, rep in enumerate(self.replicas):
            if self.health[pos].state is HealthState.DOWN:
                continue
            try:
                rep.drain()
            except Exception as exc:
                self.stats.step_faults += 1
                self._note_transition(
                    pos,
                    self.health[pos].record_failure(exc, self._step_counter),
                    exc,
                )
        if self.disaggregated:
            self._queue_migrations()
            self._drain_migration_queue()
        self._run_failovers()
        _maybe_retire(self)
        self._sweep_completions()
        self._journal_sync()

    def _sweep_completions(self) -> None:
        """Settle per-replica completion accounting for requests that
        went terminal since the last sweep: counters on ClusterStats,
        and ``(prompt_len, output_len)`` pairs into the completion
        window the autotune TrafficEstimator drains. Errored requests
        leave the open set but do NOT enter the window — a shed
        request's zero-length output is not a service-time sample."""
        if not self._open_cids:
            return
        closed = []
        for cid in self._open_cids:
            cr = self.requests.get(cid)
            if cr is None:
                closed.append(cid)
                continue
            st = cr.status
            if st not in TERMINAL_STATUSES:
                continue
            closed.append(cid)
            if st is RequestStatus.ERROR:
                continue
            produced = len(cr.output_tokens)
            self._completion_window.append((cr.prompt_len, produced))
            rep_idx = int(cr.profile.replica_id)
            if rep_idx >= 0:
                self.stats.note_completion(rep_idx)
        for cid in closed:
            self._open_cids.discard(cid)
        # bound the window even if nobody drains it (no autoscaler)
        if len(self._completion_window) > 4096:
            del self._completion_window[:-4096]

    def drain_completion_window(self) -> List[Tuple[int, int]]:
        """Hand over (and clear) the ``(prompt_len, output_len)`` pairs
        of requests that finished since the last call — the autotune
        TrafficEstimator's per-observation completion feed."""
        window, self._completion_window = self._completion_window, []
        return window

    def _attach_autoscaler(self) -> None:
        # lazy import: serve.cluster must not depend on serve.autotune
        # at import time (autotune imports the cost model stack)
        from ..autotune.policy import Autoscaler

        self.autoscaler = Autoscaler.from_manager(self)

    # ------------------------------------------------------------------
    # results

    def cluster_stats(self) -> Dict[str, object]:
        """ClusterStats snapshot over the live per-replica stats."""
        return self.stats.snapshot([r.rm.stats for r in self.replicas])

    def health_snapshot(self) -> List[str]:
        return self.health.snapshot()

    def check_no_leaks(self) -> None:
        """Page-pool audits on every replica that is NOT circuit-broken
        — a DOWN replica's pool is unreachable (on multi-host it is
        gone with the process), not leaked; it re-enters the audit set
        the moment it probes back."""
        for pos, rep in enumerate(self.replicas):
            if self.health[pos].state is HealthState.DOWN:
                continue
            rep.check_no_leaks()

    def result(self, cid: int) -> GenerationResult:
        cr = self.requests[cid]
        out = cr.output_tokens
        text = (
            self.tokenizer.decode(out) if self.tokenizer is not None else ""
        )
        error = cr.error
        if error is None and cr.rid is not None:
            error = self.replicas[cr.replica].rm.requests[cr.rid].error
        return GenerationResult(
            request_id=cid,
            prompt=cr.prompt_text,
            input_tokens=list(cr.tokens),
            output_tokens=list(out),
            output_text=text,
            profile=cr.profile,
            error=error,
        )

    def _terminal(self, cid: int) -> bool:
        return self.requests[cid].status in TERMINAL_STATUSES

    def generate(
        self,
        prompts: Union[str, Sequence[Union[str, Sequence[int]]]],
        gen: Optional[GenerationConfig] = None,
        max_new_tokens: Optional[int] = None,
        session_ids: Optional[Sequence[object]] = None,
    ) -> List[GenerationResult]:
        """Blocking generate across the cluster (router-placed)."""
        if isinstance(prompts, str):
            prompts = [prompts]
        cids = [
            self.submit(
                p, gen, max_new_tokens,
                session_id=session_ids[i] if session_ids else None,
            )
            for i, p in enumerate(prompts)
        ]
        while any(not self._terminal(c) for c in cids):
            if not self.step():
                break
        self.drain()
        return [self.result(c) for c in cids]

    def generate_stream(
        self,
        prompts: Union[str, Sequence[Union[str, Sequence[int]]]],
        gen: Optional[GenerationConfig] = None,
        max_new_tokens: Optional[int] = None,
        session_ids: Optional[Sequence[object]] = None,
    ) -> Iterator[StreamEvent]:
        """Streaming generate across the cluster: one StreamEvent per
        drained token (``request_id`` is the CLUSTER id) + a terminal
        event per request (``error`` set for sheds/failures). Token
        counts are monotone across a migration — the first output token
        is visible on both sides of the hand-off, so nothing is dropped
        or re-sent — and across a failover: the re-admission's known
        tokens are exactly the flushed (= streamed) prefix, so the
        stream resumes where it stopped."""
        if isinstance(prompts, str):
            prompts = [prompts]
        cids = [
            self.submit(
                p, gen, max_new_tokens,
                session_id=session_ids[i] if session_ids else None,
            )
            for i, p in enumerate(prompts)
        ]
        sent = {c: 0 for c in cids}
        finished: set = set()

        def drain_events():
            for c in cids:
                if c in finished:
                    continue
                cr = self.requests[c]
                out = cr.output_tokens
                while sent[c] < len(out):
                    tok = out[sent[c]]
                    sent[c] += 1
                    yield StreamEvent(c, int(tok))
                if self._terminal(c):
                    finished.add(c)
                    err = cr.error
                    if err is None and cr.rid is not None:
                        home = self.replicas[cr.replica].rm
                        err = home.requests[cr.rid].error
                    yield StreamEvent(c, None, done=True, error=err)

        while len(finished) < len(cids):
            progressed = self.step()
            yield from drain_events()
            if not progressed and len(finished) < len(cids):
                self.drain()
                yield from drain_events()
                if len(finished) < len(cids):
                    break  # nothing schedulable remains — avoid spinning
        self.drain()
        yield from drain_events()
