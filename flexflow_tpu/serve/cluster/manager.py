"""ClusterManager — one process driving N engine replicas.

The cluster front-end: RequestManager-shaped API (``submit`` /
``step`` / ``drain`` / ``generate`` / ``generate_stream`` / ``result``)
over a pool of :class:`Replica` (each its own engine, mesh and KV pool)
behind a :class:`Router`. The manager owns cluster-level request
identity (cluster ids are independent of any replica's guids), the
per-step drive loop over every replica's scheduler, and — under
disaggregation — the prefill→decode page migrations.

Request lifecycle::

    submit ──router──┬── shed ──────────────→ ERROR (terminal, PR-2 contract)
                     ├── mixed replica ─────→ prefill+decode there ("single")
                     └── prefill replica ───→ prefill, max_new_tokens=1
                             │ held slot        ("prefill")
                             └─ COMPLETED → migrate pages → decode replica
                                             adopts into DECODING ("decode")

Sheds come from SLO admission (``ServingConfig.slo_queue_delay_s``):
they surface as ``GenerationResult.error`` exactly like the PR-2
unservable-request path — a shed request is terminal the moment it is
submitted and can never hang a ``generate()``/stream/C-host loop.

With ``replicas=1`` the manager routes everything to replica 0 and the
replica runs the bit-for-bit single-engine scheduler — the router adds
bookkeeping, never a different step sequence (asserted bitwise in
tests/test_cluster.py).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, Iterator, List, Optional, Sequence, Union

from ...logging_utils import get_logger
from ...metrics import ClusterStats
from ..batch_config import (
    GenerationConfig,
    GenerationResult,
    ProfileInfo,
    StreamEvent,
)
from ..engine import ServingConfig
from ..request_manager import TERMINAL_STATUSES, RequestStatus
from .migration import migrate_request
from .replica import Replica
from .router import Router


@dataclasses.dataclass
class ClusterRequest:
    """Cluster-level view of one request: where it lives now (replica
    position + replica-local rid) and which phase of the disaggregated
    lifecycle it is in. ``rid is None`` iff the request was shed."""

    cluster_id: int
    tokens: List[int]
    prompt_text: str
    gen: GenerationConfig
    session_id: Optional[object] = None
    replica: Optional[int] = None       # position into manager.replicas
    rid: Optional[int] = None           # replica-local request id
    phase: str = "single"               # "single" | "prefill" | "decode"
    error: Optional[str] = None         # shed reason (rid is None)
    profile: ProfileInfo = dataclasses.field(default_factory=ProfileInfo)

    _manager: Any = dataclasses.field(default=None, repr=False)

    @property
    def status(self) -> RequestStatus:
        """RequestStatus-shaped view (c_backend drives clusters through
        the same loop it drives a bare RequestManager with)."""
        if self.rid is None:
            return RequestStatus.ERROR
        home = self._manager.replicas[self.replica].rm
        st = home.requests[self.rid].status
        if self.phase == "prefill" and st in TERMINAL_STATUSES:
            # completed ON THE PREFILL POOL means "awaiting migration",
            # not done — unless the manager decided it finished there
            return (
                st if st is RequestStatus.ERROR
                else RequestStatus.DECODING
            )
        return st

    @property
    def output_tokens(self) -> List[int]:
        if self.rid is None:
            return []
        home = self._manager.replicas[self.replica].rm
        return home.requests[self.rid].output_tokens


class ClusterManager:
    """Drive ``replicas`` behind a router (see module docstring)."""

    def __init__(
        self,
        replicas: Sequence[Replica],
        serving: ServingConfig,
        *,
        router: Optional[Router] = None,
        tokenizer: Any = None,
        eos_token_id: Optional[int] = None,
    ):
        serving.validate_cluster()
        if len(replicas) != serving.replicas:
            raise ValueError(
                f"ServingConfig.replicas={serving.replicas} but "
                f"{len(replicas)} replicas were built"
            )
        self.serving = serving
        self.replicas = list(replicas)
        self.tokenizer = tokenizer
        self.eos_token_id = eos_token_id
        if eos_token_id is None and tokenizer is not None:
            self.eos_token_id = getattr(tokenizer, "eos_token_id", None)
        self.stats = ClusterStats()
        self.prefill_pool = [r for r in self.replicas if r.role == "prefill"]
        self.decode_pool = [r for r in self.replicas if r.role == "decode"]
        self.disaggregated = bool(self.prefill_pool)
        if self.disaggregated and not self.decode_pool:
            raise ValueError("prefill pool without a decode pool")
        routing = self.prefill_pool if self.disaggregated else self.replicas
        self.router = router or Router(
            routing,
            serving.router_policy,
            slo_queue_delay_s=serving.slo_queue_delay_s,
            stats=lambda: self.stats,
        )
        # router positions index the ROUTING pool; map back to cluster
        # positions so ClusterRequest.replica is always cluster-wide
        self._routing_pos = [self.replicas.index(r) for r in routing]
        self.requests: Dict[int, ClusterRequest] = {}
        self._next_cid = 1
        self._step_counter = 0
        self._log = get_logger("serve")

    # ------------------------------------------------------------------
    # construction

    @classmethod
    def build(
        cls,
        model: Any,
        cfg: Any,
        params: Any,
        serving: Optional[ServingConfig] = None,
        *,
        tokenizer: Any = None,
        eos_token_id: Optional[int] = None,
        seed: int = 0,
        devices: Optional[Sequence[Any]] = None,
    ) -> "ClusterManager":
        """Build ``serving.replicas`` in-process replicas — params
        shared by reference, each replica with its own mesh over a
        device picked round-robin from ``devices`` (all of them on a
        1-device host: independent engines on one chip is the
        in-process cluster this PR ships; per-host processes slot in
        behind the same Replica surface later)."""
        serving = serving or ServingConfig()
        serving.validate_cluster()
        import jax

        devs = list(devices or jax.devices())
        roles = ["mixed"] * serving.replicas
        if serving.prefill_replicas:
            roles = (
                ["prefill"] * serving.prefill_replicas
                + ["decode"] * serving.decode_replicas
            )
        replicas = [
            Replica.build(
                i, model, cfg, params, serving,
                role=roles[i],
                devices=[devs[i % len(devs)]],
                tokenizer=tokenizer,
                eos_token_id=eos_token_id,
                seed=seed,
            )
            for i in range(serving.replicas)
        ]
        return cls(
            replicas, serving, tokenizer=tokenizer,
            eos_token_id=eos_token_id,
        )

    # ------------------------------------------------------------------
    # submission

    def _tokenize(self, prompt: Union[str, Sequence[int]]):
        if isinstance(prompt, str):
            if self.tokenizer is None:
                raise ValueError("string prompt requires a tokenizer")
            return list(self.tokenizer.encode(prompt)), prompt
        return [int(t) for t in prompt], ""

    def submit(
        self,
        prompt: Union[str, Sequence[int]],
        gen: Optional[GenerationConfig] = None,
        max_new_tokens: Optional[int] = None,
        session_id: Optional[object] = None,
    ) -> int:
        """Route + queue one request; returns its CLUSTER id
        immediately (non-blocking — drive with :meth:`step` or a
        concurrent :meth:`generate`/:meth:`generate_stream`). A shed
        request is terminal on return (``result`` carries the error)."""
        gen = gen or GenerationConfig()
        if max_new_tokens is not None:
            gen = dataclasses.replace(gen, max_new_tokens=max_new_tokens)
        tokens, text = self._tokenize(prompt)
        cid = self._next_cid
        self._next_cid += 1
        self.stats.submitted += 1
        cr = ClusterRequest(
            cluster_id=cid, tokens=tokens, prompt_text=text, gen=gen,
            session_id=session_id, _manager=self,
        )
        self.requests[cid] = cr
        pos, how = self.router.route(tokens, session_id)
        if pos is None:
            cr.error = (
                "shed by SLO admission: every replica's queue-delay "
                f"estimate exceeds slo_queue_delay_s="
                f"{self.serving.slo_queue_delay_s}"
            )
            return cid
        rep = self.replicas[self._routing_pos[pos]]
        cr.replica = self._routing_pos[pos]
        delay = rep.queue_delay_s()
        if self.disaggregated:
            # prefill pass only: max_new_tokens=1 makes the prefill-final
            # dispatch (which samples the first output token on device)
            # the request's LAST step there — the chunked-prefill
            # boundary — and the held slot keeps its pages alive for
            # the migration that follows
            cr.phase = "prefill"
            cr.rid = rep.rm.submit(
                tokens, dataclasses.replace(gen, max_new_tokens=1)
            )
            rep.rm.hold_on_finish(cr.rid)
        else:
            cr.phase = "single"
            cr.rid = rep.rm.submit(tokens, gen)
        req = rep.rm.requests[cr.rid]
        req.profile.replica_id = rep.index
        req.profile.router_queue_delay_s = delay
        cr.profile = req.profile
        return cid

    # convenience alias (c_backend drives both manager kinds identically)
    def register_request(
        self,
        prompt: Union[str, Sequence[int]],
        gen: Optional[GenerationConfig] = None,
    ) -> int:
        return self.submit(prompt, gen)

    # ------------------------------------------------------------------
    # the drive loop

    def _finish_or_migrate(self, cr: ClusterRequest) -> bool:
        """Handle one held prefill-pool completion: either the request
        is ALREADY done (1-token budget, a stop token, or an error — no
        decode phase owed) and finishes on the prefill replica, or its
        pages migrate to the least-loaded decode replica. Returns True
        when state changed."""
        src = self.replicas[cr.replica]
        req = src.rm.requests[cr.rid]
        if req.status not in TERMINAL_STATUSES or req.pipeline_refs:
            return False
        if req.status is RequestStatus.ERROR:
            # unservable on the prefill pool (PR-2 ERROR path) — the
            # cluster request is terminal with that error
            src.rm.release_held(cr.rid)
            cr.phase = "single"
            return True
        done = len(req.tokens) >= self.serving.max_sequence_length
        if req.tokens[req.prompt_len:]:
            first = req.tokens[-1]
            stops = set(cr.gen.stop_token_ids)
            if self.eos_token_id is not None:
                stops.add(self.eos_token_id)
            done = done or first in stops or cr.gen.max_new_tokens <= 1
        if done:
            src.rm.release_held(cr.rid)
            cr.phase = "single"
            return True
        dst = min(
            self.decode_pool,
            key=lambda r: (r.queue_delay_s(), r.load(), r.index),
        )
        rid_dst = migrate_request(src, dst, cr.rid, cr.gen,
                                  stats=self.stats)
        if rid_dst is None:
            return False  # decode pool full right now — retry next step
        src.rm.release_held(cr.rid)
        cr.replica = self.replicas.index(dst)
        cr.rid = rid_dst
        cr.phase = "decode"
        req = dst.rm.requests[rid_dst]
        req.profile.replica_id = dst.index
        cr.profile = req.profile
        return True

    def _migrate_ready(self) -> bool:
        progressed = False
        for cr in self.requests.values():
            if cr.phase == "prefill" and cr.rid is not None:
                progressed = self._finish_or_migrate(cr) or progressed
        return progressed

    def step(self) -> bool:
        """One cluster step: advance every replica with work, then run
        any pending prefill→decode migrations. Returns False when no
        replica has work left."""
        progressed = False
        for rep in self.replicas:
            if rep.has_work():
                progressed = rep.step() or progressed
        if self.disaggregated:
            progressed = self._migrate_ready() or progressed
        self._step_counter += 1
        if self._step_counter % 200 == 0:
            self._log.debug(
                "%s", self.stats.report([r.rm.stats for r in self.replicas])
            )
        return progressed

    def drain(self) -> None:
        """Flush every replica's pipeline, then settle any migrations
        those flushes unblocked (a prefill pass whose completion was
        still in the pipeline hands its pages off here; the adopted
        decode work itself is driven by later :meth:`step` calls, same
        as RequestManager.drain never runs new steps)."""
        for rep in self.replicas:
            rep.drain()
        if self.disaggregated:
            self._migrate_ready()

    # ------------------------------------------------------------------
    # results

    def cluster_stats(self) -> Dict[str, object]:
        """ClusterStats snapshot over the live per-replica stats."""
        return self.stats.snapshot([r.rm.stats for r in self.replicas])

    def check_no_leaks(self) -> None:
        for rep in self.replicas:
            rep.check_no_leaks()

    def result(self, cid: int) -> GenerationResult:
        cr = self.requests[cid]
        if cr.rid is None:  # shed at the router
            return GenerationResult(
                request_id=cid,
                prompt=cr.prompt_text,
                input_tokens=list(cr.tokens),
                output_tokens=[],
                output_text="",
                profile=cr.profile,
                error=cr.error,
            )
        res = self.replicas[cr.replica].rm.result(cr.rid)
        return dataclasses.replace(res, request_id=cid)

    def _terminal(self, cid: int) -> bool:
        return self.requests[cid].status in TERMINAL_STATUSES

    def generate(
        self,
        prompts: Union[str, Sequence[Union[str, Sequence[int]]]],
        gen: Optional[GenerationConfig] = None,
        max_new_tokens: Optional[int] = None,
        session_ids: Optional[Sequence[object]] = None,
    ) -> List[GenerationResult]:
        """Blocking generate across the cluster (router-placed)."""
        if isinstance(prompts, str):
            prompts = [prompts]
        cids = [
            self.submit(
                p, gen, max_new_tokens,
                session_id=session_ids[i] if session_ids else None,
            )
            for i, p in enumerate(prompts)
        ]
        while any(not self._terminal(c) for c in cids):
            if not self.step():
                break
        self.drain()
        return [self.result(c) for c in cids]

    def generate_stream(
        self,
        prompts: Union[str, Sequence[Union[str, Sequence[int]]]],
        gen: Optional[GenerationConfig] = None,
        max_new_tokens: Optional[int] = None,
        session_ids: Optional[Sequence[object]] = None,
    ) -> Iterator[StreamEvent]:
        """Streaming generate across the cluster: one StreamEvent per
        drained token (``request_id`` is the CLUSTER id) + a terminal
        event per request (``error`` set for sheds/failures). Token
        counts are monotone across a migration — the first output token
        is visible on both sides of the hand-off, so nothing is dropped
        or re-sent."""
        if isinstance(prompts, str):
            prompts = [prompts]
        cids = [
            self.submit(
                p, gen, max_new_tokens,
                session_id=session_ids[i] if session_ids else None,
            )
            for i, p in enumerate(prompts)
        ]
        sent = {c: 0 for c in cids}
        finished: set = set()

        def drain_events():
            for c in cids:
                if c in finished:
                    continue
                cr = self.requests[c]
                out = cr.output_tokens
                while sent[c] < len(out):
                    tok = out[sent[c]]
                    sent[c] += 1
                    yield StreamEvent(c, int(tok))
                if self._terminal(c):
                    finished.add(c)
                    err = cr.error
                    if err is None and cr.rid is not None:
                        home = self.replicas[cr.replica].rm
                        err = home.requests[cr.rid].error
                    yield StreamEvent(c, None, done=True, error=err)

        while len(finished) < len(cids):
            progressed = self.step()
            yield from drain_events()
            if not progressed and len(finished) < len(cids):
                self.drain()
                yield from drain_events()
                if len(finished) < len(cids):
                    break  # nothing schedulable remains — avoid spinning
        self.drain()
        yield from drain_events()
