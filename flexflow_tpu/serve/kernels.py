"""Pallas TPU kernels for the serving hot path.

The reference hand-writes CUDA kernels for generation-phase attention
(reference ``inc_multihead_self_attention.cu:46`` custom decode kernel,
``spec_inc_…`` beam and ``tree_inc_…`` verify variants). On TPU the
prefill path is MXU-shaped already (big GEMMs — XLA does it well), but
**decode** attention (one query token against a long KV cache) is
bandwidth-bound and benefits from a fused flash-style kernel: QK^T →
online softmax → PV in VMEM, one pass over the cache, no (R, H, S)
score tensor ever hitting HBM.

:func:`decode_attention` — grid (request, cache-chunk); per-request
online-softmax accumulators persist in VMEM scratch across the chunk
dimension. Per-request ``seq_lens`` mask invalid cache lines, so one
static-shape program serves every request length (the reference pads to
MAX_NUM_TOKENS the same way, batch_config.h:58-60).

:func:`verify_attention` — the tree-verify variant: C query tokens per
request with an explicit (C, S) boolean mask (the reference's causal
``BitMask``), same online-softmax core.

:func:`ragged_paged_attention` — the paged-KV variant (PAPERS.md,
arxiv 2604.15464 Ragged Paged Attention): K/V live in a pool of
fixed-size token pages and the kernel gathers them **through the page
table** — the grid is (request, logical page) and the K/V BlockSpec
index maps read the scalar-prefetched table to DMA the right physical
page, so no (R, S) virtual cache is ever materialised in HBM. One
kernel serves decode (C=1), chunked prefill and tree verify (C>1, any
mask) — the single ragged kernel for mixed batches the paper argues
for. :func:`ragged_paged_attention_xla` is the shape-identical
``jnp.take``-based fallback (via :func:`gather_pages`) used on CPU and
as the correctness reference.

On non-TPU backends the Pallas kernels fall back to ``interpret=True``
so tests run on the CPU mesh.
"""
from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _decode_kernel(
    seq_ref,      # scalar-prefetch: (R,) int32 valid cache length per slot
    q_ref,        # (1, KV, G, dk)
    k_ref,        # (1, CS, KV, dk)
    v_ref,        # (1, CS, KV, dk)
    out_ref,      # (1, KV, G, dk)
    o_scr,        # VMEM (KV, G, dk) f32
    m_scr,        # VMEM (KV, G) f32
    l_scr,        # VMEM (KV, G) f32
    *,
    block_s: int,
    scale: float,
):
    r = pl.program_id(0)
    s = pl.program_id(1)

    @pl.when(s == 0)
    def _():
        o_scr[:] = jnp.zeros_like(o_scr)
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)

    pos = s * block_s + jax.lax.broadcasted_iota(jnp.int32, (block_s,), 0)
    valid = pos < seq_ref[r]

    @pl.when(jnp.any(valid))
    def _():
        q = q_ref[0].astype(jnp.float32)                    # (KV, G, dk)
        # Mosaic batched matmul needs both batch dims leading: lay K/V
        # out as (KV, CS, dk) for the chunk
        k = k_ref[0].astype(jnp.float32).transpose(1, 0, 2)  # (KV, CS, dk)
        v = v_ref[0].astype(jnp.float32).transpose(1, 0, 2)
        # zero out-of-bounds/invalid rows: p is 0 there, but 0·NaN from
        # block padding would still poison the PV product
        v = jnp.where(valid[None, :, None], v, 0.0)
        # scores (KV, G, CS): batch over KV heads, contract dk
        scores = jax.lax.dot_general(
            q, k,
            dimension_numbers=(((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        ) * scale
        scores = jnp.where(valid[None, None, :], scores, NEG_INF)
        m_new = jnp.maximum(m_scr[:], scores.max(axis=-1))
        p = jnp.exp(scores - m_new[..., None])
        p = jnp.where(valid[None, None, :], p, 0.0)
        corr = jnp.exp(m_scr[:] - m_new)
        l_scr[:] = l_scr[:] * corr + p.sum(axis=-1)
        pv = jax.lax.dot_general(
            p, v,
            dimension_numbers=(((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        )  # (KV, G, dk)
        o_scr[:] = o_scr[:] * corr[..., None] + pv
        m_scr[:] = m_new

    @pl.when(s == pl.num_programs(1) - 1)
    def _():
        l = jnp.maximum(l_scr[:], 1e-20)
        out_ref[0] = (o_scr[:] / l[..., None]).astype(out_ref.dtype)


def decode_attention(
    q: jnp.ndarray,        # (R, H, dk)
    k_cache: jnp.ndarray,  # (R, S1, KV, dk)
    v_cache: jnp.ndarray,  # (R, S1, KV, dk)
    seq_lens: jnp.ndarray, # (R,) int32 — lines [0, seq_len) are attended
    *,
    block_s: int = 256,
    scale: Optional[float] = None,
) -> jnp.ndarray:
    """Fused decode attention: one query token per request slot against
    its cache prefix. Returns (R, H, dk)."""
    R, H, dk = q.shape
    _, S1, KV, _ = k_cache.shape
    G = H // KV
    scale = scale if scale is not None else 1.0 / math.sqrt(dk)
    # keep blocks lane-aligned: a non-multiple-of-128 block (e.g. the
    # cache's odd S1 = max_len+1) tiles catastrophically in Mosaic
    block_s = 128 * pl.cdiv(min(block_s, S1), 128)
    qg = q.reshape(R, KV, G, dk)
    grid = (R, pl.cdiv(S1, block_s))

    out = pl.pallas_call(
        functools.partial(_decode_kernel, block_s=block_s, scale=scale),
        out_shape=jax.ShapeDtypeStruct((R, KV, G, dk), q.dtype),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=grid,
            in_specs=[
                # index maps receive the scalar-prefetch ref as a trailing arg
                pl.BlockSpec((1, KV, G, dk), lambda r, s, seq: (r, 0, 0, 0)),
                pl.BlockSpec((1, block_s, KV, dk), lambda r, s, seq: (r, s, 0, 0)),
                pl.BlockSpec((1, block_s, KV, dk), lambda r, s, seq: (r, s, 0, 0)),
            ],
            out_specs=pl.BlockSpec((1, KV, G, dk), lambda r, s, seq: (r, 0, 0, 0)),
            scratch_shapes=[
                pltpu.VMEM((KV, G, dk), jnp.float32),
                pltpu.VMEM((KV, G), jnp.float32),
                pltpu.VMEM((KV, G), jnp.float32),
            ],
        ),
        interpret=_interpret(),
    )(seq_lens.astype(jnp.int32), qg, k_cache, v_cache)
    return out.reshape(R, H, dk)


def _verify_kernel(
    q_ref,        # (1, C, KV, G, dk)
    k_ref,        # (1, CS, KV, dk)
    v_ref,        # (1, CS, KV, dk)
    mask_ref,     # (1, C, CS) bool
    out_ref,      # (1, C, KV, G, dk)
    o_scr,        # VMEM (C, KV, G, dk) f32
    m_scr,        # VMEM (C, KV, G) f32
    l_scr,        # VMEM (C, KV, G) f32
    *,
    block_s: int,
    total_s: int,
    scale: float,
):
    s = pl.program_id(1)

    @pl.when(s == 0)
    def _():
        o_scr[:] = jnp.zeros_like(o_scr)
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)

    # When S1 % block_s != 0 the mask block's tail is out-of-bounds
    # padding with unspecified contents on TPU — bound it explicitly.
    pos = s * block_s + jax.lax.broadcasted_iota(jnp.int32, (block_s,), 0)
    mask = mask_ref[0] & (pos < total_s)[None, :]  # (C, CS)

    @pl.when(jnp.any(mask))
    def _():
        q = q_ref[0].astype(jnp.float32)           # (C, KV, G, dk)
        k = k_ref[0].astype(jnp.float32).transpose(1, 0, 2)  # (KV, CS, dk)
        v = v_ref[0].astype(jnp.float32).transpose(1, 0, 2)
        inb = (pos < total_s)
        v = jnp.where(inb[None, :, None], v, 0.0)
        C = q.shape[0]
        # (KV, C*G, dk) grouped layout so one batched dot serves all KV heads
        qkv = q.transpose(1, 0, 2, 3).reshape(q.shape[1], -1, q.shape[-1])
        # (KV, C*G, dk) × (KV, CS, dk) -> (KV, C*G, CS)
        scores = jax.lax.dot_general(
            qkv, k,
            dimension_numbers=(((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        ) * scale
        KV = q.shape[1]
        G = q.shape[2]
        scores = scores.reshape(KV, C, G, -1).transpose(1, 0, 2, 3)  # (C,KV,G,CS)
        scores = jnp.where(mask[:, None, None, :], scores, NEG_INF)
        m_new = jnp.maximum(m_scr[:], scores.max(axis=-1))
        p = jnp.exp(scores - m_new[..., None])
        p = jnp.where(mask[:, None, None, :], p, 0.0)
        corr = jnp.exp(m_scr[:] - m_new)
        l_scr[:] = l_scr[:] * corr + p.sum(axis=-1)
        pk = p.transpose(1, 0, 2, 3).reshape(KV, C * G, -1)   # (KV, C*G, CS)
        pv = jax.lax.dot_general(
            pk, v,
            dimension_numbers=(((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        )  # (KV, C*G, dk)
        pv = pv.reshape(KV, C, G, -1).transpose(1, 0, 2, 3)
        o_scr[:] = o_scr[:] * corr[..., None] + pv
        m_scr[:] = m_new

    @pl.when(s == pl.num_programs(1) - 1)
    def _():
        l = jnp.maximum(l_scr[:], 1e-20)
        out_ref[0] = (o_scr[:] / l[..., None]).astype(out_ref.dtype)


def verify_attention(
    q: jnp.ndarray,        # (R, C, H, dk) — C tree tokens per request
    k_cache: jnp.ndarray,  # (R, S1, KV, dk)
    v_cache: jnp.ndarray,  # (R, S1, KV, dk)
    mask: jnp.ndarray,     # (R, C, S1) bool — the spec-tree BitMask
    *,
    block_s: int = 256,
    scale: Optional[float] = None,
) -> jnp.ndarray:
    """Fused tree-verify attention: every speculative tree token attends
    its causal-bitmask cache subset in one pass (reference
    ``tree_inc_multihead_self_attention.cu``). Returns (R, C, H, dk)."""
    R, C, H, dk = q.shape
    _, S1, KV, _ = k_cache.shape
    G = H // KV
    scale = scale if scale is not None else 1.0 / math.sqrt(dk)
    block_s = 128 * pl.cdiv(min(block_s, S1), 128)  # lane-aligned blocks
    qg = q.reshape(R, C, KV, G, dk)
    grid = (R, pl.cdiv(S1, block_s))

    out = pl.pallas_call(
        functools.partial(_verify_kernel, block_s=block_s, total_s=S1,
                          scale=scale),
        out_shape=jax.ShapeDtypeStruct((R, C, KV, G, dk), q.dtype),
        grid_spec=pl.GridSpec(
            grid=grid,
            in_specs=[
                pl.BlockSpec((1, C, KV, G, dk), lambda r, s: (r, 0, 0, 0, 0)),
                pl.BlockSpec((1, block_s, KV, dk), lambda r, s: (r, s, 0, 0)),
                pl.BlockSpec((1, block_s, KV, dk), lambda r, s: (r, s, 0, 0)),
                pl.BlockSpec((1, C, block_s), lambda r, s: (r, 0, s)),
            ],
            out_specs=pl.BlockSpec(
                (1, C, KV, G, dk), lambda r, s: (r, 0, 0, 0, 0)
            ),
            scratch_shapes=[
                pltpu.VMEM((C, KV, G, dk), jnp.float32),
                pltpu.VMEM((C, KV, G), jnp.float32),
                pltpu.VMEM((C, KV, G), jnp.float32),
            ],
        ),
        interpret=_interpret(),
    )(qg, k_cache, v_cache, mask)
    return out.reshape(R, C, H, dk)


# ---------------------------------------------------------------------------
# Shared serving-mask construction. Every serving step — sync chunked
# prefill, fused decode, and the mixed continuous-batching step — uses
# the same causal-by-position contract: a query token attends every
# cache line whose position is <= its own, never the scratch line, so
# one static-shape program serves ragged rows (padding columns sit at
# the scratch position and are masked out of nothing real). These were
# previously duplicated across the model-family modules.


def causal_serve_mask(positions: jnp.ndarray, S1: int) -> jnp.ndarray:
    """Causal-by-position mask over a dense cache: positions (R, C) →
    (R, C, S1) bool. Line S1-1 is the per-slot scratch row and is never
    attended; only positions already written satisfy ``<=``, so stale
    lines from an evicted slot occupant are never read."""
    key_pos = jnp.arange(S1, dtype=jnp.int32)
    mask = key_pos[None, None, :] <= positions[:, :, None]
    return mask & (key_pos[None, None, :] < S1 - 1)


def paged_serve_mask(
    mask: Optional[jnp.ndarray],
    positions: jnp.ndarray,
    num_logical_pages: int,
    page_size: int,
    cache_len: int,
) -> jnp.ndarray:
    """Paged twin of :func:`causal_serve_mask` over the page-aligned
    virtual cache (S_virt = NP * page_size): builds the causal mask when
    ``mask`` is None, otherwise pads an explicit (R, C, cache_len+1)
    mask out to S_virt (padding is never-attended). The scratch LINE
    (index ``cache_len``, where padding tokens write) is excluded."""
    S_virt = num_logical_pages * page_size
    if mask is None:
        key_pos = jnp.arange(S_virt, dtype=jnp.int32)
        mask = key_pos[None, None, :] <= positions[:, :, None]
        return mask & (key_pos[None, None, :] < cache_len)
    if mask.shape[-1] < S_virt:
        pad = S_virt - mask.shape[-1]
        mask = jnp.pad(mask, ((0, 0), (0, 0), (0, pad)))
    return mask


# ---------------------------------------------------------------------------
# Ragged paged attention (paged KV pool + per-request page table)


def gather_pages(pool: jnp.ndarray, page_table: jnp.ndarray) -> jnp.ndarray:
    """``jnp.take``-gather of a request's logical cache from the page
    pool: pool (P+1, ps, ...) × table (R, NP) → virtual cache
    (R, NP*ps, ...). Unallocated table entries point at the scratch page
    (pool row P) — the caller's mask never exposes those lines."""
    R, NP = page_table.shape
    ps = pool.shape[1]
    flat = jnp.take(pool, page_table.reshape(-1), axis=0)
    return flat.reshape((R, NP * ps) + pool.shape[2:])


def dequant_pages(
    pool: jnp.ndarray,        # (P+1, ps, KV, dk) int8 codes
    scale: jnp.ndarray,       # (P+1, KV) f32 per-page-per-head scales
    page_table: jnp.ndarray,  # (R, NP) int32
    dtype,
) -> jnp.ndarray:
    """Quantized twin of :func:`gather_pages`: gather the int8 virtual
    cache through the table and dequantize each line at its page's
    per-KV-head scale (serve/kv_quant.py layout). Returns the
    (R, NP*ps, KV, dk) full-precision virtual cache in ``dtype``."""
    R, NP = page_table.shape
    ps, KV = pool.shape[1], pool.shape[2]
    codes = gather_pages(pool, page_table)        # (R, S, KV, dk) int8
    s = jnp.take(scale, page_table.reshape(-1), axis=0)  # (R*NP, KV)
    s = jnp.broadcast_to(
        s.reshape(R, NP, 1, KV), (R, NP, ps, KV)
    ).reshape(R, NP * ps, KV)
    return (codes.astype(jnp.float32) * s[..., None]).astype(dtype)


def ragged_paged_attention_xla(
    q: jnp.ndarray,           # (R, C, H, dk)
    k_pool: jnp.ndarray,      # (P+1, ps, KV, dk)
    v_pool: jnp.ndarray,      # (P+1, ps, KV, dk)
    page_table: jnp.ndarray,  # (R, NP) int32 physical page per logical page
    mask: jnp.ndarray,        # (R, C, NP*ps) bool
    *,
    scale: Optional[float] = None,
    k_scale: Optional[jnp.ndarray] = None,  # (P+1, KV) f32 (quantized pool)
    v_scale: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """Shape-identical XLA fallback: gather the virtual cache through
    the page table, then the standard grouped-query masked softmax —
    bit-for-bit the dense ``serve_attention`` math on the gathered
    lines. With ``k_scale``/``v_scale`` the pools hold int8 codes
    (serve/kv_quant.py) and the gathered lines are dequantized at their
    page scales first. Returns (R, C, H, dk)."""
    R, C, H, dk = q.shape
    KV = k_pool.shape[2]
    G = H // KV
    scale = scale if scale is not None else 1.0 / math.sqrt(dk)
    if k_scale is not None:
        k_virt = dequant_pages(k_pool, k_scale, page_table, q.dtype)
        v_virt = dequant_pages(v_pool, v_scale, page_table, q.dtype)
    else:
        k_virt = gather_pages(k_pool, page_table)  # (R, S, KV, dk)
        v_virt = gather_pages(v_pool, page_table)
    qg = q.reshape(R, C, KV, G, dk)
    scores = jnp.einsum(
        "rckgd,rskd->rkgcs", qg, k_virt, preferred_element_type=jnp.float32
    ) * scale
    scores = jnp.where(mask[:, None, None], scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    out = jnp.einsum("rkgcs,rskd->rckgd", probs, v_virt)
    return out.reshape(R, C, H, dk)


def _ragged_paged_kernel(
    pt_ref,       # scalar-prefetch: (R, NP) int32 page table
    q_ref,        # (1, C, KV, G, dk)
    k_ref,        # (1, ps, KV, dk) — physical page picked by index map
    v_ref,        # (1, ps, KV, dk)
    mask_ref,     # (1, C, ps)
    out_ref,      # (1, C, KV, G, dk)
    o_scr,        # VMEM (C, KV, G, dk) f32
    m_scr,        # VMEM (C, KV, G) f32
    l_scr,        # VMEM (C, KV, G) f32
    *,
    scale: float,
):
    p = pl.program_id(1)

    @pl.when(p == 0)
    def _():
        o_scr[:] = jnp.zeros_like(o_scr)
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)

    mask = mask_ref[0]  # (C, ps) — already bounded: S_virt = NP*ps exactly

    @pl.when(jnp.any(mask))
    def _():
        q = q_ref[0].astype(jnp.float32)            # (C, KV, G, dk)
        k = k_ref[0].astype(jnp.float32).transpose(1, 0, 2)  # (KV, ps, dk)
        v = v_ref[0].astype(jnp.float32).transpose(1, 0, 2)
        C, KV, G = q.shape[0], q.shape[1], q.shape[2]
        # (KV, C*G, dk) grouped layout: one batched dot per KV head
        qkv = q.transpose(1, 0, 2, 3).reshape(KV, C * G, q.shape[-1])
        scores = jax.lax.dot_general(
            qkv, k,
            dimension_numbers=(((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        ) * scale                                   # (KV, C*G, ps)
        scores = scores.reshape(KV, C, G, -1).transpose(1, 0, 2, 3)
        scores = jnp.where(mask[:, None, None, :], scores, NEG_INF)
        m_new = jnp.maximum(m_scr[:], scores.max(axis=-1))
        prob = jnp.exp(scores - m_new[..., None])
        prob = jnp.where(mask[:, None, None, :], prob, 0.0)
        corr = jnp.exp(m_scr[:] - m_new)
        l_scr[:] = l_scr[:] * corr + prob.sum(axis=-1)
        pk = prob.transpose(1, 0, 2, 3).reshape(KV, C * G, -1)
        pv = jax.lax.dot_general(
            pk, v,
            dimension_numbers=(((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        )  # (KV, C*G, dk)
        pv = pv.reshape(KV, C, G, -1).transpose(1, 0, 2, 3)
        o_scr[:] = o_scr[:] * corr[..., None] + pv
        m_scr[:] = m_new

    @pl.when(p == pl.num_programs(1) - 1)
    def _():
        l = jnp.maximum(l_scr[:], 1e-20)
        out_ref[0] = (o_scr[:] / l[..., None]).astype(out_ref.dtype)


def _ragged_paged_quant_kernel(
    pt_ref,       # scalar-prefetch: (R, NP) int32 page table
    q_ref,        # (1, C, KV, G, dk)
    k_ref,        # (1, ps, KV, dk) int8 — physical page via index map
    v_ref,        # (1, ps, KV, dk) int8
    ks_ref,       # (1, KV) f32 — the page's K scales (same index map)
    vs_ref,       # (1, KV) f32
    mask_ref,     # (1, C, ps)
    out_ref,      # (1, C, KV, G, dk)
    o_scr,        # VMEM (C, KV, G, dk) f32
    m_scr,        # VMEM (C, KV, G) f32
    l_scr,        # VMEM (C, KV, G) f32
    *,
    scale: float,
):
    """Quantized twin of :func:`_ragged_paged_kernel`: the page DMA
    moves int8 codes (half the bf16 bytes — the whole point), and the
    per-page-per-head dequant scales fold into the batched dots'
    OUTPUTS (scores ×= k_scale[kv], pv ×= v_scale[kv]) rather than
    materialising a dequantized (ps, KV, dk) block — scales are
    constant within a page, so scaling the O(C·G·ps) scores and
    O(C·G·dk) pv is exact and strictly cheaper than scaling the
    O(ps·dk) operands."""
    p = pl.program_id(1)

    @pl.when(p == 0)
    def _():
        o_scr[:] = jnp.zeros_like(o_scr)
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)

    mask = mask_ref[0]  # (C, ps)

    @pl.when(jnp.any(mask))
    def _():
        q = q_ref[0].astype(jnp.float32)            # (C, KV, G, dk)
        k = k_ref[0].astype(jnp.float32).transpose(1, 0, 2)  # (KV, ps, dk)
        v = v_ref[0].astype(jnp.float32).transpose(1, 0, 2)
        ks = ks_ref[0]                              # (KV,)
        vs = vs_ref[0]
        C, KV, G = q.shape[0], q.shape[1], q.shape[2]
        qkv = q.transpose(1, 0, 2, 3).reshape(KV, C * G, q.shape[-1])
        scores = jax.lax.dot_general(
            qkv, k,
            dimension_numbers=(((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        ) * (ks[:, None, None] * scale)             # dequant K via scores
        scores = scores.reshape(KV, C, G, -1).transpose(1, 0, 2, 3)
        scores = jnp.where(mask[:, None, None, :], scores, NEG_INF)
        m_new = jnp.maximum(m_scr[:], scores.max(axis=-1))
        prob = jnp.exp(scores - m_new[..., None])
        prob = jnp.where(mask[:, None, None, :], prob, 0.0)
        corr = jnp.exp(m_scr[:] - m_new)
        l_scr[:] = l_scr[:] * corr + prob.sum(axis=-1)
        pk = prob.transpose(1, 0, 2, 3).reshape(KV, C * G, -1)
        pv = jax.lax.dot_general(
            pk, v,
            dimension_numbers=(((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        ) * vs[:, None, None]                       # dequant V via pv
        pv = pv.reshape(KV, C, G, -1).transpose(1, 0, 2, 3)
        o_scr[:] = o_scr[:] * corr[..., None] + pv
        m_scr[:] = m_new

    @pl.when(p == pl.num_programs(1) - 1)
    def _():
        l = jnp.maximum(l_scr[:], 1e-20)
        out_ref[0] = (o_scr[:] / l[..., None]).astype(out_ref.dtype)


def ragged_paged_attention(
    q: jnp.ndarray,           # (R, C, H, dk)
    k_pool: jnp.ndarray,      # (P+1, ps, KV, dk)
    v_pool: jnp.ndarray,      # (P+1, ps, KV, dk)
    page_table: jnp.ndarray,  # (R, NP) int32
    mask: jnp.ndarray,        # (R, C, NP*ps) bool
    *,
    scale: Optional[float] = None,
    k_scale: Optional[jnp.ndarray] = None,  # (P+1, KV) f32 (quantized pool)
    v_scale: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """Fused ragged paged attention: grid (request, logical page); the
    K/V BlockSpec index maps read the scalar-prefetched page table so
    each step DMAs exactly the physical page that logical position maps
    to — gathering through the table without materialising the
    (R, S) virtual cache. One kernel covers decode (C=1), chunked
    prefill and tree verify (the explicit-mask modes). With
    ``k_scale``/``v_scale`` the pools hold int8 codes and the same
    index maps additionally DMA each page's per-KV-head scales; dequant
    happens in VMEM (:func:`_ragged_paged_quant_kernel`) so the
    full-precision cache never exists in HBM. Returns (R, C, H, dk)."""
    R, C, H, dk = q.shape
    _, ps, KV, _ = k_pool.shape
    NP = page_table.shape[1]
    G = H // KV
    scale = scale if scale is not None else 1.0 / math.sqrt(dk)
    qg = q.reshape(R, C, KV, G, dk)
    grid = (R, NP)

    in_specs = [
        pl.BlockSpec((1, C, KV, G, dk),
                     lambda r, p, pt: (r, 0, 0, 0, 0)),
        # the paged gather: block row = page_table[r, p]
        pl.BlockSpec((1, ps, KV, dk),
                     lambda r, p, pt: (pt[r, p], 0, 0, 0)),
        pl.BlockSpec((1, ps, KV, dk),
                     lambda r, p, pt: (pt[r, p], 0, 0, 0)),
    ]
    operands = [qg, k_pool, v_pool]
    if k_scale is not None:
        kernel = functools.partial(_ragged_paged_quant_kernel, scale=scale)
        in_specs += [
            pl.BlockSpec((1, KV), lambda r, p, pt: (pt[r, p], 0)),
            pl.BlockSpec((1, KV), lambda r, p, pt: (pt[r, p], 0)),
        ]
        operands += [
            k_scale.astype(jnp.float32), v_scale.astype(jnp.float32)
        ]
    else:
        kernel = functools.partial(_ragged_paged_kernel, scale=scale)
    in_specs.append(pl.BlockSpec((1, C, ps), lambda r, p, pt: (r, 0, p)))
    operands.append(mask)

    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((R, C, KV, G, dk), q.dtype),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=grid,
            in_specs=in_specs,
            out_specs=pl.BlockSpec(
                (1, C, KV, G, dk), lambda r, p, pt: (r, 0, 0, 0, 0)
            ),
            scratch_shapes=[
                pltpu.VMEM((C, KV, G, dk), jnp.float32),
                pltpu.VMEM((C, KV, G), jnp.float32),
                pltpu.VMEM((C, KV, G), jnp.float32),
            ],
        ),
        interpret=_interpret(),
    )(page_table.astype(jnp.int32), *operands)
    return out.reshape(R, C, H, dk)
