"""Pallas TPU kernels for the serving hot path.

The reference hand-writes CUDA kernels for generation-phase attention
(reference ``inc_multihead_self_attention.cu:46`` custom decode kernel,
``spec_inc_…`` beam and ``tree_inc_…`` verify variants). On TPU the
prefill path is MXU-shaped already (big GEMMs — XLA does it well), but
**decode** attention (one query token against a long KV cache) is
bandwidth-bound and benefits from a fused flash-style kernel: QK^T →
online softmax → PV in VMEM, one pass over the cache, no (R, H, S)
score tensor ever hitting HBM.

:func:`decode_attention` — grid (request, cache-chunk); per-request
online-softmax accumulators persist in VMEM scratch across the chunk
dimension. Per-request ``seq_lens`` mask invalid cache lines, so one
static-shape program serves every request length (the reference pads to
MAX_NUM_TOKENS the same way, batch_config.h:58-60).

:func:`verify_attention` — the tree-verify variant: C query tokens per
request with an explicit (C, S) boolean mask (the reference's causal
``BitMask``), same online-softmax core.

:func:`ragged_paged_attention` — the paged-KV variant (PAPERS.md,
arxiv 2604.15464 Ragged Paged Attention): K/V live in a pool of
fixed-size token pages and the kernel gathers them **through the page
table** — the grid is (request, logical page) and the K/V BlockSpec
index maps read the scalar-prefetched table to DMA the right physical
page, so no (R, S) virtual cache is ever materialised in HBM. One
kernel serves decode (C=1), chunked prefill and tree verify (C>1, any
mask) — the single ragged kernel for mixed batches the paper argues
for. :func:`ragged_paged_attention_xla` is the shape-identical
``jnp.take``-based fallback (via :func:`gather_pages`) used on CPU and
as the correctness reference.

:func:`fused_rope_paged_attention` — the **megakernel decode step**
prologue (MPK, "Mega-Kernelizing Tensor Programs", PAPERS.md): RoPE on
Q/K and the (optionally int8-quantizing) KV page write move INSIDE the
ragged paged grid, so a decode step's fresh K/V lines are rotated,
quantized and committed in VMEM and read back by attention in the same
kernel — they never round-trip HBM between the step's QKV projection
and the attention read, and the separate rope/scatter XLA ops (and
their dispatch latency) disappear from the step program.

Kernel-variant matrix — every Pallas variant of the ragged paged
kernel is emitted by ONE parameterized builder
(:func:`_build_ragged_paged_kernel`), so the quant and fused axes
compose instead of multiplying hand-written kernel bodies:

====================  =======================  =========================
variant               Pallas entry point       XLA fallback (CPU parity)
====================  =======================  =========================
plain                 ragged_paged_attention   ragged_paged_attention_xla
int8 pages            ragged_paged_attention   ragged_paged_attention_xla
                      (k_scale/v_scale)        (k_scale/v_scale)
int4 pages            ragged_paged_attention   ragged_paged_attention_xla
(packed nibbles)      (uint8 pool; nibble      (dequant_pages unpacks the
                      unpack in VMEM)          gathered codes)
fused RoPE+KV-write   fused_rope_paged_        the unfused serving step
                      attention                itself: rope + scatter +
                                               gather is ALREADY the
                                               reference math, so
                                               ``fused_decode`` with
                                               kernels="xla" is a no-op
fused + int8/int4     fused_rope_paged_        same, via quant_line_write
                      attention (qmax)
====================  =======================  =========================

The quant axis carries a ``pack`` factor inferred from the pool shapes
(``dk // pool.shape[-1]``): pack=2 pools (int4) DMA uint8 pages of
half the int8 bytes and unpack two nibble codes per byte in VMEM
(``kv_quant.unpack_nibbles`` arithmetic, mirrored op-for-op by
:func:`_unpack_codes` below — integer masks/shifts, exact on every
backend) before the same scale-folded dots; the fused write side packs
through the in-kernel twin of ``kv_quant.pack_nibbles``.

Every fused variant is bitwise-identical to its unfused counterpart on
the same backend: the builder reuses one attention body (same op
order, same online-softmax accumulation over the same (request, page)
grid), the in-kernel RoPE mirrors ``apply_rope`` op-for-op, and the
in-kernel quantized commit mirrors ``kv_quant.quant_line_write``
page-locally (running amax, rescale-on-growth, offset-0 reset). The
only unspecified bytes are the shared scratch page's, which both paths
write with padding garbage and neither ever reads.

On non-TPU backends the Pallas kernels fall back to ``interpret=True``
so tests run on the CPU mesh.
"""
from __future__ import annotations

import functools
import math
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _decode_kernel(
    seq_ref,      # scalar-prefetch: (R,) int32 valid cache length per slot
    q_ref,        # (1, KV, G, dk)
    k_ref,        # (1, CS, KV, dk)
    v_ref,        # (1, CS, KV, dk)
    out_ref,      # (1, KV, G, dk)
    o_scr,        # VMEM (KV, G, dk) f32
    m_scr,        # VMEM (KV, G) f32
    l_scr,        # VMEM (KV, G) f32
    *,
    block_s: int,
    scale: float,
):
    r = pl.program_id(0)
    s = pl.program_id(1)

    @pl.when(s == 0)
    def _():
        o_scr[:] = jnp.zeros_like(o_scr)
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)

    pos = s * block_s + jax.lax.broadcasted_iota(jnp.int32, (block_s,), 0)
    valid = pos < seq_ref[r]

    @pl.when(jnp.any(valid))
    def _():
        q = q_ref[0].astype(jnp.float32)                    # (KV, G, dk)
        # Mosaic batched matmul needs both batch dims leading: lay K/V
        # out as (KV, CS, dk) for the chunk
        k = k_ref[0].astype(jnp.float32).transpose(1, 0, 2)  # (KV, CS, dk)
        v = v_ref[0].astype(jnp.float32).transpose(1, 0, 2)
        # zero out-of-bounds/invalid rows: p is 0 there, but 0·NaN from
        # block padding would still poison the PV product
        v = jnp.where(valid[None, :, None], v, 0.0)
        # scores (KV, G, CS): batch over KV heads, contract dk
        scores = jax.lax.dot_general(
            q, k,
            dimension_numbers=(((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        ) * scale
        scores = jnp.where(valid[None, None, :], scores, NEG_INF)
        m_new = jnp.maximum(m_scr[:], scores.max(axis=-1))
        p = jnp.exp(scores - m_new[..., None])
        p = jnp.where(valid[None, None, :], p, 0.0)
        corr = jnp.exp(m_scr[:] - m_new)
        l_scr[:] = l_scr[:] * corr + p.sum(axis=-1)
        pv = jax.lax.dot_general(
            p, v,
            dimension_numbers=(((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        )  # (KV, G, dk)
        o_scr[:] = o_scr[:] * corr[..., None] + pv
        m_scr[:] = m_new

    @pl.when(s == pl.num_programs(1) - 1)
    def _():
        l = jnp.maximum(l_scr[:], 1e-20)
        out_ref[0] = (o_scr[:] / l[..., None]).astype(out_ref.dtype)


def decode_attention(
    q: jnp.ndarray,        # (R, H, dk)
    k_cache: jnp.ndarray,  # (R, S1, KV, dk)
    v_cache: jnp.ndarray,  # (R, S1, KV, dk)
    seq_lens: jnp.ndarray, # (R,) int32 — lines [0, seq_len) are attended
    *,
    block_s: int = 256,
    scale: Optional[float] = None,
) -> jnp.ndarray:
    """Fused decode attention: one query token per request slot against
    its cache prefix. Returns (R, H, dk)."""
    R, H, dk = q.shape
    _, S1, KV, _ = k_cache.shape
    G = H // KV
    scale = scale if scale is not None else 1.0 / math.sqrt(dk)
    # keep blocks lane-aligned: a non-multiple-of-128 block (e.g. the
    # cache's odd S1 = max_len+1) tiles catastrophically in Mosaic
    block_s = 128 * pl.cdiv(min(block_s, S1), 128)
    qg = q.reshape(R, KV, G, dk)
    grid = (R, pl.cdiv(S1, block_s))

    out = pl.pallas_call(
        functools.partial(_decode_kernel, block_s=block_s, scale=scale),
        out_shape=jax.ShapeDtypeStruct((R, KV, G, dk), q.dtype),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=grid,
            in_specs=[
                # index maps receive the scalar-prefetch ref as a trailing arg
                pl.BlockSpec((1, KV, G, dk), lambda r, s, seq: (r, 0, 0, 0)),
                pl.BlockSpec((1, block_s, KV, dk), lambda r, s, seq: (r, s, 0, 0)),
                pl.BlockSpec((1, block_s, KV, dk), lambda r, s, seq: (r, s, 0, 0)),
            ],
            out_specs=pl.BlockSpec((1, KV, G, dk), lambda r, s, seq: (r, 0, 0, 0)),
            scratch_shapes=[
                pltpu.VMEM((KV, G, dk), jnp.float32),
                pltpu.VMEM((KV, G), jnp.float32),
                pltpu.VMEM((KV, G), jnp.float32),
            ],
        ),
        interpret=_interpret(),
    )(seq_lens.astype(jnp.int32), qg, k_cache, v_cache)
    return out.reshape(R, H, dk)


def _verify_kernel(
    q_ref,        # (1, C, KV, G, dk)
    k_ref,        # (1, CS, KV, dk)
    v_ref,        # (1, CS, KV, dk)
    mask_ref,     # (1, C, CS) bool
    out_ref,      # (1, C, KV, G, dk)
    o_scr,        # VMEM (C, KV, G, dk) f32
    m_scr,        # VMEM (C, KV, G) f32
    l_scr,        # VMEM (C, KV, G) f32
    *,
    block_s: int,
    total_s: int,
    scale: float,
):
    s = pl.program_id(1)

    @pl.when(s == 0)
    def _():
        o_scr[:] = jnp.zeros_like(o_scr)
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)

    # When S1 % block_s != 0 the mask block's tail is out-of-bounds
    # padding with unspecified contents on TPU — bound it explicitly.
    pos = s * block_s + jax.lax.broadcasted_iota(jnp.int32, (block_s,), 0)
    mask = mask_ref[0] & (pos < total_s)[None, :]  # (C, CS)

    @pl.when(jnp.any(mask))
    def _():
        q = q_ref[0].astype(jnp.float32)           # (C, KV, G, dk)
        k = k_ref[0].astype(jnp.float32).transpose(1, 0, 2)  # (KV, CS, dk)
        v = v_ref[0].astype(jnp.float32).transpose(1, 0, 2)
        inb = (pos < total_s)
        v = jnp.where(inb[None, :, None], v, 0.0)
        C = q.shape[0]
        # (KV, C*G, dk) grouped layout so one batched dot serves all KV heads
        qkv = q.transpose(1, 0, 2, 3).reshape(q.shape[1], -1, q.shape[-1])
        # (KV, C*G, dk) × (KV, CS, dk) -> (KV, C*G, CS)
        scores = jax.lax.dot_general(
            qkv, k,
            dimension_numbers=(((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        ) * scale
        KV = q.shape[1]
        G = q.shape[2]
        scores = scores.reshape(KV, C, G, -1).transpose(1, 0, 2, 3)  # (C,KV,G,CS)
        scores = jnp.where(mask[:, None, None, :], scores, NEG_INF)
        m_new = jnp.maximum(m_scr[:], scores.max(axis=-1))
        p = jnp.exp(scores - m_new[..., None])
        p = jnp.where(mask[:, None, None, :], p, 0.0)
        corr = jnp.exp(m_scr[:] - m_new)
        l_scr[:] = l_scr[:] * corr + p.sum(axis=-1)
        pk = p.transpose(1, 0, 2, 3).reshape(KV, C * G, -1)   # (KV, C*G, CS)
        pv = jax.lax.dot_general(
            pk, v,
            dimension_numbers=(((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        )  # (KV, C*G, dk)
        pv = pv.reshape(KV, C, G, -1).transpose(1, 0, 2, 3)
        o_scr[:] = o_scr[:] * corr[..., None] + pv
        m_scr[:] = m_new

    @pl.when(s == pl.num_programs(1) - 1)
    def _():
        l = jnp.maximum(l_scr[:], 1e-20)
        out_ref[0] = (o_scr[:] / l[..., None]).astype(out_ref.dtype)


def verify_attention(
    q: jnp.ndarray,        # (R, C, H, dk) — C tree tokens per request
    k_cache: jnp.ndarray,  # (R, S1, KV, dk)
    v_cache: jnp.ndarray,  # (R, S1, KV, dk)
    mask: jnp.ndarray,     # (R, C, S1) bool — the spec-tree BitMask
    *,
    block_s: int = 256,
    scale: Optional[float] = None,
) -> jnp.ndarray:
    """Fused tree-verify attention: every speculative tree token attends
    its causal-bitmask cache subset in one pass (reference
    ``tree_inc_multihead_self_attention.cu``). Returns (R, C, H, dk)."""
    R, C, H, dk = q.shape
    _, S1, KV, _ = k_cache.shape
    G = H // KV
    scale = scale if scale is not None else 1.0 / math.sqrt(dk)
    block_s = 128 * pl.cdiv(min(block_s, S1), 128)  # lane-aligned blocks
    qg = q.reshape(R, C, KV, G, dk)
    grid = (R, pl.cdiv(S1, block_s))

    out = pl.pallas_call(
        functools.partial(_verify_kernel, block_s=block_s, total_s=S1,
                          scale=scale),
        out_shape=jax.ShapeDtypeStruct((R, C, KV, G, dk), q.dtype),
        grid_spec=pl.GridSpec(
            grid=grid,
            in_specs=[
                pl.BlockSpec((1, C, KV, G, dk), lambda r, s: (r, 0, 0, 0, 0)),
                pl.BlockSpec((1, block_s, KV, dk), lambda r, s: (r, s, 0, 0)),
                pl.BlockSpec((1, block_s, KV, dk), lambda r, s: (r, s, 0, 0)),
                pl.BlockSpec((1, C, block_s), lambda r, s: (r, 0, s)),
            ],
            out_specs=pl.BlockSpec(
                (1, C, KV, G, dk), lambda r, s: (r, 0, 0, 0, 0)
            ),
            scratch_shapes=[
                pltpu.VMEM((C, KV, G, dk), jnp.float32),
                pltpu.VMEM((C, KV, G), jnp.float32),
                pltpu.VMEM((C, KV, G), jnp.float32),
            ],
        ),
        interpret=_interpret(),
    )(qg, k_cache, v_cache, mask)
    return out.reshape(R, C, H, dk)


# ---------------------------------------------------------------------------
# Shared serving-mask construction. Every serving step — sync chunked
# prefill, fused decode, and the mixed continuous-batching step — uses
# the same causal-by-position contract: a query token attends every
# cache line whose position is <= its own, never the scratch line, so
# one static-shape program serves ragged rows (padding columns sit at
# the scratch position and are masked out of nothing real). These were
# previously duplicated across the model-family modules.


def causal_serve_mask(positions: jnp.ndarray, S1: int) -> jnp.ndarray:
    """Causal-by-position mask over a dense cache: positions (R, C) →
    (R, C, S1) bool. Line S1-1 is the per-slot scratch row and is never
    attended; only positions already written satisfy ``<=``, so stale
    lines from an evicted slot occupant are never read."""
    key_pos = jnp.arange(S1, dtype=jnp.int32)
    mask = key_pos[None, None, :] <= positions[:, :, None]
    return mask & (key_pos[None, None, :] < S1 - 1)


def paged_serve_mask(
    mask: Optional[jnp.ndarray],
    positions: jnp.ndarray,
    num_logical_pages: int,
    page_size: int,
    cache_len: int,
) -> jnp.ndarray:
    """Paged twin of :func:`causal_serve_mask` over the page-aligned
    virtual cache (S_virt = NP * page_size): builds the causal mask when
    ``mask`` is None, otherwise pads an explicit (R, C, cache_len+1)
    mask out to S_virt (padding is never-attended). The scratch LINE
    (index ``cache_len``, where padding tokens write) is excluded."""
    S_virt = num_logical_pages * page_size
    if mask is None:
        key_pos = jnp.arange(S_virt, dtype=jnp.int32)
        mask = key_pos[None, None, :] <= positions[:, :, None]
        return mask & (key_pos[None, None, :] < cache_len)
    if mask.shape[-1] < S_virt:
        pad = S_virt - mask.shape[-1]
        mask = jnp.pad(mask, ((0, 0), (0, 0), (0, pad)))
    return mask


# ---------------------------------------------------------------------------
# Ragged paged attention (paged KV pool + per-request page table)


def gather_pages(pool: jnp.ndarray, page_table: jnp.ndarray) -> jnp.ndarray:
    """``jnp.take``-gather of a request's logical cache from the page
    pool: pool (P+1, ps, ...) × table (R, NP) → virtual cache
    (R, NP*ps, ...). Unallocated table entries point at the scratch page
    (pool row P) — the caller's mask never exposes those lines."""
    R, NP = page_table.shape
    ps = pool.shape[1]
    flat = jnp.take(pool, page_table.reshape(-1), axis=0)
    return flat.reshape((R, NP * ps) + pool.shape[2:])


def _unpack_codes(block: jnp.ndarray, pack: int) -> jnp.ndarray:
    """Stored code block → f32 code values: identity cast for pack=1
    (int8), nibble unpack for pack=2 (uint8 int4 pages — op-for-op
    ``kv_quant.unpack_nibbles``: low nibble = head-dim entries
    [0, dk/2), high nibble = [dk/2, dk), bias +8; integer arithmetic,
    so the Pallas and XLA paths decode identical values)."""
    if pack == 1:
        return block.astype(jnp.float32)
    b = block.astype(jnp.int32)
    lo = (b & 0xF) - 8
    hi = ((b >> 4) & 0xF) - 8
    return jnp.concatenate([lo, hi], axis=-1).astype(jnp.float32)


def _pack_codes(codes: jnp.ndarray, dtype, pack: int) -> jnp.ndarray:
    """f32 code values → stored block (inverse of :func:`_unpack_codes`;
    the in-kernel twin of ``kv_quant.pack_nibbles``)."""
    if pack == 1:
        return codes.astype(dtype)
    dk = codes.shape[-1]
    c = codes.astype(jnp.int32) + 8
    lo, hi = c[..., : dk // 2], c[..., dk // 2 :]
    return (lo | (hi << 4)).astype(dtype)


def dequant_pages(
    pool: jnp.ndarray,        # (P+1, ps, KV, dk/pack) int8/uint8 codes
    scale: jnp.ndarray,       # (P+1, KV) f32 per-page-per-head scales
    page_table: jnp.ndarray,  # (R, NP) int32
    dtype,
) -> jnp.ndarray:
    """Quantized twin of :func:`gather_pages`: gather the quantized
    virtual cache through the table and dequantize each line at its
    page's per-KV-head scale (serve/kv_quant.py layout; uint8 pools
    unpack two nibble codes per byte first). Returns the
    (R, NP*ps, KV, dk) full-precision virtual cache in ``dtype``."""
    from .kv_quant import pool_pack

    R, NP = page_table.shape
    ps, KV = pool.shape[1], pool.shape[2]
    codes = gather_pages(pool, page_table)        # (R, S, KV, dk/pack)
    codes = _unpack_codes(codes, pool_pack(pool))  # (R, S, KV, dk) f32
    s = jnp.take(scale, page_table.reshape(-1), axis=0)  # (R*NP, KV)
    s = jnp.broadcast_to(
        s.reshape(R, NP, 1, KV), (R, NP, ps, KV)
    ).reshape(R, NP * ps, KV)
    return (codes * s[..., None]).astype(dtype)


def ragged_paged_attention_xla(
    q: jnp.ndarray,           # (R, C, H, dk)
    k_pool: jnp.ndarray,      # (P+1, ps, KV, dk)
    v_pool: jnp.ndarray,      # (P+1, ps, KV, dk)
    page_table: jnp.ndarray,  # (R, NP) int32 physical page per logical page
    mask: jnp.ndarray,        # (R, C, NP*ps) bool
    *,
    scale: Optional[float] = None,
    k_scale: Optional[jnp.ndarray] = None,  # (P+1, KV) f32 (quantized pool)
    v_scale: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """Shape-identical XLA fallback: gather the virtual cache through
    the page table, then the standard grouped-query masked softmax —
    bit-for-bit the dense ``serve_attention`` math on the gathered
    lines. With ``k_scale``/``v_scale`` the pools hold quantized codes
    (serve/kv_quant.py; packed int4 nibbles unpack first) and the
    gathered lines are dequantized at their page scales. Returns
    (R, C, H, dk)."""
    R, C, H, dk = q.shape
    KV = k_pool.shape[2]
    G = H // KV
    scale = scale if scale is not None else 1.0 / math.sqrt(dk)
    if k_scale is not None:
        k_virt = dequant_pages(k_pool, k_scale, page_table, q.dtype)
        v_virt = dequant_pages(v_pool, v_scale, page_table, q.dtype)
    else:
        k_virt = gather_pages(k_pool, page_table)  # (R, S, KV, dk)
        v_virt = gather_pages(v_pool, page_table)
    qg = q.reshape(R, C, KV, G, dk)
    scores = jnp.einsum(
        "rckgd,rskd->rkgcs", qg, k_virt, preferred_element_type=jnp.float32
    ) * scale
    scores = jnp.where(mask[:, None, None], scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    out = jnp.einsum("rkgcs,rskd->rckgd", probs, v_virt)
    return out.reshape(R, C, H, dk)


def _rope_rotate(x, cos, sin):
    """Rotate-half RoPE on the trailing head dim, op-for-op the XLA
    ``apply_rope`` (models/llama.py, models/transformer.py) so the
    in-kernel prologue stays bitwise-identical to the unfused path.
    ``cos``/``sin`` arrive pre-broadcast against ``x``; partial rotary
    widths (``cos.shape[-1] < head_dim``, Phi-style) pass the tail of
    each head through untouched."""
    rot = cos.shape[-1]
    xr = x[..., :rot]
    half = rot // 2
    x1, x2 = xr[..., :half], xr[..., half:]
    rotated = jnp.concatenate([-x2, x1], axis=-1)
    out = xr * cos + rotated * sin
    if x.shape[-1] > rot:
        out = jnp.concatenate([out, x[..., rot:].astype(out.dtype)], axis=-1)
    return out.astype(x.dtype)


def _build_ragged_paged_kernel(
    *,
    quant: bool,
    fused: bool,
    C: int,
    scale: float,
    qmax: float = 0.0,
    has_rope: bool = True,
    pack: int = 1,
):
    """ONE builder for every Pallas variant of the ragged paged kernel
    (see the module-docstring matrix): ``quant`` folds the per-page
    dequant scales into the batched dots' OUTPUTS (scores ×=
    k_scale[kv], pv ×= v_scale[kv] — scales are constant within a
    page, so scaling the O(C·G·ps) scores and O(C·G·dk) pv is exact
    and strictly cheaper than scaling the O(ps·dk) operands);
    ``pack=2`` (int4) additionally unpacks two nibble codes per DMA'd
    uint8 byte in VMEM before the dots — the page DMA moves HALF the
    int8 bytes; ``fused`` adds the megakernel prologue (in-kernel RoPE
    + KV page write through aliased pool outputs, packing through the
    same nibble layout). The quant, pack and fused axes compose, so
    the kernel variants share one attention body instead of
    hand-maintained copies."""

    def _attend(q, k, v, ks, vs, mask, o_scr, m_scr, l_scr):
        # q (C, KV, G, dk) f32; k/v (KV, ps, dk) f32; ks/vs (KV,) f32
        # (quant only); one batched dot per KV head over the grouped
        # (KV, C*G, dk) query layout
        KV, G = q.shape[1], q.shape[2]
        qkv = q.transpose(1, 0, 2, 3).reshape(KV, C * G, q.shape[-1])
        scores = jax.lax.dot_general(
            qkv, k,
            dimension_numbers=(((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        )                                           # (KV, C*G, ps)
        if quant:
            scores = scores * (ks[:, None, None] * scale)  # dequant K
        else:
            scores = scores * scale
        scores = scores.reshape(KV, C, G, -1).transpose(1, 0, 2, 3)
        scores = jnp.where(mask[:, None, None, :], scores, NEG_INF)
        m_new = jnp.maximum(m_scr[:], scores.max(axis=-1))
        prob = jnp.exp(scores - m_new[..., None])
        prob = jnp.where(mask[:, None, None, :], prob, 0.0)
        corr = jnp.exp(m_scr[:] - m_new)
        l_scr[:] = l_scr[:] * corr + prob.sum(axis=-1)
        pk = prob.transpose(1, 0, 2, 3).reshape(KV, C * G, -1)
        pv = jax.lax.dot_general(
            pk, v,
            dimension_numbers=(((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        )  # (KV, C*G, dk)
        if quant:
            pv = pv * vs[:, None, None]             # dequant V
        pv = pv.reshape(KV, C, G, -1).transpose(1, 0, 2, 3)
        o_scr[:] = o_scr[:] * corr[..., None] + pv
        m_scr[:] = m_new

    def _init(o_scr, m_scr, l_scr):
        o_scr[:] = jnp.zeros_like(o_scr)
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)

    def _finalize(p, out_ref, o_scr, l_scr):
        @pl.when(p == pl.num_programs(1) - 1)
        def _():
            l = jnp.maximum(l_scr[:], 1e-20)
            out_ref[0] = (o_scr[:] / l[..., None]).astype(out_ref.dtype)

    def _quant_commit(pool_out, scale_in, lines, belongs, offs):
        """In-kernel ``kv_quant.quant_line_write`` restricted to the
        current page block: running per-page amax, rescale-on-growth,
        offset-0 scale reset — op-for-op the XLA write-side contract,
        page-locally (pages are slot-private or the never-read scratch
        page, so the global scatter degenerates to this). ``pool_out``
        already holds the copied-through page codes; on exit it holds
        the requantized codes plus the new lines (packed layouts
        unpack, requantize on code values, and repack — the same
        arithmetic as the XLA twin, so pool bytes stay bitwise).
        Returns the page's final (KV,) scale — also the dequant scale
        attention uses, exactly as the unfused path reads the
        post-write scale row."""
        vf = lines.astype(jnp.float32)                 # (C, KV, dk)
        amax = jnp.max(jnp.abs(vf), axis=-1)           # (C, KV)
        page_amax = jnp.where(belongs[:, None], amax, 0.0).max(axis=0)
        first = belongs[0] & (offs[0] == 0)
        for c in range(1, C):
            first = first | (belongs[c] & (offs[c] == 0))
        old = jnp.where(first, 0.0, scale_in)          # (KV,)
        new = jnp.maximum(old, page_amax / qmax)
        ratio = jnp.where(new > 0.0, old / jnp.maximum(new, 1e-30), 0.0)
        codes = _unpack_codes(pool_out[0], pack)       # (ps, KV, dk)
        pool_out[0] = _pack_codes(
            jnp.round(codes * ratio[None, :, None]), pool_out.dtype, pack
        )
        q = jnp.round(vf / jnp.maximum(new, 1e-30)[None, :, None])
        q = _pack_codes(jnp.clip(q, -qmax, qmax), pool_out.dtype, pack)
        for c in range(C):
            @pl.when(belongs[c])
            def _(c=c):
                pool_out[0, offs[c]] = q[c]
        return new

    def plain_kernel(*refs):
        # (pt, q, k, v, [ks, vs], mask) -> out; o/m/l scratch
        i = 1  # refs[0] is the scalar-prefetched page table
        q_ref = refs[i]; i += 1         # (1, C, KV, G, dk)
        k_ref = refs[i]; i += 1         # (1, ps, KV, dk) via index map
        v_ref = refs[i]; i += 1
        if quant:
            ks_ref = refs[i]; i += 1    # (1, KV) f32 page scales
            vs_ref = refs[i]; i += 1
        mask_ref = refs[i]; i += 1      # (1, C, ps)
        out_ref = refs[i]; i += 1       # (1, C, KV, G, dk)
        o_scr, m_scr, l_scr = refs[i:i + 3]

        p = pl.program_id(1)

        @pl.when(p == 0)
        def _():
            _init(o_scr, m_scr, l_scr)

        mask = mask_ref[0]  # (C, ps) — already bounded: S_virt = NP*ps

        @pl.when(jnp.any(mask))
        def _():
            q = q_ref[0].astype(jnp.float32)
            k = _unpack_codes(k_ref[0], pack).transpose(1, 0, 2)
            v = _unpack_codes(v_ref[0], pack).transpose(1, 0, 2)
            ks = ks_ref[0] if quant else None
            vs = vs_ref[0] if quant else None
            _attend(q, k, v, ks, vs, mask, o_scr, m_scr, l_scr)

        _finalize(p, out_ref, o_scr, l_scr)

    def fused_kernel(*refs):
        # (pt, logical, off, q_raw, k_new, v_new, [cos, sin],
        #  k_page, v_page, [ks, vs], mask)
        #   -> (out, k_page', v_page', [ks', vs']); pool outputs alias
        #      the pools, so unvisited pages keep their bytes
        pt_ref, lg_ref, off_ref = refs[0], refs[1], refs[2]
        i = 3
        q_ref = refs[i]; i += 1         # (1, C, KV, G, dk) pre-RoPE
        kn_ref = refs[i]; i += 1        # (1, C, KV, dk) pre-RoPE
        vn_ref = refs[i]; i += 1        # (1, C, KV, dk)
        if has_rope:
            cos_ref = refs[i]; i += 1   # (1, C, rot) f32
            sin_ref = refs[i]; i += 1
        k_ref = refs[i]; i += 1         # (1, ps, KV, dk) page block
        v_ref = refs[i]; i += 1
        if quant:
            ks_ref = refs[i]; i += 1    # (1, KV) f32
            vs_ref = refs[i]; i += 1
        mask_ref = refs[i]; i += 1      # (1, C, ps)
        out_ref = refs[i]; i += 1       # (1, C, KV, G, dk)
        k_out = refs[i]; i += 1         # (1, ps, KV, dk) aliased pool
        v_out = refs[i]; i += 1
        if quant:
            ks_out = refs[i]; i += 1    # (1, KV) aliased scale row
            vs_out = refs[i]; i += 1
        o_scr, m_scr, l_scr = refs[i:i + 3]
        q_scr = refs[i + 3]             # (C, KV, G, dk) roped q, q dtype
        k_scr = refs[i + 4]             # (C, KV, dk) roped k, k dtype

        r = pl.program_id(0)
        p = pl.program_id(1)

        @pl.when(p == 0)
        def _():
            _init(o_scr, m_scr, l_scr)
            # RoPE once per row, reused across every page step; stored
            # at the model dtype so the double f32→dtype→f32 cast of
            # the unfused path (XLA rope, then kernel load) is mirrored
            if has_rope:
                cos = cos_ref[0]        # (C, rot) f32
                sin = sin_ref[0]
                q_scr[:] = _rope_rotate(
                    q_ref[0], cos[:, None, None, :], sin[:, None, None, :]
                )
                k_scr[:] = _rope_rotate(
                    kn_ref[0], cos[:, None, :], sin[:, None, :]
                )
            else:
                q_scr[:] = q_ref[0]
                k_scr[:] = kn_ref[0]

        # ---- prologue: commit this row's fresh K/V lines landing in
        # this grid step's page. Every visited page is written back as
        # a full block (copy-through + line writes): untouched pages
        # round-trip identical bytes, the token's page carries the new
        # lines, and aliasing keeps unvisited pages' bytes in place.
        k_out[0] = k_ref[0]
        v_out[0] = v_ref[0]
        belongs = [lg_ref[r, c] == p for c in range(C)]
        offs = [off_ref[r, c] for c in range(C)]
        if quant:
            bvec = jnp.stack(belongs)
            ks_new = _quant_commit(k_out, ks_ref[0], k_scr[:], bvec, offs)
            vs_new = _quant_commit(v_out, vs_ref[0], vn_ref[0], bvec, offs)
            ks_out[0] = ks_new
            vs_out[0] = vs_new
        else:
            ks_new = vs_new = None
            for c in range(C):
                @pl.when(belongs[c])
                def _(c=c):
                    k_out[0, offs[c]] = k_scr[c].astype(k_out.dtype)
                    v_out[0, offs[c]] = vn_ref[0, c].astype(v_out.dtype)

        mask = mask_ref[0]  # (C, ps)

        @pl.when(jnp.any(mask))
        def _():
            q = q_scr[:].astype(jnp.float32)
            # attention reads the page through the freshly written
            # block — the fresh K/V never left VMEM
            k = _unpack_codes(k_out[0], pack).transpose(1, 0, 2)
            v = _unpack_codes(v_out[0], pack).transpose(1, 0, 2)
            _attend(q, k, v, ks_new, vs_new, mask, o_scr, m_scr, l_scr)

        _finalize(p, out_ref, o_scr, l_scr)

    return fused_kernel if fused else plain_kernel


def ragged_paged_attention(
    q: jnp.ndarray,           # (R, C, H, dk)
    k_pool: jnp.ndarray,      # (P+1, ps, KV, dk)
    v_pool: jnp.ndarray,      # (P+1, ps, KV, dk)
    page_table: jnp.ndarray,  # (R, NP) int32
    mask: jnp.ndarray,        # (R, C, NP*ps) bool
    *,
    scale: Optional[float] = None,
    k_scale: Optional[jnp.ndarray] = None,  # (P+1, KV) f32 (quantized pool)
    v_scale: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """Fused ragged paged attention: grid (request, logical page); the
    K/V BlockSpec index maps read the scalar-prefetched page table so
    each step DMAs exactly the physical page that logical position maps
    to — gathering through the table without materialising the
    (R, S) virtual cache. One kernel covers decode (C=1), chunked
    prefill and tree verify (the explicit-mask modes). With
    ``k_scale``/``v_scale`` the pools hold quantized codes (int8, or
    packed int4 nibbles when the pool's trailing dim is dk/2) and the
    same index maps additionally DMA each page's per-KV-head scales;
    dequant — and, packed, the nibble unpack — happens in VMEM so the
    full-precision cache never exists in HBM. Returns (R, C, H, dk)."""
    R, C, H, dk = q.shape
    _, ps, KV, dkp = k_pool.shape  # dkp = dk / pack (int4 packs 2)
    NP = page_table.shape[1]
    G = H // KV
    pack = dk // dkp if k_scale is not None else 1
    scale = scale if scale is not None else 1.0 / math.sqrt(dk)
    qg = q.reshape(R, C, KV, G, dk)
    grid = (R, NP)

    in_specs = [
        pl.BlockSpec((1, C, KV, G, dk),
                     lambda r, p, pt: (r, 0, 0, 0, 0)),
        # the paged gather: block row = page_table[r, p]
        pl.BlockSpec((1, ps, KV, dkp),
                     lambda r, p, pt: (pt[r, p], 0, 0, 0)),
        pl.BlockSpec((1, ps, KV, dkp),
                     lambda r, p, pt: (pt[r, p], 0, 0, 0)),
    ]
    operands = [qg, k_pool, v_pool]
    kernel = _build_ragged_paged_kernel(
        quant=k_scale is not None, fused=False, C=C, scale=scale, pack=pack
    )
    if k_scale is not None:
        in_specs += [
            pl.BlockSpec((1, KV), lambda r, p, pt: (pt[r, p], 0)),
            pl.BlockSpec((1, KV), lambda r, p, pt: (pt[r, p], 0)),
        ]
        operands += [
            k_scale.astype(jnp.float32), v_scale.astype(jnp.float32)
        ]
    in_specs.append(pl.BlockSpec((1, C, ps), lambda r, p, pt: (r, 0, p)))
    operands.append(mask)

    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((R, C, KV, G, dk), q.dtype),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=grid,
            in_specs=in_specs,
            out_specs=pl.BlockSpec(
                (1, C, KV, G, dk), lambda r, p, pt: (r, 0, 0, 0, 0)
            ),
            scratch_shapes=[
                pltpu.VMEM((C, KV, G, dk), jnp.float32),
                pltpu.VMEM((C, KV, G), jnp.float32),
                pltpu.VMEM((C, KV, G), jnp.float32),
            ],
        ),
        interpret=_interpret(),
    )(page_table.astype(jnp.int32), *operands)
    return out.reshape(R, C, H, dk)


# ---------------------------------------------------------------------------
# Ring ragged paged attention (context-parallel serving,
# ServingConfig.kv_shard="context"): one request's KV pages are
# sequence-sharded over the mesh ``seq`` axis — shard d owns the
# contiguous pool-row slice [d*rows_local, (d+1)*rows_local) and logical
# pages stripe over shards (serve/paging.py PageAllocator cp_shards) —
# and attention runs as a shard_map program: every shard computes
# UNNORMALIZED online-softmax partials (o, m, l) over its RESIDENT pages
# only (reads stay local — each shard touches its own HBM slice at full
# bandwidth), the partial stats rotate around the ring via ``ppermute``,
# and each shard merges them with the same m/l/o online-softmax carry
# ``parallel/sequence._online_block`` uses for training ring attention.
# The merge runs in ABSOLUTE shard order (0..n-1) on every shard, so the
# result is deterministic and identical across shards — run-to-run
# bitwise, though not bitwise vs the single-shard kernel (the per-shard
# partial sums reassociate the softmax reduction; tests bound the drift
# and assert greedy-token agreement instead).
#
# :func:`ring_ragged_paged_attention_xla` is the CPU-parity fallback
# with a stronger contract: on a single-device (or replicated) layout
# every shard's pages are locally addressable, so the full-table gather
# IS the ring result — BITWISE the CP-off ``ragged_paged_attention_xla``
# math. That is what makes CP-on vs CP-off generation bitwise on this
# box (tests/test_long_context.py) and is the reference the shard_map
# program is checked against.


def ring_ragged_paged_attention_xla(
    q: jnp.ndarray,           # (R, C, H, dk)
    k_pool: jnp.ndarray,      # (rows, ps, KV, dk/pack)
    v_pool: jnp.ndarray,
    page_table: jnp.ndarray,  # (R, NP) int32
    mask: jnp.ndarray,        # (R, C, NP*ps) bool
    *,
    scale: Optional[float] = None,
    k_scale: Optional[jnp.ndarray] = None,
    v_scale: Optional[jnp.ndarray] = None,
    cp_shards: int = 1,
) -> jnp.ndarray:
    """``jnp.take``-based fallback of the ring kernel: gather the
    virtual cache through the FULL page table and run the standard
    masked softmax — bit-for-bit :func:`ragged_paged_attention_xla`
    regardless of which shard's row slice each page lives in (the
    gather is layout-blind), which is exactly the CP-on == CP-off
    bitwise contract the engine's context-parallel mode serves under
    on CPU. ``cp_shards`` documents the layout; the math ignores it."""
    del cp_shards
    return ragged_paged_attention_xla(
        q, k_pool, v_pool, page_table, mask,
        scale=scale, k_scale=k_scale, v_scale=v_scale,
    )


def _online_merge(o_a, m_a, l_a, o_b, m_b, l_b):
    """Merge two unnormalized online-softmax partials — the carry
    combine of ``parallel/sequence._online_block``, applied across
    shards instead of across K/V blocks. Fully-masked partials carry
    m = -inf and contribute nothing (the isfinite guards mirror the
    training ring's padded-block handling)."""
    m_new = jnp.maximum(m_a, m_b)
    safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
    ca = jnp.where(jnp.isfinite(m_a), jnp.exp(m_a - safe), 0.0)
    cb = jnp.where(jnp.isfinite(m_b), jnp.exp(m_b - safe), 0.0)
    l_new = l_a * ca + l_b * cb
    o_new = o_a * ca[..., None] + o_b * cb[..., None]
    return o_new, m_new, l_new


def ring_ragged_paged_attention(
    q: jnp.ndarray,           # (R, C, H, dk)
    k_pool: jnp.ndarray,      # (rows, ps, KV, dk/pack) — rows % n == 0
    v_pool: jnp.ndarray,
    page_table: jnp.ndarray,  # (R, NP) int32 GLOBAL physical pages
    mask: jnp.ndarray,        # (R, C, NP*ps) bool
    mesh,
    *,
    scale: Optional[float] = None,
    k_scale: Optional[jnp.ndarray] = None,  # (rows, KV) f32 (quant pool)
    v_scale: Optional[jnp.ndarray] = None,
    fused: Optional[dict] = None,
):
    """Context-parallel ragged paged attention over a sequence-sharded
    page pool (see the section comment above): per-shard resident-page
    partials + ``ppermute`` stat rotation + online-softmax merge in
    absolute shard order. The ``seq`` axis runs manually (partial
    shard_map — other mesh axes stay under GSPMD); pool rows (and the
    quant scale rows) shard over ``seq``, q/table/mask replicate.
    Returns (R, C, H, dk). ``mesh.shape[seq] == 1`` degenerates to the
    XLA fallback (nothing to rotate).

    ``fused`` (the PR-6 ``rope_kv_write`` prologue, lifted onto
    seq-sharded meshes): a dict ``{k_new, v_new, cos, sin, phys, off}``
    — ``q``/``k_new`` arrive PRE-RoPE and each shard rotates them
    in-body (op-for-op :func:`_rope_rotate` == the XLA ``apply_rope``)
    and commits the fresh K/V lines to its OWN resident rows
    (non-resident lines drop via an out-of-bounds scatter, exactly the
    rows a GSPMD scatter would route elsewhere) before attending — the
    separate XLA rope + replicated-index scatter leave the step
    program. Returns ``(out, k_pool, v_pool)``. ``cos``/``sin`` may be
    None (no-RoPE families: the prologue is just the commit).
    Full-precision pools only — the quantized ring commit (per-shard
    scale ownership) is still excluded at validation."""
    from jax import lax

    from ..core.mesh import SEQ_AXIS, shard_map_unchecked
    from jax.sharding import PartitionSpec as P

    n = mesh.shape[SEQ_AXIS]
    if n <= 1:
        if fused is not None:
            # degenerate single-shard layout: the unfused composition IS
            # the reference math (same ops the fused body mirrors)
            cos, sin = fused.get("cos"), fused.get("sin")
            qr, kr = q, fused["k_new"]
            if cos is not None:
                qr = _rope_rotate(q, cos[:, :, None, :], sin[:, :, None, :])
                kr = _rope_rotate(
                    fused["k_new"], cos[:, :, None, :], sin[:, :, None, :]
                )
            k_pool = k_pool.at[fused["phys"], fused["off"]].set(
                kr.astype(k_pool.dtype)
            )
            v_pool = v_pool.at[fused["phys"], fused["off"]].set(
                fused["v_new"].astype(v_pool.dtype)
            )
            out = ring_ragged_paged_attention_xla(
                qr, k_pool, v_pool, page_table, mask,
                scale=scale, k_scale=k_scale, v_scale=v_scale,
            )
            return out, k_pool, v_pool
        return ring_ragged_paged_attention_xla(
            q, k_pool, v_pool, page_table, mask,
            scale=scale, k_scale=k_scale, v_scale=v_scale,
        )
    R, C, H, dk = q.shape
    rows, ps, KV, dkp = k_pool.shape
    if rows % n:
        raise ValueError(
            f"ring ragged paged attention needs pool rows ({rows}) "
            f"divisible by the seq degree ({n}) — the engine pads the "
            "pool with unreferenced rows to align the shard slices"
        )
    if fused is not None and k_scale is not None:
        raise NotImplementedError(
            "the fused rope_kv_write prologue is not composed with "
            "quantized pools on a sequence-sharded mesh — the per-page "
            "amax scale update is not shard-local; drop the fusion or "
            "kv_quant (ServingConfig.validate_long_context names this)"
        )
    rows_local = rows // n
    G = H // KV
    quant = k_scale is not None
    scale_f = scale if scale is not None else 1.0 / math.sqrt(dk)
    has_rope = fused is not None and fused.get("cos") is not None

    def body(q_, kp, vp, pt, mk, *rest):
        i = lax.axis_index(SEQ_AXIS)
        if fused is not None:
            if has_rope:
                kn, vn, cos_, sin_, fph, fof = rest[-6:]
                q_ = _rope_rotate(
                    q_, cos_[:, :, None, :], sin_[:, :, None, :]
                )
                kn = _rope_rotate(
                    kn, cos_[:, :, None, :], sin_[:, :, None, :]
                )
            else:
                kn, vn, fph, fof = rest[-4:]
            # commit each fresh line on its OWNING shard only:
            # non-resident lines redirect out of bounds and drop — the
            # same rows a GSPMD scatter over the sharded pool routes to
            # other shards, so pool bytes stay bitwise the unfused
            # step's.
            res_line = (fph // rows_local) == i          # (R, C)
            lph = jnp.where(res_line, fph % rows_local, rows_local)
            kp = kp.at[lph, fof].set(kn.astype(kp.dtype), mode="drop")
            vp = vp.at[lph, fof].set(vn.astype(vp.dtype), mode="drop")
        # translate the GLOBAL table to this shard's rows: resident
        # pages keep their local row, everything else reads local row 0
        # and is masked out of the partial (the caller's mask already
        # excludes scratch-backed positions; the residency mask
        # additionally excludes pages another shard owns)
        resident = (pt // rows_local) == i          # (R, NP)
        lpt = jnp.where(resident, pt % rows_local, 0)
        if quant:
            ks_, vs_ = rest[0], rest[1]
            k_virt = dequant_pages(kp, ks_, lpt, q_.dtype)
            v_virt = dequant_pages(vp, vs_, lpt, q_.dtype)
        else:
            k_virt = gather_pages(kp, lpt)          # (R, S, KV, dk)
            v_virt = gather_pages(vp, lpt)
        res_cols = jnp.repeat(resident, ps, axis=1)  # (R, NP*ps)
        mk_loc = mk & res_cols[:, None, :]           # (R, C, S)
        qg = q_.reshape(R, C, KV, G, dk)
        scores = jnp.einsum(
            "rckgd,rskd->rckgs", qg, k_virt,
            preferred_element_type=jnp.float32,
        ) * scale_f                                  # (R, C, KV, G, S)
        mm = mk_loc[:, :, None, None, :]
        scores = jnp.where(mm, scores, -jnp.inf)
        m_loc = scores.max(axis=-1)                  # (R, C, KV, G)
        safe_m = jnp.where(jnp.isfinite(m_loc), m_loc, 0.0)
        p = jnp.where(mm, jnp.exp(scores - safe_m[..., None]), 0.0)
        l_loc = p.sum(axis=-1)
        o_loc = jnp.einsum(
            "rckgs,rskd->rckgd", p, v_virt.astype(jnp.float32)
        )
        # ring: rotate the (o, m, l) partials n-1 hops; parts[s] on
        # shard i originated at shard (i - s) % n
        perm = [(s, (s + 1) % n) for s in range(n)]
        cur = (o_loc, m_loc, l_loc)
        parts = [cur]
        for _ in range(n - 1):
            cur = tuple(
                lax.ppermute(x, SEQ_AXIS, perm) for x in cur
            )
            parts.append(cur)
        stk = tuple(
            jnp.stack([p_[t] for p_ in parts]) for t in range(3)
        )
        # merge in ABSOLUTE shard order 0..n-1 — every shard applies
        # the identical association, so the output replicates exactly
        def merge_j(j, carry):
            s = (i - j) % n  # which rotation slot holds shard j's part
            o_b = jnp.take(stk[0], s, axis=0)
            m_b = jnp.take(stk[1], s, axis=0)
            l_b = jnp.take(stk[2], s, axis=0)
            return _online_merge(*carry, o_b, m_b, l_b)
        o0 = jnp.zeros_like(o_loc)
        m0 = jnp.full_like(m_loc, -jnp.inf)
        l0 = jnp.zeros_like(l_loc)
        o, m, l = lax.fori_loop(0, n, merge_j, (o0, m0, l0))
        out = o / jnp.maximum(l, 1e-20)[..., None]
        out = out.astype(q_.dtype).reshape(R, C, H, dk)
        if fused is not None:
            return out, kp, vp
        return out

    rep = P(None, None, None, None)
    pool_spec = P(SEQ_AXIS, None, None, None)
    in_specs = [
        rep,                                  # q
        pool_spec,                            # k_pool rows
        pool_spec,                            # v_pool rows
        P(None, None),                        # page table (global)
        P(None, None, None),                  # mask
    ]
    operands = [q, k_pool, v_pool, page_table.astype(jnp.int32), mask]
    if quant:
        in_specs += [P(SEQ_AXIS, None), P(SEQ_AXIS, None)]
        operands += [
            k_scale.astype(jnp.float32), v_scale.astype(jnp.float32)
        ]
    out_specs: Any = rep
    if fused is not None:
        # the prologue's operands replicate (every shard sees every
        # fresh line and keeps only its resident ones); the updated
        # pools come back seq-sharded exactly as they went in
        in_specs += [P(None, None, None, None), P(None, None, None, None)]
        operands += [fused["k_new"], fused["v_new"]]
        if has_rope:
            in_specs += [P(None, None, None), P(None, None, None)]
            operands += [fused["cos"], fused["sin"]]
        in_specs += [P(None, None), P(None, None)]
        operands += [
            fused["phys"].astype(jnp.int32), fused["off"].astype(jnp.int32)
        ]
        out_specs = (rep, pool_spec, pool_spec)
    fn = shard_map_unchecked(
        body, mesh, tuple(in_specs), out_specs, manual_axes={SEQ_AXIS}
    )
    # partial-manual shard_map has no eager impl on jax 0.4.x — jit the
    # call (a no-op inside the engine's already-jitted step programs,
    # where this runs in production; standalone/test callers get the
    # same compiled path)
    return jax.jit(fn)(*operands)


def fused_rope_paged_attention(
    q: jnp.ndarray,           # (R, C, H, dk) — PRE-RoPE query projection
    k_new: jnp.ndarray,       # (R, C, KV, dk) — PRE-RoPE key projection
    v_new: jnp.ndarray,       # (R, C, KV, dk) — value projection
    cos: Optional[jnp.ndarray],   # (R, C, rot) f32, or None (no-RoPE family)
    sin: Optional[jnp.ndarray],
    k_pool: jnp.ndarray,      # (P+1, ps, KV, dk) — model dtype or int8 codes
    v_pool: jnp.ndarray,
    page_table: jnp.ndarray,  # (R, NP) int32
    logical: jnp.ndarray,     # (R, C) int32 logical page of each new line
    off: jnp.ndarray,         # (R, C) int32 in-page offset of each new line
    mask: jnp.ndarray,        # (R, C, NP*ps) bool
    *,
    scale: Optional[float] = None,
    k_scale: Optional[jnp.ndarray] = None,  # (P+1, KV) f32 (quantized pool)
    v_scale: Optional[jnp.ndarray] = None,
    qmax: Optional[float] = None,
):
    """Megakernel decode-step prologue fused into ragged paged
    attention: one ``pallas_call`` applies RoPE to Q/K, commits the
    fresh K/V lines into their table-resolved pages (quantizing at the
    page scales when ``qmax`` is set — the in-kernel twin of
    ``kv_quant.quant_line_write``) and runs the ragged paged attention
    pass, all in VMEM. The pools (and, quantized, their scale rows)
    are ALIASED outputs: unvisited pages keep their bytes, visited
    pages round-trip (identity copy-through), the written page carries
    the new lines. Returns ``(out, k_pool, v_pool, k_scale, v_scale)``
    — scales None on a full-precision pool.

    Bitwise contract: identical outputs and identical (non-scratch)
    pool bytes vs the unfused composition ``apply_rope → pool scatter
    (or quant_line_write) → ragged_paged_attention`` — same op order,
    same grid, same accumulation (tests/test_fused_decode.py). The
    XLA serving fallback needs no fused twin at all: the unfused step
    IS the reference math, so ``fused_decode`` with kernels="xla"
    routes through it unchanged.

    Intended for decode / small mixed chunks: the per-line commit
    unrolls over C, and every page in a row's table is written back
    (identity for untouched pages) — decode (C=1) is the case whose
    dispatch and HBM round-trips this removes."""
    R, C, H, dk = q.shape
    _, ps, KV, dkp = k_pool.shape  # dkp = dk / pack (int4 packs 2)
    NP = page_table.shape[1]
    G = H // KV
    scale = scale if scale is not None else 1.0 / math.sqrt(dk)
    quant = qmax is not None
    pack = dk // dkp if quant else 1
    has_rope = cos is not None
    qg = q.reshape(R, C, KV, G, dk)
    grid = (R, NP)

    kernel = _build_ragged_paged_kernel(
        quant=quant, fused=True, C=C, scale=scale,
        qmax=float(qmax) if quant else 0.0, has_rope=has_rope, pack=pack,
    )

    in_specs = [
        pl.BlockSpec((1, C, KV, G, dk),
                     lambda r, p, pt, lg, of: (r, 0, 0, 0, 0)),
        pl.BlockSpec((1, C, KV, dk),
                     lambda r, p, pt, lg, of: (r, 0, 0, 0)),
        pl.BlockSpec((1, C, KV, dk),
                     lambda r, p, pt, lg, of: (r, 0, 0, 0)),
    ]
    operands = [qg, k_new, v_new]
    if has_rope:
        rot = cos.shape[-1]
        in_specs += [
            pl.BlockSpec((1, C, rot), lambda r, p, pt, lg, of: (r, 0, 0)),
            pl.BlockSpec((1, C, rot), lambda r, p, pt, lg, of: (r, 0, 0)),
        ]
        operands += [cos, sin]
    # operand index of k_pool in the flattened pallas_call argument
    # list (scalar-prefetch args included) — the aliasing keys
    idx0 = 6 + (2 if has_rope else 0)
    in_specs += [
        pl.BlockSpec((1, ps, KV, dkp),
                     lambda r, p, pt, lg, of: (pt[r, p], 0, 0, 0)),
        pl.BlockSpec((1, ps, KV, dkp),
                     lambda r, p, pt, lg, of: (pt[r, p], 0, 0, 0)),
    ]
    operands += [k_pool, v_pool]
    aliases = {idx0: 1, idx0 + 1: 2}
    out_shapes = [
        jax.ShapeDtypeStruct((R, C, KV, G, dk), q.dtype),
        jax.ShapeDtypeStruct(k_pool.shape, k_pool.dtype),
        jax.ShapeDtypeStruct(v_pool.shape, v_pool.dtype),
    ]
    out_specs = [
        pl.BlockSpec((1, C, KV, G, dk),
                     lambda r, p, pt, lg, of: (r, 0, 0, 0, 0)),
        pl.BlockSpec((1, ps, KV, dkp),
                     lambda r, p, pt, lg, of: (pt[r, p], 0, 0, 0)),
        pl.BlockSpec((1, ps, KV, dkp),
                     lambda r, p, pt, lg, of: (pt[r, p], 0, 0, 0)),
    ]
    if quant:
        in_specs += [
            pl.BlockSpec((1, KV), lambda r, p, pt, lg, of: (pt[r, p], 0)),
            pl.BlockSpec((1, KV), lambda r, p, pt, lg, of: (pt[r, p], 0)),
        ]
        operands += [
            k_scale.astype(jnp.float32), v_scale.astype(jnp.float32)
        ]
        aliases[idx0 + 2] = 3
        aliases[idx0 + 3] = 4
        out_shapes += [
            jax.ShapeDtypeStruct(k_scale.shape, jnp.float32),
            jax.ShapeDtypeStruct(v_scale.shape, jnp.float32),
        ]
        out_specs += [
            pl.BlockSpec((1, KV), lambda r, p, pt, lg, of: (pt[r, p], 0)),
            pl.BlockSpec((1, KV), lambda r, p, pt, lg, of: (pt[r, p], 0)),
        ]
    in_specs.append(
        pl.BlockSpec((1, C, ps), lambda r, p, pt, lg, of: (r, 0, p))
    )
    operands.append(mask)

    outs = pl.pallas_call(
        kernel,
        out_shape=out_shapes,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=3,
            grid=grid,
            in_specs=in_specs,
            out_specs=out_specs,
            scratch_shapes=[
                pltpu.VMEM((C, KV, G, dk), jnp.float32),
                pltpu.VMEM((C, KV, G), jnp.float32),
                pltpu.VMEM((C, KV, G), jnp.float32),
                pltpu.VMEM((C, KV, G, dk), q.dtype),     # roped q
                pltpu.VMEM((C, KV, dk), k_new.dtype),    # roped k
            ],
        ),
        input_output_aliases=aliases,
        interpret=_interpret(),
    )(page_table.astype(jnp.int32), logical.astype(jnp.int32),
      off.astype(jnp.int32), *operands)
    if quant:
        out, k_pool, v_pool, ks, vs = outs
        return out.reshape(R, C, H, dk), k_pool, v_pool, ks, vs
    out, k_pool, v_pool = outs
    return out.reshape(R, C, H, dk), k_pool, v_pool, None, None


# ---------------------------------------------------------------------------
# Whole-step decode megakernel (ServingConfig.fused_decode=("whole_step",);
# MPK "Mega-Kernelizing Tensor Programs", PAPERS.md). PR 6 collapsed the
# decode step to ONE dispatched program, but inside that program XLA
# still runs L independent layer kernels, each round-tripping the (R, D)
# hidden state and re-fetching its weights from HBM per step.
# :func:`whole_step_decode` is the next multiple: ONE persistent
# ``pallas_call`` whose GRID WALKS THE LAYERS — grid step l computes
# layer l's full block (QKV projections, RoPE + KV page commit, ragged
# paged attention over the table, out-projection, MLP) with the hidden
# state carried in VMEM scratch, and the final grid step runs the
# epilogue (final norm, LM head, greedy argmax). Layer l's weights are
# delivered by BlockSpec index maps over the stacked (L, ...) parameter
# arrays, which is exactly Pallas's pipelined-grid contract: while grid
# step l computes, the DMA engines prefetch grid step l+1's blocks into
# the revolving VMEM buffers — double-buffered HBM→VMEM weight
# streaming without hand-written semaphores. The KV pool's per-layer
# slices stream the same way and alias their outputs, so only layer l's
# pages are resident at a time.
#
# Division of labor: THIS builder owns the grid, the streaming
# BlockSpecs, the aliasing, the hidden-state carry and the epilogue;
# the model family supplies ``block_fn``/``head_fn`` — closures over
# the SAME per-layer math its unfused XLA step runs
# (models/*.serve_step_paged's block body, op for op). That sharing is
# the bitwise contract: given identical inputs the kernel body executes
# identical operations, so whole-step decode is BITWISE the unfused XLA
# step on the same backend (fp and int8 pools; int4 under the PR-7
# packed-nibble tolerance documented in README) — the same way PR 6's
# fusions anchor on the XLA step as the CPU-parity reference.
#
# VMEM budget and SUB-BLOCK streaming: one grid step must hold 2×
# (double buffer) each layer's weight blocks + 2× its K/V pool slice
# (in + aliased out) + the resident constants (lm_head, mask, embed
# when tied) + the scratch carry + attention intermediates.
# :func:`whole_step_vmem_bytes` prices this; when the whole layer does
# not fit the budget (WHOLE_STEP_VMEM_BUDGET, ~a TPU core's usable
# VMEM, overridable via FF_WHOLE_STEP_VMEM_MB), the engine does NOT
# fall back — it picks a tile count K (:func:`whole_step_pick_tiles`)
# and the walk streams each projection weight in K output-column
# sub-tiles over an inner grid dimension (grid (L, 4·K): QKV tiles →
# attention → out-proj tiles → MLP up/gate tiles → down tiles), each
# tile's partial result accumulated into VMEM scratch. Column-tiling
# the OUTPUT dim only — never the contraction dim — keeps every tile's
# matmul bit-identical to the corresponding column slice of the full
# matmul, so the tiled walk stays bitwise the unfused XLA step; the
# footprint is bounded by the tile size, not the layer, which is what
# makes the megakernel the default path for 7B-class geometries
# (ROADMAP item 5a/5b). README "Whole-step decode megakernel" carries
# the math.


#: bytes of VMEM one grid step of the whole-step program may occupy
#: before the engine picks a sub-block tile count (see
#: :func:`whole_step_pick_tiles`); ~16 MB is a TPU core's VMEM
#: (pallas_guide.md), minus headroom.
WHOLE_STEP_VMEM_BUDGET = 12 * 1024 * 1024

#: canonical sub-block streaming roles (column-tiled projection
#: weights) in the stage order the inner grid dimension walks them:
#: stage 0 = QKV projections (→ attention at the last tile), stage 1 =
#: attention out-projection, stage 2 = MLP up/gate, stage 3 = MLP down.
_TILE_ROLE_ORDER = ("q", "k", "v", "o", "gate", "up", "down")
_TILE_ROLE_STAGE = {"q": 0, "k": 0, "v": 0, "o": 1,
                    "gate": 2, "up": 2, "down": 3}
_TILE_STAGES = 4


def whole_step_vmem_bytes(
    layer_arrays: Dict[str, jnp.ndarray],
    head_arrays: Dict[str, jnp.ndarray],
    cache: Dict[str, jnp.ndarray],
    x0: jnp.ndarray,
    mask: jnp.ndarray,
    num_heads: int,
    *,
    tiles: int = 1,
    tile_roles: Optional[Dict[str, Tuple[str, Optional[str]]]] = None,
) -> int:
    """Estimate the per-grid-step VMEM working set of
    :func:`whole_step_decode` (see the section comment): 2× the layer
    weight blocks and 2× the per-layer pool slices (stream double
    buffering + aliased outputs), the resident constants, the f32
    hidden-state intermediates and the (R, C, H, S_virt) f32 attention
    score/probability pair.

    ``tiles > 1`` prices the SUB-BLOCK streaming walk instead: each
    role-tiled projection weight (``tile_roles``, the family's
    ``whole_step_tile_roles`` map) is resident one 1/tiles output-column
    slice at a time, and the per-role VMEM accumulators (q/k/v/attn
    rows, the residual/norm carries, the MLP activation) are added —
    the footprint the engine's gate compares against the budget when it
    picks a tile count."""
    tiled_names = set()
    if tiles > 1:
        if tile_roles is None:
            raise ValueError(
                "whole_step_vmem_bytes: tiles > 1 needs tile_roles "
                "(the family's whole_step_tile_roles map)"
            )
        tiled_names = {w for (w, _b) in tile_roles.values()}
    per_layer = 0
    for name, a in layer_arrays.items():
        b = int(a.nbytes) // a.shape[0]
        if name in tiled_names:
            b //= tiles
        per_layer += b
    pool = sum(int(a.nbytes) // a.shape[0] for a in cache.values())
    const = sum(int(a.nbytes) for a in head_arrays.values())
    const += int(x0.nbytes) + int(mask.nbytes)
    R, C, S = mask.shape
    scores = 2 * 4 * R * C * num_heads * S        # scores + probs, f32
    hidden = 6 * 4 * R * C * x0.shape[-1]         # f32 block temporaries
    total = 2 * per_layer + 2 * pool + const + scores + hidden
    if tiles > 1:
        # tiled-walk accumulators (model dtype, serve/kernels
        # _whole_step_decode_tiled scratch): x/h/x2/h2 residual and
        # norm carries, q + attn rows, k/v rows, the MLP activation
        item = jnp.dtype(x0.dtype).itemsize
        D = int(x0.shape[-1])
        Hdk = int(layer_arrays[tile_roles["q"][0]].shape[-1])
        KVdk = int(layer_arrays[tile_roles["k"][0]].shape[-1])
        F = int(layer_arrays[tile_roles["up"][0]].shape[-1])
        total += item * R * C * (4 * D + 2 * Hdk + 2 * KVdk + F)
    return total


def whole_step_tile_candidates(
    layer_arrays: Dict[str, jnp.ndarray],
    tile_roles: Dict[str, Tuple[str, Optional[str]]],
) -> Tuple[int, ...]:
    """Legal sub-block tile counts for this weight layout, ascending:
    every count must divide EVERY tiled weight's output (last) dim so
    each role splits into equal column tiles — the divisors of the gcd
    of the tiled last dims."""
    g = 0
    for wname, _b in tile_roles.values():
        g = math.gcd(g, int(layer_arrays[wname].shape[-1]))
    return tuple(t for t in range(1, g + 1) if g % t == 0)


def whole_step_pick_tiles(
    layer_arrays: Dict[str, jnp.ndarray],
    head_arrays: Dict[str, jnp.ndarray],
    cache: Dict[str, jnp.ndarray],
    x0: jnp.ndarray,
    mask: jnp.ndarray,
    num_heads: int,
    *,
    tile_roles: Dict[str, Tuple[str, Optional[str]]],
    budget: int,
) -> Tuple[Optional[int], int]:
    """Pick the SMALLEST tile count whose priced working set fits the
    budget (1 = the untiled walk; larger counts trade grid steps for
    footprint). Returns ``(tiles, est_bytes)`` — ``(None, best_est)``
    when even the finest legal tiling cannot fit (the pool + resident
    constants + accumulators alone exceed the budget), which is the
    only remaining fallback-to-per-layer-path condition."""
    best_est = None
    for t in whole_step_tile_candidates(layer_arrays, tile_roles):
        est = whole_step_vmem_bytes(
            layer_arrays, head_arrays, cache, x0, mask, num_heads,
            tiles=t, tile_roles=tile_roles,
        )
        if best_est is None or est < best_est:
            best_est = est
        if est <= budget:
            return t, est
    return None, int(best_est if best_est is not None else 0)


def whole_step_decode(
    layer_arrays: Dict[str, jnp.ndarray],  # each (L, ...): streamed blocks
    head_arrays: Dict[str, jnp.ndarray],   # resident epilogue params
    x0: jnp.ndarray,            # (R, C, D) embedded step input
    cos: Optional[jnp.ndarray],  # (R, C, rot) f32, or None (no-RoPE family)
    sin: Optional[jnp.ndarray],
    cache: Dict[str, jnp.ndarray],  # k/v (L, P+1, ps, KV, dkp) [+ scales]
    page_table: jnp.ndarray,    # (R, NP) int32
    phys: jnp.ndarray,          # (R, C) int32 physical page per new line
    off: jnp.ndarray,           # (R, C) int32 in-page offset per new line
    mask: jnp.ndarray,          # (R, C, NP*ps) bool
    logits_idx: jnp.ndarray,    # (R,) int32
    *,
    block_fn: Callable,
    head_fn: Callable,
    tiles: int = 1,
    tile_plan: Optional[Dict[str, Any]] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray, Dict[str, jnp.ndarray]]:
    """ONE persistent Pallas program for the FULL step (see the
    section comment above): grid = (L,), layer weights and KV pool
    slices streamed per grid step (double-buffered by the Pallas
    pipeline), hidden state carried in VMEM scratch, epilogue fused
    into the last grid step. C = 1 is the decode step; C > 1 is the
    whole-step MIXED step (chunked prefill + decode in the same walk —
    the per-row ``logits_idx`` head select is already ragged).

    ``block_fn(p_l, x, cos, sin, mask, k, v, ks, vs, phys, off,
    page_table) -> (x, k, v, ks, vs)`` runs one layer on VALUES —
    the model family passes the same math its unfused XLA step runs.
    ``head_fn(head, x, logits_idx) -> (R, V) f32`` is the epilogue.

    ``tiles > 1`` selects the SUB-BLOCK streaming walk
    (:func:`_whole_step_decode_tiled`): the projection weights named by
    ``tile_plan["roles"]`` stream in output-column sub-tiles over an
    inner grid dimension, so the per-grid-step footprint is bounded by
    the tile size instead of the layer — the path the engine's VMEM
    gate picks for geometries the untiled walk cannot fit. The tiled
    walk runs the same ops on column slices (no contraction splits),
    so both paths are bitwise the unfused XLA step.

    Returns ``(logits (R, V) f32, greedy_tokens (R,) int32,
    new_cache)`` — the greedy tokens are the fused sampling epilogue's
    argmax head (``sample_tokens`` mode="greedy", in-kernel); non-greedy
    batches sample from the returned logits in the same jitted program.
    """
    if tiles > 1:
        if tile_plan is None:
            raise ValueError(
                "whole_step_decode: tiles > 1 needs a tile_plan (the "
                "family's _whole_tile_plan closures)"
            )
        return _whole_step_decode_tiled(
            layer_arrays, head_arrays, x0, cos, sin, cache, page_table,
            phys, off, mask, logits_idx, tiles=tiles,
            tile_plan=tile_plan, head_fn=head_fn,
        )
    L = cache["k"].shape[0]
    R, C, D = x0.shape
    quant = "k_scale" in cache
    has_rope = cos is not None
    layer_names = sorted(layer_arrays)
    head_names = sorted(head_arrays)

    def _const(spec_shape):
        nd = len(spec_shape)
        return pl.BlockSpec(
            spec_shape, lambda l, _nd=nd: (0,) * _nd
        )

    in_specs = []
    operands = []
    # streamed per-layer weight blocks: index map walks the layer dim —
    # the Pallas pipeline prefetches step l+1's blocks during step l
    for name in layer_names:
        a = layer_arrays[name]
        if a.shape[0] != L:
            raise ValueError(
                f"whole_step_decode: layer array {name!r} leading dim "
                f"{a.shape[0]} != num layers {L}"
            )
        nd = a.ndim - 1
        in_specs.append(pl.BlockSpec(
            (1,) + a.shape[1:], lambda l, _nd=nd: (l,) + (0,) * _nd
        ))
        operands.append(a)
    # streamed + aliased KV pool slices (and quant scale rows)
    pool_names = ["k", "v"] + (["k_scale", "v_scale"] if quant else [])
    pool_in_idx = {}
    for name in pool_names:
        a = cache[name]
        nd = a.ndim - 1
        pool_in_idx[name] = len(operands)
        in_specs.append(pl.BlockSpec(
            (1,) + a.shape[1:], lambda l, _nd=nd: (l,) + (0,) * _nd
        ))
        operands.append(a)
    # resident (constant index map) operands
    const_ops = [x0]
    const_specs = [_const((R, C, D))]
    if has_rope:
        const_ops += [cos, sin]
        const_specs += [_const(cos.shape), _const(sin.shape)]
    const_ops += [
        page_table.astype(jnp.int32), phys.astype(jnp.int32),
        off.astype(jnp.int32), logits_idx.astype(jnp.int32), mask,
    ]
    const_specs += [
        _const(page_table.shape), _const(phys.shape), _const(off.shape),
        _const(logits_idx.shape), _const(mask.shape),
    ]
    for name in head_names:
        const_ops.append(head_arrays[name])
        const_specs.append(_const(head_arrays[name].shape))
    in_specs += const_specs
    operands += const_ops

    # epilogue output shapes: probe the head on abstract values. The
    # full shape (not just V) so head twins with different ranks
    # compose — the decode head returns (R, V), the all-positions head
    # the spec verify fold dispatches returns (R, C, V); the argmax
    # epilogue below is rank-agnostic either way.
    head_abs = {n: head_arrays[n] for n in head_names}
    head_shape = jax.eval_shape(
        lambda h, x, li: head_fn(h, x, li),
        head_abs, jnp.zeros((R, C, D), x0.dtype),
        logits_idx.astype(jnp.int32),
    ).shape

    out_shapes = [
        jax.ShapeDtypeStruct(head_shape, jnp.float32),      # logits
        jax.ShapeDtypeStruct(head_shape[:-1], jnp.int32),   # greedy tokens
    ]
    out_specs = [_const(head_shape), _const(head_shape[:-1])]
    aliases = {}
    for j, name in enumerate(pool_names):
        a = cache[name]
        nd = a.ndim - 1
        out_shapes.append(jax.ShapeDtypeStruct(a.shape, a.dtype))
        out_specs.append(pl.BlockSpec(
            (1,) + a.shape[1:], lambda l, _nd=nd: (l,) + (0,) * _nd
        ))
        aliases[pool_in_idx[name]] = 2 + j

    def kernel(*refs):
        i = 0
        p_l = {}
        for name in layer_names:
            p_l[name] = refs[i][0]
            i += 1
        pool_refs = {}
        for name in pool_names:
            pool_refs[name] = refs[i]
            i += 1
        x0_ref = refs[i]; i += 1
        if has_rope:
            cos_ref = refs[i]; i += 1
            sin_ref = refs[i]; i += 1
        pt_ref = refs[i]; i += 1
        ph_ref = refs[i]; i += 1
        of_ref = refs[i]; i += 1
        li_ref = refs[i]; i += 1
        mask_ref = refs[i]; i += 1
        head_vals = {}
        for name in head_names:
            head_vals[name] = refs[i][...]
            i += 1
        logits_ref = refs[i]; i += 1
        tok_ref = refs[i]; i += 1
        pool_out = {}
        for name in pool_names:
            pool_out[name] = refs[i]
            i += 1
        x_scr = refs[i]

        l = pl.program_id(0)

        @pl.when(l == 0)
        def _():
            x_scr[:] = x0_ref[...]

        x = x_scr[:]
        cs = cos_ref[...] if has_rope else None
        sn = sin_ref[...] if has_rope else None
        kb = pool_refs["k"][0]
        vb = pool_refs["v"][0]
        ks = pool_refs["k_scale"][0] if quant else None
        vs = pool_refs["v_scale"][0] if quant else None
        x, kb, vb, ks, vs = block_fn(
            p_l, x, cs, sn, mask_ref[...], kb, vb, ks, vs,
            ph_ref[...], of_ref[...], pt_ref[...],
        )
        pool_out["k"][0] = kb
        pool_out["v"][0] = vb
        if quant:
            pool_out["k_scale"][0] = ks
            pool_out["v_scale"][0] = vs
        x_scr[:] = x

        @pl.when(l == L - 1)
        def _():
            logits = head_fn(head_vals, x, li_ref[...])
            logits_ref[...] = logits
            # fused sampling epilogue, greedy head: op-for-op
            # serve/sampling.sample_tokens mode="greedy" (logits are
            # already f32 — the astype there is a no-op)
            tok_ref[...] = jnp.argmax(logits, axis=-1).astype(jnp.int32)

    outs = pl.pallas_call(
        kernel,
        out_shape=out_shapes,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=0,
            grid=(L,),
            in_specs=in_specs,
            out_specs=out_specs,
            scratch_shapes=[pltpu.VMEM((R, C, D), x0.dtype)],
        ),
        input_output_aliases=aliases,
        interpret=_interpret(),
    )(*operands)
    logits, toks = outs[0], outs[1]
    new_cache = dict(cache)
    for j, name in enumerate(pool_names):
        new_cache[name] = outs[2 + j]
    return logits, toks, new_cache


def _whole_step_decode_tiled(
    layer_arrays: Dict[str, jnp.ndarray],
    head_arrays: Dict[str, jnp.ndarray],
    x0: jnp.ndarray,
    cos: Optional[jnp.ndarray],
    sin: Optional[jnp.ndarray],
    cache: Dict[str, jnp.ndarray],
    page_table: jnp.ndarray,
    phys: jnp.ndarray,
    off: jnp.ndarray,
    mask: jnp.ndarray,
    logits_idx: jnp.ndarray,
    *,
    tiles: int,
    tile_plan: Dict[str, Any],
    head_fn: Callable,
) -> Tuple[jnp.ndarray, jnp.ndarray, Dict[str, jnp.ndarray]]:
    """The SUB-BLOCK streaming whole-step walk: grid ``(L, 4·K)`` —
    the outer dimension walks the layers exactly like
    :func:`whole_step_decode`, the inner dimension walks
    ``_TILE_STAGES`` stages of K output-column weight tiles each:

      stage 0  QKV tiles — each grid step matmuls the normed hidden
               state against one (in, cols/K) column tile of wq/wk/wv
               and writes the column slice of the q/k/v accumulators;
               the LAST tile runs RoPE + KV commit + ragged paged
               attention (``attend_fn``) on the assembled rows
      stage 1  out-projection tiles — each tile produces D/K columns
               of the attention output and adds the residual slice
      stage 2  MLP up (+ gate, GLU families) tiles → activation slices
      stage 3  down-projection tiles close the layer into the residual

    Tile index maps freeze outside a role's stage (col 0 before, col
    K-1 after), so each operand's revolving VMEM buffer only refetches
    while its stage is live — Pallas double-buffers the K tiles across
    the inner grid steps the same way the layer walk double-buffers
    layers. Only the OUTPUT dim is split (the contraction dims stay
    whole), so every tile's matmul is bit-identical to the matching
    column slice of the full matmul and the tiled walk stays bitwise
    the unfused XLA step.

    ``tile_plan`` is the family's closure bundle:
    ``roles`` ({role: (weight_name, bias_name|None)} over
    ``_TILE_ROLE_ORDER``; "gate" only for GLU MLPs), ``mm_fn`` (the
    family's ``_mm``), ``pre_fn(p, x) -> h`` (attention norm),
    ``attend_fn(p, q, k, v, cos, sin, mask, kb, vb, ks, vs, phys, off,
    pt) -> (attn, kb, vb, ks, vs)`` (RoPE + commit + gather + attend on
    the assembled flat rows), ``mid_fn(p, x, h, x2) -> h2`` (the MLP
    norm — parallel-block aware) and ``act_fn(gate|None, up) -> act``.
    """
    L = cache["k"].shape[0]
    R, C, D = x0.shape
    K = int(tiles)
    quant = "k_scale" in cache
    has_rope = cos is not None
    roles = tile_plan["roles"]
    glu = "gate" in roles
    mm_fn = tile_plan["mm_fn"]
    pre_fn = tile_plan["pre_fn"]
    attend_fn = tile_plan["attend_fn"]
    mid_fn = tile_plan["mid_fn"]
    act_fn = tile_plan["act_fn"]

    role_order = tuple(r for r in _TILE_ROLE_ORDER if r in roles)
    for r in ("q", "k", "v", "o", "up", "down"):
        if r not in roles:
            raise ValueError(
                f"whole_step tile_plan is missing role {r!r}"
            )
    tiled_w = {r: roles[r][0] for r in role_order}
    tiled_names = set(tiled_w.values())
    tw = {}
    for r in role_order:
        a = layer_arrays[tiled_w[r]]
        if a.ndim != 3 or a.shape[0] != L:
            raise ValueError(
                f"whole_step tiled role {r!r}: weight "
                f"{tiled_w[r]!r} must be (L, in, out), got {a.shape}"
            )
        if a.shape[-1] % K:
            raise ValueError(
                f"whole_step tiles={K} does not divide {tiled_w[r]!r} "
                f"output dim {a.shape[-1]} (see "
                "whole_step_tile_candidates)"
            )
        tw[r] = a.shape[-1] // K
    Hdk = layer_arrays[tiled_w["q"]].shape[-1]
    KVdk = layer_arrays[tiled_w["k"]].shape[-1]
    F = layer_arrays[tiled_w["up"]].shape[-1]
    layer_names = sorted(n for n in layer_arrays if n not in tiled_names)
    head_names = sorted(head_arrays)
    pool_names = ["k", "v"] + (["k_scale", "v_scale"] if quant else [])
    I = _TILE_STAGES * K

    def _const(spec_shape):
        nd = len(spec_shape)
        return pl.BlockSpec(
            spec_shape, lambda l, i, _nd=nd: (0,) * _nd
        )

    def _per_layer(shape):
        nd = len(shape) - 1
        return pl.BlockSpec(
            (1,) + tuple(shape[1:]),
            lambda l, i, _nd=nd: (l,) + (0,) * _nd,
        )

    in_specs = []
    operands = []
    # streamed weight SUB-TILES: the index map walks the columns during
    # the role's stage and freezes at the stage boundaries (col 0
    # before, col K-1 after), so the revolving buffer neither refetches
    # out of stage nor thrashes — Pallas prefetches tile t+1 while tile
    # t computes, the same pipelined-grid contract as the layer walk
    for r in role_order:
        a = layer_arrays[tiled_w[r]]

        def _tile_idx(l, i, _s=_TILE_ROLE_STAGE[r], _K=K):
            st = i // _K
            t = i % _K
            col = jnp.where(
                st < _s, 0, jnp.where(st == _s, t, _K - 1)
            )
            return (l, 0, col)

        in_specs.append(pl.BlockSpec((1, a.shape[1], tw[r]), _tile_idx))
        operands.append(a)
    # untiled per-layer params (norm scales, biases): whole blocks,
    # refetched once per layer
    for name in layer_names:
        a = layer_arrays[name]
        if a.shape[0] != L:
            raise ValueError(
                f"whole_step_decode: layer array {name!r} leading dim "
                f"{a.shape[0]} != num layers {L}"
            )
        in_specs.append(_per_layer(a.shape))
        operands.append(a)
    # streamed + aliased KV pool slices (and quant scale rows)
    pool_in_idx = {}
    for name in pool_names:
        a = cache[name]
        pool_in_idx[name] = len(operands)
        in_specs.append(_per_layer(a.shape))
        operands.append(a)
    # resident (constant index map) operands
    const_ops = [x0]
    const_specs = [_const((R, C, D))]
    if has_rope:
        const_ops += [cos, sin]
        const_specs += [_const(cos.shape), _const(sin.shape)]
    const_ops += [
        page_table.astype(jnp.int32), phys.astype(jnp.int32),
        off.astype(jnp.int32), logits_idx.astype(jnp.int32), mask,
    ]
    const_specs += [
        _const(page_table.shape), _const(phys.shape), _const(off.shape),
        _const(logits_idx.shape), _const(mask.shape),
    ]
    for name in head_names:
        const_ops.append(head_arrays[name])
        const_specs.append(_const(head_arrays[name].shape))
    in_specs += const_specs
    operands += const_ops

    head_abs = {n: head_arrays[n] for n in head_names}
    V = jax.eval_shape(
        lambda h, x, li: head_fn(h, x, li),
        head_abs, jnp.zeros((R, C, D), x0.dtype),
        logits_idx.astype(jnp.int32),
    ).shape[-1]

    out_shapes = [
        jax.ShapeDtypeStruct((R, V), jnp.float32),       # logits
        jax.ShapeDtypeStruct((R,), jnp.int32),           # greedy tokens
    ]
    out_specs = [_const((R, V)), _const((R,))]
    aliases = {}
    for j, name in enumerate(pool_names):
        a = cache[name]
        out_shapes.append(jax.ShapeDtypeStruct(a.shape, a.dtype))
        out_specs.append(_per_layer(a.shape))
        aliases[pool_in_idx[name]] = 2 + j

    def kernel(*refs):
        i = 0
        wref = {}
        for r in role_order:
            wref[r] = refs[i]
            i += 1
        p_l = {}
        for name in layer_names:
            p_l[name] = refs[i][0]
            i += 1
        pool_refs = {}
        for name in pool_names:
            pool_refs[name] = refs[i]
            i += 1
        x0_ref = refs[i]; i += 1
        if has_rope:
            cos_ref = refs[i]; i += 1
            sin_ref = refs[i]; i += 1
        pt_ref = refs[i]; i += 1
        ph_ref = refs[i]; i += 1
        of_ref = refs[i]; i += 1
        li_ref = refs[i]; i += 1
        mask_ref = refs[i]; i += 1
        head_vals = {}
        for name in head_names:
            head_vals[name] = refs[i][...]
            i += 1
        logits_ref = refs[i]; i += 1
        tok_ref = refs[i]; i += 1
        pool_out = {}
        for name in pool_names:
            pool_out[name] = refs[i]
            i += 1
        x_scr = refs[i]; i += 1      # residual carry
        h_scr = refs[i]; i += 1      # attention-norm output
        x2_scr = refs[i]; i += 1     # post-attention residual
        h2_scr = refs[i]; i += 1     # MLP-norm output
        q_scr = refs[i]; i += 1
        k_scr = refs[i]; i += 1
        v_scr = refs[i]; i += 1
        attn_scr = refs[i]; i += 1
        act_scr = refs[i]

        l = pl.program_id(0)
        ii = pl.program_id(1)
        st = ii // K
        t = ii % K
        cs = cos_ref[...] if has_rope else None
        sn = sin_ref[...] if has_rope else None

        def _bias(r):
            bname = roles[r][1]
            if bname is None:
                return None
            return jax.lax.dynamic_slice_in_dim(
                p_l[bname], t * tw[r], tw[r], axis=0
            )

        def _proj(r, h):
            out_t = mm_fn(h, wref[r][0])
            b = _bias(r)
            return out_t if b is None else out_t + b

        @pl.when((l == 0) & (ii == 0))
        def _():
            x_scr[:] = x0_ref[...]

        # stage 0: attention norm once, then QKV column tiles; the
        # last tile runs RoPE + KV commit + attention on the full rows
        @pl.when((st == 0) & (t == 0))
        def _():
            h_scr[:] = pre_fn(p_l, x_scr[:])

        @pl.when(st == 0)
        def _():
            h = h_scr[:]
            q_scr[:, :, pl.ds(t * tw["q"], tw["q"])] = _proj("q", h)
            k_scr[:, :, pl.ds(t * tw["k"], tw["k"])] = _proj("k", h)
            v_scr[:, :, pl.ds(t * tw["v"], tw["v"])] = _proj("v", h)

        @pl.when((st == 0) & (t == K - 1))
        def _():
            kb = pool_refs["k"][0]
            vb = pool_refs["v"][0]
            ks = pool_refs["k_scale"][0] if quant else None
            vs = pool_refs["v_scale"][0] if quant else None
            attn, kb, vb, ks, vs = attend_fn(
                p_l, q_scr[:], k_scr[:], v_scr[:], cs, sn,
                mask_ref[...], kb, vb, ks, vs,
                ph_ref[...], of_ref[...], pt_ref[...],
            )
            attn_scr[:] = attn
            pool_out["k"][0] = kb
            pool_out["v"][0] = vb
            if quant:
                pool_out["k_scale"][0] = ks
                pool_out["v_scale"][0] = vs

        # stage 1: out-projection tiles accumulate the post-attention
        # residual slice by slice; the last tile runs the MLP norm
        @pl.when(st == 1)
        def _():
            ao = _proj("o", attn_scr[:])
            sl = pl.ds(t * tw["o"], tw["o"])
            x2_scr[:, :, sl] = x_scr[:, :, sl] + ao

        @pl.when((st == 1) & (t == K - 1))
        def _():
            h2_scr[:] = mid_fn(p_l, x_scr[:], h_scr[:], x2_scr[:])

        # stage 2: MLP up (+ gate) tiles → activation slices
        @pl.when(st == 2)
        def _():
            h2 = h2_scr[:]
            up_t = _proj("up", h2)
            g_t = _proj("gate", h2) if glu else None
            act_scr[:, :, pl.ds(t * tw["up"], tw["up"])] = (
                act_fn(g_t, up_t)
            )

        # stage 3: down-projection tiles close the layer's residual
        @pl.when(st == 3)
        def _():
            dn = _proj("down", act_scr[:])
            sl = pl.ds(t * tw["down"], tw["down"])
            x_scr[:, :, sl] = x2_scr[:, :, sl] + dn

        @pl.when((l == L - 1) & (ii == I - 1))
        def _():
            logits = head_fn(head_vals, x_scr[:], li_ref[...])
            logits_ref[...] = logits
            tok_ref[...] = jnp.argmax(logits, axis=-1).astype(jnp.int32)

    outs = pl.pallas_call(
        kernel,
        out_shape=out_shapes,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=0,
            grid=(L, I),
            in_specs=in_specs,
            out_specs=out_specs,
            scratch_shapes=[
                pltpu.VMEM((R, C, D), x0.dtype),
                pltpu.VMEM((R, C, D), x0.dtype),
                pltpu.VMEM((R, C, D), x0.dtype),
                pltpu.VMEM((R, C, D), x0.dtype),
                pltpu.VMEM((R, C, Hdk), x0.dtype),
                pltpu.VMEM((R, C, KVdk), x0.dtype),
                pltpu.VMEM((R, C, KVdk), x0.dtype),
                pltpu.VMEM((R, C, Hdk), x0.dtype),
                pltpu.VMEM((R, C, F), x0.dtype),
            ],
        ),
        input_output_aliases=aliases,
        interpret=_interpret(),
    )(*operands)
    logits, toks = outs[0], outs[1]
    new_cache = dict(cache)
    for j, name in enumerate(pool_names):
        new_cache[name] = outs[2 + j]
    return logits, toks, new_cache
