"""Python side of the embeddable C serving ABI.

The reference exposes ~300 ``flexflow_*`` C functions
(reference ``src/c/flexflow_c.cc:1-2680``) so non-Python hosts can
drive it through opaque handles. The TPU framework's C surface is
deliberately narrow — serving is the embed case that matters — and maps
1:1 onto :class:`RequestManager`'s step-wise API:

    ff_serve_init(config_json)        -> init
    ff_serve_register_request(...)    -> register_request
    ff_serve_step()                   -> step
    ff_serve_num_active()             -> num_active
    ff_serve_fetch(rid, buf, cap)     -> fetch
    ff_serve_shutdown()               -> shutdown

State is one module-global engine + manager, mirroring the reference's
singleton (``request_manager.cc`` ``get_request_manager``). The C shim
(:mod:`flexflow_tpu.native` ``serve_c_api.cpp``) embeds CPython and
forwards into this module, so a plain C host only links
``libffserve.so`` + ``libpython``.

Config JSON accepted by :func:`init`::

    {
      "family": "llama",            # model family in flexflow_tpu.models
      "model": {...},               # family Config kwargs (e.g. hidden_size)
      "serving": {...},             # ServingConfig kwargs
      "max_new_tokens": 32,         # default per-request budget
      "seed": 0,                    # random-weight init seed
      "platform": "cpu"             # optional: force a JAX platform
    }
"""
from __future__ import annotations

import dataclasses
import json
from typing import List, Optional

_STATE: dict = {}


def init(cfg_json: str) -> int:
    """Build the engine + request manager. Returns 0 on success."""
    cfg = json.loads(cfg_json) if cfg_json else {}
    platform = cfg.get("platform")
    import jax

    if platform:
        # the config API, not the env var — the container sitecustomize
        # overrides JAX_PLATFORMS programmatically
        jax.config.update("jax_platforms", platform)
    import importlib

    import jax.numpy as jnp

    def _dtypes(d, *keys):
        # JSON carries dtypes as strings ("bfloat16", "float32")
        return {
            k: getattr(jnp, v) if k in keys and isinstance(v, str) else v
            for k, v in d.items()
        }

    family = cfg.get("family", "llama")
    mod = importlib.import_module(f"flexflow_tpu.models.{family}")
    model_kw = _dtypes(cfg.get("model", {}), "dtype")
    if hasattr(mod, "LLaMAConfig"):
        mcfg = mod.LLaMAConfig(**model_kw)
    else:
        # generic-decoder families (opt/falcon/mpt/starcoder/qwen2)
        # expose a config() factory over DecoderConfig
        mcfg = mod.config(**model_kw)
    from .engine import InferenceEngine, ServingConfig
    from .request_manager import RequestManager

    sc = ServingConfig(**_dtypes(cfg.get("serving", {}), "cache_dtype"))
    params = mod.init_params(jax.random.PRNGKey(cfg.get("seed", 0)), mcfg)
    if sc.replicas > 1 or sc.prefill_replicas:
        # Cluster serving: the C host drives the ClusterManager through
        # the SAME step loop — register/step/num_active/fetch all read
        # the RequestStatus-shaped cluster requests, so a request SHED
        # by SLO admission is terminal (ERROR) exactly like the PR-2
        # unservable-request path: num_active drops, fetch returns
        # None, and the host's loop never spins on it.
        from .cluster import ClusterManager

        rm = ClusterManager.build(
            mod, mcfg, params, sc, seed=cfg.get("seed", 0)
        )
    else:
        rm = RequestManager(InferenceEngine(mod, mcfg, params, sc))
    _STATE["rm"] = rm
    _STATE["max_new_tokens"] = int(cfg.get("max_new_tokens", 32))
    return 0


def register_request(tokens: List[int], max_new: int = 0) -> int:
    """Queue a prompt; returns the request id (guid)."""
    from .batch_config import GenerationConfig

    rm = _STATE["rm"]
    gen = GenerationConfig(
        max_new_tokens=max_new or _STATE["max_new_tokens"]
    )
    return rm.register_request([int(t) for t in tokens], gen)


def step() -> int:
    """One scheduling step. Returns 1 while work remains, else 0."""
    return 1 if _STATE["rm"].step() else 0


def num_active() -> int:
    """Requests not yet terminal (pending + in slots). ERROR requests
    count as done — a request that can never be served must not keep
    the C host's step loop spinning."""
    from .request_manager import TERMINAL_STATUSES

    rm = _STATE["rm"]
    return sum(
        1 for r in rm.requests.values()
        if r.status not in TERMINAL_STATUSES
    )


def fetch(rid: int) -> Optional[List[int]]:
    """Output tokens of a COMPLETED request, else None."""
    from .request_manager import RequestStatus

    rm = _STATE["rm"]
    req = rm.requests.get(rid)
    if req is None or req.status is not RequestStatus.COMPLETED:
        return None
    return list(req.output_tokens)


def shutdown() -> int:
    _STATE.clear()
    return 0
