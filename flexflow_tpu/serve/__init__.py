"""Serving stack — continuous batching, incremental decoding, SpecInfer.

TPU-native counterpart of the reference serving layer (reference
``src/runtime/request_manager.cc``, ``inference_manager.cc``,
``batch_config.cc``, SURVEY.md §2.1 "Serving"). The Legion future pipeline
becomes an async host loop over donated-buffer jitted step functions; the
three attention operators become one compiled program per static mode.
"""
from .batch_config import (
    BatchConfig,
    GenerationConfig,
    GenerationResult,
    StreamEvent,
)
from .cluster import (
    ClusterManager,
    Fault,
    FaultPlan,
    HealthConfig,
    HealthState,
    Replica,
    Router,
)
from .engine import InferenceEngine, ServingConfig
from .llm import LLM, SSM, detect_family
from .paging import PageAllocator
from .prefix_cache import PrefixCache
from .request_manager import Request, RequestManager, RequestStatus
from .sampling import sample_tokens
from .specinfer import SpecConfig, SpecInferManager, TokenTree

__all__ = [
    "BatchConfig",
    "ClusterManager",
    "Fault",
    "FaultPlan",
    "HealthConfig",
    "HealthState",
    "Replica",
    "Router",
    "GenerationConfig",
    "GenerationResult",
    "InferenceEngine",
    "LLM",
    "PageAllocator",
    "PrefixCache",
    "SSM",
    "detect_family",
    "ServingConfig",
    "StreamEvent",
    "Request",
    "RequestManager",
    "RequestStatus",
    "sample_tokens",
    "SpecConfig",
    "SpecInferManager",
    "TokenTree",
]
