"""Draft distillation + accept-rate-per-draft-FLOP pricing.

The SpecInfer concept (PAPER.md) assumes the SSM draft is *distilled*
from the target — layer-skip and early-exit drafts are cheap stand-ins.
This module closes that loop on served traffic:

1. **Harvest** (prompt, target-logits) pairs from the engine's verify
   rounds — `SpecInferManager.logit_sink` hands every verify dispatch's
   full teacher logits along the accepted path to an attached
   :class:`HarvestBuffer` — or replay a token trace offline through the
   teacher's training ``forward``.
2. **Train** a narrow/shallow decoder on the harvested pairs with a
   KL-to-target loss, reusing the existing training stack
   (``models/*.forward`` + ``losses.categorical_crossentropy`` +
   ``optimizers.AdamOptimizer``) in ONE jitted fixed-shape step.
3. **Emit** a checkpoint (``checkpoint.save_params`` + a geometry json)
   loadable as an SSM spec for ``LLM.compile(ssms=[...])``.
4. **Price** drafts by measured utility: drafted accept rate from a
   live verify ladder divided by the draft's per-token GFLOPs from the
   cost model's 2·params pricing — so distilled vs layer-skip vs
   early-exit is a *measurement*, not a vibe. The measured acceptance
   also feeds ``autotune.cost_model.TrafficProfile.measured_accept_rate``
   so the serving cost model prices speculation with it instead of its
   prior.

Everything here is the OFFLINE/side-channel path: the harvest sink is
``None`` in production serving (``specinfer.py`` fetches verify logits
only while a sink is attached), and training never touches the serving
step-key space.
"""
from __future__ import annotations

import dataclasses
import json
import os
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import checkpoint
from ..losses import categorical_crossentropy
from ..optimizers import AdamOptimizer
from .autotune.cost_model import ModelGeometry
from .batch_config import GenerationConfig


def _default_family():
    from ..models import llama

    return llama


# ----------------------------------------------------------------------
# harvest


class HarvestBuffer:
    """Accumulates (context, teacher-logits) training pairs.

    ``add(tokens, logits, start)`` stores one pair per logits row:
    row ``k`` is the teacher's next-token distribution after seeing
    ``tokens[:start + k + 1]``. The default ``start`` lines the rows up
    against the END of ``tokens`` — exactly the shape of the verify
    round's accepted-path logits, so ``manager.logit_sink = buf.add``
    harvests live traffic with no adapter.
    """

    def __init__(self, max_examples: int = 65536):
        self.max_examples = max_examples
        # list of (context token list, (V,) float32 teacher logits)
        self.examples: List[Tuple[List[int], np.ndarray]] = []

    def __len__(self) -> int:
        return len(self.examples)

    @property
    def full(self) -> bool:
        return len(self.examples) >= self.max_examples

    def add(
        self,
        tokens: Sequence[int],
        logits: Any,
        start: Optional[int] = None,
    ) -> None:
        rows = np.asarray(logits, np.float32)
        if rows.ndim == 1:
            rows = rows[None, :]
        if start is None:
            start = len(tokens) - rows.shape[0]
        for k in range(rows.shape[0]):
            ctx = [int(t) for t in tokens[: start + k + 1]]
            if not ctx or self.full:
                return
            self.examples.append((ctx, rows[k]))

    def batches(
        self, seq_len: int, batch_size: int
    ) -> List[Tuple[np.ndarray, np.ndarray, np.ndarray]]:
        """Fixed-shape training batches: right-truncate each context to
        its last ``seq_len`` tokens, right-pad, and carry the index of
        the last real token so the trainer selects the one position the
        teacher distribution targets. The ragged tail (fewer than
        ``batch_size`` leftovers) is dropped — every batch compiles to
        the same shapes, so the jitted step traces exactly once."""
        out = []
        n = (len(self.examples) // batch_size) * batch_size
        for i in range(0, n, batch_size):
            chunk = self.examples[i : i + batch_size]
            toks = np.zeros((batch_size, seq_len), np.int32)
            idx = np.zeros((batch_size,), np.int32)
            tgt = np.stack([row for _, row in chunk]).astype(np.float32)
            for b, (ctx, _) in enumerate(chunk):
                window = ctx[-seq_len:]
                toks[b, : len(window)] = window
                idx[b] = len(window) - 1
            out.append((toks, idx, tgt))
        return out


def harvest_online(
    manager: Any,
    prompts: Sequence[Any],
    *,
    buf: Optional[HarvestBuffer] = None,
    gen: Optional[GenerationConfig] = None,
    max_new_tokens: Optional[int] = 32,
) -> HarvestBuffer:
    """Serve ``prompts`` through a :class:`SpecInferManager` with the
    harvest sink attached: every verify round's full teacher logits
    along the accepted path land in the buffer. The sink is detached
    on exit, so the manager goes back to never fetching verify logits."""
    buf = buf if buf is not None else HarvestBuffer()
    prev = manager.logit_sink
    manager.logit_sink = buf.add
    try:
        manager.generate(list(prompts), gen, max_new_tokens)
    finally:
        manager.logit_sink = prev
    return buf


def harvest_offline(
    family: Any,
    cfg: Any,
    params: Dict[str, Any],
    traces: Sequence[Any],
    *,
    buf: Optional[HarvestBuffer] = None,
    max_len: Optional[int] = None,
) -> HarvestBuffer:
    """Replay token traces through the teacher's training ``forward``
    and harvest every position's next-token logits. A trace is a token
    sequence or a ``GenerationResult`` (input + output tokens). Each
    distinct trace length traces the jitted forward once — an offline
    tool's compile cost, never the serving step-key space."""
    buf = buf if buf is not None else HarvestBuffer()
    fwd = jax.jit(lambda p, t: family.forward(p, t, cfg))
    for trace in traces:
        if hasattr(trace, "output_tokens"):
            toks = list(trace.input_tokens) + list(trace.output_tokens)
        else:
            toks = [int(t) for t in trace]
        if max_len is not None:
            toks = toks[:max_len]
        if len(toks) < 2:
            continue
        lg = fwd(
            params,
            jnp.asarray(np.asarray(toks, np.int32)[None, :], dtype=jnp.int32),
        )
        # ffcheck: disable=FF107 -- offline trace replay (distillation harvest): blocking teacher-logit fetch is the tool's whole job; never runs on a serving path
        rows = np.asarray(jax.device_get(lg))[0]
        buf.add(toks, rows, start=0)
        if buf.full:
            break
    return buf


# ----------------------------------------------------------------------
# training


@dataclasses.dataclass
class DistillConfig:
    """Student geometry + training knobs. The student inherits every
    teacher config field not named here (vocab, rope, norm eps, dtype),
    so its checkpoint drops straight into ``LLM.compile(ssms=[...])``."""

    hidden_size: int = 64
    num_layers: int = 2
    num_heads: int = 4
    num_kv_heads: Optional[int] = None       # None = num_heads
    intermediate_size: Optional[int] = None  # None = 4 * hidden_size
    seq_len: int = 64
    batch_size: int = 8
    steps: int = 200
    lr: float = 1e-3
    #: Distillation temperature for the teacher targets: the loss
    #: matches ``softmax(teacher_logits / temperature)``. 1.0 keeps the
    #: teacher's own distribution; below 1.0 sharpens it toward the
    #: argmax — the right regime when the verify ladder is GREEDY
    #: (acceptance is argmax agreement, so the student should spend its
    #: capacity on the teacher's top choice, not the full tail).
    temperature: float = 1.0
    seed: int = 0

    def __post_init__(self):
        if self.temperature <= 0.0:
            raise ValueError(
                f"temperature must be > 0 (got {self.temperature})"
            )
        if self.hidden_size % self.num_heads:
            raise ValueError(
                f"hidden_size ({self.hidden_size}) must be divisible by "
                f"num_heads ({self.num_heads})"
            )
        kv = self.num_kv_heads or self.num_heads
        if self.num_heads % kv:
            raise ValueError(
                f"num_heads ({self.num_heads}) must be divisible by "
                f"num_kv_heads ({kv})"
            )


def student_config(teacher_cfg: Any, dcfg: DistillConfig) -> Any:
    """Narrow/shallow student config cut from the teacher's."""
    return dataclasses.replace(
        teacher_cfg,
        hidden_size=dcfg.hidden_size,
        num_hidden_layers=dcfg.num_layers,
        num_attention_heads=dcfg.num_heads,
        num_key_value_heads=dcfg.num_kv_heads or dcfg.num_heads,
        intermediate_size=dcfg.intermediate_size or 4 * dcfg.hidden_size,
    )


def train_distilled_draft(
    buf: HarvestBuffer,
    teacher_cfg: Any,
    dcfg: DistillConfig,
    *,
    family: Any = None,
) -> Tuple[Any, Dict[str, Any], List[float]]:
    """KL-distill a student draft from harvested teacher logits.

    The loss is cross-entropy of the student's logits at each example's
    last real position against ``softmax(teacher_logits / temperature)``
    — KL to the (tempered) teacher up to the teacher-entropy constant,
    so its argmin is the same. One jitted step over fixed shapes; with the pinned threefry
    PRNG the whole run is bitwise deterministic per backend.

    Returns ``(student_cfg, params, loss_history)``.
    """
    family = family or _default_family()
    scfg = student_config(teacher_cfg, dcfg)
    params = family.init_params(jax.random.PRNGKey(dcfg.seed), scfg)
    opt = AdamOptimizer(lr=dcfg.lr)
    opt_state = opt.init(params)

    def _step(params, opt_state, toks, idx, tgt):
        def loss_fn(p):
            logits = family.forward(p, toks, scfg)       # (B, S, V)
            sel = jnp.take_along_axis(
                logits, idx[:, None, None], axis=1
            )[:, 0]                                      # (B, V)
            probs = jax.nn.softmax(
                tgt.astype(jnp.float32) / dcfg.temperature, axis=-1
            )
            return categorical_crossentropy(sel, probs, from_logits=True)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        params, opt_state = opt.update(grads, opt_state, params)
        return params, opt_state, loss

    step = jax.jit(_step, donate_argnums=(0, 1))
    batches = buf.batches(dcfg.seq_len, dcfg.batch_size)
    if not batches:
        raise ValueError(
            f"HarvestBuffer holds {len(buf)} examples — fewer than one "
            f"batch of {dcfg.batch_size}; harvest more traffic first"
        )
    history: List[float] = []
    i = 0
    while i < dcfg.steps:
        for toks, idx, tgt in batches:
            if i >= dcfg.steps:
                break
            params, opt_state, loss = step(params, opt_state, toks, idx, tgt)
            # ffcheck: disable=FF107 -- training loop, not a serving path: per-step loss fetch feeds the history the eval harness reports
            history.append(float(jax.device_get(loss)))
            i += 1
    return scfg, params, history


# ----------------------------------------------------------------------
# checkpoint emit / load

_GEOMETRY_FIELDS = (
    "hidden_size",
    "num_hidden_layers",
    "num_attention_heads",
    "num_key_value_heads",
    "intermediate_size",
)


def save_distilled_draft(
    directory: str, cfg: Any, params: Dict[str, Any]
) -> None:
    """Emit the student as an SSM spec: orbax params + a geometry json
    (`draft_config.json`) naming the fields that differ from whatever
    teacher it is loaded next to."""
    checkpoint.save_params(directory, params)
    geom = {k: int(getattr(cfg, k)) for k in _GEOMETRY_FIELDS}
    with open(os.path.join(directory, "draft_config.json"), "w") as f:
        json.dump(geom, f, indent=2, sort_keys=True)


def load_distilled_draft(
    directory: str, teacher_cfg: Any, *, family: Any = None
) -> Tuple[Any, Dict[str, Any]]:
    """Rebuild (student_cfg, params) from :func:`save_distilled_draft`
    output against a teacher config (vocab/rope/dtype inherit)."""
    family = family or _default_family()
    with open(os.path.join(directory, "draft_config.json")) as f:
        geom = json.load(f)
    cfg = dataclasses.replace(
        teacher_cfg, **{k: int(geom[k]) for k in _GEOMETRY_FIELDS}
    )
    template = family.init_params(jax.random.PRNGKey(0), cfg)
    return cfg, checkpoint.load_params(directory, template)


# ----------------------------------------------------------------------
# pricing: accept-rate-per-draft-FLOP


@dataclasses.dataclass
class DraftEval:
    """One draft's measured utility on a verify ladder."""

    name: str
    accept_rate: float              # drafted accept rate, measured
    draft_gflops_per_token: float   # cost-model 2·params pricing
    accept_rate_per_gflop: float    # the figure drafts are ranked by
    output_tokens: int = 0


def draft_gflops_per_token(cfg: Any) -> float:
    """Dense per-token draft GFLOPs from the cost model's 2·params
    forward pricing — the denominator of accept-rate-per-draft-FLOP."""
    return 2.0 * ModelGeometry.from_model_config(cfg).param_count() / 1e9


def measure_draft_utility(
    manager: Any,
    prompts: Sequence[Any],
    *,
    gen: Optional[GenerationConfig] = None,
    max_new_tokens: Optional[int] = 32,
    name: str = "draft",
) -> DraftEval:
    """Run a verify ladder over ``prompts`` on a compiled
    :class:`SpecInferManager` and price the draft it speculates with:
    measured drafted-accept rate ÷ the draft stack's per-token GFLOPs
    (``manager.draft_flops_per_token``). The returned ``accept_rate``
    is what ``TrafficProfile.measured_accept_rate`` wants."""
    results = manager.generate(list(prompts), gen, max_new_tokens)
    accept = float(manager.stats.spec_accept_rate)
    gfl = float(getattr(manager, "draft_flops_per_token", 0.0)) / 1e9
    return DraftEval(
        name=name,
        accept_rate=accept,
        draft_gflops_per_token=gfl,
        accept_rate_per_gflop=accept / gfl if gfl > 0 else 0.0,
        output_tokens=sum(len(r.output_tokens) for r in results),
    )


def rank_drafts(evals: Sequence[DraftEval]) -> List[DraftEval]:
    """Best draft first, by measured accept-rate-per-draft-GFLOP."""
    return sorted(
        evals, key=lambda e: e.accept_rate_per_gflop, reverse=True
    )
