"""High-level ``LLM``/``SSM`` serving API.

TPU-native counterpart of the reference's Python serving entry points
(reference ``python/flexflow/serve/serve.py:71-502``: ``LLM``/``SSM``
classes that download + convert HF weights, compile per inference mode,
and generate). Differences by design: weights load from a *local* HF
checkpoint directory straight into sharded device arrays (no binary
file cache), and "compile" builds jitted step functions over the mesh
instead of a Legion task graph.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Sequence, Union

import jax
import jax.numpy as jnp

from ..core.mesh import MachineSpec
from .. import models as zoo
from ..models import hf_utils
from .batch_config import GenerationConfig, GenerationResult
from .engine import InferenceEngine, ServingConfig
from .request_manager import RequestManager
from .specinfer import SpecConfig, SpecInferManager


def detect_family(hf_config: Dict[str, Any]):
    """Map an HF config to a model-family module (reference
    ``serve.py:__get_ff_model_type`` dispatch on architectures)."""
    mt = hf_config.get("model_type", "")
    if mt in zoo.FAMILIES:
        return zoo.FAMILIES[mt]
    for arch in hf_config.get("architectures", []):
        # longest key first: "qwen2" must not shadow "qwen2_moe" when
        # only the architectures list is present
        for key in sorted(zoo.FAMILIES, key=len, reverse=True):
            if key.replace("_", "") in arch.lower().replace("_", ""):
                return zoo.FAMILIES[key]
    raise ValueError(f"unsupported model family: {mt!r} / "
                     f"{hf_config.get('architectures')}")


class LLM:
    """A servable causal LM bound to a mesh.

    Build either from a local HF checkpoint directory
    (``LLM.from_pretrained``) or from in-memory (family, cfg, params)
    — the latter is what tests and SSM distillation use.
    """

    def __init__(
        self,
        family: Any,
        cfg: Any,
        params: Optional[Dict[str, Any]] = None,
        *,
        tokenizer: Any = None,
        machine: Optional[MachineSpec] = None,
        mesh=None,
        seed: int = 0,
    ):
        self.family = family
        self.cfg = cfg
        self.tokenizer = tokenizer
        if mesh is None:
            machine = machine or MachineSpec()
            mesh = machine.make_mesh(jax.devices()[: machine.num_devices])
        self.mesh = mesh
        if params is None:
            params = family.init_params(jax.random.PRNGKey(seed), cfg)
        self.params = params
        self.engine: Optional[InferenceEngine] = None
        self.rm: Optional[RequestManager] = None

    # ------------------------------------------------------------------

    @classmethod
    def from_pretrained(
        cls,
        model_dir: str,
        *,
        dtype: Any = jnp.bfloat16,
        tokenizer: Any = "auto",
        machine: Optional[MachineSpec] = None,
        mesh=None,
        **cfg_overrides,
    ) -> "LLM":
        """Load config + weights from a local HF checkpoint directory
        (this environment has no network egress; the reference's HF-hub
        download step happens out of band)."""
        hf_cfg = hf_utils.load_hf_config(model_dir)
        family = detect_family(hf_cfg)
        cfg = family.from_hf(hf_cfg, dtype=dtype, **cfg_overrides)
        sd = hf_utils.load_state_dict(model_dir)
        params = family.convert_hf_state_dict(sd, cfg)
        if tokenizer == "auto":
            try:
                from transformers import AutoTokenizer

                tokenizer = AutoTokenizer.from_pretrained(
                    model_dir, local_files_only=True
                )
            except Exception:
                tokenizer = None
        return cls(
            family, cfg, params, tokenizer=tokenizer, machine=machine, mesh=mesh
        )

    # ------------------------------------------------------------------

    def compile(
        self,
        serving: Optional[ServingConfig] = None,
        *,
        ssms: Sequence["LLM"] = (),
        spec: Optional[SpecConfig] = None,
        eos_token_id: Optional[int] = None,
        seed: int = 0,
        quantization: Optional[str] = None,  # "int8" | "int4"
        offload: bool = False,
        output_file: Optional[str] = None,
    ) -> None:
        """Build the inference engine(s) and request manager (reference
        ``LLM.compile`` → InferenceManager.compile_model_and_allocate_buffer).
        With ``ssms`` the request manager runs the SpecInfer loop.

        ``quantization`` converts the layer matmul weights to int8/int4
        {"q","scale"} form at placement time (reference
        ``file_loader.cc:651,710`` quantized loading + decompress
        kernels); ``offload`` places params in pinned host memory on TPU
        so XLA streams them per step (the reference's ``--offload``
        zero-copy double buffering, config.h:155-157).
        """
        serving = serving or ServingConfig()
        # Cluster-field validation fails HERE, before any params are
        # placed or engines built. SpecInfer composes with replicated
        # clusters (each replica gets its own SSM mirror engines,
        # serve/cluster/replica.py); only the disaggregated
        # prefill/decode pools still reject the combination.
        serving.validate_cluster(specinfer=bool(ssms))
        from ..core.mesh import PIPE_AXIS
        from ..config import get_config
        from ..core.dtypes import DataType

        # ff.init(use_4bit_quantization=..., offload=...) flags apply
        # here (the reference's FFConfig → FileDataLoader path).
        ffc = get_config()
        if quantization is None and ffc.quantization_type is not None:
            quantization = {
                DataType.INT8: "int8", DataType.INT4: "int4"
            }[ffc.quantization_type]
        offload = offload or ffc.cpu_offload
        pipelined = self.mesh.shape.get(PIPE_AXIS, 1) > 1
        self.params = self._place_params(
            self.family, self.cfg, self.params, pipelined, quantization, offload
        )
        if (
            serving.replicas > 1 or serving.prefill_replicas
            or serving.journal_dir
        ):
            # Cluster serving (serve/cluster/): N engine replicas behind
            # the prefix-aware router. With ``ssms`` every replica runs
            # a SpecInferManager over its OWN draft mirror engines —
            # draft params are placed once here and shared by reference
            # across replicas, exactly like the target's. A journal_dir
            # forces the cluster manager even at replicas=1 — the
            # durable request journal (crash recovery, scale_out from
            # one replica) lives at the cluster control plane.
            from .cluster import ClusterManager

            ssm_triples = []
            for ssm in ssms:
                ssm.params = self._place_params(
                    ssm.family, ssm.cfg, ssm.params, pipelined,
                    quantization, offload,
                )
                ssm_triples.append((ssm.family, ssm.cfg, ssm.params))
            self.rm = ClusterManager.build(
                self.family, self.cfg, self.params, serving,
                tokenizer=self.tokenizer, eos_token_id=eos_token_id,
                seed=seed, ssms=ssm_triples, spec=spec,
            )
            self.engine = self.rm.replicas[0].engine
            return
        self.engine = InferenceEngine(
            self.family, self.cfg, self.params, serving, self.mesh
        )
        if ssms or getattr(spec, "draft", "ssm") == "early_exit":
            # SpecInfer serving: external SSM drafts, or — with
            # SpecConfig(draft="early_exit") and no ssms — the target
            # self-speculating off its own truncated layer stack.
            for ssm in ssms:
                ssm.params = self._place_params(
                    ssm.family, ssm.cfg, ssm.params, pipelined, quantization,
                    offload,
                )
                ssm.engine = InferenceEngine(
                    ssm.family, ssm.cfg, ssm.params, serving, self.mesh
                )
            self.rm = SpecInferManager(
                self.engine, [s.engine for s in ssms], spec,
                tokenizer=self.tokenizer, eos_token_id=eos_token_id, seed=seed,
                output_file=output_file,
            )
        else:
            self.rm = RequestManager(
                self.engine,
                tokenizer=self.tokenizer,
                eos_token_id=eos_token_id,
                seed=seed,
                output_file=output_file,
            )

    def _place_params(
        self, family, cfg, params, pipelined: bool,
        quantization: Optional[str], offload: bool,
    ):
        """Quantize (optionally), shard, and place params — on device,
        or in pinned host memory when offloading on TPU."""
        if pipelined:
            from ..core.mesh import PIPE_AXIS

            pp = self.mesh.shape[PIPE_AXIS]
            if cfg.num_hidden_layers % pp:
                raise ValueError(
                    f"pipeline serving needs num_hidden_layers "
                    f"({cfg.num_hidden_layers}) divisible by the pipe "
                    f"degree ({pp})"
                )
        pspecs = family.param_pspecs(cfg, pipeline=pipelined)
        if quantization is not None:
            from .. import quantization as quant

            bits = {"int8": 8, "int4": 4}[quantization]
            params = quant.quantize_params(params, bits)
            pspecs = quant.quantize_pspecs(pspecs, params)
        memory_kind = None
        if offload:
            if jax.devices()[0].platform == "tpu":
                memory_kind = "pinned_host"
            else:
                import warnings

                warnings.warn(
                    "offload=True has no effect off-TPU (params already "
                    "live in host memory on this backend)", stacklevel=3,
                )
        return hf_utils.device_put_sharded(
            params, self.mesh, pspecs, memory_kind=memory_kind
        )

    def generate(
        self,
        prompts: Union[str, Sequence[Union[str, Sequence[int]]]],
        gen: Optional[GenerationConfig] = None,
        max_new_tokens: Optional[int] = None,
    ) -> List[GenerationResult]:
        if self.rm is None:
            self.compile()
        if gen is not None and gen.num_beams > 1:
            from .beam import generate_with_beams

            if gen.do_sample:
                # Beam scoring here is deterministic log-prob ranking —
                # fail loudly rather than silently ignore sampling knobs
                # (same contract as SpecInferManager.register_request).
                raise ValueError(
                    "num_beams > 1 is greedy-scored; do_sample cannot be "
                    "honored — use num_beams=1 for sampling"
                )
            if max_new_tokens is not None:
                gen = dataclasses.replace(gen, max_new_tokens=max_new_tokens)
            if isinstance(prompts, str):
                prompts = [prompts]
            return generate_with_beams(
                self.engine, prompts, gen,
                eos_token_id=self.rm.eos_token_id, tokenizer=self.tokenizer,
            )
        return self.rm.generate(prompts, gen, max_new_tokens)


class SSM(LLM):
    """Small speculative model (reference ``serve.py`` SSM): same object
    as LLM, compiled onto the LLM's mesh by ``LLM.compile(ssms=[...])``."""
