"""Paged KV cache — free-list page allocator + per-slot page tables.

TPU-native port of the Ragged Paged Attention memory layout
(PAPERS.md, arxiv 2604.15464; vLLM's PagedAttention ancestry): instead
of a dense per-slot cache of ``slots × (max_len+1)`` lines, K/V live in
a pool of fixed-size token **pages** and each request slot owns a
**page table** mapping logical pages (line // page_size) to physical
pages. HBM cost is then proportional to pages actually allocated — live
tokens rounded up to the page size — not to the worst-case sequence
length, which is what lets serving run the reference's 64 request slots
on one chip (VERDICT.md round 5, missing #3).

The allocator is host-side state owned by the :class:`InferenceEngine`
(one per engine — a SpecInfer LLM/SSM pair allocates independently
because their pools differ in layer count and budget). The
RequestManager drives it on admit/evict/completion; the device only
ever sees the resulting ``(slots, pages_per_slot)`` int32 table shipped
with each step.

Physical page ``num_pages`` (one past the pool) is the shared
**scratch page**: unallocated table entries point at it, so padding
tokens' K/V writes and gathers through unallocated entries land on a
real buffer that no mask ever exposes (the paged analog of the dense
layout's per-slot scratch row, models/llama.py init_kv_cache).
"""
from __future__ import annotations

from typing import List

import numpy as np


class PageAllocator:
    """Free-list allocator over a physical KV page pool.

    Invariants (asserted, tested in tests/test_paged_kv.py):
      * a physical page is owned by at most one slot at a time;
      * ``ensure`` either covers the requested lines fully or changes
        nothing (no partial allocation to roll back);
      * ``release`` returns exactly the slot's owned pages — double
        release is a no-op, never a double-free.
    """

    def __init__(self, num_pages: int, pages_per_slot: int, num_slots: int,
                 page_size: int):
        if num_pages < pages_per_slot:
            raise ValueError(
                f"page pool ({num_pages} pages) smaller than one request's "
                f"worst case ({pages_per_slot} pages) — no request could "
                "ever run to max_sequence_length"
            )
        self.num_pages = int(num_pages)
        self.page_size = int(page_size)
        self.pages_per_slot = int(pages_per_slot)
        self.scratch_page = int(num_pages)  # pool row num_pages is scratch
        # pop() takes from the end: keep ascending ids there
        self._free: List[int] = list(range(num_pages - 1, -1, -1))
        self.table = np.full(
            (num_slots, pages_per_slot), self.scratch_page, np.int32
        )
        # bumped on every table mutation — the engine caches the device
        # copy of the table against it, so steady-state decode (table
        # unchanged across steps) re-ships nothing
        self.version = 0

    # ------------------------------------------------------------------

    @property
    def free_pages(self) -> int:
        return len(self._free)

    @property
    def used_pages(self) -> int:
        return self.num_pages - len(self._free)

    def slot_pages(self, slot: int) -> int:
        """Physical pages currently owned by ``slot``."""
        return int((self.table[slot] != self.scratch_page).sum())

    def pages_for(self, num_lines: int) -> int:
        """Logical pages needed to cover cache lines [0, num_lines)."""
        return -(-int(num_lines) // self.page_size)

    # ------------------------------------------------------------------

    def ensure(self, slot: int, num_lines: int) -> bool:
        """Grow ``slot``'s table to cover ``num_lines`` cache lines.
        Already-covered prefixes are kept (idempotent). Returns False —
        with NOTHING allocated — when the free list cannot cover the
        growth; the caller preempts a victim and retries."""
        need = min(self.pages_for(num_lines), self.pages_per_slot)
        row = self.table[slot]
        have = int((row[:need] != self.scratch_page).sum())
        grow = need - have
        if grow <= 0:
            return True
        if grow > len(self._free):
            return False
        for j in range(have, need):
            assert row[j] == self.scratch_page, (
                f"slot {slot} page table has a hole before logical page {j}"
            )
            row[j] = self._free.pop()
        self.version += 1
        return True

    def release(self, slot: int) -> int:
        """Return all of ``slot``'s pages to the free list; resets the
        row to scratch. Returns the number of pages freed."""
        row = self.table[slot]
        freed = 0
        for j in range(self.pages_per_slot):
            page = int(row[j])
            if page == self.scratch_page:
                continue
            assert page not in self._free, (
                f"double free of physical page {page} (slot {slot})"
            )
            self._free.append(page)
            row[j] = self.scratch_page
            freed += 1
        if freed:
            self.version += 1
        return freed

    def check_no_leaks(self) -> None:
        """All pages are either free or table-owned, with no overlap —
        the no-leak/no-alias invariant tests assert after a workload."""
        owned = set()
        for row in self.table:
            for page in row:
                if int(page) == self.scratch_page:
                    continue
                assert int(page) not in owned, f"page {page} aliased"
                owned.add(int(page))
        free = set(self._free)
        assert not (owned & free), f"pages both owned and free: {owned & free}"
        assert len(free) == len(self._free), "free list holds duplicates"
        assert owned | free == set(range(self.num_pages)), (
            f"leaked pages: {set(range(self.num_pages)) - owned - free}"
        )
