"""Paged KV cache — refcounted page allocator + per-slot page tables.

TPU-native port of the Ragged Paged Attention memory layout
(PAPERS.md, arxiv 2604.15464; vLLM's PagedAttention ancestry): instead
of a dense per-slot cache of ``slots × (max_len+1)`` lines, K/V live in
a pool of fixed-size token **pages** and each request slot owns a
**page table** mapping logical pages (line // page_size) to physical
pages. HBM cost is then proportional to pages actually allocated — live
tokens rounded up to the page size — not to the worst-case sequence
length, which is what lets serving run the reference's 64 request slots
on one chip (VERDICT.md round 5, missing #3).

HBM accounting: one page costs ``2 · page_size · KV · ceil(dk / pack)
· itemsize(cache_dtype)`` bytes per layer (K and V; ``pack`` is the
codes-per-element factor of the storage layout — 1 for fp and int8,
2 for int4's packed nibbles), and ``ServingConfig.max_cached_tokens``
prices the pool in the pack=1 full-precision units — it is an HBM
budget expressed as full-precision tokens. With
``ServingConfig.kv_quant`` (serve/kv_quant.py) pages store quantized
codes plus two per-page f32 scale rows (``8·KV`` bytes — under 1% of
a page at real head dims), so the SAME budget buys ~2x (int8) or ~4x
(int4, two codes per byte along dk) the physical pages
(``kv_quant.quantized_pool_pages`` converts; the engine sizes this
allocator with the converted count). The allocator itself is
dtype-blind — it hands out page INDICES; every invariant below holds
identically over bf16, f32 and quantized pools of either pack
(asserted by the randomized property test in tests/test_paged_kv.py,
which runs the same sweep over int8 and packed-int4 engines' pools).

Pages are **reference counted** so the automatic prefix cache
(serve/prefix_cache.py) can keep a finished request's prompt pages
alive and splice them into later requests' tables: a physical page may
be referenced by several slot tables at once (a shared prompt prefix)
plus one reference held by the prefix-cache radix tree. A page returns
to the free list exactly when its refcount drains to zero — cached-but-
idle pages (refcount 1, held only by the tree) are reclaimed through
``reclaim_cb`` before an allocation ever fails, so the cache can never
cause an admission preemption that a cold pool would not. (With the
hierarchical host tier — ``ServingConfig.host_cache_bytes`` — that
reclaim SPILLS the page's content to host RAM instead of discarding
it; the page index still returns to the free list, and the tree's
host-resident nodes hold no allocator reference until re-admitted.)

The allocator is host-side state owned by the :class:`InferenceEngine`
(one per engine — a SpecInfer LLM/SSM pair allocates independently
because their pools differ in layer count and budget). The
RequestManager drives it on admit/evict/completion; the device only
ever sees the resulting ``(slots, pages_per_slot)`` int32 table shipped
with each step.

Physical page ``num_pages`` (one past the pool) is the shared
**scratch page**: unallocated table entries point at it, so padding
tokens' K/V writes and gathers through unallocated entries land on a
real buffer that no mask ever exposes (the paged analog of the dense
layout's per-slot scratch row, models/llama.py init_kv_cache).

Context parallelism (``ServingConfig.kv_shard="context"``): with
``cp_shards`` > 1 the pool is partitioned into per-shard slices —
shard ``d`` owns physical pages ``[d*pages_per_shard,
(d+1)*pages_per_shard)`` (the contiguous row range that shards over
the mesh ``seq`` axis) — and LOGICAL page ``j`` of every request is
STRIPED to shard ``j % cp_shards``, so one long request's pages (and
its decode-time reads) spread evenly over the shards instead of
filling one shard's slice while the others idle. All allocation is
per-shard: ``ensure`` covers each shard's share of the growth
all-or-nothing, ``cow``/``take_free_page`` draw from the logical
page's owning shard, and ``check_no_leaks`` additionally audits the
striping invariant (every mapped logical page lives on its owning
shard) and the per-shard free-list partition. ``cp_shards=1``
(default) is byte-for-byte the single-pool allocator.
"""
from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence

import numpy as np


class PageAllocator:
    """Refcounted free-list allocator over a physical KV page pool.

    Invariants (asserted, tested in tests/test_paged_kv.py):
      * ``refcount[p]`` equals the number of live references to physical
        page ``p``: one per slot-table entry pointing at it, plus any
        external references (the prefix cache's radix tree) the caller
        reports to :meth:`check_no_leaks`;
      * a page is on the free list **iff** its refcount is zero
        (refcount-zero-iff-free) — there is no leaked and no aliased
        state in between;
      * ``ensure`` either covers the requested lines fully or changes
        nothing (no partial allocation to roll back);
      * releasing never double-frees: a refcount decrement below zero is
        an assertion failure, and ``release`` of an already-clean slot
        is a no-op.
    """

    def __init__(self, num_pages: int, pages_per_slot: int, num_slots: int,
                 page_size: int, cp_shards: int = 1):
        if num_pages < pages_per_slot and cp_shards == 1:
            raise ValueError(
                f"page pool ({num_pages} pages) smaller than one request's "
                f"worst case ({pages_per_slot} pages) — no request could "
                "ever run to max_sequence_length"
            )
        if cp_shards < 1:
            raise ValueError(f"cp_shards must be >= 1 (got {cp_shards})")
        if num_pages % cp_shards:
            raise ValueError(
                f"context-parallel pool needs num_pages ({num_pages}) "
                f"divisible by cp_shards ({cp_shards}) — the engine sizes "
                "per-shard slices of equal page count"
            )
        self.num_pages = int(num_pages)
        self.page_size = int(page_size)
        self.pages_per_slot = int(pages_per_slot)
        self.cp_shards = int(cp_shards)
        self.pages_per_shard = self.num_pages // self.cp_shards
        if cp_shards > 1 and -(-int(pages_per_slot) // cp_shards) > (
            self.pages_per_shard
        ):
            raise ValueError(
                f"context-parallel pool ({num_pages} pages over "
                f"{cp_shards} shards, {self.pages_per_shard}/shard) "
                f"smaller than one request's worst case "
                f"({pages_per_slot} striped logical pages = "
                f"{-(-pages_per_slot // cp_shards)}/shard) — no request "
                "could ever run to max_sequence_length"
            )
        self.scratch_page = int(num_pages)  # pool row num_pages is scratch
        # per-shard free lists (one list when cp_shards == 1 — the
        # single-pool allocator, unchanged); pop() takes from the end:
        # keep ascending ids there
        self._free_by_shard: List[List[int]] = [
            list(range((d + 1) * self.pages_per_shard - 1,
                       d * self.pages_per_shard - 1, -1))
            for d in range(self.cp_shards)
        ]
        self.refcount = np.zeros((num_pages,), np.int32)
        self.table = np.full(
            (num_slots, pages_per_slot), self.scratch_page, np.int32
        )
        # bumped on every table mutation — the engine caches the device
        # copy of the table against it, so steady-state decode (table
        # unchanged across steps) re-ships nothing
        self.version = 0
        # Last-resort page supplier: called with the shortfall (pages)
        # when the free list cannot cover a request; expected to free
        # reclaimable pages (the prefix cache evicts idle cached pages)
        # and return how many it freed. Under context parallelism the
        # call carries ``shard=`` so reclaim frees pages on the shard
        # that is actually short. None = allocation just fails.
        self.reclaim_cb: Optional[Callable[[int], int]] = None

    # ------------------------------------------------------------------
    # context-parallel partition (no-ops collapsing to shard 0 when
    # cp_shards == 1)

    def shard_of_logical(self, logical: int) -> int:
        """Owning shard of a LOGICAL page index — striped so consecutive
        logical pages land on consecutive shards (decode reads and long
        prompts load-balance)."""
        return int(logical) % self.cp_shards

    def shard_of_page(self, page: int) -> int:
        """Owning shard of a PHYSICAL page (contiguous row slices)."""
        return int(page) // self.pages_per_shard

    def shard_page_need(self, num_lines: int) -> List[int]:
        """Pages each shard must supply to cover lines [0, num_lines)
        under the striped ownership."""
        need = self.pages_for(num_lines)
        base, rem = divmod(need, self.cp_shards)
        return [base + (1 if d < rem else 0) for d in range(self.cp_shards)]

    def can_ever_fit(self, num_lines: int) -> bool:
        """Whether a request needing ``num_lines`` cache lines could ever
        be admitted into an EMPTY pool — the per-shard admission bound
        (each shard must cover its striped share)."""
        return all(
            n <= self.pages_per_shard
            for n in self.shard_page_need(num_lines)
        )

    def free_pages_by_shard(self) -> List[int]:
        return [len(f) for f in self._free_by_shard]

    def used_pages_by_shard(self) -> List[int]:
        return [
            self.pages_per_shard - len(f) for f in self._free_by_shard
        ]

    def shard_balance(self) -> float:
        """Occupancy balance gauge: min/max used pages across shards
        (1.0 = perfectly balanced or idle) — the striping telemetry
        SchedulerStats surfaces."""
        used = self.used_pages_by_shard()
        hi = max(used)
        return 1.0 if hi == 0 else min(used) / hi

    # ------------------------------------------------------------------

    @property
    def free_pages(self) -> int:
        return sum(len(f) for f in self._free_by_shard)

    @property
    def used_pages(self) -> int:
        return self.num_pages - self.free_pages

    def slot_pages(self, slot: int) -> int:
        """Physical pages currently mapped by ``slot``'s table."""
        return int((self.table[slot] != self.scratch_page).sum())

    def pages_for(self, num_lines: int) -> int:
        """Logical pages needed to cover cache lines [0, num_lines)."""
        return -(-int(num_lines) // self.page_size)

    # ------------------------------------------------------------------
    # reference counting (shared pages: prefix-cache splicing)

    def acquire(self, page: int) -> None:
        """Add one reference to ``page`` (a slot table or the prefix
        cache now also points at it). The page must not be on the free
        list — either it already has references, or it was just popped
        via :meth:`take_free_page`."""
        assert 0 <= page < self.num_pages, f"acquire of page {page}"
        self.refcount[page] += 1

    def release_ref(self, page: int) -> bool:
        """Drop one reference; when the count drains to zero the page
        returns to its owning shard's free list. Returns True iff the
        page was freed. Decrementing a zero refcount is a double-free
        (asserted)."""
        assert self.refcount[page] > 0, f"double free of physical page {page}"
        self.refcount[page] -= 1
        if self.refcount[page] == 0:
            self._free_by_shard[self.shard_of_page(page)].append(int(page))
            return True
        return False

    def _reclaim(self, shortfall: int, shard: int = 0) -> None:
        """Ask the reclaim hook (prefix-cache LRU eviction/spill) to free
        at least ``shortfall`` pages — on ``shard`` under context
        parallelism (freeing another shard's pages cannot satisfy a
        striped allocation). Best-effort: the free list after the call
        is the only truth."""
        if shortfall > 0 and self.reclaim_cb is not None:
            if self.cp_shards > 1:
                self.reclaim_cb(shortfall, shard=shard)
            else:
                self.reclaim_cb(shortfall)

    def take_free_page(self, shard: int = 0) -> Optional[int]:
        """Pop one page off ``shard``'s free list (evicting idle cached
        pages first if it is dry), with refcount still ZERO — the caller
        must follow up with :meth:`acquire`/:meth:`splice` before
        control returns to the scheduler. None when nothing can be
        freed. Callers allocating for a specific LOGICAL page pass
        ``shard_of_logical(logical)`` so the striping invariant holds."""
        free = self._free_by_shard[shard]
        if not free:
            self._reclaim(1, shard)
        if not free:
            return None
        return free.pop()

    def claim_free_page(self, shard: int = 0) -> Optional[int]:
        """:meth:`take_free_page` + the caller's own single reference
        (refcount 1) in one step — the prefix cache's page-adoption
        idiom (host-tier re-admits and standby tree imports take a
        page the TREE owns, never a slot). None when nothing can be
        freed."""
        page = self.take_free_page(shard)
        if page is not None:
            self.refcount[page] = 1
        return page

    # ------------------------------------------------------------------

    def ensure(self, slot: int, num_lines: int) -> bool:
        """Grow ``slot``'s table to cover cache lines [0, num_lines).

        Contract: already-covered prefixes are kept (idempotent —
        calling again with the same or a smaller bound changes nothing);
        growth pages are freshly allocated with refcount 1 owned by this
        slot — each logical page from its OWNING shard's free list
        (striped, ``shard_of_logical``). When the free lists cannot
        cover the growth even after ``reclaim_cb`` eviction, returns
        False with NOTHING allocated — the caller preempts a victim and
        retries. Returns True once the lines are covered."""
        need = min(self.pages_for(num_lines), self.pages_per_slot)
        row = self.table[slot]
        have = int((row[:need] != self.scratch_page).sum())
        if need - have <= 0:
            return True
        # all-or-nothing across shards: reclaim each short shard first,
        # allocate only once every shard can cover its striped share
        grow_by_shard = [0] * self.cp_shards
        for j in range(have, need):
            grow_by_shard[self.shard_of_logical(j)] += 1
        for d, grow in enumerate(grow_by_shard):
            short = grow - len(self._free_by_shard[d])
            if short > 0:
                self._reclaim(short, d)
        if any(
            grow > len(self._free_by_shard[d])
            for d, grow in enumerate(grow_by_shard)
        ):
            return False
        for j in range(have, need):
            assert row[j] == self.scratch_page, (
                f"slot {slot} page table has a hole before logical page {j}"
            )
            page = self._free_by_shard[self.shard_of_logical(j)].pop()
            assert self.refcount[page] == 0, (
                f"free list held referenced page {page}"
            )
            self.refcount[page] = 1
            row[j] = page
        self.version += 1
        return True

    def splice(self, slot: int, pages: Sequence[int]) -> None:
        """Map ``slot``'s leading logical pages to ``pages`` (a cached
        prompt prefix), acquiring one reference per entry. The slot's
        table must be empty (fresh admission) — splicing is only ever
        the FIRST thing that happens to a slot's table, before
        :meth:`ensure` grows the uncached suffix behind it. Cached
        blocks are logical-page-aligned from the root, so under context
        parallelism a spliced page is on its logical index's owning
        shard by construction (asserted)."""
        row = self.table[slot]
        assert int((row != self.scratch_page).sum()) == 0, (
            f"splice into non-empty slot {slot}"
        )
        assert len(pages) <= self.pages_per_slot
        for j, page in enumerate(pages):
            if self.cp_shards > 1:
                assert self.shard_of_page(int(page)) == (
                    self.shard_of_logical(j)
                ), (
                    f"splice breaks striping: logical page {j} (shard "
                    f"{self.shard_of_logical(j)}) mapped to physical "
                    f"{int(page)} (shard {self.shard_of_page(int(page))})"
                )
            self.acquire(int(page))
            row[j] = int(page)
        if len(pages):
            self.version += 1

    def cow(self, slot: int, logical: int) -> Optional[int]:
        """Copy-on-write bookkeeping for ``slot``'s logical page
        ``logical``: allocate a private page (refcount 1, from the
        logical page's owning shard), swap it into the table, and drop
        this slot's reference on the shared page. Returns the new
        physical page (the caller copies the page CONTENT device-side,
        engine.copy_page), or None when no page could be allocated even
        after reclaim — the table is unchanged."""
        row = self.table[slot]
        old = int(row[logical])
        assert old != self.scratch_page, "COW of an unmapped logical page"
        fresh = self.take_free_page(self.shard_of_logical(logical))
        if fresh is None:
            return None
        self.refcount[fresh] = 1
        row[logical] = fresh
        self.release_ref(old)
        self.version += 1
        return fresh

    def release(self, slot: int) -> int:
        """Drop ``slot``'s reference on every page its table maps and
        reset the row to scratch. Shared pages (spliced prompt prefixes,
        cached pages) survive under their remaining references; only
        pages whose refcount drains to zero return to the free list.
        Returns the number of pages actually freed. Releasing an
        already-clean slot is a no-op (never a double-free)."""
        row = self.table[slot]
        freed = 0
        changed = False
        for j in range(self.pages_per_slot):
            page = int(row[j])
            if page == self.scratch_page:
                continue
            freed += int(self.release_ref(page))
            row[j] = self.scratch_page
            changed = True
        if changed:
            self.version += 1
        return freed

    def check_no_leaks(
        self, external: Optional[Dict[int, int]] = None
    ) -> None:
        """Full refcount audit — the no-leak/no-double-free invariant
        the tests assert after (and, in the property test, DURING) a
        workload: every physical page's refcount equals its slot-table
        reference count plus ``external`` references (the prefix cache's
        ``page_refs()``), and a page is free iff that count is zero."""
        external = external or {}
        counts = np.zeros((self.num_pages,), np.int64)
        for slot, row in enumerate(self.table):
            for j, page in enumerate(row):
                if int(page) == self.scratch_page:
                    continue
                counts[int(page)] += 1
                if self.cp_shards > 1:
                    # striping invariant: every mapped logical page
                    # lives on its owning shard
                    assert self.shard_of_page(int(page)) == (
                        self.shard_of_logical(j)
                    ), (
                        f"slot {slot} logical page {j} (shard "
                        f"{self.shard_of_logical(j)}) maps to physical "
                        f"{int(page)} on shard "
                        f"{self.shard_of_page(int(page))}"
                    )
        for page, n in external.items():
            counts[int(page)] += int(n)
        all_free = [p for f in self._free_by_shard for p in f]
        free = set(all_free)
        assert len(free) == len(all_free), "free list holds duplicates"
        for d, flist in enumerate(self._free_by_shard):
            for p in flist:
                assert self.shard_of_page(p) == d, (
                    f"page {p} (shard {self.shard_of_page(p)}) on shard "
                    f"{d}'s free list"
                )
        for page in range(self.num_pages):
            rc = int(self.refcount[page])
            assert rc == int(counts[page]), (
                f"page {page}: refcount {rc} != {int(counts[page])} live "
                "references (leak or double-free)"
            )
            assert (rc == 0) == (page in free), (
                f"page {page}: refcount {rc} but "
                f"{'on' if page in free else 'off'} the free list"
            )
