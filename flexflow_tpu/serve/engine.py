"""InferenceEngine — compiled-step management for serving.

TPU-native counterpart of the reference ``InferenceManager`` (reference
``src/runtime/inference_manager.cc:81-708``): where the reference compiles
the op graph per inference mode, assigns MachineViews per pipeline stage
and allocates/reuses activation buffers, we jit one step function per
static signature (chunk size × logits mode × mask mode) over a device
mesh, with the KV cache donated through every call so steady-state
decoding allocates nothing.
"""
from __future__ import annotations

import dataclasses
import functools
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..core.mesh import DATA_AXIS, MachineSpec, set_mesh as _set_mesh
from ..obs.tracer import NULL_TRACER
from .batch_config import BatchConfig


@dataclasses.dataclass
class ServingConfig:
    """Serving limits (reference batch_config.h:58-60 + RequestManager
    setters, request_manager.h)."""

    max_requests_per_batch: int = 16
    max_sequence_length: int = 2048
    prefill_chunk: int = 128
    max_spec_tree_tokens: int = 64
    cache_dtype: Any = jnp.bfloat16
    # "xla" (default) or "pallas" — fused decode/tree-verify attention
    # kernels (serve/kernels.py) for models that support the kwarg.
    kernels: str = "xla"
    # Steady-state decode keeps up to this many steps in flight: sampled
    # tokens feed the next step on-device, the host fetches results one
    # step behind (the reference's 4-deep batch-future pipeline,
    # request_manager.cc:2310-2325).
    dispatch_ahead: int = 4
    # Iteration-level continuous batching: prefill chunks ride in the
    # SAME pipelined step as decode rows (one jitted "mixed step" with
    # on-device sampling for decode rows and prefill-final rows), so
    # admissions, chunk progression and completions never drain the
    # dispatch-ahead pipeline. False restores the flush-on-admit
    # scheduler (any PREFILLING request forces the blocking sync path) —
    # kept as the bench baseline and an escape hatch.
    continuous_batching: bool = True
    # Per-step chunked-prefill token budget of the mixed step: each
    # prefilling slot contributes at most this many NEW prompt tokens
    # per iteration (decode rows are not budgeted — they always get
    # their one token). It is the mixed step's compiled row width
    # C = min(prefill_chunk, max_tokens_per_step), so it directly bounds
    # the compute (R×C) — and therefore the latency — a joining prompt
    # adds to in-flight decodes, Sarathi/vLLM-style: small mixed steps
    # keep decode throughput high under churn, at the cost of slower
    # prompt ingestion. The cap is per ROW, not across rows: the padded
    # (R, C) step pays R×C compute regardless of how many rows carry
    # prefill tokens, so limiting the number of prefilling rows per step
    # would save nothing. 0 (default) = a full prefill_chunk per row.
    max_tokens_per_step: int = 0
    # Serving-triage dump directory (reference inference_debugging,
    # serve/__init__.py:48 — per-op inputs/outputs saved to file): every
    # engine step additionally runs an eager per-layer forward and
    # writes each layer's hidden states + the step's tokens/positions as
    # .npy. None = off; the FF_INFERENCE_DEBUGGING env var (a directory
    # path) switches it on without touching code.
    inference_debugging: Optional[str] = None
    # KV cache layout. "dense": per-slot (slots, max_len+1) lines — HBM
    # scales with the worst case. "paged": fixed-size token pages + a
    # per-slot page table (Ragged Paged Attention, PAPERS.md arxiv
    # 2604.15464) — HBM scales with pages actually allocated, which is
    # what lets one chip run the reference's 64 request slots.
    kv_layout: str = "dense"
    page_size: int = 128                    # tokens per KV page
    # Page-pool budget in tokens (rounded up to whole pages). None =
    # worst case (slots × pages_per_slot — same capacity as dense, still
    # allocated lazily). Set it below the worst case to oversubscribe:
    # the RequestManager preempts (recompute-on-readmit) on exhaustion.
    max_cached_tokens: Optional[int] = None
    # Quantized paged KV pages (serve/kv_quant.py; paged layout only).
    # "int8": pages store int8 codes + per-page-per-KV-head f32 amax
    # scales; serve_step's KV write quantizes in the step and attention
    # dequantizes at read time (fused into the Pallas ragged paged
    # kernel), so full-precision K/V never round-trip HBM. "int4":
    # packed nibbles — two codes per byte along head_dim, unpacked in
    # VMEM by the same kernel (logit tolerance is wider than int8's;
    # README "Hierarchical KV cache" documents both). The
    # max_cached_tokens budget keeps meaning "this much KV HBM": the
    # same budget buys ~2x (int8) / ~4x (int4) the pages
    # (kv_quant.quantized_pool_pages; ≥1.9x / ≥3.8x measured after
    # scale rows). None (default) = full-precision cache_dtype pages.
    kv_quant: Optional[str] = None
    # Automatic prefix caching (serve/prefix_cache.py, paged layout
    # only — a no-op passthrough on dense): finished requests' prompt
    # pages stay live in a radix tree; a new request whose prompt shares
    # a cached page-aligned prefix splices those pages into its table
    # and prefills only the uncached suffix. Cached-but-idle pages are
    # LRU-evicted before any allocation fails, so the cache never causes
    # a preemption a cold pool would not. Off by default: cached pages
    # intentionally outlive their requests, which changes the pool
    # accounting benchmarks/tests of the cold allocator assert on.
    prefix_caching: bool = False
    # Hierarchical KV cache — host-RAM spill tier for cold prefix
    # pages (serve/prefix_cache.py; requires prefix_caching): instead
    # of dropping an idle cached page under pool pressure, its content
    # (codes + scales) is copied to pinned host memory with an ASYNC
    # device→host DMA and the HBM page is freed; a later prompt that
    # matches the spilled prefix re-admits the page with an async
    # host→device copy before splice — a cache miss to HBM becomes a
    # host hit instead of a full prefill recompute. The value bounds
    # the host tier in bytes (its own LRU drops cold host pages past
    # it); None (default) = off, cold pages are simply evicted.
    # Spill→re-admit round-trips are byte-exact, so generation over a
    # re-admitted prefix is BITWISE the never-evicted warm path's
    # (tests/test_kv_hierarchy.py).
    host_cache_bytes: Optional[int] = None
    # Context-parallel long-context serving (ROADMAP item 5a; paged
    # layout only). "context": ONE request's KV pages are sharded
    # across ``context_shards`` sequence shards — logical page j lives
    # on shard j % n (striped, so decode reads and long prompts
    # load-balance) and each shard owns its own slice of the pool, so
    # a prompt far beyond one shard's HBM budget serves at the
    # aggregate capacity n × max_cached_tokens. ``max_cached_tokens``
    # becomes a PER-SHARD budget and admission accounting goes
    # per-shard (a request is servable iff every shard can cover its
    # striped share). Attention over the sharded pool is ring ragged
    # paged attention (serve/kernels.ring_ragged_paged_attention): on
    # a mesh whose ``seq`` degree matches, each shard attends its
    # resident pages and partial softmax stats rotate via ppermute;
    # on a single-device mesh (this box) every "shard" is locally
    # addressable and the standard table gather IS the ring result —
    # bitwise the CP-off step, which is what keeps CP-on vs CP-off
    # generation BITWISE (tests/test_long_context.py). "none"
    # (default) = the single-pool layout, byte-for-byte unchanged.
    kv_shard: str = "none"
    # Number of context shards; 0 derives it from the mesh's ``seq``
    # axis degree. On a mesh with seq > 1 the two must agree.
    context_shards: int = 0
    # What gets published into the prefix tree: "complete" (default) —
    # the whole sequence, prompt + generated, at request completion (the
    # multi-turn case: the next turn's prompt extends this turn's
    # transcript); "prefill" — the prompt alone, as soon as its last
    # chunk is dispatched (concurrent same-prompt requests hit sooner).
    cache_policy: str = "complete"
    # Megakernel decode step (ROADMAP item 2, MPK-style): which
    # decode-step fusions to enable, each independently toggleable and
    # bitwise-identical to its unfused counterpart
    # (tests/test_fused_decode.py).
    #   "rope_kv_write" — RoPE on Q/K and the (optionally
    #     int8-quantizing) KV page write fold INSIDE the ragged paged
    #     Pallas kernel (serve/kernels.fused_rope_paged_attention), so
    #     fresh K/V never round-trip HBM between the step's projection
    #     and its attention read. Paged layout only; model families
    #     advertise support via their FUSED_DECODE tuple. With
    #     kernels="xla" the flag is a no-op — the unfused XLA step IS
    #     the CPU-parity fallback.
    #   "sampling" — the greedy/top-k sampling epilogue fuses into the
    #     step program with a mode-specialized head
    #     (serve/sampling.choose_sample_mode): greedy-only decode
    #     batches skip the (R, V) sorts entirely, and the sync path
    #     drops from two dispatched programs per step (step + host-side
    #     sample) to one (engine.run_sampled).
    #   "whole_step" — the WHOLE decode step (embedding, all L layers'
    #     QKV/attention/MLP, the fused RoPE+KV-write prologue, ragged
    #     paged attention over fp/int8/int4 pools, final norm, LM head
    #     and the greedy sampling epilogue) runs as ONE persistent
    #     Pallas program whose grid walks the layers with
    #     double-buffered HBM→VMEM weight streaming
    #     (serve/kernels.whole_step_decode; models/*.serve_step_whole).
    #     Paged layout only; families advertise support via
    #     FUSED_DECODE and gate unstreamable layouts (MoE, ALiBi,
    #     weight-quantized params) in whole_step_weight_layout. On TP
    #     meshes the walk runs collective-explicit (one
    #     serve/collectives.tp_allreduce per row-parallel matmul —
    #     see quantized_allreduce), still one dispatched program. When
    #     the per-layer working set exceeds the VMEM budget
    #     (kernels.WHOLE_STEP_VMEM_BUDGET, FF_WHOLE_STEP_VMEM_MB) the
    #     engine logs and FALLS BACK to the PR-6 per-layer fusions.
    #     Bitwise the unfused kernels="xla" step on the same backend.
    # Off by default; () compiles exactly the pre-fusion step programs
    # under exactly the pre-fusion step keys.
    fused_decode: Tuple[str, ...] = ()
    # Quantized TP decode collectives (serve/collectives.py, EQuARX —
    # PAPERS.md arxiv 2506.17615), whole_step + TP meshes only. None or
    # "exact": the walk's per-layer allreduce is literally lax.psum —
    # bitwise the GSPMD reduction of the unfused step. "int8": the
    # reduce ships int8 codes + per-128-block f32 amax scales (~27% of
    # the f32 bytes) and accumulates dequantized shards in absolute
    # shard order — deterministic, greedy-token-stable in practice, but
    # NOT bitwise (per-element error ≤ n·amax_block/254; an explicit
    # accuracy/bandwidth trade like kv_quant).
    quantized_allreduce: Optional[str] = None
    # Cluster serving (serve/cluster/): one process drives this many
    # engine replicas — each its own mesh and KV pool — behind a
    # front-end Router (prefix-cache-aware placement, session affinity,
    # SLO-aware load shedding). 1 (default) = the single-engine path,
    # byte-for-byte unchanged. The per-replica engine is cluster-blind:
    # every replica is built with this same ServingConfig and the
    # cluster fields only steer the ClusterManager above them.
    replicas: int = 1
    # Placement policy of the front-end router: "prefix" routes to the
    # replica whose radix tree holds the longest match on the incoming
    # prompt (falling back to least-loaded on a universal miss),
    # "round_robin" cycles, "least_loaded" picks the smallest
    # queue-delay estimate. Session affinity (submit(session_id=...))
    # overrides the policy for multi-turn chat whichever is chosen.
    router_policy: str = "prefix"
    # Disaggregated prefill/decode pools: the first ``prefill_replicas``
    # replicas only prefill, the remaining ``decode_replicas`` only
    # decode — a request prefills on a prefill-pool replica and its KV
    # pages MIGRATE to a decode-pool replica at the chunked-prefill
    # boundary (serve/cluster/migration.py: gather_page_kv →
    # scatter_page_kv, byte-exact, so disaggregated generation is
    # bitwise the single-replica path's). Both 0 (default) = every
    # replica serves both phases; when set they must sum to
    # ``replicas`` and the layout must be paged (pages are the unit
    # being shipped).
    prefill_replicas: int = 0
    decode_replicas: int = 0
    # SLO-aware admission: shed a request at the router when EVERY
    # eligible replica's queue-delay estimate (backlog tokens over its
    # observed token rate, serve/cluster/replica.py) exceeds this many
    # seconds. A shed surfaces as RequestStatus.ERROR /
    # GenerationResult.error — the PR-2 contract: terminal, never a
    # hang. None (default) = never shed.
    slo_queue_delay_s: Optional[float] = None
    # Fault tolerance (serve/cluster/health.py + manager failover):
    # when a replica is circuit-broken (DOWN), each of its in-flight
    # requests is re-admitted to a healthy replica through recompute
    # (prompt + tokens generated so far re-prefill — the vLLM-style
    # preemption path, so greedy generations stay bitwise the
    # fault-free run's). failover_retries bounds how many times ONE
    # request may be re-admitted before it turns into a terminal
    # GenerationResult.error (never a hang); repeat re-admissions back
    # off failover_backoff_steps × 2^(retries-2) cluster steps.
    failover_retries: int = 2
    failover_backoff_steps: int = 4
    # Migration back-pressure (disaggregated serving): at most this
    # many finished prefills may WAIT for decode-pool capacity holding
    # their slot + pages (ROADMAP item 1: a full decode pool must not
    # park held prefills unboundedly). Overflow entries release their
    # pages immediately and drain through recompute re-admission on the
    # decode pool's own pending queue instead. None (default) = no
    # bound — the PR-8 behavior.
    migration_queue_budget: Optional[int] = None
    # Replica RPC transport (serve/cluster/transport.py + remote.py).
    # "inproc" (default): replicas are driven by direct method calls —
    # the PR-8/9 in-process cluster, byte-for-byte unchanged.
    # "loopback": every Replica call round-trips the length-prefixed
    # binary wire codec in-process (encode → frame → decode → dispatch
    # → encode → decode) — the transported cluster is BITWISE the
    # in-process one (tests/test_transport.py), and all transport
    # machinery (deadlines, retries, heartbeats, gap detection,
    # transport fault kinds) runs for real. "socket": localhost TCP to
    # subprocess replica servers (python -m
    # flexflow_tpu.serve.cluster.server), one single-process JAX
    # runtime per replica — true multi-process serving that sidesteps
    # the CPU backend's missing multiprocess collectives; requires
    # replica_endpoints.
    replica_transport: str = "inproc"
    # "host:port" per remote replica (socket transport only): one entry
    # per replica, then one per warm standby, in position order.
    replica_endpoints: Tuple[str, ...] = ()
    # Warm-standby replicas (serve/cluster/manager.py): this many extra
    # pre-built engines sit OUTSIDE the routing set; when a routed
    # replica is circuit-broken (DOWN), a standby ADOPTS its position —
    # the dead replica's prefix-cache radix tree (block keys + page
    # bytes, host-spilled pages included) ships over the transport and
    # re-admits on the standby, which then joins routing in the dead
    # replica's place. Failover re-admissions land on a WARM tree
    # instead of survivors re-seeding the families cold. Export is
    # best-effort: a truly dead process (unreachable transport) makes
    # the standby join cold — capacity is still replaced. 0 = none
    # (the PR-9 behavior: survivors absorb the load).
    standby_replicas: int = 0
    # Every replica RPC's deadline in seconds (the socket timeout on
    # send + response read; injected "delay" faults at/over it fail the
    # attempt). A deadline expiry is retried like any transport error.
    rpc_deadline_s: float = 5.0
    # Bounded retries per RPC past the first attempt; retries reuse the
    # request's seq id and the server replays cached responses, so a
    # retried step/submit is at-most-once even when only the response
    # was lost. Exhausted retries surface the TransportError to the
    # drive loop — the same health observation path as a local step
    # exception.
    rpc_retries: int = 2
    # Wall-clock base of the exponential retry backoff (socket
    # transport only — the loopback fails or succeeds instantly, and
    # all HEALTH accounting stays in deterministic cluster steps).
    rpc_backoff_s: float = 0.02
    # Concurrent cluster stepping (the default): ClusterManager.step
    # fans the per-replica step RPCs (and due idle heartbeats) out to
    # every routable remote member at once and harvests them in
    # replica-index order — a cluster step costs ~one round-trip
    # instead of N. Completion order never changes behavior (health
    # observations, failover order and journal records apply in
    # replica-index order either way). False = the serial
    # one-RPC-at-a-time reference loop, kept as the bench A/B arm and
    # determinism oracle; in-process ("inproc") clusters always use it
    # (there is no wire latency to overlap).
    concurrent_stepping: bool = True
    # Elastic, crash-recoverable control plane (serve/cluster/
    # journal.py + reconfigure.py): a directory for the durable request
    # journal — an append-only, CRC-framed log of submissions,
    # flushed-token deltas (batched at the drive loop's flush sync
    # point; no hot-path fsync) and terminal records, plus the
    # membership snapshots live reconfiguration (scale_out / scale_in /
    # set_pools) commits. A SIGKILL'd ClusterManager restarts with
    # ``ClusterManager.recover(...)``: the journal replays (a torn tail
    # truncates, never corrupts), still-running subprocess replica
    # servers reconnect, and every unfinished request re-admits through
    # the recompute path with its journaled prompt + flushed prefix —
    # greedy outputs bitwise the uninterrupted run, zero lost or
    # duplicated requests. None (default) = no journal (a manager crash
    # strands in-flight requests, the pre-PR-14 behavior).
    journal_dir: Optional[str] = None
    # Idle remote replicas are heartbeated every this many cluster
    # steps (a step RPC counts as contact, so busy replicas never pay
    # a separate heartbeat); the response carries the SchedulerStats
    # snapshot + queue-delay inputs the router reads.
    heartbeat_interval_steps: int = 1
    # No successful exchange for this many CLUSTER steps = a heartbeat
    # gap: ONE health observation per gapped step (deduplicated against
    # same-step RPC-error observations — a replica that is both gapped
    # and erroring is observed once, preserving the PR-9 threshold
    # arithmetic). Counted in cluster steps, never wall clock.
    heartbeat_gap_steps: int = 4
    # Runtime hazard sanitizers (flexflow_tpu/analysis/): "retrace" — a
    # strict RetraceGuard on the engine's jit chokepoint that raises on
    # any step recompile after its first compile (the shape/dtype-drift
    # perf-bug class caught at test time instead of as a 100x TPU
    # slowdown); "retrace-warn" — record + FF_LOG=serve=debug log only;
    # "donation" — poison donated cache pytrees after every dispatch so
    # use-after-donate (the PR-2 page-corruption class) raises loudly;
    # "locks" — the process-global LockSanitizer watches every
    # SanitizableLock in the transport/server stack (acquisition-order
    # graph, per-thread held stacks) and raises LockOrderInversion on
    # the A->B / B->A deadlock recipe at the second acquisition.
    # Off by default (zero steady-state overhead); tests and bench flip
    # them on, and FF_SANITIZERS=retrace,donation,locks enables them
    # from the environment without touching code.
    sanitizers: Tuple[str, ...] = ()
    # Self-driving serving (serve/autotune/policy.py): None (default) =
    # no policy loop; "drive" = a cost-model Autoscaler rides
    # ClusterManager.step and APPLIES journaled reconfigurations
    # (scale_out / scale_in / retune advisories); "advise" = the same
    # loop evaluates and journals every decision but applies none
    # (dry-run — the counters and the journal audit trail still fill).
    autoscale: Optional[str] = None
    # Latency SLOs the autoscaler's PREDICTIONS are held to, seconds.
    # slo_ttft_s governs time-to-first-token p99 — admission wait on
    # the ROUTED pool plus the prefill pass; slo_tpot_s governs
    # time-per-output-token p99 — the decode-step interval on whichever
    # pool decodes. At least one must be set when autoscale is on
    # (a policy with no objective can never act). Both are PREDICTED
    # quantities over the fitted traffic profile, distinct from
    # slo_queue_delay_s, which is the router's MEASURED admission gate.
    slo_ttft_s: Optional[float] = None
    slo_tpot_s: Optional[float] = None
    # Minimum cluster steps between APPLIED autoscale actions — the
    # hysteresis floor that keeps a burst from triggering a scale_out /
    # scale_in flap (counted in cluster steps, never wall clock, so
    # replays reproduce decisions).
    autoscale_cooldown_steps: int = 64
    # The replica-count band the policy may move within. max_replicas
    # must be set (>= min) when autoscale="drive" — an unbounded
    # scale_out is a cost bug, not a default.
    autoscale_min_replicas: int = 1
    autoscale_max_replicas: int = 0

    def validate_cluster(self, *, specinfer: bool = False) -> None:
        """Fail-fast validation of the cluster fields — called from
        engine construction (every replica carries this config, so a
        bad value dies before any replica exists) AND from
        ClusterManager, the consumer (cluster/manager.py), mirroring
        how ``kv_quant``/``fused_decode`` fail at construction rather
        than mid-serve. ``specinfer=True`` (LLM.compile with ssms)
        additionally rejects SpecInfer × DISAGGREGATED pools — the
        prefill→decode migration itself (including its RPC wire
        transport) is built; what it does not carry yet is the SSM
        mirror engines' draft caches. Plain replicated clusters
        compose (per-replica SSM mirror engines,
        serve/cluster/replica.py)."""
        if specinfer and self.prefill_replicas:
            raise ValueError(
                "disaggregated prefill/decode pools are not composed "
                "with SpecInfer ssms — the prefill→decode migration "
                "hand-off (built, including the multiplexed RPC wire "
                "transport, serve/cluster/remote.py) ships only the "
                "TARGET engine's pages; the remaining gap is shipping "
                "the draft mirrors' caches in the same hand-off. Use "
                "replicas > 1 WITHOUT prefill_replicas/decode_replicas "
                "(each replica then runs its own SSM mirrors, "
                "serve/cluster/replica.py)"
            )
        if self.replicas < 1:
            raise ValueError(
                f"replicas must be >= 1 (got {self.replicas})"
            )
        if self.router_policy not in ("prefix", "round_robin",
                                      "least_loaded"):
            raise ValueError(
                f"unknown router_policy {self.router_policy!r} (expected "
                "'prefix', 'round_robin' or 'least_loaded')"
            )
        if (self.prefill_replicas < 0) or (self.decode_replicas < 0):
            raise ValueError("prefill_replicas/decode_replicas must be >= 0")
        if bool(self.prefill_replicas) != bool(self.decode_replicas):
            raise ValueError(
                "disaggregated serving needs BOTH pools: set "
                "prefill_replicas and decode_replicas together (got "
                f"prefill={self.prefill_replicas}, "
                f"decode={self.decode_replicas})"
            )
        if self.prefill_replicas:
            if self.prefill_replicas + self.decode_replicas != self.replicas:
                raise ValueError(
                    f"prefill_replicas ({self.prefill_replicas}) + "
                    f"decode_replicas ({self.decode_replicas}) must equal "
                    f"replicas ({self.replicas})"
                )
            if self.kv_layout != "paged":
                raise ValueError(
                    "disaggregated prefill/decode pools require "
                    "kv_layout='paged' — prefill→decode migration ships "
                    "KV PAGES (gather_page_kv/scatter_page_kv), which "
                    "the dense layout does not have"
                )
        if self.slo_queue_delay_s is not None and self.slo_queue_delay_s < 0:
            raise ValueError(
                f"slo_queue_delay_s must be >= 0 (got "
                f"{self.slo_queue_delay_s})"
            )
        if self.slo_queue_delay_s is not None and self.prefill_replicas:
            # Under disaggregated pools the ROUTED set is the PREFILL
            # pool only (cluster/manager.py rebuild_routing), so this
            # SLO would shed on prefill-pool admission delay while the
            # decode pool's backlog — where TPOT pain actually lives —
            # stays invisible to admission. That half-blind gate has
            # bitten quietly; refuse it loudly instead.
            raise ValueError(
                "slo_queue_delay_s is not composed with disaggregated "
                "prefill/decode pools: the router only sees the PREFILL "
                "pool's queue-delay estimates (routing targets the "
                "prefill pool; decode backlog is invisible to "
                "admission), so the SLO would govern only prefill "
                "admission wait and silently ignore decode saturation. "
                "Use slo_ttft_s/slo_tpot_s with autoscale to manage a "
                "disaggregated cluster's latency, or drop the pools "
                f"(got slo_queue_delay_s={self.slo_queue_delay_s}, "
                f"prefill_replicas={self.prefill_replicas})"
            )
        if self.failover_retries < 0:
            raise ValueError(
                f"failover_retries must be >= 0 (got "
                f"{self.failover_retries})"
            )
        if self.failover_backoff_steps < 1:
            raise ValueError(
                f"failover_backoff_steps must be >= 1 (got "
                f"{self.failover_backoff_steps})"
            )
        if (
            self.migration_queue_budget is not None
            and self.migration_queue_budget < 0
        ):
            raise ValueError(
                f"migration_queue_budget must be >= 0 or None (got "
                f"{self.migration_queue_budget})"
            )
        if self.replica_transport not in ("inproc", "loopback", "socket"):
            raise ValueError(
                f"unknown replica_transport {self.replica_transport!r} "
                "(expected 'inproc', 'loopback' or 'socket')"
            )
        if self.standby_replicas < 0:
            raise ValueError(
                f"standby_replicas must be >= 0 (got "
                f"{self.standby_replicas})"
            )
        if self.standby_replicas and self.prefill_replicas:
            raise ValueError(
                "warm standbys are not composed with disaggregated "
                "prefill/decode pools yet — a standby adopts ONE routing "
                "position, which is ambiguous across split pools; use "
                "standby_replicas with mixed replicas"
            )
        if self.replica_transport == "socket":
            want = self.replicas + self.standby_replicas
            if len(self.replica_endpoints) != want:
                raise ValueError(
                    "replica_transport='socket' needs one "
                    "replica_endpoints entry per replica + standby "
                    f"(want {want}, got {len(self.replica_endpoints)})"
                )
        if self.rpc_deadline_s <= 0:
            raise ValueError(
                f"rpc_deadline_s must be > 0 (got {self.rpc_deadline_s})"
            )
        if self.rpc_retries < 0:
            raise ValueError(
                f"rpc_retries must be >= 0 (got {self.rpc_retries})"
            )
        if self.rpc_backoff_s < 0:
            raise ValueError(
                f"rpc_backoff_s must be >= 0 (got {self.rpc_backoff_s})"
            )
        if self.heartbeat_interval_steps < 1:
            raise ValueError(
                f"heartbeat_interval_steps must be >= 1 (got "
                f"{self.heartbeat_interval_steps})"
            )
        if self.heartbeat_gap_steps < 1:
            raise ValueError(
                f"heartbeat_gap_steps must be >= 1 (got "
                f"{self.heartbeat_gap_steps})"
            )
        if self.journal_dir is not None and not str(self.journal_dir):
            raise ValueError(
                "journal_dir must be a non-empty directory path or None"
            )
        if self.autoscale not in (None, "drive", "advise"):
            raise ValueError(
                f"unknown autoscale {self.autoscale!r} (expected None, "
                "'drive' or 'advise')"
            )
        if self.slo_ttft_s is not None and self.slo_ttft_s <= 0:
            raise ValueError(
                f"slo_ttft_s must be > 0 (got {self.slo_ttft_s})"
            )
        if self.slo_tpot_s is not None and self.slo_tpot_s <= 0:
            raise ValueError(
                f"slo_tpot_s must be > 0 (got {self.slo_tpot_s})"
            )
        if self.autoscale_cooldown_steps < 1:
            raise ValueError(
                f"autoscale_cooldown_steps must be >= 1 (got "
                f"{self.autoscale_cooldown_steps})"
            )
        if self.autoscale_min_replicas < 1:
            raise ValueError(
                f"autoscale_min_replicas must be >= 1 (got "
                f"{self.autoscale_min_replicas})"
            )
        if self.autoscale is not None:
            if self.slo_ttft_s is None and self.slo_tpot_s is None:
                raise ValueError(
                    f"autoscale={self.autoscale!r} needs an objective: "
                    "set slo_ttft_s and/or slo_tpot_s (PREDICTED-latency "
                    "SLOs — the policy scales to hold them)"
                )
            if self.autoscale_max_replicas < self.autoscale_min_replicas:
                raise ValueError(
                    f"autoscale_max_replicas "
                    f"({self.autoscale_max_replicas}) must be >= "
                    f"autoscale_min_replicas "
                    f"({self.autoscale_min_replicas}) when autoscale is "
                    "on — an unbounded scale_out is a cost bug, so the "
                    "ceiling is explicit"
                )
            if not (
                self.autoscale_min_replicas <= self.replicas
                <= self.autoscale_max_replicas
            ):
                raise ValueError(
                    f"replicas ({self.replicas}) must start inside the "
                    f"autoscale band [{self.autoscale_min_replicas}, "
                    f"{self.autoscale_max_replicas}]"
                )

    def resolved_context_shards(self, mesh_seq_degree: int = 1) -> int:
        """The context-parallel degree this config resolves to on a mesh
        with ``mesh_seq_degree`` sequence shards (1 when kv_shard is
        off)."""
        if self.kv_shard != "context":
            return 1
        return self.context_shards or max(1, int(mesh_seq_degree))

    def validate_long_context(self, *, mesh_seq_degree: int = 1) -> None:
        """Fail-fast validation of the context-parallel fields — called
        from engine construction (like :meth:`validate_cluster`), so a
        bad combination dies before any pool is allocated, naming the
        fix instead of failing mid-serve."""
        if self.kv_shard not in ("none", "context"):
            raise ValueError(
                f"unknown kv_shard {self.kv_shard!r} (expected 'none' "
                "or 'context')"
            )
        if self.context_shards < 0:
            raise ValueError(
                f"context_shards must be >= 0 (got {self.context_shards})"
            )
        if self.kv_shard == "none":
            if self.context_shards > 1:
                raise ValueError(
                    f"context_shards={self.context_shards} has no effect "
                    "without kv_shard='context' — set kv_shard, or drop "
                    "context_shards"
                )
            return
        if self.kv_layout != "paged":
            raise ValueError(
                "kv_shard='context' requires kv_layout='paged' — context "
                "parallelism shards KV PAGES across sequence shards, "
                "which the dense per-slot layout does not have"
            )
        n = self.resolved_context_shards(mesh_seq_degree)
        if n < 2:
            raise ValueError(
                "kv_shard='context' needs at least 2 shards: set "
                f"context_shards >= 2 (got {self.context_shards}) or "
                "serve on a mesh with a seq-axis degree > 1 "
                f"(mesh seq degree is {mesh_seq_degree})"
            )
        if mesh_seq_degree > 1 and n != mesh_seq_degree:
            raise ValueError(
                f"context_shards ({n}) must equal the mesh seq-axis "
                f"degree ({mesh_seq_degree}) when the mesh is sequence-"
                "sharded — each shard owns one slice of the pool; set "
                "context_shards=0 to derive the degree from the mesh"
            )
        if (
            self.max_cached_tokens is not None
            and self.max_cached_tokens < self.page_size
        ):
            raise ValueError(
                f"kv_shard='context' prices max_cached_tokens "
                f"({self.max_cached_tokens}) PER SHARD, and each shard "
                f"needs at least one whole page (page_size="
                f"{self.page_size}) — raise the budget or shrink "
                "page_size"
            )
        # PR-11's blanket rope_kv_write exclusion on sequence-sharded
        # meshes is LIFTED: the fused prologue now joins the ring body
        # (serve/kernels.ring_ragged_paged_attention fused mode — each
        # shard rotates Q/K and commits its resident lines inside the
        # shard_map program). What remains excluded is the QUANTIZED
        # ring commit: the per-page amax scale update is not
        # shard-local.
        if (
            "rope_kv_write" in (self.fused_decode or ())
            and mesh_seq_degree > 1
            and self.kv_quant is not None
        ):
            raise ValueError(
                "fused_decode='rope_kv_write' is not composed with "
                "QUANTIZED pools on a sequence-sharded mesh — the "
                "in-ring quantizing commit's per-page scale update is "
                "not shard-local; drop kv_quant or the fusion (full-"
                "precision pools compose)"
            )
        if "whole_step" in (self.fused_decode or ()) and mesh_seq_degree > 1:
            raise ValueError(
                "fused_decode='whole_step' is not composed with ring "
                "context parallelism on a sequence-sharded mesh — the "
                "layer walk gathers pages through the full table; serve "
                "whole_step with context_shards on a seq-degree-1 mesh "
                "(the layout-blind gather), or drop one of the two"
            )

    @property
    def cache_len(self) -> int:
        # Committed tokens + in-flight speculative tree slack
        # (reference BatchConfig::MAX_SPEC_TREE_TOKEN_NUM headroom).
        return self.max_sequence_length + self.max_spec_tree_tokens

    @property
    def mixed_chunk(self) -> int:
        """Static per-row chunk width of the mixed continuous-batching
        step (its compiled token-matrix is (slots, mixed_chunk)) — the
        per-slot per-step prefill token budget."""
        if self.max_tokens_per_step <= 0:
            return self.prefill_chunk
        return max(1, min(self.prefill_chunk, self.max_tokens_per_step))

    @property
    def pages_per_slot(self) -> int:
        """Logical pages covering one slot's worst case (cache_len lines
        + the scratch line)."""
        return -(-(self.cache_len + 1) // self.page_size)

    @property
    def num_pages(self) -> int:
        """Physical pages in the pool (excluding the scratch page).
        Under ``kv_shard='context'`` this is the PER-SHARD page count
        (``max_cached_tokens`` is a per-shard HBM budget); the engine
        sizes the total pool at ``num_pages × context_shards``."""
        if self.max_cached_tokens is None:
            return self.max_requests_per_batch * self.pages_per_slot
        return max(
            self.pages_per_slot if self.kv_shard != "context" else 1,
            -(-self.max_cached_tokens // self.page_size),
        )


class InferenceEngine:
    """Owns device-resident params + KV cache and the jitted step fns.

    ``model`` is a model-family module exposing the serving protocol
    (see models/llama.py): ``init_kv_cache(cfg, slots, max_len, dtype)``,
    ``commit_kv(cache, src, dst)`` and
    ``serve_step(params, cache, tokens, positions, logits_idx, mask,
    cache_positions, *, cfg, all_logits)``.
    """

    def __init__(
        self,
        model: Any,
        cfg: Any,
        params: Dict[str, Any],
        serving: Optional[ServingConfig] = None,
        mesh: Optional[Mesh] = None,
    ):
        import os

        self.model = model
        self.cfg = cfg
        self.serving = serving or ServingConfig()
        if self.serving.inference_debugging is None:
            self.serving = dataclasses.replace(
                self.serving,
                inference_debugging=os.environ.get("FF_INFERENCE_DEBUGGING")
                or None,
            )
        self._debug_step = 0
        self.mesh = mesh or MachineSpec().make_mesh(jax.devices()[:1])
        self.params = params
        # Key: (chunk, all_logits, with_mask) for plain steps, or a
        # tagged tuple for fused variants (("mixed_fused", chunk, ...)).
        self._steps: Dict[Any, Callable] = {}
        self._commit: Optional[Callable] = None
        # Hazard sanitizers (flexflow_tpu/analysis — see
        # ServingConfig.sanitizers): every step program is created
        # through self._jit, which the RetraceGuard hooks; every donated
        # dispatch hands the old cache to self._poison_donated.
        self.retrace_guard = None
        self.donation_sanitizer = None
        self.lock_sanitizer = None
        sanitizers = self.serving.sanitizers
        if isinstance(sanitizers, str):
            sanitizers = tuple(
                s.strip() for s in sanitizers.split(",") if s.strip()
            )
        if not sanitizers:
            env = os.environ.get("FF_SANITIZERS", "")
            sanitizers = tuple(s.strip() for s in env.split(",") if s.strip())
        for name in sanitizers:
            if name in ("retrace", "retrace-warn"):
                from ..analysis.retrace import RetraceGuard

                self.retrace_guard = RetraceGuard(strict=(name == "retrace"))
            elif name == "donation":
                from ..analysis.donation import DonationSanitizer

                self.donation_sanitizer = DonationSanitizer()
            elif name == "locks":
                from ..analysis.locks import enable_lock_sanitizer

                # process-global (locks are shared across engines in a
                # loopback cluster); idempotent — a second engine joins
                # the already-active sanitizer
                self.lock_sanitizer = enable_lock_sanitizer(strict=True)
            else:
                raise ValueError(
                    f"unknown sanitizer {name!r} (expected 'retrace', "
                    "'retrace-warn', 'donation' or 'locks')"
                )
        # Cluster fields (serve/cluster/) fail here, at the first
        # replica's engine construction, like kv_quant/fused_decode do.
        self.serving.validate_cluster()
        self.paged = self.serving.kv_layout == "paged"
        if self.serving.kv_layout not in ("dense", "paged"):
            raise ValueError(
                f"unknown kv_layout {self.serving.kv_layout!r} "
                "(expected 'dense' or 'paged')"
            )
        # Context-parallel long-context serving (kv_shard="context"):
        # resolve the shard degree against this engine's mesh and fail
        # bad combinations here, not mid-serve.
        from ..core.mesh import SEQ_AXIS

        seq_deg = self.mesh.shape.get(SEQ_AXIS, 1)
        self.serving.validate_long_context(mesh_seq_degree=seq_deg)
        self.cp_shards = self.serving.resolved_context_shards(seq_deg)
        # per-shard BUDGET pages (quant-converted) the admission check
        # enforces; set by _alloc_cache when max_cached_tokens is given
        self.cp_budget_pages_per_shard = None
        # the ring shard_map program only engages on a mesh that is
        # actually sequence-sharded; on a seq-degree-1 mesh every shard
        # is locally addressable and the plain table gather IS the ring
        # result (bitwise the CP-off step — serve/kernels.py)
        self.cp_ring = self.cp_shards > 1 and seq_deg > 1
        # Megakernel decode step: validate the fusion set up front so a
        # bad toggle fails at engine construction, not mid-serve.
        fused = self.serving.fused_decode
        if isinstance(fused, str):
            fused = tuple(s.strip() for s in fused.split(",") if s.strip())
            self.serving = dataclasses.replace(self.serving,
                                               fused_decode=fused)
        for name in fused:
            if name not in ("rope_kv_write", "sampling", "whole_step"):
                raise ValueError(
                    f"unknown fused_decode entry {name!r} (expected "
                    "'rope_kv_write', 'sampling' and/or 'whole_step')"
                )
        # Whole-step megakernel (serve/kernels.whole_step_decode):
        # capability-gated at construction. The VMEM gate below picks a
        # sub-block tile count per step shape (1 = untiled walk);
        # whole_step_on only flips to False when even the finest legal
        # tiling cannot fit the budget (whole_step_fallbacks counts
        # those, mirrored into SchedulerStats). whole_step_mixed_on
        # extends the walk to the C>1 mixed/chunked-prefill step.
        self.whole_step_on = False
        self.whole_step_tiles = 1
        self.whole_step_mixed_on = False
        self.whole_step_mixed_tiles = 1
        self.whole_step_fallbacks = 0
        self.whole_step_vmem_est = 0
        from .collectives import resolve_mode as _resolve_collective

        self.collective_mode = _resolve_collective(
            self.serving.quantized_allreduce
        )
        if (
            self.serving.quantized_allreduce is not None
            and "whole_step" not in fused
        ):
            raise ValueError(
                "quantized_allreduce only applies to the whole-step "
                "decode walk — set fused_decode=('whole_step',) (TP "
                "meshes), or drop quantized_allreduce"
            )
        if "whole_step" in fused:
            if not self.paged:
                raise ValueError(
                    "fused_decode='whole_step' requires "
                    "kv_layout='paged' — the layer walk commits and "
                    "gathers K/V through the page table"
                )
            if "whole_step" not in getattr(model, "FUSED_DECODE", ()):
                raise ValueError(
                    "fused_decode='whole_step' requested but "
                    f"{getattr(model, '__name__', repr(model))} does not "
                    "advertise it (model.FUSED_DECODE)"
                )
            if self.pipelined:
                raise ValueError(
                    "fused_decode='whole_step' is not composed with "
                    "pipeline parallelism — the walk owns the whole "
                    "layer stack"
                )
            from ..core.mesh import MODEL_AXIS as _MODEL_AXIS

            tp = self.mesh.shape.get(_MODEL_AXIS, 1)
            if tp > 1 and (
                cfg.num_attention_heads % tp
                or cfg.num_key_value_heads % tp
            ):
                raise ValueError(
                    "fused_decode='whole_step' on a TP mesh needs head "
                    f"counts divisible by the model degree ({tp}): got "
                    f"H={cfg.num_attention_heads}, "
                    f"KV={cfg.num_key_value_heads} (MQA replicated "
                    "caches are not composed with the manual TP walk)"
                )
            # capability gate: the family's weight-layout hook raises a
            # named error for unstreamable layouts (MoE, ALiBi,
            # weight-quantized params) — at construction, never mid-serve
            model.whole_step_weight_layout(params, cfg)
            self.whole_step_on = True
        if "rope_kv_write" in fused:
            if not self.paged:
                raise ValueError(
                    "fused_decode='rope_kv_write' requires "
                    "kv_layout='paged' — the fused prologue commits K/V "
                    "through the page table inside the ragged paged "
                    "kernel"
                )
            if "rope_kv_write" not in getattr(model, "FUSED_DECODE", ()):
                raise ValueError(
                    "fused_decode='rope_kv_write' requested but "
                    f"{getattr(model, '__name__', repr(model))} does not "
                    "advertise it (model.FUSED_DECODE) — the family's "
                    "serve_step_paged has no fused prologue"
                )
        # Dispatch telemetry (bench serve_fused): device programs this
        # engine's serving loop issued — every jitted step dispatched
        # here plus host-side decode heads the scheduler counts via
        # count_dispatch. The fused-epilogue claim ("strictly fewer
        # programs per step") is measured against this counter.
        self.dispatch_count = 0
        # Observability (flexflow_tpu/obs): count_dispatch doubles as
        # the tracing chokepoint — with a tracer attached (shared with
        # the owning scheduler's lane by obs.attach_observability),
        # every dispatched device program becomes a trace event, which
        # is what lets a timeline show dispatched-programs-per-step.
        # NULL_TRACER (default) keeps the counter a bare increment.
        self.tracer = NULL_TRACER
        # Quantized KV pages (serve/kv_quant.py): validated up front so
        # a bad value fails at engine construction, not mid-serve.
        self.kv_quant_spec = None
        if self.serving.kv_quant is not None:
            if not self.paged:
                raise ValueError(
                    "kv_quant requires kv_layout='paged' — the dense "
                    "layout has no per-page scale granularity"
                )
            from .kv_quant import resolve_spec

            self.kv_quant_spec = resolve_spec(self.serving.kv_quant)
        if self.serving.cache_policy not in ("complete", "prefill"):
            raise ValueError(
                f"unknown cache_policy {self.serving.cache_policy!r} "
                "(expected 'complete' or 'prefill')"
            )
        # Hierarchical KV host tier: validated up front — the spill
        # path only exists as the prefix cache's eviction alternative.
        if self.serving.host_cache_bytes:
            if not self.paged or not self.serving.prefix_caching:
                raise ValueError(
                    "host_cache_bytes requires kv_layout='paged' with "
                    "prefix_caching=True — the host tier spills cold "
                    "prefix-cache pages, so there is nothing to spill "
                    "without the radix tree"
                )
        self.pager = None  # PageAllocator when paged (host-side tables)
        if self.pipelined:
            pp = self.mesh.shape["pipe"]
            L = cfg.num_hidden_layers
            if L % pp:
                raise ValueError(
                    f"pipeline serving needs num_hidden_layers ({L}) "
                    f"divisible by the pipe degree ({pp})"
                )
            if self.paged:
                raise ValueError(
                    "kv_layout='paged' is not composed with pipeline "
                    "parallelism yet — use kv_layout='dense' with pipe>1"
                )
        self.cache = self._alloc_cache()
        if self.whole_step_on:
            self._whole_step_vmem_gate()

    @staticmethod
    def _whole_step_vmem_budget() -> int:
        """Resolve the whole-step VMEM budget: the kernel default
        (kernels.WHOLE_STEP_VMEM_BUDGET) unless FF_WHOLE_STEP_VMEM_MB
        overrides it. A malformed override raises a ValueError NAMING
        the env var — never an unhandled float() traceback mid-
        construction."""
        import os

        from . import kernels as _pk

        env = os.environ.get("FF_WHOLE_STEP_VMEM_MB")
        if not env:
            return _pk.WHOLE_STEP_VMEM_BUDGET
        try:
            mb = float(env)
        except ValueError:
            raise ValueError(
                f"FF_WHOLE_STEP_VMEM_MB={env!r} is not a number — set "
                "the whole-step VMEM budget override in megabytes "
                "(e.g. FF_WHOLE_STEP_VMEM_MB=14), or unset it for the "
                "kernel default"
            ) from None
        if mb <= 0:
            raise ValueError(
                f"FF_WHOLE_STEP_VMEM_MB={env!r} must be positive — "
                "the whole-step VMEM budget is a size in megabytes"
            )
        return int(mb * 1024 * 1024)

    def _whole_step_vmem_gate(self):
        """VMEM gate of the whole-step walk (single-shard meshes — the
        TP walk is collective-explicit XLA, not one kernel): for each
        step shape the walk serves (the C=1 decode step; the C=
        mixed-chunk mixed step) pick the SMALLEST sub-block tile count
        whose priced working set (serve/kernels.whole_step_vmem_bytes)
        fits the budget (kernels.WHOLE_STEP_VMEM_BUDGET;
        FF_WHOLE_STEP_VMEM_MB overrides). Geometries whose layer does
        not fit untiled get a tile count, NOT a fallback — the walk's
        projection weights stream in output-column sub-tiles
        (serve/kernels._whole_step_decode_tiled), so the footprint is
        bounded by the tile size. The only remaining fallback is a
        budget below the walk's irreducible floor (pool slices +
        resident constants + accumulators), which no tiling can shrink;
        that flips the path off loudly and bumps
        ``whole_step_fallbacks`` (mirrored into SchedulerStats /
        ClusterStats). README "Whole-step decode megakernel" carries
        the budget math."""
        from ..core.mesh import MODEL_AXIS
        from . import kernels as _pk
        from ..logging_utils import get_logger

        if self.mesh.shape.get(MODEL_AXIS, 1) > 1:
            return  # TP walk: per-layer XLA programs, no VMEM gate
        budget = self._whole_step_vmem_budget()
        layer_arrays, head_arrays = self.model.whole_step_weight_layout(
            self.params, self.cfg
        )
        tile_roles = self.model.whole_step_tile_roles(self.cfg)
        R = self.num_slots
        D = self.cfg.hidden_size
        S_virt = self.serving.pages_per_slot * self.serving.page_size

        def pick(C):
            x0 = np.zeros((R, C, D), jnp.dtype(self.cfg.dtype))
            mask = np.zeros((R, C, S_virt), np.bool_)
            return _pk.whole_step_pick_tiles(
                layer_arrays, head_arrays, self.cache, x0, mask,
                self.cfg.num_attention_heads,
                tile_roles=tile_roles, budget=budget,
            )

        tiles, est = pick(1)
        self.whole_step_vmem_est = int(est)
        if tiles is None:
            self.whole_step_fallbacks += 1
            get_logger("serve").warning(
                "whole_step: even the finest sub-block tiling prices "
                "%.1f MB against the %.1f MB budget (the pool slices + "
                "resident constants + accumulators floor) — falling "
                "back to the PR-6 per-layer fused decode path (raise "
                "FF_WHOLE_STEP_VMEM_MB, or shrink the pool/model; "
                "README 'Whole-step decode megakernel')",
                est / 1e6, budget / 1e6,
            )
            self.whole_step_on = False
            return
        self.whole_step_tiles = int(tiles)
        if tiles > 1:
            get_logger("serve").info(
                "whole_step: layer working set over budget untiled — "
                "streaming weight sub-blocks at tiles=%d (%.1f MB "
                "priced vs %.1f MB budget)",
                tiles, est / 1e6, budget / 1e6,
            )
        # the whole-step MIXED step: the same walk over the (R, C)
        # chunked-prefill step shape, priced at the widest chunk the
        # scheduler dispatches
        C = self.serving.prefill_chunk
        if C <= 1:
            self.whole_step_mixed_on = True
            self.whole_step_mixed_tiles = self.whole_step_tiles
            return
        mtiles, mest = pick(C)
        if mtiles is None:
            self.whole_step_fallbacks += 1
            get_logger("serve").warning(
                "whole_step: the C=%d mixed step prices %.1f MB "
                "against the %.1f MB budget at every tiling — decode "
                "keeps the walk, mixed steps keep the per-layer path",
                C, mest / 1e6, budget / 1e6,
            )
            return
        self.whole_step_mixed_on = True
        self.whole_step_mixed_tiles = int(mtiles)

    @property
    def pipelined(self) -> bool:
        """Serve-time pipeline parallelism: stage-sharded layer stack
        (reference inference_manager.cc:91-133 stage assignment)."""
        from ..core.mesh import PIPE_AXIS

        return self.mesh.shape.get(PIPE_AXIS, 1) > 1

    def _alloc_cache(self):
        """Allocate the KV cache sharded over the mesh (the model's
        kv_cache_pspecs: slots — or pages, when paged — on the data
        axis, KV heads on the model axis) — the analog of the
        reference's per-shard tensor_buffer allocation
        (inference_manager.cc:143-200). The paged branch also (re)builds
        the host-side page allocator: a fresh cache means empty tables."""
        sc = self.serving
        if self.paged:
            from .paging import PageAllocator

            num_pages = sc.num_pages
            if self.kv_quant_spec is not None and sc.max_cached_tokens is not None:
                # bytes-per-page accounting (serve/kv_quant.py): the
                # max_cached_tokens budget is an HBM budget priced at
                # cache_dtype — int8 pages cost ~half the bytes, so the
                # same budget exposes ~2x the pages to the allocator
                from .kv_quant import quantized_pool_pages

                num_pages = quantized_pool_pages(
                    num_pages,
                    sc.page_size,
                    self.cfg.num_key_value_heads,
                    self.cfg.head_dim,
                    jnp.dtype(sc.cache_dtype).itemsize,
                    self.kv_quant_spec,
                )
            extra_rows = 0
            if self.cp_shards > 1:
                # context parallelism: num_pages is the PER-SHARD
                # budget; the pool holds every shard's slice. Like the
                # single-pool layout (whose num_pages property clamps
                # up to pages_per_slot), the ALLOCATOR is clamped to
                # one slot's striped worst case so construction always
                # succeeds — the admission check enforces the BUDGET
                # (request_manager reads cp_budget_pages_per_shard, so
                # an over-budget prompt is a terminal ERROR, the PR-2
                # live-lock contract, never a constructor crash).
                self.cp_budget_pages_per_shard = (
                    num_pages if sc.max_cached_tokens is not None else None
                )
                per_shard = max(
                    num_pages, -(-sc.pages_per_slot // self.cp_shards)
                )
                num_pages = per_shard * self.cp_shards
                # The ring layout shards pool ROWS over the seq axis:
                # pad with unreferenced rows until (total + scratch)
                # divides the degree — the allocator never hands a pad
                # row out (its num_pages excludes them) and the scratch
                # row keeps index num_pages.
                if self.cp_ring:
                    extra_rows = (-(num_pages + 1)) % self.cp_shards
            else:
                data = self.mesh.shape.get(DATA_AXIS, 1)
                if data > 1:
                    # pool rows (num_pages + scratch) shard over data —
                    # round up so the leading dim divides evenly
                    num_pages += (-(num_pages + 1)) % data
            self.pager = PageAllocator(
                num_pages, sc.pages_per_slot, sc.max_requests_per_batch,
                sc.page_size, cp_shards=self.cp_shards,
            )
            self._table_cache = None  # fresh pager → stale device copy
            init_kw = dict(kv_quant=sc.kv_quant)
            if extra_rows:
                init_kw["extra_rows"] = extra_rows
            init = functools.partial(
                self.model.init_paged_kv_cache,
                self.cfg,
                num_pages,
                sc.page_size,
                sc.cache_dtype,
                **init_kw,
            )
            pspec_fn = functools.partial(
                self.model.paged_kv_cache_pspecs, kv_quant=sc.kv_quant,
                kv_shard=sc.kv_shard if self.cp_ring else None,
            )
        else:
            init = functools.partial(
                self.model.init_kv_cache,
                self.cfg,
                sc.max_requests_per_batch,
                sc.cache_len,
                sc.cache_dtype,
            )
            pspec_fn = self.model.kv_cache_pspecs
        with _set_mesh(self.mesh):
            if any(n > 1 for n in self.mesh.shape.values()):
                pspecs = pspec_fn(self.cfg, pipeline=self.pipelined)
                shardings = jax.tree.map(
                    lambda p: NamedSharding(self.mesh, p),
                    pspecs,
                    is_leaf=lambda x: isinstance(x, P),
                )
                return jax.jit(init, out_shardings=shardings)()
            return init()

    # ------------------------------------------------------------------
    # paged-layout accounting (bench + tests)

    def page_table_device(self) -> jnp.ndarray:
        """The engine's own page table as a device array — every step's
        read-only gather/scatter indices. Cached against the allocator's
        version counter: steady-state decode (no admissions, no page
        growth) re-ships nothing."""
        cached = getattr(self, "_table_cache", None)
        if cached is not None and cached[0] == self.pager.version:
            return cached[1]
        dev = jnp.asarray(self.pager.table, dtype=jnp.int32)
        self._table_cache = (self.pager.version, dev)
        return dev

    def kv_cache_bytes(self) -> int:
        """Device bytes held by the cache buffers (dense: the whole
        slots × max_len cache; paged: the page pool)."""
        return sum(int(leaf.nbytes) for leaf in jax.tree.leaves(self.cache))

    def kv_bytes_per_line(self) -> float:
        """K+V bytes one cached token line costs across all layers —
        quantized pools amortize their per-page f32 scale rows into the
        per-line figure, so the metric stays an honest HBM cost."""
        k, v = self.cache["k"], self.cache["v"]
        lines = k.shape[1] * k.shape[2]  # slots×(len+1) or pages×page_size
        total = int(k.nbytes) + int(v.nbytes)
        for name in ("k_scale", "v_scale"):
            if name in self.cache:
                total += int(self.cache[name].nbytes)
        return total / lines

    def kv_allocated_bytes(self) -> int:
        """Bytes of KV HBM backing ALLOCATED pages (paged layout): the
        footprint proportional-to-live-tokens claim, measured."""
        if not self.paged:
            return self.kv_cache_bytes()
        return int(
            self.pager.used_pages * self.serving.page_size
            * self.kv_bytes_per_line()
        )

    @property
    def scratch_pos(self) -> int:
        return self.serving.cache_len

    @property
    def num_slots(self) -> int:
        return self.serving.max_requests_per_batch

    # ------------------------------------------------------------------
    # sanitizer chokepoints (flexflow_tpu/analysis)

    def _jit(self, fn: Callable, *, key: Any,
             donate_argnums: Tuple[int, ...] = ()) -> Callable:
        """Every step program (``_steps``/``_commit``) is compiled
        through this chokepoint so the retrace sentinel can observe it:
        the guard wraps ``fn`` to record each trace — which is exactly
        one XLA compile — under ``key`` and, in strict mode, raises on
        any recompile of a known key (analysis/retrace.py)."""
        if self.retrace_guard is not None:
            fn = self.retrace_guard.instrument(fn, key=key)
        return jax.jit(fn, donate_argnums=donate_argnums)

    def _poison_donated(self, donated: Any, key: Any) -> None:
        """Donation-sanitizer hook: after a donated dispatch the OLD
        cache pytree is poisoned (leaves deleted, entries swapped for
        DeletedBufferProxy) so any lingering host-side reference raises
        UseAfterDonateError at the faulty read instead of silently
        reading donated memory (analysis/donation.py)."""
        if self.donation_sanitizer is not None and donated is not self.cache:
            self.donation_sanitizer.poison(
                donated, context=f"engine step {key!r}"
            )

    # ------------------------------------------------------------------

    def _serve_step_fn(self, all_logits: bool,
                       num_layers: Optional[int] = None) -> Callable:
        """model.serve_step (or serve_step_paged) bound to this engine's
        static kwargs. The paged variant takes the page table as a
        trailing positional and needs cache_len for its scratch-line
        mask cutoff. ``num_layers`` binds the LAYER-SLICED early-exit
        draft step (SpecConfig.draft="early_exit"): the model runs only
        its first ``num_layers`` blocks and leaves the deeper cache
        rows untouched."""
        kw = dict(cfg=self.cfg, all_logits=all_logits)
        if num_layers is not None:
            kw["num_layers"] = int(num_layers)
        if self.serving.kernels != "xla":
            kw["kernels"] = self.serving.kernels
        if self.pipelined:
            kw["mesh"] = self.mesh
        if self.paged:
            kw["cache_len"] = self.serving.cache_len
            if self.serving.kv_quant is not None:
                kw["kv_quant"] = self.serving.kv_quant
            if "rope_kv_write" in self.serving.fused_decode:
                kw["fused_rope"] = True
            if self.cp_ring:
                # sequence-sharded pool: attention reads go through the
                # ring ragged paged program (partial shard_map over the
                # seq axis; serve/kernels.ring_ragged_paged_attention)
                kw["cp_mesh"] = self.mesh
            return functools.partial(self.model.serve_step_paged, **kw)
        return functools.partial(self.model.serve_step, **kw)

    def count_dispatch(self, kind: str = "step") -> None:
        """Record one dispatched device program (see dispatch_count)."""
        self.dispatch_count += 1
        tr = self.tracer
        if tr.enabled:
            tr.event("dispatch", kind=kind)

    def _get_step(self, chunk: int, all_logits: bool, with_mask: bool):
        """One compiled program per static signature — the analog of the
        reference's per-InferenceMode compiled graphs (compile_inference),
        cached like Legion's replayed traces."""
        key = (chunk, all_logits, with_mask)
        if key not in self._steps:
            fn = self._serve_step_fn(all_logits)

            if self.paged:
                def step(params, cache, tokens, positions, logits_idx,
                         mask, cpos, page_table):
                    return fn(params, cache, tokens, positions, logits_idx,
                              mask, cpos, page_table)
            else:
                def step(params, cache, tokens, positions, logits_idx,
                         mask, cpos):
                    return fn(params, cache, tokens, positions, logits_idx,
                              mask, cpos)

            self._steps[key] = self._jit(step, key=key, donate_argnums=(1,))
        return self._steps[key]

    def _get_mixed_step(self, chunk: int, with_logits: bool = False,
                        sample_mode: Optional[str] = None,
                        topk_cap: int = 0):
        """Fused MIXED step — the continuous-batching workhorse: token
        select (device feedback vs host) for column 0 → serve_step over
        (R, chunk) ragged rows (decode rows use one column, prefill rows
        up to ``chunk``; padding sits at the scratch position) →
        per-slot sampling at each row's ``logits_idx``. One program,
        cache donated, sampled tokens stay on device so decode rows AND
        prefill-final rows feed the next step without a host round-trip.
        With ``chunk == 1`` this is exactly the fused decode step (the
        reference's 4-deep batch-future pipeline); larger chunks carry
        chunked prefill in the same dispatch, which is what lets the
        scheduler admit and prefill without ever draining the pipeline.
        ``with_logits`` additionally returns the pre-sampling logits
        (parity tests/debug only — the serving path skips the extra
        output).

        ``sample_mode``/``topk_cap`` (the "sampling" decode fusion,
        serve/sampling.py): a mode-specialized sampling head replaces
        the full-sort reference head — greedy-only decode batches skip
        the (R, V) sorts entirely. None keeps the pre-fusion program
        AND its pre-fusion step key; a set mode tags the key, so each
        head the workload actually needs compiles exactly once."""
        key_id = ("mixed_fused", chunk, with_logits)
        if sample_mode is not None:
            key_id = key_id + (sample_mode, topk_cap)
        if key_id not in self._steps:
            from .sampling import sample_tokens

            fn = self._serve_step_fn(all_logits=False)
            paged = self.paged
            mode = sample_mode or "full"

            def step(params, cache, last_tokens, host_tokens, use_last,
                     positions, logits_idx, key, greedy, temperature,
                     topp, topk, page_table=None):
                first = jnp.where(use_last, last_tokens, host_tokens[:, 0])
                tokens = jnp.concatenate(
                    [first[:, None], host_tokens[:, 1:]], axis=1
                )
                args = (params, cache, tokens, positions, logits_idx,
                        None, None)
                if paged:
                    args = args + (page_table,)
                logits, cache = fn(*args)
                toks = sample_tokens(
                    logits, key,
                    greedy=greedy, temperature=temperature, topp=topp,
                    topk_arr=topk, mode=mode, topk_cap=topk_cap,
                )
                if with_logits:
                    return toks, logits, cache
                return toks, cache

            self._steps[key_id] = self._jit(
                step, key=key_id, donate_argnums=(1,)
            )
        return self._steps[key_id]

    def _serve_whole_fn(self, tiles: int = 1) -> Callable:
        """model.serve_step_whole bound to this engine's static kwargs
        (the whole-step layer walk — serve/kernels.whole_step_decode on
        single-shard meshes, the collective-explicit TP walk
        otherwise). ``tiles`` is the VMEM gate's sub-block tile count
        for the step shape being compiled (1 = untiled walk)."""
        from ..core.mesh import MODEL_AXIS

        tp = self.mesh.shape.get(MODEL_AXIS, 1)
        return functools.partial(
            self.model.serve_step_whole,
            cfg=self.cfg,
            cache_len=self.serving.cache_len,
            kv_quant=self.serving.kv_quant,
            tp_mesh=self.mesh if tp > 1 else None,
            collective=self.collective_mode,
            tiles=tiles,
        )

    @property
    def whole_step_spec_on(self) -> bool:
        """Whether SpecInfer rounds fold into the whole-step walk: the
        draft pass (early-exit ``num_layers`` slice) and the verify
        pass (tree mask + slack-line ``cache_positions`` +
        ``all_logits``) dispatch as two programs of the ONE persistent
        layer walk instead of the per-layer unfused step. Requires the
        untiled single-shard walk — sub-block streaming, context-ring
        and TP meshes keep the unfused spec programs (the fold's
        all-positions epilogue and layer slicing are not composed with
        those walks)."""
        from ..core.mesh import MODEL_AXIS

        return (
            self.whole_step_on
            and self.whole_step_tiles == 1
            and not self.cp_ring
            and self.mesh.shape.get(MODEL_AXIS, 1) == 1
        )

    def _get_tree_whole_step(self, chunk: int):
        """The VERIFY half of the speculation fold
        (:attr:`whole_step_spec_on`): the whole-step walk dispatched
        with the verify round's tree mask, slack-line cache positions
        and the all-positions head twin — same signature as the paged
        :meth:`_get_step`, so :meth:`run` routes verify dispatches here
        transparently. One program per chunk (the spec manager's
        padded tree width), bitwise the unfused verify step because
        the walk runs the same ``_block_paged_xla`` body."""
        key_id = ("whole_step_tree", chunk)
        if key_id not in self._steps:
            wfn = self._serve_whole_fn(1)

            def step(params, cache, tokens, positions, logits_idx,
                     mask, cpos, page_table):
                logits, _gtoks, cache = wfn(
                    params, cache, tokens, positions, logits_idx,
                    page_table, mask=mask, cache_positions=cpos,
                    all_logits=True,
                )
                return logits, cache

            self._steps[key_id] = self._jit(
                step, key=key_id, donate_argnums=(1,)
            )
        return self._steps[key_id]

    def _get_whole_step(self, with_logits: bool, sample_mode: str,
                        topk_cap: int, chunk: int = 1):
        """The whole-step program (fused_decode=("whole_step",)):
        token select (device feedback vs host) → the ONE-program layer
        walk (model.serve_step_whole) → the sampling epilogue.
        ``chunk == 1`` is the decode step; ``chunk > 1`` the whole-step
        MIXED step (chunked prefill + decode in the same walk — the
        columns past the token select ride through like the fused
        mixed step's). Greedy batches take the walk's in-kernel argmax
        head; other modes sample from the walk's logits inside the
        same jitted program — either way ONE dispatched program per
        step, with strictly fewer kernel launches than the per-layer
        path (:func:`program_launch_count` is the measured proxy). The
        step key carries the chunk and the gate's tile count, so each
        (shape, tiling) compiles exactly once."""
        tiles = (self.whole_step_tiles if chunk == 1
                 else self.whole_step_mixed_tiles)
        key_id = ("whole_step", chunk, tiles, sample_mode, topk_cap,
                  with_logits)
        if key_id not in self._steps:
            from .sampling import sample_tokens

            fn = self._serve_whole_fn(tiles)
            mode = sample_mode or "full"

            def step(params, cache, last_tokens, host_tokens, use_last,
                     positions, logits_idx, key, greedy, temperature,
                     topp, topk, page_table=None):
                first = jnp.where(use_last, last_tokens, host_tokens[:, 0])
                if chunk == 1:
                    tokens = first[:, None]
                else:
                    tokens = jnp.concatenate(
                        [first[:, None], host_tokens[:, 1:]], axis=1
                    )
                logits, gtoks, cache = fn(
                    params, cache, tokens, positions, logits_idx,
                    page_table,
                )
                if mode == "greedy":
                    toks = gtoks  # the walk's fused argmax head
                else:
                    toks = sample_tokens(
                        logits, key,
                        greedy=greedy, temperature=temperature, topp=topp,
                        topk_arr=topk, mode=mode, topk_cap=topk_cap,
                    )
                if with_logits:
                    return toks, logits, cache
                return toks, cache

            self._steps[key_id] = self._jit(
                step, key=key_id, donate_argnums=(1,)
            )
        return self._steps[key_id]

    def run_mixed(self, last_tokens, host_tokens, use_last, positions,
                  logits_idx, key, greedy, temperature, topp, topk,
                  with_logits: bool = False):
        """Dispatch one fused mixed step over (R, C) host data; returns
        the sampled tokens as a DEVICE array (R,) — the caller fetches
        them up to ``dispatch_ahead`` steps later. ``with_logits``
        additionally returns the (R, V) logits (device array)."""
        kw = {}
        if self.paged:
            kw["page_table"] = self.page_table_device()
        host_tokens = np.asarray(host_tokens)
        if self.whole_step_on and (
            host_tokens.shape[1] == 1 or self.whole_step_mixed_on
        ):
            # the whole-step megakernel owns the C==1 decode step AND —
            # when the VMEM gate priced the chunked shape — the C>1
            # mixed step; the sampling epilogue is part of the walk's
            # contract
            return self._run_whole(
                last_tokens, host_tokens, use_last, positions,
                logits_idx, key, greedy, temperature, topp, topk,
                with_logits, kw,
            )
        mode, cap = None, 0
        if "sampling" in self.serving.fused_decode:
            from .sampling import choose_sample_mode

            mode, cap = choose_sample_mode(
                greedy, topp, topk, self.cfg.vocab_size
            )
        # every jit-call argument converts with a PINNED dtype: the
        # abstract signature — and so the compile-cache key — must not
        # follow whatever host types the scheduler happened to produce
        # (weak-type/x64 retrace hazard, ffcheck FF103)
        donated = self.cache
        self.count_dispatch("mixed")
        with _set_mesh(self.mesh):
            step = self._get_mixed_step(host_tokens.shape[1], with_logits,
                                        mode, cap)
            out = step(
                self.params,
                self.cache,
                last_tokens,
                jnp.asarray(host_tokens, dtype=jnp.int32),
                jnp.asarray(use_last, dtype=jnp.bool_),
                jnp.asarray(positions, dtype=jnp.int32),
                jnp.asarray(logits_idx, dtype=jnp.int32),
                key,
                jnp.asarray(greedy, dtype=jnp.bool_),
                jnp.asarray(temperature, dtype=jnp.float32),
                jnp.asarray(topp, dtype=jnp.float32),
                jnp.asarray(topk, dtype=jnp.int32),
                **kw,
            )
        if with_logits:
            toks, logits, self.cache = out
            self._poison_donated(
                donated, ("mixed_fused", host_tokens.shape[1], with_logits)
            )
            return toks, logits
        toks, self.cache = out
        self._poison_donated(
            donated, ("mixed_fused", host_tokens.shape[1], with_logits)
        )
        return toks

    def _run_whole(self, last_tokens, host_tokens, use_last, positions,
                   logits_idx, key, greedy, temperature, topp, topk,
                   with_logits, kw):
        """Dispatch ONE whole-step program (run_mixed's route with
        fused_decode=("whole_step",) — the C==1 decode walk, or the
        C>1 mixed walk when the gate enabled it): same argument
        contract, same pinned-dtype conversion, same donation — the
        step key is mode-tagged like the fused sampling head's."""
        from .sampling import choose_sample_mode

        host_tokens = np.asarray(host_tokens)
        chunk = int(host_tokens.shape[1])
        mode, cap = choose_sample_mode(
            greedy, topp, topk, self.cfg.vocab_size
        )
        donated = self.cache
        self.count_dispatch(
            "whole_step" if chunk == 1 else "whole_step_mixed"
        )
        with _set_mesh(self.mesh):
            step = self._get_whole_step(with_logits, mode, cap, chunk)
            out = step(
                self.params,
                self.cache,
                last_tokens,
                jnp.asarray(host_tokens, dtype=jnp.int32),
                jnp.asarray(use_last, dtype=jnp.bool_),
                jnp.asarray(positions, dtype=jnp.int32),
                jnp.asarray(logits_idx, dtype=jnp.int32),
                key,
                jnp.asarray(greedy, dtype=jnp.bool_),
                jnp.asarray(temperature, dtype=jnp.float32),
                jnp.asarray(topp, dtype=jnp.float32),
                jnp.asarray(topk, dtype=jnp.int32),
                **kw,
            )
        if with_logits:
            toks, logits, self.cache = out
            self._poison_donated(donated, ("whole_step", chunk, mode, cap))
            return toks, logits
        toks, self.cache = out
        self._poison_donated(donated, ("whole_step", chunk, mode, cap))
        return toks

    def run_decode(self, last_tokens, host_tokens, use_last, positions,
                   key, greedy, temperature, topp, topk=None):
        """Dispatch one fused decode step (the C == 1 mixed step);
        returns the sampled tokens as a DEVICE array (R,) — the caller
        fetches it a step later."""
        R = self.num_slots
        if topk is None:
            topk = np.zeros((R,), np.int32)
        return self.run_mixed(
            last_tokens, host_tokens, use_last, positions,
            np.zeros((R,), np.int32), key, greedy, temperature, topp, topk,
        )

    def _get_step_sampled(self, chunk: int, with_mask: bool,
                          sample_mode: str, topk_cap: int,
                          with_logits: bool = False):
        """The "sampling"-fused SYNC step (megakernel decode epilogue):
        serve_step plus the mode-specialized decode head in ONE
        compiled program, cache donated — where the unfused sync path
        dispatches two programs per step (the step, then the host-side
        ``sample_tokens``), this dispatches one and keeps the logits on
        device. ``with_logits`` additionally returns them (parity
        tests; the serving path skips the extra output)."""
        key_id = ("step_sampled", chunk, with_mask, sample_mode, topk_cap,
                  with_logits)
        if key_id not in self._steps:
            from .sampling import sample_tokens

            fn = self._serve_step_fn(all_logits=False)
            paged = self.paged

            def step(params, cache, tokens, positions, logits_idx, mask,
                     cpos, key, greedy, temperature, topp, topk,
                     page_table=None):
                args = (params, cache, tokens, positions, logits_idx,
                        mask, cpos)
                if paged:
                    args = args + (page_table,)
                logits, cache = fn(*args)
                toks = sample_tokens(
                    logits, key,
                    greedy=greedy, temperature=temperature, topp=topp,
                    topk_arr=topk, mode=sample_mode, topk_cap=topk_cap,
                )
                if with_logits:
                    return toks, logits, cache
                return toks, cache

            self._steps[key_id] = self._jit(
                step, key=key_id, donate_argnums=(1,)
            )
        return self._steps[key_id]

    def run_sampled(self, bc: BatchConfig, key, greedy, temperature, topp,
                    topk, with_logits: bool = False):
        """Dispatch one step WITH the fused sampling epilogue (the
        ``fused_decode=("sampling",)`` sync path): one program computes
        the step's logits at each row's ``logits_idx`` AND samples
        them, so the (R, V) logits never reach the host. Returns the
        sampled tokens as a device array (R,) — plus the logits when
        ``with_logits``."""
        from .sampling import choose_sample_mode

        if self.serving.inference_debugging:
            with _set_mesh(self.mesh):
                self._dump_debug(bc)
        if (
            self.whole_step_on
            and (bc.chunk == 1 or self.whole_step_mixed_on)
            and bc.mask is None
            and bc.cache_positions is None
        ):
            # sync decode step — or sync chunked-prefill/mixed step
            # when the gate enabled the mixed walk: same whole-step
            # program (and step key) the pipelined path compiles —
            # use_last all-False feeds the host tokens through the
            # same token select
            R = self.num_slots
            kw = {}
            if self.paged:
                kw["page_table"] = self.page_table_device()
            return self._run_whole(
                jnp.zeros((R,), jnp.int32), np.asarray(bc.tokens),
                np.zeros((R,), bool), bc.positions, bc.logits_idx,
                key, greedy, temperature, topp, topk, with_logits, kw,
            )
        mode, cap = choose_sample_mode(
            greedy, topp, topk, self.cfg.vocab_size
        )
        args = (
            jnp.asarray(bc.tokens, dtype=jnp.int32),
            jnp.asarray(bc.positions, dtype=jnp.int32),
            jnp.asarray(bc.logits_idx, dtype=jnp.int32),
            jnp.asarray(bc.mask, dtype=jnp.bool_)
            if bc.mask is not None else None,
            jnp.asarray(bc.cache_positions, dtype=jnp.int32)
            if bc.cache_positions is not None
            else None,
            key,
            jnp.asarray(greedy, dtype=jnp.bool_),
            jnp.asarray(temperature, dtype=jnp.float32),
            jnp.asarray(topp, dtype=jnp.float32),
            jnp.asarray(topk, dtype=jnp.int32),
        )
        kw = {}
        if self.paged:
            kw["page_table"] = self.page_table_device()
        donated = self.cache
        self.count_dispatch("step_sampled")
        with _set_mesh(self.mesh):
            step = self._get_step_sampled(
                bc.chunk, bc.mask is not None, mode, cap, with_logits
            )
            out = step(self.params, self.cache, *args, **kw)
        if with_logits:
            toks, logits, self.cache = out
            self._poison_donated(
                donated, ("step_sampled", bc.chunk, bc.mask is not None)
            )
            return toks, logits
        toks, self.cache = out
        self._poison_donated(
            donated, ("step_sampled", bc.chunk, bc.mask is not None)
        )
        return toks

    def _get_speculate(self, W: int, D: int,
                       num_layers: Optional[int] = None):
        """Whole-tree SSM speculation as ONE compiled program: a scan
        over beam depths, each feeding the W-wide frontier through
        serve_step (tree-mask mode), expanding top-W-of-(W*V) children
        with cumulative logprobs, and writing K/V at the device-computed
        slack lines (prefix + 1 + d*W + w). Replaces the host round-trip
        per depth the reference pays once per beam step too
        (prepare_next_batch_beam); the host fetches the finished tree in
        a single transfer.

        One program per (W, D[, num_layers]) — adaptive tree shaping
        moves requests along a BUCKETED W×D ladder (serve/specinfer.py
        SpecConfig.bucket_ladder), so the key set stays bounded by the
        ladder, never free-form. ``num_layers`` is the self-speculation
        early-exit draft: the frontier expands through a layer-sliced
        step over THIS engine's own params + cache.

        With :attr:`whole_step_spec_on` the per-depth expansion runs
        the whole-step walk (early-exit slice + all-positions head +
        tree mask + slack lines) — the DRAFT half of the speculation
        fold: the draft becomes the first ``num_layers`` grid steps of
        the same persistent program the verify pass dispatches, bitwise
        the unfused spec round (shared ``_block_paged_xla`` body)."""
        key_id = ("speculate", W, D)
        if num_layers is not None:
            key_id = key_id + (int(num_layers),)
        whole = self.whole_step_spec_on
        if whole:
            key_id = key_id + ("whole_step",)
        if key_id not in self._steps:
            if whole:
                wfn = self._serve_whole_fn(1)

                def fn(params, cache, tokens, positions, logits_idx,
                       mask, cpos, page_table):
                    logits, _gtoks, cache = wfn(
                        params, cache, tokens, positions, logits_idx,
                        page_table, mask=mask, cache_positions=cpos,
                        all_logits=True, num_layers=num_layers,
                    )
                    return logits, cache
            else:
                fn = self._serve_step_fn(all_logits=True,
                                         num_layers=num_layers)
            from .sampling import log_softmax

            R = self.num_slots
            S1 = self.serving.cache_len + 1
            scratch = self.scratch_pos
            NEG = -1e30

            paged = self.paged

            def speculate(params, cache, root_tokens, prefix, active,
                          page_table=None):
                key_pos = jnp.arange(S1, dtype=jnp.int32)
                # frontier state, beam dim = W; only w0 live at depth 0
                w_iota = jnp.arange(W, dtype=jnp.int32)
                f_tok = jnp.where(
                    (w_iota == 0)[None, :], root_tokens[:, None], 0
                ).astype(jnp.int32)
                f_valid = (w_iota == 0)[None, :] & active[:, None]
                f_cum = jnp.where(f_valid, 0.0, NEG).astype(jnp.float32)
                f_line = jnp.where(
                    f_valid, prefix[:, None], scratch
                ).astype(jnp.int32)
                committed = key_pos[None, :] < prefix[:, None]  # (R, S1)
                f_mask = (
                    committed[:, None, :]
                    | (key_pos[None, None, :] == f_line[:, :, None])
                ) & f_valid[:, :, None]

                def body(carry, d):
                    cache, f_tok, f_cum, f_valid, f_mask, f_line = carry
                    pos = jnp.where(
                        f_valid, prefix[:, None] + d, scratch
                    ).astype(jnp.int32)
                    args = (params, cache, f_tok, pos,
                            jnp.zeros((R,), jnp.int32), f_mask, f_line)
                    if paged:
                        args = args + (page_table,)
                    logits, cache = fn(*args)  # (R, W, V)
                    V = logits.shape[-1]
                    logp = log_softmax(logits) + f_cum[:, :, None]
                    logp = jnp.where(f_valid[:, :, None], logp, NEG)
                    vals, flat = jax.lax.top_k(logp.reshape(R, W * V), W)
                    parent = (flat // V).astype(jnp.int32)
                    token = (flat % V).astype(jnp.int32)
                    child_valid = (vals > NEG / 2) & active[:, None]
                    new_line = jnp.where(
                        child_valid,
                        prefix[:, None] + 1 + d * W + w_iota[None, :],
                        scratch,
                    ).astype(jnp.int32)
                    parent_mask = jnp.take_along_axis(
                        f_mask, parent[:, :, None], axis=1
                    )
                    new_mask = (
                        parent_mask
                        | (key_pos[None, None, :] == new_line[:, :, None])
                    ) & child_valid[:, :, None]
                    carry = (cache, token, vals, child_valid, new_mask, new_line)
                    return carry, (token, parent, vals)

                init = (cache, f_tok, f_cum, f_valid, f_mask, f_line)
                (cache, *_), (toks, parents, logps) = jax.lax.scan(
                    body, init, jnp.arange(D, dtype=jnp.int32)
                )
                return toks, parents, logps, cache  # each (D, R, W)

            self._steps[key_id] = self._jit(
                speculate, key=key_id, donate_argnums=(1,)
            )
        return self._steps[key_id]

    def run_speculate(self, root_tokens, prefix, active, W: int, D: int,
                      num_layers: Optional[int] = None):
        """Dispatch one whole speculation round; returns device arrays
        (tokens, parents, logps) each (D, R, W). The cache advances in
        place with every tree node's K/V at its slack line.
        ``num_layers`` drafts through the layer-sliced early-exit step
        (self-speculation: this engine doubles as its own SSM)."""
        kw = {}
        if self.paged:
            kw["page_table"] = self.page_table_device()
        donated = self.cache
        self.count_dispatch("speculate")
        with _set_mesh(self.mesh):
            step = self._get_speculate(W, D, num_layers)
            toks, parents, logps, self.cache = step(
                self.params,
                self.cache,
                jnp.asarray(root_tokens, jnp.int32),
                jnp.asarray(prefix, jnp.int32),
                jnp.asarray(active, dtype=jnp.bool_),
                **kw,
            )
        self._poison_donated(donated, ("speculate", W, D, num_layers))
        return toks, parents, logps

    def _dump_debug(self, bc: BatchConfig):
        """inference_debugging: eager per-layer forward on the CURRENT
        cache (read-only — must run before the donating step), each
        layer's hidden states to .npy (reference per-op tensor dumps)."""
        import os

        fn = getattr(self.model, "serve_debug_activations", None)
        if fn is None:
            # loud skip, never a silent no-op (ADVICE.md round 5): the
            # family module lacks the hook, so nothing can be dumped —
            # warn once and keep serving at full speed (the
            # RequestManager only downgrades fast decode when the hook
            # exists, request_manager.py).
            if not getattr(self, "_warned_no_debug_hook", False):
                from ..logging_utils import get_logger

                get_logger("serve").warning(
                    "inference_debugging is enabled but %s has no "
                    "serve_debug_activations hook — nothing will be "
                    "dumped for this engine",
                    getattr(self.model, "__name__", repr(self.model)),
                )
                self._warned_no_debug_hook = True
            return
        # per-engine subdirectory: a SpecInfer pair (LLM + SSM engines)
        # shares the dump dir, and both counters start at 0 — same-named
        # files would silently overwrite across engines
        outdir = os.path.join(
            self.serving.inference_debugging,
            f"{self.model.__name__.rsplit('.', 1)[-1]}-"
            f"L{self.cfg.num_hidden_layers}-{id(self) & 0xFFFF:04x}",
        )
        os.makedirs(outdir, exist_ok=True)
        kw = dict(cfg=self.cfg, kernels=self.serving.kernels)
        if self.paged:
            kw["page_table"] = self.page_table_device()
            kw["cache_len"] = self.serving.cache_len
            if self.serving.kv_quant is not None:
                kw["kv_quant"] = self.serving.kv_quant
        acts = fn(
            self.params, self.cache, jnp.asarray(bc.tokens, dtype=jnp.int32),
            jnp.asarray(bc.positions, dtype=jnp.int32),
            jnp.asarray(bc.mask, dtype=jnp.bool_)
            if bc.mask is not None else None,
            jnp.asarray(bc.cache_positions, dtype=jnp.int32)
            if bc.cache_positions is not None else None,
            **kw,
        )
        step = self._debug_step
        np.save(os.path.join(outdir, f"step{step:05d}_tokens.npy"),
                np.asarray(bc.tokens))
        np.save(os.path.join(outdir, f"step{step:05d}_positions.npy"),
                np.asarray(bc.positions))
        for l, h in enumerate(acts):
            np.save(
                os.path.join(outdir, f"step{step:05d}_layer{l:03d}.npy"),
                # ffcheck: disable=FF107 -- inference_debugging triage dump: deliberately slow, forced off the fast path by the RequestManager
                np.asarray(jax.device_get(h)),
            )
        self._debug_step += 1

    def run(self, bc: BatchConfig, all_logits: bool = False):
        """Dispatch one step (reference ``InferenceManager::inference``,
        inference_manager.cc:334). Returns logits on device; the cache is
        advanced in place (donated)."""
        if self.serving.inference_debugging:
            with _set_mesh(self.mesh):
                self._dump_debug(bc)
        args = (
            jnp.asarray(bc.tokens, dtype=jnp.int32),
            jnp.asarray(bc.positions, dtype=jnp.int32),
            jnp.asarray(bc.logits_idx, dtype=jnp.int32),
            jnp.asarray(bc.mask, dtype=jnp.bool_)
            if bc.mask is not None else None,
            jnp.asarray(bc.cache_positions, dtype=jnp.int32)
            if bc.cache_positions is not None
            else None,
        )
        if self.paged:
            # the engine's own table is authoritative (a SpecInfer pair
            # shares one BatchConfig across engines whose pools differ);
            # bc.page_table is carried as host-side metadata
            args = args + (self.page_table_device(),)
        donated = self.cache
        self.count_dispatch("step")
        # the speculation fold's verify half: tree-masked all-logits
        # dispatches ride the whole-step walk when the engine runs it
        # (whole_step_spec_on) — same signature, one persistent program
        fold_verify = (
            all_logits and bc.mask is not None
            and bc.cache_positions is not None and self.whole_step_spec_on
        )
        with _set_mesh(self.mesh):
            step = (
                self._get_tree_whole_step(bc.chunk) if fold_verify
                else self._get_step(bc.chunk, all_logits,
                                    bc.mask is not None)
            )
            logits, self.cache = step(self.params, self.cache, *args)
        self._poison_donated(
            donated,
            ("whole_step_tree", bc.chunk) if fold_verify
            else (bc.chunk, all_logits, bc.mask is not None),
        )
        return logits

    def copy_page(self, src: int, dst: int):
        """Device-side copy of one physical page's K/V lines across all
        layers (prefix-cache copy-on-write, serve/prefix_cache.py:
        a request that must append into a SHARED cached tail page gets a
        private copy first). One jitted program, page ids traced — the
        compile is paid once."""
        if "copy_page" not in self._steps:
            self._steps["copy_page"] = self._jit(
                self.model.copy_page_kv, key="copy_page",
                donate_argnums=(0,),
            )
        donated = self.cache
        self.count_dispatch("copy_page")
        with _set_mesh(self.mesh):
            self.cache = self._steps["copy_page"](
                self.cache,
                jnp.asarray(src, jnp.int32),
                jnp.asarray(dst, jnp.int32),
            )
        self._poison_donated(donated, "copy_page")

    def fetch_page(self, page: int):
        """Device→host SPILL read of one physical page (hierarchical KV
        cache, serve/prefix_cache.py host tier): one jitted program
        slices the page's content out of every cache buffer —
        K/V codes, quantized scale rows, the generic decoder's position
        lines — and an ASYNC host copy starts on each slice. Returns
        the slice pytree immediately; the caller converts to host
        arrays later (PrefixCache.harvest, at the scheduler's existing
        flush sync point), so a spill never stalls a decode step
        (ffcheck FF107 is the lint guard for that contract). The slice
        buffers are data-independent of the pool from the moment the
        program is enqueued, so freeing and reusing the page cannot
        corrupt the copy."""
        if "fetch_page" not in self._steps:
            self._steps["fetch_page"] = self._jit(
                self.model.gather_page_kv, key="fetch_page"
            )
        self.count_dispatch("fetch_page")
        with _set_mesh(self.mesh):
            out = self._steps["fetch_page"](
                self.cache, jnp.asarray(page, jnp.int32)
            )
        for leaf in jax.tree.leaves(out):
            leaf.copy_to_host_async()
        return out

    def upload_page(self, page: int, values) -> None:
        """Host→device RE-ADMIT of a previously spilled page: one
        jitted program (cache donated) writes the spilled content back
        into pool row ``page``. ``values`` is whatever
        :meth:`fetch_page` returned — harvested numpy arrays, or the
        original device slices if the spill was never harvested (the
        transfer then stays device-side). ``jax.device_put`` semantics
        are async: the upload overlaps the host loop and orders before
        the prefill step that reads the page."""
        if "upload_page" not in self._steps:
            self._steps["upload_page"] = self._jit(
                self.model.scatter_page_kv, key="upload_page",
                donate_argnums=(0,),
            )
        dtypes = {k: v.dtype for k, v in self.cache.items()}
        donated = self.cache
        self.count_dispatch("upload_page")
        with _set_mesh(self.mesh):
            self.cache = self._steps["upload_page"](
                self.cache,
                jnp.asarray(page, jnp.int32),
                {
                    k: jnp.asarray(v, dtype=dtypes[k])
                    for k, v in values.items()
                },
            )
        self._poison_donated(donated, "upload_page")

    def page_host_bytes(self) -> int:
        """Host bytes one spilled page occupies (every cache buffer's
        per-page slice) — prices the ``host_cache_bytes`` budget."""
        shapes = jax.eval_shape(
            self.model.gather_page_kv,
            jax.tree.map(
                lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype),
                self.cache,
            ),
            jax.ShapeDtypeStruct((), jnp.int32),
        )
        return sum(
            int(np.prod(leaf.shape)) * jnp.dtype(leaf.dtype).itemsize
            for leaf in jax.tree.leaves(shapes)
        )

    def reorder(self, src_slots: np.ndarray):
        """Slot permutation/gather of the whole cache (beam search
        hypothesis reordering): new slot r holds old slot src_slots[r].
        Paged layout: page ownership stays put, page CONTENT is copied
        through the table (model.reorder_slots_paged)."""
        if "reorder" not in self._steps:
            if self.paged:
                self._steps["reorder"] = self._jit(
                    self.model.reorder_slots_paged, key="reorder",
                    donate_argnums=(0,),
                )
            else:
                self._steps["reorder"] = self._jit(
                    self.model.reorder_slots, key="reorder",
                    donate_argnums=(0,),
                )
        donated = self.cache
        self.count_dispatch("reorder")
        with _set_mesh(self.mesh):
            if self.paged:
                self.cache = self._steps["reorder"](
                    self.cache, self.page_table_device(),
                    jnp.asarray(src_slots, jnp.int32),
                )
            else:
                self.cache = self._steps["reorder"](
                    self.cache, jnp.asarray(src_slots, jnp.int32)
                )
        self._poison_donated(donated, "reorder")

    def commit(self, src: np.ndarray, dst: np.ndarray):
        """Move accepted speculative cache lines to committed positions
        (src/dst (R, K); unused entries scratch→scratch)."""
        if self._commit is None:
            if self.paged:
                fn = self.model.commit_kv_paged
                if self.serving.kv_quant is not None:
                    # quantized pools dequant/requant moved lines at the
                    # page scales (models/*.commit_kv_paged)
                    fn = functools.partial(
                        fn, kv_quant=self.serving.kv_quant
                    )
            else:
                fn = self.model.commit_kv
            self._commit = self._jit(fn, key="commit", donate_argnums=(0,))
        donated = self.cache
        self.count_dispatch("commit")
        with _set_mesh(self.mesh):
            if self.paged:
                self.cache = self._commit(
                    self.cache, self.page_table_device(),
                    jnp.asarray(src, dtype=jnp.int32),
                    jnp.asarray(dst, dtype=jnp.int32),
                )
            else:
                self.cache = self._commit(
                    self.cache, jnp.asarray(src, dtype=jnp.int32),
                    jnp.asarray(dst, dtype=jnp.int32),
                )
        self._poison_donated(donated, "commit")

    def reset(self):
        """Drop all cached sequences (fresh KV cache; paged: fresh
        allocator — all pages back on the free list). Any PrefixCache
        built over the old allocator is invalidated with it — managers
        are expected to be rebuilt alongside an engine reset."""
        self.cache = self._alloc_cache()


def program_launch_count(fn, *args, **kwargs) -> int:
    """Structural kernel-launch proxy of one step program: ``fn`` is
    traced to a jaxpr and its equations counted recursively — each
    primitive equation is one launch-site execution, ``scan`` bodies
    multiply by their trip count, call-like primitives (pjit /
    shard_map / custom calls / remat) recurse into their subjaxprs,
    ``cond`` counts its largest branch. Not an HLO kernel count (XLA
    fuses elementwise chains), but a faithful ORDER comparison: the
    PR-6 fused decode step executes O(L) launch sites (one scan
    iteration per layer, each with its projections, Pallas kernel and
    MLP), the whole-step walk O(1) — ONE pallas_call whose grid walks
    the layers. bench serve_megakernel and tests/test_whole_step.py
    assert the strict inequality on this measure."""
    jaxpr = jax.make_jaxpr(fn)(*args, **kwargs).jaxpr

    def count(jx, mult: int) -> int:
        total = 0
        for eqn in jx.eqns:
            name = eqn.primitive.name
            if name == "scan":
                total += count(
                    eqn.params["jaxpr"].jaxpr,
                    mult * int(eqn.params["length"]),
                )
            elif name == "while":
                total += count(eqn.params["cond_jaxpr"].jaxpr, mult)
                total += count(eqn.params["body_jaxpr"].jaxpr, mult)
            elif name == "cond":
                total += max(
                    count(b.jaxpr, mult) for b in eqn.params["branches"]
                )
            else:
                sub = None
                for k in ("jaxpr", "call_jaxpr"):
                    if k in eqn.params:
                        sub = eqn.params[k]
                        break
                if sub is not None:
                    total += count(getattr(sub, "jaxpr", sub), mult)
                else:
                    total += mult
        return total

    return count(jaxpr, 1)
