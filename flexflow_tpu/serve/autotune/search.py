"""Offline ServingConfig search (ROADMAP item 2a).

The serving twin of ``search/unity.py``: enumerate the candidate space
with hard pruning (chip budget, HBM feasibility), score every survivor
through the analytical cost model, pick by feasible-beats-infeasible
keying, then coordinate-descent refine the winner — re-optimizing one
axis at a time holding the rest (the backtracking flavor unity uses
where axes interact: TP trades against replicas under a chip budget,
page_size against kv_quant under a page budget, speculation against
batch under the verify tax). The emitted candidate lowers to a
ready-to-run ServingConfig that ``validate_cluster`` accepts —
asserted by the search itself before returning, the same
fail-before-emit discipline the engine applies at construction.

SLOs are CONSTRAINTS, not weights: a candidate whose predicted TTFT/
TPOT p99 breaches the SLO is infeasible however fast it is, exactly
like unity's memory-budget λ treatment. Predicted-vs-measured is
validated in bench (``serve_autotune`` phase) the way
``unity_searched_train_mfu`` validates the training search — by rank
correlation on this box, absolute error on a chip.
"""
from __future__ import annotations

import dataclasses
from typing import List, Optional, Tuple

from .cost_model import (
    ModelGeometry,
    ServingCandidate,
    ServingCostModel,
    ServingPrediction,
    TrafficProfile,
)

__all__ = ["ServingSearchReport", "search_serving_config"]


@dataclasses.dataclass
class ServingSearchReport:
    """What the search did — mirrors unity's SearchReport shape."""

    evaluated: int = 0
    pruned: int = 0
    refined_moves: int = 0
    best: Optional[ServingCandidate] = None
    prediction: Optional[ServingPrediction] = None
    #: (candidate, prediction) leaderboard, best first, for bench tables
    table: List[Tuple[ServingCandidate, ServingPrediction]] = (
        dataclasses.field(default_factory=list)
    )

    def summary(self) -> str:
        if self.best is None:
            return "serving search: no feasible candidate"
        p = self.prediction
        return (
            f"serving search: {self.evaluated} evaluated / "
            f"{self.pruned} pruned / {self.refined_moves} refine moves — "
            f"best tp={self.best.tp} pp={self.best.pp} "
            f"replicas={self.best.replicas} page={self.best.page_size} "
            f"kv={self.best.kv_quant or 'fp'} "
            f"spec={'on' if self.best.speculation else 'off'} "
            f"→ {p.tokens_per_s:.0f} tok/s "
            f"(ttft_p99={p.ttft_s_p99 * 1e3:.1f} ms, "
            f"tpot_p99={p.tpot_s_p99 * 1e3:.2f} ms)"
        )


def _pow2s(limit: int) -> List[int]:
    out, v = [], 1
    while v <= limit:
        out.append(v)
        v *= 2
    return out


def _slo_ok(pred: ServingPrediction, slo_ttft_s: Optional[float],
            slo_tpot_s: Optional[float]) -> bool:
    if slo_ttft_s is not None and pred.ttft_s_p99 > slo_ttft_s:
        return False
    if slo_tpot_s is not None and pred.tpot_s_p99 > slo_tpot_s:
        return False
    return True


def _key(pred: ServingPrediction, slo_ttft_s, slo_tpot_s):
    """Feasible-beats-infeasible, then throughput (higher better),
    then latency as the tie-break — unity's keying transposed to a
    maximization."""
    ok = pred.feasible and _slo_ok(pred, slo_ttft_s, slo_tpot_s)
    return (not ok, -pred.tokens_per_s, pred.ttft_s_p99)


def search_serving_config(
    geometry: ModelGeometry,
    traffic: TrafficProfile,
    *,
    chip_budget: int = 8,
    slo_ttft_s: Optional[float] = None,
    slo_tpot_s: Optional[float] = None,
    cost_model: Optional[ServingCostModel] = None,
    max_requests_per_batch: int = 16,
    max_sequence_length: int = 2048,
    allow_disagg: bool = True,
    top_k: int = 8,
) -> Tuple[Optional[ServingCandidate], ServingSearchReport]:
    """Search the serving shape space for ``geometry`` under
    ``traffic``, maximizing predicted tokens/sec subject to the SLOs,
    over at most ``chip_budget`` chips. Returns ``(best, report)`` —
    ``best`` is None only when nothing fits (report says why via the
    leaderboard's infeasibility reasons)."""
    cm = cost_model or ServingCostModel(geometry)
    report = ServingSearchReport()
    scored: List[Tuple[ServingCandidate, ServingPrediction]] = []

    # ---- phase 1: pruned enumeration --------------------------------
    weight_gb = geometry.weight_bytes() / cm.chip.hbm_capacity
    for tp in _pow2s(chip_budget):
        # hard prune: sharded weights alone must leave KV headroom
        if weight_gb / tp > 0.9:
            report.pruned += 1
            continue
        for pp in _pow2s(chip_budget // tp):
            for replicas in range(1, chip_budget // (tp * pp) + 1):
                for page_size in (16, 64, 128, 256):
                    for kv_quant in (None, "int8", "int4"):
                        for spec in (
                            (False, True) if traffic.spec_accept_rate > 0
                            else (False,)
                        ):
                            splits = [(0, 0)]
                            if allow_disagg and replicas >= 3:
                                splits.append((1, replicas - 1))
                            for pf, dc in splits:
                                if spec and pf:
                                    # SpecInfer × disagg pools is
                                    # rejected by validate_cluster —
                                    # never emit it
                                    report.pruned += 1
                                    continue
                                cand = ServingCandidate(
                                    tp=tp, pp=pp, replicas=replicas,
                                    page_size=page_size,
                                    kv_quant=kv_quant,
                                    prefill_replicas=pf,
                                    decode_replicas=dc,
                                    speculation=spec,
                                    max_requests_per_batch=(
                                        max_requests_per_batch
                                    ),
                                    max_sequence_length=(
                                        max_sequence_length
                                    ),
                                )
                                pred = cm.predict(cand, traffic)
                                report.evaluated += 1
                                scored.append((cand, pred))

    if not scored:
        return None, report
    scored.sort(key=lambda cp: _key(cp[1], slo_ttft_s, slo_tpot_s))
    report.table = scored[:top_k]
    best, best_pred = scored[0]
    if _key(best_pred, slo_ttft_s, slo_tpot_s)[0]:
        # even the leader is infeasible — report it, emit nothing
        report.best, report.prediction = None, best_pred
        return None, report

    # ---- phase 2: coordinate-descent refinement of the winner -------
    # (the unity backtracking flavor: one axis at a time, keep a move
    # only if it strictly improves the key, loop until a full sweep
    # makes no move)
    axes = ("tp", "pp", "replicas", "page_size", "kv_quant",
            "speculation", "whole_step")
    moved = True
    while moved:
        moved = False
        for axis in axes:
            for value in _axis_values(axis, best, chip_budget, traffic):
                cand = dataclasses.replace(best, **{axis: value})
                if cand.chips > chip_budget:
                    continue
                pred = cm.predict(cand, traffic)
                report.evaluated += 1
                if (_key(pred, slo_ttft_s, slo_tpot_s)
                        < _key(best_pred, slo_ttft_s, slo_tpot_s)):
                    best, best_pred = cand, pred
                    report.refined_moves += 1
                    moved = True

    # fail-before-emit: the winning candidate must lower to a config
    # the cluster will actually accept
    best.to_serving_config().validate_cluster()
    report.best, report.prediction = best, best_pred
    return best, report


def _axis_values(axis: str, cur: ServingCandidate, chip_budget: int,
                 traffic: TrafficProfile):
    if axis == "tp":
        return [v for v in _pow2s(chip_budget) if v != cur.tp]
    if axis == "pp":
        return [v for v in _pow2s(chip_budget) if v != cur.pp]
    if axis == "replicas":
        vals = {max(1, cur.replicas - 1), cur.replicas + 1}
        return [v for v in sorted(vals) if v != cur.replicas]
    if axis == "page_size":
        return [v for v in (16, 64, 128, 256) if v != cur.page_size]
    if axis == "kv_quant":
        return [v for v in (None, "int8", "int4") if v != cur.kv_quant]
    if axis == "speculation":
        if traffic.spec_accept_rate <= 0 or cur.prefill_replicas:
            return []
        return [not cur.speculation]
    if axis == "whole_step":
        return [not cur.whole_step]
    return []
