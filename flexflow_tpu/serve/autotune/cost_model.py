"""Analytical serving cost model (ROADMAP item 2a).

The training search (``search/unity.py``) already has what the paper
calls the simulator: per-op rooflines + ring-collective formulas from
``search/machine_model.py`` with predicted-vs-measured validation in
bench. This module is the SERVING counterpart: the same chip model,
priced over the serving-specific kernel regimes the repo actually
ships —

* **decode** is bandwidth-bound weight + KV streaming: every decode
  step reads the full (TP-sharded) weight set once plus every live
  request's KV context (fp / int8 / int4 pages), so step time is
  ``max(flops, bytes)`` through :func:`~..search.machine_model
  .compute_time` with bytes dominating at serving batch sizes. The
  whole-step megakernel (PR 15/16) collapses per-layer dispatch
  overhead to one program; the unfused path pays a per-layer launch
  tax.
* **prefill** is compute-bound: ``2·params`` FLOPs per uncached prompt
  token (prefix caching removes the cached share), chunked at
  ``prefill_chunk``.
* **TP collectives** go through :class:`~..search.machine_model
  .CollectiveModel` ring formulas over the topology's link degrees —
  two all-reduces of the batch's activations per layer, with the
  EQuARX-style int8 reduce (``quantized_allreduce``) shipping ~27% of
  the f32 bytes.
* **speculation** multiplies committed tokens per verify step by the
  expected accepted path length (a geometric series in the accept
  rate over the bucket ladder's depth), while the verify step prices
  the whole tree's rows.

Queueing is a deterministic M/D/c-flavored approximation over
Little's-law concurrency — good enough to RANK configurations, which
is all the offline search and the online autoscaler consume. On this
CPU box the absolute numbers are fiction (the chip constants describe
a TPU); predictions are ranked, not absolute, off-chip — the README
design note and the bench ``serve_autotune`` phase (rank correlation,
not error bars) both carry that caveat. :func:`~..search.machine_model
.calibrate_chip` substitutes host-measured constants where absolute
numbers matter.
"""
from __future__ import annotations

import dataclasses
import math
from typing import Any, Optional, Tuple

from ...search.machine_model import (
    CollectiveModel,
    TPUChip,
    TPUTopology,
    compute_time,
)

__all__ = [
    "ModelGeometry",
    "ServingCandidate",
    "ServingCostModel",
    "ServingPrediction",
    "TrafficProfile",
]

#: Effective KV bytes per stored element by quantization mode, relative
#: to a 2-byte cache dtype: int8 pages carry 1-byte codes + per-page
#: per-KV-head f32 amax scales (measured >=1.9x pages per budget,
#: serve/kv_quant.py), int4 packs two codes per byte (>=3.8x).
_KV_QUANT_BYTES = {None: 2.0, "int8": 1.05, "int4": 0.53}

#: Host-side dispatch overhead per launched program (s). The unfused
#: decode step launches ~2 programs per layer; the whole-step
#: megakernel launches ONE per step — this constant is what makes the
#: cost model reproduce the PR-15/16 fusion win.
_DISPATCH_S = 8e-6

#: Dequantization arithmetic per quantized KV byte read (FLOPs): the
#: fused Pallas kernel dequantizes in VMEM nearly for free on a TPU's
#: flops-rich roofline, but on a flops-poor (CPU-calibrated) chip the
#: same term correctly prices quantized pools SLOWER — matching what
#: the XLA fallback path measures off-chip.
_DEQUANT_FLOPS_PER_BYTE = 8.0


@dataclasses.dataclass(frozen=True)
class TrafficProfile:
    """What the cluster is being asked to serve — the cost model's
    second input (fit online by :class:`~.workload.TrafficEstimator`,
    or written down for offline search). Lengths are tokens; the
    arrival rate is requests/second (the estimator converts its
    per-step rate with an explicit step-time, keeping the profile
    itself wall-clock-free)."""

    arrival_rate_rps: float = 1.0
    prompt_len_p50: float = 128.0
    prompt_len_p99: float = 512.0
    output_len_p50: float = 128.0
    output_len_p99: float = 512.0
    #: fraction of prompt tokens served from the prefix cache (hit
    #: tokens / prompt tokens) — removes prefill compute, not KV reads
    prefix_share: float = 0.0
    #: accepted drafted tokens per drafted token (0 = no speculation
    #: signal; the spec pricing treats it as the per-level acceptance)
    spec_accept_rate: float = 0.0
    #: MEASURED drafted-accept rate from a live verify ladder
    #: (SchedulerStats.spec_accept_rate, or the spec_distill eval
    #: harness) — when set, the speculation term prices with this
    #: instead of the ``spec_accept_rate`` prior. None = no measurement.
    measured_accept_rate: Optional[float] = None

    @property
    def prompt_len_mean(self) -> float:
        return 0.7 * self.prompt_len_p50 + 0.3 * self.prompt_len_p99

    @property
    def output_len_mean(self) -> float:
        return 0.7 * self.output_len_p50 + 0.3 * self.output_len_p99


@dataclasses.dataclass(frozen=True)
class ModelGeometry:
    """The model shape the cost model prices — derivable from any
    LLaMA-flavored config object via :meth:`from_model_config`."""

    hidden_size: int
    num_layers: int
    num_heads: int
    num_kv_heads: int
    intermediate_size: int
    vocab_size: int
    param_bytes: float = 2.0       # bytes per weight (bf16)

    @classmethod
    def from_model_config(cls, cfg: Any) -> "ModelGeometry":
        """Read the standard family config attributes (``hidden_size``,
        ``num_hidden_layers``, ...) — the same duck-typed surface the
        engine itself consumes."""
        return cls(
            hidden_size=int(cfg.hidden_size),
            num_layers=int(cfg.num_hidden_layers),
            num_heads=int(cfg.num_attention_heads),
            num_kv_heads=int(
                getattr(cfg, "num_key_value_heads", None)
                or cfg.num_attention_heads
            ),
            intermediate_size=int(cfg.intermediate_size),
            vocab_size=int(cfg.vocab_size),
        )

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads

    def param_count(self) -> float:
        """Dense parameter count: embeddings + per-layer QKV/O + MLP
        (gate/up/down) + the untied LM head."""
        h, kv = self.hidden_size, self.num_kv_heads * self.head_dim
        per_layer = (
            h * h + 2 * h * kv + h * h          # Q, K, V, O
            + 3 * h * self.intermediate_size    # gate, up, down
        )
        return (
            self.num_layers * per_layer + 2 * self.vocab_size * h
        )

    def weight_bytes(self) -> float:
        return self.param_count() * self.param_bytes

    def kv_bytes_per_token(self, kv_quant: Optional[str]) -> float:
        """HBM bytes one token's K+V occupy across all layers."""
        per_elem = _KV_QUANT_BYTES[kv_quant]
        return (
            2.0 * self.num_layers * self.num_kv_heads
            * self.head_dim * per_elem
        )


@dataclasses.dataclass(frozen=True)
class ServingCandidate:
    """One point in the serving search space — the knobs PRs 1–17 left
    hand-tuned. ``to_serving_config`` lowers it to a ready-to-run
    :class:`~..engine.ServingConfig` (TP×PP live outside ServingConfig
    — they are mesh facts the engine derives at build — so the
    candidate carries them alongside)."""

    tp: int = 1
    pp: int = 1
    replicas: int = 1
    page_size: int = 128
    kv_quant: Optional[str] = None
    prefill_replicas: int = 0
    decode_replicas: int = 0
    speculation: bool = False
    #: W×D ladder top rung the speculative arm drafts at
    spec_width: int = 2
    spec_depth: int = 4
    whole_step: bool = True
    quantized_allreduce: Optional[str] = None
    max_requests_per_batch: int = 16
    max_sequence_length: int = 2048
    prefill_chunk: int = 128

    @property
    def chips(self) -> int:
        """Chips the whole candidate occupies."""
        return self.tp * self.pp * self.replicas

    def to_serving_config(self, base: Any = None, **overrides) -> Any:
        """Lower to a :class:`~..engine.ServingConfig` (cluster fields
        validated by the caller running ``validate_cluster`` — the
        search does it before emitting). ``base`` seeds non-searched
        fields (cache dtype, transport, journal, ...)."""
        import dataclasses as _dc

        from ..engine import ServingConfig

        fused = ("whole_step",) if self.whole_step else ()
        kw = dict(
            max_requests_per_batch=self.max_requests_per_batch,
            max_sequence_length=self.max_sequence_length,
            prefill_chunk=self.prefill_chunk,
            kv_layout="paged",
            page_size=self.page_size,
            kv_quant=self.kv_quant,
            replicas=self.replicas,
            prefill_replicas=self.prefill_replicas,
            decode_replicas=self.decode_replicas,
            fused_decode=fused,
            quantized_allreduce=(
                self.quantized_allreduce if self.whole_step and self.tp > 1
                else None
            ),
        )
        kw.update(overrides)
        if base is not None:
            return _dc.replace(base, **kw)
        return ServingConfig(**kw)


@dataclasses.dataclass(frozen=True)
class ServingPrediction:
    """What the cost model predicts for one (candidate, traffic) pair.
    ``tokens_per_s`` is ACHIEVED throughput (offered load capped by
    capacity); ``capacity_tokens_per_s`` is the saturated ceiling —
    both monotone in ``replicas`` by construction."""

    tokens_per_s: float
    capacity_tokens_per_s: float
    ttft_s_p50: float
    ttft_s_p99: float
    tpot_s_p50: float
    tpot_s_p99: float
    queue_delay_s: float
    decode_step_s: float
    #: HBM bytes one chip holds (sharded weights + its KV pool share)
    hbm_bytes_per_chip: float
    hbm_fill: float
    #: pages the (quantization-scaled) pool budget affords per replica
    kv_pages_capacity: int
    #: pages the steady-state working set needs per replica
    kv_pages_needed: int
    page_fill: float
    feasible: bool
    reason: str = ""


class ServingCostModel:
    """Prices :class:`ServingCandidate` × :class:`TrafficProfile` on a
    chip roofline. Stateless between calls — the autoscaler re-predicts
    every evaluation window with the live profile."""

    def __init__(
        self,
        geometry: ModelGeometry,
        chip: Optional[TPUChip] = None,
        topo: Optional[TPUTopology] = None,
    ):
        self.geometry = geometry
        self.chip = chip or TPUChip.v5e()
        self.topo = topo or TPUTopology(chip=self.chip)
        self.collectives = CollectiveModel(self.topo)

    # -- decode ------------------------------------------------------

    def _decode_step_s(
        self,
        cand: ServingCandidate,
        batch: float,
        context_len: float,
        *,
        tree_tokens: float = 1.0,
        oversubscription: float = 1.0,
    ) -> float:
        """One decode (or tree-verify) step's wall time per pipeline
        stage at ``batch`` live rows with ``context_len`` tokens of KV
        each. ``oversubscription > 1`` divides the chip between that
        many co-resident replicas — the CPU-box reality where every
        in-process replica time-slices one device (bench calibrates
        and sets it; dedicated chips leave it at 1)."""
        g = self.geometry
        shards = cand.tp * cand.pp
        rows = batch * tree_tokens
        flops = 2.0 * g.param_count() * rows / shards
        kv_bytes = (
            batch * context_len * g.kv_bytes_per_token(cand.kv_quant)
            / shards
        )
        if cand.kv_quant is not None:
            flops += kv_bytes * _DEQUANT_FLOPS_PER_BYTE
        bytes_moved = g.weight_bytes() / shards + kv_bytes
        chip = self._scaled_chip(oversubscription)
        t = compute_time(chip, flops, bytes_moved)
        # TP collectives: two all-reduces of the rows' activations per
        # layer, through the ring model's link degrees
        if cand.tp > 1:
            ar_bytes = rows * g.hidden_size * g.param_bytes
            if cand.quantized_allreduce == "int8":
                ar_bytes *= 0.27
            t += (g.num_layers / cand.pp) * 2.0 * self.collectives.all_reduce(
                ar_bytes, cand.tp, "model"
            )
        # dispatch overhead: one program per step under whole_step, ~2
        # per layer unfused (the PR-6 per-layer fusions)
        launches = 1.0 if cand.whole_step else 2.0 * g.num_layers / cand.pp
        t += launches * _DISPATCH_S
        return t

    def _scaled_chip(self, oversubscription: float) -> TPUChip:
        if oversubscription <= 1.0:
            return self.chip
        return dataclasses.replace(
            self.chip,
            bf16_flops=self.chip.bf16_flops / oversubscription,
            hbm_bandwidth=self.chip.hbm_bandwidth / oversubscription,
        )

    def _spec_commit(self, cand: ServingCandidate,
                     traffic: TrafficProfile) -> Tuple[float, float]:
        """(committed tokens per verify step, tree rows verified). The
        expected accepted path length is the geometric series in the
        per-level accept rate over the ladder's top-rung depth, +1 for
        the verifier's own bonus token."""
        if not cand.speculation:
            return 1.0, 1.0
        rate = traffic.spec_accept_rate
        if traffic.measured_accept_rate is not None:
            # measured verify-ladder acceptance beats the workload prior
            # (serve/spec_distill.py eval harness feeds this)
            rate = traffic.measured_accept_rate
        a = min(max(rate, 0.0), 0.99)
        d = max(1, cand.spec_depth)
        accepted = a * (1.0 - a ** d) / (1.0 - a) if a > 0 else 0.0
        tree = 1.0 + cand.spec_width * cand.spec_depth
        return 1.0 + accepted, tree

    # -- prefill -----------------------------------------------------

    def _prefill_s(
        self,
        cand: ServingCandidate,
        prompt_len: float,
        prefix_share: float,
        *,
        oversubscription: float = 1.0,
    ) -> float:
        """One prompt's prefill wall time: compute-bound 2·params FLOPs
        per UNCACHED token, weight-stream floor, chunk dispatch tax."""
        g = self.geometry
        shards = cand.tp * cand.pp
        uncached = max(1.0, prompt_len * (1.0 - prefix_share))
        flops = 2.0 * g.param_count() * uncached / shards
        bytes_moved = g.weight_bytes() / shards
        chip = self._scaled_chip(oversubscription)
        t = compute_time(chip, flops, bytes_moved)
        if cand.tp > 1:
            ar_bytes = uncached * g.hidden_size * g.param_bytes
            t += (g.num_layers / cand.pp) * 2.0 * self.collectives.all_reduce(
                ar_bytes, cand.tp, "model"
            )
        chunks = math.ceil(uncached / max(1, cand.prefill_chunk))
        t += chunks * _DISPATCH_S * (
            1.0 if cand.whole_step else 2.0 * g.num_layers / cand.pp
        )
        # pipeline fill: the first token crosses every stage once
        t += (cand.pp - 1) * self.topo.per_hop_latency
        return t

    # -- the prediction ----------------------------------------------

    def predict(
        self,
        cand: ServingCandidate,
        traffic: TrafficProfile,
        *,
        oversubscription: float = 1.0,
    ) -> ServingPrediction:
        """Price one candidate under one traffic profile.

        Concurrency comes from Little's law iterated to a fixed point
        (service time depends on batch, batch on service time — three
        rounds converge well within the model's accuracy); queue wait
        is an M/D/c-flavored closed form that is deterministic, smooth
        and monotone in utilization, which is what the hysteresis
        bands in :mod:`policy` need."""
        g = self.geometry
        slots = cand.max_requests_per_batch
        lam_r = traffic.arrival_rate_rps / max(1, cand.replicas)
        ctx_mean = traffic.prompt_len_mean + 0.5 * traffic.output_len_mean
        commit, tree = self._spec_commit(cand, traffic)

        # Little's-law fixed point for per-replica live batch
        batch = min(float(slots), 1.0)
        t_dec = self._decode_step_s(
            cand, batch, ctx_mean, tree_tokens=tree,
            oversubscription=oversubscription,
        )
        for _ in range(3):
            t_pre = self._prefill_s(
                cand, traffic.prompt_len_mean, traffic.prefix_share,
                oversubscription=oversubscription,
            )
            # per-token latency pays every pipeline stage; per-step
            # throughput overlaps them (dispatch-ahead keeps it full)
            tpot = t_dec * cand.pp / commit
            service = t_pre + traffic.output_len_mean * tpot
            batch = min(float(slots), max(1.0, lam_r * service))
            t_dec = self._decode_step_s(
                cand, batch, ctx_mean, tree_tokens=tree,
                oversubscription=oversubscription,
            )

        # capacity: decode throughput at full slots
        t_dec_full = self._decode_step_s(
            cand, float(slots), ctx_mean, tree_tokens=tree,
            oversubscription=oversubscription,
        )
        cap_per_replica = slots * commit / t_dec_full
        capacity = cap_per_replica * cand.replicas
        offered = traffic.arrival_rate_rps * traffic.output_len_mean
        tokens_per_s = min(offered, capacity)

        # queueing: utilization of the replica's slot pool
        service = t_pre + traffic.output_len_mean * (
            t_dec * cand.pp / commit
        )
        rho = min(lam_r * service / slots, 4.0)
        if rho < 1.0:
            queue = 0.5 * (rho ** 2) / (1.0 - rho) * (service / slots)
        else:
            # saturated: backlog grows — charge the overload linearly
            # so the search/policy still sees a smooth, monotone signal
            queue = service * (1.0 + (rho - 1.0) * slots)

        tpot_p50 = t_dec * cand.pp / commit
        t_dec_p99 = self._decode_step_s(
            cand, min(float(slots), batch + 1),
            traffic.prompt_len_p99 + traffic.output_len_p99,
            tree_tokens=tree, oversubscription=oversubscription,
        )
        tpot_p99 = t_dec_p99 * cand.pp / commit
        ttft_p50 = queue + self._prefill_s(
            cand, traffic.prompt_len_p50, traffic.prefix_share,
            oversubscription=oversubscription,
        )
        ttft_p99 = 3.0 * queue + self._prefill_s(
            cand, traffic.prompt_len_p99, traffic.prefix_share,
            oversubscription=oversubscription,
        )

        # memory: sharded weights + the page pool. The budget keeps the
        # kv_quant invariant: max_cached_tokens means "this much KV HBM"
        # at the FP dtype, so quantized pages multiply the page count.
        budget_tokens = slots * cand.max_sequence_length
        budget_bytes = budget_tokens * g.kv_bytes_per_token(None)
        page_bytes = cand.page_size * g.kv_bytes_per_token(cand.kv_quant)
        pages_capacity = int(budget_bytes // max(1.0, page_bytes))
        # working set: live contexts rounded UP to whole pages (+ half
        # a page of rounding waste per request)
        pages_needed = int(math.ceil(
            batch * (ctx_mean / cand.page_size + 0.5)
        ))
        page_fill = pages_needed / max(1, pages_capacity)
        hbm = (
            g.weight_bytes() / (cand.tp * cand.pp)
            + min(budget_bytes, pages_needed * page_bytes)
            / (cand.tp * cand.pp)
        )
        hbm_fill = hbm / self.chip.hbm_capacity

        feasible, reason = True, ""
        if hbm_fill > 0.95:
            feasible, reason = False, (
                f"weights+KV need {hbm / 1e9:.2f} GB/chip "
                f"({hbm_fill:.0%} of {self.chip.name} HBM)"
            )
        elif rho >= 1.0:
            feasible, reason = False, (
                f"saturated: utilization {rho:.2f} at "
                f"{cand.replicas} replica(s)"
            )
        return ServingPrediction(
            tokens_per_s=tokens_per_s,
            capacity_tokens_per_s=capacity,
            ttft_s_p50=ttft_p50,
            ttft_s_p99=ttft_p99,
            tpot_s_p50=tpot_p50,
            tpot_s_p99=tpot_p99,
            queue_delay_s=queue,
            decode_step_s=t_dec,
            hbm_bytes_per_chip=hbm,
            hbm_fill=hbm_fill,
            kv_pages_capacity=pages_capacity,
            kv_pages_needed=pages_needed,
            page_fill=page_fill,
            feasible=feasible,
            reason=reason,
        )
