"""Online traffic estimation on the deterministic cluster step clock.

:class:`TrafficEstimator` fits a :class:`~.cost_model.TrafficProfile`
from the telemetry the cluster already produces — the PR-13
ClusterStats counters and the per-replica queue-delay estimates — one
observation per cluster step, with NO wall clock anywhere: rates are
EMAs in per-STEP units, length distributions are fixed-boundary
histograms, and percentiles are nearest-rank over those buckets. The
same observation sequence therefore always fits bit-identical profiles
(tests/test_autotune.py asserts it), which is what makes autoscaler
decisions replayable: a journal replay that reconstructs the same
counters reconstructs the same profile, the same predictions and the
same decisions.

Wall time enters exactly once, at the EDGE: :meth:`profile` takes an
explicit ``step_time_s`` (the caller's measured ``cluster_step_ms``
p50, or a pinned constant in tests) to convert per-step rates into the
per-second units the cost model prices.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

from .cost_model import TrafficProfile

__all__ = ["TrafficEstimator"]

#: Length-histogram bucket upper edges (tokens): powers of two — fixed
#: boundaries keep the percentile arithmetic deterministic and the
#: state O(1) regardless of how long the cluster runs.
_LEN_EDGES = tuple(2 ** i for i in range(1, 21))


class _LenHistogram:
    """Fixed-boundary histogram with nearest-rank percentiles (the
    same discipline metrics.py's ``_pct`` uses over reservoirs, but
    with bounded state)."""

    def __init__(self) -> None:
        self.counts = [0] * (len(_LEN_EDGES) + 1)
        self.total = 0
        self.sum = 0.0

    def add(self, value: float) -> None:
        self.total += 1
        self.sum += float(value)
        for i, edge in enumerate(_LEN_EDGES):
            if value <= edge:
                self.counts[i] += 1
                return
        self.counts[-1] += 1

    def pct(self, q: float) -> float:
        """Nearest-rank percentile, reported at the bucket's upper
        edge. 0 on an empty histogram — the pre-envelope window."""
        if self.total == 0:
            return 0.0
        rank = max(1, int(round(q * self.total)))
        seen = 0
        for i, c in enumerate(self.counts):
            seen += c
            if seen >= rank:
                return float(
                    _LEN_EDGES[i] if i < len(_LEN_EDGES)
                    else 2 * _LEN_EDGES[-1]
                )
        return float(2 * _LEN_EDGES[-1])

    def mean(self) -> float:
        return self.sum / self.total if self.total else 0.0


class TrafficEstimator:
    """Fits a TrafficProfile online, one :meth:`observe` per cluster
    step. All inputs are plain numbers read off counters — cumulative
    where the source is cumulative (``submitted``, prefix/spec
    counters; the estimator takes deltas itself) — so feeding the same
    sequence twice yields the same profile."""

    def __init__(self, *, ema_alpha: float = 0.05,
                 warmup_steps: int = 8) -> None:
        if not 0.0 < ema_alpha <= 1.0:
            raise ValueError(f"ema_alpha must be in (0, 1] (got {ema_alpha})")
        self.ema_alpha = ema_alpha
        #: observations before :meth:`ready` — pre-envelope windows
        #: (remote stats mirrors fill from heartbeats) fit garbage
        self.warmup_steps = warmup_steps
        self.steps_observed = 0
        # per-step EMAs
        self._arrivals_per_step = 0.0
        self._completions_per_step = 0.0
        self._queue_delay_ema = 0.0
        # cumulative high-water marks (deltas taken per observation)
        self._seen_submitted = 0
        self._seen_prefix = (0, 0)       # hits, misses
        self._seen_spec = (0, 0)         # accepted, drafted
        # ratio EMAs
        self._prefix_share_ema = 0.0
        self._accept_ema = 0.0
        # length histograms over completed requests
        self._prompt_hist = _LenHistogram()
        self._output_hist = _LenHistogram()

    # -- observation --------------------------------------------------

    def observe(
        self,
        *,
        submitted: int,
        completions: Sequence[Tuple[int, int]] = (),
        queue_delay_s: float = 0.0,
        prefix_hits: int = 0,
        prefix_misses: int = 0,
        spec_accepted: int = 0,
        spec_drafted: int = 0,
    ) -> None:
        """Fold one cluster step's telemetry in. ``submitted`` /
        prefix / spec inputs are the CUMULATIVE counters (pass the
        stats fields verbatim); ``completions`` is this step's newly
        terminal requests as ``(prompt_len, output_len)`` pairs;
        ``queue_delay_s`` is the max routable-replica estimate (0 on
        pre-envelope windows — see Replica.rate_snapshot)."""
        self.steps_observed += 1
        a = self.ema_alpha
        arrived = max(0, int(submitted) - self._seen_submitted)
        self._seen_submitted = max(self._seen_submitted, int(submitted))
        self._arrivals_per_step += a * (arrived - self._arrivals_per_step)
        self._completions_per_step += a * (
            len(completions) - self._completions_per_step
        )
        self._queue_delay_ema += a * (
            max(0.0, float(queue_delay_s)) - self._queue_delay_ema
        )
        for prompt_len, output_len in completions:
            self._prompt_hist.add(max(1, int(prompt_len)))
            self._output_hist.add(max(1, int(output_len)))
        hits_d = max(0, int(prefix_hits) - self._seen_prefix[0])
        miss_d = max(0, int(prefix_misses) - self._seen_prefix[1])
        self._seen_prefix = (
            max(self._seen_prefix[0], int(prefix_hits)),
            max(self._seen_prefix[1], int(prefix_misses)),
        )
        if hits_d + miss_d:
            inst = hits_d / (hits_d + miss_d)
            self._prefix_share_ema += a * (inst - self._prefix_share_ema)
        acc_d = max(0, int(spec_accepted) - self._seen_spec[0])
        drf_d = max(0, int(spec_drafted) - self._seen_spec[1])
        self._seen_spec = (
            max(self._seen_spec[0], int(spec_accepted)),
            max(self._seen_spec[1], int(spec_drafted)),
        )
        if drf_d:
            inst = min(1.0, acc_d / drf_d)
            self._accept_ema += a * (inst - self._accept_ema)

    def observe_cluster(self, cm) -> None:
        """Convenience: gather one step's inputs from a live
        ClusterManager — the autoscaler's per-step path. Reads only
        host-side counters and the documented replica rate surface
        (Replica.rate_snapshot); never touches a device."""
        st = cm.stats
        agg_hits = agg_miss = agg_acc = agg_drf = 0
        delay = 0.0
        for rep in cm.replicas:
            try:
                s = rep.stats
                agg_hits += int(getattr(s, "prefix_hits", 0))
                agg_miss += int(getattr(s, "prefix_misses", 0))
                agg_acc += int(getattr(s, "spec_accepted", 0))
                agg_drf += int(getattr(s, "spec_drafted", 0))
                delay = max(delay, rep.rate_snapshot()["queue_delay_s"])
            except Exception:
                # a DOWN / mid-reconnect replica must not stall the
                # estimator — its stats simply sit this window out
                continue
        completions = cm.drain_completion_window()
        self.observe(
            submitted=st.submitted,
            completions=completions,
            queue_delay_s=delay,
            prefix_hits=agg_hits,
            prefix_misses=agg_miss,
            spec_accepted=agg_acc,
            spec_drafted=agg_drf,
        )

    # -- the fitted profile -------------------------------------------

    def ready(self) -> bool:
        """True once the warmup window has passed AND at least one
        request completed — before that, :meth:`profile` extrapolates
        from defaults and the policy should hold."""
        return (
            self.steps_observed >= self.warmup_steps
            and self._prompt_hist.total > 0
        )

    def arrival_rate_per_step(self) -> float:
        return self._arrivals_per_step

    def queue_delay_s(self) -> float:
        return self._queue_delay_ema

    def spec_accept_rate(self) -> float:
        return self._accept_ema

    def profile(self, *, step_time_s: float) -> TrafficProfile:
        """The fitted TrafficProfile. ``step_time_s`` converts per-step
        rates to per-second — the ONE wall-clock input, supplied by the
        caller (measured cluster_step_ms p50, or pinned in tests)."""
        if step_time_s <= 0.0:
            raise ValueError(
                f"step_time_s must be > 0 (got {step_time_s})"
            )
        return TrafficProfile(
            arrival_rate_rps=self._arrivals_per_step / step_time_s,
            prompt_len_p50=self._prompt_hist.pct(0.50) or 128.0,
            prompt_len_p99=self._prompt_hist.pct(0.99) or 512.0,
            output_len_p50=self._output_hist.pct(0.50) or 128.0,
            output_len_p99=self._output_hist.pct(0.99) or 512.0,
            prefix_share=self._prefix_share_ema,
            spec_accept_rate=self._accept_ema,
        )

    def snapshot(self) -> Dict[str, float]:
        """Debug/test surface: every fitted statistic as plain floats."""
        return {
            "steps_observed": self.steps_observed,
            "arrivals_per_step": self._arrivals_per_step,
            "completions_per_step": self._completions_per_step,
            "queue_delay_s": self._queue_delay_ema,
            "prefix_share": self._prefix_share_ema,
            "spec_accept_rate": self._accept_ema,
            "prompt_len_p50": self._prompt_hist.pct(0.50),
            "prompt_len_p99": self._prompt_hist.pct(0.99),
            "output_len_p50": self._output_hist.pct(0.50),
            "output_len_p99": self._output_hist.pct(0.99),
            "completed": self._prompt_hist.total,
        }
