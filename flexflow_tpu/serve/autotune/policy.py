"""The live autoscaler (ROADMAP item 2b): cost-model-driven, journaled,
step-clocked.

One :class:`Autoscaler` rides the ClusterManager's drive loop —
``ClusterManager.step`` calls :meth:`Autoscaler.on_step` once per
cluster step, after replicas stepped and retirements settled but
BEFORE the step's journal sync, so a decision's records batch into the
same durable flush as the step that produced them. Every step it feeds
one telemetry observation to the :class:`~.workload.TrafficEstimator`;
every ``eval_interval_steps`` it runs the fitted profile through the
:class:`~.cost_model.ServingCostModel` and compares predictions
against the config's SLOs:

* **scale_out** when the predicted queue delay / TTFT p99 breaches the
  SLO for ``breach_evals`` consecutive evaluations — capacity is added
  through the PR-14 journaled :func:`~..cluster.reconfigure.scale_out`
  (begin → commit, so a SIGKILL mid-event recovers: an uncommitted
  begin replays as "never happened", a committed one rebuilds the
  grown membership).
* **scale_in** when the one-smaller cluster is predicted to hold the
  SLO with margin (``low_band``) for ``clear_evals`` consecutive
  evaluations — drain-based (:func:`begin_scale_in`; the drive loop's
  ``maybe_retire`` finishes it), never a kill.
* **set_pools** on a disaggregated cluster when the prefill/decode
  backlog ratio leaves its band — re-splits the pools one replica at a
  time.
* **retune** when the live speculation accept rate has drifted across
  a bucket-ladder rung boundary: the decision journals the advised W×D
  rung. The per-request TreeControllers (PR 10) already shape trees
  from their own acceptance — the cluster-level retune is the
  AUDITABLE record of where the fleet-wide ladder should sit, consumed
  by operators and the offline search's next run.

Hysteresis is two one-sided streak counters (breach vs clear) with a
dead band between ``low_band``·SLO and the SLO itself — inside the
band the policy holds. Cooldown windows and streaks are counted in
CLUSTER STEPS, never wall clock: replaying the same telemetry replays
the same decisions. ``dry_run`` (ServingConfig ``autoscale="advise"``)
evaluates, journals and counts every decision but applies none.

Every decision — applied or advisory — increments
``ClusterStats.autoscale_decisions``, journals an ``"autoscale"``
record (replay-ignored: the scale ops' own begin/commit records carry
the recoverable state), and refreshes the predicted-vs-measured
gauges (``autoscale_predicted_tps`` / ``autoscale_measured_tps``) the
Prometheus exporter scrapes.
"""
from __future__ import annotations

import dataclasses
import logging
from typing import Any, Dict, List, Optional

from .cost_model import ModelGeometry, ServingCandidate, ServingCostModel
from .workload import TrafficEstimator

__all__ = ["AutoscaleDecision", "Autoscaler"]

_log = logging.getLogger("flexflow.serve.autotune")


@dataclasses.dataclass
class AutoscaleDecision:
    """One policy decision, journaled and kept on
    ``Autoscaler.decisions`` for tests/bench to read back."""

    step: int
    kind: str            # "scale_out" | "scale_in" | "set_pools" | "retune"
    reason: str
    applied: bool
    detail: Dict[str, Any] = dataclasses.field(default_factory=dict)


class Autoscaler:
    """Policy loop over one ClusterManager. Construction is cheap and
    device-free; all per-step work is host-side counter arithmetic
    (ffcheck FF107 roots this file's drive-loop surface)."""

    def __init__(
        self,
        cm,
        *,
        cost_model: ServingCostModel,
        estimator: Optional[TrafficEstimator] = None,
        dry_run: bool = False,
        cooldown_steps: int = 64,
        min_replicas: int = 1,
        max_replicas: int = 2,
        eval_interval_steps: int = 8,
        breach_evals: int = 2,
        clear_evals: int = 4,
        low_band: float = 0.5,
        step_time_s: Optional[float] = None,
    ):
        if max_replicas < min_replicas:
            raise ValueError(
                f"max_replicas ({max_replicas}) < min_replicas "
                f"({min_replicas})"
            )
        if cooldown_steps < 1 or eval_interval_steps < 1:
            raise ValueError(
                "cooldown_steps and eval_interval_steps must be >= 1"
            )
        if not 0.0 < low_band < 1.0:
            raise ValueError(f"low_band must be in (0, 1) (got {low_band})")
        self.cm = cm
        self.cost_model = cost_model
        self.estimator = estimator or TrafficEstimator()
        self.dry_run = dry_run
        self.cooldown_steps = cooldown_steps
        self.min_replicas = min_replicas
        self.max_replicas = max_replicas
        self.eval_interval_steps = eval_interval_steps
        self.breach_evals = breach_evals
        self.clear_evals = clear_evals
        self.low_band = low_band
        #: pins the step-time used for rate conversion (tests/bench);
        #: None = the live measured cluster_step_ms p50
        self.step_time_s = step_time_s
        self.decisions: List[AutoscaleDecision] = []
        # hysteresis state — streaks at eval cadence, cooldown armed
        # from the CURRENT step so a freshly recovered manager never
        # fires into a cluster it has not yet observed
        self._breach_streak = 0
        self._clear_streak = 0
        self._last_action_step = int(getattr(cm, "_step_counter", 0))
        self._advised_rung: Optional[int] = None
        self._measured_window: List[int] = []   # tokens completed/step

    # -- construction from a live manager -----------------------------

    @classmethod
    def from_manager(cls, cm) -> "Autoscaler":
        """Build from ``cm.serving``'s autoscale fields + the lead
        replica's model config (the geometry every replica shares)."""
        sc = cm.serving
        ctx = getattr(cm, "_build_ctx", None)
        cfg = ctx["cfg"] if ctx else cm.replicas[0].engine.cfg
        geom = ModelGeometry.from_model_config(cfg)
        return cls(
            cm,
            cost_model=ServingCostModel(geom),
            dry_run=(sc.autoscale == "advise"),
            cooldown_steps=sc.autoscale_cooldown_steps,
            min_replicas=sc.autoscale_min_replicas,
            max_replicas=sc.autoscale_max_replicas,
        )

    # -- the per-step hook --------------------------------------------

    def on_step(self, step_no: int) -> Optional[AutoscaleDecision]:
        """One cluster step: observe always, evaluate at the eval
        cadence. Returns the decision made this step, if any."""
        self.estimator.observe_cluster(self.cm)
        self._measured_window.append(self._completed_tokens_delta())
        if len(self._measured_window) > 256:
            del self._measured_window[:-256]
        if step_no % self.eval_interval_steps != 0:
            return None
        if not self.estimator.ready():
            return None
        return self._evaluate(step_no)

    def _completed_tokens_delta(self) -> int:
        # decode_tokens is cumulative over replicas; delta per step
        total = 0
        for rep in self.cm.replicas:
            try:
                total += int(getattr(rep.stats, "decode_tokens", 0))
            except Exception:
                continue
        prev = getattr(self, "_seen_decode_tokens", 0)
        self._seen_decode_tokens = max(prev, total)
        return max(0, total - prev)

    # -- evaluation ---------------------------------------------------

    def _step_time(self) -> float:
        if self.step_time_s is not None:
            return self.step_time_s
        measured = self.cm.stats.cluster_step_ms_p50 / 1e3
        return measured if measured > 0 else 0.01

    def _candidate(self, replicas: int) -> ServingCandidate:
        sc = self.cm.serving
        pf = sc.prefill_replicas
        return ServingCandidate(
            replicas=replicas,
            page_size=sc.page_size,
            kv_quant=sc.kv_quant,
            prefill_replicas=min(pf, max(0, replicas - 1)) if pf else 0,
            decode_replicas=(
                replicas - min(pf, max(0, replicas - 1)) if pf else 0
            ),
            speculation=self.estimator.spec_accept_rate() > 0,
            whole_step="whole_step" in sc.fused_decode,
            quantized_allreduce=sc.quantized_allreduce,
            max_requests_per_batch=sc.max_requests_per_batch,
            max_sequence_length=sc.max_sequence_length,
            prefill_chunk=sc.prefill_chunk,
        )

    def _slo(self) -> Dict[str, Optional[float]]:
        sc = self.cm.serving
        return {
            "ttft": sc.slo_ttft_s,
            "tpot": sc.slo_tpot_s,
            "queue": sc.slo_queue_delay_s,
        }

    def _breaches(self, pred, slo) -> Optional[str]:
        """Which SLO the prediction breaches, or None."""
        if slo["ttft"] is not None and pred.ttft_s_p99 > slo["ttft"]:
            return (f"predicted ttft_p99 {pred.ttft_s_p99:.3f}s > "
                    f"slo_ttft_s {slo['ttft']}")
        if slo["tpot"] is not None and pred.tpot_s_p99 > slo["tpot"]:
            return (f"predicted tpot_p99 {pred.tpot_s_p99:.4f}s > "
                    f"slo_tpot_s {slo['tpot']}")
        if slo["queue"] is not None and pred.queue_delay_s > slo["queue"]:
            return (f"predicted queue delay {pred.queue_delay_s:.3f}s > "
                    f"slo_queue_delay_s {slo['queue']}")
        return None

    def _clear(self, pred, slo) -> bool:
        """True when the prediction holds EVERY set SLO with the
        hysteresis margin — the scale-in side of the dead band."""
        ok = True
        if slo["ttft"] is not None:
            ok &= pred.ttft_s_p99 <= self.low_band * slo["ttft"]
        if slo["tpot"] is not None:
            ok &= pred.tpot_s_p99 <= self.low_band * slo["tpot"]
        if slo["queue"] is not None:
            ok &= pred.queue_delay_s <= self.low_band * slo["queue"]
        return ok

    def _evaluate(self, step_no: int) -> Optional[AutoscaleDecision]:
        cm = self.cm
        n = len(cm.replicas) - len(getattr(cm, "_draining", ()))
        profile = self.estimator.profile(step_time_s=self._step_time())
        slo = self._slo()
        pred_now = self.cost_model.predict(self._candidate(n), profile)
        # predicted-vs-measured gauges: what the model says the current
        # shape should stream vs what the fleet actually committed
        st = self._step_time()
        window = self._measured_window[-64:]
        measured = (sum(window) / (len(window) * st)) if window else 0.0
        cm.stats.autoscale_predicted_tps = round(pred_now.tokens_per_s, 3)
        cm.stats.autoscale_measured_tps = round(measured, 3)

        breach = self._breaches(pred_now, slo)
        if breach is not None:
            self._breach_streak += 1
            self._clear_streak = 0
        else:
            self._breach_streak = 0
            if n > self.min_replicas:
                pred_smaller = self.cost_model.predict(
                    self._candidate(n - 1), profile
                )
                if pred_smaller.feasible and self._clear(pred_smaller, slo):
                    self._clear_streak += 1
                else:
                    self._clear_streak = 0
            else:
                self._clear_streak = 0

        in_cooldown = (
            step_no - self._last_action_step < self.cooldown_steps
        )
        if not in_cooldown:
            if (self._breach_streak >= self.breach_evals
                    and n < self.max_replicas):
                return self._decide_scale_out(step_no, breach, pred_now)
            if (self._clear_streak >= self.clear_evals
                    and n > self.min_replicas):
                return self._decide_scale_in(step_no, pred_now)
            d = self._maybe_retune(step_no)
            if d is not None:
                return d
        return None

    # -- decisions ----------------------------------------------------

    def _record(self, dec: AutoscaleDecision) -> AutoscaleDecision:
        cm = self.cm
        cm.stats.autoscale_decisions += 1
        self.decisions.append(dec)
        if cm.journal is not None:
            # the decision record is the audit trail; the applied ops'
            # own reconfig begin/commit records (written by scale_out /
            # begin_scale_in / set_pools) carry the recoverable state
            cm.journal.append({
                "type": "autoscale", "step": dec.step, "kind": dec.kind,
                "applied": dec.applied, "reason": dec.reason,
                **{k: v for k, v in dec.detail.items()
                   if isinstance(v, (int, float, str, bool))},
            })
        self._last_action_step = dec.step
        self._breach_streak = 0
        self._clear_streak = 0
        _log.warning(
            "autoscale[%s]%s @step %d: %s", dec.kind,
            "" if dec.applied else " (advise)", dec.step, dec.reason,
        )
        return dec

    def _decide_scale_out(self, step_no, breach, pred) -> AutoscaleDecision:
        cm = self.cm
        role = "mixed"
        if cm.disaggregated:
            # grow the pool whose SLO is hurting: TTFT lives on the
            # routed prefill pool, TPOT/queue on the decode pool
            role = "prefill" if "ttft" in breach else "decode"
        applied = not self.dry_run
        detail = {"role": role}
        if applied:
            try:
                # journaled begin→commit inside scale_out — the
                # crash-recovery contract lives there, not here
                detail["pos"] = cm.scale_out(role=role)
            except Exception as exc:
                # e.g. a socket cluster with no spare endpoint: the
                # decision downgrades to advisory, the drive loop lives
                applied = False
                breach = f"{breach}; scale_out failed: {exc}"
        dec = AutoscaleDecision(
            step=step_no, kind="scale_out", applied=applied,
            reason=breach, detail=detail,
        )
        return self._record(dec)

    def _scale_in_target(self) -> Optional[int]:
        """The retiree: the LAST-joined routable replica whose pool
        can spare it (reverse join order keeps the original build's
        replicas stable — the bench's zero-recompiles-on-untouched
        assertion depends on it)."""
        cm = self.cm
        draining = getattr(cm, "_draining", set())
        for pos in sorted(
            range(len(cm.replicas)),
            key=lambda p: -cm.replicas[p].index,
        ):
            rep = cm.replicas[pos]
            if rep.index in draining or not cm._routable_pos(pos):
                continue
            if cm.disaggregated:
                pool = (cm.prefill_pool if rep.role == "prefill"
                        else cm.decode_pool)
                if len([r for r in pool
                        if r.index not in draining]) <= 1:
                    continue
            return pos
        return None

    def _decide_scale_in(self, step_no, pred) -> Optional[AutoscaleDecision]:
        cm = self.cm
        pos = self._scale_in_target()
        if pos is None:
            return None
        reason = (
            f"predicted SLOs hold at {len(cm.replicas) - 1} replica(s) "
            f"with {self.low_band:.0%} margin "
            f"(queue {pred.queue_delay_s * 1e3:.1f} ms)"
        )
        applied = not self.dry_run
        if applied:
            try:
                cm.begin_scale_in(pos)
            except Exception as exc:
                applied = False
                reason = f"{reason}; begin_scale_in failed: {exc}"
        dec = AutoscaleDecision(
            step=step_no, kind="scale_in", applied=applied,
            reason=reason, detail={"pos": pos,
                                   "index": cm.replicas[pos].index},
        )
        return self._record(dec)

    def _maybe_retune(self, step_no) -> Optional[AutoscaleDecision]:
        """Speculation-bucket retune from the live accept EMA: advise
        the ladder rung the fleet's acceptance earns. Only fires on
        clusters actually speculating (a spec manager on the lead
        replica), and only when the advised rung CHANGES."""
        cm = self.cm
        spec = getattr(cm.replicas[0].rm, "spec", None)
        ladder = getattr(spec, "bucket_ladder", None)
        if not ladder or len(ladder) < 2:
            return None
        a = self.estimator.spec_accept_rate()
        if a <= 0.0:
            return None
        rung = min(len(ladder) - 1, int(round(a * (len(ladder) - 1))))
        if rung == self._advised_rung:
            return None
        self._advised_rung = rung
        w, d = ladder[rung]
        cm.stats.retunes += 1
        dec = AutoscaleDecision(
            step=step_no, kind="retune", applied=not self.dry_run,
            reason=(f"live accept EMA {a:.2f} advises ladder rung "
                    f"{rung} (W={w}, D={d})"),
            detail={"rung": rung, "width": w, "depth": d},
        )
        return self._record(dec)
