"""Self-driving serving (ROADMAP item 2): a serving cost model, offline
ServingConfig search, and a live journaled autoscaler.

Four parts, layered bottom-up:

* :mod:`cost_model` — an analytical serving cost model on top of
  ``search/machine_model.py``'s chip rooflines and ring-collective
  formulas: given model geometry + a candidate serving shape
  (:class:`~.cost_model.ServingCandidate`) + a
  :class:`~.cost_model.TrafficProfile`, predict tokens/sec, TTFT/TPOT
  p50/p99 and HBM/page-pool occupancy. Decode steps are priced as
  bandwidth-bound weight+KV streaming, prefill as compute-bound, TP
  collectives through the machine model's link degrees.
* :mod:`workload` — :class:`~.workload.TrafficEstimator`: fits a
  TrafficProfile ONLINE from the cluster's own telemetry on the
  deterministic cluster step clock (no wall clock — the same
  observation sequence always fits the same profile).
* :mod:`search` — offline pruned enumeration + coordinate-descent
  refinement (the ``search/unity.py`` flavor) over the ServingConfig
  space, maximizing predicted tokens/sec under TTFT/TPOT SLOs and
  emitting a ready-to-run, ``validate_cluster``-clean ServingConfig.
* :mod:`policy` — :class:`~.policy.Autoscaler`: the online loop in
  ``ClusterManager.step`` that feeds the live estimator through the
  cost model and DRIVES the PR-14 journaled reconfigurations
  (scale_out / scale_in / set_pools / speculation-bucket retunes) with
  hysteresis bands + cooldown windows counted in cluster steps.
"""
from .cost_model import (
    ModelGeometry,
    ServingCandidate,
    ServingCostModel,
    ServingPrediction,
    TrafficProfile,
)
from .policy import AutoscaleDecision, Autoscaler
from .search import ServingSearchReport, search_serving_config
from .workload import TrafficEstimator

__all__ = [
    "AutoscaleDecision",
    "Autoscaler",
    "ModelGeometry",
    "ServingCandidate",
    "ServingCostModel",
    "ServingPrediction",
    "ServingSearchReport",
    "TrafficEstimator",
    "TrafficProfile",
    "search_serving_config",
]
