from .dtypes import DataType, BF16, F32
from .tensor import (
    TensorSpec,
    DimSharding,
    ShardedTensorSpec,
    sharded,
    replicated_spec,
)
from .mesh import (
    MachineSpec,
    AXIS_ORDER,
    DATA_AXIS,
    EXPERT_AXIS,
    PIPE_AXIS,
    SEQ_AXIS,
    MODEL_AXIS,
)
from .graph import Graph, OpNode, TensorRef, freeze_attrs

__all__ = [
    "DataType",
    "BF16",
    "F32",
    "TensorSpec",
    "DimSharding",
    "ShardedTensorSpec",
    "sharded",
    "replicated_spec",
    "MachineSpec",
    "AXIS_ORDER",
    "DATA_AXIS",
    "EXPERT_AXIS",
    "PIPE_AXIS",
    "SEQ_AXIS",
    "MODEL_AXIS",
    "Graph",
    "OpNode",
    "TensorRef",
    "freeze_attrs",
]
