"""Operator graph IR — the TPU-native Parallel Computation Graph (PCG).

The reference builds a ``Layer`` graph that ``FFModel::compile`` lowers to
``PCG::Graph`` whose nodes are hash-consed on per-op ``Params`` structs
(reference ``include/flexflow/graph.h:293``, ``model.h:935-964``). We keep
the same two-level idea in pure Python:

  * :class:`OpNode` — one operator instance: op type, frozen attrs,
    input tensor refs, output specs.
  * :class:`Graph`  — append-only DAG with topological node ids; the Unity
    search and the compile pipeline both walk it.

Node attrs are canonicalised to hashable tuples so structurally identical
ops hash equal — the property the reference's ``get_or_create_node<T>``
relies on for search-state dedup.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Sequence, Tuple

from .tensor import TensorSpec


def freeze_attrs(attrs: Dict[str, Any]) -> Tuple[Tuple[str, Any], ...]:
    """Canonicalise an attr dict into a sorted hashable tuple."""

    def conv(v):
        if isinstance(v, dict):
            return tuple(sorted((k, conv(x)) for k, x in v.items()))
        if isinstance(v, (list, tuple)):
            return tuple(conv(x) for x in v)
        if isinstance(v, set):
            return tuple(sorted(conv(x) for x in v))
        return v

    return tuple(sorted((k, conv(v)) for k, v in attrs.items()))


@dataclasses.dataclass(frozen=True)
class TensorRef:
    """Reference to output ``out_idx`` of node ``node_id`` — the PCG edge."""

    node_id: int
    out_idx: int = 0


@dataclasses.dataclass
class OpNode:
    id: int
    op_type: str
    attrs: Tuple[Tuple[str, Any], ...]
    inputs: Tuple[TensorRef, ...]
    out_specs: Tuple[TensorSpec, ...]
    name: str = ""

    @property
    def attrs_dict(self) -> Dict[str, Any]:
        return dict(self.attrs)

    def signature(self) -> Tuple:
        """Hash-consing key: structural identity ignoring node id/name."""
        return (self.op_type, self.attrs, self.inputs)


class Graph:
    """Append-only operator DAG in topological order."""

    def __init__(self):
        self.nodes: List[OpNode] = []
        self._sig_index: Dict[Tuple, int] = {}
        self._used_names: Dict[str, int] = {}
        # Rewrite redirect history: one dict PER REWRITE, chronological,
        # each mapping (old_name, old_out_idx) -> the post-rewrite
        # (name, out_idx) that value moved to. Covers dropped nodes
        # (fused-away relu) and REPLACED survivors whose outputs changed
        # meaning (merge_sibling_dense: old a.0 lives at the split's
        # out 0). Generations matter: one rewrite's redirects are
        # SIMULTANEOUS (old b.0 -> new b.1 must not re-apply to a value
        # that just arrived at b.0), so resolution applies each dict at
        # most once, in order.
        self.name_aliases: List[Dict[Tuple[str, int], Tuple[str, int]]] = []

    def alias_generation(self) -> int:
        """Number of rewrite generations recorded so far — coordinates
        minted NOW are valid from this generation on (pass it back to
        :meth:`resolve_name` as ``start_gen`` so later resolution skips
        redirects that predate the coordinate)."""
        return len(self._alias_generations())

    def _alias_generations(self):
        generations = getattr(self, "name_aliases", None) or []
        if isinstance(generations, dict):  # pre-generations format
            generations = [
                {
                    (k if isinstance(k, tuple) else (k, 0)): v
                    for k, v in generations.items()
                }
            ]
        return generations

    def resolve_name(self, name: str, out_idx: int = 0, start_gen: int = 0):
        """Resolve where a (name, out_idx) value minted at rewrite
        generation ``start_gen`` lives now; returns (node, out_idx) or
        (None, out_idx) when unresolvable. Generations BEFORE start_gen
        are skipped — a post-rewrite coordinate must not be re-run
        through the rewrite that minted it (e.g. the sibling-merge's
        simultaneous b.0→b.1 redirect). getattr guard: graphs unpickled
        from strategy files saved before this attribute existed lack
        it; their bare-str keys mean out_idx 0."""
        for gen in self._alias_generations()[start_gen:]:
            if (name, out_idx) in gen:
                name, out_idx = gen[(name, out_idx)]
        node = next((n for n in self.nodes if n.name == name), None)
        return node, out_idx

    def add_node(
        self,
        op_type: str,
        attrs: Dict[str, Any],
        inputs: Sequence[TensorRef],
        out_specs: Sequence[TensorSpec],
        name: str = "",
        dedup: bool = False,
    ) -> OpNode:
        frozen = freeze_attrs(attrs)
        sig = (op_type, frozen, tuple(inputs))
        if dedup and sig in self._sig_index:
            return self.nodes[self._sig_index[sig]]
        base = name or f"{op_type}_{len(self.nodes)}"
        # Uniquify deterministically: params are keyed by node name, so two
        # layers sharing a user-given name must not silently alias weights.
        count = self._used_names.get(base, 0)
        self._used_names[base] = count + 1
        unique = base if count == 0 else f"{base}_{count}"
        node = OpNode(
            id=len(self.nodes),
            op_type=op_type,
            attrs=frozen,
            inputs=tuple(inputs),
            out_specs=tuple(out_specs),
            name=unique,
        )
        self.nodes.append(node)
        self._sig_index[sig] = node.id
        return node

    def node(self, node_id: int) -> OpNode:
        return self.nodes[node_id]

    def out_spec(self, ref: TensorRef) -> TensorSpec:
        return self.nodes[ref.node_id].out_specs[ref.out_idx]

    def consumers(self, node_id: int) -> List[OpNode]:
        return [
            n
            for n in self.nodes
            if any(r.node_id == node_id for r in n.inputs)
        ]

    def topo_order(self) -> List[OpNode]:
        return list(self.nodes)  # insertion order is topological

    def __len__(self):
        return len(self.nodes)

    def __iter__(self):
        return iter(self.nodes)

    def to_dot(self) -> str:
        """Graphviz export, mirroring the reference's ``--export-strategy``
        dot dumps (reference ``src/runtime/graph.cc`` dot output)."""
        lines = ["digraph pcg {"]
        for n in self.nodes:
            shapes = ",".join(str(list(s.shape)) for s in n.out_specs)
            lines.append(f'  n{n.id} [label="{n.name}\\n{n.op_type} {shapes}"];')
            for r in n.inputs:
                lines.append(f"  n{r.node_id} -> n{n.id};")
        lines.append("}")
        return "\n".join(lines)
