"""Tensor shape metadata — the TPU-native ParallelTensor.

The reference models distribution with ``ParallelDim{size, degree,
parallel_idx, is_replica_dim}`` and ``ParallelTensorShape`` (reference
``include/flexflow/parallel_tensor.h:36-120``), binding each tensor to a
Legion region/partition. Here a tensor's *logical* shape lives in
:class:`TensorSpec`, and its *distribution* is a mapping of named mesh axes
per dimension (:class:`DimSharding`) that lowers directly to a
``jax.sharding.PartitionSpec``. Replica dims — the reference's trick for
representing weight replication and pending partial sums — become either
replication (axis unused in the spec) or "unreduced" partial sums, which
XLA tracks for us after GSPMD propagation.
"""
from __future__ import annotations

import dataclasses
from typing import Optional, Sequence, Tuple

import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from .dtypes import DataType

MAX_TENSOR_DIM = 5  # reference FF_MAX_DIM (CMakeLists.txt:100) default 5


@dataclasses.dataclass(frozen=True)
class TensorSpec:
    """Logical (unpartitioned) tensor: shape + dtype + optional name."""

    shape: Tuple[int, ...]
    dtype: DataType = DataType.FLOAT
    name: Optional[str] = None

    def __post_init__(self):
        object.__setattr__(self, "shape", tuple(int(d) for d in self.shape))
        object.__setattr__(self, "dtype", DataType.from_any(self.dtype))

    @property
    def ndim(self) -> int:
        return len(self.shape)

    @property
    def num_elements(self) -> int:
        n = 1
        for d in self.shape:
            n *= d
        return n

    @property
    def size_bytes(self) -> int:
        return (self.num_elements * self.dtype.itemsize_bits) // 8

    @property
    def jnp_dtype(self):
        return self.dtype.jnp_dtype

    def with_shape(self, shape: Sequence[int]) -> "TensorSpec":
        return dataclasses.replace(self, shape=tuple(shape))

    def with_dtype(self, dtype) -> "TensorSpec":
        return dataclasses.replace(self, dtype=DataType.from_any(dtype))

    def zeros(self):
        return jnp.zeros(self.shape, self.jnp_dtype)

    def __repr__(self):
        return f"TensorSpec({list(self.shape)}, {self.dtype.value}" + (
            f", {self.name!r})" if self.name else ")"
        )


@dataclasses.dataclass(frozen=True)
class DimSharding:
    """Sharding of one logical dim: tuple of mesh axis names (possibly
    empty = replicated along that dim). Multiple axes on one dim mirror the
    reference's multi-degree ParallelDim."""

    axes: Tuple[str, ...] = ()

    def degree(self, mesh: Mesh) -> int:
        d = 1
        for a in self.axes:
            d *= mesh.shape[a]
        return d


@dataclasses.dataclass(frozen=True)
class ShardedTensorSpec:
    """TensorSpec + per-dim mesh-axis assignment — the ParallelTensorShape
    equivalent (reference ``parallel_tensor.h:76-120``)."""

    spec: TensorSpec
    dim_shardings: Tuple[DimSharding, ...] = ()
    # Axes over which this tensor holds *unreduced partial sums* — the
    # reference's replica dim on an output awaiting a Reduction parallel op.
    unreduced_axes: Tuple[str, ...] = ()

    def __post_init__(self):
        ds = self.dim_shardings
        if len(ds) < self.spec.ndim:
            ds = tuple(ds) + tuple(
                DimSharding() for _ in range(self.spec.ndim - len(ds))
            )
        object.__setattr__(self, "dim_shardings", tuple(ds))

    def partition_spec(self) -> PartitionSpec:
        entries = []
        for d in self.dim_shardings:
            if not d.axes:
                entries.append(None)
            elif len(d.axes) == 1:
                entries.append(d.axes[0])
            else:
                entries.append(tuple(d.axes))
        while entries and entries[-1] is None:
            entries.pop()
        return PartitionSpec(*entries)

    def named_sharding(self, mesh: Mesh) -> NamedSharding:
        return NamedSharding(mesh, self.partition_spec())

    def shard_shape(self, mesh: Mesh) -> Tuple[int, ...]:
        """Per-device block shape, like the reference's Legion partition
        subregions."""
        out = []
        for size, d in zip(self.spec.shape, self.dim_shardings):
            deg = d.degree(mesh)
            if size % deg:
                raise ValueError(
                    f"dim of size {size} not divisible by degree {deg}"
                )
            out.append(size // deg)
        return tuple(out)

    def check_valid(self, mesh: Mesh) -> None:
        seen = set()
        for d in self.dim_shardings:
            for a in d.axes:
                if a in seen:
                    raise ValueError(f"mesh axis {a!r} used on two dims")
                if a not in mesh.axis_names:
                    raise ValueError(f"unknown mesh axis {a!r}")
                seen.add(a)
        self.shard_shape(mesh)


def sharded(spec: TensorSpec, *axes_per_dim) -> ShardedTensorSpec:
    """Helper: ``sharded(ts, 'data', None, 'model')`` shards dim0 on data,
    dim2 on model."""
    ds = []
    for a in axes_per_dim:
        if a is None:
            ds.append(DimSharding())
        elif isinstance(a, str):
            ds.append(DimSharding((a,)))
        else:
            ds.append(DimSharding(tuple(a)))
    return ShardedTensorSpec(spec, tuple(ds))


def replicated_spec(spec: TensorSpec) -> ShardedTensorSpec:
    return ShardedTensorSpec(spec)
