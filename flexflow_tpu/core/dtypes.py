"""Data types for flexflow-tpu.

Mirrors the reference's ``DataType`` enum (reference
``include/flexflow/ffconst.h``) mapped onto JAX dtypes. On TPU the MXU
natively computes in bfloat16 with float32 accumulation, so BF16 is the
default compute dtype; INT4/INT8 exist for weight-only quantization
(reference ``src/ops/kernels/decompress_kernels.cu``).
"""
from __future__ import annotations

import enum

import jax.numpy as jnp
import numpy as np


class DataType(enum.Enum):
    BOOL = "bool"
    INT4 = "int4"
    INT8 = "int8"
    INT32 = "int32"
    INT64 = "int64"
    HALF = "float16"
    BFLOAT16 = "bfloat16"
    FLOAT = "float32"
    DOUBLE = "float64"

    @property
    def jnp_dtype(self):
        return _TO_JNP[self]

    @property
    def itemsize_bits(self) -> int:
        return _BITS[self]

    @classmethod
    def from_any(cls, dt) -> "DataType":
        """Coerce a DataType, jnp dtype, np dtype, or string to DataType."""
        if isinstance(dt, DataType):
            return dt
        name = jnp.dtype(dt).name if not isinstance(dt, str) else dt
        for member in cls:
            if member.value == name:
                return member
        raise ValueError(f"unsupported dtype: {dt!r}")


_TO_JNP = {
    DataType.BOOL: jnp.bool_,
    DataType.INT4: jnp.int4,
    DataType.INT8: jnp.int8,
    DataType.INT32: jnp.int32,
    DataType.INT64: jnp.int64,
    DataType.HALF: jnp.float16,
    DataType.BFLOAT16: jnp.bfloat16,
    DataType.FLOAT: jnp.float32,
    DataType.DOUBLE: jnp.float64,
}

_BITS = {
    DataType.BOOL: 8,
    DataType.INT4: 4,
    DataType.INT8: 8,
    DataType.INT32: 32,
    DataType.INT64: 64,
    DataType.HALF: 16,
    DataType.BFLOAT16: 16,
    DataType.FLOAT: 32,
    DataType.DOUBLE: 64,
}

# Convenient aliases used across the codebase.
BF16 = jnp.bfloat16
F32 = jnp.float32


def is_floating(dt) -> bool:
    return np.issubdtype(jnp.dtype(DataType.from_any(dt).jnp_dtype), np.floating)
