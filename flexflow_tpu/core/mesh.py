"""Device mesh layer — the TPU-native replacement for FlexFlow's MachineView.

The reference places operators on devices with ``MachineView{ndims,
start_device_id, dim[], stride[]}`` (reference ``include/flexflow/
machine_view.h:18-39``) resolved by a Legion mapper. On TPU the idiomatic
equivalent is a single logical ``jax.sharding.Mesh`` whose named axes carry
the parallelism meaning; GSPMD compiles sharding annotations into ICI/DCN
collectives, so placement is declarative instead of a task mapper.

Axis convention (outermost → innermost):

    data  — data parallel (batch sharding; gradients all-reduced)
    expert— expert parallel (MoE expert ranges)
    pipe  — pipeline parallel (layer stages; ppermute between neighbours)
    seq   — sequence/context parallel (ring attention / Ulysses)
    model — tensor parallel (Megatron head/FFN sharding)

``model`` is the innermost axis so TP collectives ride the fastest ICI
links between physically adjacent chips; ``data`` is outermost so DP
gradient all-reduces may cross DCN on multi-slice topologies.
"""
from __future__ import annotations

import dataclasses
import math
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

try:
    from jax import shard_map
except ImportError:  # older jax (0.4.x)
    from jax.experimental.shard_map import shard_map

# Canonical axis order; see module docstring.
AXIS_ORDER = ("data", "expert", "pipe", "seq", "model")

DATA_AXIS = "data"
EXPERT_AXIS = "expert"
PIPE_AXIS = "pipe"
SEQ_AXIS = "seq"
MODEL_AXIS = "model"


@dataclasses.dataclass(frozen=True)
class MachineSpec:
    """Logical machine description — the TPU analog of FlexFlow's
    ``MachineResource`` (reference ``machine_view.h:55``).

    Degrees multiply to the total device count. Any degree may be 1.
    """

    data: int = 1
    expert: int = 1
    pipe: int = 1
    seq: int = 1
    model: int = 1

    @property
    def num_devices(self) -> int:
        return self.data * self.expert * self.pipe * self.seq * self.model

    def axis_sizes(self) -> dict:
        return {
            "data": self.data,
            "expert": self.expert,
            "pipe": self.pipe,
            "seq": self.seq,
            "model": self.model,
        }

    def make_mesh(self, devices: Optional[Sequence] = None) -> Mesh:
        """Build a Mesh over ``devices`` (default: all local devices)."""
        if devices is None:
            devices = jax.devices()
        n = self.num_devices
        if len(devices) < n:
            raise ValueError(
                f"MachineSpec needs {n} devices, only {len(devices)} available"
            )
        shape = tuple(self.axis_sizes()[a] for a in AXIS_ORDER)
        dev_array = np.asarray(devices[:n]).reshape(shape)
        return Mesh(dev_array, AXIS_ORDER)

    @classmethod
    def from_degrees(
        cls,
        num_devices: int,
        *,
        tensor: int = 1,
        pipeline: int = 1,
        expert: int = 1,
        sequence: int = 1,
        data: Optional[int] = None,
    ) -> "MachineSpec":
        """Mirror of the reference CLI degrees (``-data/tensor/pipeline-
        parallelism-degree``, reference ``src/runtime/model.cc:4183``):
        whatever is not claimed by tensor/pipeline/expert/sequence becomes
        data parallelism.
        """
        denom = tensor * pipeline * expert * sequence
        if num_devices % denom:
            raise ValueError(
                f"{num_devices} devices not divisible by tp*pp*ep*sp={denom}"
            )
        if data is None:
            data = num_devices // denom
        if data * denom != num_devices:
            raise ValueError(
                f"degrees {data}*{denom} != device count {num_devices}"
            )
        return cls(data=data, expert=expert, pipe=pipeline, seq=sequence, model=tensor)


def shard_map_unchecked(fn, mesh, in_specs, out_specs, manual_axes=None):
    """``shard_map`` with the static replication checker OFF, across jax
    versions — the ONE compat shim for every collective primitive in the
    repo (ring/Ulysses attention, the pipeline stage loop, the ring
    ragged paged attention serving kernel). New jax spells the knob
    ``check_vma``; 0.4.x (this container) spells it ``check_rep``.

    Why the checker is off: jax 0.4.37's replication checker mis-types
    scan carries when these collectives run inside a layer scan over a
    mesh with unrelated (expert/pipe) axes — the carry enters untyped
    (None) and leaves typed replicated-over-the-unused-axes, which the
    scan fixpoint rejects. Every caller is an exact layout transform
    tested against a dense reference, so disabling the *static* check
    is sound (the math, not the checker, is the contract).

    ``manual_axes`` selects the partial-manual mode (only those axes
    run manually; the rest stay under GSPMD): new jax names the MANUAL
    set (``axis_names``), 0.4.x names the complement (``auto``).
    """
    if manual_axes is not None:
        try:
            return shard_map(
                fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                axis_names=frozenset(manual_axes), check_vma=False,
            )
        except TypeError:
            auto = frozenset(mesh.axis_names) - frozenset(manual_axes)
            return shard_map(
                fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                check_rep=False, auto=auto,
            )
    try:
        return shard_map(
            fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=False,
        )
    except TypeError:
        return shard_map(
            fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_rep=False,
        )


def set_mesh(mesh: Mesh):
    """Context manager installing ``mesh`` as the ambient mesh.

    ``jax.set_mesh`` where it exists (JAX >= 0.6); on older releases
    (this container ships 0.4.x, where the attribute is missing and
    every call site died with AttributeError) the ``Mesh`` object's own
    context manager provides the same ambient-mesh scoping the call
    sites need."""
    fn = getattr(jax, "set_mesh", None)
    return fn(mesh) if fn is not None else mesh


def single_device_spec() -> MachineSpec:
    return MachineSpec()


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())


def named_sharding(mesh: Mesh, *spec) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec(*spec))


def used_axes(mesh: Mesh) -> tuple:
    """Mesh axes with size > 1 (the only ones worth annotating)."""
    return tuple(a for a in mesh.axis_names if mesh.shape[a] > 1)


def host_local_mesh(spec: MachineSpec) -> Mesh:
    """Mesh over this process's addressable devices only (used by tests and
    the single-host serving path)."""
    return spec.make_mesh(jax.local_devices())


def validate_spec_for_devices(spec: MachineSpec, n_devices: int) -> None:
    if spec.num_devices != n_devices:
        raise ValueError(
            f"MachineSpec covers {spec.num_devices} devices, have {n_devices}"
        )
