"""Named rematerialisation policies shared by every model family and
the fused graph-IR ops.

``None`` is full per-block remat (save only block boundaries); "dots"
saves MXU matmul outputs and recomputes just the cheap elementwise/norm
work in backward — less recompute at slightly more memory, the standard
transformer training tradeoff. (The reference has no analog: Legion
keeps every activation.)
"""
from __future__ import annotations

from typing import Optional

import jax


def resolve_remat_policy(name: Optional[str]):
    if name is None:
        return None
    if name == "dots":
        return jax.checkpoint_policies.dots_with_no_batch_dims_saveable
    raise ValueError(f"unknown remat policy {name!r}")
