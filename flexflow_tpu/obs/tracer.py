"""Structured tracing — the span/event recorder under flexflow_tpu's
observability layer (ROADMAP: the telemetry substrate item 2's
self-driving serving loop reads).

Design constraints, in order:

1. **Disabled mode must be free.** Every emission site in the serve
   stack guards on ``tracer.enabled`` (a plain bool attribute read)
   before building ANY argument, and the module-level
   :data:`NULL_TRACER` never records — with tracing off, the scheduler
   step loop does no observability work beyond that attribute check
   (tests/test_observability.py proves it: zero obs-frame allocations,
   identical dispatched-program counts).
2. **Dual clock.** Every event carries BOTH a wall-clock stamp
   (``time.perf_counter()``, what the Chrome/Perfetto export renders)
   and a deterministic step stamp (the owner's scheduler / cluster
   step counter, what tests assert on). Nothing in the trace pipeline
   ever *decides* anything off wall time.
3. **Wire-safe events.** An event is one flat dict of codec-safe
   primitives (str/int/float/None — see serve/cluster/transport.py),
   so a remote replica's events ride the PR-12 RPC envelope unchanged
   and the client stitches one cross-host timeline
   (serve/cluster/{server,remote}.py).

One :class:`TraceBuffer` holds the run's events; components record
through per-lane :class:`Tracer` views (``buffer.tracer("replica0",
clock=...)``). Lanes become Perfetto process rows in the Chrome export
(obs/export.py); the optional :class:`~.flight_recorder.FlightRecorder`
observes every append for its bounded per-lane ring.
"""
from __future__ import annotations

import time
from typing import Any, Callable, Dict, List, Optional

__all__ = ["TraceBuffer", "Tracer", "NullTracer", "NULL_TRACER"]


def _zero() -> int:
    return 0


class NullTracer:
    """The disabled tracer: ``enabled`` is False and stays False.

    Emission sites check ``tracer.enabled`` BEFORE building event
    arguments, so on the hot path a disabled run costs one attribute
    read and one branch — the record methods below exist only so that
    an unguarded call is still safe (and so tests can monkeypatch them
    to raise, proving the guards hold)."""

    __slots__ = ()
    enabled = False
    lane = ""

    def event(self, name: str, **kw: Any) -> None:
        return None

    def span(self, name: str, **kw: Any) -> "_NullSpan":
        return _NULL_SPAN


class _NullSpan:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()

#: The process-wide disabled tracer every serve component starts with.
NULL_TRACER = NullTracer()


class TraceBuffer:
    """The run's event store (append-only, bounded).

    ``capacity`` bounds host memory on long runs: past it the oldest
    events drop and ``dropped`` counts them — an export of a bounded
    buffer says how much history it lost instead of silently
    truncating."""

    def __init__(self, capacity: int = 200_000):
        self.capacity = int(capacity)
        self.events: List[Dict[str, Any]] = []
        self.dropped = 0
        #: optional FlightRecorder observing every append
        self.recorder = None

    @property
    def enabled(self) -> bool:
        return True

    def append(self, ev: Dict[str, Any]) -> None:
        self.events.append(ev)
        if len(self.events) > self.capacity:
            overflow = len(self.events) - self.capacity
            del self.events[:overflow]
            self.dropped += overflow
        rec = self.recorder
        if rec is not None:
            rec.observe(ev)

    def extend(self, events, lane: Optional[str] = None) -> None:
        """Merge events shipped from another buffer (a remote replica's
        envelope). ``lane`` re-tags them when the shipper did not know
        its cluster lane; events are appended one by one so the flight
        recorder observes each."""
        for ev in events:
            if lane is not None and not ev.get("lane"):
                ev = dict(ev)
                ev["lane"] = lane
            self.append(ev)

    def drain(self) -> List[Dict[str, Any]]:
        """Take (and clear) the buffered events — how a replica server
        ships its spans home inside the RPC envelope."""
        out = self.events
        self.events = []
        return out

    def tracer(self, lane: str, clock: Optional[Callable[[], int]] = None
               ) -> "Tracer":
        """A per-lane recording view over this buffer."""
        return Tracer(self, lane, clock)


class Tracer:
    """A lane-tagged, clock-bound view over a :class:`TraceBuffer`.

    ``clock`` is the DETERMINISTIC half of the dual clock — a zero-arg
    callable returning the owner's step counter (scheduler steps for a
    RequestManager, cluster steps for the ClusterManager, client-side
    RPC steps for a RemoteReplica). Wall time is stamped alongside on
    every event.
    """

    __slots__ = ("buffer", "lane", "clock")

    enabled = True

    def __init__(self, buffer: TraceBuffer, lane: str,
                 clock: Optional[Callable[[], int]] = None):
        self.buffer = buffer
        self.lane = lane
        self.clock = clock or _zero

    def event(
        self,
        name: str,
        *,
        trace_id: int = -1,
        dur: float = 0.0,
        t: Optional[float] = None,
        step: Optional[int] = None,
        lane: Optional[str] = None,
        **attrs: Any,
    ) -> None:
        """Record one instant (``dur`` 0) or completed span. ``attrs``
        must be codec-safe primitives — they ride RPC envelopes and the
        JSON exports verbatim."""
        ev: Dict[str, Any] = {
            "name": name,
            "lane": self.lane if lane is None else lane,
            "trace_id": int(trace_id),
            "t": time.perf_counter() if t is None else t,
            "step": self.clock() if step is None else int(step),
            "dur": float(dur),
        }
        if attrs:
            ev["attrs"] = attrs
        self.buffer.append(ev)

    def span(self, name: str, *, trace_id: int = -1,
             lane: Optional[str] = None, **attrs: Any) -> "_Span":
        """Context manager recording ``name`` with its measured wall
        duration (step stamped at ENTRY — the deterministic clock of a
        span is when it began)."""
        return _Span(self, name, trace_id, lane, attrs)


class _Span:
    __slots__ = ("_tr", "_name", "_tid", "_lane", "_attrs", "_t0", "_s0")

    def __init__(self, tracer: Tracer, name: str, trace_id: int,
                 lane: Optional[str], attrs: Dict[str, Any]):
        self._tr = tracer
        self._name = name
        self._tid = trace_id
        self._lane = lane
        self._attrs = attrs

    def __enter__(self):
        self._t0 = time.perf_counter()
        self._s0 = self._tr.clock()
        return self

    def __exit__(self, exc_type, exc, tb):
        self._tr.event(
            self._name,
            trace_id=self._tid,
            t=self._t0,
            dur=time.perf_counter() - self._t0,
            step=self._s0,
            lane=self._lane,
            **(
                dict(self._attrs, error=type(exc).__name__)
                if exc_type is not None else self._attrs
            ),
        )
        return False
