"""flexflow_tpu.obs — cluster-wide request tracing, metrics export and
the failure flight recorder.

The observability layer the reference ships in three pieces —
per-op ``--profiling`` timing, per-request ``ProfileInfo``, Legion Prof
timeline captures — rebuilt TPU-native over the serve stack:

* :mod:`.tracer` — a low-overhead span/event recorder with a DUAL
  clock: wall time for humans/exports, deterministic scheduler/cluster
  step counts for tests. Request-lifecycle spans (admit →
  prefix_lookup → prefill_chunk* → decode/mixed steps → spec
  draft/verify → migrate → flush/terminal) flow from the
  RequestManager, the engine's dispatch chokepoint, SpecInfer and the
  ClusterManager; RPC retries, heartbeat gaps and health transitions
  become events too. Disabled (the default, :data:`NULL_TRACER`) the
  layer costs one attribute read per emission site — proven free in
  tests.
* :mod:`.export` — Chrome/Perfetto ``trace_event`` JSON (one lane per
  replica; a migrated request is ONE trace id hopping lanes) and a
  Prometheus text snapshot mechanically derived from
  ``SchedulerStats``/``ClusterStats``/``ProfileInfo`` with a drift
  guard asserting every counter is exported or explicitly excluded.
* :mod:`.flight_recorder` — a bounded per-lane ring of recent events
  that auto-dumps a REDACTED post-mortem on health-machine DOWN trips,
  failover errors and terminal request errors.

Cross-host correlation: a trace id is bound per request at submission
and rides the PR-12 RPC envelope (``serve/cluster/{remote,server}.py``)
— a replica server traces into its own buffer and ships the events
home inside every state-bearing response, so the front-end stitches
router + prefill replica + wire hop + decode replica into ONE timeline
even across processes.

Entry points: :func:`attach_observability` wires a tracer (and
optionally a recorder) onto a RequestManager / Replica /
ClusterManager; the CLI exposes ``--trace-out`` / ``--metrics-out`` /
``--flight-recorder`` on ``flexflow_tpu serve``.
"""
from __future__ import annotations

from typing import Optional

from .export import (
    ExportDriftError,
    check_export_coverage,
    chrome_trace,
    prometheus_text,
    write_chrome_trace,
    write_prometheus,
)
from .flight_recorder import REDACTED_ATTRS, FlightRecorder
from .tracer import NULL_TRACER, NullTracer, TraceBuffer, Tracer

__all__ = [
    "TraceBuffer",
    "Tracer",
    "NullTracer",
    "NULL_TRACER",
    "FlightRecorder",
    "REDACTED_ATTRS",
    "chrome_trace",
    "write_chrome_trace",
    "prometheus_text",
    "write_prometheus",
    "check_export_coverage",
    "ExportDriftError",
    "attach_observability",
]


def _attach_rm(rm, buffer: TraceBuffer, lane: str, recorder) -> None:
    """Wire one scheduler (RequestManager or SpecInferManager): the
    manager and every engine it keeps in sync share ONE lane-tagged
    tracer whose deterministic clock is the scheduler step counter."""
    tr = buffer.tracer(lane, clock=lambda: rm._step_counter)
    rm.tracer = tr
    rm.flight_recorder = recorder
    for eng in rm._engines():
        eng.tracer = tr


def _attach_replica(rep, buffer: TraceBuffer, recorder) -> None:
    lane = f"replica{rep.index}"
    if getattr(rep, "is_remote", False):
        # the client-side view traces the WIRE (rpc spans, retries) on
        # its own lane, clocked by the replica's client-side step
        # counter; the server-side scheduler traces into its OWN buffer
        # and its events come home inside RPC envelopes, already tagged
        # with the replica lane (loopback: the wrapped local replica;
        # socket: the subprocess enables tracing via its spec).
        rep.tracer = buffer.tracer(
            "wire", clock=lambda rep=rep: rep.steps_taken
        )
        transport = getattr(rep, "transport", None)
        if transport is not None:
            transport.tracer = rep.tracer
        if rep.local is not None:
            _attach_rm(rep.local.rm, TraceBuffer(), lane, None)
    else:
        _attach_rm(rep.rm, buffer, lane, recorder)


def attach_observability(
    target,
    *,
    buffer: Optional[TraceBuffer] = None,
    recorder: Optional[FlightRecorder] = None,
    capacity: int = 200_000,
) -> TraceBuffer:
    """Enable tracing on ``target`` — a ClusterManager, a Replica, or a
    bare RequestManager/SpecInferManager — and return the
    :class:`TraceBuffer` that collects the run's events (export it with
    :func:`write_chrome_trace` / :func:`prometheus_text`). ``recorder``
    additionally arms the flight recorder's per-lane ring + dump
    triggers. Duck-typed so :mod:`flexflow_tpu.serve` never imports
    this package on its hot path."""
    if buffer is None:
        buffer = TraceBuffer(capacity)
    if recorder is not None:
        buffer.recorder = recorder
    if hasattr(target, "replicas") and hasattr(target, "router"):
        # ClusterManager: the router/manager lane runs on cluster steps
        target.tracer = buffer.tracer(
            "router", clock=lambda: target._step_counter
        )
        target.flight_recorder = recorder
        for rep in list(target.replicas) + list(
            getattr(target, "standbys", ())
        ):
            _attach_replica(rep, buffer, recorder)
        return buffer
    if hasattr(target, "rm") and hasattr(target, "index"):
        _attach_replica(target, buffer, recorder)
        return buffer
    if hasattr(target, "engine") and hasattr(target, "_engines"):
        _attach_rm(target, buffer, "engine", recorder)
        return buffer
    raise TypeError(
        f"attach_observability: unsupported target {type(target).__name__}"
        " (expected a ClusterManager, Replica, or RequestManager)"
    )
