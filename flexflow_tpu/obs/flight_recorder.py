"""Failure flight recorder — a bounded ring of recent trace events that
auto-dumps a redacted post-mortem when something dies.

The recorder observes every event entering a
:class:`~.tracer.TraceBuffer` (``buffer.recorder = recorder``) and
keeps the last ``capacity`` events PER LANE (one lane per replica, plus
the router and wire lanes) — so when a replica circuit-breaks, the dump
is that replica's final moments, not a cluster-wide haystack. Dumps
fire on:

* health-machine **DOWN trips** (the ClusterManager's transition hook),
* **router failover errors** (a request exhausted its re-admissions or
  found no healthy replica),
* **terminal request errors** (the PR-2 ERROR contract — unservable,
  shed, failed over past the retry bound).

Every dump is **redacted** before it leaves the process: attribute keys
carrying user content (token ids, prompt text) are stripped, so a
post-mortem can be attached to a bug report without shipping the
prompt. What remains is structure: event names, lanes, trace ids, the
dual clock stamps, counters.

Deterministic by construction: the ring holds whatever the tracer
recorded (step-stamped), dump triggers are the same code paths the
health machine drives, and tests replay them under ``FaultPlan``
(tests/test_observability.py asserts a partitioned replica's dump ends
with exactly the health transition the machine recorded).
"""
from __future__ import annotations

import collections
import json
import os
from typing import Any, Deque, Dict, List, Optional

__all__ = ["FlightRecorder", "REDACTED_ATTRS"]

#: attribute keys stripped from dumped events — user content never
#: rides a post-mortem.
REDACTED_ATTRS = frozenset({"tokens", "prompt", "text", "output_text"})


def redact_event(ev: Dict[str, Any]) -> Dict[str, Any]:
    """A copy of ``ev`` with user-content attribute keys removed (and
    a marker recording that redaction happened)."""
    attrs = ev.get("attrs")
    if not attrs or not (REDACTED_ATTRS & attrs.keys()):
        return dict(ev)
    out = dict(ev)
    out["attrs"] = {
        k: v for k, v in attrs.items() if k not in REDACTED_ATTRS
    }
    out["attrs"]["redacted"] = True
    return out


class FlightRecorder:
    """Bounded per-lane event ring + dump sink (see module docstring).

    ``out_dir`` (optional) writes each dump as
    ``flightrec_<lane>_<reason>_<step>.json``; every dump is also kept
    on ``self.dumps`` (tests and the CLI read it back)."""

    def __init__(self, capacity: int = 256,
                 out_dir: Optional[str] = None):
        self.capacity = int(capacity)
        self.out_dir = out_dir
        self._rings: Dict[str, Deque[Dict[str, Any]]] = {}
        self.dumps: List[Dict[str, Any]] = []
        self.paths: List[str] = []

    # ------------------------------------------------------------------

    def observe(self, ev: Dict[str, Any]) -> None:
        """One event entering the trace buffer (called per append)."""
        lane = str(ev.get("lane", ""))
        ring = self._rings.get(lane)
        if ring is None:
            ring = self._rings[lane] = collections.deque(
                maxlen=self.capacity
            )
        ring.append(ev)

    def events(self, lane: str) -> List[Dict[str, Any]]:
        return list(self._rings.get(lane, ()))

    # ------------------------------------------------------------------

    def dump(self, lane: str, reason: str, *, step: int = 0,
             extra: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
        """Snapshot ``lane``'s ring as a redacted post-mortem document
        (written to ``out_dir`` when configured)."""
        doc: Dict[str, Any] = {
            "reason": str(reason),
            "lane": str(lane),
            "step": int(step),
            "events": [redact_event(e) for e in self._rings.get(lane, ())],
        }
        if extra:
            doc.update(extra)
        self.dumps.append(doc)
        if self.out_dir:
            os.makedirs(self.out_dir, exist_ok=True)
            path = os.path.join(
                self.out_dir,
                f"flightrec_{lane or 'untagged'}_{reason}_{int(step)}.json",
            )
            with open(path, "w") as f:
                json.dump(doc, f, indent=1)
            self.paths.append(path)
        return doc

    def dumps_for(self, lane: str) -> List[Dict[str, Any]]:
        return [d for d in self.dumps if d["lane"] == lane]
