"""Trace + metrics exporters.

Two output formats, zero dependencies:

* **Chrome/Perfetto trace_event JSON** (:func:`chrome_trace` /
  :func:`write_chrome_trace`): every :class:`~.tracer.TraceBuffer` lane
  becomes one process row (``pid`` + a ``process_name`` metadata
  record), every event a complete ``"X"`` slice whose ``tid`` is its
  trace id — so one request's lifecycle reads as one row that hops
  between replica lanes, and a migrated/failed-over request is ONE
  ``tid`` visible across the prefill replica, the wire lane and the
  decode replica. Load the file in ``ui.perfetto.dev`` or
  ``chrome://tracing``.

* **Prometheus text format** (:func:`prometheus_text` /
  :func:`write_prometheus`): mechanically derived from the repo's
  counter dataclasses — :class:`~flexflow_tpu.metrics.SchedulerStats`
  (per-replica, labeled), :class:`~flexflow_tpu.metrics.ClusterStats`,
  and per-request :class:`~flexflow_tpu.serve.batch_config.ProfileInfo`
  aggregated to ``_sum`` series. The **drift guard**
  (:func:`check_export_coverage`) asserts every dataclass field is
  either exported or explicitly excluded (with the excluded set naming
  its replacement) — adding a counter to ``metrics.py`` without
  teaching the exporter fails premerge gate 10, so the scrape surface
  can never silently fall behind the stats.
"""
from __future__ import annotations

import dataclasses
import json
from typing import Any, Dict, Iterable, List, Mapping, Optional, Sequence

__all__ = [
    "ExportDriftError",
    "chrome_trace",
    "write_chrome_trace",
    "prometheus_text",
    "write_prometheus",
    "check_export_coverage",
]


# ---------------------------------------------------------------------------
# Chrome / Perfetto trace_event JSON

def chrome_trace(events: Iterable[Dict[str, Any]],
                 *, dropped: int = 0) -> Dict[str, Any]:
    """Render tracer events (see obs/tracer.py for the schema) as a
    ``{"traceEvents": [...]}`` document. Lanes map to pids in
    first-seen order; timestamps are microseconds of the wall clock
    half of the dual stamp (the deterministic ``step`` rides in
    ``args`` for tooling and tests)."""
    lanes: Dict[str, int] = {}
    out: List[Dict[str, Any]] = []
    for ev in events:
        lane = str(ev.get("lane", ""))
        pid = lanes.get(lane)
        if pid is None:
            pid = len(lanes) + 1
            lanes[lane] = pid
            out.append({
                "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
                "args": {"name": lane or "untagged"},
            })
        tid = int(ev.get("trace_id", -1))
        args = {"step": ev.get("step", 0), "trace_id": tid}
        args.update(ev.get("attrs") or {})
        out.append({
            "name": str(ev.get("name", "event")),
            "ph": "X",
            "pid": pid,
            "tid": tid if tid >= 0 else 0,
            "ts": float(ev.get("t", 0.0)) * 1e6,
            "dur": float(ev.get("dur", 0.0)) * 1e6,
            "args": args,
        })
    doc: Dict[str, Any] = {"traceEvents": out, "displayTimeUnit": "ms"}
    if dropped:
        doc["flexflow_dropped_events"] = int(dropped)
    return doc


def write_chrome_trace(path: str, source) -> Dict[str, Any]:
    """Write ``source`` (a TraceBuffer or an event list) as a Chrome
    trace JSON file; returns the document."""
    events = getattr(source, "events", source)
    dropped = getattr(source, "dropped", 0)
    doc = chrome_trace(events, dropped=dropped)
    with open(path, "w") as f:
        json.dump(doc, f)
    return doc


# ---------------------------------------------------------------------------
# Prometheus text format — mechanically derived + drift-guarded

class ExportDriftError(AssertionError):
    """A stats dataclass field is neither exported nor explicitly
    excluded (or the exporter names a field that no longer exists) —
    the metrics surface drifted from the code."""


#: SchedulerStats fields exported verbatim as counters.
SCHED_COUNTERS = frozenset({
    "steps", "mixed_steps", "decode_steps", "sync_steps", "flushes",
    "pipeline_drains", "admitted", "preemptions", "failed",
    "prefill_tokens", "decode_tokens",
    "prefix_hits", "prefix_misses", "prefix_hit_tokens", "prefix_inserts",
    "prefix_evictions", "prefix_cows",
    "spills", "readmits", "host_hit_tokens",
    "spec_rounds", "spec_drafted", "spec_accepted", "spec_resizes",
    "verify_skipped_rounds", "spec_reprobes",
    "ring_steps", "compiles", "retraces", "whole_step_fallbacks",
})
#: SchedulerStats fields exported verbatim as gauges.
SCHED_GAUGES = frozenset({
    "host_bytes", "cp_shards", "shard_balance", "whole_step_vmem_est",
})
#: SchedulerStats fields NOT exported verbatim — each maps to the
#: derived snapshot() gauge that replaces it on the scrape surface.
SCHED_EXCLUDED = {
    "occupancy_sum": "mean_occupancy",
    "budget_fill_sum": "mean_budget_fill",
    # the raw reservoir is host-side sample storage; the scrape surface
    # carries its derived percentiles
    "decode_step_ms_samples": "decode_step_ms_p50",
}
#: Derived snapshot() rates exported as gauges alongside the counters.
SCHED_DERIVED = (
    "mean_occupancy", "mean_budget_fill", "prefix_hit_rate",
    "host_hit_rate", "spec_accept_rate",
    "decode_step_ms_p50", "decode_step_ms_p99",
)

CLUSTER_COUNTERS = frozenset({
    "submitted", "affinity_hits", "sheds", "migrations", "migrated_pages",
    "migrated_bytes", "step_faults", "replica_down", "replica_suspect",
    "probes", "replica_recoveries", "failovers", "retries",
    "failover_errors", "migration_failures", "migration_queue_overflows",
    "rpc_errors", "rpc_retries", "heartbeat_gaps", "reconnects",
    "standby_adoptions", "wire_bytes_sent", "wire_bytes_received",
    "scale_outs", "scale_ins", "pool_flips", "journal_records",
    "journal_bytes", "journal_compactions", "manager_recoveries",
    "journal_replayed", "autoscale_decisions", "retunes",
})
CLUSTER_GAUGES = frozenset({
    "migration_queue_depth", "migration_queue_peak", "rpc_inflight_peak",
    "autoscale_predicted_tps", "autoscale_measured_tps",
})
#: ``placements`` is a by-how dict — exported as ONE labeled counter
#: series rather than a scalar field. The RTT/step-time reservoirs are
#: host-side sample storage; the scrape surface carries their derived
#: percentile properties (and per-replica RTT rides the labeled
#: ``flexflow_cluster_rpc_rtt_ms`` series).
CLUSTER_EXCLUDED = {
    "placements": "flexflow_cluster_placements{how=...}",
    "cluster_step_ms_samples": "cluster_step_ms_p50",
    "rpc_rtt_ms_samples": "rpc_rtt_ms_p50",
    # per-replica maps ride the snapshot's reconciliation dict; the
    # scalar scrape surface carries the summed counters + percentiles
    "arrivals_per_replica": "arrivals_completions_per_replica",
    "completions_per_replica": "arrivals_completions_per_replica",
    "queue_delay_s_samples": "queue_delay_s_p50",
}
#: Derived ClusterStats properties exported as gauges alongside the
#: raw counters (the percentile halves of the excluded reservoirs).
CLUSTER_DERIVED = (
    "cluster_step_ms_p50", "cluster_step_ms_p99",
    "rpc_rtt_ms_p50", "rpc_rtt_ms_p99",
    "queue_delay_s_p50", "queue_delay_s_p99",
)

#: ProfileInfo numeric fields aggregated to ``_sum`` counters over the
#: finished requests handed to the exporter.
PROFILE_SUMS = frozenset({
    "cached_prefix_len", "host_hit_tokens", "llm_decoding_steps",
    "ssm_decoding_steps", "speculated_tokens", "accepted_tokens",
    "spec_rounds", "tree_resizes", "retries", "transport_retries",
    "router_queue_delay_s",
})
#: ProfileInfo fields NOT aggregated — wall-clock stamps fold into the
#: derived latency/TTFT sums; identity/shape fields are per-request
#: routing facts with no meaningful sum.
PROFILE_EXCLUDED = {
    "start_time": "flexflow_request_latency_seconds_sum",
    "finish_time": "flexflow_request_latency_seconds_sum",
    "first_token_time": "flexflow_request_ttft_seconds_sum",
    "tree_width": "per-request shape, no meaningful sum",
    "tree_depth": "per-request shape, no meaningful sum",
    "draft_flops_per_token": "per-request draft pricing, no meaningful sum",
    "context_shards": "per-request layout fact, no meaningful sum",
    "replica_id": "per-request placement fact, no meaningful sum",
    "failover_replica_id": "per-request placement fact, no meaningful sum",
}


def _stats_classes():
    from ..metrics import ClusterStats, SchedulerStats
    from ..serve.batch_config import ProfileInfo

    return SchedulerStats, ClusterStats, ProfileInfo


def check_export_coverage() -> None:
    """The drift guard: every ``SchedulerStats`` / ``ClusterStats`` /
    ``ProfileInfo`` dataclass field must be exported or explicitly
    excluded, exactly once, and the exporter must not name fields that
    no longer exist. Raises :class:`ExportDriftError` naming the
    drifted fields."""
    SchedulerStats, ClusterStats, ProfileInfo = _stats_classes()
    specs = (
        ("SchedulerStats", SchedulerStats,
         SCHED_COUNTERS | SCHED_GAUGES, set(SCHED_EXCLUDED)),
        ("ClusterStats", ClusterStats,
         CLUSTER_COUNTERS | CLUSTER_GAUGES, set(CLUSTER_EXCLUDED)),
        ("ProfileInfo", ProfileInfo, set(PROFILE_SUMS),
         set(PROFILE_EXCLUDED)),
    )
    problems: List[str] = []
    for name, cls, exported, excluded in specs:
        fields = {f.name for f in dataclasses.fields(cls)}
        missing = fields - exported - excluded
        stale = (exported | excluded) - fields
        overlap = exported & excluded
        if missing:
            problems.append(
                f"{name}: field(s) {sorted(missing)} are neither "
                "exported nor excluded — add them to the exporter maps "
                "in obs/export.py (or the excluded set, naming the "
                "replacement)"
            )
        if stale:
            problems.append(
                f"{name}: exporter names field(s) {sorted(stale)} that "
                "no longer exist on the dataclass"
            )
        if overlap:
            problems.append(
                f"{name}: field(s) {sorted(overlap)} are both exported "
                "and excluded"
            )
    if problems:
        raise ExportDriftError("\n".join(problems))


def _fmt(value: Any) -> str:
    v = float(value)
    return repr(int(v)) if v == int(v) else repr(v)


def _labels(labels: Dict[str, str]) -> str:
    if not labels:
        return ""
    body = ",".join(f'{k}="{v}"' for k, v in sorted(labels.items()))
    return "{" + body + "}"


class _Lines:
    """Prometheus text assembler: one ``# TYPE`` header per metric, in
    first-emission order."""

    def __init__(self):
        self.lines: List[str] = []
        self._typed: set = set()

    def add(self, metric: str, mtype: str, value: Any,
            labels: Optional[Dict[str, str]] = None) -> None:
        if metric not in self._typed:
            self._typed.add(metric)
            self.lines.append(f"# TYPE {metric} {mtype}")
        self.lines.append(f"{metric}{_labels(labels or {})} {_fmt(value)}")

    def text(self) -> str:
        return "\n".join(self.lines) + "\n"


def prometheus_text(
    *,
    scheduler: Optional[Mapping[str, Any]] = None,
    cluster: Any = None,
    profiles: Sequence[Any] = (),
) -> str:
    """Render a Prometheus text-format snapshot.

    ``scheduler`` maps a replica label to a SchedulerStats-shaped
    object (anything with ``snapshot()`` — live stats or a remote
    mirror); ``cluster`` is a ClusterStats; ``profiles`` are finished
    requests' ProfileInfo objects. The drift guard runs first, so a
    snapshot can never be produced from a drifted exporter."""
    check_export_coverage()
    out = _Lines()
    for label, stats in (scheduler or {}).items():
        snap = stats.snapshot()
        labels = {"replica": str(label)}
        for field in sorted(SCHED_COUNTERS):
            out.add(f"flexflow_scheduler_{field}", "counter",
                    snap.get(field, 0), labels)
        for field in sorted(SCHED_GAUGES) + list(SCHED_DERIVED):
            out.add(f"flexflow_scheduler_{field}", "gauge",
                    snap.get(field, 0), labels)
    if cluster is not None:
        for field in sorted(CLUSTER_COUNTERS):
            out.add(f"flexflow_cluster_{field}", "counter",
                    getattr(cluster, field))
        for field in sorted(CLUSTER_GAUGES) + list(CLUSTER_DERIVED):
            out.add(f"flexflow_cluster_{field}", "gauge",
                    getattr(cluster, field))
        for how, n in sorted(cluster.placements.items()):
            out.add("flexflow_cluster_placements", "counter", n,
                    {"how": str(how)})
        for idx, pcts in cluster.rpc_rtt_ms_per_replica().items():
            for q, v in sorted(pcts.items()):
                out.add("flexflow_cluster_rpc_rtt_ms", "gauge", v,
                        {"replica": str(idx), "quantile": q})
    if profiles:
        out.add("flexflow_requests_total", "counter", len(profiles))
        for field in sorted(PROFILE_SUMS):
            out.add(
                f"flexflow_request_{field}_sum", "counter",
                sum(getattr(p, field) for p in profiles),
            )
        out.add("flexflow_request_latency_seconds_sum", "counter",
                sum(p.latency_s for p in profiles))
        out.add("flexflow_request_ttft_seconds_sum", "counter",
                sum(p.ttft_s for p in profiles))
        out.add(
            "flexflow_request_first_token_observed_total", "counter",
            sum(1 for p in profiles if p.first_token_time),
        )
    return out.text()


def write_prometheus(path: str, **kw) -> str:
    text = prometheus_text(**kw)
    with open(path, "w") as f:
        f.write(text)
    return text
