"""Recompile-on-condition — the reference's only dynamic-adaptation
hook (reference ``include/flexflow/recompile.h:26-41`` RecompileState +
``FFModel::recompile_on_condition``, model.cc:2789; used by the MoE
example to rebalance experts mid-training, moe.cc:65-99).

TPU-native meaning: "recompile" = re-lower the (possibly altered) graph
to fresh jitted step functions. XLA caches compilations by shape, so an
alter that doesn't change shapes is nearly free; one that does pays one
compile. Parameters of unchanged layers carry over across the
recompile (see FFModel._maybe_recompile).
"""
from __future__ import annotations

import dataclasses
from typing import Callable


@dataclasses.dataclass
class RecompileState:
    trigger: Callable  # (FFModel) -> bool, checked once per train step
    alter: Callable    # (FFModel) -> None, mutates graph/config
    recompilations: int = 0
