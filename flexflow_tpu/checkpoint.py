"""Checkpoint / resume — orbax-backed, sharding-aware, async.

The reference can only host-read/write individual parameters
(reference ``parallel_tensor.h:164-169`` get_tensor/set_tensor) and
export *strategies*, not training state; SURVEY.md §5 sets the TPU bar
higher: native async checkpointing of the full sharded train state.
This module wraps orbax.checkpoint:

* ``save_train_state`` / ``restore_train_state`` — whole-pytree save of
  params + optimizer state + model state + step counter; restore is
  sharding-aware (each shard loads only its slice, resharding on a
  different mesh works by passing the new state template).
* ``FFModel.save_checkpoint`` / ``restore_checkpoint`` use them (see
  model.py); serving weights can round-trip the same way.

Saves are async by default (orbax writes in a background thread while
training continues — the "orbax-style async ckpt" SURVEY.md asks for);
``wait_until_finished`` or a second save joins the previous write.
"""
from __future__ import annotations

import os
from typing import Any, Dict, Optional

import jax
import numpy as np


# One long-lived manager per directory: closing a manager joins its
# background write (orbax CheckpointManager.close() calls
# wait_until_finished()), which would make every save synchronous and
# rebuild thread pools per call.
_managers: Dict[str, Any] = {}


def _manager(directory: str, max_to_keep: Optional[int] = 3):
    import orbax.checkpoint as ocp

    directory = os.path.abspath(directory)
    mgr = _managers.get(directory)
    if mgr is None:
        mgr = ocp.CheckpointManager(
            directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep,
                enable_async_checkpointing=True,
            ),
        )
        _managers[directory] = mgr
    return mgr


def save_train_state(
    directory: str,
    step: int,
    state: Dict[str, Any],
    *,
    wait: bool = False,
) -> None:
    """Save a train-state pytree (async unless ``wait``: the write runs
    in orbax's background thread while training continues)."""
    import orbax.checkpoint as ocp

    mgr = _manager(directory)
    mgr.save(int(step), args=ocp.args.StandardSave(state))
    if wait:
        mgr.wait_until_finished()


def wait_until_finished(directory: str) -> None:
    """Join any in-flight async save for ``directory``."""
    mgr = _managers.get(os.path.abspath(directory))
    if mgr is not None:
        mgr.wait_until_finished()


def latest_step(directory: str) -> Optional[int]:
    if not os.path.isdir(directory):  # don't create dirs on a read query
        return None
    mgr = _manager(directory)
    # the cached manager's step list is in-memory; re-scan so saves by
    # ANOTHER process (trainer vs evaluator) are visible
    mgr.reload()
    return mgr.latest_step()


def restore_train_state(
    directory: str,
    template: Dict[str, Any],
    *,
    step: Optional[int] = None,
) -> Dict[str, Any]:
    """Restore a train-state pytree. ``template`` provides shapes,
    dtypes AND shardings (pass the live state of a freshly compiled
    model — each host loads only its own shards)."""
    import orbax.checkpoint as ocp

    if not os.path.isdir(directory):
        raise FileNotFoundError(f"no checkpoint directory: {directory}")
    mgr = _manager(directory)
    if step is None:
        mgr.reload()  # see saves from other processes
        step = mgr.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoint found under {directory}")
    abstract = jax.tree.map(ocp.utils.to_shape_dtype_struct, template)
    try:
        return mgr.restore(int(step), args=ocp.args.StandardRestore(abstract))
    except (ValueError, KeyError, TypeError) as e:
        # structure mismatches surface as these; add the likely cause
        # without clobbering the original exception type/args
        e.add_note(
            "(checkpoint pytree structure must match the current model "
            "+ optimizer — e.g. optimizer state carries an 'lr' scalar "
            "since r3; checkpoints saved by older builds need migration)"
        )
        raise


def save_params(directory: str, params: Dict[str, Any], *, wait: bool = True):
    """Serving-weight save (one unnamed step 0)."""
    save_train_state(directory, 0, {"params": params}, wait=wait)


def load_params(directory: str, template: Dict[str, Any]) -> Dict[str, Any]:
    return restore_train_state(directory, {"params": template})["params"]
