"""Parameter initializers.

TPU-native equivalents of the reference's Initializer hierarchy
(reference ``include/flexflow/initializer.h:1-122``, ``src/runtime/
initializer.cc`` — Glorot-uniform, Zero, Constant, Uniform, Normal GPU
tasks). Here each initializer is a pure function ``(key, shape, dtype) ->
array``; they run inside the jitted init program so large weights
materialise directly on-device, sharded, with no host round-trip.
"""
from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import jax
import jax.numpy as jnp


class Initializer:
    def __call__(self, key, shape: Tuple[int, ...], dtype=jnp.float32):
        raise NotImplementedError


@dataclasses.dataclass(frozen=True)
class GlorotUniform(Initializer):
    """fan_in/fan_out computed like the reference's GlorotUniform task:
    last dim = fan_out, second-to-last = fan_in, conv receptive field
    multiplies both."""

    scale: float = 1.0

    def __call__(self, key, shape, dtype=jnp.float32):
        if len(shape) == 2:  # dense (in, out)
            fan_in, fan_out = shape
        elif len(shape) >= 3:  # conv OIHW: (out, in, *spatial)
            receptive = 1
            for d in shape[2:]:
                receptive *= d
            fan_in = shape[1] * receptive
            fan_out = shape[0] * receptive
        else:
            fan_in = fan_out = shape[0] if shape else 1
        limit = self.scale * jnp.sqrt(6.0 / (fan_in + fan_out))
        return jax.random.uniform(
            key, shape, dtype=jnp.float32, minval=-limit, maxval=limit
        ).astype(dtype)


@dataclasses.dataclass(frozen=True)
class Zero(Initializer):
    def __call__(self, key, shape, dtype=jnp.float32):
        return jnp.zeros(shape, dtype)


@dataclasses.dataclass(frozen=True)
class Constant(Initializer):
    value: float = 0.0

    def __call__(self, key, shape, dtype=jnp.float32):
        return jnp.full(shape, self.value, dtype)


@dataclasses.dataclass(frozen=True)
class Uniform(Initializer):
    min_val: float = -0.05
    max_val: float = 0.05

    def __call__(self, key, shape, dtype=jnp.float32):
        return jax.random.uniform(
            key, shape, dtype=jnp.float32, minval=self.min_val, maxval=self.max_val
        ).astype(dtype)


@dataclasses.dataclass(frozen=True)
class Normal(Initializer):
    mean: float = 0.0
    stddev: float = 1.0

    def __call__(self, key, shape, dtype=jnp.float32):
        return (
            self.mean + self.stddev * jax.random.normal(key, shape, dtype=jnp.float32)
        ).astype(dtype)


def resolve(init: Optional[object], default: Initializer) -> Initializer:
    if init is None:
        return default
    if isinstance(init, Initializer):
        return init
    if isinstance(init, str):
        return {
            "glorot_uniform": GlorotUniform(),
            "zeros": Zero(),
            "zero": Zero(),
            "normal": Normal(stddev=0.02),
            "uniform": Uniform(),
        }[init]
    raise TypeError(f"bad initializer {init!r}")
