"""Optimizers: SGD (momentum/nesterov) and Adam.

TPU-native equivalent of the reference Optimizer hierarchy (reference
``include/flexflow/optimizer.h:36-110``, ``src/runtime/optimizer.cc``,
``optimizer_kernel.cu``). The reference has two gradient-sync paths —
parameter-server accumulation in zero-copy memory vs ``ncclAllReduce``
then local update. Under GSPMD both collapse into one: gradients of
replicated params are automatically all-reduced over the ``data`` mesh
axis by XLA during the backward pass, so the optimizer here is a pure
per-shard update rule (state and params share the same sharding).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp


class Optimizer:
    """The learning rate lives in ``opt_state["lr"]`` (a device scalar),
    not baked into the compiled step — so schedulers
    (keras.callbacks.LearningRateScheduler, the reference
    ``Optimizer::set_learning_rate``) change it without a re-jit."""

    def init(self, params) -> Any:
        raise NotImplementedError

    def update(self, grads, opt_state, params) -> Tuple[Any, Any]:
        """Returns (new_params, new_opt_state)."""
        raise NotImplementedError


@dataclasses.dataclass
class SGDOptimizer(Optimizer):
    """reference ``SGDOptimizer`` (optimizer.h:36): lr, momentum, nesterov,
    weight decay."""

    lr: float = 0.01
    momentum: float = 0.0
    nesterov: bool = False
    weight_decay: float = 0.0

    def init(self, params):
        state = {"lr": jnp.asarray(self.lr, jnp.float32)}
        if self.momentum != 0.0:
            state["v"] = jax.tree.map(jnp.zeros_like, params)
        return state

    def update(self, grads, opt_state, params):
        wd = self.weight_decay
        lr = opt_state["lr"]

        if self.momentum == 0.0:
            def upd(p, g):
                g = g + wd * p if wd else g
                return (p - lr * g).astype(p.dtype)

            return jax.tree.map(upd, params, grads), opt_state

        def upd(p, g, v):
            g = g + wd * p if wd else g
            v_new = self.momentum * v + g
            step = g + self.momentum * v_new if self.nesterov else v_new
            return (p - lr * step).astype(p.dtype), v_new

        flat = jax.tree.map(upd, params, grads, opt_state["v"])
        new_params = jax.tree.map(lambda t: t[0], flat, is_leaf=lambda t: isinstance(t, tuple))
        new_v = jax.tree.map(lambda t: t[1], flat, is_leaf=lambda t: isinstance(t, tuple))
        return new_params, {"lr": opt_state["lr"], "v": new_v}


@dataclasses.dataclass
class AdamOptimizer(Optimizer):
    """reference ``AdamOptimizer`` (optimizer.h:77): bias-corrected Adam
    with the reference's alpha_t running product formulation."""

    lr: float = 0.001
    beta1: float = 0.9
    beta2: float = 0.999
    epsilon: float = 1e-8
    weight_decay: float = 0.0

    def init(self, params):
        zeros = lambda p: jnp.zeros(p.shape, jnp.float32)
        return {
            "lr": jnp.asarray(self.lr, jnp.float32),
            "m": jax.tree.map(zeros, params),
            "v": jax.tree.map(zeros, params),
            "step": jnp.zeros((), jnp.int32),
        }

    def update(self, grads, opt_state, params):
        step = opt_state["step"] + 1
        b1, b2 = self.beta1, self.beta2
        # Bias-corrected step size (reference optimizer.cc next_* updates).
        alpha_t = (
            opt_state["lr"]
            * jnp.sqrt(1.0 - jnp.power(b2, step.astype(jnp.float32)))
            / (1.0 - jnp.power(b1, step.astype(jnp.float32)))
        )
        wd = self.weight_decay

        def upd(p, g, m, v):
            g = g.astype(jnp.float32)
            if wd:
                g = g + wd * p.astype(jnp.float32)
            m_new = b1 * m + (1 - b1) * g
            v_new = b2 * v + (1 - b2) * g * g
            p_new = p.astype(jnp.float32) - alpha_t * m_new / (
                jnp.sqrt(v_new) + self.epsilon
            )
            return p_new.astype(p.dtype), m_new, v_new

        out = jax.tree.map(upd, params, grads, opt_state["m"], opt_state["v"])
        pick = lambda i: jax.tree.map(
            lambda t: t[i], out, is_leaf=lambda t: isinstance(t, tuple)
        )
        return pick(0), {
            "lr": opt_state["lr"], "m": pick(1), "v": pick(2), "step": step,
        }
