from .registry import OpDef, OpContext, register, get_op, all_ops
from . import core  # noqa: F401  (registers core ops)
from . import moe   # noqa: F401  (registers MoE ops)

__all__ = ["OpDef", "OpContext", "register", "get_op", "all_ops"]
