from .registry import OpDef, OpContext, register, get_op, all_ops
from . import core  # noqa: F401  (registers core ops)
from . import moe   # noqa: F401  (registers MoE ops)
from . import fused_transformer  # noqa: F401  (fused decoder stack)

__all__ = ["OpDef", "OpContext", "register", "get_op", "all_ops"]
