"""Operator registry — the TPU-native analog of the reference's ``Op``
base class + per-op ``Params`` structs (reference
``include/flexflow/operator.h:75-335``, ``include/flexflow/ops/*_params.h``).

Each operator is an :class:`OpDef` subclass registered by type name. An op
contributes:

  * ``infer``   — output TensorSpecs from input specs + attrs (the
                  reference's shape inference in each op's constructor).
  * ``init``    — weight pytree initialisation (reference per-op
                  ``init`` Legion tasks + Initializer kernels).
  * ``forward`` — pure function on jnp arrays; XLA fuses and lowers it to
                  MXU/VPU code, replacing the reference's hand-written CUDA
                  kernels under ``src/ops/kernels/``.
  * ``weight_pspecs`` — tensor-parallel PartitionSpecs for its weights
                  (the declarative version of the reference's
                  ``ParallelDimMappingRecord`` registry, ``operator.h:42-73``).
  * ``flops``   — analytic cost for the Unity-style search simulator.

Ops are stateless; all state (weights, rng, KV caches) flows through
arguments, which is what makes the whole graph jit-able as one XLA program.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec

from ..core.tensor import TensorSpec


@dataclasses.dataclass
class OpContext:
    """Per-call execution context (training flag, dropout rng, mesh)."""

    training: bool = False
    rng: Optional[jax.Array] = None
    mesh: Optional[Mesh] = None
    compute_dtype: Any = jnp.float32
    # Serving-only: BatchConfig-derived device metadata (set by the
    # InferenceManager; None during training).
    batch_meta: Optional[Any] = None
    # Non-trainable state (batch-norm running stats): node_id -> pytree,
    # read via ``state`` and written via ``state_updates`` — the functional
    # replacement for the reference's in-place running-stat kernels.
    state: Optional[Dict[int, Any]] = None
    state_updates: Optional[Dict[int, Any]] = None

    def fold_rng(self, node_id: int) -> Optional[jax.Array]:
        if self.rng is None:
            return None
        return jax.random.fold_in(self.rng, node_id)


_WEIGHT_SHAPE_MEMO: Dict[Any, Any] = {}


class OpDef:
    type: str = "abstract"

    def infer(self, in_specs: List[TensorSpec], attrs: Dict) -> List[TensorSpec]:
        raise NotImplementedError

    def init(self, key, in_specs: List[TensorSpec], attrs: Dict) -> Dict:
        return {}

    def forward(self, weights: Dict, inputs: List, attrs: Dict, ctx: OpContext):
        raise NotImplementedError

    def weight_pspecs(
        self, in_specs: List[TensorSpec], attrs: Dict, model_axis: str
    ) -> Dict:
        """PartitionSpec per weight leaf for Megatron-style TP. Default:
        fully replicated."""
        w = self.weight_shapes(in_specs, attrs)
        return jax.tree.map(lambda _: PartitionSpec(), w)

    def weight_shapes(self, in_specs: List[TensorSpec], attrs: Dict):
        """Abstract weight pytree (ShapeDtypeStructs), memoized — the one
        shared shape-walk used by the search cost model, strategy
        lowering, and FFModel sharding (avoids re-tracing ``init``)."""
        from ..core.graph import freeze_attrs

        key = (self.type, freeze_attrs(attrs), tuple(in_specs))
        if key not in _WEIGHT_SHAPE_MEMO:
            _WEIGHT_SHAPE_MEMO[key] = jax.eval_shape(
                lambda: self.init(jax.random.PRNGKey(0), in_specs, attrs)
            )
        return _WEIGHT_SHAPE_MEMO[key]

    def flops(self, in_specs: List[TensorSpec], attrs: Dict) -> int:
        """Forward FLOPs estimate for the search cost model."""
        return sum(s.num_elements for s in in_specs)

    # Ops that must observe/force a resharding can override this to return
    # activation PartitionSpecs for their outputs (used by the TP pass).
    def output_pspecs(
        self, in_specs: List[TensorSpec], attrs: Dict, model_axis: str
    ) -> Optional[List[PartitionSpec]]:
        return None


_REGISTRY: Dict[str, OpDef] = {}


def register(op_cls):
    """Class decorator: ``@register`` on an OpDef subclass."""
    inst = op_cls()
    if inst.type in _REGISTRY:
        raise ValueError(f"duplicate op type {inst.type!r}")
    _REGISTRY[inst.type] = inst
    return op_cls


def get_op(op_type: str) -> OpDef:
    try:
        return _REGISTRY[op_type]
    except KeyError:
        raise KeyError(
            f"unknown op type {op_type!r}; registered: {sorted(_REGISTRY)}"
        ) from None


def all_ops() -> Dict[str, OpDef]:
    return dict(_REGISTRY)
