"""Core operator library.

TPU-native equivalents of the reference's compute operators
(reference ``src/ops/`` — 127 files of Legion glue + CUDA/HIP kernels,
SURVEY.md §2.1). Each reference op's ``forward_kernel`` becomes a pure
jnp/lax function that XLA fuses and tiles onto the MXU/VPU; backward
passes come from autodiff instead of hand-written ``backward_kernel``s.

Layout conventions follow the reference's logical shapes (NCHW convs,
``(batch, seq, hidden)`` transformers) so frontends translate 1:1; XLA's
TPU layout assignment picks the physical layout.
"""
from __future__ import annotations

import math
from typing import Dict, List

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..core.dtypes import DataType
from ..core.mesh import DATA_AXIS
from ..core.tensor import TensorSpec
from .. import initializers as ffinit
from .registry import OpDef, OpContext, register


def _act(x, activation):
    """Fused activation epilogue (reference fuses these into cuBLAS/cuDNN
    calls; XLA fuses them into the matmul epilogue on TPU)."""
    if activation in (None, "", "identity"):
        return x
    if activation == "relu":
        return jax.nn.relu(x)
    if activation == "sigmoid":
        return jax.nn.sigmoid(x)
    if activation == "tanh":
        return jnp.tanh(x)
    if activation == "gelu":
        return jax.nn.gelu(x)
    if activation == "elu":
        return jax.nn.elu(x)
    if activation == "silu":
        return jax.nn.silu(x)
    raise ValueError(f"unknown activation {activation!r}")


def _wdt(weights, x):
    """Cast weights to the activation dtype (bf16 compute path)."""
    if weights is None:
        return None
    return jax.tree.map(lambda w: w.astype(x.dtype) if jnp.issubdtype(w.dtype, jnp.floating) else w, weights)


def _maybe_regularize(kernel, attrs, ctx):
    """Weight-decay penalty through the aux-loss channel (reference
    Linear REG_MODE_L1/L2, keras/regularizers.py + metrics_functions
    loss accumulation). attrs["kernel_regularizer"] = ("l1"|"l2", λ)."""
    reg = attrs.get("kernel_regularizer")
    if not reg or not ctx.training or ctx.state_updates is None:
        return
    kind, lam = reg
    if kind not in ("l1", "l2"):
        # trace-time guard: a typo'd kind must not silently become L2
        raise ValueError(f"unknown regularizer kind {kind!r} (l1|l2)")
    if kernel is None or lam <= 0.0:
        return
    w = kernel.astype(jnp.float32)
    pen = lam * (
        jnp.sum(jnp.abs(w)) if kind == "l1" else jnp.sum(w * w)
    )
    ctx.state_updates.setdefault("__aux__", []).append(pen)


# ---------------------------------------------------------------------------
# Placeholders


@register
class InputOp(OpDef):
    """INPUT placeholder — reference NoOp (src/ops/noop.cc)."""

    type = "input"

    def infer(self, in_specs, attrs):
        return [TensorSpec(tuple(attrs["shape"]), attrs["dtype"])]

    def forward(self, weights, inputs, attrs, ctx):
        raise RuntimeError("input nodes are fed, not executed")


@register
class ConstantOp(OpDef):
    """Inline constant tensor (no inputs, no weights): the value lives in
    the attrs as raw bytes and bakes into the compiled program. Used by
    frontends for traced buffers (position ids, causal masks) — the
    reference materialises such buffers as frozen weight tensors
    (python/flexflow/torch/model.py attribute tensors)."""

    type = "constant"

    def infer(self, in_specs, attrs):
        return [TensorSpec(tuple(attrs["shape"]), attrs["dtype"])]

    def _value(self, attrs):
        import numpy as np

        dt = DataType.from_any(attrs["dtype"])
        return np.frombuffer(
            attrs["data"], dtype=np.dtype(dt.value)
        ).reshape(tuple(attrs["shape"]))

    def forward(self, weights, inputs, attrs, ctx):
        val = self._value(attrs)
        return [jnp.asarray(val, dtype=val.dtype)]

    def flops(self, in_specs, attrs):
        return 0


@register
class WeightOp(OpDef):
    """WEIGHT placeholder node (standalone trainable tensor)."""

    type = "weight"

    def infer(self, in_specs, attrs):
        return [TensorSpec(tuple(attrs["shape"]), attrs["dtype"])]

    def init(self, key, in_specs, attrs):
        init = ffinit.resolve(attrs.get("initializer"), ffinit.GlorotUniform())
        dt = DataType.from_any(attrs["dtype"]).jnp_dtype
        return {"w": init(key, tuple(attrs["shape"]), dt)}

    def forward(self, weights, inputs, attrs, ctx):
        return [weights["w"]]


# ---------------------------------------------------------------------------
# Dense / embedding / matmul


@register
class DenseOp(OpDef):
    """Linear layer — reference ``src/ops/linear.cc:1-1617`` (cuBLAS GEMM +
    activation + replica-aware weight sharding). TP sharding is declared
    via the ``tp_shard`` attr set by the Megatron rewrite pass:
    'col' shards out_dim, 'row' shards in_dim (output left unreduced for a
    following all-reduce, like the reference's row-parallel Linear +
    Reduction pair)."""

    type = "dense"

    def infer(self, in_specs, attrs):
        (x,) = in_specs
        out = x.shape[:-1] + (attrs["out_dim"],)
        return [TensorSpec(out, x.dtype)]

    def init(self, key, in_specs, attrs):
        (x,) = in_specs
        in_dim, out_dim = x.shape[-1], attrs["out_dim"]
        kinit = ffinit.resolve(attrs.get("kernel_initializer"), ffinit.GlorotUniform())
        binit = ffinit.resolve(attrs.get("bias_initializer"), ffinit.Zero())
        kk, kb = jax.random.split(key)
        dt = x.jnp_dtype
        w = {"kernel": kinit(kk, (in_dim, out_dim), dt)}
        if attrs.get("use_bias", True):
            w["bias"] = binit(kb, (out_dim,), dt)
        return w

    def forward(self, weights, inputs, attrs, ctx):
        (x,) = inputs
        w = _wdt(weights, x)
        y = jnp.matmul(x, w["kernel"], preferred_element_type=jnp.float32)
        y = y.astype(x.dtype)
        if "bias" in w:
            y = y + w["bias"]
        _maybe_regularize(weights.get("kernel"), attrs, ctx)
        return [_act(y, attrs.get("activation"))]

    def weight_pspecs(self, in_specs, attrs, model_axis):
        tp = attrs.get("tp_shard")
        if tp == "col":
            specs = {"kernel": P(None, model_axis)}
            if attrs.get("use_bias", True):
                specs["bias"] = P(model_axis)
        elif tp == "row":
            specs = {"kernel": P(model_axis, None)}
            if attrs.get("use_bias", True):
                specs["bias"] = P()
        elif tp == "param":
            # parameter-parallel (ZeRO-style): weights shard over the
            # DATA axis and GSPMD all-gathers them per step; activations
            # stay batch-sharded (reference enable_parameter_parallel)
            specs = {"kernel": P(DATA_AXIS, None)}
            if attrs.get("use_bias", True):
                specs["bias"] = P()
        else:
            specs = {"kernel": P()}
            if attrs.get("use_bias", True):
                specs["bias"] = P()
        return specs

    def flops(self, in_specs, attrs):
        (x,) = in_specs
        return 2 * x.num_elements * attrs["out_dim"]


@register
class EmbeddingOp(OpDef):
    """Token embedding — reference ``src/ops/embedding.cc`` with aggr modes
    none/sum/avg."""

    type = "embedding"

    def infer(self, in_specs, attrs):
        (idx,) = in_specs
        aggr = attrs.get("aggr", "none")
        if aggr == "none":
            out = idx.shape + (attrs["out_dim"],)
        else:  # sum/avg pool the bag dimension (last)
            out = idx.shape[:-1] + (attrs["out_dim"],)
        return [TensorSpec(out, attrs.get("dtype", DataType.FLOAT))]

    def init(self, key, in_specs, attrs):
        init = ffinit.resolve(
            attrs.get("kernel_initializer"), ffinit.Normal(stddev=0.02)
        )
        dt = DataType.from_any(attrs.get("dtype", DataType.FLOAT)).jnp_dtype
        return {"table": init(key, (attrs["num_entries"], attrs["out_dim"]), dt)}

    def forward(self, weights, inputs, attrs, ctx):
        (idx,) = inputs
        table = weights["table"]
        emb = jnp.take(table, idx.astype(jnp.int32), axis=0)
        aggr = attrs.get("aggr", "none")
        if aggr == "sum":
            emb = emb.sum(axis=-2)
        elif aggr == "avg":
            emb = emb.mean(axis=-2)
        return [emb]

    def weight_pspecs(self, in_specs, attrs, model_axis):
        if attrs.get("tp_shard") == "col":
            return {"table": P(None, model_axis)}
        if attrs.get("tp_shard") == "param":
            return {"table": P(DATA_AXIS, None)}
        return {"table": P()}

    def flops(self, in_specs, attrs):
        return in_specs[0].num_elements * attrs["out_dim"]


@register
class BatchMatmulOp(OpDef):
    """Batched matmul — reference ``src/ops/batch_matmul.cc`` (with
    ``a_seq_length_dim`` used for variable-length training batches;
    reference ``model.h:581-585``). Static shapes on TPU: sequence
    truncation is handled by masking upstream rather than dynamic K."""

    type = "batch_matmul"

    def infer(self, in_specs, attrs):
        a, b = in_specs
        out = a.shape[:-1] + (b.shape[-1],)
        return [TensorSpec(out, a.dtype)]

    def forward(self, weights, inputs, attrs, ctx):
        a, b = inputs
        y = jnp.matmul(a, b, preferred_element_type=jnp.float32)
        return [y.astype(a.dtype)]

    def flops(self, in_specs, attrs):
        a, b = in_specs
        return 2 * a.num_elements * b.shape[-1]


# ---------------------------------------------------------------------------
# Convolution stack


@register
class Conv2DOp(OpDef):
    """2-D convolution (NCHW/OIHW logical layout like the reference's cuDNN
    path, ``src/ops/conv_2d.cc``); XLA re-lays-out for TPU."""

    type = "conv2d"

    def _geom(self, x_shape, attrs):
        kh, kw = attrs["kernel_h"], attrs["kernel_w"]
        sh, sw = attrs.get("stride_h", 1), attrs.get("stride_w", 1)
        ph, pw = attrs.get("padding_h", 0), attrs.get("padding_w", 0)
        n, c, h, wdim = x_shape
        oh = (h + 2 * ph - kh) // sh + 1
        ow = (wdim + 2 * pw - kw) // sw + 1
        return (kh, kw, sh, sw, ph, pw, n, c, oh, ow)

    def infer(self, in_specs, attrs):
        (x,) = in_specs
        kh, kw, sh, sw, ph, pw, n, c, oh, ow = self._geom(x.shape, attrs)
        return [TensorSpec((n, attrs["out_channels"], oh, ow), x.dtype)]

    def init(self, key, in_specs, attrs):
        (x,) = in_specs
        groups = attrs.get("groups", 1)
        cin = x.shape[1] // groups
        kinit = ffinit.resolve(attrs.get("kernel_initializer"), ffinit.GlorotUniform())
        binit = ffinit.resolve(attrs.get("bias_initializer"), ffinit.Zero())
        kk, kb = jax.random.split(key)
        dt = x.jnp_dtype
        w = {
            "kernel": kinit(
                kk, (attrs["out_channels"], cin, attrs["kernel_h"], attrs["kernel_w"]), dt
            )
        }
        if attrs.get("use_bias", True):
            w["bias"] = binit(kb, (attrs["out_channels"],), dt)
        return w

    def forward(self, weights, inputs, attrs, ctx):
        (x,) = inputs
        w = _wdt(weights, x)
        sh, sw = attrs.get("stride_h", 1), attrs.get("stride_w", 1)
        ph, pw = attrs.get("padding_h", 0), attrs.get("padding_w", 0)
        y = lax.conv_general_dilated(
            x,
            w["kernel"],
            window_strides=(sh, sw),
            padding=((ph, ph), (pw, pw)),
            dimension_numbers=("NCHW", "OIHW", "NCHW"),
            feature_group_count=attrs.get("groups", 1),
            preferred_element_type=jnp.float32,
        ).astype(x.dtype)
        if "bias" in w:
            y = y + w["bias"][None, :, None, None]
        _maybe_regularize(weights.get("kernel"), attrs, ctx)
        return [_act(y, attrs.get("activation"))]

    def flops(self, in_specs, attrs):
        (x,) = in_specs
        _, _, _, _, _, _, n, c, oh, ow = self._geom(x.shape, attrs)
        groups = attrs.get("groups", 1)
        return (
            2 * n * attrs["out_channels"] * oh * ow
            * (c // groups) * attrs["kernel_h"] * attrs["kernel_w"]
        )


@register
class Pool2DOp(OpDef):
    """Max/avg pooling — reference ``src/ops/pool_2d.cc``."""

    type = "pool2d"

    def infer(self, in_specs, attrs):
        (x,) = in_specs
        kh, kw = attrs["kernel_h"], attrs["kernel_w"]
        sh, sw = attrs.get("stride_h", 1), attrs.get("stride_w", 1)
        ph, pw = attrs.get("padding_h", 0), attrs.get("padding_w", 0)
        n, c, h, w = x.shape
        oh = (h + 2 * ph - kh) // sh + 1
        ow = (w + 2 * pw - kw) // sw + 1
        return [TensorSpec((n, c, oh, ow), x.dtype)]

    def forward(self, weights, inputs, attrs, ctx):
        (x,) = inputs
        kh, kw = attrs["kernel_h"], attrs["kernel_w"]
        sh, sw = attrs.get("stride_h", 1), attrs.get("stride_w", 1)
        ph, pw = attrs.get("padding_h", 0), attrs.get("padding_w", 0)
        dims = (1, 1, kh, kw)
        strides = (1, 1, sh, sw)
        pads = ((0, 0), (0, 0), (ph, ph), (pw, pw))
        if attrs.get("pool_type", "max") == "max":
            y = lax.reduce_window(x, -jnp.inf, lax.max, dims, strides, pads)
        else:
            s = lax.reduce_window(x, 0.0, lax.add, dims, strides, pads)
            y = s / (kh * kw)
        return [_act(y.astype(x.dtype), attrs.get("activation"))]


@register
class FlatOp(OpDef):
    """(N, C, H, W) → (N, C*H*W) — reference ``src/ops/flat.cc``."""

    type = "flat"

    def infer(self, in_specs, attrs):
        (x,) = in_specs
        n = x.shape[0]
        rest = 1
        for d in x.shape[1:]:
            rest *= d
        return [TensorSpec((n, rest), x.dtype)]

    def forward(self, weights, inputs, attrs, ctx):
        (x,) = inputs
        return [x.reshape(x.shape[0], -1)]


# ---------------------------------------------------------------------------
# Normalisation


@register
class BatchNormOp(OpDef):
    """BatchNorm over NCHW channel dim — reference ``src/ops/batch_norm.cc``.
    Running stats live in the model's non-trainable state collection and
    are updated outside the gradient path."""

    type = "batch_norm"

    def infer(self, in_specs, attrs):
        return [in_specs[0]]

    def init(self, key, in_specs, attrs):
        c = in_specs[0].shape[1]
        dt = in_specs[0].jnp_dtype
        return {"scale": jnp.ones((c,), dt), "bias": jnp.zeros((c,), dt)}

    def init_state(self, in_specs, attrs):
        c = in_specs[0].shape[1]
        return {
            "mean": jnp.zeros((c,), jnp.float32),
            "var": jnp.ones((c,), jnp.float32),
        }

    def forward(self, weights, inputs, attrs, ctx):
        (x,) = inputs
        eps = attrs.get("eps", 1e-5)
        momentum = attrs.get("momentum", 0.9)
        st = ctx.state[attrs["_node"]] if ctx.state else self.init_state(
            [TensorSpec(x.shape, x.dtype)], attrs
        )
        axes = (0, 2, 3) if x.ndim == 4 else (0,)
        if ctx.training:
            xf = x.astype(jnp.float32)
            mean = xf.mean(axis=axes)
            var = xf.var(axis=axes)
            if ctx.state_updates is not None:
                ctx.state_updates[attrs["_node"]] = {
                    "mean": momentum * st["mean"] + (1 - momentum) * lax.stop_gradient(mean),
                    "var": momentum * st["var"] + (1 - momentum) * lax.stop_gradient(var),
                }
        else:
            mean, var = st["mean"], st["var"]
        shape = (1, -1, 1, 1) if x.ndim == 4 else (1, -1)
        inv = lax.rsqrt(var + eps).reshape(shape).astype(x.dtype)
        mean = mean.reshape(shape).astype(x.dtype)
        y = (x - mean) * inv * weights["scale"].reshape(shape) + weights[
            "bias"
        ].reshape(shape)
        if attrs.get("relu", True):
            y = jax.nn.relu(y)
        return [y]


def _layer_norm(x, gamma, beta, eps, axes=(-1,)):
    axes = tuple(a % x.ndim for a in axes)
    xf = x.astype(jnp.float32)
    mean = xf.mean(axis=axes, keepdims=True)
    var = xf.var(axis=axes, keepdims=True)
    y = (xf - mean) * lax.rsqrt(var + eps)
    y = y.astype(x.dtype)
    bshape = tuple(x.shape[i] if i in axes else 1 for i in range(x.ndim))
    if gamma is not None:
        y = y * gamma.reshape(bshape)
    if beta is not None:
        y = y + beta.reshape(bshape)
    return y


def _rms_norm(x, gamma, eps):
    xf = x.astype(jnp.float32)
    rms = lax.rsqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + eps)
    return ((xf * rms).astype(x.dtype)) * gamma


@register
class LayerNormOp(OpDef):
    """reference ``src/ops/layer_norm.cc`` (last-dim normalisation)."""

    type = "layer_norm"

    def infer(self, in_specs, attrs):
        return [in_specs[0]]

    def _norm_shape(self, spec, attrs):
        ndim = spec.ndim
        axes = tuple(a % ndim for a in attrs.get("axes", (-1,)))
        return tuple(spec.shape[a] for a in sorted(axes))

    def init(self, key, in_specs, attrs):
        if not attrs.get("elementwise_affine", True):
            return {}
        shape = self._norm_shape(in_specs[0], attrs)
        dt = in_specs[0].jnp_dtype
        w = {"gamma": jnp.ones(shape, dt)}
        if attrs.get("use_bias", True):
            w["beta"] = jnp.zeros(shape, dt)
        return w

    def forward(self, weights, inputs, attrs, ctx):
        (x,) = inputs
        w = _wdt(weights, x)
        return [
            _layer_norm(
                x,
                w.get("gamma"),
                w.get("beta"),
                attrs.get("eps", 1e-5),
                axes=tuple(attrs.get("axes", (-1,))),
            )
        ]


@register
class RMSNormOp(OpDef):
    """reference ``src/ops/rms_norm.cc``."""

    type = "rms_norm"

    def infer(self, in_specs, attrs):
        return [in_specs[0]]

    def init(self, key, in_specs, attrs):
        d = in_specs[0].shape[-1]
        return {"gamma": jnp.ones((d,), in_specs[0].jnp_dtype)}

    def forward(self, weights, inputs, attrs, ctx):
        (x,) = inputs
        w = _wdt(weights, x)
        return [_rms_norm(x, w["gamma"], attrs.get("eps", 1e-6))]


@register
class ResidualRMSNormOp(OpDef):
    """Fused residual-add + RMSNorm, two outputs (sum, normed) — reference
    ``src/ops/residual_rms_norm.cc``."""

    type = "residual_rms_norm"

    def infer(self, in_specs, attrs):
        return [in_specs[0], in_specs[0]]

    def init(self, key, in_specs, attrs):
        d = in_specs[0].shape[-1]
        return {"gamma": jnp.ones((d,), in_specs[0].jnp_dtype)}

    def forward(self, weights, inputs, attrs, ctx):
        x, res = inputs
        w = _wdt(weights, x)
        s = x + res
        return [s, _rms_norm(s, w["gamma"], attrs.get("eps", 1e-6))]


@register
class ResidualLayerNormOp(OpDef):
    """Fused residual-add(s) + LayerNorm — reference
    ``src/ops/residual_layer_norm.cc``."""

    type = "residual_layer_norm"

    def infer(self, in_specs, attrs):
        return [in_specs[0], in_specs[0]]

    def init(self, key, in_specs, attrs):
        if not attrs.get("elementwise_affine", True):
            return {}
        d = in_specs[0].shape[-1]
        dt = in_specs[0].jnp_dtype
        w = {"gamma": jnp.ones((d,), dt)}
        if attrs.get("use_bias", True):
            w["beta"] = jnp.zeros((d,), dt)
        return w

    def forward(self, weights, inputs, attrs, ctx):
        x = inputs[0]
        s = x
        for r in inputs[1:]:
            s = s + r
        w = _wdt(weights, x)
        return [s, _layer_norm(s, w.get("gamma"), w.get("beta"), attrs.get("eps", 1e-5))]


@register
class AddBiasResidualLayerNormOp(OpDef):
    """reference ``src/ops/add_bias_residual_layer_norm.cc``: out = LN(x +
    attn_out_bias + residual)."""

    type = "add_bias_residual_layer_norm"

    def infer(self, in_specs, attrs):
        return [in_specs[0], in_specs[0]]

    def init(self, key, in_specs, attrs):
        d = in_specs[0].shape[-1]
        dt = in_specs[0].jnp_dtype
        w = {"attn_bias": jnp.zeros((d,), dt)}
        if attrs.get("elementwise_affine", True):
            w["gamma"] = jnp.ones((d,), dt)
            if attrs.get("use_bias", True):
                w["beta"] = jnp.zeros((d,), dt)
        return w

    def forward(self, weights, inputs, attrs, ctx):
        x, res = inputs
        w = _wdt(weights, x)
        s = x + w["attn_bias"] + res
        return [s, _layer_norm(s, w.get("gamma"), w.get("beta"), attrs.get("eps", 1e-5))]


@register
class SigmoidSiluMultiOp(OpDef):
    """SwiGLU glue: silu(x1) * x2 — reference ``src/ops/sigmoid_silu_multi.cc``."""

    type = "sigmoid_silu_multi"

    def infer(self, in_specs, attrs):
        return [in_specs[0]]

    def forward(self, weights, inputs, attrs, ctx):
        x1, x2 = inputs
        return [jax.nn.silu(x1) * x2]


# ---------------------------------------------------------------------------
# Elementwise / shape ops


_UNARY = {
    "relu": jax.nn.relu,
    "sigmoid": jax.nn.sigmoid,
    "tanh": jnp.tanh,
    "elu": jax.nn.elu,
    "gelu": jax.nn.gelu,
    "silu": jax.nn.silu,
    "identity": lambda x: x,
    "exp": jnp.exp,
    "log": jnp.log,
    "sin": jnp.sin,
    "cos": jnp.cos,
    "sqrt": jnp.sqrt,
    "rsqrt": lax.rsqrt,
    "negative": jnp.negative,
}

_UNARY_SCALAR = {
    "scalar_multiply": lambda x, s: x * s,
    "scalar_add": lambda x, s: x + s,
    "scalar_sub": lambda x, s: x - s,
    "scalar_truediv": lambda x, s: x / s,
    "pow": lambda x, s: jnp.power(x, s),
    # comparisons yield 0/1 in the input dtype (frontends import traced
    # masks like `(x > 0).float()` through these)
    "scalar_gt": lambda x, s: (x > s).astype(x.dtype),
    "scalar_lt": lambda x, s: (x < s).astype(x.dtype),
    "scalar_ge": lambda x, s: (x >= s).astype(x.dtype),
    "scalar_le": lambda x, s: (x <= s).astype(x.dtype),
    "scalar_eq": lambda x, s: (x == s).astype(x.dtype),
}


@register
class ElementUnaryOp(OpDef):
    """reference ``src/ops/element_unary.cc``."""

    type = "element_unary"

    def infer(self, in_specs, attrs):
        return [in_specs[0]]

    def forward(self, weights, inputs, attrs, ctx):
        (x,) = inputs
        op = attrs["op"]
        if op in _UNARY:
            return [_UNARY[op](x)]
        return [_UNARY_SCALAR[op](x, attrs["scalar"])]


_BINARY = {
    "add": jnp.add,
    "subtract": jnp.subtract,
    "multiply": jnp.multiply,
    "divide": jnp.divide,
    "max": jnp.maximum,
    "min": jnp.minimum,
}


@register
class ElementBinaryOp(OpDef):
    """reference ``src/ops/element_binary.cc`` (broadcasting ew ops)."""

    type = "element_binary"

    def infer(self, in_specs, attrs):
        a, b = in_specs
        shape = jnp.broadcast_shapes(a.shape, b.shape)
        return [TensorSpec(shape, a.dtype)]

    def forward(self, weights, inputs, attrs, ctx):
        a, b = inputs
        return [_BINARY[attrs["op"]](a, b)]


@register
class SoftmaxOp(OpDef):
    """reference ``src/ops/softmax.cc``."""

    type = "softmax"

    def infer(self, in_specs, attrs):
        return [in_specs[0]]

    def forward(self, weights, inputs, attrs, ctx):
        (x,) = inputs
        return [jax.nn.softmax(x, axis=attrs.get("axis", -1))]


@register
class DropoutOp(OpDef):
    """reference ``src/ops/dropout.cc`` (cuDNN dropout); here a jax.random
    mask keyed per-node from the step rng."""

    type = "dropout"

    def infer(self, in_specs, attrs):
        return [in_specs[0]]

    def forward(self, weights, inputs, attrs, ctx):
        (x,) = inputs
        rate = attrs.get("rate", 0.5)
        if not ctx.training or rate <= 0.0:
            return [x]
        rng = ctx.fold_rng(attrs["_node"])
        keep = jax.random.bernoulli(rng, 1.0 - rate, x.shape)
        return [jnp.where(keep, x / (1.0 - rate), 0).astype(x.dtype)]


@register
class CastOp(OpDef):
    type = "cast"

    def infer(self, in_specs, attrs):
        return [in_specs[0].with_dtype(attrs["dtype"])]

    def forward(self, weights, inputs, attrs, ctx):
        dt = DataType.from_any(attrs["dtype"]).jnp_dtype
        return [inputs[0].astype(dt)]


@register
class ConcatOp(OpDef):
    type = "concat"

    def infer(self, in_specs, attrs):
        ax = attrs.get("axis", 0)
        shape = list(in_specs[0].shape)
        shape[ax] = sum(s.shape[ax] for s in in_specs)
        return [TensorSpec(tuple(shape), in_specs[0].dtype)]

    def forward(self, weights, inputs, attrs, ctx):
        return [jnp.concatenate(inputs, axis=attrs.get("axis", 0))]


@register
class SplitOp(OpDef):
    type = "split"

    def infer(self, in_specs, attrs):
        (x,) = in_specs
        ax = attrs.get("axis", 0)
        out = []
        for sz in attrs["sizes"]:
            shape = list(x.shape)
            shape[ax] = sz
            out.append(TensorSpec(tuple(shape), x.dtype))
        return out

    def forward(self, weights, inputs, attrs, ctx):
        (x,) = inputs
        ax = attrs.get("axis", 0)
        splits = []
        ofs = 0
        for sz in attrs["sizes"]:
            splits.append(lax.slice_in_dim(x, ofs, ofs + sz, axis=ax))
            ofs += sz
        return splits


@register
class ReshapeOp(OpDef):
    type = "reshape"

    def infer(self, in_specs, attrs):
        return [TensorSpec(tuple(attrs["shape"]), in_specs[0].dtype)]

    def forward(self, weights, inputs, attrs, ctx):
        return [inputs[0].reshape(tuple(attrs["shape"]))]


@register
class TransposeOp(OpDef):
    type = "transpose"

    def infer(self, in_specs, attrs):
        (x,) = in_specs
        perm = attrs["perm"]
        return [TensorSpec(tuple(x.shape[p] for p in perm), x.dtype)]

    def forward(self, weights, inputs, attrs, ctx):
        return [jnp.transpose(inputs[0], attrs["perm"])]


@register
class ReverseOp(OpDef):
    type = "reverse"

    def infer(self, in_specs, attrs):
        return [in_specs[0]]

    def forward(self, weights, inputs, attrs, ctx):
        return [jnp.flip(inputs[0], axis=attrs.get("axis", 0))]


@register
class ReduceOp(OpDef):
    """reduce_sum / reduce_mean / reduce_max — reference ``src/ops/reduce.cc``,
    ``mean.cc``."""

    type = "reduce"

    def infer(self, in_specs, attrs):
        (x,) = in_specs
        axes = tuple(a % x.ndim for a in attrs["axes"])
        keep = attrs.get("keepdims", False)
        shape = []
        for i, d in enumerate(x.shape):
            if i in axes:
                if keep:
                    shape.append(1)
            else:
                shape.append(d)
        return [TensorSpec(tuple(shape), x.dtype)]

    def forward(self, weights, inputs, attrs, ctx):
        (x,) = inputs
        fn = {"sum": jnp.sum, "mean": jnp.mean, "max": jnp.max, "min": jnp.min}[
            attrs.get("op", "sum")
        ]
        axes = tuple(a % x.ndim for a in attrs["axes"])
        return [fn(x, axis=axes, keepdims=attrs.get("keepdims", False))]


@register
class GatherOp(OpDef):
    """take_along_axis — reference ``src/ops/gather.cc``."""

    type = "gather"

    def infer(self, in_specs, attrs):
        data, idx = in_specs
        return [TensorSpec(idx.shape, data.dtype)]

    def forward(self, weights, inputs, attrs, ctx):
        data, idx = inputs
        return [jnp.take_along_axis(data, idx.astype(jnp.int32), axis=attrs.get("axis", -1))]


# ---------------------------------------------------------------------------
# Attention (training path)


@register
class MultiHeadAttentionOp(OpDef):
    """Classic training MHA — reference ``src/ops/attention.cc`` (cuDNN
    MHA). Inputs (query, key, value) shaped (B, L, D); optional causal
    mask for decoder training (a capability the reference routes through
    its serving ops instead)."""

    type = "multihead_attention"

    def infer(self, in_specs, attrs):
        q = in_specs[0]
        return [TensorSpec(q.shape[:-1] + (attrs["embed_dim"],), q.dtype)]

    def init(self, key, in_specs, attrs):
        d = in_specs[0].shape[-1]
        h = attrs["num_heads"]
        dk = attrs.get("kdim") or attrs["embed_dim"] // h
        dv = attrs.get("vdim") or attrs["embed_dim"] // h
        e = attrs["embed_dim"]
        ks = jax.random.split(key, 4)
        gi = ffinit.GlorotUniform()
        dt = in_specs[0].jnp_dtype
        w = {
            "wq": gi(ks[0], (d, h * dk), dt),
            "wk": gi(ks[1], (in_specs[1].shape[-1], h * dk), dt),
            "wv": gi(ks[2], (in_specs[2].shape[-1], h * dv), dt),
            "wo": gi(ks[3], (h * dv, e), dt),
        }
        if attrs.get("bias", True):
            w["bq"] = jnp.zeros((h * dk,), dt)
            w["bk"] = jnp.zeros((h * dk,), dt)
            w["bv"] = jnp.zeros((h * dv,), dt)
            w["bo"] = jnp.zeros((e,), dt)
        return w

    def forward(self, weights, inputs, attrs, ctx):
        q_in, k_in, v_in = inputs
        w = _wdt(weights, q_in)
        h = attrs["num_heads"]
        dk = attrs.get("kdim") or attrs["embed_dim"] // h
        dv = attrs.get("vdim") or attrs["embed_dim"] // h
        B, Lq, _ = q_in.shape
        Lk = k_in.shape[1]

        def proj(x, wname, bname, dd):
            y = jnp.matmul(x, w[wname], preferred_element_type=jnp.float32).astype(x.dtype)
            if bname in w:
                y = y + w[bname]
            return y.reshape(x.shape[0], x.shape[1], h, dd)

        q = proj(q_in, "wq", "bq", dk)
        k = proj(k_in, "wk", "bk", dk)
        v = proj(v_in, "wv", "bv", dv)
        scores = jnp.einsum(
            "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
        ) / math.sqrt(dk)
        if attrs.get("causal", False):
            mask = jnp.tril(jnp.ones((Lq, Lk), bool))
            scores = jnp.where(mask[None, None], scores, -1e9)
        probs = jax.nn.softmax(scores, axis=-1).astype(q_in.dtype)
        rate = attrs.get("dropout", 0.0)
        if ctx.training and rate > 0.0:
            rng = ctx.fold_rng(attrs["_node"])
            keep = jax.random.bernoulli(rng, 1.0 - rate, probs.shape)
            probs = jnp.where(keep, probs / (1.0 - rate), 0).astype(probs.dtype)
        o = jnp.einsum("bhqk,bkhd->bqhd", probs, v).reshape(B, Lq, h * dv)
        y = jnp.matmul(o, w["wo"], preferred_element_type=jnp.float32).astype(q_in.dtype)
        if "bo" in w:
            y = y + w["bo"]
        return [y]

    def weight_pspecs(self, in_specs, attrs, model_axis):
        # Head-parallel: shard the head dim (columns of wq/wk/wv, rows of wo)
        if attrs.get("tp_shard") == "heads":
            specs = {
                "wq": P(None, model_axis),
                "wk": P(None, model_axis),
                "wv": P(None, model_axis),
                "wo": P(model_axis, None),
            }
            if attrs.get("bias", True):
                specs.update(
                    bq=P(model_axis), bk=P(model_axis), bv=P(model_axis), bo=P()
                )
            return specs
        return super().weight_pspecs(in_specs, attrs, model_axis)

    def flops(self, in_specs, attrs):
        q = in_specs[0]
        B, Lq, D = q.shape
        Lk = in_specs[1].shape[1]
        e = attrs["embed_dim"]
        return 2 * B * (Lq * D * e * 3 + Lq * Lk * e * 2 + Lq * e * e)
