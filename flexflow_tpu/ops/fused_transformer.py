"""Fused transformer decoder stack as ONE graph-IR operator.

This op is the bridge between the graph-IR training stack (FFModel +
Unity search) and the fast hand-sharded path (models/llama.py): the
whole N-layer decoder — RMSNorm → QKV+RoPE → attention → residual →
SwiGLU FFN, scanned over stacked layer weights with per-block remat and
optionally the Pallas flash-attention kernel — executes as a single op
inside ``FFModel.run_graph``. The Unity search prices and shards it like
any other node, so ``compile(auto_parallel=True)`` now reaches the same
compiled program quality as ``llama.make_train_step`` instead of the
interpreted per-op graph.

The reference gets the equivalent effect from its FusedOp + the
substitution rules that pack a transformer block into fused operators
(reference ``src/ops/fused.cc``, ``graph_subst_3_v2.json`` transformer
rules); on TPU the fusion *inside* the op is XLA's job — what this op
contributes is scan-over-layers (compile time independent of depth),
``jax.checkpoint`` remat, and the flash-attention kernel, none of which
the per-op graph interpretation can express.

Sharding: the ``TP_MEGATRON`` strategy state maps to the classic
Megatron layout (QKV/up column-parallel, O/down row-parallel on the
``model`` axis; GSPMD inserts the two per-layer all-reduces). Input and
output activations are batch-sharded full-feature tensors, so from the
search's resharding point of view the op behaves like a DP node.
"""
from __future__ import annotations

import functools
from typing import Dict, List

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..core.tensor import TensorSpec
from .. import initializers as ffinit  # noqa: F401  (kept for API symmetry)
from .registry import OpDef, register


def _cfg_from_attrs(attrs: Dict, D: int, S: int, dtype):
    from ..models import llama

    H = attrs["num_heads"]
    return llama.LLaMAConfig(
        vocab_size=1,  # unused: embed/head live outside this op
        hidden_size=D,
        intermediate_size=attrs["intermediate_size"],
        num_hidden_layers=attrs["num_layers"],
        num_attention_heads=H,
        num_key_value_heads=attrs.get("num_kv_heads") or H,
        rms_norm_eps=attrs.get("eps", 1e-6),
        rope_theta=attrs.get("rope_theta", 10000.0),
        max_position_embeddings=max(S, 1),
        dtype=dtype,
    )


@register
class TransformerDecoderStackOp(OpDef):
    """N fused decoder blocks over (B, S, D) hidden states.

    attrs: num_layers, num_heads, num_kv_heads (None = MHA),
    intermediate_size, eps, rope_theta, remat (default True), attention
    ("xla" | "flash" — the Pallas kernel, ops/flash_attention.py).
    """

    type = "transformer_decoder_stack"

    def infer(self, in_specs: List[TensorSpec], attrs: Dict) -> List[TensorSpec]:
        (x,) = in_specs
        assert x.ndim == 3, "decoder stack input must be (B, S, D)"
        D, H = x.shape[-1], attrs["num_heads"]
        assert D % H == 0, f"hidden {D} not divisible by heads {H}"
        kv = attrs.get("num_kv_heads") or H
        assert H % kv == 0, f"heads {H} not divisible by kv heads {kv}"
        return [x]

    def init(self, key, in_specs: List[TensorSpec], attrs: Dict) -> Dict:
        from ..models import llama

        (x,) = in_specs
        cfg = _cfg_from_attrs(attrs, x.shape[-1], x.shape[1], x.jnp_dtype)
        # init_params builds embed/head too (tiny at vocab_size=1);
        # keep only the stacked layer weights this op owns.
        full = llama.init_params(key, cfg)
        return full["layers"]

    def forward(self, weights, inputs, attrs, ctx):
        from ..models import llama

        (x,) = inputs
        B, S, D = x.shape
        cfg = _cfg_from_attrs(attrs, D, S, x.dtype)
        positions = jnp.arange(S, dtype=jnp.int32)
        cos, sin = llama.rope_freqs(cfg, positions)
        attn_impl = attrs.get("attention", "xla")
        attn_fn = llama.make_flash_attention() if attn_impl == "flash" else None
        mask = None if attn_fn is not None else llama.causal_mask(S)
        blk = functools.partial(llama.block, cfg, attn_fn=attn_fn)
        if attrs.get("remat", True):
            from ..core.remat import resolve_remat_policy

            blk = jax.checkpoint(
                blk, policy=resolve_remat_policy(attrs.get("remat_policy"))
            )

        def body(carry, p_l):
            y, _ = blk(p_l, carry, cos, sin, mask)
            return y, None

        y, _ = lax.scan(body, x, weights)
        return [y]

    # -- search/sharding hooks -----------------------------------------

    def weight_pspecs(self, in_specs, attrs, model_axis):
        if attrs.get("tp_shard") == "megatron":
            return {
                "attn_norm": P(None, None),
                "wq": P(None, None, model_axis),
                "wk": P(None, None, model_axis),
                "wv": P(None, None, model_axis),
                "wo": P(None, model_axis, None),
                "ffn_norm": P(None, None),
                "w1": P(None, None, model_axis),
                "w2": P(None, model_axis, None),
                "w3": P(None, None, model_axis),
            }
        return super().weight_pspecs(in_specs, attrs, model_axis)

    def flops(self, in_specs, attrs):
        (x,) = in_specs
        B, S, D = x.shape
        L, H = attrs["num_layers"], attrs["num_heads"]
        kv = attrs.get("num_kv_heads") or H
        dk = D // H
        F = attrs["intermediate_size"]
        per_layer_params = (
            D * (H * dk) + 2 * D * (kv * dk) + (H * dk) * D + 3 * D * F
        )
        # 2 FLOPs per param per token + the S-quadratic attention term
        return B * S * (2 * L * per_layer_params + 4 * L * D * S)

    def activation_bytes(self, in_specs, attrs, training: bool) -> float:
        """Live activation bytes for the memory model: with full
        per-block remat only the L inter-block boundaries are saved for
        backward (plus one block's working set, dominated by the
        boundaries for realistic L). The "dots" policy additionally
        keeps every matmul output, so its footprint is modelled like
        no-remat (a conservative upper bound — softmax/norm
        intermediates are the recomputed part)."""
        (x,) = in_specs
        xb = float(x.size_bytes)
        if not training:
            return xb
        full_remat = attrs.get("remat", True) and not attrs.get("remat_policy")
        if full_remat:
            return (attrs["num_layers"] + 1) * xb
        # no remat / dots policy: blocks keep hidden + qkv + ffn dots
        F = attrs["intermediate_size"]
        D = x.shape[-1]
        return attrs["num_layers"] * xb * (4 + 2 * F / D)

    def internal_collectives(self, in_specs, attrs, state: str, training: bool):
        """Per-step collectives GSPMD inserts *inside* this op under the
        given sharding state: Megatron TP pays one all-reduce of the
        (per-data-shard) activation after attention and one after the
        FFN per layer, and the backward pass mirrors both."""
        if state != "TP_MEGATRON":
            return []
        (x,) = in_specs
        act_bytes = float(x.size_bytes)
        per_layer = 2 * (2 if training else 1)
        return [("all_reduce", act_bytes)] * (per_layer * attrs["num_layers"])
