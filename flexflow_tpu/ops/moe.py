"""Mixture-of-Experts operators.

The reference ships two MoE paths (SURVEY.md §2.1/§2.2 EP):

  * training: ``top_k → group_by → per-expert dense → aggregate`` with a
    load-balancing term (reference ``model.h:509-531,622-645``,
    ``src/ops/{topk,group_by,aggregate}.cc``, MoE example
    ``examples/cpp/mixture_of_experts/moe.cc:100-130``);
  * inference: the fused ``Experts`` op — thrust-sorted token routing +
    batched GEMMs with expert-range sharding (``src/ops/experts.cc``,
    params ``num_experts``/``experts_start_idx``).

TPU re-design: scatter/sort routing is hostile to the MXU, so dispatch
is **dense one-hot matmul** (Switch-Transformer style): tokens →
capacity-bucketed one-hot dispatch tensor → batched expert GEMMs via
einsum → weighted combine. Everything is static-shaped, vmappable, and
the expert dim shards over the ``expert`` mesh axis so each device
group holds only its expert range (the TPU version of
``experts_start_idx`` range sharding); GSPMD inserts the all-to-alls.

Ops registered here:
  * ``top_k``     — router values+indices (reference topk.cc)
  * ``group_by``  — dispatch tokens to (E, C, D) expert buckets
  * ``aggregate`` — weighted combine back to (N, D), adds the
                    load-balance aux loss during training
  * ``moe``       — fused gate+dispatch+experts+combine layer
  * ``experts``   — fused expert FFN on pre-computed routing (inference)
"""
from __future__ import annotations

import math
from typing import Any, Dict, List

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..core.mesh import EXPERT_AXIS
from ..core.tensor import TensorSpec
from ..core.dtypes import DataType
from .registry import OpDef, register
from .. import initializers as ffinit


def _capacity(num_tokens: int, num_experts: int, top_k: int, factor: float) -> int:
    return max(1, int(math.ceil(top_k * num_tokens / num_experts * factor)))


def _dispatch_from_topk(gates: jnp.ndarray, idx: jnp.ndarray, num_experts: int,
                        capacity: int):
    """(gates, idx) (N, K) → dispatch (N, E, C) one-hot + gate-weighted
    combine (N, E, C). Queue positions are assigned k-major then
    token-order (cumsum over the flattened (K, N) axis); tokens beyond
    an expert's capacity are dropped — standard Switch semantics, and
    the reference's group_by likewise truncates at ``alpha``-scaled
    capacity. Shared by the training (moe/group_by) and inference
    (experts) paths."""
    N, K = gates.shape
    dt = gates.dtype
    gates = gates / jnp.maximum(gates.sum(-1, keepdims=True), 1e-9)
    onehot = jax.nn.one_hot(idx, num_experts, dtype=dt)        # (N, K, E)
    pos = jnp.cumsum(onehot.transpose(1, 0, 2).reshape(K * N, num_experts), axis=0)
    pos = (pos - 1).reshape(K, N, num_experts).transpose(1, 0, 2)   # (N, K, E)
    within = (pos < capacity) & (onehot > 0)
    pos_clipped = jnp.clip(pos, 0, capacity - 1).astype(jnp.int32)
    slot = jax.nn.one_hot(pos_clipped, capacity, dtype=dt)     # (N,K,E,C)
    dispatch_k = slot * within[..., None].astype(dt) * onehot[..., None]
    dispatch = dispatch_k.sum(axis=1)                          # (N, E, C)
    combine = (dispatch_k * gates[:, :, None, None]).sum(axis=1)
    return dispatch, combine


def _routing(probs: jnp.ndarray, top_k: int, capacity: int):
    """probs (N, E) → (dispatch, combine, gates, idx)."""
    gates, idx = lax.top_k(probs, top_k)                      # (N, K)
    dispatch, combine = _dispatch_from_topk(gates, idx, probs.shape[-1], capacity)
    gates = gates / jnp.maximum(gates.sum(-1, keepdims=True), 1e-9)
    return dispatch, combine, gates, idx


def _load_balance_loss(probs: jnp.ndarray, dispatch: jnp.ndarray) -> jnp.ndarray:
    """Switch load-balance loss: E · Σ_e fraction_e · mean-prob_e (the
    reference's aggregate λ term)."""
    E = probs.shape[-1]
    frac = (dispatch.sum(axis=2) > 0).astype(jnp.float32).mean(axis=0)  # (E,)
    mean_prob = probs.astype(jnp.float32).mean(axis=0)
    return E * jnp.sum(frac * mean_prob)


def _expert_ffn(x_ecd, w, activation: str):
    """Batched per-expert FFN: (E, C, D) × (E, D, F) × (E, F, D)."""
    h = jnp.einsum("ecd,edf->ecf", x_ecd, w["w1"],
                   preferred_element_type=jnp.float32).astype(x_ecd.dtype)
    if "b1" in w:
        h = h + w["b1"][:, None, :]
    if activation == "relu":
        h = jax.nn.relu(h)
    elif activation == "gelu":
        h = jax.nn.gelu(h)
    elif activation == "silu":
        h = jax.nn.silu(h)
    elif activation not in (None, "none"):
        raise ValueError(f"unknown expert activation {activation!r}")
    y = jnp.einsum("ecf,efd->ecd", h, w["w2"],
                   preferred_element_type=jnp.float32).astype(x_ecd.dtype)
    if "b2" in w:
        y = y + w["b2"][:, None, :]
    return y


def _maybe_constrain_experts(t, ctx, spec):
    mesh = getattr(ctx, "mesh", None)
    if mesh is not None and EXPERT_AXIS in mesh.shape and mesh.shape[EXPERT_AXIS] > 1:
        return lax.with_sharding_constraint(t, spec)
    return t


@register
class TopKOp(OpDef):
    """Router top-k — reference ``src/ops/topk.cc`` / ``arg_topk.cc``."""

    type = "top_k"

    def infer(self, in_specs, attrs):
        (x,) = in_specs
        k = attrs["k"]
        out = x.shape[:-1] + (k,)
        return [TensorSpec(out, x.dtype), TensorSpec(out, DataType.INT32)]

    def forward(self, weights, inputs, attrs, ctx):
        (x,) = inputs
        vals, idx = lax.top_k(x, attrs["k"])
        return [vals, idx.astype(jnp.int32)]

    def flops(self, in_specs, attrs):
        return in_specs[0].num_elements * int(math.log2(max(2, attrs["k"])))


@register
class GroupByOp(OpDef):
    """Dispatch tokens into per-expert capacity buckets — reference
    ``src/ops/group_by.cc`` (its CUDA scatter becomes a one-hot matmul).
    Inputs: x (N, D), probs (N, E). Outputs: buckets (E, C, D),
    dispatch (N, E, C), combine (N, E, C)."""

    type = "group_by"

    def infer(self, in_specs, attrs):
        x, probs = in_specs
        D = x.shape[-1]
        N = x.num_elements // D                 # leading dims flatten
        E = probs.shape[-1]
        C = _capacity(N, E, attrs["k"], attrs.get("capacity_factor", 1.25))
        return [
            TensorSpec((E, C, D), x.dtype),
            TensorSpec((N, E, C), x.dtype),
            TensorSpec((N, E, C), x.dtype),
        ]

    def forward(self, weights, inputs, attrs, ctx):
        x, probs = inputs
        x = x.reshape(-1, x.shape[-1])          # accept (B, S, D) tokens
        probs = probs.reshape(-1, probs.shape[-1])
        N, D = x.shape
        E = probs.shape[-1]
        C = _capacity(N, E, attrs["k"], attrs.get("capacity_factor", 1.25))
        dispatch, combine, _, _ = _routing(probs, attrs["k"], C)
        buckets = jnp.einsum("nec,nd->ecd", dispatch, x,
                             preferred_element_type=jnp.float32).astype(x.dtype)
        buckets = _maybe_constrain_experts(buckets, ctx, P(EXPERT_AXIS, None, None))
        return [buckets, dispatch, combine]

    def flops(self, in_specs, attrs):
        x, probs = in_specs
        E = probs.shape[-1]
        C = _capacity(x.shape[0], E, attrs["k"], attrs.get("capacity_factor", 1.25))
        return 2 * x.num_elements * E * C  # 'nec,nd->ecd' = 2·N·D·E·C


@register
class AggregateOp(OpDef):
    """Weighted combine of expert outputs — reference
    ``src/ops/aggregate.cc`` (adds the load-balance aux loss in
    training, like the reference's λ term in aggregate's backward).
    Inputs: expert_out (E, C, D), combine (N, E, C), probs (N, E)."""

    type = "aggregate"

    def infer(self, in_specs, attrs):
        eo, combine, probs = in_specs
        N = combine.shape[0]
        return [TensorSpec((N, eo.shape[-1]), eo.dtype)]

    def forward(self, weights, inputs, attrs, ctx):
        expert_out, combine, probs = inputs
        y = jnp.einsum("nec,ecd->nd", combine, expert_out,
                       preferred_element_type=jnp.float32).astype(expert_out.dtype)
        lam = attrs.get("load_balance_lambda", 0.0)
        if ctx.training and lam > 0.0 and ctx.state_updates is not None:
            dispatch = (combine > 0).astype(jnp.float32)
            aux = lam * _load_balance_loss(probs, dispatch)
            ctx.state_updates.setdefault("__aux__", []).append(aux)
        return [y]

    def flops(self, in_specs, attrs):
        eo, combine, _ = in_specs
        return 2 * combine.num_elements * eo.shape[-1]


@register
class MoEOp(OpDef):
    """Fused MoE layer: gate → top-k dispatch → batched expert FFNs →
    combine (+ aux loss). The TPU equivalent of the reference's MoE
    wrapper (``FFModel::moe``, model.h:622-645) and the training
    composition in the MoE example. Expert weights carry a leading E dim
    sharded over the ``expert`` mesh axis."""

    type = "moe"

    def infer(self, in_specs, attrs):
        (x,) = in_specs
        return [TensorSpec(x.shape, x.dtype)]

    def init(self, key, in_specs, attrs):
        (x,) = in_specs
        D = x.shape[-1]
        E, F = attrs["num_experts"], attrs["expert_hidden"]
        k1, k2, k3 = jax.random.split(key, 3)
        dt = x.jnp_dtype
        glorot = ffinit.GlorotUniform()
        w = {
            "gate": glorot(k1, (D, E), dt),
            "w1": glorot(k2, (E, D, F), dt),
            "w2": glorot(k3, (E, F, D), dt),
        }
        if attrs.get("use_bias", False):
            w["b1"] = jnp.zeros((E, F), dt)
            w["b2"] = jnp.zeros((E, D), dt)
        return w

    def weight_pspecs(self, in_specs, attrs, model_axis):
        specs = {
            "gate": P(),
            "w1": P(EXPERT_AXIS, None, None),
            "w2": P(EXPERT_AXIS, None, None),
        }
        if attrs.get("use_bias", False):
            specs["b1"] = P(EXPERT_AXIS, None)
            specs["b2"] = P(EXPERT_AXIS, None)
        return specs

    def forward(self, weights, inputs, attrs, ctx):
        (x,) = inputs
        orig_shape = x.shape
        D = orig_shape[-1]
        xt = x.reshape(-1, D)
        N = xt.shape[0]
        E, K = attrs["num_experts"], attrs["top_k"]
        C = _capacity(N, E, K, attrs.get("capacity_factor", 1.25))
        logits = jnp.matmul(xt, weights["gate"],
                            preferred_element_type=jnp.float32)
        probs = jax.nn.softmax(logits, axis=-1).astype(x.dtype)
        dispatch, combine, _, _ = _routing(probs, K, C)
        buckets = jnp.einsum("nec,nd->ecd", dispatch, xt,
                             preferred_element_type=jnp.float32).astype(x.dtype)
        buckets = _maybe_constrain_experts(buckets, ctx, P(EXPERT_AXIS, None, None))
        out = _expert_ffn(buckets, weights, attrs.get("activation", "relu"))
        y = jnp.einsum("nec,ecd->nd", combine, out,
                       preferred_element_type=jnp.float32).astype(x.dtype)
        lam = attrs.get("load_balance_lambda", 1e-2)
        if ctx.training and lam > 0.0 and ctx.state_updates is not None:
            aux = lam * _load_balance_loss(probs, dispatch)
            ctx.state_updates.setdefault("__aux__", []).append(aux)
        return [y.reshape(orig_shape)]

    def flops(self, in_specs, attrs):
        (x,) = in_specs
        D = x.shape[-1]
        N = x.num_elements // D
        E, F = attrs["num_experts"], attrs["expert_hidden"]
        C = _capacity(N, E, attrs["top_k"], attrs.get("capacity_factor", 1.25))
        # gate + dispatch einsum + expert GEMMs + combine einsum
        return 2 * N * D * E + 4 * N * D * E * C + 4 * E * C * D * F


@register
class ExpertsOp(OpDef):
    """Fused inference experts on pre-computed routing — reference
    ``src/ops/experts.cc`` (``num_experts`` + ``experts_start_idx``
    range sharding → the E dim over the expert mesh axis here).
    Inputs: x (N, D), idx (N, K) int32, gates (N, K)."""

    type = "experts"

    def infer(self, in_specs, attrs):
        x = in_specs[0]
        return [TensorSpec(x.shape, x.dtype)]

    def init(self, key, in_specs, attrs):
        x = in_specs[0]
        D = x.shape[-1]
        E, F = attrs["num_experts"], attrs["expert_hidden"]
        k1, k2 = jax.random.split(key)
        glorot = ffinit.GlorotUniform()
        dt = x.jnp_dtype
        return {"w1": glorot(k1, (E, D, F), dt), "w2": glorot(k2, (E, F, D), dt)}

    def weight_pspecs(self, in_specs, attrs, model_axis):
        return {"w1": P(EXPERT_AXIS, None, None), "w2": P(EXPERT_AXIS, None, None)}

    def forward(self, weights, inputs, attrs, ctx):
        x, idx, gates = inputs
        orig_shape = x.shape
        x = x.reshape(-1, x.shape[-1])          # accept (B, S, D) tokens
        idx = idx.reshape(-1, idx.shape[-1])
        gates = gates.reshape(-1, gates.shape[-1])
        N, D = x.shape
        E, K = attrs["num_experts"], attrs["top_k"]
        C = _capacity(N, E, K, attrs.get("capacity_factor", 2.0))
        dispatch, combine = _dispatch_from_topk(
            gates.astype(x.dtype), idx, E, C
        )
        buckets = jnp.einsum("nec,nd->ecd", dispatch, x,
                             preferred_element_type=jnp.float32).astype(x.dtype)
        buckets = _maybe_constrain_experts(buckets, ctx, P(EXPERT_AXIS, None, None))
        out = _expert_ffn(buckets, weights, attrs.get("activation", "gelu"))
        y = jnp.einsum("nec,ecd->nd", combine, out,
                       preferred_element_type=jnp.float32).astype(x.dtype)
        return [y]

    def flops(self, in_specs, attrs):
        x = in_specs[0]
        N, D = x.shape
        E, F = attrs["num_experts"], attrs["expert_hidden"]
        C = _capacity(N, E, attrs["top_k"], attrs.get("capacity_factor", 2.0))
        return 4 * E * C * D * F


@register
class AggregateSpecOp(OpDef):
    """Spec-mode weighted combine of expert outputs — reference
    ``src/ops/aggregate_spec.cc`` (``include/flexflow/ops/
    aggregate_spec.h:14``): during speculative/beam decoding the routing
    decisions come from the draft pass and are FIXED, so unlike
    :class:`AggregateOp` the combine weights carry no gradient and no
    load-balance aux loss is accumulated. Inputs match ``aggregate``:
    expert_out (E, C, D), combine (N, E, C), probs (N, E)."""

    type = "aggregate_spec"

    def infer(self, in_specs, attrs):
        eo, combine, probs = in_specs
        return [TensorSpec((combine.shape[0], eo.shape[-1]), eo.dtype)]

    def forward(self, weights, inputs, attrs, ctx):
        expert_out, combine, _probs = inputs
        combine = lax.stop_gradient(combine)  # routing fixed in spec mode
        y = jnp.einsum(
            "nec,ecd->nd", combine, expert_out,
            preferred_element_type=jnp.float32,
        ).astype(expert_out.dtype)
        return [y]

    def flops(self, in_specs, attrs):
        eo, combine, _ = in_specs
        return 2 * combine.num_elements * eo.shape[-1]


@register
class CacheOp(OpDef):
    """Activation cache — reference ``src/ops/cache.cc``
    (``include/flexflow/ops/cache.h:8``): memoize an upstream tensor
    (e.g. embeddings of a repeated static batch) and serve the cached
    copy at inference, refreshed whenever the op runs in training mode.
    The reference triggers refresh through a host ``cache_update`` task
    and a staleness score; functionally the cached value lives in the
    model's non-trainable state collection here (like batch-norm running
    stats) and updates out-of-gradient."""

    type = "cache"

    def infer(self, in_specs, attrs):
        return [in_specs[0]]

    def init_state(self, in_specs, attrs):
        (x,) = in_specs
        return {
            "value": jnp.zeros(x.shape, x.jnp_dtype),
            "valid": jnp.zeros((), jnp.bool_),
        }

    def forward(self, weights, inputs, attrs, ctx):
        (x,) = inputs
        st = ctx.state.get(attrs["_node"]) if ctx.state else None
        if ctx.training or st is None:
            if ctx.state_updates is not None:
                ctx.state_updates[attrs["_node"]] = {
                    "value": lax.stop_gradient(x),
                    "valid": jnp.ones((), jnp.bool_),
                }
            return [x]
        # inference: serve the cached copy when it exists, else the
        # live input (first run before any training step)
        return [jnp.where(st["valid"], st["value"].astype(x.dtype), x)]

    def flops(self, in_specs, attrs):
        return 0
