"""Flash attention (fwd + custom-VJP bwd) as Pallas TPU kernels — the
training-path counterpart of the serving kernels in serve/kernels.py.

The reference's training attention is cuDNN MHA (reference
``src/ops/attention.cc``); its serving attentions are hand-written CUDA.
On TPU the XLA path materialises the (B, H, S, T) score tensor in HBM,
which caps MFU and sequence length; this kernel streams K/V blocks
through VMEM with an online softmax so scores never leave the chip, and
the backward pass recomputes them blockwise from the saved LSE — the
FlashAttention-2 schedule laid out for the MXU (128-aligned blocks,
f32 accumulators).

Layout: ``(B, S, H, dk)`` queries / ``(B, T, H, dk)`` keys+values (GQA
heads repeated by the caller, as models/llama.py already does for the
XLA path). Non-TPU backends run ``interpret=True`` so the CPU-mesh
tests exercise the same code path numerically.
"""
from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30
# m/l accumulators are stored lane-replicated at this width: TPU vector
# memory tiles are (sublane, 128); a (bq,) scalar column would occupy a
# full tile anyway, and replicated storage keeps every op elementwise
LANES = 128


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


# ---------------------------------------------------------------------------
# forward


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref,
                o_scr, m_scr, l_scr, *,
                block_q, block_k, total_q, total_k, causal, scale):
    i = pl.program_id(1)  # query block
    j = pl.program_id(2)  # kv block (innermost: accumulators carry over)

    @pl.when(j == 0)
    def _():
        o_scr[:] = jnp.zeros_like(o_scr)
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)

    qpos = i * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, 1), 0
    )
    kpos = j * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (1, block_k), 1
    )
    mask = (kpos < total_k) & (qpos < total_q)
    if causal:
        mask = mask & (qpos >= kpos)

    @pl.when(jnp.any(mask))
    def _():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        # zero padded K/V rows — 0·exp(NEG_INF)=0 still, but NaN padding
        # from out-of-bounds block reads would poison the products
        kvalid = (kpos < total_k).reshape(block_k, 1)
        k = jnp.where(kvalid, k, 0.0)
        v = jnp.where(kvalid, v, 0.0)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale                                   # (bq, bk)
        s = jnp.where(mask, s, NEG_INF)
        m_prev = m_scr[:]                           # (bq, LANES)
        m_next = jnp.maximum(m_prev, s.max(axis=-1, keepdims=True))
        corr = jnp.exp(m_prev - m_next)             # (bq, LANES)
        p = jnp.exp(s - m_next[:, :1])
        p = jnp.where(mask, p, 0.0)
        l_scr[:] = l_scr[:] * corr + p.sum(axis=-1, keepdims=True)
        pv = jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                                           # (bq, dk)
        o_scr[:] = o_scr[:] * corr[:, :1] + pv
        m_scr[:] = m_next

    @pl.when(j == pl.num_programs(2) - 1)
    def _():
        l = jnp.maximum(l_scr[:, :1], 1e-30)
        o_ref[0] = (o_scr[:] / l).astype(o_ref.dtype)
        lse_ref[0] = (m_scr[:, :1] + jnp.log(l)).reshape(block_q)


def _flash_fwd(q, k, v, causal, scale, block_q, block_k):
    """q (N, S, dk), k/v (N, T, dk) → (out (N, S, dk), lse (N, S))."""
    N, S, dk = q.shape
    T = k.shape[1]
    bq, bk = min(block_q, S), min(block_k, T)
    grid = (N, pl.cdiv(S, bq), pl.cdiv(T, bk))
    return pl.pallas_call(
        functools.partial(
            _fwd_kernel, block_q=bq, block_k=bk, total_q=S, total_k=T,
            causal=causal, scale=scale,
        ),
        out_shape=(
            jax.ShapeDtypeStruct((N, S, dk), q.dtype),
            jax.ShapeDtypeStruct((N, S), jnp.float32),
        ),
        grid_spec=pl.GridSpec(
            grid=grid,
            in_specs=[
                pl.BlockSpec((1, bq, dk), lambda n, i, j: (n, i, 0)),
                pl.BlockSpec((1, bk, dk), lambda n, i, j: (n, j, 0)),
                pl.BlockSpec((1, bk, dk), lambda n, i, j: (n, j, 0)),
            ],
            out_specs=(
                pl.BlockSpec((1, bq, dk), lambda n, i, j: (n, i, 0)),
                pl.BlockSpec((1, bq), lambda n, i, j: (n, i)),
            ),
            scratch_shapes=[
                pltpu.VMEM((bq, dk), jnp.float32),
                pltpu.VMEM((bq, LANES), jnp.float32),
                pltpu.VMEM((bq, LANES), jnp.float32),
            ],
        ),
        interpret=_interpret(),
    )(q, k, v)


# ---------------------------------------------------------------------------
# backward: dK/dV accumulate over query blocks, dQ over kv blocks —
# scores recomputed blockwise from the saved LSE (FlashAttention-2)


def _bwd_kv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                   dk_ref, dv_ref, dk_scr, dv_scr, *,
                   block_q, block_k, total_q, total_k, causal, scale):
    j = pl.program_id(1)  # kv block
    i = pl.program_id(2)  # query block (innermost)

    @pl.when(i == 0)
    def _():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    qpos = i * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, 1), 0
    )
    kpos = j * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (1, block_k), 1
    )
    mask = (kpos < total_k) & (qpos < total_q)
    if causal:
        mask = mask & (qpos >= kpos)

    @pl.when(jnp.any(mask))
    def _():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        # out-of-bounds block rows read unspecified values: 0·NaN from a
        # padded lse/delta would poison ds even where p is masked to 0
        qvalid = qpos < total_q                     # (bq, 1)
        lse = jnp.where(qvalid, lse_ref[0].reshape(block_q, 1), 0.0)
        delta = jnp.where(qvalid, delta_ref[0].reshape(block_q, 1), 0.0)
        do = jnp.where(qvalid, do, 0.0)
        q = jnp.where(qvalid, q, 0.0)  # ds.T @ q contracts the q rows
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale
        s = jnp.where(mask, s, NEG_INF)
        p = jnp.exp(s - lse)
        p = jnp.where(mask, p, 0.0)                 # (bq, bk)
        dv_scr[:] += jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                                           # (bk, dk)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                                           # (bq, bk)
        ds = p * (dp - delta) * scale
        dk_scr[:] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                                           # (bk, dk)

    @pl.when(i == pl.num_programs(2) - 1)
    def _():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def _bwd_q_kernel(q_ref, k_ref, do_ref, lse_ref, delta_ref, v_ref,
                  dq_ref, dq_scr, *,
                  block_q, block_k, total_q, total_k, causal, scale):
    i = pl.program_id(1)  # query block
    j = pl.program_id(2)  # kv block (innermost)

    @pl.when(j == 0)
    def _():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    qpos = i * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, 1), 0
    )
    kpos = j * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (1, block_k), 1
    )
    mask = (kpos < total_k) & (qpos < total_q)
    if causal:
        mask = mask & (qpos >= kpos)

    @pl.when(jnp.any(mask))
    def _():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        qvalid = qpos < total_q
        kvalid = (kpos < total_k).reshape(block_k, 1)
        lse = jnp.where(qvalid, lse_ref[0].reshape(block_q, 1), 0.0)
        delta = jnp.where(qvalid, delta_ref[0].reshape(block_q, 1), 0.0)
        do = jnp.where(qvalid, do, 0.0)
        k = jnp.where(kvalid, k, 0.0)  # ds @ k contracts the kv rows
        v = jnp.where(kvalid, v, 0.0)  # do @ v.T feeds ds at padded cols
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale
        s = jnp.where(mask, s, NEG_INF)
        p = jnp.exp(s - lse)
        p = jnp.where(mask, p, 0.0)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta) * scale               # (bq, bk)
        dq_scr[:] += jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                                           # (bq, dk)

    @pl.when(j == pl.num_programs(2) - 1)
    def _():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)


def _flash_bwd(q, k, v, out, lse, do, causal, scale, block_q, block_k):
    N, S, dk = q.shape
    T = k.shape[1]
    bq, bk = min(block_q, S), min(block_k, T)
    delta = jnp.sum(
        do.astype(jnp.float32) * out.astype(jnp.float32), axis=-1
    )  # (N, S)

    dkv = pl.pallas_call(
        functools.partial(
            _bwd_kv_kernel, block_q=bq, block_k=bk, total_q=S, total_k=T,
            causal=causal, scale=scale,
        ),
        out_shape=(
            jax.ShapeDtypeStruct((N, T, dk), k.dtype),
            jax.ShapeDtypeStruct((N, T, dk), v.dtype),
        ),
        grid_spec=pl.GridSpec(
            grid=(N, pl.cdiv(T, bk), pl.cdiv(S, bq)),
            in_specs=[
                pl.BlockSpec((1, bq, dk), lambda n, j, i: (n, i, 0)),
                pl.BlockSpec((1, bk, dk), lambda n, j, i: (n, j, 0)),
                pl.BlockSpec((1, bk, dk), lambda n, j, i: (n, j, 0)),
                pl.BlockSpec((1, bq, dk), lambda n, j, i: (n, i, 0)),
                pl.BlockSpec((1, bq), lambda n, j, i: (n, i)),
                pl.BlockSpec((1, bq), lambda n, j, i: (n, i)),
            ],
            out_specs=(
                pl.BlockSpec((1, bk, dk), lambda n, j, i: (n, j, 0)),
                pl.BlockSpec((1, bk, dk), lambda n, j, i: (n, j, 0)),
            ),
            scratch_shapes=[
                pltpu.VMEM((bk, dk), jnp.float32),
                pltpu.VMEM((bk, dk), jnp.float32),
            ],
        ),
        interpret=_interpret(),
    )(q, k, v, do, lse, delta)

    dq = pl.pallas_call(
        functools.partial(
            _bwd_q_kernel, block_q=bq, block_k=bk, total_q=S, total_k=T,
            causal=causal, scale=scale,
        ),
        out_shape=jax.ShapeDtypeStruct((N, S, dk), q.dtype),
        grid_spec=pl.GridSpec(
            grid=(N, pl.cdiv(S, bq), pl.cdiv(T, bk)),
            in_specs=[
                pl.BlockSpec((1, bq, dk), lambda n, i, j: (n, i, 0)),
                pl.BlockSpec((1, bk, dk), lambda n, i, j: (n, j, 0)),
                pl.BlockSpec((1, bq, dk), lambda n, i, j: (n, i, 0)),
                pl.BlockSpec((1, bq), lambda n, i, j: (n, i)),
                pl.BlockSpec((1, bq), lambda n, i, j: (n, i)),
                pl.BlockSpec((1, bk, dk), lambda n, i, j: (n, j, 0)),
            ],
            out_specs=pl.BlockSpec((1, bq, dk), lambda n, i, j: (n, i, 0)),
            scratch_shapes=[pltpu.VMEM((bq, dk), jnp.float32)],
        ),
        interpret=_interpret(),
    )(q, k, do, lse, delta, v)
    return dq, dkv[0], dkv[1]


# ---------------------------------------------------------------------------
# custom-VJP wrapper


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash(q, k, v, causal, scale, block_q, block_k):
    out, _ = _flash_fwd(q, k, v, causal, scale, block_q, block_k)
    return out


def _flash_vjp_fwd(q, k, v, causal, scale, block_q, block_k):
    out, lse = _flash_fwd(q, k, v, causal, scale, block_q, block_k)
    return out, (q, k, v, out, lse)


def _flash_vjp_bwd(causal, scale, block_q, block_k, res, do):
    q, k, v, out, lse = res
    return _flash_bwd(q, k, v, out, lse, do, causal, scale, block_q, block_k)


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def flash_attention(
    q: jnp.ndarray,  # (B, S, H, dk)
    k: jnp.ndarray,  # (B, T, H, dk)
    v: jnp.ndarray,  # (B, T, H, dk)
    *,
    causal: bool = True,
    scale: Optional[float] = None,
    block_q: int = 128,
    block_k: int = 128,
) -> jnp.ndarray:
    """Fused multi-head attention, differentiable. Heads must already be
    repeated for GQA (matches the XLA path in models/llama.py)."""
    B, S, H, dk = q.shape
    T = k.shape[1]
    scale = scale if scale is not None else 1.0 / math.sqrt(dk)
    qf = q.transpose(0, 2, 1, 3).reshape(B * H, S, dk)
    kf = k.transpose(0, 2, 1, 3).reshape(B * H, T, dk)
    vf = v.transpose(0, 2, 1, 3).reshape(B * H, T, dk)
    out = _flash(qf, kf, vf, causal, scale, block_q, block_k)
    return out.reshape(B, H, S, dk).transpose(0, 2, 1, 3)
