"""Framework configuration — the TPU-native FFConfig.

Mirrors the reference's three-tier flag system (reference
``src/runtime/model.cc:4049-4200`` ``FFConfig::parse_args`` and the Python
``ff.init(**configs)`` dict, ``python/flexflow/serve/__init__.py:32-77``),
collapsed into one dataclass. Legion resource flags (``-ll:gpu`` etc.)
have no TPU meaning: device inventory comes from ``jax.devices()`` and
process topology from ``jax.distributed``.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

import jax

from .core.dtypes import DataType
from .core.mesh import MachineSpec


@dataclasses.dataclass
class FFConfig:
    # --- training loop (reference FFConfig epochs/batchSize/learningRate) ---
    batch_size: int = 64
    epochs: int = 1
    learning_rate: float = 0.01
    weight_decay: float = 0.0
    seed: int = 0

    # --- parallelism degrees (reference -data/tensor/pipeline-parallelism-degree)
    data_parallelism_degree: int = 1
    tensor_parallelism_degree: int = 1
    pipeline_parallelism_degree: int = 1
    expert_parallelism_degree: int = 1
    # New capability vs the reference (SURVEY.md §2.2: SP absent there).
    sequence_parallelism_degree: int = 1
    only_data_parallel: bool = False

    # --- numerics ---
    compute_dtype: DataType = DataType.FLOAT
    param_dtype: DataType = DataType.FLOAT

    # --- auto-parallel search (reference --budget/--alpha/--enable-*-parallel)
    search_budget: int = -1
    search_alpha: float = 1.2
    enable_sample_parallel: bool = True
    enable_parameter_parallel: bool = True
    enable_attribute_parallel: bool = True
    # Calibrate the search cost model with on-device op timings
    # (reference inner_measure_operator_cost, model.cu:38).
    search_measured: bool = False
    # Persist those timings to a JSON file and reuse across processes
    # (per-(op, shape) timing costs a compile on TPU — SURVEY §7:
    # "cache aggressively"); keyed by device kind.
    search_measured_cache: Optional[str] = None
    # Replace the chip preset's mxu/hbm efficiency guesses with measured
    # roofline fractions (search.machine_model.calibrate_chip) before
    # searching — the other half of the fidelity loop.
    search_calibrate_chip: bool = False
    # User-editable machine config for the search topology (reference
    # --machine-model-file + machine_config_example); overrides the
    # default v5e preset via TPUTopology.from_file.
    machine_config_file: Optional[str] = None
    export_strategy_file: Optional[str] = None
    import_strategy_file: Optional[str] = None
    # extra declarative rewrite rules (reference --substitution-json)
    substitution_json_file: Optional[str] = None

    # --- perf knobs (reference --fusion/--offload/--4bit-quantization) ---
    fusion: bool = True
    cpu_offload: bool = False
    offload_reserve_space_gb: float = 8.0
    quantization_type: Optional[DataType] = None  # DataType.INT4 / INT8
    profiling: bool = False
    remat: bool = False  # jax.checkpoint on per-layer blocks

    # --- serving limits (reference batch_config.h:58-60) ---
    max_requests_per_batch: int = 16
    max_tokens_per_batch: int = 1024
    max_sequence_length: int = 2048

    num_devices: Optional[int] = None

    def __post_init__(self):
        if self.num_devices is None:
            try:
                self.num_devices = len(jax.devices())
            except RuntimeError:
                self.num_devices = 1
        if self.only_data_parallel:
            self.tensor_parallelism_degree = 1
            self.pipeline_parallelism_degree = 1
            self.expert_parallelism_degree = 1
            self.sequence_parallelism_degree = 1

    def machine_spec(self) -> MachineSpec:
        return MachineSpec.from_degrees(
            self.num_devices,
            tensor=self.tensor_parallelism_degree,
            pipeline=self.pipeline_parallelism_degree,
            expert=self.expert_parallelism_degree,
            sequence=self.sequence_parallelism_degree,
        )

    @classmethod
    def from_dict(cls, configs: Dict[str, Any]) -> "FFConfig":
        known = {f.name for f in dataclasses.fields(cls)}
        kwargs = {}
        for k, v in configs.items():
            # Reference boolean quantization flags → DataType values.
            if k == "use_4bit_quantization":
                if v:
                    kwargs["quantization_type"] = DataType.INT4
                continue
            if k == "use_8bit_quantization":
                if v:
                    kwargs.setdefault("quantization_type", DataType.INT8)
                continue
            key = _ALIASES.get(k, k)
            if key in known:
                kwargs[key] = v
        return cls(**kwargs)


# Reference ff.init() key names → our field names.
_ALIASES = {
    "num_gpus": "num_devices",
    "tensor_parallelism_degree": "tensor_parallelism_degree",
    "data_parallelism_degree": "data_parallelism_degree",
    "pipeline_parallelism_degree": "pipeline_parallelism_degree",
    "offload": "cpu_offload",
    "use_4bit_quantization": "quantization_type",
    "batchSize": "batch_size",
    "learningRate": "learning_rate",
}

_global_config: Optional[FFConfig] = None


def init(configs: Optional[Dict[str, Any]] = None, **kwargs) -> FFConfig:
    """``ff.init()`` — set the process-global config (reference
    ``python/flexflow/serve/__init__.py:32``). Safe to call repeatedly."""
    global _global_config
    merged = dict(configs or {})
    merged.update(kwargs)
    _global_config = FFConfig.from_dict(merged)
    return _global_config


def get_config() -> FFConfig:
    global _global_config
    if _global_config is None:
        _global_config = FFConfig()
    return _global_config
