// Native GPT-2-style byte-level BPE tokenizer — the analog of the
// reference's C++ tokenizer (reference src/runtime/gpt_tokenizer.cc;
// its main serving path uses the external tokenizers-cpp dep). Flat C
// ABI for ctypes, self-contained (a minimal JSON-object parser for the
// {"token": id} vocab format, no third-party deps).
//
// Byte-level BPE: text bytes map through the GPT-2 byte->unicode table,
// words split into (optional-space + letter/digit/other runs), each
// word merges greedily by lowest merge rank, tokens look up vocab ids.
// Decode inverts: ids -> token strings -> bytes -> utf8.
//
// Build: g++ -O2 -shared -fPIC -std=c++17 gpt_tokenizer.cpp
//        -o libfftok.so   (flexflow_tpu/tokenizer.py does this on demand)
#include <algorithm>
#include <cctype>
#include <cstdint>
#include <cstring>
#include <fstream>
#include <map>
#include <sstream>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

// GPT-2 bytes_to_unicode: printable bytes map to themselves; the rest
// map to 256+k codepoints, so every byte has a visible unicode char.
std::map<uint8_t, std::string> byte_encoder() {
  std::vector<int> bs;
  for (int b = '!'; b <= '~'; b++) bs.push_back(b);
  for (int b = 0xA1; b <= 0xAC; b++) bs.push_back(b);
  for (int b = 0xAE; b <= 0xFF; b++) bs.push_back(b);
  std::vector<int> cs = bs;
  int n = 0;
  for (int b = 0; b < 256; b++) {
    if (std::find(bs.begin(), bs.end(), b) == bs.end()) {
      bs.push_back(b);
      cs.push_back(256 + n++);
    }
  }
  auto utf8 = [](int cp) {
    std::string s;
    if (cp < 0x80) {
      s += static_cast<char>(cp);
    } else if (cp < 0x800) {
      s += static_cast<char>(0xC0 | (cp >> 6));
      s += static_cast<char>(0x80 | (cp & 0x3F));
    } else {
      s += static_cast<char>(0xE0 | (cp >> 12));
      s += static_cast<char>(0x80 | ((cp >> 6) & 0x3F));
      s += static_cast<char>(0x80 | (cp & 0x3F));
    }
    return s;
  };
  std::map<uint8_t, std::string> enc;
  for (size_t i = 0; i < bs.size(); i++) {
    enc[static_cast<uint8_t>(bs[i])] = utf8(cs[i]);
  }
  return enc;
}

struct Tokenizer {
  std::unordered_map<std::string, int32_t> vocab;
  std::unordered_map<int32_t, std::string> inv_vocab;
  std::unordered_map<std::string, int> ranks;  // "a b" -> rank
  std::map<uint8_t, std::string> benc;
  std::unordered_map<std::string, uint8_t> bdec;

  std::vector<std::string> bpe(const std::string &word_units_joined,
                               const std::vector<std::string> &units) const {
    std::vector<std::string> parts = units;
    while (parts.size() > 1) {
      int best_rank = INT32_MAX;
      size_t best_i = 0;
      for (size_t i = 0; i + 1 < parts.size(); i++) {
        auto it = ranks.find(parts[i] + " " + parts[i + 1]);
        if (it != ranks.end() && it->second < best_rank) {
          best_rank = it->second;
          best_i = i;
        }
      }
      if (best_rank == INT32_MAX) break;
      std::vector<std::string> merged;
      for (size_t i = 0; i < parts.size();) {
        if (i == best_i) {
          merged.push_back(parts[i] + parts[i + 1]);
          i += 2;
        } else {
          merged.push_back(parts[i]);
          i += 1;
        }
      }
      parts.swap(merged);
    }
    return parts;
  }
};

enum CharClass { kLetter, kDigit, kOther, kSpace };

CharClass classify(uint8_t c) {
  if ((c >= 'a' && c <= 'z') || (c >= 'A' && c <= 'Z') || c >= 0x80)
    return kLetter;  // multibyte utf8 treated as letters
  if (c >= '0' && c <= '9') return kDigit;
  if (c == ' ' || c == '\t' || c == '\n' || c == '\r') return kSpace;
  return kOther;
}

// Split raw bytes into GPT-2-ish words: a run of same-class bytes,
// optionally claiming one preceding space. A whitespace run of length
// k followed by a word keeps its last space as the word prefix and
// emits the first k-1 spaces as their own word (the \s+(?!\S) rule).
std::vector<std::string> split_words(const std::string &text) {
  std::vector<std::string> words;
  size_t i = 0;
  while (i < text.size()) {
    if (classify(text[i]) == kSpace) {
      size_t j = i;
      while (j < text.size() && classify(text[j]) == kSpace) j++;
      size_t extra = (j < text.size()) ? (j - i - 1) : (j - i);
      if (extra > 0) words.push_back(text.substr(i, extra));
      i += extra;
      if (i >= text.size()) break;
      size_t start = i;  // the single claimed leading space
      i++;
      CharClass cls = classify(text[i]);
      size_t k = i;
      while (k < text.size() && classify(text[k]) == cls) k++;
      words.push_back(text.substr(start, k - start));
      i = k;
    } else {
      CharClass cls = classify(text[i]);
      size_t k = i;
      while (k < text.size() && classify(text[k]) == cls) k++;
      words.push_back(text.substr(i, k - i));
      i = k;
    }
  }
  return words;
}

// Minimal parser for a flat {"escaped string": int, ...} JSON object.
bool parse_vocab_json(const std::string &path,
                      std::unordered_map<std::string, int32_t> &out) {
  std::ifstream f(path, std::ios::binary);
  if (!f) return false;
  std::stringstream ss;
  ss << f.rdbuf();
  std::string s = ss.str();
  size_t i = 0;
  auto skip_ws = [&] {
    while (i < s.size() && (s[i] == ' ' || s[i] == '\n' || s[i] == '\t' ||
                            s[i] == '\r' || s[i] == ','))
      i++;
  };
  skip_ws();
  if (i >= s.size() || s[i] != '{') return false;
  i++;
  while (true) {
    skip_ws();
    if (i < s.size() && s[i] == '}') return true;
    if (i >= s.size() || s[i] != '"') return false;
    i++;
    std::string key;
    while (i < s.size() && s[i] != '"') {
      if (s[i] == '\\' && i + 1 < s.size()) {
        char c = s[i + 1];
        if (c == 'u' && i + 5 < s.size()) {
          int cp = std::stoi(s.substr(i + 2, 4), nullptr, 16);
          if (cp < 0x80) {
            key += static_cast<char>(cp);
          } else if (cp < 0x800) {
            key += static_cast<char>(0xC0 | (cp >> 6));
            key += static_cast<char>(0x80 | (cp & 0x3F));
          } else {
            key += static_cast<char>(0xE0 | (cp >> 12));
            key += static_cast<char>(0x80 | ((cp >> 6) & 0x3F));
            key += static_cast<char>(0x80 | (cp & 0x3F));
          }
          i += 6;
        } else {
          if (c == 'n') key += '\n';
          else if (c == 't') key += '\t';
          else if (c == 'r') key += '\r';
          else key += c;  // \" \\ \/
          i += 2;
        }
      } else {
        key += s[i++];
      }
    }
    i++;  // closing quote
    skip_ws();
    if (i >= s.size() || s[i] != ':') return false;
    i++;
    skip_ws();
    size_t j = i;
    while (j < s.size() && (isdigit(s[j]) || s[j] == '-')) j++;
    out[key] = static_cast<int32_t>(std::stol(s.substr(i, j - i)));
    i = j;
  }
}

}  // namespace

extern "C" {

void *fftok_create(const char *vocab_json, const char *merges_txt) {
  auto *t = new Tokenizer;
  t->benc = byte_encoder();
  for (auto &kv : t->benc) t->bdec[kv.second] = kv.first;
  if (!parse_vocab_json(vocab_json, t->vocab)) {
    delete t;
    return nullptr;
  }
  for (auto &kv : t->vocab) t->inv_vocab[kv.second] = kv.first;
  std::ifstream mf(merges_txt);
  if (!mf) {
    delete t;
    return nullptr;
  }
  std::string line;
  int rank = 0;
  while (std::getline(mf, line)) {
    if (line.empty() || line[0] == '#') continue;
    t->ranks[line] = rank++;
  }
  return t;
}

int64_t fftok_vocab_size(void *h) {
  return static_cast<Tokenizer *>(h)->vocab.size();
}

// Encode utf-8 text into ids; returns count (<= max_len, truncating).
int64_t fftok_encode(void *h, const char *text, int32_t *out, int64_t max_len) {
  auto *t = static_cast<Tokenizer *>(h);
  int64_t n = 0;
  for (const std::string &word : split_words(text)) {
    // word bytes -> unicode units
    std::vector<std::string> units;
    for (unsigned char c : word) units.push_back(t->benc[c]);
    if (units.empty()) continue;
    for (const std::string &tok : t->bpe(word, units)) {
      auto it = t->vocab.find(tok);
      if (it == t->vocab.end()) {
        // unknown merges fall back to per-unit ids
        for (size_t k = 0; k < tok.size();) {
          size_t len = 1;
          unsigned char c = tok[k];
          if (c >= 0xF0) len = 4;
          else if (c >= 0xE0) len = 3;
          else if (c >= 0xC0) len = 2;
          auto u = t->vocab.find(tok.substr(k, len));
          if (u != t->vocab.end() && n < max_len) out[n++] = u->second;
          k += len;
        }
        continue;
      }
      if (n >= max_len) return n;
      out[n++] = it->second;
    }
  }
  return n;
}

// Decode ids into utf-8; returns byte length written (<= buf_len).
int64_t fftok_decode(void *h, const int32_t *ids, int64_t n, char *buf,
                     int64_t buf_len) {
  auto *t = static_cast<Tokenizer *>(h);
  std::string units;
  for (int64_t i = 0; i < n; i++) {
    auto it = t->inv_vocab.find(ids[i]);
    if (it != t->inv_vocab.end()) units += it->second;
  }
  // unicode units -> raw bytes
  std::string out;
  for (size_t k = 0; k < units.size();) {
    size_t len = 1;
    unsigned char c = units[k];
    if (c >= 0xF0) len = 4;
    else if (c >= 0xE0) len = 3;
    else if (c >= 0xC0) len = 2;
    auto u = t->bdec.find(units.substr(k, len));
    if (u != t->bdec.end()) out += static_cast<char>(u->second);
    k += len;
  }
  int64_t m = std::min<int64_t>(out.size(), buf_len);
  std::memcpy(buf, out.data(), m);
  return m;
}

void fftok_destroy(void *h) { delete static_cast<Tokenizer *>(h); }

}  // extern "C"
