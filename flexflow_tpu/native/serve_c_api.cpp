// Embeddable C serving ABI — the TPU analog of the reference's C API
// surface (reference src/c/flexflow_c.cc:1-2680, flexflow_serve_*
// handles). A non-Python host links this library (+ libpython) and
// drives continuous-batching serving through five functions; the
// implementation embeds CPython and forwards into
// flexflow_tpu.serve.c_backend, whose RequestManager does the actual
// scheduling. Handles are plain ints (request guids), matching the
// reference's guid-based RequestManager API rather than its per-object
// opaque structs.
//
// Thread-model: every entry point takes the GIL (PyGILState_Ensure),
// so the ABI is safe to call from any single host thread at a time.
#include <Python.h>

#include <cstdint>

namespace {

struct Gil {
  PyGILState_STATE st;
  Gil() : st(PyGILState_Ensure()) {}
  ~Gil() { PyGILState_Release(st); }
};

PyObject* backend() {
  static PyObject* mod = nullptr;  // borrowed forever (module cache)
  if (mod == nullptr) {
    mod = PyImport_ImportModule("flexflow_tpu.serve.c_backend");
    if (mod == nullptr) PyErr_Print();
  }
  return mod;
}

long call_long(const char* fn, PyObject* args /* stolen, may be null */) {
  PyObject* m = backend();
  if (m == nullptr) {
    Py_XDECREF(args);
    return -1;
  }
  PyObject* f = PyObject_GetAttrString(m, fn);
  if (f == nullptr) {
    PyErr_Print();
    Py_XDECREF(args);
    return -1;
  }
  PyObject* r = PyObject_CallObject(f, args);
  Py_DECREF(f);
  Py_XDECREF(args);
  if (r == nullptr) {
    PyErr_Print();
    return -1;
  }
  long v = PyLong_Check(r) ? PyLong_AsLong(r) : 0;
  Py_DECREF(r);
  return v;
}

}  // namespace

// Out-of-order calls (step before init, or after finalization) must
// return the documented -1, not hit PyGILState_Ensure's fatal abort.
#define FF_REQUIRE_PY() \
  do {                  \
    if (!Py_IsInitialized()) return -1; \
  } while (0)

extern "C" {

// Initialize the engine from a JSON config (see c_backend docstring).
// Returns 0 on success, -1 on error. Safe to call from a host with or
// without a live interpreter (Py_IsInitialized is checked).
int ff_serve_init(const char* config_json) {
  if (!Py_IsInitialized()) {
    Py_InitializeEx(0);
    // Release the GIL acquired by initialization so the Gil guards
    // below (and any host threads) can take it normally.
    PyEval_SaveThread();
  }
  Gil gil;
  PyObject* args = Py_BuildValue("(s)", config_json ? config_json : "{}");
  return static_cast<int>(call_long("init", args));
}

// Queue a prompt of n int32 tokens; max_new <= 0 uses the config
// default. Returns the request id (>= 0) or -1.
int ff_serve_register_request(const int32_t* tokens, int n, int max_new) {
  FF_REQUIRE_PY();
  Gil gil;
  PyObject* lst = PyList_New(n);
  if (lst == nullptr) return -1;
  for (int i = 0; i < n; ++i) {
    PyObject* tok = PyLong_FromLong(tokens[i]);
    if (tok == nullptr) {  // SET_ITEM would store a null element
      PyErr_Print();
      Py_DECREF(lst);
      return -1;
    }
    PyList_SET_ITEM(lst, i, tok);
  }
  PyObject* args = Py_BuildValue("(Ni)", lst, max_new);  // N steals lst
  if (args == nullptr) {  // on failure N does NOT release the list
    PyErr_Print();
    Py_DECREF(lst);
    return -1;
  }
  return static_cast<int>(call_long("register_request", args));
}

// One continuous-batching scheduling step (prefill chunk or decode
// round across all admitted requests). Returns 1 while work remains,
// 0 when drained, -1 on error.
int ff_serve_step(void) {
  FF_REQUIRE_PY();
  Gil gil;
  return static_cast<int>(call_long("step", nullptr));
}

// Number of registered-but-not-completed requests.
int ff_serve_num_active(void) {
  FF_REQUIRE_PY();
  Gil gil;
  return static_cast<int>(call_long("num_active", nullptr));
}

// Copy a completed request's output tokens into out (capacity cap).
// Returns the token count (may exceed cap; only cap are written), or
// -1 while the request is still running / unknown.
int ff_serve_fetch(int request_id, int32_t* out, int cap) {
  FF_REQUIRE_PY();
  Gil gil;
  PyObject* m = backend();
  if (m == nullptr) return -1;
  PyObject* r = PyObject_CallMethod(m, "fetch", "i", request_id);
  if (r == nullptr) {
    PyErr_Print();
    return -1;
  }
  if (r == Py_None) {
    Py_DECREF(r);
    return -1;
  }
  if (!PyList_Check(r)) {  // PyList_Size on a non-list is fatal/-1+err
    Py_DECREF(r);
    return -1;
  }
  Py_ssize_t n = PyList_Size(r);
  for (Py_ssize_t i = 0; i < n && i < cap; ++i) {
    out[i] = static_cast<int32_t>(PyLong_AsLong(PyList_GET_ITEM(r, i)));
  }
  Py_DECREF(r);
  return static_cast<int>(n);
}

// Drop the engine and all request state. Returns 0.
int ff_serve_shutdown(void) {
  if (!Py_IsInitialized()) return 0;  // nothing to drop
  Gil gil;
  return static_cast<int>(call_long("shutdown", nullptr));
}

}  // extern "C"
