"""Native (C++) runtime components + on-demand builder.

The reference's runtime around the compute path is C++ (dataloader,
tokenizer, C API — SURVEY.md §2.1); the TPU framework keeps that split:
JAX/XLA/Pallas own the compute, these C++ components own the host-side
runtime hot paths, bound via ctypes (no pybind11 in this image).

Libraries build lazily with g++ into ``_build/`` next to the sources
and are cached by source mtime.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional

_DIR = os.path.dirname(os.path.abspath(__file__))
_BUILD = os.path.join(_DIR, "_build")

def _python_embed_flags():
    """Compile/link flags to embed CPython (the serve C ABI needs
    Python.h + libpython; no pybind11 in this image)."""
    import sysconfig

    inc = sysconfig.get_paths()["include"]
    libdir = sysconfig.get_config_var("LIBDIR") or ""
    ver = sysconfig.get_config_var("LDVERSION") or sysconfig.get_config_var(
        "VERSION"
    )
    flags = [f"-I{inc}"]
    if libdir:
        flags += [f"-L{libdir}", f"-Wl,-rpath,{libdir}"]
    flags += [f"-lpython{ver}"]
    return flags


_SOURCES = {
    "ffdata": ("dataloader.cpp", ["-pthread"]),
    "fftok": ("gpt_tokenizer.cpp", []),
    # embeddable C serving ABI (reference flexflow_c.cc analog); flags
    # resolved lazily so import never pays sysconfig
    "ffserve": ("serve_c_api.cpp", _python_embed_flags),
}

_loaded = {}


def load_library(name: str) -> Optional[ctypes.CDLL]:
    """Build (if stale) and dlopen a native component; None when no
    toolchain is available (callers fall back to pure Python)."""
    if name in _loaded:
        return _loaded[name]
    src_name, extra = _SOURCES[name]
    if callable(extra):
        extra = extra()
    src = os.path.join(_DIR, src_name)
    out = os.path.join(_BUILD, f"lib{name}.so")
    try:
        if (
            not os.path.exists(out)
            or os.path.getmtime(out) < os.path.getmtime(src)
        ):
            os.makedirs(_BUILD, exist_ok=True)
            subprocess.run(
                # source before the extra flags: -l libraries must
                # follow the objects that need them for GNU ld
                ["g++", "-O2", "-shared", "-fPIC", "-std=c++17",
                 src, *extra, "-o", out],
                check=True,
                capture_output=True,
                text=True,
            )
        lib = ctypes.CDLL(out)
    except (OSError, subprocess.CalledProcessError):
        lib = None
    _loaded[name] = lib
    return lib
