// Native prefetching data loader — the TPU-framework analog of the
// reference's SingleDataLoader (reference src/dataloader/dataloader.cc:
// full dataset staged in zero-copy memory, per-batch index tasks copy
// shard-appropriate slices ahead of compute). Here a C++ worker thread
// assembles shuffled batches into a bounded ready-queue while the
// training step runs, so the host-side gather never sits on the
// critical path. Exposed as a flat C ABI for ctypes (the same
// binding style as the reference's flexflow_c.cc C API).
//
// Build: g++ -O2 -shared -fPIC -std=c++17 -pthread dataloader.cpp
//        -o libffdata.so   (flexflow_tpu/data.py does this on demand)
#include <algorithm>
#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <mutex>
#include <numeric>
#include <random>
#include <thread>
#include <vector>

namespace {

struct Batch {
  std::vector<float> x;
  std::vector<int32_t> y;
};

struct Loader {
  const float *x;
  const int32_t *y;
  int64_t n, feat, batch, depth;
  bool shuffle, drop_last;
  uint64_t seed;

  std::vector<int64_t> order;
  int64_t cursor = 0;
  int64_t epoch = 0;

  std::deque<Batch *> ready;
  std::mutex mu;
  std::condition_variable cv_ready, cv_space;
  std::thread worker;
  std::atomic<bool> stop{false};

  int64_t batches_per_epoch() const {
    return drop_last ? n / batch : (n + batch - 1) / batch;
  }

  void reshuffle() {
    order.resize(n);
    std::iota(order.begin(), order.end(), 0);
    if (shuffle) {
      std::mt19937_64 rng(seed + static_cast<uint64_t>(epoch));
      std::shuffle(order.begin(), order.end(), rng);
    }
  }

  Batch *assemble() {
    if (cursor >= batches_per_epoch() * batch) {
      epoch++;
      cursor = 0;
      reshuffle();
    }
    auto *b = new Batch;
    b->x.resize(batch * feat);
    b->y.resize(batch);
    for (int64_t i = 0; i < batch; i++) {
      // last partial batch wraps (static shapes for XLA)
      int64_t row = order[(cursor + i) % n];
      std::memcpy(&b->x[i * feat], x + row * feat, feat * sizeof(float));
      b->y[i] = y[row];
    }
    cursor += batch;
    return b;
  }

  void run() {
    while (!stop.load()) {
      Batch *b = assemble();
      std::unique_lock<std::mutex> lk(mu);
      cv_space.wait(lk, [&] {
        return stop.load() || static_cast<int64_t>(ready.size()) < depth;
      });
      if (stop.load()) {
        delete b;
        return;
      }
      ready.push_back(b);
      cv_ready.notify_one();
    }
  }
};

}  // namespace

extern "C" {

void *ffdl_create(const float *x, const int32_t *y, int64_t n, int64_t feat,
                  int64_t batch, int64_t depth, uint64_t seed, int shuffle,
                  int drop_last) {
  auto *l = new Loader;
  l->x = x;
  l->y = y;
  l->n = n;
  l->feat = feat;
  l->batch = batch;
  l->depth = depth > 0 ? depth : 2;
  l->seed = seed;
  l->shuffle = shuffle != 0;
  l->drop_last = drop_last != 0;
  l->reshuffle();
  l->worker = std::thread([l] { l->run(); });
  return l;
}

int64_t ffdl_batches_per_epoch(void *h) {
  return static_cast<Loader *>(h)->batches_per_epoch();
}

// Blocks until the prefetch thread has a batch ready, then copies it
// into the caller's buffers (shape: out_x[batch*feat], out_y[batch]).
void ffdl_next(void *h, float *out_x, int32_t *out_y) {
  auto *l = static_cast<Loader *>(h);
  Batch *b = nullptr;
  {
    std::unique_lock<std::mutex> lk(l->mu);
    l->cv_ready.wait(lk, [&] { return !l->ready.empty(); });
    b = l->ready.front();
    l->ready.pop_front();
    l->cv_space.notify_one();
  }
  std::memcpy(out_x, b->x.data(), b->x.size() * sizeof(float));
  std::memcpy(out_y, b->y.data(), b->y.size() * sizeof(int32_t));
  delete b;
}

int64_t ffdl_ready(void *h) {
  auto *l = static_cast<Loader *>(h);
  std::lock_guard<std::mutex> lk(l->mu);
  return static_cast<int64_t>(l->ready.size());
}

void ffdl_destroy(void *h) {
  auto *l = static_cast<Loader *>(h);
  {
    std::lock_guard<std::mutex> lk(l->mu);
    l->stop.store(true);
  }
  l->cv_space.notify_all();
  l->cv_ready.notify_all();
  if (l->worker.joinable()) {
    l->worker.join();
  }
  for (Batch *b : l->ready) {
    delete b;
  }
  delete l;
}

}  // extern "C"
