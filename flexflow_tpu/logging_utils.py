"""Leveled category loggers — the analog of the reference's Legion
logger categories (``log_measure`` operator.h:14, ``log_dp`` graph.h:27,
``log_req_mgr``, ``log_xfers`` …) with ``-level cat=verbosity`` control.

Usage::

    from flexflow_tpu.logging_utils import get_logger
    log = get_logger("search")
    log.debug("evaluated %d candidates", n)

Verbosity comes from ``FF_LOG`` (e.g. ``FF_LOG=search=debug,serve=info``
or ``FF_LOG=debug`` for everything), mirroring the reference's
``-level`` flags.
"""
from __future__ import annotations

import logging
import os
import warnings
from typing import Dict, Set

_CONFIGURED = False
_LEVELS = {
    "debug": logging.DEBUG,
    "info": logging.INFO,
    "warning": logging.WARNING,
    "error": logging.ERROR,
}
#: bad FF_LOG level tokens already warned about — a typo'd level (e.g.
#: ``FF_LOG=serve=trace``) falls back to INFO, but SILENTLY doing so
#: hides exactly the debug output the user was trying to turn on, so
#: the first resolution of each bad token warns loudly (once: every
#: get_logger call re-parses the spec).
_WARNED_LEVELS: Set[str] = set()


def _resolve_level(token: str) -> int:
    lvl = token.strip().lower()
    if lvl in _LEVELS:
        return _LEVELS[lvl]
    if lvl not in _WARNED_LEVELS:
        _WARNED_LEVELS.add(lvl)
        warnings.warn(
            f"FF_LOG: unknown level {token.strip()!r} — falling back to "
            f"INFO (accepted levels: {', '.join(sorted(_LEVELS))})",
            stacklevel=4,
        )
    return logging.INFO


def _parse_ff_log() -> Dict[str, int]:
    spec = os.environ.get("FF_LOG", "")
    out: Dict[str, int] = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        if "=" in part:
            cat, lvl = part.split("=", 1)
            out[cat.strip()] = _resolve_level(lvl)
        else:
            out["*"] = _resolve_level(part)
    return out


def get_logger(category: str) -> logging.Logger:
    """Category logger ``flexflow_tpu.<category>`` honoring FF_LOG."""
    global _CONFIGURED
    logger = logging.getLogger(f"flexflow_tpu.{category}")
    if not _CONFIGURED:
        root = logging.getLogger("flexflow_tpu")
        if not root.handlers:
            h = logging.StreamHandler()
            h.setFormatter(
                logging.Formatter("[%(name)s %(levelname).1s] %(message)s")
            )
            root.addHandler(h)
        root.setLevel(logging.WARNING)
        _CONFIGURED = True
    levels = _parse_ff_log()
    if category in levels:
        logger.setLevel(levels[category])
    elif "*" in levels:
        logger.setLevel(levels["*"])
    return logger
