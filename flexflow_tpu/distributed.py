"""Multi-host runtime — the TPU-native replacement for the reference's
GASNet/UCX + MPI launch path (reference ``MULTI-NODE.md``,
``CMakeLists.txt:80-90``, ``tests/multinode_helpers/mpi_wrapper*.sh``).

JAX is single-program multi-controller across hosts: every process runs
the same script, ``initialize()`` wires them into one runtime via the
coordination service, and ``jax.devices()`` then returns the GLOBAL
device list — a ``MachineSpec.make_mesh()`` over it spans all hosts,
with GSPMD compiling cross-host collectives onto ICI within a slice and
DCN across slices (the ``data`` axis is outermost in
``core.mesh.AXIS_ORDER`` precisely so DP gradient reductions ride DCN).

Launch (the mpirun analog): one process per host, e.g.

    JAX_COORDINATOR=host0:9955 NPROC=4 PID=$i python train.py

    import flexflow_tpu.distributed as dist
    dist.initialize()               # env-driven, or pass args explicitly
    model = ff.FFModel(ff.FFConfig(num_devices=jax.device_count()))

Single-box multi-node emulation (the reference's mpi_wrapper2.sh) works
on CPU: N processes × JAX_PLATFORMS=cpu each with a virtual device
count — see tests/test_distributed.py.
"""
from __future__ import annotations

import os
from typing import Any, Dict, Optional

import jax
import numpy as np

_initialized = False


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    local_device_ids: Optional[Any] = None,
) -> None:
    """Join the multi-process runtime (idempotent). Arguments default
    from env (JAX_COORDINATOR / NPROC / PID) and, on cloud TPU VMs,
    from the TPU metadata that jax.distributed reads natively."""
    global _initialized
    if _initialized:
        return
    coordinator_address = coordinator_address or os.environ.get("JAX_COORDINATOR")
    if num_processes is None and os.environ.get("NPROC"):
        num_processes = int(os.environ["NPROC"])
    if process_id is None and os.environ.get("PID"):
        process_id = int(os.environ["PID"])
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
        local_device_ids=local_device_ids,
    )
    _initialized = True


def is_multiprocess() -> bool:
    return jax.process_count() > 1


def global_batch(arrays: Dict[str, np.ndarray], mesh, pspecs) -> Dict[str, Any]:
    """Build globally-sharded arrays from host-local data. Every process
    passes its LOCAL slice of the batch (the reference's per-rank
    dataloader shard); shapes must tile the global batch."""
    from jax.sharding import NamedSharding, PartitionSpec

    out = {}
    for k, v in arrays.items():
        spec = pspecs[k] if isinstance(pspecs, dict) else pspecs
        sharding = NamedSharding(mesh, spec)
        out[k] = jax.make_array_from_process_local_data(sharding, v)
    return out


def hybrid_mesh(spec, dcn_axes=("data",)):
    """Mesh for multi-slice topologies: the ``dcn_axes`` map onto slice
    (process-group) boundaries so their collectives ride DCN, while the
    remaining axes stay within a slice on ICI (the layout the cost
    model's ``TPUTopology.dcn_axes`` assumes). Uses
    ``mesh_utils.create_hybrid_device_mesh``; single-process falls back
    to ``spec.make_mesh()``."""
    from jax.experimental import mesh_utils
    from jax.sharding import Mesh

    from .core.mesh import AXIS_ORDER

    if jax.process_count() == 1:
        return spec.make_mesh()
    sizes = spec.axis_sizes()
    # granule = slice on true multi-slice TPUs (several hosts may share
    # one slice); otherwise each process is its own DCN granule
    devices = jax.devices()
    slice_ids = {getattr(d, "slice_index", 0) for d in devices}
    n_slices = len(slice_ids) if len(slice_ids) > 1 else jax.process_count()
    dcn_shape, ici_shape = [], []
    remaining = n_slices
    for a in AXIS_ORDER:
        if a in dcn_axes and remaining > 1:
            d = min(sizes[a], remaining)
            assert sizes[a] % d == 0 and remaining % d == 0, (
                f"axis {a} (size {sizes[a]}) must absorb a divisor of the "
                f"remaining {remaining} slices; got {d}"
            )
            dcn_shape.append(d)
            ici_shape.append(sizes[a] // d)
            remaining //= d
        else:
            dcn_shape.append(1)
            ici_shape.append(sizes[a])
    assert remaining == 1, (
        f"dcn_axes {dcn_axes} too small to cover {n_slices} slices"
    )
    devs = mesh_utils.create_hybrid_device_mesh(
        tuple(ici_shape),
        tuple(dcn_shape),
        devices=devices,
        process_is_granule=len(slice_ids) <= 1,
    )
    return Mesh(devs, AXIS_ORDER)


def process_local_slice(n: int) -> slice:
    """This process's contiguous shard of a length-n leading dim."""
    if n % jax.process_count():
        raise ValueError(
            f"leading dim {n} not divisible by {jax.process_count()} "
            f"processes — pad or drop the tail explicitly"
        )
    per = n // jax.process_count()
    start = per * jax.process_index()
    return slice(start, start + per)
