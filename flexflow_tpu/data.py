"""SingleDataLoader — prefetching batch feed.

Mirrors the reference's ``SingleDataLoader`` (reference
``src/dataloader/dataloader.cc``, ``dataloader.h:34-125``: full dataset
in zero-copy memory, per-batch index tasks copy slices to each shard
ahead of compute). Here the batch assembly (shuffle + gather) runs on a
native C++ worker thread with a bounded ready-queue
(``native/dataloader.cpp``), so the host never assembles a batch on the
step's critical path; a pure-Python fallback covers toolchain-less
environments. ``FFModel.fit`` accepts a loader in place of (x, y).
"""
from __future__ import annotations

import ctypes
from typing import Dict, Iterator, Optional, Tuple

import numpy as np

from .native import load_library


class SingleDataLoader:
    """Iterates (x, y) batches forever; ``batches_per_epoch`` bounds one
    epoch. X must be float32 (N, F...), y int32 (N,)."""

    def __init__(
        self,
        x: np.ndarray,
        y: np.ndarray,
        batch_size: int,
        *,
        shuffle: bool = True,
        seed: int = 0,
        prefetch_depth: int = 2,
        native: Optional[bool] = None,
    ):
        assert len(x) == len(y), (len(x), len(y))
        self._feat_shape = x.shape[1:]
        self.x = np.ascontiguousarray(
            x.reshape(len(x), -1), dtype=np.float32
        )
        self.y = np.ascontiguousarray(y, dtype=np.int32)
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.seed = seed
        self._h = None
        self._lib = None
        if native is not False:
            self._lib = load_library("ffdata")
        if self._lib is not None:
            lib = self._lib
            lib.ffdl_create.restype = ctypes.c_void_p
            lib.ffdl_create.argtypes = [
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
                ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
                ctypes.c_uint64, ctypes.c_int, ctypes.c_int,
            ]
            lib.ffdl_next.argtypes = [
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p
            ]
            lib.ffdl_batches_per_epoch.restype = ctypes.c_int64
            lib.ffdl_batches_per_epoch.argtypes = [ctypes.c_void_p]
            lib.ffdl_destroy.argtypes = [ctypes.c_void_p]
            self._h = lib.ffdl_create(
                self.x.ctypes.data_as(ctypes.c_void_p),
                self.y.ctypes.data_as(ctypes.c_void_p),
                len(self.y),
                self.x.shape[1],
                batch_size,
                prefetch_depth,
                seed,
                1 if shuffle else 0,
                0,
            )
        else:
            # pure-Python fallback (no prefetch thread)
            self._rng_epoch = 0
            self._cursor = 0
            self._order = self._perm(0)

    @property
    def native(self) -> bool:
        return self._h is not None

    @property
    def batches_per_epoch(self) -> int:
        n, b = len(self.y), self.batch_size
        return (n + b - 1) // b

    def _perm(self, epoch: int) -> np.ndarray:
        if not self.shuffle:
            return np.arange(len(self.y))
        return np.random.default_rng(self.seed + epoch).permutation(len(self.y))

    def next_batch(self) -> Tuple[np.ndarray, np.ndarray]:
        b, f = self.batch_size, self.x.shape[1]
        if self._h is not None:
            out_x = np.empty((b, f), np.float32)
            out_y = np.empty((b,), np.int32)
            self._lib.ffdl_next(
                self._h,
                out_x.ctypes.data_as(ctypes.c_void_p),
                out_y.ctypes.data_as(ctypes.c_void_p),
            )
        else:
            n = len(self.y)
            if self._cursor >= self.batches_per_epoch * b:
                self._rng_epoch += 1
                self._cursor = 0
                self._order = self._perm(self._rng_epoch)
            idx = [
                self._order[(self._cursor + i) % n] for i in range(b)
            ]
            out_x, out_y = self.x[idx], self.y[idx]
            self._cursor += b
        return out_x.reshape((b,) + self._feat_shape), out_y

    def __iter__(self) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
        while True:
            yield self.next_batch()

    def __del__(self):
        if getattr(self, "_h", None) is not None and self._lib is not None:
            self._lib.ffdl_destroy(self._h)
            self._h = None
