"""Profiling / observability.

TPU-native counterpart of the reference's profiling hooks (reference
``--profiling`` per-op cudaEvent timing printed from kernels,
``src/ops/kernels/linear_kernels.cu:131-164``; per-request ProfileInfo;
Legion Prof): per-step wall timing with device sync, per-op on-device
timing via the search simulator's measured mode, and a
``jax.profiler`` trace context for xprof-style captures.
"""
from __future__ import annotations

import contextlib
import dataclasses
import time
from typing import Dict, List, Optional

import jax
import numpy as np


@dataclasses.dataclass
class StepTimes:
    """Per-step wall times of a training/serving loop."""

    times_ms: List[float] = dataclasses.field(default_factory=list)

    def record(self, dt_s: float) -> None:
        self.times_ms.append(dt_s * 1e3)

    def summary(self) -> Dict[str, float]:
        if not self.times_ms:
            return {}
        a = np.asarray(self.times_ms)
        return {
            "steps": len(a),
            "mean_ms": round(float(a.mean()), 3),
            "p50_ms": round(float(np.percentile(a, 50)), 3),
            "p90_ms": round(float(np.percentile(a, 90)), 3),
            # the SLO figure soak/latency work quotes (p90 alone hides
            # the tail a stall or recompile puts there)
            "p99_ms": round(float(np.percentile(a, 99)), 3),
            "max_ms": round(float(a.max()), 3),
            # total recorded wall time: the denominator of
            # throughput-per-step-loop comparisons
            "total_ms": round(float(a.sum()), 3),
        }

    def report(self) -> str:
        s = self.summary()
        if not s:
            return "no steps recorded"
        return (
            f"{s['steps']} steps: mean {s['mean_ms']}ms, "
            f"p50 {s['p50_ms']}ms, p90 {s['p90_ms']}ms, "
            f"p99 {s['p99_ms']}ms, max {s['max_ms']}ms, "
            f"total {s['total_ms']}ms"
        )


@contextlib.contextmanager
def trace(logdir: str):
    """jax.profiler trace capture (view with xprof/tensorboard) — the
    TPU analog of Legion Prof's ``-lg:prof`` captures."""
    with jax.profiler.trace(logdir):
        yield


def profile_ops(model, iters: int = 5) -> Dict[str, float]:
    """Per-op on-device forward timing of a compiled FFModel's graph —
    the reference's per-op kernel timing under ``--profiling``. Reuses
    the Unity simulator's measured mode (one jitted program per op, so
    numbers exclude XLA's whole-graph fusion; treat as relative cost)."""
    from .core.mesh import MachineSpec
    from .search.machine_model import TPUChip, TPUTopology
    from .search.simulator import CostModel

    cm = CostModel(
        topo=TPUTopology(chip=TPUChip.v5e()), machine=MachineSpec()
    )
    out: Dict[str, float] = {}
    skipped = []
    for i, node in enumerate(model.graph.topo_order()):
        if node.op_type in ("input", "weight"):
            continue
        try:
            secs = cm.measure_op(model.graph, node, "REP", iters=iters)
        except Exception as e:  # ops without a standalone forward
            skipped.append(f"{node.name or node.op_type}: {e}")
            continue
        out[f"{node.name or node.op_type}#{i}"] = round(secs * 1e3, 4)
    if skipped:
        import warnings

        warnings.warn(
            f"profile_ops skipped {len(skipped)} op(s): "
            + "; ".join(skipped[:3]),
            stacklevel=2,
        )
    return out
