from .tp import apply_tensor_parallel
from .pipeline import pipeline_forward, make_pipelined_apply

__all__ = ["apply_tensor_parallel", "pipeline_forward", "make_pipelined_apply"]
