"""Pipeline parallelism — GPipe-style microbatching over the ``pipe``
mesh axis.

The reference only pipelines *inference*: layers map to stages via
``transformer_layer_id / layers_per_stage`` → ``MachineView.start_device_id``
(reference ``src/runtime/inference_manager.cc:91-133``), overlapped by a
4-deep in-flight batch-future queue (``request_manager.cc:2310-2325``);
training pipeline task IDs exist but are unimplemented. Here we go
further and pipeline **training** too, the TPU-native way: every pipeline
stage runs the same SPMD program under ``shard_map``; stage-local layer
parameters arrive pre-sharded on the ``pipe`` axis (leading stacked-layer
dim), activations flow stage-to-stage with ``lax.ppermute`` over the ICI
ring, and a ``lax.scan`` over (microbatches + stages - 1) ticks implements
the GPipe schedule with static shapes throughout.

This module is generic over a "block_fn" (params_slice, x) -> x so the
flagship transformer and any homogeneous stack can use it.
"""
from __future__ import annotations

import functools
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..core.mesh import DATA_AXIS, PIPE_AXIS, shard_map_unchecked


def _partial_shard_map(fn, mesh, in_specs, out_specs, manual_axes):
    """Partial-manual shard_map (only ``manual_axes`` run manually) —
    the jax-version compat handling lives in the ONE shared shim,
    core.mesh.shard_map_unchecked (previously copy-pasted here and in
    parallel/sequence.py)."""
    return shard_map_unchecked(
        fn, mesh, in_specs, out_specs, manual_axes=manual_axes
    )


def pipeline_forward(
    block_fn: Callable[[Any, jnp.ndarray], jnp.ndarray],
    stage_params: Any,
    x: jnp.ndarray,
    *,
    num_stages: int,
    num_microbatches: int,
    axis_name: str = PIPE_AXIS,
):
    """Run ``x`` through ``num_stages`` pipeline stages inside shard_map.

    Must be called from *within* a shard_map region sharded over
    ``axis_name``. ``stage_params`` are this stage's local layer params
    (leading dim = layers-per-stage). ``x`` is the full batch of
    microbatches, shape (num_microbatches, mb, ...); every stage holds a
    copy (stage 0 consumes it, later stages consume permuted activations).

    Returns the final-stage outputs for all microbatches, valid on the
    last stage (other stages hold garbage of the same shape — callers
    typically ppermute the result back or reduce over it).
    """
    stage = lax.axis_index(axis_name)
    mb_shape = x.shape[1:]
    n_ticks = num_microbatches + num_stages - 1

    # state: per-stage input buffer for the current tick
    def tick(carry, t):
        outputs, cur_in = carry
        # Stage 0 feeds microbatch t (when valid); others use received acts.
        mb_idx = jnp.clip(t, 0, num_microbatches - 1)
        stage0_in = lax.dynamic_index_in_dim(x, mb_idx, axis=0, keepdims=False)
        inp = jnp.where(stage == 0, stage0_in, cur_in)
        out = block_fn(stage_params, inp)
        # Shift activations to the next stage over the ICI ring.
        nxt = lax.ppermute(
            out,
            axis_name,
            perm=[(i, (i + 1) % num_stages) for i in range(num_stages)],
        )
        # Last stage banks its finished microbatch (valid when
        # t - (num_stages-1) in [0, num_microbatches)).
        done_idx = jnp.clip(t - (num_stages - 1), 0, num_microbatches - 1)
        is_valid = (t >= num_stages - 1) & (stage == num_stages - 1)
        banked = lax.dynamic_update_index_in_dim(
            outputs,
            jnp.where(is_valid, out, lax.dynamic_index_in_dim(outputs, done_idx, 0, keepdims=False)),
            done_idx,
            axis=0,
        )
        return (banked, nxt), None

    out_shape = jax.eval_shape(block_fn, stage_params, x[0])
    outputs0 = jnp.zeros((num_microbatches,) + out_shape.shape, out_shape.dtype)
    (outputs, _), _ = lax.scan(
        tick, (outputs0, jnp.zeros_like(x[0])), jnp.arange(n_ticks)
    )
    return outputs


def make_pipelined_serve(
    mesh: Mesh,
    stage_fn: Callable[..., tuple],
    *,
    params_spec: Any,
    cache_spec: Any,
    row_specs: tuple = (),
    x_spec: P = None,
    num_microbatches: int = None,
):
    """Pipeline-parallel *serving* step over the ``pipe`` axis, with
    inter-batch overlap.

    The reference pipelines inference by mapping layer ranges to stages
    and keeps up to 4 batches in flight across them (reference
    ``src/runtime/inference_manager.cc:91-133`` stage mapping +
    ``request_manager.cc:2310-2325`` batch-future pipeline). Here each
    stage holds its slice of the stacked layer params AND of the
    layer-major KV cache, and the request slots are split into
    ``num_microbatches`` groups that flow through the stages
    GPipe-style: while stage 1 runs group 0, stage 0 already runs group
    1 — ≥2 batches in flight, the reference's overlap. Activations move
    stage-to-stage over the ICI ring via ``ppermute``.

    ``stage_fn(stage_layers, stage_caches, h, row_args) -> (h,
    new_caches)`` runs one stage's local layer stack over ONE slot
    group, updating that group's rows of its local cache slice (slot
    slicing happens here, outside ``stage_fn``). ``row_args`` is a
    pytree of per-slot tensors (masks, positions, rope tables) with
    leading dim = slots; they are grouped the same way. They must be
    passed as args, NOT captured by closure: closures replicate over
    manual axes, which would mismatch the slot-sharded activations.

    Schedule: ``M + S - 1`` ticks for M groups over S stages, each tick
    costing (layers/S × slots/M) — stage-tick utilisation M/(M+S-1)
    versus 1/S for the unoverlapped single-batch schedule. Defaults to
    M = S groups when the local slot count divides evenly, else M = 1
    (the old schedule). Stage s's group-m cache commit happens at tick
    s+m; garbage ticks are masked out. The final stage banks each
    finished group; the banked full batch is broadcast with a psum.

    Partial-manual shard_map: ``pipe`` AND ``data`` are manual (each DP
    group serves its own request slots, so the KV-cache scatter stays
    shard-local); Megatron TP of the per-stage weights stays under
    GSPMD on ``model``.
    """
    num_stages = mesh.shape[PIPE_AXIS]
    perm = [(i, (i + 1) % num_stages) for i in range(num_stages)]
    if x_spec is None:
        x_spec = P(DATA_AXIS)

    def inner(stage_layers, caches, h, row_args):
        stage = lax.axis_index(PIPE_AXIS)
        R = h.shape[0]  # local slots (data axis is manual)
        M = num_microbatches or num_stages
        if R % M:
            if num_microbatches:
                # an EXPLICITLY requested schedule is being dropped —
                # say so (the default M=num_stages case may degrade
                # silently, same as the flash/SP fallbacks)
                from ..logging_utils import get_logger

                get_logger("serve").warning(
                    "pipelined serve: requested num_microbatches=%d does"
                    " not divide local slot count %d — falling back to"
                    " M=1 (no overlap)", num_microbatches, R,
                )
            M = 1
        G = R // M
        S = num_stages
        h_mb = h.reshape(M, G, *h.shape[1:])
        row_mb = jax.tree.map(
            lambda a: a.reshape(M, G, *a.shape[1:]), row_args
        )
        out_struct = jax.eval_shape(
            lambda: stage_fn(
                stage_layers,
                jax.tree.map(
                    lambda c: lax.dynamic_slice_in_dim(c, 0, G, axis=1),
                    caches,
                ),
                h_mb[0],
                jax.tree.map(lambda a: a[0], row_mb),
            )[0]
        )

        def tick(carry, t):
            outputs, cur_in, cs = carry
            m = jnp.clip(t - stage, 0, M - 1)  # this stage's group now
            valid = (t >= stage) & (t - stage < M)
            inp0 = lax.dynamic_index_in_dim(h_mb, m, 0, keepdims=False)
            inp = jnp.where(stage == 0, inp0, cur_in)
            row_t = jax.tree.map(
                lambda a: lax.dynamic_index_in_dim(a, m, 0, keepdims=False),
                row_mb,
            )
            slot0 = m * G
            cs_g = jax.tree.map(
                lambda c: lax.dynamic_slice_in_dim(c, slot0, G, axis=1), cs
            )
            out, cs_g_new = stage_fn(stage_layers, cs_g, inp, row_t)
            cs = jax.tree.map(
                lambda c, new, old: lax.dynamic_update_slice_in_dim(
                    c, jnp.where(valid, new, old), slot0, axis=1
                ),
                cs,
                cs_g_new,
                cs_g,
            )
            # final stage banks its finished group
            bank = jnp.clip(t - (S - 1), 0, M - 1)
            is_done = valid & (stage == S - 1)
            outputs = lax.dynamic_update_index_in_dim(
                outputs,
                jnp.where(
                    is_done,
                    out,
                    lax.dynamic_index_in_dim(outputs, bank, 0, keepdims=False),
                ),
                bank,
                axis=0,
            )
            nxt = lax.ppermute(out, PIPE_AXIS, perm)
            return (outputs, nxt, cs), None

        outputs0 = jnp.zeros((M,) + out_struct.shape, out_struct.dtype)
        (outputs, _, caches_out), _ = lax.scan(
            tick,
            (outputs0, jnp.zeros_like(h_mb[0]), caches),
            jnp.arange(M + S - 1),
        )
        full = outputs.reshape((R,) + out_struct.shape[1:])
        out = lax.psum(
            jnp.where(stage == S - 1, full, jnp.zeros_like(full)), PIPE_AXIS
        )
        return out, caches_out

    return _partial_shard_map(
        inner, mesh,
        (params_spec, cache_spec, x_spec, row_specs),
        (x_spec, cache_spec),
        {PIPE_AXIS, DATA_AXIS},
    )


def make_pipelined_apply(
    mesh: Mesh,
    block_fn: Callable[[Any, jnp.ndarray], jnp.ndarray],
    *,
    num_microbatches: int,
    params_spec: Any,
    x_spec: P = P(),
):
    """Wrap ``pipeline_forward`` in shard_map over the mesh's pipe axis.

    params_spec: PartitionSpec pytree for the stacked layer params whose
    leading (layer) dim is sharded over 'pipe'. In partial-manual mode the
    specs may only name the ``pipe`` axis — data/model sharding of the
    activations stays under GSPMD (x replicated across stages).
    """
    num_stages = mesh.shape[PIPE_AXIS]

    def inner(stage_params, x_mb):
        out = pipeline_forward(
            block_fn,
            stage_params,
            x_mb,
            num_stages=num_stages,
            num_microbatches=num_microbatches,
        )
        # Broadcast final-stage result back to all stages so downstream
        # (loss) code is stage-agnostic: zero non-final copies, psum.
        if num_stages > 1:
            is_last = lax.axis_index(PIPE_AXIS) == num_stages - 1
            out = lax.psum(
                jnp.where(is_last, out, jnp.zeros_like(out)), PIPE_AXIS
            )
        return out

    # Partial-manual mode: only the pipe axis is manual; data/model axes
    # remain under GSPMD, so DP batch sharding and Megatron TP compose
    # with the pipeline loop without manual collectives for them.
    return _partial_shard_map(
        inner, mesh, (params_spec, x_spec), x_spec, {PIPE_AXIS},
    )
