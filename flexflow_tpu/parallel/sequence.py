"""Sequence/context parallelism: ring attention + Ulysses all-to-all.

The reference has **no** sequence parallelism (SURVEY.md §2.2: closest
is variable-seq-len batch_matmul); long context is a required new
capability of the TPU framework (SURVEY.md §7 step 7). Two standard
schemes, both as `shard_map` primitives over the ``seq`` mesh axis:

  * :func:`ring_attention` — K/V blocks rotate around the ICI ring via
    ``ppermute`` while each device keeps its query block resident,
    accumulating softmax online (flash-attention style m/l/o carry).
    Memory per device stays O(S/n); comm overlaps with the next block's
    compute in XLA's scheduler. Causality is enforced from global block
    positions, so later K/V blocks are masked without materialising an
    S×S mask.
  * :func:`ulysses_attention` — all-to-all re-shards (B, S/n, H, d) →
    (B, S, H/n, d), runs plain attention on whole sequences for a head
    subset, and all-to-alls back. Cheaper comm volume for moderate S;
    requires heads % seq_degree == 0.

Both compute attention exactly (they are layout transforms + online
softmax), so tests assert bit-level-ish equality with the dense
reference implementation.
"""
from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..core.mesh import (
    DATA_AXIS,
    MODEL_AXIS,
    SEQ_AXIS,
    shard_map_unchecked as _shard_map_unchecked,
)

# The check_rep/check_vma compat shim previously copy-pasted here and in
# parallel/pipeline.py lives in core.mesh.shard_map_unchecked now — ONE
# shim for every collective primitive (see its docstring for why the
# static replication checker is off on jax 0.4.x).


def _online_block(q, k, v, o, m, l, qpos, kpos, scale, causal, kv_len=None):
    """One K/V block of online-softmax attention.

    q (B,Sq,H,d) f.* ; k/v (B,Sk,H,d); o (B,Sq,H,d) f32 accumulator;
    m/l (B,H,Sq) running max / denominator (f32). ``kv_len`` masks
    padded K/V positions (global kpos >= kv_len) when the sequence was
    right-padded to a multiple of the seq-axis degree.
    """
    scores = jnp.einsum(
        "bshd,bthd->bhst", q, k, preferred_element_type=jnp.float32
    ) * scale
    mask = None
    if causal:
        mask = kpos[None, :] <= qpos[:, None]  # (Sq, Sk)
    if kv_len is not None:
        kv_valid = jnp.broadcast_to(kpos[None, :] < kv_len, (qpos.shape[0], kpos.shape[0]))
        mask = kv_valid if mask is None else (mask & kv_valid)
    if mask is not None:
        scores = jnp.where(mask[None, None], scores, -jnp.inf)
    m_new = jnp.maximum(m, scores.max(axis=-1))
    # fully-masked rows keep m=-inf; guard the exp against -inf - -inf
    safe_m = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
    p = jnp.exp(jnp.where(jnp.isfinite(scores), scores - safe_m[..., None], -jnp.inf))
    p = jnp.where(jnp.isfinite(p), p, 0.0)
    corr = jnp.where(jnp.isfinite(m), jnp.exp(m - safe_m), jnp.where(m_new == m, 1.0, 0.0))
    corr = jnp.where(jnp.isfinite(corr), corr, 0.0)
    l_new = l * corr + p.sum(axis=-1)
    o_new = o * corr.transpose(0, 2, 1)[..., None] + jnp.einsum(
        "bhst,bthd->bshd", p, v.astype(jnp.float32)
    )
    return o_new, m_new, l_new


def _ring_attention_local(q, k, v, *, axis_name: str, causal: bool, scale: float,
                          kv_len: Optional[int] = None):
    """Per-shard body (inside shard_map): local q stays, k/v rotate.
    K/V may carry fewer (GQA/MQA) heads than q — they rotate compact
    (H/KV× less ppermute traffic) and expand only inside the block."""
    n = lax.psum(1, axis_name)
    i = lax.axis_index(axis_name)
    B, S, H, d = q.shape
    rep = H // k.shape[2]
    qf = q.astype(jnp.float32)
    q_pos = i * S + jnp.arange(S)

    def body(step, carry):
        o, m, l, kk, vv = carry
        j = (i - step) % n
        k_pos = j * S + jnp.arange(S)
        ke = jnp.repeat(kk, rep, axis=2) if rep > 1 else kk
        ve = jnp.repeat(vv, rep, axis=2) if rep > 1 else vv
        o, m, l = _online_block(qf, ke, ve, o, m, l, q_pos, k_pos, scale, causal, kv_len)
        perm = [(s, (s + 1) % n) for s in range(n)]
        kk = lax.ppermute(kk, axis_name, perm)
        vv = lax.ppermute(vv, axis_name, perm)
        return o, m, l, kk, vv

    # derive accumulators from q so they carry the same varying-manual-axes
    # type as loop-computed values (shard_map tracks axis provenance)
    o0 = jnp.zeros_like(qf)
    m0 = jnp.full_like(qf[..., 0].transpose(0, 2, 1), -jnp.inf)  # (B, H, S)
    l0 = jnp.zeros_like(m0)
    o, m, l, _, _ = lax.fori_loop(0, n, body, (o0, m0, l0, k, v))
    l = jnp.maximum(l, 1e-20)
    return (o / l.transpose(0, 2, 1)[..., None]).astype(q.dtype)


def ring_attention(
    q: jnp.ndarray,  # (B, S, H, d) — S sharded on the seq axis
    k: jnp.ndarray,
    v: jnp.ndarray,
    mesh: Mesh,
    *,
    causal: bool = True,
    scale: Optional[float] = None,
    shard_heads: bool = True,
) -> jnp.ndarray:
    """Exact attention with the sequence dim sharded over ``seq`` and
    (optionally) heads over ``model``. K/V may carry fewer heads
    (GQA/MQA) — they rotate compact and expand per block."""
    scale = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    h_axis = MODEL_AXIS if shard_heads else None
    if shard_heads and mesh.shape[MODEL_AXIS] > 1:
        if k.shape[2] % mesh.shape[MODEL_AXIS]:
            # K/V rotate COMPACT around the ring (GQA heads expand only
            # inside each block), so the KV-head dim itself must split
            # over the model axis. Name the fixes that actually resolve
            # it: expand K/V to the full head count BEFORE calling
            # (jnp.repeat — trades the compact-rotation bandwidth win
            # for shardability), lower the tensor-parallel (model)
            # degree to a divisor of the KV head count, or pass
            # shard_heads=False and take the seq-only sharding.
            raise ValueError(
                f"GQA ring attention shards KV heads over the model "
                f"axis, but {k.shape[2]} KV heads do not divide by the "
                f"model degree ({mesh.shape[MODEL_AXIS]}). Fix: repeat "
                f"K/V to the full {q.shape[2]} heads before the call, "
                f"lower the tensor-parallel degree to a divisor of "
                f"{k.shape[2]}, or pass shard_heads=False"
            )
    n_seq = mesh.shape[SEQ_AXIS]
    S = q.shape[1]
    pad = (-S) % n_seq  # shard_map needs S % n_seq == 0: right-pad + mask
    if pad:
        q, k, v = (jnp.pad(t, ((0, 0), (0, pad), (0, 0), (0, 0))) for t in (q, k, v))
    qspec = P(DATA_AXIS, SEQ_AXIS, h_axis, None)
    fn = _shard_map_unchecked(
        functools.partial(
            _ring_attention_local, axis_name=SEQ_AXIS, causal=causal, scale=scale,
            kv_len=S if pad else None,
        ),
        mesh=mesh,
        in_specs=(qspec, qspec, qspec),
        out_specs=qspec,
    )
    out = fn(q, k, v)
    return out[:, :S] if pad else out


def _ulysses_local(q, k, v, *, axis_name: str, causal: bool, scale: float,
                   kv_len: Optional[int] = None):
    """Per-shard body: all-to-all seq→heads, dense attention, back."""
    n = lax.psum(1, axis_name)

    def to_heads(x):  # (B, S/n, H, d) -> (B, S, H/n, d)
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1, tiled=True)

    def to_seq(x):  # inverse
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2, tiled=True)

    rep = q.shape[2] // k.shape[2]
    if rep > 1 and k.shape[2] % n == 0:
        # keep K/V compact through the all-to-all, expand after
        kh = jnp.repeat(to_heads(k), rep, axis=2)
        vh = jnp.repeat(to_heads(v), rep, axis=2)
    elif rep > 1:
        kh = to_heads(jnp.repeat(k, rep, axis=2))
        vh = to_heads(jnp.repeat(v, rep, axis=2))
    else:
        kh, vh = to_heads(k), to_heads(v)
    qh = to_heads(q)
    B, S, Hn, d = qh.shape
    scores = jnp.einsum(
        "bshd,bthd->bhst", qh.astype(jnp.float32), kh, preferred_element_type=jnp.float32
    ) * scale
    mask = jnp.tril(jnp.ones((S, S), bool)) if causal else None
    if kv_len is not None:
        kv_valid = jnp.broadcast_to(jnp.arange(S)[None, :] < kv_len, (S, S))
        mask = kv_valid if mask is None else (mask & kv_valid)
    if mask is not None:
        scores = jnp.where(mask[None, None], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhst,bthd->bshd", probs, vh.astype(jnp.float32))
    return to_seq(out.astype(q.dtype))


def ulysses_attention(
    q: jnp.ndarray,  # (B, S, H, d) — S sharded on the seq axis
    k: jnp.ndarray,
    v: jnp.ndarray,
    mesh: Mesh,
    *,
    causal: bool = True,
    scale: Optional[float] = None,
    shard_heads: bool = True,
) -> jnp.ndarray:
    """DeepSpeed-Ulysses-style SP: all-to-all head redistribution, then
    whole-sequence attention per head subset. Heads must divide by the
    seq degree (after any ``model``-axis head sharding)."""
    n_seq = mesh.shape[SEQ_AXIS]
    H = q.shape[2]
    if shard_heads:
        H = H // mesh.shape[MODEL_AXIS] if mesh.shape[MODEL_AXIS] > 1 else H
    assert H % n_seq == 0, (
        f"ulysses needs heads-per-TP-shard ({H}) divisible by seq degree ({n_seq})"
    )
    scale = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    h_axis = MODEL_AXIS if shard_heads else None
    S = q.shape[1]
    pad = (-S) % n_seq  # all_to_all needs S % n_seq == 0: right-pad + mask
    if pad:
        q, k, v = (jnp.pad(t, ((0, 0), (0, pad), (0, 0), (0, 0))) for t in (q, k, v))
    spec = P(DATA_AXIS, SEQ_AXIS, h_axis, None)
    fn = _shard_map_unchecked(
        functools.partial(
            _ulysses_local, axis_name=SEQ_AXIS, causal=causal, scale=scale,
            kv_len=S if pad else None,
        ),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )
    out = fn(q, k, v)
    return out[:, :S] if pad else out
