"""Tensor-parallel rewrite pass.

The reference hardcodes a Megatron-style rewrite in
``FFModel::create_operators_from_layers`` (reference
``src/runtime/model.cc:3239-3312``): partition attention heads and the
FFN hidden dim across the TP group, insert ``AllReduce`` after the
attention output projection and FFN down-projection, and ``Combine``
before softmax/argmax heads. On TPU the same strategy is *declarative*:
this pass pattern-matches the graph and stamps ``tp_shard`` attrs on the
matched ops; their ``weight_pspecs`` then emit column/row/head-parallel
PartitionSpecs, and GSPMD compiles the implied all-reduces (partial-sum
contractions over the ``model`` axis) onto ICI — no explicit parallel
ops needed.

Patterns (mirroring the reference's matcher at model.cc:3279-3306):
  * ``multihead_attention``            → head-parallel (col QKV, row O)
  * up-proj dense (+act / SwiGLU glue) → column-parallel
  * the dense consuming it             → row-parallel (partial sums
                                         all-reduced by GSPMD)
  * ``embedding``                      → hidden-dim (column) parallel
"""
from __future__ import annotations

from typing import Dict, Set

from ..core.graph import Graph, OpNode

# Ops through which a column-sharded activation flows unchanged (the
# elementwise epilogue between up-proj and down-proj).
_PASSTHROUGH = {
    "element_unary",
    "dropout",
    "sigmoid_silu_multi",
    "element_binary",
    "cast",
}


def _set_attr(node: OpNode, key: str, value) -> None:
    d = dict(node.attrs)
    d[key] = value
    node.attrs = tuple(sorted(d.items()))


def _consumers_through(graph: Graph, node_id: int, seen: Set[int]):
    """Yield dense consumers reachable through passthrough ops."""
    for c in graph.consumers(node_id):
        if c.id in seen:
            continue
        seen.add(c.id)
        if c.op_type == "dense":
            yield c
        elif c.op_type in _PASSTHROUGH:
            yield from _consumers_through(graph, c.id, seen)


def apply_tensor_parallel(graph: Graph, tp_degree: int) -> Dict[str, str]:
    """Stamp tp_shard attrs; returns {node_name: role} for logging/tests."""
    if tp_degree <= 1:
        return {}
    decisions: Dict[str, str] = {}
    row_nodes: Set[int] = set()

    for node in graph.nodes:
        if node.op_type == "multihead_attention":
            attrs = node.attrs_dict
            if attrs["num_heads"] % tp_degree == 0:
                _set_attr(node, "tp_shard", "heads")
                decisions[node.name] = "heads"
        elif node.op_type == "transformer_decoder_stack":
            attrs = node.attrs_dict
            kv = attrs.get("num_kv_heads") or attrs["num_heads"]
            if kv % tp_degree == 0 and attrs["intermediate_size"] % tp_degree == 0:
                _set_attr(node, "tp_shard", "megatron")
                decisions[node.name] = "megatron"
        # embeddings stay replicated: vocab/hidden sharding of the table is
        # a serving-time decision (lm_head fusion), not part of this pass.

    for node in graph.nodes:
        if node.op_type != "dense" or node.id in row_nodes:
            continue
        attrs = node.attrs_dict
        if attrs.get("tp_shard"):
            continue
        in_spec = graph.out_spec(node.inputs[0])
        in_dim, out_dim = in_spec.shape[-1], attrs["out_dim"]
        if out_dim % tp_degree:
            continue
        if out_dim >= in_dim * 2:  # up-projection heuristic (FFN expand)
            partners = [
                c
                for c in _consumers_through(graph, node.id, set())
                if c.attrs_dict["out_dim"] == in_dim
                and graph.out_spec(c.inputs[0]).shape[-1] % tp_degree == 0
            ]
            if partners:
                _set_attr(node, "tp_shard", "col")
                decisions[node.name] = "col"
                for p in partners:
                    if not p.attrs_dict.get("tp_shard"):
                        _set_attr(p, "tp_shard", "row")
                        decisions[p.name] = "row"
                        row_nodes.add(p.id)
    return decisions
