"""Native GPT-2-style BPE tokenizer binding.

The reference ships a C++ BPE tokenizer (reference
``src/runtime/gpt_tokenizer.cc``) used by its tests, with the main
serving path on the external tokenizers-cpp dependency; the serving
stack here delegates to HF AutoTokenizer the same way
(serve/llm.py). This module binds our own C++ implementation
(``native/gpt_tokenizer.cpp``) for HF-free environments — it reads the
standard GPT-2 artifact pair (vocab.json + merges.txt).
"""
from __future__ import annotations

import ctypes
from typing import List

from .native import load_library


class GPTTokenizer:
    def __init__(self, vocab_json: str, merges_txt: str):
        lib = load_library("fftok")
        if lib is None:
            raise RuntimeError("native tokenizer unavailable (no g++?)")
        lib.fftok_create.restype = ctypes.c_void_p
        lib.fftok_create.argtypes = [ctypes.c_char_p, ctypes.c_char_p]
        lib.fftok_encode.restype = ctypes.c_int64
        lib.fftok_encode.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_void_p, ctypes.c_int64
        ]
        lib.fftok_decode.restype = ctypes.c_int64
        lib.fftok_decode.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
            ctypes.c_void_p, ctypes.c_int64,
        ]
        lib.fftok_vocab_size.restype = ctypes.c_int64
        lib.fftok_vocab_size.argtypes = [ctypes.c_void_p]
        lib.fftok_destroy.argtypes = [ctypes.c_void_p]
        self._lib = lib
        self._h = lib.fftok_create(
            vocab_json.encode(), merges_txt.encode()
        )
        if not self._h:
            raise ValueError(
                f"failed to load tokenizer from {vocab_json} / {merges_txt}"
            )

    @property
    def vocab_size(self) -> int:
        return self._lib.fftok_vocab_size(self._h)

    def encode(self, text: str) -> List[int]:
        data = text.encode("utf-8")
        cap = max(16, 2 * len(data))
        out = (ctypes.c_int32 * cap)()
        n = self._lib.fftok_encode(self._h, data, out, cap)
        return list(out[:n])

    def decode(self, ids: List[int]) -> str:
        n = len(ids)
        arr = (ctypes.c_int32 * n)(*[int(i) for i in ids])
        cap = max(64, 16 * n)
        while True:
            buf = ctypes.create_string_buffer(cap)
            m = self._lib.fftok_decode(self._h, arr, n, buf, cap)
            if m < cap:  # m == cap means the C side clamped: grow
                return buf.raw[:m].decode("utf-8", errors="replace")
            cap *= 4

    def __del__(self):
        if getattr(self, "_h", None):
            self._lib.fftok_destroy(self._h)
            self._h = None
