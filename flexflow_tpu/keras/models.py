"""Keras-style Model / Sequential (reference
``python/flexflow/keras/models/``): lower the symbolic layer graph onto
an FFModel, then delegate compile/fit/evaluate/predict."""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Union

import numpy as np

from ..config import FFConfig
from ..model import FFModel
from .layers import Input, KTensor, Layer


_LOSS_MAP = {
    "sparse_categorical_crossentropy": "sparse_categorical_crossentropy",
    "categorical_crossentropy": "categorical_crossentropy",
    "mse": "mean_squared_error",
    "mean_squared_error": "mean_squared_error",
}


class Model:
    """Functional model: ``Model(inputs, outputs)`` (reference keras
    ``Model``). The KTensor graph is topologically lowered to FFModel
    builder calls at construction."""

    def __init__(self, inputs, outputs, batch_size: int = 64,
                 config: Optional[FFConfig] = None, name: str = "model"):
        self.inputs = list(inputs) if isinstance(inputs, (list, tuple)) else [inputs]
        self.outputs = list(outputs) if isinstance(outputs, (list, tuple)) else [outputs]
        assert len(self.outputs) == 1, "single-output models supported"
        self.name = name
        self.config = config or FFConfig(batch_size=batch_size)
        self.batch_size = self.config.batch_size
        self.ffmodel = FFModel(self.config)
        self._lower()

    def _lower(self):
        ff = self.ffmodel
        env: Dict[int, Any] = {}
        for kt in self.inputs:
            shape = (self.batch_size,) + tuple(kt.shape[1:])
            dtype = "int32" if getattr(kt, "dtype", "float32") in ("int32", "int64") else "float32"
            env[id(kt)] = ff.create_tensor(shape, dtype=dtype, name=kt.name)

        def visit(kt: KTensor):
            if id(kt) in env:
                return env[id(kt)]
            ins = [visit(t) for t in kt.inputs]
            env[id(kt)] = kt.layer.emit(ff, ins)
            return env[id(kt)]

        self._ff_output = visit(self.outputs[0])

    # ------------------------------------------------------------------

    def compile(self, optimizer=None, loss="sparse_categorical_crossentropy",
                metrics: Sequence[str] = ("accuracy",), **kw):
        loss = _LOSS_MAP.get(loss, loss)
        self.ffmodel.compile(optimizer=optimizer, loss_type=loss,
                             metrics=metrics, output=self._ff_output, **kw)
        return self

    def fit(self, x, y, epochs: int = 1, batch_size: Optional[int] = None,
            callbacks: Optional[Sequence] = None, verbose: bool = True,
            **kw):
        """Per-epoch loop with the callback protocol (reference
        ``keras/callbacks.py``); returns a History."""
        from .callbacks import History

        history = History()
        cbs = [history] + list(callbacks or [])
        self.stop_training = False
        for cb in cbs:
            cb.set_model(self)
            cb.set_params({"epochs": epochs, "batch_size": batch_size})
            cb.on_train_begin()
        x, y = np.asarray(x), np.asarray(y)
        logs: Dict[str, float] = {}
        for epoch in range(epochs):
            for cb in cbs:
                cb.on_epoch_begin(epoch)
            perf = self.ffmodel.fit(
                x, y, batch_size=batch_size, epochs=1, verbose=False
            )
            logs = dict(perf.averages())
            if verbose:
                stats = " ".join(f"{k}={v:.4f}" for k, v in logs.items())
                print(f"epoch {epoch}/{epochs}: {stats}")
            for cb in cbs:
                cb.on_epoch_end(epoch, logs)
            if self.stop_training:
                break
        for cb in cbs:
            cb.on_train_end(logs)
        return history

    def evaluate(self, x, y, **kw):
        return self.ffmodel.evaluate(np.asarray(x), np.asarray(y))

    def predict(self, x, **kw):
        return self.ffmodel.forward(np.asarray(x))

    def summary(self) -> str:
        lines = [f'Model "{self.name}"']
        for node in self.ffmodel.graph.nodes:
            lines.append(f"  {node.name:<24} {node.op_type:<16} "
                         f"{[s.shape for s in node.out_specs]}")
        return "\n".join(lines)


class Sequential(Model):
    """reference keras ``Sequential``: stack of layers; input shape comes
    from an ``Input`` first element or ``input_shape`` on the first
    layer call."""

    def __init__(self, layers: Optional[Sequence[Union[KTensor, Layer]]] = None,
                 batch_size: int = 64, config: Optional[FFConfig] = None,
                 name: str = "sequential"):
        self._layers: List[Layer] = []
        self._input: Optional[KTensor] = None
        self._pending = list(layers or [])
        self._batch_size = batch_size
        self._config = config
        self._name = name
        self._built = False
        for item in self._pending:
            self.add(item, _defer=True)

    def add(self, item: Union[KTensor, Layer], _defer: bool = False):
        if isinstance(item, KTensor):
            assert item.layer is None, "first element must be an Input"
            self._input = item
        else:
            self._layers.append(item)

    def _build(self):
        assert self._input is not None, "Sequential needs an Input first"
        t = self._input
        for layer in self._layers:
            t = layer(t)
        super().__init__(self._input, t, batch_size=self._batch_size,
                         config=self._config, name=self._name)
        self._built = True

    def compile(self, *a, **kw):
        if not self._built:
            self._build()
        return super().compile(*a, **kw)
