"""Keras-style weight regularizers (reference
``python/flexflow/keras/regularizers.py`` L1/L2 → REG_MODE_L1/L2).

A regularizer lowers to the ``("l1"|"l2", λ)`` attr that the dense/conv
ops turn into an aux-loss term inside the jitted train step."""
from __future__ import annotations

import dataclasses


@dataclasses.dataclass(frozen=True)
class Regularizer:
    kind: str = ""
    lam: float = 0.0

    def to_attr(self):
        return (self.kind, float(self.lam)) if self.kind else None


class L1(Regularizer):
    def __init__(self, l1: float = 0.01):
        super().__init__(kind="l1", lam=l1)


class L2(Regularizer):
    def __init__(self, l2: float = 0.01):
        super().__init__(kind="l2", lam=l2)


def l1(l1: float = 0.01) -> L1:  # noqa: A001 — keras-compatible names
    return L1(l1)


def l2(l2: float = 0.01) -> L2:  # noqa: A001
    return L2(l2)


def resolve(reg):
    """Regularizer | ("l1"/"l2", λ) | "l1"/"l2" | None → attr tuple.
    Unknown kinds raise here, next to the user's layer call — not as a
    silently-wrong penalty deep in the train step."""
    if reg is None:
        return None
    if isinstance(reg, Regularizer):
        out = reg.to_attr()
    elif isinstance(reg, str):
        out = (reg.lower(), 0.01)
    else:
        kind, lam = reg
        out = (str(kind).lower(), float(lam))
    if out is not None and out[0] not in ("l1", "l2"):
        raise ValueError(
            f"unknown regularizer kind {out[0]!r}; supported: l1, l2"
        )
    return out
