"""Keras-style optimizer shims (reference
``python/flexflow/keras/optimizers.py``) mapping onto the framework
optimizers."""
from __future__ import annotations

from ..optimizers import AdamOptimizer, SGDOptimizer


def SGD(learning_rate: float = 0.01, momentum: float = 0.0,
        nesterov: bool = False, weight_decay: float = 0.0):
    return SGDOptimizer(lr=learning_rate, momentum=momentum,
                        nesterov=nesterov, weight_decay=weight_decay)


def Adam(learning_rate: float = 0.001, beta_1: float = 0.9,
         beta_2: float = 0.999, epsilon: float = 1e-8):
    return AdamOptimizer(lr=learning_rate, beta1=beta_1, beta2=beta_2,
                         epsilon=epsilon)
