"""Keras-style dataset loaders (reference
``python/flexflow/keras/datasets/``: mnist, cifar10, reuters).

This environment has no network egress, so each loader reads the
standard cached artifact from a local path (``~/.keras/datasets`` or
``path=``) when present — the exact files keras would have downloaded —
and otherwise falls back to a deterministic synthetic set with the real
shapes/dtypes so examples and tests run anywhere. The return contract
matches tf.keras: ``(x_train, y_train), (x_test, y_test)``.
"""
from . import cifar10, mnist, reuters  # noqa: F401
