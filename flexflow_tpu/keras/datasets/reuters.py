"""Reuters newswire topic loader (reference
``keras/datasets/reuters.py``)."""
import os

import numpy as np

_CACHE = os.path.expanduser("~/.keras/datasets/reuters.npz")


def load_data(path: str = _CACHE, num_words=None, test_split: float = 0.2,
              seed: int = 113, synthetic_ok: bool = True):
    """Returns ((x_train, y_train), (x_test, y_test)); x = lists of word
    indices, y = topic ids (46 classes)."""
    if os.path.exists(path):
        with np.load(path, allow_pickle=True) as f:
            xs, labels = f["x"], f["y"]
        rng = np.random.default_rng(seed)
        idx = rng.permutation(len(xs))
        xs, labels = xs[idx], labels[idx]
        if num_words:
            xs = np.asarray(
                [[w for w in seq if w < num_words] for seq in xs],
                dtype=object,
            )
        n_test = int(len(xs) * test_split)
        return (xs[n_test:], labels[n_test:]), (xs[:n_test], labels[:n_test])
    if not synthetic_ok:
        raise FileNotFoundError(path)
    rng = np.random.default_rng(seed)
    vocab = num_words or 1000

    def make(n):
        y = rng.integers(0, 46, size=n).astype(np.int64)
        xs = []
        for label in y:
            length = int(rng.integers(20, 200))
            # topic-dependent word distribution
            xs.append(
                list(
                    (rng.integers(0, vocab // 4, size=length)
                     + label * 3) % vocab
                )
            )
        return np.asarray(xs, dtype=object), y

    x, y = make(2000)
    n_test = int(2000 * test_split)
    return (x[n_test:], y[n_test:]), (x[:n_test], y[:n_test])
