"""CIFAR-10 loader (reference ``keras/datasets/cifar10.py`` /
``cifar.py``)."""
import os

import numpy as np

_CACHE = os.path.expanduser("~/.keras/datasets/cifar-10-batches-py")


def _load_batch(fpath):
    import pickle

    with open(fpath, "rb") as f:
        d = pickle.load(f, encoding="bytes")
    data = d[b"data"].reshape(-1, 3, 32, 32)
    labels = np.asarray(d[b"labels"], np.uint8)
    return data, labels


def load_data(path: str = _CACHE, synthetic_ok: bool = True):
    """Returns ((x_train, y_train), (x_test, y_test)); x uint8
    (N, 3, 32, 32) channel-first like the reference's loader."""
    if os.path.isdir(path):
        xs, ys = [], []
        for i in range(1, 6):
            x, y = _load_batch(os.path.join(path, f"data_batch_{i}"))
            xs.append(x)
            ys.append(y)
        x_test, y_test = _load_batch(os.path.join(path, "test_batch"))
        return (np.concatenate(xs), np.concatenate(ys)), (x_test, y_test)
    if not synthetic_ok:
        raise FileNotFoundError(path)
    rng = np.random.default_rng(1)

    def make(n):
        y = rng.integers(0, 10, size=n).astype(np.uint8)
        base = rng.integers(0, 255, size=(10, 3, 32, 32)).astype(np.uint8)
        noise = rng.integers(0, 60, size=(n, 3, 32, 32)).astype(np.uint8)
        return (base[y] // 2 + noise), y

    return make(5000), make(1000)
