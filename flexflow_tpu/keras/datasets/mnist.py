"""MNIST loader (reference ``keras/datasets/mnist.py``)."""
import os

import numpy as np

_CACHE = os.path.expanduser("~/.keras/datasets/mnist.npz")


def load_data(path: str = _CACHE, synthetic_ok: bool = True):
    """Returns ((x_train, y_train), (x_test, y_test)); x uint8
    (N, 28, 28), y uint8 (N,). Reads keras' standard mnist.npz when
    available, else a deterministic synthetic stand-in."""
    if os.path.exists(path):
        with np.load(path, allow_pickle=True) as f:
            return (f["x_train"], f["y_train"]), (f["x_test"], f["y_test"])
    if not synthetic_ok:
        raise FileNotFoundError(path)
    rng = np.random.default_rng(0)

    def make(n):
        y = rng.integers(0, 10, size=n).astype(np.uint8)
        x = np.zeros((n, 28, 28), np.uint8)
        # class-dependent blob so models can actually fit it
        for c in range(10):
            idx = y == c
            cx, cy = 4 + 2 * c, 24 - 2 * c
            x[idx, cx - 3 : cx + 3, cy - 3 : cy + 3] = 200
        x += rng.integers(0, 40, size=x.shape).astype(np.uint8)
        return x, y

    return make(6000), make(1000)
