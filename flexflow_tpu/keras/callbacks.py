"""Keras callbacks (reference ``python/flexflow/keras/callbacks.py``:
Callback base, LearningRateScheduler, VerifyMetrics,
EpochVerifyMetrics) plus the standard EarlyStopping and History."""
from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np


class Callback:
    def __init__(self):
        self.model = None
        self.params: Dict[str, Any] = {}
        self.validation_data = None

    def set_params(self, params):
        self.params = params

    def set_model(self, model):
        self.model = model

    def on_train_begin(self, logs=None):
        pass

    def on_train_end(self, logs=None):
        pass

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass


class History(Callback):
    """Records per-epoch logs; ``fit`` returns it (keras convention)."""

    def on_train_begin(self, logs=None):
        self.epoch: List[int] = []
        self.history: Dict[str, List[float]] = {}

    def on_epoch_end(self, epoch, logs=None):
        self.epoch.append(epoch)
        for k, v in (logs or {}).items():
            self.history.setdefault(k, []).append(v)


class LearningRateScheduler(Callback):
    """reference LearningRateScheduler: ``schedule(epoch) -> lr``. The
    LR is a device scalar in the optimizer state, so no recompile."""

    def __init__(self, schedule):
        super().__init__()
        self.schedule = schedule

    def on_epoch_begin(self, epoch, logs=None):
        lr = self.schedule(epoch)
        if not isinstance(lr, (float, np.floating)):
            raise ValueError('the "schedule" function should return float')
        self.model.ffmodel.set_learning_rate(float(lr))


class VerifyMetrics(Callback):
    """reference VerifyMetrics: assert final accuracy above a bar."""

    def __init__(self, accuracy: float):
        super().__init__()
        self.accuracy = getattr(accuracy, "value", accuracy)

    def on_train_end(self, logs=None):
        acc = (logs or {}).get("accuracy", 0.0)
        assert acc >= self.accuracy, (
            f"accuracy {acc:.4f} below the verification bar {self.accuracy}"
        )


class EpochVerifyMetrics(Callback):
    """reference EpochVerifyMetrics: stop early once accuracy clears the
    bar (early_stop=True)."""

    def __init__(self, accuracy: float, early_stop: bool = True):
        super().__init__()
        self.accuracy = getattr(accuracy, "value", accuracy)
        self.early_stop = early_stop

    def on_epoch_end(self, epoch, logs=None):
        acc = (logs or {}).get("accuracy", 0.0)
        if self.early_stop and acc >= self.accuracy:
            self.model.stop_training = True


class EarlyStopping(Callback):
    """Stop when a monitored metric stops improving."""

    def __init__(self, monitor: str = "loss", min_delta: float = 0.0,
                 patience: int = 0, mode: str = "auto"):
        super().__init__()
        self.monitor = monitor
        self.min_delta = abs(min_delta)
        self.patience = patience
        if mode not in ("auto", "min", "max"):
            mode = "auto"
        if mode == "auto":
            mode = "max" if "acc" in monitor else "min"
        self.mode = mode

    def on_train_begin(self, logs=None):
        self.wait = 0
        self.best = -np.inf if self.mode == "max" else np.inf

    def on_epoch_end(self, epoch, logs=None):
        cur = (logs or {}).get(self.monitor)
        if cur is None:
            return
        improved = (
            cur > self.best + self.min_delta
            if self.mode == "max"
            else cur < self.best - self.min_delta
        )
        if improved:
            self.best = cur
            self.wait = 0
        else:
            self.wait += 1
            if self.wait >= self.patience:  # tf.keras semantics
                self.model.stop_training = True
