"""Sequence preprocessing (reference keras ``preprocessing/sequence.py``
API: pad_sequences, make_sampling_table, skipgrams)."""
from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np


def pad_sequences(
    sequences: Sequence[Sequence[int]],
    maxlen: Optional[int] = None,
    dtype="int32",
    padding: str = "pre",
    truncating: str = "pre",
    value: float = 0.0,
) -> np.ndarray:
    """tf.keras-compatible padding/truncation to (N, maxlen)."""
    lengths = [len(s) for s in sequences]
    if maxlen is None:
        maxlen = max(lengths) if lengths else 0
    out = np.full((len(sequences), maxlen), value, dtype=dtype)
    for i, seq in enumerate(sequences):
        seq = list(seq)
        if len(seq) > maxlen:
            seq = seq[-maxlen:] if truncating == "pre" else seq[:maxlen]
        if not seq:
            continue
        if padding == "pre":
            out[i, -len(seq):] = seq
        else:
            out[i, : len(seq)] = seq
    return out


def make_sampling_table(size: int, sampling_factor: float = 1e-5) -> np.ndarray:
    """Word-rank -> keep-probability table (word2vec subsampling), same
    formula as keras_preprocessing."""
    gamma = 0.577
    rank = np.arange(size)
    rank[0] = 1
    inv_fq = rank * (np.log(rank) + gamma) + 0.5 - 1.0 / (12.0 * rank)
    f = sampling_factor * inv_fq
    return np.minimum(1.0, f / np.sqrt(f))


def skipgrams(
    sequence: Sequence[int],
    vocabulary_size: int,
    window_size: int = 4,
    negative_samples: float = 1.0,
    shuffle: bool = True,
    seed: Optional[int] = None,
):
    """(word, context) skip-gram pairs with negative sampling."""
    rng = np.random.default_rng(seed)
    couples: List[List[int]] = []
    labels: List[int] = []
    for i, wi in enumerate(sequence):
        if not wi:
            continue
        lo = max(0, i - window_size)
        hi = min(len(sequence), i + window_size + 1)
        for j in range(lo, hi):
            if j == i or not sequence[j]:
                continue
            couples.append([wi, sequence[j]])
            labels.append(1)
    if negative_samples > 0:
        n_neg = int(len(labels) * negative_samples)
        words = [c[0] for c in couples]
        rng.shuffle(words)
        for k in range(n_neg):
            couples.append(
                [words[k % len(words)], int(rng.integers(1, vocabulary_size))]
            )
            labels.append(0)
    if shuffle:
        order = rng.permutation(len(couples))
        couples = [couples[i] for i in order]
        labels = [labels[i] for i in order]
    return couples, labels
