"""Keras-style preprocessing (reference
``python/flexflow/keras/preprocessing/``: sequence + text utilities —
the reference re-exports keras_preprocessing; these are self-contained
implementations of the same API)."""
from . import sequence, text  # noqa: F401
from .sequence import pad_sequences  # noqa: F401
from .text import Tokenizer  # noqa: F401
