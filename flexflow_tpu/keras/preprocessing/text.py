"""Text preprocessing (reference keras ``preprocessing/text.py`` API:
Tokenizer with fit_on_texts / texts_to_sequences / texts_to_matrix)."""
from __future__ import annotations

import collections
from typing import Dict, List, Optional, Sequence

import numpy as np

_FILTERS = '!"#$%&()*+,-./:;<=>?@[\\]^_`{|}~\t\n'


def text_to_word_sequence(
    text: str, filters: str = _FILTERS, lower: bool = True, split: str = " "
) -> List[str]:
    if lower:
        text = text.lower()
    table = str.maketrans({c: split for c in filters})
    return [w for w in text.translate(table).split(split) if w]


class Tokenizer:
    """Word-index tokenizer: index 0 reserved, 1 = OOV when set."""

    def __init__(self, num_words: Optional[int] = None,
                 filters: str = _FILTERS, lower: bool = True,
                 split: str = " ", oov_token: Optional[str] = None):
        self.num_words = num_words
        self.filters = filters
        self.lower = lower
        self.split = split
        self.oov_token = oov_token
        self.word_counts: collections.OrderedDict = collections.OrderedDict()
        self.word_index: Dict[str, int] = {}
        self.index_word: Dict[int, str] = {}
        self.document_count = 0

    def fit_on_texts(self, texts: Sequence[str]) -> None:
        for text in texts:
            self.document_count += 1
            for w in text_to_word_sequence(
                text, self.filters, self.lower, self.split
            ):
                self.word_counts[w] = self.word_counts.get(w, 0) + 1
        sorted_words = [
            w for w, _ in sorted(
                self.word_counts.items(), key=lambda kv: -kv[1]
            )
        ]
        if self.oov_token is not None:
            sorted_words = [self.oov_token] + sorted_words
        self.word_index = {w: i + 1 for i, w in enumerate(sorted_words)}
        self.index_word = {i: w for w, i in self.word_index.items()}

    def texts_to_sequences(self, texts: Sequence[str]) -> List[List[int]]:
        oov = self.word_index.get(self.oov_token) if self.oov_token else None
        out = []
        for text in texts:
            seq = []
            for w in text_to_word_sequence(
                text, self.filters, self.lower, self.split
            ):
                idx = self.word_index.get(w)
                if idx is None:
                    if oov is not None:
                        seq.append(oov)
                    continue
                if self.num_words and idx >= self.num_words:
                    if oov is not None:
                        seq.append(oov)
                    continue
                seq.append(idx)
            out.append(seq)
        return out

    def texts_to_matrix(self, texts: Sequence[str], mode: str = "binary"):
        n = self.num_words or (len(self.word_index) + 1)
        m = np.zeros((len(texts), n), np.float32)
        for i, seq in enumerate(self.texts_to_sequences(texts)):
            if not seq:
                continue
            counts = collections.Counter(seq)
            for idx, c in counts.items():
                if mode == "binary":
                    m[i, idx] = 1.0
                elif mode == "count":
                    m[i, idx] = c
                elif mode == "freq":
                    m[i, idx] = c / len(seq)
                else:
                    raise ValueError(mode)
        return m
