"""Keras-style layer records (reference ``python/flexflow/keras/layers/``).

Each layer is a config object; calling it on a :class:`KTensor` records
an edge in the symbolic graph. ``emit(ff, inputs)`` lowers onto FFModel
builders at Model-build time.
"""
from __future__ import annotations

import itertools
from typing import Any, List, Optional, Sequence, Tuple

_counter = itertools.count()

# keras initializer names → the names flexflow_tpu.initializers.resolve
# understands (Initializer instances pass through untouched)
_INIT_NAMES = {
    "glorot_uniform": "glorot_uniform",
    "zeros": "zeros",
    "zero": "zero",
    "random_normal": "normal",
    "random_uniform": "uniform",
    "normal": "normal",
    "uniform": "uniform",
}


def _init_attr(init):
    """Layer-kwarg initializer → op attr (string name or Initializer)."""
    if init is None or not isinstance(init, str):
        return init
    try:
        return _INIT_NAMES[init]
    except KeyError:
        raise ValueError(
            f"unknown initializer {init!r}; known: {sorted(_INIT_NAMES)}"
        ) from None


class KTensor:
    """Symbolic tensor in the Keras graph (pre-FFModel)."""

    __slots__ = ("layer", "inputs", "shape", "name", "dtype")

    def __init__(self, layer, inputs, shape, name):
        self.layer = layer          # producing Layer or None for Input
        self.inputs = inputs        # list[KTensor]
        self.shape = tuple(shape)
        self.name = name
        self.dtype = "float32"


def Input(shape: Sequence[int], batch_size: Optional[int] = None,
          dtype="float32", name: str = ""):
    """Placeholder (reference keras ``Input``): ``shape`` excludes the
    batch dim, matching tf.keras."""
    name = name or f"input_{next(_counter)}"
    full = (batch_size or 0,) + tuple(shape)
    t = KTensor(None, [], full, name)
    t.dtype = dtype
    return t


class Layer:
    n_inputs = 1

    def __init__(self, name: str = ""):
        self.name = name or f"{type(self).__name__.lower()}_{next(_counter)}"

    def __call__(self, x):
        xs = list(x) if isinstance(x, (list, tuple)) else [x]
        return KTensor(self, xs, self.output_shape([t.shape for t in xs]), self.name)

    def output_shape(self, in_shapes: List[Tuple[int, ...]]):
        return in_shapes[0]

    def emit(self, ff, inputs):
        raise NotImplementedError


class Dense(Layer):
    def __init__(self, units: int, activation: Optional[str] = None,
                 use_bias: bool = True,
                 kernel_initializer="glorot_uniform",
                 bias_initializer="zeros",
                 kernel_regularizer=None,
                 bias_regularizer=None,
                 activity_regularizer=None,
                 name: str = ""):
        # kernel knobs match the reference's Dense surface
        # (python/flexflow/keras/layers/core.py:26-40); like the
        # reference, only the kernel regularizer is supported
        super().__init__(name)
        if bias_regularizer is not None or activity_regularizer is not None:
            raise NotImplementedError(
                "bias/activity regularizers are not supported (the "
                "reference rejects them too)"
            )
        self.units, self.activation, self.use_bias = units, activation, use_bias
        self.kernel_initializer = _init_attr(kernel_initializer)
        self.bias_initializer = _init_attr(bias_initializer)
        from . import regularizers as _reg

        self.kernel_regularizer = _reg.resolve(kernel_regularizer)

    def output_shape(self, s):
        return s[0][:-1] + (self.units,)

    def emit(self, ff, inputs):
        return ff.dense(inputs[0], self.units, activation=self.activation,
                        use_bias=self.use_bias,
                        kernel_initializer=self.kernel_initializer,
                        bias_initializer=self.bias_initializer,
                        kernel_regularizer=self.kernel_regularizer,
                        name=self.name)


class Conv2D(Layer):
    def __init__(self, filters: int, kernel_size, strides=(1, 1),
                 padding="valid", activation: Optional[str] = None,
                 use_bias: bool = True, groups: int = 1,
                 kernel_initializer="glorot_uniform",
                 bias_initializer="zeros",
                 kernel_regularizer=None,
                 name: str = ""):
        super().__init__(name)
        self.kernel_initializer = _init_attr(kernel_initializer)
        self.bias_initializer = _init_attr(bias_initializer)
        from . import regularizers as _reg

        self.kernel_regularizer = _reg.resolve(kernel_regularizer)
        self.filters = filters
        self.kernel = (kernel_size,) * 2 if isinstance(kernel_size, int) else tuple(kernel_size)
        self.strides = (strides,) * 2 if isinstance(strides, int) else tuple(strides)
        self.padding = padding
        self.activation = activation
        self.use_bias = use_bias
        self.groups = groups

    def _pads(self):
        if self.padding == "same":
            if self.kernel[0] % 2 == 0 or self.kernel[1] % 2 == 0:
                raise NotImplementedError(
                    "padding='same' with even kernels needs asymmetric "
                    "padding (TF semantics); use odd kernels or 'valid'"
                )
            return self.kernel[0] // 2, self.kernel[1] // 2
        return 0, 0

    def output_shape(self, s):
        (b, c, h, w) = s[0]
        ph, pw = self._pads()
        oh = (h + 2 * ph - self.kernel[0]) // self.strides[0] + 1
        ow = (w + 2 * pw - self.kernel[1]) // self.strides[1] + 1
        return (b, self.filters, oh, ow)

    def emit(self, ff, inputs):
        ph, pw = self._pads()
        return ff.conv2d(inputs[0], self.filters, self.kernel[0], self.kernel[1],
                         self.strides[0], self.strides[1], ph, pw,
                         activation=self.activation, groups=self.groups,
                         use_bias=self.use_bias,
                         kernel_initializer=self.kernel_initializer,
                         bias_initializer=self.bias_initializer,
                         kernel_regularizer=self.kernel_regularizer,
                         name=self.name)


class _Pool2D(Layer):
    kind = "max"

    def __init__(self, pool_size=(2, 2), strides=None, padding="valid",
                 name: str = ""):
        super().__init__(name)
        self.pool = (pool_size,) * 2 if isinstance(pool_size, int) else tuple(pool_size)
        strides = strides if strides is not None else self.pool
        self.strides = (strides,) * 2 if isinstance(strides, int) else tuple(strides)
        self.padding = padding

    def _pads(self):
        if self.padding != "same":
            return 0, 0
        if self.pool[0] % 2 == 0 or self.pool[1] % 2 == 0:
            raise NotImplementedError(
                "padding='same' with even pool sizes needs asymmetric "
                "padding (TF semantics); use odd sizes or 'valid'"
            )
        return self.pool[0] // 2, self.pool[1] // 2

    def output_shape(self, s):
        (b, c, h, w) = s[0]
        ph, pw = self._pads()
        oh = (h + 2 * ph - self.pool[0]) // self.strides[0] + 1
        ow = (w + 2 * pw - self.pool[1]) // self.strides[1] + 1
        return (b, c, oh, ow)

    def emit(self, ff, inputs):
        ph, pw = self._pads()
        return ff.pool2d(inputs[0], self.pool[0], self.pool[1],
                         self.strides[0], self.strides[1], ph, pw,
                         pool_type=self.kind, name=self.name)


class MaxPooling2D(_Pool2D):
    kind = "max"


class AveragePooling2D(_Pool2D):
    kind = "avg"


class Flatten(Layer):
    def output_shape(self, s):
        b = s[0][0]
        n = 1
        for d in s[0][1:]:
            n *= d
        return (b, n)

    def emit(self, ff, inputs):
        return ff.flat(inputs[0], name=self.name)


class Dropout(Layer):
    def __init__(self, rate: float, name: str = ""):
        super().__init__(name)
        self.rate = rate

    def emit(self, ff, inputs):
        return ff.dropout(inputs[0], rate=self.rate, name=self.name)


class Activation(Layer):
    def __init__(self, activation: str, name: str = ""):
        super().__init__(name)
        self.activation = activation

    def emit(self, ff, inputs):
        if self.activation == "softmax":
            return ff.softmax(inputs[0], name=self.name)
        return getattr(ff, self.activation)(inputs[0], name=self.name)


class Embedding(Layer):
    def __init__(self, input_dim: int, output_dim: int, name: str = ""):
        super().__init__(name)
        self.input_dim, self.output_dim = input_dim, output_dim

    def output_shape(self, s):
        return s[0] + (self.output_dim,)

    def emit(self, ff, inputs):
        return ff.embedding(inputs[0], self.input_dim, self.output_dim,
                            name=self.name)


class Concatenate(Layer):
    n_inputs = None

    def __init__(self, axis: int = -1, name: str = ""):
        super().__init__(name)
        self.axis = axis

    def output_shape(self, s):
        ax = self.axis if self.axis >= 0 else len(s[0]) + self.axis
        out = list(s[0])
        out[ax] = sum(shape[ax] for shape in s)
        return tuple(out)

    def emit(self, ff, inputs):
        return ff.concat(list(inputs), axis=self.axis, name=self.name)


class Add(Layer):
    n_inputs = None

    def emit(self, ff, inputs):
        out = inputs[0]
        for t in inputs[1:]:
            out = ff.add(out, t, name=self.name)
        return out


class BatchNormalization(Layer):
    def __init__(self, name: str = ""):
        super().__init__(name)

    def emit(self, ff, inputs):
        return ff.batch_norm(inputs[0], relu=False, name=self.name)


class LayerNormalization(Layer):
    def __init__(self, epsilon: float = 1e-5, name: str = ""):
        super().__init__(name)
        self.epsilon = epsilon

    def emit(self, ff, inputs):
        return ff.layer_norm(inputs[0], eps=self.epsilon, name=self.name)
