"""Keras-style frontend.

Compact functional equivalent of the reference's tf.keras clone
(reference ``python/flexflow/keras/``: Sequential/Functional ``Model``,
layer classes, optimizers, datasets glue — ~35 files). Layers are thin
config records; ``Model``/``Sequential`` lower the symbolic layer graph
onto :class:`flexflow_tpu.FFModel` builder calls, and ``compile/fit/
evaluate/predict`` delegate to the FFModel training loop, so every
Keras-built net inherits the mesh/sharding machinery for free.
"""
from .layers import (
    Activation,
    Add,
    AveragePooling2D,
    BatchNormalization,
    Concatenate,
    Conv2D,
    Dense,
    Dropout,
    Embedding,
    Flatten,
    Input,
    LayerNormalization,
    MaxPooling2D,
)
from .models import Model, Sequential
from .optimizers import SGD, Adam
from . import callbacks, datasets, preprocessing, regularizers  # noqa: F401

__all__ = [
    "Input", "Dense", "Conv2D", "MaxPooling2D", "AveragePooling2D",
    "Flatten", "Dropout", "Activation", "Embedding", "Concatenate", "Add",
    "BatchNormalization", "LayerNormalization",
    "Model", "Sequential", "SGD", "Adam",
    "callbacks", "datasets", "preprocessing",
]
