"""Weight-only int8/int4 quantization (+ host offload placement).

TPU-native counterpart of the reference's quantized serving path
(reference ``src/ops/kernels/decompress_kernels.cu`` int4/int8
dequantize kernels, ``inference/file_loader.cc:651,710`` quantized
weight loading, and the ``--4bit/8bit-quantization`` flags,
``include/flexflow/config.h:155-157``). Design differences for TPU:

* Weights quantize **per output channel** with a symmetric scale
  (q = round(w/s), s = max|w| / qmax over the input dim), stored as a
  ``{"q", "scale"}`` pytree node in place of the dense array. The model
  matmul helpers dequantize inline; XLA fuses the convert+multiply into
  the dot-operand read, so the bf16 weight never round-trips HBM — the
  compiled analog of the reference's decompress-into-shared-memory
  kernels.
* int4 packs two values per byte along the input dim (low nibble =
  even rows), biased to [0, 15] around 8.
* Offload: instead of the reference's zero-copy-memory double
  buffering, quantized/bf16 params can be *placed* in ``pinned_host``
  memory (``NamedSharding.with_memory_kind``); XLA streams them over
  PCIe per step. See ``serve/llm.py``.

Quantized leaves keep the dense weight's PartitionSpec for ``q`` (the
packed dim halves but stays divisible by any power-of-two mesh axis);
``scale`` drops the contracted dim's axis.
"""
from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P


INT8_MAX = 127.0
INT4_MAX = 7.0


def is_quantized(w: Any) -> bool:
    return isinstance(w, dict) and "q" in w and "scale" in w


def quantize_tensor(w: jnp.ndarray, bits: int) -> Dict[str, jnp.ndarray]:
    """Quantize a (..., in, out) weight per output channel over the
    input dim. Returns {"q", "scale"} (+ packed int4 layout)."""
    assert bits in (4, 8), bits
    wf = jnp.asarray(w, jnp.float32)
    qmax = INT8_MAX if bits == 8 else INT4_MAX
    scale = jnp.max(jnp.abs(wf), axis=-2, keepdims=True) / qmax  # (...,1,out)
    scale = jnp.maximum(scale, 1e-12)
    q = jnp.round(wf / scale)
    if bits == 8:
        q = jnp.clip(q, -INT8_MAX, INT8_MAX).astype(jnp.int8)
    else:
        assert w.shape[-2] % 2 == 0, (
            f"int4 packing needs an even input dim, got {w.shape}"
        )
        qb = (jnp.clip(q, -INT4_MAX, INT4_MAX) + 8).astype(jnp.uint8)
        lo = qb[..., 0::2, :]
        hi = qb[..., 1::2, :]
        q = (lo | (hi << 4)).astype(jnp.uint8)  # (..., in//2, out)
    return {"q": q, "scale": scale.astype(jnp.float32)}


def dequantize(qw: Dict[str, jnp.ndarray], dtype) -> jnp.ndarray:
    """{"q","scale"} → dense (..., in, out) weight in ``dtype``. The bit
    width is carried by the storage dtype: int8 = 8-bit, uint8 = packed
    4-bit nibbles."""
    q, scale = qw["q"], qw["scale"]
    if q.dtype == jnp.int8:
        deq = q.astype(jnp.float32)
    else:
        lo = (q & 0xF).astype(jnp.int32) - 8
        hi = ((q >> 4) & 0xF).astype(jnp.int32) - 8
        # Re-interleave even/odd input rows: (..., in//2, 2, out)
        deq = jnp.stack([lo, hi], axis=-2).reshape(
            *q.shape[:-2], q.shape[-2] * 2, q.shape[-1]
        ).astype(jnp.float32)
    return (deq * scale).astype(dtype)


def _leaf_names(layers: Dict[str, Any]):
    """Names of quantizable stacked-layer weights: 3-D matmul kernels
    (wq/wk/wv/wo/w1..w3/w_up/w_down/w_gate) and 4-D expert-stacked MoE
    kernels (L, E, in, out) — norms/biases stay dense, matching the
    reference which quantizes Linear weights only. The MoE ROUTER stays
    dense too: it is a tiny (L, D, E) matmul whose rounding would
    perturb top-k expert selection — the worst accuracy/byte trade in
    the model."""
    return [
        k for k, v in layers.items()
        if k.startswith("w") and hasattr(v, "ndim") and v.ndim in (3, 4)
        and k != "w_router"
    ]


def quantize_params(params: Dict[str, Any], bits: int) -> Dict[str, Any]:
    """Quantize a model-family param pytree's layer matmul weights."""
    out = dict(params)
    layers = dict(params["layers"])
    for name in _leaf_names(layers):
        layers[name] = quantize_tensor(layers[name], bits)
    out["layers"] = layers
    return out


def quantize_pspecs(
    pspecs: Dict[str, Any], params: Dict[str, Any]
) -> Dict[str, Any]:
    """Transform a param PartitionSpec tree to match quantized params:
    ``q`` keeps the dense spec; ``scale`` (size-1 contracted dim) drops
    that dim's axis."""
    out = dict(pspecs)
    layer_specs = dict(pspecs["layers"])
    for name in _leaf_names_from_quantized(params["layers"]):
        spec = layer_specs[name]
        ndim = params["layers"][name]["q"].ndim
        parts = list(spec) + [None] * (ndim - len(spec))
        # scale has size 1 on the contracted (second-to-last) dim —
        # drop that dim's axis, keep the rest (works for 3-D dense and
        # 4-D expert-stacked kernels alike)
        scale_spec = P(*parts[:-2], None, parts[-1])
        layer_specs[name] = {"q": spec, "scale": scale_spec}
    out["layers"] = layer_specs
    return out


def _leaf_names_from_quantized(layers: Dict[str, Any]):
    return [k for k, v in layers.items() if is_quantized(v)]


def quantized_nbytes(params: Dict[str, Any]) -> int:
    """Total bytes of the param pytree (for footprint assertions)."""
    return sum(
        x.nbytes for x in jax.tree.leaves(params) if hasattr(x, "nbytes")
    )
