"""ffcheck AST lint core — trace-context discovery + rule driver.

Static analysis over the package for JAX-on-TPU hazards that no runtime
test catches until they cost a 100x slowdown in production: host↔device
syncs inside jit-traced code, Python control flow on tracer values,
weak-dtype ``jnp.asarray`` at jit-call boundaries, unordered-container
iteration in trace code, cache buffers threaded through ``jax.jit``
without donation, and unhashable static arguments.

The analyzer is file-local and heuristic by design: it never imports
the code under analysis (safe on broken trees, no device needed) and it
prefers precision over recall — a rule that cries wolf gets suppressed
into uselessness. Rules live one-per-file in ``analysis/rules/`` and
register by exposing a module-level ``RULE`` object; see
``analysis/__init__.py`` for the catalog.

Trace-context discovery
-----------------------
A function is considered **traced** (its body runs under ``jax.jit``
tracing, so host-sync and Python-control-flow hazards apply) when any
of these hold:

* decorated with ``jax.jit``/``pjit`` (bare, called, or via
  ``functools.partial(jax.jit, ...)``) or a tracing transform
  (``vmap``/``grad``/``checkpoint``/...);
* passed by name to ``jax.jit``/``pjit``/``jax.lax.scan``/``cond``/
  ``while_loop``/``vmap``/... anywhere in the same file (including the
  engine's ``self._jit`` sanitizer chokepoint);
* a module-level function whose name matches the serving-protocol trace
  roots (``serve_step*``, ``commit_kv*``, ``forward``, ... — the model
  hooks the InferenceEngine jits from another file);
* defined inside, or called (by simple name, intra-file) from, a traced
  function — computed to a fixpoint.

Suppressions
------------
``# ffcheck: disable=RULE[,RULE...] [-- reason]`` on the offending line
(or alone on the line above it) suppresses by rule code (``FF101``) or
slug (``host-sync``); ``all`` suppresses every rule.
``# ffcheck: disable-file=RULE`` anywhere in a file suppresses the rule
for the whole file. Give a reason after ``--``; the repo guard
(tests/test_ffcheck.py) keeps the suppression inventory reviewable.
"""
from __future__ import annotations

import ast
import dataclasses
import io
import os
import re
import tokenize
from typing import Dict, Iterable, Iterator, List, Optional, Sequence, Set, Tuple

# ---------------------------------------------------------------------------
# findings

FuncDef = (ast.FunctionDef, ast.AsyncFunctionDef)


@dataclasses.dataclass(frozen=True)
class Finding:
    """One lint hit: ``path:line:col: CODE [slug] message``."""

    path: str
    line: int
    col: int
    rule: str      # rule code, e.g. "FF101"
    slug: str      # human slug, e.g. "host-sync"
    message: str

    def format(self) -> str:
        return (
            f"{self.path}:{self.line}:{self.col}: "
            f"{self.rule} [{self.slug}] {self.message}"
        )


class Rule:
    """Base class for lint rules (one module per rule in
    ``analysis/rules/``; expose an instance as ``RULE``)."""

    code: str = "FF000"
    slug: str = "abstract"
    doc: str = ""

    def check(self, ctx: "FileContext") -> Iterator[Finding]:
        raise NotImplementedError

    def finding(self, ctx: "FileContext", node: ast.AST, message: str) -> Finding:
        return Finding(
            path=ctx.path,
            line=getattr(node, "lineno", 0),
            col=getattr(node, "col_offset", 0),
            rule=self.code,
            slug=self.slug,
            message=message,
        )


# ---------------------------------------------------------------------------
# suppression comments

_SUPPRESS_RE = re.compile(
    r"#\s*ffcheck:\s*(disable(?:-file)?)\s*=\s*"
    r"([A-Za-z0-9_\-, ]+?)\s*(?:--\s*(?P<reason>.*))?$"
)


def parse_suppressions(source: str) -> Tuple[Dict[int, Set[str]], Set[str]]:
    """Returns (line -> suppressed rule tokens, file-level tokens).

    A suppression comment alone on its line also guards the next line
    (the common "comment above the offending statement" layout)."""
    line_rules: Dict[int, Set[str]] = {}
    file_rules: Set[str] = set()
    try:
        tokens = list(tokenize.generate_tokens(io.StringIO(source).readline))
    except (tokenize.TokenError, SyntaxError, IndentationError):
        return line_rules, file_rules
    for tok in tokens:
        if tok.type != tokenize.COMMENT:
            continue
        m = _SUPPRESS_RE.search(tok.string)
        if not m:
            continue
        kind = m.group(1)
        rules = {r.strip() for r in m.group(2).split(",") if r.strip()}
        if kind == "disable-file":
            file_rules |= rules
            continue
        line_rules.setdefault(tok.start[0], set()).update(rules)
        if not tok.line[: tok.start[1]].strip():
            # standalone comment: guard the following statement line
            line_rules.setdefault(tok.start[0] + 1, set()).update(rules)
    return line_rules, file_rules


def _is_suppressed(
    f: Finding, line_rules: Dict[int, Set[str]], file_rules: Set[str]
) -> bool:
    keys = {f.rule, f.slug, "all"}
    if keys & file_rules:
        return True
    return bool(keys & line_rules.get(f.line, set()))


# ---------------------------------------------------------------------------
# trace-context analysis

# Dotted paths that create a jit-compiled callable from their first arg.
JIT_PATHS = {
    "jax.jit",
    "jax.pjit",
    "jax.experimental.pjit.pjit",
}
# Method names treated as jit wrappers regardless of the receiver — the
# engine's sanitizer chokepoint (engine._jit) and bare `jit` imports.
JIT_METHOD_NAMES = {"jit", "pjit", "_jit"}

# Transforms whose function arguments are traced.
TRANSFORM_PATHS = {
    "jax.lax.scan",
    "jax.lax.while_loop",
    "jax.lax.fori_loop",
    "jax.lax.cond",
    "jax.lax.switch",
    "jax.lax.map",
    "jax.lax.associative_scan",
    "jax.vmap",
    "jax.pmap",
    "jax.grad",
    "jax.value_and_grad",
    "jax.jacfwd",
    "jax.jacrev",
    "jax.hessian",
    "jax.checkpoint",
    "jax.remat",
    "jax.custom_vjp",
    "jax.custom_jvp",
    "jax.experimental.shard_map.shard_map",
}

# Module-level serving-protocol functions the InferenceEngine jits from
# another file — the cross-file trace roots file-local analysis cannot
# see. Methods (functions taking ``self``) never match these: protocol
# hooks are module-level by convention.
DEFAULT_TRACE_ROOT_PATTERNS = (
    r"^serve_",
    r"^commit_kv",
    r"^reorder_slots",
    r"^copy_page_kv$",
    r"^forward$",
    r"^attention$",
    r"^block$",
    r"^apply_rope$",
    r"^rope_freqs$",
    r"^sample_tokens$",
    r"^log_softmax$",
    r"^next_token_loss$",
)
# Protocol-adjacent functions that are EAGER by design (triage dumps run
# outside jit so they can fetch per-layer activations to host).
TRACE_ROOT_EXCLUDE = {"serve_debug_activations"}


class FileContext:
    """Parsed file + alias resolution + traced-function analysis, handed
    to every rule's ``check``."""

    def __init__(
        self,
        path: str,
        source: str,
        trace_root_patterns: Sequence[str] = DEFAULT_TRACE_ROOT_PATTERNS,
    ):
        self.path = path
        self.source = source
        self.tree = ast.parse(source)
        self._parent: Dict[ast.AST, ast.AST] = {}
        for node in ast.walk(self.tree):
            for child in ast.iter_child_nodes(node):
                self._parent[child] = node
        self.aliases = self._collect_aliases()
        self.functions: List[ast.AST] = [
            n for n in ast.walk(self.tree) if isinstance(n, FuncDef)
        ]
        self._fn_by_name: Dict[str, List[ast.AST]] = {}
        for fn in self.functions:
            self._fn_by_name.setdefault(fn.name, []).append(fn)
        self.jit_calls = self._collect_jit_calls()
        self.traced: Set[ast.AST] = self._find_traced(trace_root_patterns)

    # -- alias / dotted-path resolution ---------------------------------

    def _collect_aliases(self) -> Dict[str, str]:
        aliases: Dict[str, str] = {}
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    aliases[a.asname or a.name.split(".")[0]] = (
                        a.name if a.asname else a.name.split(".")[0]
                    )
            elif isinstance(node, ast.ImportFrom) and node.module:
                for a in node.names:
                    aliases[a.asname or a.name] = f"{node.module}.{a.name}"
        return aliases

    def resolve(self, node: Optional[ast.AST]) -> Optional[str]:
        """Dotted path of a Name/Attribute with import aliases expanded
        (``np.asarray`` -> ``numpy.asarray``), or None."""
        if isinstance(node, ast.Name):
            return self.aliases.get(node.id, node.id)
        if isinstance(node, ast.Attribute):
            base = self.resolve(node.value)
            return f"{base}.{node.attr}" if base else None
        return None

    # -- jit call inventory ----------------------------------------------

    def is_jit_call(self, call: ast.AST) -> bool:
        if not isinstance(call, ast.Call):
            return False
        path = self.resolve(call.func)
        if path in JIT_PATHS:
            return True
        return (
            isinstance(call.func, ast.Attribute)
            and call.func.attr in JIT_METHOD_NAMES
        )

    def is_partial_jit(self, call: ast.AST) -> bool:
        """``functools.partial(jax.jit, ...)`` (decorator form)."""
        if not isinstance(call, ast.Call):
            return False
        return (
            self.resolve(call.func) in ("functools.partial", "partial")
            and bool(call.args)
            and self.resolve(call.args[0]) in JIT_PATHS
        )

    def _collect_jit_calls(self) -> List[dict]:
        """Every jit creation site: plain calls, bare decorators, and
        partial-jit decorators, with the target function def resolved
        when it is a simple local name."""
        out: List[dict] = []
        for node in ast.walk(self.tree):
            if self.is_jit_call(node):
                target = node.args[0] if node.args else None
                out.append(
                    {
                        "call": node,
                        "keywords": {k.arg: k.value for k in node.keywords},
                        "target": target,
                        "target_fn": self.lookup_function(target),
                    }
                )
        for fn in self.functions:
            for dec in fn.decorator_list:
                if self.resolve(dec) in JIT_PATHS:
                    out.append(
                        {"call": dec, "keywords": {}, "target": None,
                         "target_fn": fn}
                    )
                elif self.is_partial_jit(dec) or (
                    isinstance(dec, ast.Call) and self.is_jit_call(dec)
                    and not dec.args
                ):
                    out.append(
                        {
                            "call": dec,
                            "keywords": {k.arg: k.value for k in dec.keywords},
                            "target": None,
                            "target_fn": fn,
                        }
                    )
        return out

    def lookup_function(self, node: Optional[ast.AST]) -> Optional[ast.AST]:
        """A Name argument -> the (single) local def it denotes. None
        when the name is absent OR ambiguous (several same-named defs) —
        precision matters for the rules that inspect the target."""
        cands = self.lookup_all(node)
        return cands[0] if len(cands) == 1 else None

    def lookup_all(self, node: Optional[ast.AST]) -> List[ast.AST]:
        """Every local def a Name argument could denote — the safe
        over-approximation traced-detection wants (a nested ``step``
        defined per branch and jitted under one name)."""
        if isinstance(node, ast.Name):
            return list(self._fn_by_name.get(node.id, []))
        return []

    @staticmethod
    def param_names(fn: ast.AST) -> Set[str]:
        a = fn.args
        names = [p.arg for p in a.posonlyargs + a.args + a.kwonlyargs]
        if a.vararg:
            names.append(a.vararg.arg)
        if a.kwarg:
            names.append(a.kwarg.arg)
        return set(names)

    @staticmethod
    def positional_params(fn: ast.AST) -> List[str]:
        a = fn.args
        return [p.arg for p in a.posonlyargs + a.args]

    # -- traced-function discovery ---------------------------------------

    def _decorated_traced(self, fn: ast.AST) -> bool:
        for dec in fn.decorator_list:
            path = self.resolve(dec)
            if path in JIT_PATHS or path in TRANSFORM_PATHS:
                return True
            if isinstance(dec, ast.Call):
                if self.is_jit_call(dec) or self.is_partial_jit(dec):
                    return True
                if self.resolve(dec.func) in TRANSFORM_PATHS:
                    return True
        return False

    def _find_traced(self, patterns: Sequence[str]) -> Set[ast.AST]:
        traced: Set[ast.AST] = set()
        pats = [re.compile(p) for p in patterns]
        for fn in self.functions:
            if fn.name in TRACE_ROOT_EXCLUDE:
                continue
            if self._decorated_traced(fn):
                traced.add(fn)
                continue
            # protocol roots: module-level functions only (methods take
            # self and are never the cross-file jit targets)
            if (
                isinstance(self._parent.get(fn), ast.Module)
                and "self" not in self.positional_params(fn)[:1]
                and any(p.search(fn.name) for p in pats)
            ):
                traced.add(fn)
        # functions passed by name to jit/transform calls
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Call):
                continue
            is_transform = self.resolve(node.func) in TRANSFORM_PATHS
            if not (is_transform or self.is_jit_call(node)):
                continue
            args = list(node.args) + [k.value for k in node.keywords]
            for arg in args:
                traced.update(self.lookup_all(arg))
        # fixpoint: nested defs + intra-file callees of traced functions
        changed = True
        while changed:
            changed = False
            for fn in self.functions:
                if fn in traced:
                    continue
                anc = self._parent.get(fn)
                while anc is not None:
                    if anc in traced:
                        traced.add(fn)
                        changed = True
                        break
                    anc = self._parent.get(anc)
            for fn in list(traced):
                for node in ast.walk(fn):
                    if not isinstance(node, ast.Call):
                        continue
                    callees = self.lookup_all(node.func)
                    if not callees and self.resolve(node.func) in (
                        "functools.partial", "partial"
                    ) and node.args:
                        callees = self.lookup_all(node.args[0])
                    for callee in callees:
                        if callee not in traced:
                            traced.add(callee)
                            changed = True
        return traced

    def enclosing_function(self, node: ast.AST) -> Optional[ast.AST]:
        anc = self._parent.get(node)
        while anc is not None:
            if isinstance(anc, FuncDef):
                return anc
            anc = self._parent.get(anc)
        return None

    def is_traced(self, node: ast.AST) -> bool:
        """Is this node inside the body of a traced function?"""
        fn = self.enclosing_function(node)
        while fn is not None:
            if fn in self.traced:
                return True
            fn = self.enclosing_function(fn)
        return False

    def enclosing_traced_function(self, node: ast.AST) -> Optional[ast.AST]:
        fn = self.enclosing_function(node)
        while fn is not None:
            if fn in self.traced:
                return fn
            fn = self.enclosing_function(fn)
        return None

    def walk_traced(self, types) -> Iterator[ast.AST]:
        """Every node of the given AST type(s) inside traced code. The
        traced function's own body only — decorators and parameter
        defaults evaluate eagerly and are excluded."""
        seen: Set[int] = set()
        for fn in self.traced:
            for stmt in fn.body:
                for node in ast.walk(stmt):
                    if isinstance(node, types) and id(node) not in seen:
                        seen.add(id(node))
                        yield node


# ---------------------------------------------------------------------------
# driver

def get_rules() -> Tuple[Rule, ...]:
    from .rules import ALL_RULES

    return ALL_RULES


def lint_source(
    source: str,
    path: str = "<string>",
    rules: Optional[Sequence[Rule]] = None,
    trace_root_patterns: Sequence[str] = DEFAULT_TRACE_ROOT_PATTERNS,
    with_suppressed: bool = False,
) -> List[Finding]:
    """Lint one file's source. Returns findings sorted by position,
    suppression comments applied (unless ``with_suppressed``)."""
    rules = tuple(rules) if rules is not None else get_rules()
    try:
        ctx = FileContext(path, source, trace_root_patterns)
    except SyntaxError as e:
        return [
            Finding(path, e.lineno or 0, e.offset or 0, "FF000",
                    "syntax-error", f"file does not parse: {e.msg}")
        ]
    findings: List[Finding] = []
    for rule in rules:
        findings.extend(rule.check(ctx))
    if not with_suppressed:
        line_rules, file_rules = parse_suppressions(source)
        findings = [
            f for f in findings
            if not _is_suppressed(f, line_rules, file_rules)
        ]
    return sorted(findings, key=lambda f: (f.path, f.line, f.col, f.rule))


def iter_py_files(paths: Iterable[str]) -> Iterator[str]:
    for p in paths:
        if os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = [
                    d for d in dirs
                    if d not in ("__pycache__", ".git", ".venv")
                ]
                for f in sorted(files):
                    if f.endswith(".py"):
                        yield os.path.join(root, f)
        elif p.endswith(".py"):
            yield p


def lint_paths(
    paths: Iterable[str],
    rules: Optional[Sequence[Rule]] = None,
    with_suppressed: bool = False,
) -> List[Finding]:
    """Lint every ``.py`` under the given files/directories."""
    findings: List[Finding] = []
    for path in iter_py_files(paths):
        with open(path, "r") as fh:
            src = fh.read()
        findings.extend(
            lint_source(src, path, rules=rules, with_suppressed=with_suppressed)
        )
    return findings
