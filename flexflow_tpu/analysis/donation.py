"""Donation sanitizer — make use-after-donate fail loudly.

Every engine step donates the KV cache (``donate_argnums`` on all of
``engine._steps``/``_commit``): on TPU the output cache aliases the
input buffer, so any host-side reference into the OLD cache pytree now
reads (or scribbles on) memory the new step owns — the exact corruption
class PR-2 fixed in the paged fast-decode path (a released page reused
while an in-flight dispatch still wrote through the old table). On
backends/paths where donation is ignored, the stale reference silently
*works*, which is worse: tests pass, production corrupts.

The sanitizer turns the hazard into a deterministic error: after each
donated call the engine hands the OLD cache pytree to :meth:`poison`,
which

* deletes any leaf buffer jax did not already invalidate (simulating
  the TPU aliasing semantics on backends that ignored donation), and
* swaps every leaf entry of the (mutable) pytree for a
  :class:`DeletedBufferProxy` that raises :class:`UseAfterDonateError`
  — naming the donating step and dispatch ordinal — on ANY access.

Holders of the cache *container* hit the proxy with a descriptive
error; holders of a raw leaf array hit jax's own deleted-buffer error.
Either way the use-after-donate fails at the faulty read in tests,
instead of corrupting pages under load.

Enable via ``ServingConfig(sanitizers=("donation",))`` or
``FF_SANITIZERS=donation``.
"""
from __future__ import annotations

from typing import Any, List, Optional


class UseAfterDonateError(RuntimeError):
    """A buffer that was donated to a jitted step was touched again."""


_RAISING_DUNDERS = (
    "__getitem__", "__setitem__", "__delitem__", "__iter__", "__len__",
    "__contains__", "__array__", "__float__", "__int__", "__bool__",
    "__index__", "__add__", "__radd__", "__sub__", "__rsub__", "__mul__",
    "__rmul__", "__truediv__", "__rtruediv__", "__matmul__", "__rmatmul__",
    "__neg__", "__pos__", "__abs__", "__eq__", "__ne__", "__lt__",
    "__le__", "__gt__", "__ge__", "__call__", "__format__",
)


class DeletedBufferProxy:
    """Poison value swapped in for donated buffers: any use raises
    :class:`UseAfterDonateError` naming the donation site."""

    __slots__ = ("_ffcheck_context",)

    def __init__(self, context: str):
        object.__setattr__(self, "_ffcheck_context", context)

    def _ffcheck_raise(self, op: str):
        raise UseAfterDonateError(
            f"use-after-donate: {op} on a buffer donated to {self._ffcheck_context}. "
            "This reference went stale when the step donated its cache "
            "(donate_argnums) — on TPU the memory now belongs to the new "
            "cache and this access would read/corrupt it. Re-read the "
            "engine's current cache instead of holding the old pytree."
        )

    def __getattr__(self, name):
        object.__getattribute__(self, "_ffcheck_raise")(
            f"attribute access .{name}"
        )

    def __setattr__(self, name, value):
        object.__getattribute__(self, "_ffcheck_raise")(
            f"attribute write .{name}"
        )

    def __repr__(self):  # keep debuggers/logging safe
        return (
            f"<DeletedBufferProxy donated at "
            f"{object.__getattribute__(self, '_ffcheck_context')}>"
        )


def _add_raising_dunders():
    for name in _RAISING_DUNDERS:
        def method(self, *a, _op=name, **k):
            object.__getattribute__(self, "_ffcheck_raise")(f"{_op}()")
        method.__name__ = name
        setattr(DeletedBufferProxy, name, method)


_add_raising_dunders()


class DonationSanitizer:
    """Poisons donated pytrees after each donated dispatch (see module
    docstring). One instance per engine; ``n_poisoned`` counts poisoned
    call sites for telemetry/tests."""

    def __init__(self):
        self.n_poisoned = 0
        self.contexts: List[str] = []

    def poison(self, tree: Any, context: str = "a donated step") -> int:
        """Invalidate every array leaf of ``tree`` and swap leaf entries
        of mutable containers (dict/list) for :class:`DeletedBufferProxy`.
        Returns the number of leaves poisoned. Safe to call on an
        already-poisoned tree (idempotent)."""
        self.n_poisoned += 1
        self.contexts.append(context)
        if len(self.contexts) > 64:  # bounded telemetry
            del self.contexts[:32]
        return self._poison(tree, context)

    def _poison(self, node: Any, context: str) -> int:
        import jax

        n = 0
        if isinstance(node, dict):
            for k, v in list(node.items()):
                if isinstance(v, (dict, list)):
                    n += self._poison(v, context)
                else:
                    n += self._poison_leaf(v)
                    node[k] = DeletedBufferProxy(
                        f"{context} (cache leaf {k!r})"
                    )
        elif isinstance(node, list):
            for i, v in enumerate(list(node)):
                if isinstance(v, (dict, list)):
                    n += self._poison(v, context)
                else:
                    n += self._poison_leaf(v)
                    node[i] = DeletedBufferProxy(
                        f"{context} (cache leaf [{i}])"
                    )
        else:
            # immutable container (tuple) or a bare leaf: can't swap in
            # a proxy, but deleting the buffers still trips jax's own
            # deleted-array error on use
            for leaf in jax.tree.leaves(node):
                n += self._poison_leaf(leaf)
        return n

    @staticmethod
    def _poison_leaf(leaf: Any) -> int:
        import jax

        if isinstance(leaf, DeletedBufferProxy):
            return 0
        if isinstance(leaf, jax.Array):
            try:
                if not leaf.is_deleted():
                    leaf.delete()
            except RuntimeError:
                pass
            return 1
        return 0
