"""Retrace sentinel — compile-event accounting for the serving engine.

The InferenceEngine's whole performance contract is "one compiled
program per static step signature, replayed forever" (engine._get_step
and friends). The silent killer is the *retrace*: a host-side change —
a weak dtype flipping, a page-table shape drifting, an int that used to
be an np.int32 arriving as a Python int — gives an existing step key a
NEW abstract signature, and XLA quietly recompiles. On CPU tests that
costs milliseconds and nobody notices; on a TPU pod it is a 100x
step-latency spike in production.

:class:`RetraceGuard` hooks the engine's jit chokepoint
(``InferenceEngine._jit`` — every entry in ``engine._steps`` plus
``_commit``/``copy_page``/``reorder`` is created through it): the
function handed to ``jax.jit`` is wrapped so that each *trace* (which
is exactly one compile) records a :class:`CompileEvent` with the step
key, the abstract ``(shape, dtype, weak_type)`` signature of every
argument, and the cumulative per-key count. In strict mode a second
compile for the same key raises :class:`RetraceError` at the dispatch
that caused it — the shape/dtype-drift bug class fails in tests instead
of shipping. ``seal()`` additionally forbids compiles of *new* keys
(full steady-state assertion for benches).

Enable via ``ServingConfig(sanitizers=("retrace",))`` (strict) or
``("retrace-warn",)`` (record + log only), or ``FF_SANITIZERS=retrace``
in the environment. Compile events are logged at
``FF_LOG=serve=debug`` and mirrored into ``SchedulerStats.compiles``/
``retraces`` when a RequestManager drives the engine.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..logging_utils import get_logger


class RetraceError(RuntimeError):
    """A jitted step recompiled (or, sealed, compiled anew) after it was
    supposed to be steady-state."""


def abstract_signature(args: tuple, kwargs: dict) -> str:
    """Stable string of the abstract (shape/dtype/weak_type) signature
    of a call — the part of jax's cache key that retraces key on.
    Works on tracers (during trace) and concrete arrays alike."""
    import jax

    leaves, treedef = jax.tree.flatten((args, kwargs))
    parts: List[str] = []
    for leaf in leaves:
        aval = getattr(leaf, "aval", None)
        if aval is not None:
            # ShapedArray repr includes weak_type when set
            parts.append(repr(aval))
        elif hasattr(leaf, "shape") and hasattr(leaf, "dtype"):
            parts.append(
                f"{leaf.dtype}[{','.join(str(d) for d in leaf.shape)}]"
            )
        else:
            parts.append(f"{type(leaf).__name__}:{leaf!r}")
    return f"{treedef} :: " + ", ".join(parts)


@dataclasses.dataclass(frozen=True)
class CompileEvent:
    """One lowering/compile of one step program."""

    key: Any           # engine step key, e.g. (1, False, False)
    signature: str     # abstract signature of the traced call
    count: int         # cumulative compiles for this key (1 = first)
    seq: int           # global compile ordinal across all keys


class RetraceGuard:
    """Records every compile of every instrumented step program; in
    strict mode a recompile raises at the offending dispatch."""

    def __init__(self, strict: bool = True,
                 stats_cb: Optional[Callable[[], Any]] = None):
        self.strict = strict
        self.compiles: Dict[Any, List[str]] = {}
        self.events: List[CompileEvent] = []
        self.retraces = 0
        self._sealed = False
        # () -> SchedulerStats; wired by the RequestManager so compile
        # events surface in the serving telemetry (bench + FF_LOG)
        self.stats_cb = stats_cb
        self._log = get_logger("serve")

    # -- engine integration ------------------------------------------------

    def instrument(self, fn: Callable, key: Any) -> Callable:
        """Wrap a to-be-jitted function so each trace (= compile) is
        recorded under ``key`` before tracing proceeds. The wrapper
        preserves positional arguments, so ``donate_argnums`` indices
        are unchanged."""

        def traced(*args, **kwargs):
            self.record(key, args, kwargs)
            return fn(*args, **kwargs)

        return traced

    def record(self, key: Any, args: tuple = (), kwargs: Optional[dict] = None):
        sig = abstract_signature(args, kwargs or {})
        prev = self.compiles.setdefault(key, [])
        is_retrace = bool(prev)
        prev.append(sig)
        event = CompileEvent(
            key=key, signature=sig, count=len(prev), seq=len(self.events)
        )
        self.events.append(event)
        stats = self.stats_cb() if self.stats_cb is not None else None
        if stats is not None:
            stats.compiles += 1
            if is_retrace:
                stats.retraces += 1
        self._log.debug(
            "compile key=%r count=%d sig=%s", key, event.count, sig
        )
        if is_retrace:
            self.retraces += 1
            if self.strict or self._sealed:
                raise RetraceError(
                    f"step {key!r} RECOMPILED (compile #{len(prev)}): the "
                    f"abstract signature drifted.\n  first:  {prev[0]}\n"
                    f"  now:    {sig}\n"
                    "A host-side shape/dtype/weak-type changed between "
                    "dispatches of the same step key — on TPU this is a "
                    "silent 100x step-latency spike."
                )
        elif self._sealed:
            # the trace aborts here — un-record it so an unseal()+retry
            # is a first compile, not a phantom recompile
            prev.pop()
            if not prev:
                self.compiles.pop(key, None)
            self.events.pop()
            if stats is not None:
                stats.compiles -= 1
            raise RetraceError(
                f"NEW step key {key!r} compiled after seal(): sig={sig}. "
                "Steady state was declared (seal()) but this dispatch "
                "still needed a fresh program."
            )

    # -- assertions / reporting -------------------------------------------

    def seal(self):
        """Declare steady state: any further compile — same key or new —
        raises. Call after warmup in benches."""
        self._sealed = True

    def unseal(self):
        self._sealed = False

    def reset(self):
        """Forget all recorded compiles (e.g. after an engine.reset())."""
        self.compiles.clear()
        self.events.clear()
        self.retraces = 0
        self._sealed = False

    @property
    def total_compiles(self) -> int:
        return len(self.events)

    def compile_counts(self) -> Dict[Any, int]:
        """{step key: number of compiles}. Steady-state healthy = every
        value is exactly 1."""
        return {k: len(v) for k, v in self.compiles.items()}

    def assert_one_compile_per_key(self):
        """The churn-test invariant: every step key compiled exactly
        once over the guarded run."""
        bad = {k: n for k, n in self.compile_counts().items() if n != 1}
        if bad:
            raise RetraceError(
                f"step keys recompiled (key -> compiles): {bad}; "
                f"signatures: "
                + "; ".join(
                    f"{k!r}: {self.compiles[k]}" for k in bad
                )
            )

    def report(self) -> str:
        counts = self.compile_counts()
        return (
            f"[retrace-guard] {self.total_compiles} compiles over "
            f"{len(counts)} step keys, {self.retraces} retraces"
        )
