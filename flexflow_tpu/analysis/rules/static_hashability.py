"""FF106 static-hashability: unhashable static jit arguments.

``static_argnums``/``static_argnames`` values become part of the jit
cache key, so they must be hashable AND cheaply equality-comparable. A
list/dict/set default (or annotation) on a static parameter either
raises ``ValueError: non-hashable static arguments`` at the first call
— or, when callers pass tuples sometimes and lists other times, keys a
fresh compile per call. Statics should be scalars, strings, or tuples.
"""
from __future__ import annotations

import ast
from typing import Iterator, List, Optional, Tuple

from ..lint import FileContext, Finding, Rule

UNHASHABLE_ANNOTATIONS = {"list", "dict", "set", "List", "Dict", "Set",
                          "bytearray"}


def _static_params(fn, keywords) -> List[Tuple[str, Optional[ast.AST], Optional[ast.AST]]]:
    """(name, default, annotation) for each static parameter we can
    resolve from static_argnums/static_argnames literals."""
    pos = fn.args.posonlyargs + fn.args.args
    names = [p.arg for p in pos]
    # defaults align to the TAIL of the positional list
    defaults: dict = {}
    for p, d in zip(pos[len(pos) - len(fn.args.defaults):], fn.args.defaults):
        defaults[p.arg] = d
    for p, d in zip(fn.args.kwonlyargs, fn.args.kw_defaults):
        if d is not None:
            defaults[p.arg] = d
    annotations = {p.arg: p.annotation for p in pos + fn.args.kwonlyargs}
    picked: List[str] = []
    argnums = keywords.get("static_argnums")
    if argnums is not None:
        nums = []
        if isinstance(argnums, ast.Constant) and isinstance(argnums.value, int):
            nums = [argnums.value]
        elif isinstance(argnums, (ast.Tuple, ast.List)):
            nums = [
                e.value for e in argnums.elts
                if isinstance(e, ast.Constant) and isinstance(e.value, int)
            ]
        picked += [names[n] for n in nums if 0 <= n < len(names)]
    argnames = keywords.get("static_argnames")
    if argnames is not None:
        if isinstance(argnames, ast.Constant) and isinstance(argnames.value, str):
            picked.append(argnames.value)
        elif isinstance(argnames, (ast.Tuple, ast.List)):
            picked += [
                e.value for e in argnames.elts
                if isinstance(e, ast.Constant) and isinstance(e.value, str)
            ]
    return [
        (n, defaults.get(n), annotations.get(n))
        for n in picked
        if n in set(names) | {p.arg for p in fn.args.kwonlyargs}
    ]


def _unhashable_expr(node: Optional[ast.AST]) -> Optional[str]:
    if node is None:
        return None
    if isinstance(node, (ast.List, ast.ListComp)):
        return "list"
    if isinstance(node, (ast.Dict, ast.DictComp)):
        return "dict"
    if isinstance(node, (ast.Set, ast.SetComp)):
        return "set"
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Name):
        if node.func.id in ("list", "dict", "set", "bytearray"):
            return node.func.id
    return None


def _unhashable_annotation(node: Optional[ast.AST]) -> Optional[str]:
    if node is None:
        return None
    base = node.value if isinstance(node, ast.Subscript) else node
    if isinstance(base, ast.Name) and base.id in UNHASHABLE_ANNOTATIONS:
        return base.id
    if isinstance(base, ast.Attribute) and base.attr in UNHASHABLE_ANNOTATIONS:
        return base.attr
    return None


class StaticHashabilityRule(Rule):
    code = "FF106"
    slug = "static-hashability"
    doc = (
        "static_argnums/static_argnames parameter whose default or "
        "annotation is unhashable (list/dict/set) — jit raises, or "
        "retraces per call"
    )

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for jc in ctx.jit_calls:
            fn = jc["target_fn"]
            if fn is None:
                continue
            kw = jc["keywords"]
            if not ({"static_argnums", "static_argnames"} & set(kw)):
                continue
            for name, default, annotation in _static_params(fn, kw):
                bad = _unhashable_expr(default)
                if bad:
                    yield self.finding(
                        ctx, jc["call"],
                        f"static argument {name!r} of {fn.name}() has an "
                        f"unhashable {bad} default — jit will raise "
                        "(or, with mixed caller types, retrace per call)",
                    )
                    continue
                bad = _unhashable_annotation(annotation)
                if bad:
                    yield self.finding(
                        ctx, jc["call"],
                        f"static argument {name!r} of {fn.name}() is "
                        f"annotated {bad} — statics must be hashable "
                        "(use a tuple)",
                    )


RULE = StaticHashabilityRule()
