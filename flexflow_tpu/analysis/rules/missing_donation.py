"""FF105 missing-donation: a cache/state buffer threaded through
``jax.jit`` without ``donate_argnums``.

The serving KV cache (and a training step's optimizer state) flows
in-and-out of every step. Without donation XLA must preserve the input
buffer while producing the output — steady-state decode then allocates
a full cache copy per step, doubling KV HBM and capping concurrency at
half the budget. Every engine program donates its cache
(engine._jit(... donate_argnums=...)); this rule keeps new jit sites
honest.
"""
from __future__ import annotations

import ast
import re
from typing import Iterator

from ..lint import FileContext, Finding, Rule

# Parameter names that, by repo convention, are device buffers updated
# in place per step — the donate-or-copy-per-step set.
DONATABLE_PARAMS = {"cache", "kv_cache", "opt_state"}
# Attribute targets (model hooks) that thread the cache by contract.
CACHE_HOOK_RE = re.compile(
    r"^(commit_kv(_paged)?|reorder_slots(_paged)?|copy_page_kv|"
    r"init_kv_cache|serve_step(_paged)?)$"
)
DONATE_KWARGS = {"donate_argnums", "donate_argnames"}


class MissingDonationRule(Rule):
    code = "FF105"
    slug = "missing-donation"
    doc = (
        "jax.jit of a function threading a cache/opt_state buffer "
        "without donate_argnums — a full buffer copy per step"
    )

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for jc in ctx.jit_calls:
            if DONATE_KWARGS & set(jc["keywords"]):
                continue
            fn = jc["target_fn"]
            if fn is not None:
                hot = sorted(
                    set(ctx.positional_params(fn)) & DONATABLE_PARAMS
                )
                if hot:
                    yield self.finding(
                        ctx, jc["call"],
                        f"jit of {fn.name}() threads buffer parameter(s) "
                        f"{', '.join(hot)} without donate_argnums — "
                        "steady state allocates a full copy per step",
                    )
                continue
            target = jc["target"]
            if (
                isinstance(target, ast.Attribute)
                and CACHE_HOOK_RE.match(target.attr)
                and not target.attr.startswith("init_")
            ):
                yield self.finding(
                    ctx, jc["call"],
                    f"jit of cache-threading hook .{target.attr} without "
                    "donate_argnums — steady state allocates a full "
                    "cache copy per step",
                )


RULE = MissingDonationRule()
