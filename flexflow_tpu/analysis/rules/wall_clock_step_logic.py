"""FF109 wall-clock-in-step-logic: wall-clock reads/sleeps inside the
step-clock-contracted cluster control plane.

The determinism contract (PR 9, re-affirmed by PR 18): health
transitions, heartbeat gaps, autoscaler cooldowns/streaks/windows and
journal replay are all counted in CLUSTER STEPS, never wall clock —
that is what makes failover, chaos and autoscale runs bitwise
reproducible under a seed. Wall time enters the control plane exactly
once, at the measurement edge (``TrafficEstimator.profile(
step_time_s=...)`` is handed a duration; it never reads a clock).

This rule machine-checks the contract over the contracted file set
(``serve/cluster/{health,journal,manager,remote,transport}.py`` and
``serve/autotune/{policy,workload}.py``): any call to ``time.time``,
``time.monotonic`` (plus their ``_ns`` variants), ``time.sleep`` or an
argless ``datetime.now()`` is a finding. ``time.perf_counter`` is
explicitly ALLOWED — it only ever feeds measurement outputs (latency
EMAs, RTT percentiles, profile stamps), never a control decision, and
banning it would just push timing telemetry out of the files the rule
can see.

The two legitimate wall-clock sites carry reasoned suppressions: the
socket retry backoff (``remote.py`` — real links recover with time;
outputs are unaffected because the loopback transport never backs
off) and the loopback worker's injected link delay (``transport.py`` —
the delay IS the simulated wire latency the chaos tests script).

Suppress with ``# ffcheck: disable=FF109 -- reason``.
"""
from __future__ import annotations

import ast
from typing import Iterator

from ..lint import FileContext, Finding, Rule

#: the step-clock-contracted file set (path suffixes, "/"-normalized)
CONTRACT_SUFFIXES = (
    "serve/cluster/health.py",
    "serve/cluster/journal.py",
    "serve/cluster/manager.py",
    "serve/cluster/remote.py",
    "serve/cluster/transport.py",
    "serve/autotune/policy.py",
    "serve/autotune/workload.py",
)

#: wall-clock calls banned anywhere in a contracted file
WALL_CLOCK_PATHS = {
    "time.time",
    "time.time_ns",
    "time.monotonic",
    "time.monotonic_ns",
    "time.sleep",
}
#: argless ``datetime.now()`` / ``datetime.datetime.now()`` — the
#: naive-local-time read; a tz-carrying call is assumed to be
#: formatting an externally supplied stamp and left to review
DATETIME_NOW_PATHS = {"datetime.now", "datetime.datetime.now"}


def in_contract_set(path: str) -> bool:
    norm = path.replace("\\", "/")
    return any(norm.endswith(sfx) for sfx in CONTRACT_SUFFIXES)


class WallClockStepLogicRule(Rule):
    code = "FF109"
    slug = "wall-clock-in-step-logic"
    doc = (
        "time.time/time.monotonic/time.sleep/datetime.now inside the "
        "step-clock-contracted cluster control plane (health, "
        "autoscaler, journal, manager/remote/transport step logic) — "
        "transitions and cooldowns count cluster steps, never wall "
        "clock; time.perf_counter (measurement-only) is allowed"
    )

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        if not in_contract_set(ctx.path):
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            resolved = ctx.resolve(node.func)
            if resolved in WALL_CLOCK_PATHS:
                yield self.finding(
                    ctx, node,
                    f"{resolved}() in step-clock-contracted code — "
                    "health/autoscale/journal logic counts cluster "
                    "steps, never wall clock (use the step counter, or "
                    "time.perf_counter for measurement-only stamps)",
                )
            elif resolved in DATETIME_NOW_PATHS and not node.args:
                yield self.finding(
                    ctx, node,
                    "datetime.now() in step-clock-contracted code — "
                    "wall-clock timestamps break the deterministic "
                    "replay contract; derive times from the step clock "
                    "or stamp at the measurement edge",
                )


RULE = WallClockStepLogicRule()
