"""FF108 tracer-sync: observability calls that force a device sync on
the serving hot path.

The tracing layer (flexflow_tpu/obs) is only free because every event
records HOST-side primitives the scheduler already holds. The failure
mode this rule guards against is an attribute like
``tracer.event("decode", logit=float(logits[0]))`` or
``tr.event("step", tok=toks.item())`` inside a span-annotated hot
loop: the innocent-looking telemetry argument is a host read of an
un-flushed device array — it stalls the dispatch-ahead pipeline on a
PCIe round-trip per step, silently reintroducing exactly the syncs
PR 6 removed (and that FF107 polices for non-tracer code).

Mechanically this is the :mod:`.sync_transfer` machinery re-aimed:
the same HOT_ROOTS reachability walk over serve/ files, but scoped to
the ARGUMENT subtrees of tracer emission calls (``*.event(...)`` /
``*.span(...)`` on a ``tracer``/``tr`` receiver) — and therefore
strict about a wider set of concretizers (``.item()``, ``.tolist()``,
``np.asarray``/``np.array``, the ``jax.*`` transfer calls): inside a
trace-event argument there is never a legitimate reason to touch
device memory. Telemetry must be computed from host state, or deferred
to a flush point.

Suppress with ``# ffcheck: disable=FF108 -- reason``.
"""
from __future__ import annotations

import ast
from typing import Iterator, Set

from ..lint import FileContext, Finding, FuncDef, Rule
from .sync_transfer import RULE as _SYNC_TRANSFER

#: emission methods of obs.tracer.Tracer
TRACER_METHODS = {"event", "span"}
#: receiver names the serve stack binds tracers to (``self.tracer``,
#: a local ``tr = self.tracer``, or a ``tracer=`` parameter)
TRACER_NAMES = {"tr", "tracer"}

#: dotted calls that force a transfer / concretization of a device
#: array when evaluated inside an event's argument list
SYNC_PATHS = {
    "jax.device_get",
    "jax.device_put",
    "jax.block_until_ready",
    "numpy.asarray",
    "numpy.array",
    "numpy.copy",
}
#: zero-arg methods that force a device->host read on an array receiver
SYNC_METHODS = {"item", "tolist", "block_until_ready"}


def _is_tracer_call(node: ast.Call) -> bool:
    func = node.func
    if not isinstance(func, ast.Attribute) or func.attr not in TRACER_METHODS:
        return False
    recv = func.value
    if isinstance(recv, ast.Name):
        return recv.id in TRACER_NAMES
    if isinstance(recv, ast.Attribute):
        return recv.attr in TRACER_NAMES
    return False


class TracerSyncRule(Rule):
    code = "FF108"
    slug = "tracer-sync"
    doc = (
        "device sync (.item()/.tolist()/np.asarray/jax.device_get/...) "
        "inside a tracer event/span argument on the serving hot path — "
        "telemetry must read host state, never un-flushed arrays"
    )

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        path = ctx.path.replace("\\", "/")
        if not ("/serve/" in path or path.startswith("serve/")
                or "/obs/" in path or path.startswith("obs/")):
            return
        reachable = _SYNC_TRANSFER._reachable(ctx)
        seen: Set[int] = set()
        for fn in reachable:
            for stmt in fn.body if isinstance(fn, FuncDef) else []:
                for node in ast.walk(stmt):
                    if (
                        not isinstance(node, ast.Call)
                        or id(node) in seen
                        or not _is_tracer_call(node)
                    ):
                        continue
                    seen.add(id(node))
                    yield from self._check_args(ctx, node)

    def _check_args(self, ctx: FileContext,
                    call: ast.Call) -> Iterator[Finding]:
        subtrees = list(call.args) + [kw.value for kw in call.keywords]
        for arg in subtrees:
            for node in ast.walk(arg):
                if not isinstance(node, ast.Call):
                    continue
                resolved = ctx.resolve(node.func)
                if resolved in SYNC_PATHS:
                    yield self.finding(
                        ctx, node,
                        f"{resolved} inside a tracer "
                        f"{call.func.attr}() argument forces a device "
                        "sync on the hot path — record host state, or "
                        "defer the read to a flush point",
                    )
                elif (
                    isinstance(node.func, ast.Attribute)
                    and node.func.attr in SYNC_METHODS
                    and not node.args
                ):
                    yield self.finding(
                        ctx, node,
                        f".{node.func.attr}() inside a tracer "
                        f"{call.func.attr}() argument is a blocking "
                        "device->host read — the telemetry stalls the "
                        "dispatch pipeline it is measuring",
                    )


RULE = TracerSyncRule()
