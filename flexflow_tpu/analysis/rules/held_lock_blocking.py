"""FF111 held-lock-blocking-call: blocking operations inside a
``with <lock>:`` body, plus the module-level lock-acquisition-order
graph with cycle detection.

Holding a lock across a blocking operation turns one slow peer into a
stalled cluster: every thread that needs the lock queues behind a
socket ``recv``, an ``Event.wait``, a ``sleep`` or an RPC dispatch.
The rule flags calls that (directly, or transitively through intra-file
callees) block, when they sit lexically inside a ``with`` scope whose
context expression looks like a lock (name contains ``lock``). The
stack's deliberate hold-across-blocking sites — the writer lock
serializing ``sendall``/re-dials, the loopback dispatch lock
serializing engine steps — carry reasoned suppressions; everything
else is a hang waiting for a slow peer.

The second half is deadlock prevention across files:
:func:`analyze_lock_order` builds the acquisition-order graph over a
corpus (``transport.py``/``server.py``/``remote.py`` — edge A→B when
code acquires B while holding A, including acquisitions reached
through cross-file calls matched by method name), and
:func:`find_order_cycles` reports any cycle — the static mirror of the
runtime :class:`~..locks.LockSanitizer` inversion check.
``scripts/ffcheck.py`` runs it over ``serve/cluster/`` on every lint.

Suppress findings with ``# ffcheck: disable=FF111 -- reason``.
"""
from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple

from ..lint import FileContext, Finding, FuncDef, Rule

#: dotted calls that block the calling thread
BLOCKING_PATHS = {
    "time.sleep",
    "socket.create_connection",
    "select.select",
    "os.fsync",
}
#: function simple names (imported helpers) that block
BLOCKING_NAMES = {"read_frame_from_socket"}
#: attribute-method calls that block regardless of receiver: socket
#: I/O, Event/future waits, thread joins, and RPC dispatch (the
#: loopback's dispatch call runs a whole engine step)
BLOCKING_METHODS = {
    "sendall", "recv", "accept", "connect", "sendto", "recvfrom",
    "wait", "join", "result", "dispatch",
}
#: argless ``.get()`` is a queue take (a dict ``.get`` always has args)
BLOCKING_ARGLESS_METHODS = {"get"}


def _is_lockish_name(name: Optional[str]) -> bool:
    return name is not None and "lock" in name.lower()


def _with_item_lock(expr: ast.AST) -> Optional[str]:
    """``with self._lock:`` -> ``_lock``; ``with _STATS_LOCK:`` ->
    ``_STATS_LOCK``; non-lock context managers -> None."""
    if isinstance(expr, ast.Attribute) and _is_lockish_name(expr.attr):
        return expr.attr
    if isinstance(expr, ast.Name) and _is_lockish_name(expr.id):
        return expr.id
    return None


def _blocks_directly(node: ast.Call, ctx: FileContext) -> Optional[str]:
    """The reason string when this single call blocks, else None."""
    resolved = ctx.resolve(node.func)
    if resolved in BLOCKING_PATHS:
        return resolved
    if resolved is not None and resolved.split(".")[-1] in BLOCKING_NAMES:
        return resolved.split(".")[-1]
    if isinstance(node.func, ast.Attribute):
        attr = node.func.attr
        if attr in BLOCKING_METHODS:
            return f".{attr}()"
        if attr in BLOCKING_ARGLESS_METHODS and not node.args \
                and not node.keywords:
            return f".{attr}()"
    return None


def _local_callee_names(node: ast.Call) -> List[str]:
    """Simple names a call might resolve to intra-file: ``self._m(...)``
    and bare ``fn(...)``."""
    f = node.func
    if isinstance(f, ast.Attribute):
        return [f.attr]
    if isinstance(f, ast.Name):
        return [f.id]
    return []


def _blocking_functions(ctx: FileContext) -> Set[str]:
    """Names of local functions/methods that (transitively) contain a
    blocking call — fixpoint over simple-name calls."""
    contains: Set[str] = set()
    calls: Dict[str, Set[str]] = {}
    for fn in ctx.functions:
        callees: Set[str] = set()
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            if _blocks_directly(node, ctx):
                contains.add(fn.name)
            callees.update(_local_callee_names(node))
        calls[fn.name] = callees
    names = {fn.name for fn in ctx.functions}
    changed = True
    while changed:
        changed = False
        for name, callees in calls.items():
            if name not in contains and callees & (contains & names):
                contains.add(name)
                changed = True
    return contains


class HeldLockBlockingRule(Rule):
    code = "FF111"
    slug = "held-lock-blocking-call"
    doc = (
        "blocking operation (socket I/O, Event.wait, sleep, queue "
        "take, RPC dispatch — directly or through a local callee) "
        "inside a `with <lock>:` body — one slow peer stalls every "
        "thread queuing on the lock; move the blocking op outside the "
        "critical section or suppress with the reason it must be held"
    )

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        blocking_fns = _blocking_functions(ctx)
        for wnode in ast.walk(ctx.tree):
            if not isinstance(wnode, ast.With):
                continue
            locks = [
                lk for item in wnode.items
                if (lk := _with_item_lock(item.context_expr)) is not None
            ]
            if not locks:
                continue
            lock = locks[0]
            for stmt in wnode.body:
                for node in ast.walk(stmt):
                    if not isinstance(node, ast.Call):
                        continue
                    why = _blocks_directly(node, ctx)
                    if why is None:
                        for name in _local_callee_names(node):
                            if name in blocking_fns:
                                why = f"{name}() (blocks transitively)"
                                break
                    if why is None:
                        continue
                    yield self.finding(
                        ctx, node,
                        f"blocking call {why} while holding {lock!r} — "
                        "threads queuing on the lock stall behind the "
                        "slow peer; hoist it out of the critical "
                        "section (or suppress with the reason the "
                        "hold is the protocol)",
                    )


RULE = HeldLockBlockingRule()


# ---------------------------------------------------------------------------
# module-level lock-acquisition-order graph (corpus-wide)


def _qualify(lock: str, cls: Optional[str], expr: ast.AST) -> str:
    """Graph node id: instance locks are ``Class.attr`` (two classes'
    ``_lock`` attributes are different locks); module-level lock names
    stay global."""
    if isinstance(expr, ast.Attribute) and cls is not None:
        return f"{cls}.{lock}"
    return lock


def analyze_lock_order(
    sources: Dict[str, str],
) -> Dict[Tuple[str, str], str]:
    """Build the acquisition-order graph over a corpus of files.

    Returns ``{(held, acquired): "file:line"}`` — an edge per observed
    "acquire B inside a ``with A:`` body", where the acquisition is a
    lexically nested ``with`` OR a call (matched by simple name across
    the whole corpus — the loopback's ``self.dispatch(...)`` reaching
    the server core's ``_dispatch_lock``) into a function that
    acquires locks, computed to a fixpoint."""
    # pass 1: per file — every function with its class context, the
    # locks each function acquires directly, and its callee names
    ctxs = {path: FileContext(path, src) for path, src in sources.items()}
    fn_infos: List[dict] = []
    for path, ctx in ctxs.items():
        class_of: Dict[ast.AST, str] = {}
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ClassDef):
                for stmt in node.body:
                    if isinstance(stmt, FuncDef):
                        class_of[stmt] = node.name
        for fn in ctx.functions:
            cls = class_of.get(fn)
            acquires: List[Tuple[str, int]] = []
            callees: Set[str] = set()
            for node in ast.walk(fn):
                if isinstance(node, ast.With):
                    for item in node.items:
                        lk = _with_item_lock(item.context_expr)
                        if lk is not None:
                            acquires.append((
                                _qualify(lk, cls, item.context_expr),
                                node.lineno,
                            ))
                elif isinstance(node, ast.Call):
                    callees.update(_local_callee_names(node))
            fn_infos.append({
                "path": path, "ctx": ctx, "fn": fn, "cls": cls,
                "name": fn.name, "acquires": acquires,
                "callees": callees,
            })
    by_name: Dict[str, List[dict]] = {}
    for info in fn_infos:
        by_name.setdefault(info["name"], []).append(info)
    # pass 2: transitive acquisition sets per function (corpus-wide
    # name matching; over-approximate on purpose — a false edge is a
    # review prompt, a missed edge is a deadlock)
    trans: Dict[int, Set[str]] = {
        id(info["fn"]): {lk for lk, _ in info["acquires"]}
        for info in fn_infos
    }
    changed = True
    while changed:
        changed = False
        for info in fn_infos:
            mine = trans[id(info["fn"])]
            for callee in info["callees"]:
                for target in by_name.get(callee, ()):
                    extra = trans[id(target["fn"])] - mine
                    if extra:
                        mine |= extra
                        changed = True
    # pass 3: edges — for every `with L:` body, locks acquired inside
    # (nested withs + callees' transitive sets)
    edges: Dict[Tuple[str, str], str] = {}
    for info in fn_infos:
        ctx, cls = info["ctx"], info["cls"]
        for wnode in ast.walk(info["fn"]):
            if not isinstance(wnode, ast.With):
                continue
            held = [
                _qualify(lk, cls, item.context_expr)
                for item in wnode.items
                if (lk := _with_item_lock(item.context_expr)) is not None
            ]
            if not held:
                continue
            inner: Set[str] = set()
            for stmt in wnode.body:
                for node in ast.walk(stmt):
                    if isinstance(node, ast.With):
                        for item in node.items:
                            lk = _with_item_lock(item.context_expr)
                            if lk is not None:
                                inner.add(
                                    _qualify(lk, cls, item.context_expr)
                                )
                    elif isinstance(node, ast.Call):
                        for name in _local_callee_names(node):
                            for target in by_name.get(name, ()):
                                inner |= trans[id(target["fn"])]
            site = f"{info['path']}:{wnode.lineno}"
            for h in held:
                for a in inner:
                    if a != h:
                        edges.setdefault((h, a), site)
    return edges


def find_order_cycles(
    edges: Dict[Tuple[str, str], str],
) -> List[List[str]]:
    """Cycles in the acquisition-order graph (each is a potential
    deadlock). Returns lists of node names, cycle closed implicitly."""
    graph: Dict[str, Set[str]] = {}
    for a, b in edges:
        graph.setdefault(a, set()).add(b)
        graph.setdefault(b, set())
    cycles: List[List[str]] = []
    seen_cycles: Set[Tuple[str, ...]] = set()
    WHITE, GRAY, BLACK = 0, 1, 2
    color = {n: WHITE for n in graph}

    def dfs(node: str, path: List[str]) -> None:
        color[node] = GRAY
        path.append(node)
        for nxt in sorted(graph[node]):
            if color[nxt] == GRAY:
                cyc = path[path.index(nxt):]
                key = tuple(sorted(cyc))
                if key not in seen_cycles:
                    seen_cycles.add(key)
                    cycles.append(list(cyc))
            elif color[nxt] == WHITE:
                dfs(nxt, path)
        path.pop()
        color[node] = BLACK

    for node in sorted(graph):
        if color[node] == WHITE:
            dfs(node, [])
    return cycles


def check_lock_order(paths: Sequence[str]) -> List[str]:
    """The ffcheck entry point: read the corpus, report each cycle as
    one problem line (empty list = acyclic = clean)."""
    sources: Dict[str, str] = {}
    for p in paths:
        with open(p, "r") as fh:
            sources[p] = fh.read()
    edges = analyze_lock_order(sources)
    problems = []
    for cyc in find_order_cycles(edges):
        hops = " -> ".join(cyc + [cyc[0]])
        sites = "; ".join(
            f"{a}->{b} at {edges[(a, b)]}"
            for a, b in zip(cyc, cyc[1:] + [cyc[0]])
            if (a, b) in edges
        )
        problems.append(
            f"lock-order cycle: {hops} ({sites}) — two threads taking "
            "these locks in opposite orders deadlock"
        )
    return problems
