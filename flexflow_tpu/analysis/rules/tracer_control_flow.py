"""FF102 tracer-control-flow: Python branching on traced array values.

``if jnp.any(x):`` inside a traced function is not a device-side branch
— it concretizes the array at trace time (ConcretizationTypeError), or,
when tracing happens to succeed, bakes ONE side of the branch into the
compiled program forever. Device-dependent control flow belongs in
``jnp.where``/``jax.lax.cond``/``jax.lax.switch``.
"""
from __future__ import annotations

import ast
from typing import Iterator, Optional

from ..lint import FileContext, Finding, Rule

# A call into these namespaces produces a traced array; branching on it
# in Python is the hazard.
ARRAY_NAMESPACES = ("jax.numpy.", "jax.lax.", "jax.nn.", "jax.random.")


class TracerControlFlowRule(Rule):
    code = "FF102"
    slug = "tracer-control-flow"
    doc = (
        "Python if/while/assert on a value computed by jnp/jax.lax "
        "inside jit-traced code"
    )

    def _array_call(self, ctx: FileContext, test: ast.AST) -> Optional[str]:
        for node in ast.walk(test):
            if isinstance(node, ast.Call):
                path = ctx.resolve(node.func)
                if path and (
                    path.startswith(ARRAY_NAMESPACES)
                    or path in ("jax.numpy", "jax.lax")
                ):
                    return path
        return None

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for node in ctx.walk_traced((ast.If, ast.While, ast.Assert)):
            test = node.test
            path = self._array_call(ctx, test)
            if path is None:
                continue
            kind = {
                ast.If: "if", ast.While: "while", ast.Assert: "assert"
            }[type(node)]
            yield self.finding(
                ctx, node,
                f"Python `{kind}` on the result of {path} inside "
                "jit-traced code — concretization error at trace time "
                "or one branch baked into the compiled program; use "
                "jnp.where / jax.lax.cond",
            )


RULE = TracerControlFlowRule()
