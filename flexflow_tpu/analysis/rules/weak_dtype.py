"""FF103 weak-dtype: ``jnp.asarray``/``jnp.array`` without an explicit
dtype.

``jnp.asarray`` of host data inherits whatever dtype the host side
happened to produce — and for Python scalars/lists the result is
*weak-typed*, which participates in jit cache keys. One call site that
sometimes receives ``np.int32`` and sometimes a Python list retraces
the step program on every flip; with x64 enabled the same site silently
doubles every buffer. On the serving hot path a single such retrace is
a 100x step-latency spike. Pinning ``dtype=`` makes the abstract
signature — and therefore the compile cache key — independent of the
caller's host types.
"""
from __future__ import annotations

import ast
from typing import Iterator

from ..lint import FileContext, Finding, Rule

CONVERTERS = {"jax.numpy.asarray", "jax.numpy.array"}


class WeakDtypeRule(Rule):
    code = "FF103"
    slug = "weak-dtype"
    doc = (
        "jnp.asarray/jnp.array without an explicit dtype — weak-type "
        "promotion (or a host-side type flip) can key an XLA retrace"
    )

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            path = ctx.resolve(node.func)
            if path not in CONVERTERS:
                continue
            if len(node.args) >= 2:  # positional dtype
                continue
            if any(k.arg == "dtype" for k in node.keywords):
                continue
            if len(node.args) != 1:
                continue
            arg = node.args[0]
            # converting a value that is already a jax expression keeps
            # its (strong) dtype — no weak-type hazard
            if isinstance(arg, ast.Call):
                apath = ctx.resolve(arg.func)
                if apath and apath.startswith("jax."):
                    continue
            name = path.rsplit(".", 1)[-1]
            yield self.finding(
                ctx, node,
                f"jnp.{name}(...) without an explicit dtype — the "
                "result's (possibly weak) dtype follows the caller's "
                "host types and can key a retrace of every jitted "
                "consumer; pass dtype=",
            )


RULE = WeakDtypeRule()
