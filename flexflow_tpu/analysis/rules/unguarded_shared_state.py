"""FF110 unguarded-shared-state: cross-thread instance attributes must
be in a declared ``guarded-by`` registry, and every access must hold
the declared lock.

The transport layer runs real threads (the socket reader, the loopback
worker); any attribute those threads WRITE and caller-thread code also
touches is shared mutable state. This rule makes the guarding
discipline declarative and machine-checked:

1. **Discovery** — per class (in-file base classes merged), the rule
   finds every ``threading.Thread(target=self._x)`` entry point,
   closes over intra-class ``self.m()`` calls to get the
   thread-reachable method set, and intersects the attributes those
   methods write with the attributes the caller-facing methods touch
   (``__init__`` excluded — construction precedes the thread).
2. **Registry** — each shared attribute must be declared, either
   inline on its initializer line::

       self._pending = {}  # ffcheck: guarded-by=_lock

   or in bulk anywhere in the class body::

       # ffcheck: guarded-by[_lock]=_pending,_sock

   The lock name is an instance attribute (``self._lock``) or a
   module-level lock (``_STATS_LOCK``). An undeclared shared
   attribute is a finding.
3. **Scope check** — every access (load or store) to a REGISTERED
   attribute outside ``__init__`` must sit lexically inside a
   ``with self.<lock>:`` / ``with <LOCK>:`` scope for its declared
   lock. Two escape hatches encode "caller holds the lock" contracts:
   a method whose name ends in ``_locked`` (the transport's existing
   convention), or an explicit ``# ffcheck: requires-lock=<lock>``
   comment on/above the ``def`` line. Both are runtime-checkable via
   :meth:`analysis.locks.SanitizableLock.assert_held`.

Suppress with ``# ffcheck: disable=FF110 -- reason``.
"""
from __future__ import annotations

import ast
import re
from typing import Dict, Iterator, List, Optional, Set, Tuple

from ..lint import FileContext, Finding, FuncDef, Rule

#: attribute-method calls treated as writes to the receiver
MUTATOR_METHODS = {
    "append", "extend", "insert", "add", "discard", "remove", "pop",
    "popitem", "clear", "update", "setdefault", "appendleft",
}

_GUARDED_BULK_RE = re.compile(
    r"#\s*ffcheck:\s*guarded-by\[(?P<lock>[A-Za-z_][A-Za-z0-9_.]*)\]\s*=\s*"
    r"(?P<attrs>[A-Za-z0-9_, ]+)"
)
_GUARDED_INLINE_RE = re.compile(
    r"#\s*ffcheck:\s*guarded-by\s*=\s*(?P<lock>[A-Za-z_][A-Za-z0-9_.]*)"
    r"(?!\])"
)
_REQUIRES_LOCK_RE = re.compile(
    r"#\s*ffcheck:\s*requires-lock\s*=\s*(?P<lock>[A-Za-z_][A-Za-z0-9_.]*)"
)


def _self_attr(node: ast.AST) -> Optional[str]:
    """``self.x`` -> ``x`` (else None)."""
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


def _attr_accesses(fn: ast.AST) -> Iterator[Tuple[str, bool, ast.AST]]:
    """Yield ``(attr, is_write, node)`` for every ``self.attr`` touch in
    ``fn``'s body: assignments (plain/augmented/subscript/del), mutator
    method calls, and plain loads."""
    for node in ast.walk(fn):
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = (
                node.targets if isinstance(node, ast.Assign)
                else [node.target]
            )
            for t in targets:
                attr = _self_attr(t)
                if attr is not None:
                    yield attr, True, t
                    continue
                if isinstance(t, ast.Subscript):
                    attr = _self_attr(t.value)
                    if attr is not None:
                        yield attr, True, t
        elif isinstance(node, ast.Delete):
            for t in node.targets:
                base = t.value if isinstance(t, ast.Subscript) else t
                attr = _self_attr(base)
                if attr is not None:
                    yield attr, True, t
        elif isinstance(node, ast.Call):
            f = node.func
            if (
                isinstance(f, ast.Attribute)
                and f.attr in MUTATOR_METHODS
            ):
                attr = _self_attr(f.value)
                if attr is not None:
                    yield attr, True, node
        elif isinstance(node, ast.Attribute):
            attr = _self_attr(node)
            if attr is not None and isinstance(node.ctx, ast.Load):
                yield attr, False, node


class _ClassView:
    """One class with its in-file base-class methods merged (the
    transport hierarchy keeps counters on the base and threads on the
    subclass — the analysis needs the flat view)."""

    def __init__(self, cls: ast.ClassDef,
                 by_name: Dict[str, ast.ClassDef]):
        self.cls = cls
        self.methods: Dict[str, ast.AST] = {}
        #: every FuncDef in the chain, INCLUDING base methods shadowed
        #: by a subclass override — registry comments on a base
        #: initializer line must bind even when the subclass has its
        #: own ``__init__``
        self.all_methods: List[ast.AST] = []
        self.spans: List[Tuple[int, int]] = []
        seen: Set[str] = set()
        stack, chain = [cls], []
        while stack:
            c = stack.pop(0)
            if c.name in seen:
                continue
            seen.add(c.name)
            chain.append(c)
            for b in c.bases:
                if isinstance(b, ast.Name) and b.id in by_name:
                    stack.append(by_name[b.id])
        for c in chain:
            self.spans.append(
                (c.lineno, getattr(c, "end_lineno", c.lineno))
            )
            for stmt in c.body:
                if isinstance(stmt, FuncDef):
                    self.all_methods.append(stmt)
                    if stmt.name not in self.methods:
                        self.methods[stmt.name] = stmt

    def contains_line(self, lineno: int) -> bool:
        return any(a <= lineno <= b for a, b in self.spans)


def _thread_targets(view: _ClassView, ctx: FileContext) -> Set[str]:
    """Method names handed to ``threading.Thread(target=self._x)``
    anywhere in the class."""
    roots: Set[str] = set()
    for fn in view.methods.values():
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            resolved = ctx.resolve(node.func)
            if resolved not in ("threading.Thread", "Thread"):
                continue
            for kw in node.keywords:
                if kw.arg == "target":
                    attr = _self_attr(kw.value)
                    if attr is not None and attr in view.methods:
                        roots.add(attr)
    return roots


def _close_over_calls(view: _ClassView, seeds: Set[str],
                      stop: Set[str] = frozenset()) -> Set[str]:
    """Transitive closure of intra-class ``self.m()`` calls from the
    seed methods, never descending into ``stop`` methods."""
    reach = set(seeds)
    frontier = list(seeds)
    while frontier:
        fn = view.methods.get(frontier.pop())
        if fn is None:
            continue
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            attr = _self_attr(node.func)
            if (
                attr is not None and attr in view.methods
                and attr not in reach and attr not in stop
            ):
                reach.add(attr)
                frontier.append(attr)
    return reach


def _parse_registry(
    source: str, view: _ClassView, ctx: FileContext
) -> Tuple[Dict[str, str], Dict[int, str], Set[int]]:
    """Returns (attr -> lock, def-line -> required lock,
    lines carrying an inline guarded-by comment). Inline form binds to
    the ``self.attr`` assignment on its line; bulk form lists attrs
    explicitly; requires-lock binds to the def on/below its line."""
    registry: Dict[str, str] = {}
    requires: Dict[int, str] = {}
    inline_lines: Dict[int, str] = {}
    lines = source.splitlines()
    for i, line in enumerate(lines, start=1):
        if not view.contains_line(i):
            continue
        m = _GUARDED_BULK_RE.search(line)
        if m:
            for attr in m.group("attrs").split(","):
                attr = attr.strip()
                if attr:
                    registry[attr] = m.group("lock")
            continue
        m = _GUARDED_INLINE_RE.search(line)
        if m:
            inline_lines[i] = m.group("lock")
        m = _REQUIRES_LOCK_RE.search(line)
        if m:
            # bind to this line's def, or the next line's (comment
            # above the def)
            requires[i] = m.group("lock")
            requires[i + 1] = m.group("lock")
    if inline_lines:
        for fn in view.all_methods:
            for attr, is_write, node in _attr_accesses(fn):
                if not is_write:
                    continue
                lock = inline_lines.get(getattr(node, "lineno", -1))
                if lock is not None:
                    registry.setdefault(attr, lock)
    req_by_def: Dict[int, str] = {}
    for fn in view.methods.values():
        lock = requires.get(fn.lineno)
        if lock is None and fn.decorator_list:
            lock = requires.get(fn.decorator_list[0].lineno)
        if lock is not None:
            req_by_def[fn.lineno] = lock
    return registry, req_by_def, set(inline_lines)


def _with_locks_around(ctx: FileContext, node: ast.AST) -> Set[str]:
    """Lock names of every ``with`` scope lexically enclosing ``node``
    (``self._lock`` -> ``_lock``; module-level ``_STATS_LOCK`` as-is;
    ``lock.acquire()``-style is out of scope — the stack uses context
    managers only)."""
    held: Set[str] = set()
    anc = ctx._parent.get(node)
    while anc is not None:
        if isinstance(anc, ast.With):
            for item in anc.items:
                expr = item.context_expr
                attr = _self_attr(expr)
                if attr is not None:
                    held.add(attr)
                elif isinstance(expr, ast.Name):
                    held.add(expr.id)
        anc = ctx._parent.get(anc)
    return held


class UnguardedSharedStateRule(Rule):
    code = "FF110"
    slug = "unguarded-shared-state"
    doc = (
        "instance attribute written from a threading.Thread-targeted "
        "method and touched from caller threads without a "
        "`# ffcheck: guarded-by=<lock>` registry entry, or a "
        "registered attribute accessed outside its `with <lock>:` "
        "scope (escape hatches: *_locked method names, "
        "`# ffcheck: requires-lock=<lock>`)"
    )

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        classes = [
            n for n in ast.walk(ctx.tree) if isinstance(n, ast.ClassDef)
        ]
        by_name = {c.name: c for c in classes}
        # base-class methods are re-visited once per subclass (the flat
        # view) — dedupe by position so each site reports once
        seen: Set[Tuple[int, int, str]] = set()
        for cls in classes:
            view = _ClassView(cls, by_name)
            roots = _thread_targets(view, ctx)
            registry, requires, _ = _parse_registry(
                ctx.source, view, ctx
            )
            if not roots and not registry:
                continue
            for f in self._check_class(ctx, view, roots, registry,
                                       requires):
                key = (f.line, f.col, f.message.split(" in ")[0])
                if key not in seen:
                    seen.add(key)
                    yield f

    def _check_class(
        self,
        ctx: FileContext,
        view: _ClassView,
        roots: Set[str],
        registry: Dict[str, str],
        requires: Dict[int, str],
    ) -> Iterator[Finding]:
        thread_reach = _close_over_calls(view, roots)
        caller_entries = {
            name for name in view.methods if name not in roots
        }
        caller_reach = _close_over_calls(view, caller_entries, stop=roots)
        # discovery: thread-written ∩ caller-touched (outside __init__)
        thread_writes: Dict[str, ast.AST] = {}
        for name in thread_reach:
            fn = view.methods[name]
            for attr, is_write, node in _attr_accesses(fn):
                if is_write:
                    thread_writes.setdefault(attr, node)
        caller_touches: Set[str] = set()
        for name in caller_reach:
            if name == "__init__":
                continue
            for attr, _, _node in _attr_accesses(view.methods[name]):
                caller_touches.add(attr)
        shared = set(thread_writes) & caller_touches
        for attr in sorted(shared - set(registry) - set(view.methods)):
            yield self.finding(
                ctx, thread_writes[attr],
                f"attribute '{attr}' of class {view.cls.name} is "
                "written on a thread-target path and touched from "
                "caller threads, but is not in the guarded-by "
                "registry — declare `# ffcheck: guarded-by=<lock>` "
                "on its initializer (or fix the sharing)",
            )
        # scope check over registered attrs
        for name, fn in view.methods.items():
            if name == "__init__":
                continue
            exempt_lock: Optional[str] = requires.get(fn.lineno)
            if exempt_lock is None and name.endswith("_locked"):
                exempt_lock = "*"
            for attr, is_write, node in _attr_accesses(fn):
                lock = registry.get(attr)
                if lock is None:
                    continue
                if exempt_lock == "*" or exempt_lock == lock:
                    continue
                if lock in _with_locks_around(ctx, node):
                    continue
                verb = "write to" if is_write else "read of"
                yield self.finding(
                    ctx, node,
                    f"{verb} '{attr}' (guarded-by={lock}) outside a "
                    f"`with {lock}:` scope in {view.cls.name}."
                    f"{name} — hold the declared lock, or mark the "
                    "method `# ffcheck: requires-lock="
                    f"{lock}` / name it *_locked if the caller holds it",
                )


RULE = UnguardedSharedStateRule()
