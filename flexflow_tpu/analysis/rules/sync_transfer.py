"""FF107 sync-transfer: blocking device↔host transfers on the serving
hot path.

The hierarchical KV cache spills cold prefix pages to host RAM and
re-admits them on a hit (serve/prefix_cache.py). That tier is only free
because every transfer is ASYNC: ``fetch_page`` starts a
``copy_to_host_async`` and the handle is harvested at the scheduler's
flush (already a sync point); ``upload_page`` relies on dispatch
ordering. A stray ``jax.device_get`` (or blocking ``jax.device_put`` /
``block_until_ready``) introduced anywhere the scheduler's dispatch
path can reach would serialize the dispatch-ahead pipeline — every
decode step would wait out a PCIe round-trip, the exact stall the
spill tier is designed never to cause.

Unlike FF101 (host syncs inside jit-TRACED code), this rule walks the
HOST-side scheduler: functions in ``flexflow_tpu/serve/`` reachable —
through the file-local call graph, ``self.``-method calls included —
from the serving hot-path roots (``step``/dispatch/admission/page
reservation/prefix-cache attach+reclaim and the engine's ``run*``
dispatch methods). Paths that block BY DESIGN (the pipeline flush, the
blocking sync scheduler, triage dumps) carry explicit suppressions
with reasons — the point is that every blocking transfer on the hot
path is a reviewed decision, not an accident.

Suppress with ``# ffcheck: disable=FF107 -- reason``.
"""
from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Set

from ..lint import FileContext, Finding, FuncDef, Rule

# Host-side entry points of the serving hot path: the scheduler's step
# loop and everything it runs per iteration, the admission/page-
# reservation path (where spill/readmit live), and the engine's
# dispatch methods. Reachability is computed from these by name over
# the file-local call graph.
HOT_ROOTS = {
    "step",
    "_step_pipelined",
    "_dispatch_mixed",
    "_dispatch_decode",
    "_reserve_active_pages",
    "_admit_pending",
    "_reclaim_slots_for_admission",
    "_trim_pipeline",
    "attach",
    "reclaim",
    "run",
    "run_mixed",
    "run_decode",
    "run_sampled",
    "run_speculate",
    "commit",
    "reorder",
    "copy_page",
    "fetch_page",
    "upload_page",
    # cluster serving (serve/cluster/): the router/manager drive loop
    # and the prefill→decode migration — its one blocking harvest is a
    # designed flush point and must carry a reasoned suppression
    "submit",
    "route",
    "migrate_request",
    "_queue_migrations",
    "_drain_migration_queue",
    "_recompute_readmit",
    # fault tolerance (health/failover/probe): everything that runs
    # when a replica dies or recovers is ON the drive loop — a blocking
    # transfer in a failover would stall every healthy replica's decode
    # exactly when the cluster is degraded
    "_place",
    "_on_replica_down",
    "_run_failovers",
    "_schedule_failover",
    "abandon",
    "on_step",
    "record_failure",
    "record_success",
    "maybe_probe",
    # context-parallel long-context serving (kv_shard="context"): the
    # per-shard admission/allocation path (striped ensure/COW/readmit
    # run inside admissions and page reservation) and the ring ragged
    # paged attention entry points — a blocking transfer anywhere here
    # would stall every decode step on a 100k-token request's critical
    # path
    "ensure",
    "take_free_page",
    "cow",
    "splice",
    "release",
    "_readmit",
    "_admission_error",
    "_ensure_pages",
    "shard_balance",
    "ring_ragged_paged_attention",
    "ring_ragged_paged_attention_xla",
    # replica RPC transport (serve/cluster/transport.py + remote.py +
    # server.py): the RPC send/recv core, heartbeats and the server's
    # dispatch table all run ON the cluster drive loop — a blocking
    # device transfer anywhere here would stall every replica's decode
    # behind one replica's PCIe round-trip. The two reviewed flush
    # points (the wire migration harvest in _m_migrate_out and the
    # standby tree-export harvest in export_tree) carry reasoned
    # suppressions; the server's handlers are reached dynamically
    # (getattr dispatch), so each one is its own root.
    "call",
    "_rpc",
    "heartbeat",
    "_heartbeat_remote",
    "_check_gap",
    "_observe_failure",
    # concurrent cluster stepping (multiplexed transport + fan-out
    # drive loop): the async issue/harvest pair, the per-connection
    # reader/worker loops that complete futures off-thread, and the
    # manager's concurrent step — ALL of it is the cluster's
    # once-per-step critical path, and a blocking device transfer in
    # an issue phase serializes the very RPCs the fan-out exists to
    # overlap
    "call_async",
    "step_async",
    "finish_step",
    "heartbeat_async",
    "finish_heartbeat",
    "prefix_score_async",
    "finish_prefix_score",
    "prefix_score",
    "wait",
    "result",
    "_prefix_scores",
    "_step_replicas_serial",
    "_step_replicas_concurrent",
    "_apply_step_failure",
    "_reader_loop",
    "_worker_loop",
    "_fail_pending",
    "dispatch",
    "_m_step",
    "_m_heartbeat",
    "_m_submit",
    "_m_migrate_out",
    "_m_migrate_in",
    "_m_export_tree",
    "_m_import_tree",
    "migrate_out",
    "migrate_in",
    "_migrate_remote",
    "export_tree",
    "import_tree",
    "_adopt_standby",
    # elastic control plane (serve/cluster/journal.py + reconfigure.py):
    # the journal's append/flush run at the drive loop's flush sync
    # point EVERY cluster step and the reconfiguration ops run under
    # live traffic — a blocking device transfer (or a hot-path fsync
    # smuggled in as one) anywhere here would stall every replica's
    # decode behind control-plane bookkeeping. The retire-time tree
    # hand-off reuses export_tree's reviewed harvest suppression.
    "append",
    "append_now",
    "flush",
    "_journal_sync",
    "_journal_checkpoint",
    "compact",
    "scale_out",
    "begin_scale_in",
    "maybe_retire",
    "_retire",
    "_warm_join",
    "set_pools",
    "rebuild_routing",
    "on_cluster_step",
    # whole-step decode megakernel (serve/kernels.whole_step_decode +
    # serve/collectives.py + engine._run_whole): the one-program layer
    # walk IS the decode hot path, and the quantized TP collectives run
    # inside it once per row-parallel matmul per layer — a blocking
    # transfer in the walk builder, the collective quantize/dequant, or
    # the dispatch wrapper would serialize every decode step
    "whole_step_decode",
    "whole_step_vmem_bytes",
    "tp_allreduce",
    "quantize_blocks",
    "dequantize_blocks",
    "_run_whole",
    "_get_whole_step",
    "_serve_whole_fn",
    # self-driving serving (serve/autotune/): the autoscaler's per-step
    # hook, its evaluation + decision paths and the estimator's
    # observation fold all run INSIDE ClusterManager.step — host-side
    # counter arithmetic only, and a blocking device transfer smuggled
    # into any of them would tax every cluster step. on_step is already
    # a root (fault injection shares the name); these cover the rest of
    # the policy/estimator drive-loop surface. observe/observe_cluster/
    # profile fold the telemetry; predict prices a candidate; the
    # _decide_* and _sweep_completions paths mutate cluster state.
    "observe",
    "observe_cluster",
    "profile",
    "predict",
    "_evaluate",
    "_decide_scale_out",
    "_decide_scale_in",
    "_maybe_retune",
    "_sweep_completions",
    "drain_completion_window",
    "rate_snapshot",
    # draft distillation (serve/spec_distill.py): the harvest path
    # fetches teacher logits by design — an offline/side-channel tool,
    # but it lives in serve/ and attaches a sink the verify round
    # calls, so every blocking fetch it can reach must be a reviewed
    # suppression, not a silent sync the sink smuggles onto the hot
    # path. measure_draft_utility drives the live verify ladder; the
    # skip arm's incremental decode rides the existing ``step`` root.
    "harvest_online",
    "harvest_offline",
    "train_distilled_draft",
    "measure_draft_utility",
}

# Calls that force a synchronous transfer / device round-trip.
# ``.copy_to_host_async()`` is the blessed idiom and is not listed.
SYNC_PATHS = {
    "jax.device_get",
    "jax.device_put",
    "jax.block_until_ready",
}
SYNC_METHODS = {"block_until_ready"}


class SyncTransferRule(Rule):
    code = "FF107"
    slug = "sync-transfer"
    doc = (
        "synchronous device<->host transfer (jax.device_get / blocking "
        "jax.device_put / block_until_ready) reachable from the serving "
        "hot path — spill-tier traffic must stay async"
    )

    def _applies(self, ctx: FileContext) -> bool:
        path = ctx.path.replace("\\", "/")
        return "/serve/" in path or path.startswith("serve/")

    def _reachable(self, ctx: FileContext) -> Set[ast.AST]:
        """Functions reachable from HOT_ROOTS over the file-local call
        graph. Both plain-name calls (``attach(...)``) and method calls
        (``self._flush_one(...)``) resolve by the callee's simple name
        — the safe over-approximation for a one-file class."""
        by_name: Dict[str, List[ast.AST]] = {}
        for fn in ctx.functions:
            by_name.setdefault(fn.name, []).append(fn)
        reachable: Set[ast.AST] = {
            fn for fn in ctx.functions if fn.name in HOT_ROOTS
        }
        changed = True
        while changed:
            changed = False
            for fn in list(reachable):
                for node in ast.walk(fn):
                    if not isinstance(node, ast.Call):
                        continue
                    name = None
                    if isinstance(node.func, ast.Name):
                        name = node.func.id
                    elif isinstance(node.func, ast.Attribute):
                        name = node.func.attr
                    for callee in by_name.get(name, ()):
                        if callee not in reachable:
                            reachable.add(callee)
                            changed = True
        # nested defs inherit their enclosing function's reachability
        for fn in ctx.functions:
            if fn in reachable:
                continue
            anc = ctx.enclosing_function(fn)
            while anc is not None:
                if anc in reachable:
                    reachable.add(fn)
                    break
                anc = ctx.enclosing_function(anc)
        return reachable

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        if not self._applies(ctx):
            return
        reachable = self._reachable(ctx)
        seen: Set[int] = set()
        for fn in reachable:
            for stmt in fn.body if isinstance(fn, FuncDef) else []:
                for node in ast.walk(stmt):
                    if (
                        not isinstance(node, ast.Call)
                        or id(node) in seen
                    ):
                        continue
                    seen.add(id(node))
                    path = ctx.resolve(node.func)
                    if path in SYNC_PATHS:
                        yield self.finding(
                            ctx, node,
                            f"{path} on the serving hot path blocks the "
                            "dispatch pipeline on a device round-trip — "
                            "use the async spill idiom "
                            "(copy_to_host_async + harvest at flush), "
                            "or suppress with a reason if this path "
                            "blocks by design",
                        )
                    elif (
                        isinstance(node.func, ast.Attribute)
                        and node.func.attr in SYNC_METHODS
                        and not node.args
                    ):
                        yield self.finding(
                            ctx, node,
                            f".{node.func.attr}() on the serving hot "
                            "path stalls until the device drains — the "
                            "hot loop must never wait on a transfer",
                        )


RULE = SyncTransferRule()
