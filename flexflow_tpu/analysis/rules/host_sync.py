"""FF101 host-sync: host↔device synchronization inside jit-traced code.

Inside a traced function every value is an abstract tracer;
``jax.device_get``/``np.asarray``/``.item()``/``float(tracer)`` either
raise a ConcretizationTypeError at trace time or — worse, when the value
happens to be concrete — silently constant-fold host data into the
compiled program, baking one step's runtime values into every future
step. In the serving hot path a surviving host sync also serializes the
dispatch-ahead pipeline: the decode loop stalls on a device round-trip
per step, the exact failure mode continuous batching exists to avoid.
"""
from __future__ import annotations

import ast
from typing import Iterator

from ..lint import FileContext, Finding, Rule

# Dotted calls that force a transfer / concretization.
HOST_SYNC_PATHS = {
    "jax.device_get",
    "jax.device_put",
    "jax.block_until_ready",
    "numpy.asarray",
    "numpy.array",
    "numpy.copy",
}
# Zero-arg methods that force a transfer on an array receiver.
HOST_SYNC_METHODS = {"item", "tolist", "block_until_ready"}
# Builtin casts that concretize a tracer.
CAST_BUILTINS = {"float", "int", "bool", "complex"}
# Parameters that are static configuration, never tracers.
STATIC_PARAM_NAMES = {"self", "cls", "cfg", "config", "mesh", "serving"}


class HostSyncRule(Rule):
    code = "FF101"
    slug = "host-sync"
    doc = (
        "host-sync call (jax.device_get / np.asarray / .item() / "
        "float(tracer) / ...) reachable inside jit-traced code"
    )

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for call in ctx.walk_traced(ast.Call):
            path = ctx.resolve(call.func)
            if path in HOST_SYNC_PATHS:
                yield self.finding(
                    ctx, call,
                    f"call to {path} inside jit-traced code forces a "
                    "host sync (or constant-folds runtime data into the "
                    "compiled program)",
                )
                continue
            if (
                isinstance(call.func, ast.Attribute)
                and call.func.attr in HOST_SYNC_METHODS
                and not call.args
            ):
                yield self.finding(
                    ctx, call,
                    f".{call.func.attr}() inside jit-traced code forces "
                    "a device->host transfer per call",
                )
                continue
            if (
                isinstance(call.func, ast.Name)
                and call.func.id in CAST_BUILTINS
                and len(call.args) == 1
                and isinstance(call.args[0], ast.Name)
            ):
                fn = ctx.enclosing_traced_function(call)
                if fn is None:
                    continue
                arg = call.args[0].id
                if (
                    arg in ctx.param_names(fn)
                    and arg not in STATIC_PARAM_NAMES
                ):
                    yield self.finding(
                        ctx, call,
                        f"{call.func.id}({arg}) concretizes a traced "
                        "argument — a ConcretizationTypeError at trace "
                        "time, or a silent constant-fold if it happens "
                        "to be static",
                    )


RULE = HostSyncRule()
