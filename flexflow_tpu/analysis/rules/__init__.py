"""ffcheck rule registry — one module per rule, auto-collected.

Adding a rule is one file: drop ``my_rule.py`` in this package exposing
a module-level ``RULE`` (an ``analysis.lint.Rule`` instance) and list it
in ``_RULE_MODULES`` below. The catalog in ``analysis/__init__.py`` and
``scripts/ffcheck.py --list-rules`` render from the registry.
"""
from __future__ import annotations

from . import (
    held_lock_blocking,
    host_sync,
    missing_donation,
    static_hashability,
    sync_transfer,
    tracer_control_flow,
    tracer_sync,
    unguarded_shared_state,
    unordered_iteration,
    wall_clock_step_logic,
    weak_dtype,
)

_RULE_MODULES = (
    host_sync,
    tracer_control_flow,
    weak_dtype,
    unordered_iteration,
    missing_donation,
    static_hashability,
    sync_transfer,
    tracer_sync,
    wall_clock_step_logic,
    unguarded_shared_state,
    held_lock_blocking,
)

ALL_RULES = tuple(m.RULE for m in _RULE_MODULES)

__all__ = ["ALL_RULES"]
