"""FF104 unordered-iteration: iterating a set inside traced code.

Trace order is program order: a ``for x in {...}`` loop inside a traced
function linearizes its iterations into the compiled program in
whatever order the set yields — which for int/str sets depends on hash
seeding and insertion history. Two processes tracing the "same" step
can compile different programs (non-deterministic numerics,
cache-key-identical but result-divergent executables). Iterate sorted
containers (or lists/dicts, which preserve insertion order) in trace
code.
"""
from __future__ import annotations

import ast
from typing import Iterator, Optional

from ..lint import FileContext, Finding, Rule

UNORDERED_CALLS = {"set", "frozenset", "vars", "globals", "locals", "dir"}


class UnorderedIterationRule(Rule):
    code = "FF104"
    slug = "unordered-iteration"
    doc = (
        "iteration over a set/frozenset (or vars()/globals()) inside "
        "jit-traced code — trace order, and so the compiled program, "
        "becomes nondeterministic"
    )

    def _unordered(self, ctx: FileContext, it: ast.AST) -> Optional[str]:
        if isinstance(it, (ast.Set, ast.SetComp)):
            return "a set literal"
        if isinstance(it, ast.Call):
            path = ctx.resolve(it.func)
            if path in UNORDERED_CALLS:
                return f"{path}()"
        return None

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for node in ctx.walk_traced(
            (ast.For, ast.ListComp, ast.SetComp, ast.DictComp,
             ast.GeneratorExp)
        ):
            iters = (
                [node.iter] if isinstance(node, ast.For)
                else [g.iter for g in node.generators]
            )
            for it in iters:
                what = self._unordered(ctx, it)
                if what:
                    yield self.finding(
                        ctx, it,
                        f"iterating {what} inside jit-traced code makes "
                        "the traced program depend on hash order — "
                        "sort it (or use a list/dict, which preserve "
                        "insertion order)",
                    )


RULE = UnorderedIterationRule()
