"""ffcheck — JAX/TPU hazard analysis for the serving stack.

FlexFlow's pitch is that the *runtime* keeps the execution plan optimal
(SURVEY.md: Unity's simulator-guided search, SpecInfer's batched
verify). In a JAX port the equivalent silent killers are unplanned XLA
recompiles, host↔device syncs inside the decode loop, and
use-after-donate on the KV cache — none of which fail a test until they
cost a 100x step-latency spike (or corrupted pages) in production.
This package is the correctness tooling for that class of bug, in
three parts:

1. **AST lint** (:mod:`.lint` + :mod:`.rules`) — static rules over the
   package, run by ``scripts/ffcheck.py`` and the tier-1 guard
   ``tests/test_ffcheck.py`` (zero unsuppressed findings required).
2. **Retrace sentinel** (:mod:`.retrace` — :class:`RetraceGuard`) —
   records every compile of every engine step program via the
   ``InferenceEngine._jit`` chokepoint; strict mode raises
   :class:`RetraceError` on any recompile of a known step key.
3. **Donation sanitizer** (:mod:`.donation` —
   :class:`DonationSanitizer`) — after every donated dispatch the old
   cache pytree is poisoned (:class:`DeletedBufferProxy`), so
   use-after-donate — the PR-2 page-corruption bug class — raises
   :class:`UseAfterDonateError` at the faulty read.
4. **Lock sanitizer** (:mod:`.locks` — :class:`LockSanitizer`) —
   every lock in the threaded transport/server stack is a
   :class:`SanitizableLock`; enabled, each acquisition records the
   per-thread held stack and the global acquisition-order graph, so a
   lock-order inversion (the A→B / B→A deadlock recipe) raises
   :class:`LockOrderInversion` at the second acquisition with BOTH
   stacks, and ``assert_held`` turns "caller holds the lock" comments
   into checked contracts (:class:`LockNotHeld`).
5. **Protocol drift checker** (:mod:`.protocol`) — statically diffs
   ``ReplicaServerCore``'s dispatch table against ``RemoteReplica``'s
   ``_rpc`` call sites (method names, argument arity, required
   envelope fields), so client/server skew fails ``scripts/ffcheck.py``
   instead of a subprocess chaos test 20 minutes in.

Runtime sanitizers are enabled per engine with
``ServingConfig(sanitizers=("retrace", "donation", "locks"))`` (or
``"retrace-warn"`` for record-only), or globally with
``FF_SANITIZERS=retrace,donation,locks`` in the environment.

Rule catalog
------------
========  ====================  ==============================================
Code      Slug                  Hazard
========  ====================  ==============================================
FF101     host-sync             ``jax.device_get``/``np.asarray``/``.item()``/
                                ``float(tracer)`` reachable inside jit-traced
                                code: a forced device sync per step, or host
                                data constant-folded into the program.
FF102     tracer-control-flow   Python ``if``/``while``/``assert`` on a value
                                computed by ``jnp``/``jax.lax`` in traced
                                code: concretization error, or one branch
                                baked in forever.
FF103     weak-dtype            ``jnp.asarray``/``jnp.array`` without an
                                explicit dtype: the abstract signature follows
                                the caller's host types — weak-type promotion
                                (or an int-list → np.int32 flip) keys a
                                retrace of every jitted consumer.
FF104     unordered-iteration   Iterating a ``set``/``frozenset`` (or
                                ``vars()``/``globals()``) in traced code: the
                                compiled program depends on hash order.
FF105     missing-donation      ``jax.jit`` of a function threading a
                                ``cache``/``opt_state`` buffer without
                                ``donate_argnums``: a full buffer copy per
                                step.
FF106     static-hashability    ``static_argnums``/``static_argnames`` whose
                                parameter defaults/annotations are unhashable
                                (list/dict/set): jit raises, or retraces per
                                call.
FF107     sync-transfer         ``jax.device_get``/blocking
                                ``jax.device_put``/``block_until_ready`` in
                                host-side serve code reachable from the
                                scheduler's hot path: one stray sync stalls
                                every decode step — hierarchical-KV spill
                                traffic must stay async (copy_to_host_async
                                + harvest at the flush sync point).
FF108     tracer-sync           A device sync (``.item()``/``.tolist()``/
                                ``np.asarray``/``jax.device_get``…) inside a
                                tracer ``event()``/``span()`` ARGUMENT on the
                                serving hot path: telemetry reading an
                                un-flushed array stalls the very pipeline it
                                measures — the observability layer must
                                record host state (or defer to a flush).
FF109     wall-clock-in-step-logic
                                ``time.time``/``time.monotonic``/``time.sleep``
                                /argless ``datetime.now`` in step-clock-
                                contracted cluster/autotune files: health,
                                autoscaling and journal decisions must count
                                cluster steps, not seconds — wall clock
                                enters once at ``TrafficEstimator.profile``.
                                ``time.perf_counter`` (measurement-only) is
                                allowed.
FF110     unguarded-shared-state
                                an attribute written from a ``threading.
                                Thread``-targeted callable and touched from
                                non-thread methods must appear in the class's
                                ``# ffcheck: guarded-by=<lock>`` registry, and
                                registered attrs must only be touched inside
                                ``with <lock>:`` scopes (or ``*_locked`` /
                                ``# ffcheck: requires-lock=<lock>`` methods).
FF111     held-lock-blocking-call
                                blocking op (socket I/O, ``Event.wait``,
                                ``sleep``, queue take, RPC dispatch — directly
                                or via a local callee) inside a ``with
                                <lock>:`` body: one slow peer stalls every
                                thread queuing on the lock. The same module
                                also builds the cross-file lock-acquisition-
                                order graph and fails on cycles.
========  ====================  ==============================================

Suppressions: ``# ffcheck: disable=FF101 -- reason`` on (or alone
above) the offending line; ``# ffcheck: disable-file=RULE`` for a whole
file; rule codes and slugs both work, ``all`` disables everything.

Standalone::

    python scripts/ffcheck.py                  # lint flexflow_tpu/
    python scripts/ffcheck.py --diff main      # only files changed vs main
    python scripts/ffcheck.py --list-rules
"""
from __future__ import annotations

from .donation import (
    DeletedBufferProxy,
    DonationSanitizer,
    UseAfterDonateError,
)
from .lint import (
    Finding,
    Rule,
    get_rules,
    lint_paths,
    lint_source,
)
from .locks import (
    LockNotHeld,
    LockOrderInversion,
    LockSanitizer,
    SanitizableLock,
    active_lock_sanitizer,
    disable_lock_sanitizer,
    enable_lock_sanitizer,
    make_lock,
)
from .protocol import (
    check_protocol_drift,
    client_call_sites,
    diff_protocol,
    server_dispatch_table,
)
from .retrace import CompileEvent, RetraceError, RetraceGuard
from .rules.held_lock_blocking import check_lock_order

__all__ = [
    "CompileEvent",
    "DeletedBufferProxy",
    "DonationSanitizer",
    "Finding",
    "LockNotHeld",
    "LockOrderInversion",
    "LockSanitizer",
    "RetraceError",
    "RetraceGuard",
    "Rule",
    "SanitizableLock",
    "UseAfterDonateError",
    "active_lock_sanitizer",
    "check_lock_order",
    "check_protocol_drift",
    "client_call_sites",
    "diff_protocol",
    "disable_lock_sanitizer",
    "enable_lock_sanitizer",
    "get_rules",
    "lint_paths",
    "lint_source",
    "make_lock",
    "server_dispatch_table",
]
