"""ffcheck — JAX/TPU hazard analysis for the serving stack.

FlexFlow's pitch is that the *runtime* keeps the execution plan optimal
(SURVEY.md: Unity's simulator-guided search, SpecInfer's batched
verify). In a JAX port the equivalent silent killers are unplanned XLA
recompiles, host↔device syncs inside the decode loop, and
use-after-donate on the KV cache — none of which fail a test until they
cost a 100x step-latency spike (or corrupted pages) in production.
This package is the correctness tooling for that class of bug, in
three parts:

1. **AST lint** (:mod:`.lint` + :mod:`.rules`) — static rules over the
   package, run by ``scripts/ffcheck.py`` and the tier-1 guard
   ``tests/test_ffcheck.py`` (zero unsuppressed findings required).
2. **Retrace sentinel** (:mod:`.retrace` — :class:`RetraceGuard`) —
   records every compile of every engine step program via the
   ``InferenceEngine._jit`` chokepoint; strict mode raises
   :class:`RetraceError` on any recompile of a known step key.
3. **Donation sanitizer** (:mod:`.donation` —
   :class:`DonationSanitizer`) — after every donated dispatch the old
   cache pytree is poisoned (:class:`DeletedBufferProxy`), so
   use-after-donate — the PR-2 page-corruption bug class — raises
   :class:`UseAfterDonateError` at the faulty read.

Runtime sanitizers are enabled per engine with
``ServingConfig(sanitizers=("retrace", "donation"))`` (or
``"retrace-warn"`` for record-only), or globally with
``FF_SANITIZERS=retrace,donation`` in the environment.

Rule catalog
------------
========  ====================  ==============================================
Code      Slug                  Hazard
========  ====================  ==============================================
FF101     host-sync             ``jax.device_get``/``np.asarray``/``.item()``/
                                ``float(tracer)`` reachable inside jit-traced
                                code: a forced device sync per step, or host
                                data constant-folded into the program.
FF102     tracer-control-flow   Python ``if``/``while``/``assert`` on a value
                                computed by ``jnp``/``jax.lax`` in traced
                                code: concretization error, or one branch
                                baked in forever.
FF103     weak-dtype            ``jnp.asarray``/``jnp.array`` without an
                                explicit dtype: the abstract signature follows
                                the caller's host types — weak-type promotion
                                (or an int-list → np.int32 flip) keys a
                                retrace of every jitted consumer.
FF104     unordered-iteration   Iterating a ``set``/``frozenset`` (or
                                ``vars()``/``globals()``) in traced code: the
                                compiled program depends on hash order.
FF105     missing-donation      ``jax.jit`` of a function threading a
                                ``cache``/``opt_state`` buffer without
                                ``donate_argnums``: a full buffer copy per
                                step.
FF106     static-hashability    ``static_argnums``/``static_argnames`` whose
                                parameter defaults/annotations are unhashable
                                (list/dict/set): jit raises, or retraces per
                                call.
FF107     sync-transfer         ``jax.device_get``/blocking
                                ``jax.device_put``/``block_until_ready`` in
                                host-side serve code reachable from the
                                scheduler's hot path: one stray sync stalls
                                every decode step — hierarchical-KV spill
                                traffic must stay async (copy_to_host_async
                                + harvest at the flush sync point).
FF108     tracer-sync           A device sync (``.item()``/``.tolist()``/
                                ``np.asarray``/``jax.device_get``…) inside a
                                tracer ``event()``/``span()`` ARGUMENT on the
                                serving hot path: telemetry reading an
                                un-flushed array stalls the very pipeline it
                                measures — the observability layer must
                                record host state (or defer to a flush).
========  ====================  ==============================================

Suppressions: ``# ffcheck: disable=FF101 -- reason`` on (or alone
above) the offending line; ``# ffcheck: disable-file=RULE`` for a whole
file; rule codes and slugs both work, ``all`` disables everything.

Standalone::

    python scripts/ffcheck.py                  # lint flexflow_tpu/
    python scripts/ffcheck.py --diff main      # only files changed vs main
    python scripts/ffcheck.py --list-rules
"""
from __future__ import annotations

from .donation import (
    DeletedBufferProxy,
    DonationSanitizer,
    UseAfterDonateError,
)
from .lint import (
    Finding,
    Rule,
    get_rules,
    lint_paths,
    lint_source,
)
from .retrace import CompileEvent, RetraceError, RetraceGuard

__all__ = [
    "CompileEvent",
    "DeletedBufferProxy",
    "DonationSanitizer",
    "Finding",
    "RetraceError",
    "RetraceGuard",
    "Rule",
    "UseAfterDonateError",
    "get_rules",
    "lint_paths",
    "lint_source",
]
