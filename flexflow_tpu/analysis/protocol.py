"""Wire-protocol drift checker — statically diffs the replica server's
dispatch table against the client's RPC call sites.

The RPC protocol has two independent truths: ``ReplicaServerCore``'s
``_m_<method>`` handlers (``server.py``) and ``RemoteReplica``'s
``_rpc("method", {...})`` / ``_AsyncCall(self, "method", {...})`` call
sites (``remote.py``). Nothing ties them together at import time — a
renamed method, a dropped argument or a removed envelope field only
surfaces when a subprocess test exercises that RPC, often 20 minutes
into a chaos suite. This checker makes skew a ``scripts/ffcheck.py``
failure instead:

* **methods** — every client-called method must have a ``_m_<name>``
  handler (server-only entry points — ``hello``, ``reset_rate``,
  ``shutdown`` — are allowed to have no client call site);
* **arity** — for call sites passing a dict literal, the handler's
  REQUIRED args (``args["k"]`` subscripts) must all be supplied, and
  every supplied key must be one the handler reads (``args["k"]`` or
  ``args.get("k")``) — an ignored argument is drift in the making;
* **envelope fields** — keys the client REQUIRES from the response
  (``res["k"]`` subscripts on the variable bound to the call, or
  directly on the call) must be keys the handler's return provides
  (``self._envelope(k=...)`` keywords + the envelope's own
  ``telemetry``/``updates``, or dict-literal keys). ``res.get(...)``
  reads are optional by construction and not checked.

Everything is AST-only (never imports the serving stack — safe on
broken trees, no JAX needed), same as the lint rules.
"""
from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional, Set, Tuple

#: dispatch-table entries with no RemoteReplica call site by design:
#: ``hello`` (the subprocess handshake the spawner speaks directly),
#: ``reset_rate`` (client-side mirror reset only), ``shutdown`` (the
#: spawner's teardown RPC).
SERVER_ONLY_METHODS = frozenset({"hello", "reset_rate", "shutdown"})

#: keys every ``_envelope()`` response carries regardless of extras
ENVELOPE_BASE_KEYS = frozenset({"telemetry", "updates"})


@dataclasses.dataclass
class HandlerSpec:
    """One ``_m_<name>`` handler's statically visible contract."""

    method: str
    line: int
    required_args: Set[str]
    optional_args: Set[str]
    result_keys: Optional[Set[str]]  # None = not statically knowable


@dataclasses.dataclass
class CallSite:
    """One client RPC call site."""

    method: str
    line: int
    path: str
    arg_keys: Optional[Set[str]]      # None = non-literal args dict
    required_reads: Set[str]          # res["k"] subscripts


def _str_const(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _dict_literal_keys(node: ast.AST) -> Optional[Set[str]]:
    """String keys of a dict literal (None when not a literal or any
    key is dynamic — ``**spread`` etc.)."""
    if not isinstance(node, ast.Dict):
        return None
    keys: Set[str] = set()
    for k in node.keys:
        s = _str_const(k) if k is not None else None
        if s is None:
            return None
        keys.add(s)
    return keys


# ---------------------------------------------------------------------------
# server side


def _args_usage(fn: ast.AST, param: str) -> Tuple[Set[str], Set[str]]:
    """(required, optional) keys read off the ``args`` parameter."""
    required: Set[str] = set()
    optional: Set[str] = set()
    for node in ast.walk(fn):
        if (
            isinstance(node, ast.Subscript)
            and isinstance(node.value, ast.Name)
            and node.value.id == param
        ):
            key = _str_const(node.slice)
            if key is not None:
                required.add(key)
        elif (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "get"
            and isinstance(node.func.value, ast.Name)
            and node.func.value.id == param
            and node.args
        ):
            key = _str_const(node.args[0])
            if key is not None:
                optional.add(key)
    return required, optional


def _handler_result_keys(fn: ast.AST) -> Optional[Set[str]]:
    """Union of keys over every ``return`` in the handler: dict
    literals contribute their keys; ``self._envelope(**extra)``
    contributes the base envelope keys + keyword names. ``None`` when
    any return is opaque."""
    keys: Set[str] = set()
    for node in ast.walk(fn):
        if not isinstance(node, ast.Return) or node.value is None:
            continue
        v = node.value
        lit = _dict_literal_keys(v)
        if lit is not None:
            keys |= lit
            continue
        if (
            isinstance(v, ast.Call)
            and isinstance(v.func, ast.Attribute)
            and v.func.attr == "_envelope"
        ):
            kw_names = {k.arg for k in v.keywords}
            if None in kw_names:  # **spread — opaque
                return None
            keys |= ENVELOPE_BASE_KEYS | {k for k in kw_names if k}
            continue
        return None
    return keys


def server_dispatch_table(source: str) -> Dict[str, HandlerSpec]:
    """Every ``_m_<name>`` method of ``ReplicaServerCore``."""
    tree = ast.parse(source)
    table: Dict[str, HandlerSpec] = {}
    for cls in ast.walk(tree):
        if not isinstance(cls, ast.ClassDef) \
                or cls.name != "ReplicaServerCore":
            continue
        for stmt in cls.body:
            if not isinstance(stmt, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            if not stmt.name.startswith("_m_"):
                continue
            params = [a.arg for a in stmt.args.args if a.arg != "self"]
            args_param = params[0] if params else "args"
            required, optional = _args_usage(stmt, args_param)
            table[stmt.name[3:]] = HandlerSpec(
                method=stmt.name[3:],
                line=stmt.lineno,
                required_args=required,
                optional_args=optional,
                result_keys=_handler_result_keys(stmt),
            )
    return table


# ---------------------------------------------------------------------------
# client side


def _rpc_call_method(node: ast.Call) -> Optional[Tuple[str, ast.AST]]:
    """``x._rpc("m", ARGS)`` or ``_AsyncCall(owner, "m", ARGS)`` ->
    (method, ARGS node); None otherwise (dynamic method names — the
    generic ``_rpc`` body itself — are skipped)."""
    f = node.func
    if isinstance(f, ast.Attribute) and f.attr == "_rpc" and node.args:
        m = _str_const(node.args[0])
        if m is not None:
            return m, (node.args[1] if len(node.args) > 1 else None)
    if (
        isinstance(f, ast.Name) and f.id == "_AsyncCall"
        and len(node.args) >= 2
    ):
        m = _str_const(node.args[1])
        if m is not None:
            return m, (node.args[2] if len(node.args) > 2 else None)
    return None


def _required_reads(fn: ast.AST, call: ast.Call,
                    parents: Dict[ast.AST, ast.AST]) -> Set[str]:
    """Keys the client demands of this call's response: a direct
    subscript on the call (``self._rpc(...)["score"]``), or
    ``res["k"]`` subscripts where ``res`` is the name the call was
    assigned to in the same function."""
    reads: Set[str] = set()
    parent = parents.get(call)
    if isinstance(parent, ast.Subscript) and parent.value is call:
        key = _str_const(parent.slice)
        if key is not None:
            reads.add(key)
        return reads
    var: Optional[str] = None
    if isinstance(parent, ast.Assign) and len(parent.targets) == 1 \
            and isinstance(parent.targets[0], ast.Name):
        var = parent.targets[0].id
    if var is None:
        return reads
    for node in ast.walk(fn):
        if (
            isinstance(node, ast.Subscript)
            and isinstance(node.value, ast.Name)
            and node.value.id == var
        ):
            key = _str_const(node.slice)
            if key is not None:
                reads.add(key)
    return reads


def client_call_sites(source: str, path: str = "remote.py"
                      ) -> List[CallSite]:
    """Every literal-method RPC call site in a client file."""
    tree = ast.parse(source)
    parents: Dict[ast.AST, ast.AST] = {}
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            parents[child] = node
    fns = [
        n for n in ast.walk(tree)
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
    ]
    sites: List[CallSite] = []
    seen: Set[int] = set()
    for fn in fns:
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call) or id(node) in seen:
                continue
            hit = _rpc_call_method(node)
            if hit is None:
                continue
            seen.add(id(node))
            method, args_node = hit
            sites.append(CallSite(
                method=method,
                line=node.lineno,
                path=path,
                arg_keys=(
                    _dict_literal_keys(args_node)
                    if args_node is not None else set()
                ),
                required_reads=_required_reads(fn, node, parents),
            ))
    return sites


# ---------------------------------------------------------------------------
# the diff


def diff_protocol(
    server_source: str,
    client_sources: Dict[str, str],
) -> List[str]:
    """Every drift between the dispatch table and the client call
    sites, as human-readable problem lines (empty = no drift)."""
    table = server_dispatch_table(server_source)
    problems: List[str] = []
    if not table:
        return ["protocol: no ReplicaServerCore dispatch table found"]
    called: Set[str] = set()
    for path, src in client_sources.items():
        for site in client_call_sites(src, path):
            called.add(site.method)
            where = f"{site.path}:{site.line}"
            spec = table.get(site.method)
            if spec is None:
                problems.append(
                    f"{where}: client calls {site.method!r} but the "
                    "server dispatch table has no _m_"
                    f"{site.method} handler"
                )
                continue
            if site.arg_keys is not None:
                missing = spec.required_args - site.arg_keys
                if missing:
                    problems.append(
                        f"{where}: {site.method!r} call omits required "
                        f"arg(s) {sorted(missing)} (server reads "
                        f"args[...] at server.py:{spec.line})"
                    )
                unknown = site.arg_keys - spec.required_args \
                    - spec.optional_args
                if unknown:
                    problems.append(
                        f"{where}: {site.method!r} call passes arg(s) "
                        f"{sorted(unknown)} the handler never reads — "
                        "dead wire weight or a renamed field"
                    )
            if spec.result_keys is not None and site.required_reads:
                absent = site.required_reads - spec.result_keys
                if absent:
                    problems.append(
                        f"{where}: client requires response key(s) "
                        f"{sorted(absent)} of {site.method!r} but the "
                        "handler's returns only provide "
                        f"{sorted(spec.result_keys)}"
                    )
    for method in sorted(set(table) - called - SERVER_ONLY_METHODS):
        problems.append(
            f"server.py:{table[method].line}: handler _m_{method} has "
            "no client call site and is not in SERVER_ONLY_METHODS — "
            "dead protocol surface or a renamed client call"
        )
    return problems


def check_protocol_drift(server_path: str,
                         client_paths: List[str]) -> List[str]:
    """File-path front door for :func:`diff_protocol` (what
    ``scripts/ffcheck.py`` calls)."""
    with open(server_path, "r") as fh:
        server_src = fh.read()
    client_sources: Dict[str, str] = {}
    for p in client_paths:
        with open(p, "r") as fh:
            client_sources[p] = fh.read()
    return diff_protocol(server_src, client_sources)
