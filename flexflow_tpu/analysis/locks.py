"""Lock sanitizer — runtime lock-discipline checking for the threaded
cluster stack (the dynamic half of the ffcheck concurrency rules).

The static rules (FF110 unguarded-shared-state, FF111
held-lock-blocking-call) prove lock discipline about code they can SEE;
this module proves it about executions. Every lock the transport/server
stack takes is a :class:`SanitizableLock` built through :func:`make_lock`
— a zero-overhead pass-through to ``threading.Lock`` until a
:class:`LockSanitizer` is enabled, at which point every acquisition
records:

* the **per-thread held stack** (which locks this thread holds, in
  acquisition order) — :meth:`SanitizableLock.held_by_current_thread`
  and :meth:`SanitizableLock.assert_held` make "caller holds the lock"
  contracts (``*_locked`` methods, guarded ClusterStats increments)
  checkable at test time instead of by comment;
* the **global acquisition-order graph**: acquiring B while holding A
  records the edge A→B with the acquiring stack. The moment any thread
  acquires A while holding B — the classic deadlock recipe, each order
  observed on its own thread so no single run ever actually deadlocks —
  the sanitizer flags a :class:`LockOrderInversion` carrying BOTH
  stacks (strict mode raises at the second acquisition; record mode
  appends to :attr:`LockSanitizer.findings`).

Enable per engine with ``ServingConfig(sanitizers=("locks",))`` (or
``FF_SANITIZERS=locks``), or directly with
:func:`enable_lock_sanitizer` in a test. The sanitizer is process-
global (module-level locks like the transport's ``_STATS_LOCK`` must
participate), so tests disable it in a ``finally``. The instrumented
path takes no extra locks of its own beyond one internal mutex on the
order graph — enabling the sanitizer can reorder nothing, which is
what the sanitizer-on == sanitizer-off bitwise suites assert.
"""
from __future__ import annotations

import threading
import traceback
from typing import Dict, List, Optional, Tuple

__all__ = [
    "LockNotHeld",
    "LockOrderInversion",
    "LockSanitizer",
    "SanitizableLock",
    "active_lock_sanitizer",
    "disable_lock_sanitizer",
    "enable_lock_sanitizer",
    "make_lock",
]


class LockOrderInversion(RuntimeError):
    """Two locks were acquired in both orders (A→B on one code path,
    B→A on another) — a latent deadlock. Carries both acquisition
    stacks in the message."""


class LockNotHeld(RuntimeError):
    """An ``assert_held`` contract failed: the current thread touched
    guarded shared state without holding the guarding lock."""


def _stack_summary(skip: int = 3, limit: int = 6) -> str:
    """A short culprit stack (this module's frames dropped)."""
    frames = traceback.extract_stack()[:-skip]
    frames = [f for f in frames if "analysis/locks" not in f.filename]
    return " <- ".join(
        f"{f.name}({f.filename.rsplit('/', 1)[-1]}:{f.lineno})"
        for f in reversed(frames[-limit:])
    )


class LockSanitizer:
    """Recorder + checker behind every :class:`SanitizableLock` while
    enabled (see module docstring). ``strict=True`` raises on the
    acquisition that completes an inversion; ``strict=False`` records
    findings for a post-run assert (``sanitizer.findings == []``)."""

    def __init__(self, strict: bool = True):
        self.strict = strict
        #: human-readable inversion/contract findings (record mode)
        self.findings: List[str] = []
        #: total instrumented acquisitions (test introspection)
        self.acquisitions = 0
        self._tls = threading.local()
        # (held, acquired) -> stack summary of the first observation;
        # a plain threading.Lock (not Sanitizable — the sanitizer must
        # not instrument itself) guards the graph and counters.
        self._edges: Dict[Tuple[str, str], str] = {}
        self._mutex = threading.Lock()

    # -- per-thread held stack ------------------------------------------

    def held(self) -> Tuple[str, ...]:
        return tuple(getattr(self._tls, "stack", ()))

    def _push(self, name: str) -> None:
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        stack.append(name)

    def _pop(self, name: str) -> None:
        stack = getattr(self._tls, "stack", None)
        if stack and name in stack:
            # remove the innermost occurrence — out-of-order releases
            # (lock.release() without context managers) stay correct
            for i in range(len(stack) - 1, -1, -1):
                if stack[i] == name:
                    del stack[i]
                    break

    # -- order graph -----------------------------------------------------

    def note_acquire(self, name: str) -> None:
        held = self.held()
        site = _stack_summary()
        with self._mutex:
            self.acquisitions += 1
            problem = None
            for h in held:
                if h == name:
                    continue
                edge = (h, name)
                if edge not in self._edges:
                    self._edges[edge] = site
                rev = self._edges.get((name, h))
                if rev is not None and (h, name) != (name, h):
                    problem = (
                        f"lock-order inversion: {h!r} -> {name!r} at "
                        f"[{site}] but {name!r} -> {h!r} was taken at "
                        f"[{rev}]"
                    )
            if problem is not None:
                self.findings.append(problem)
        self._push(name)
        if problem is not None and self.strict:
            raise LockOrderInversion(problem)

    def note_release(self, name: str) -> None:
        self._pop(name)

    def check_held(self, name: str, what: str = "") -> None:
        if name in self.held():
            return
        msg = (
            f"unguarded access{f' to {what}' if what else ''}: thread "
            f"{threading.current_thread().name!r} does not hold "
            f"{name!r} (held: {list(self.held())}) at "
            f"[{_stack_summary()}]"
        )
        with self._mutex:
            self.findings.append(msg)
        if self.strict:
            raise LockNotHeld(msg)

    def report(self) -> str:
        with self._mutex:
            edges = len(self._edges)
            lines = list(self.findings)
        head = (
            f"lock sanitizer: {self.acquisitions} acquisitions, "
            f"{edges} order edges, {len(lines)} finding(s)"
        )
        return "\n".join([head] + lines)


#: process-global active sanitizer; None = every SanitizableLock is a
#: plain pass-through (the zero-overhead default)
_ACTIVE: Optional[LockSanitizer] = None


def enable_lock_sanitizer(strict: bool = True) -> LockSanitizer:
    """Install (or return the already-active) global sanitizer."""
    global _ACTIVE
    if _ACTIVE is None:
        _ACTIVE = LockSanitizer(strict=strict)
    return _ACTIVE


def disable_lock_sanitizer() -> Optional[LockSanitizer]:
    """Uninstall and return the active sanitizer (None if none was)."""
    global _ACTIVE
    active, _ACTIVE = _ACTIVE, None
    return active


def active_lock_sanitizer() -> Optional[LockSanitizer]:
    return _ACTIVE


class SanitizableLock:
    """``threading.Lock`` with a name and an instrumentation hook. The
    un-instrumented path is a straight delegate (one ``is None`` check
    per acquire); with a sanitizer active every acquire/release feeds
    the held-stack + order-graph machinery above."""

    __slots__ = ("name", "_lock")

    def __init__(self, name: str):
        self.name = name
        self._lock = threading.Lock()

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        ok = self._lock.acquire(blocking, timeout)
        if ok and _ACTIVE is not None:
            _ACTIVE.note_acquire(self.name)
        return ok

    def release(self) -> None:
        if _ACTIVE is not None:
            _ACTIVE.note_release(self.name)
        self._lock.release()

    def locked(self) -> bool:
        return self._lock.locked()

    def __enter__(self) -> "SanitizableLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def held_by_current_thread(self) -> bool:
        """Only answerable with a sanitizer active (False otherwise —
        plain locks don't track owners)."""
        return _ACTIVE is not None and self.name in _ACTIVE.held()

    def assert_held(self, what: str = "") -> None:
        """The runtime form of a ``*_locked`` naming contract: no-op
        without a sanitizer; with one, flags (strict: raises
        :class:`LockNotHeld`) when the current thread does not hold
        this lock."""
        if _ACTIVE is not None:
            _ACTIVE.check_held(self.name, what)

    def __repr__(self) -> str:
        return f"SanitizableLock({self.name!r})"


def make_lock(name: str) -> SanitizableLock:
    """The one constructor the serving stack uses for every lock that
    guards cross-thread state — always sanitizable, instrumented only
    while a sanitizer is enabled."""
    return SanitizableLock(name)
