"""Loss functions.

TPU-native equivalents of the reference loss ops (reference
``src/loss_functions/loss_functions.cc:121-200`` — categorical/sparse
cross-entropy, MSE, identity, each with a hand-written backward kernel).
Here each loss is a pure scalar function; backward comes from autodiff.
The reference scales gradients by 1/batch (and by replica count under
parameter-server sync); with jnp.mean + GSPMD gradient psum we get the
same normalisation for free.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

SPARSE_CATEGORICAL_CROSSENTROPY = "sparse_categorical_crossentropy"
CATEGORICAL_CROSSENTROPY = "categorical_crossentropy"
MEAN_SQUARED_ERROR = "mean_squared_error"
IDENTITY = "identity"


def sparse_categorical_crossentropy(preds, labels, from_logits=True):
    """labels: int class ids; preds: (..., C) logits, or probabilities when
    the graph ends in an explicit softmax op (the reference asserts a
    softmax feeds this loss and differentiates through probs)."""
    labels = labels.reshape(preds.shape[:-1]).astype(jnp.int32)
    x = preds.astype(jnp.float32)
    if from_logits:
        logp = jax.nn.log_softmax(x, axis=-1)
    else:
        logp = jnp.log(jnp.clip(x, 1e-12, 1.0))
    nll = -jnp.take_along_axis(logp, labels[..., None], axis=-1)[..., 0]
    return nll.mean()


def categorical_crossentropy(probs_or_logits, labels, from_logits=False):
    """labels: one-hot/prob targets with same shape as predictions."""
    x = probs_or_logits.astype(jnp.float32)
    if from_logits:
        logp = jax.nn.log_softmax(x, axis=-1)
    else:
        logp = jnp.log(jnp.clip(x, 1e-12, 1.0))
    return -(labels.astype(jnp.float32) * logp).sum(axis=-1).mean()


def mean_squared_error(preds, labels):
    d = preds.astype(jnp.float32) - labels.astype(jnp.float32)
    return (d * d).mean()


def identity(preds, labels):
    """Pass-through loss: mean of predictions (reference IDENTITY loss used
    when the graph computes its own loss)."""
    del labels
    return preds.astype(jnp.float32).mean()


_LOSSES = {
    SPARSE_CATEGORICAL_CROSSENTROPY: sparse_categorical_crossentropy,
    CATEGORICAL_CROSSENTROPY: categorical_crossentropy,
    MEAN_SQUARED_ERROR: mean_squared_error,
    "mse": mean_squared_error,
    IDENTITY: identity,
}


def get_loss(name: str, from_logits: bool = True):
    fn = _LOSSES[name]
    if "crossentropy" in name:
        import functools

        return functools.partial(fn, from_logits=from_logits)
    return fn
