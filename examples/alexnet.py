"""AlexNet data-parallel training — the reference's canonical CNN app
(reference ``examples/cpp/AlexNet/alexnet.cc:40-90`` builds the same
conv/pool/dense stack layer by layer through the FFModel API).

Defaults reproduce the reference geometry (3x229x229 inputs); the
``image_size``/``width_mult`` knobs scale it down so the same script
doubles as a fast smoke test on the virtual CPU mesh.

Run: python examples/alexnet.py [--devices N] [--image-size 229]
"""
import argparse

import numpy as np


def synthetic_images(n, image_size, num_classes, seed=0):
    rng = np.random.default_rng(seed)
    y = rng.integers(0, num_classes, size=n).astype(np.int32)
    # per-class prototype images + noise (separable, like the MNIST demo)
    protos = rng.normal(size=(num_classes, 3, image_size, image_size))
    x = protos[y] + 0.4 * rng.normal(size=(n, 3, image_size, image_size))
    return x.astype(np.float32), y


def build(model, batch_size, image_size=229, num_classes=10, width_mult=1.0):
    """The reference stack (alexnet.cc): 5 convs, 3 pools, 3 denses."""
    w = lambda c: max(4, int(c * width_mult))
    t = model.create_tensor((batch_size, 3, image_size, image_size), name="x")
    t = model.conv2d(t, w(64), 11, 11, 4, 4, 2, 2, activation="relu")
    t = model.pool2d(t, 3, 3, 2, 2)
    t = model.conv2d(t, w(192), 5, 5, 1, 1, 2, 2, activation="relu")
    t = model.pool2d(t, 3, 3, 2, 2)
    t = model.conv2d(t, w(384), 3, 3, 1, 1, 1, 1, activation="relu")
    t = model.conv2d(t, w(256), 3, 3, 1, 1, 1, 1, activation="relu")
    t = model.conv2d(t, w(256), 3, 3, 1, 1, 1, 1, activation="relu")
    t = model.pool2d(t, 3, 3, 2, 2)
    t = model.flat(t)
    t = model.dense(t, w(4096), activation="relu")
    t = model.dense(t, w(4096), activation="relu")
    t = model.dense(t, num_classes)
    return model.softmax(t)


def main(num_devices=1, epochs=2, batch_size=32, image_size=64,
         width_mult=0.125, num_classes=10, n_samples=256):
    import flexflow_tpu as ff

    cfg = ff.FFConfig(
        batch_size=batch_size, epochs=epochs, num_devices=num_devices
    )
    model = ff.FFModel(cfg)
    build(model, batch_size, image_size, num_classes, width_mult)
    model.compile(
        optimizer=ff.AdamOptimizer(lr=1e-3),
        loss_type="sparse_categorical_crossentropy",
        metrics=("accuracy",),
    )
    x, y = synthetic_images(n_samples, image_size, num_classes)
    model.fit(x, y)
    final = model.evaluate(x, y)
    print("final:", final)
    return final


if __name__ == "__main__":
    p = argparse.ArgumentParser()
    p.add_argument("--devices", type=int, default=1)
    p.add_argument("--epochs", type=int, default=2)
    p.add_argument("--image-size", type=int, default=64)
    p.add_argument("--width-mult", type=float, default=0.125)
    a = p.parse_args()
    main(a.devices, a.epochs, image_size=a.image_size, width_mult=a.width_mult)
