"""Mixture-of-experts training with expert parallelism — the
reference's MoE example (reference ``examples/cpp/mixture_of_experts/
moe.cc:100-130``: top_k gate → group_by → experts → aggregate with a
load-balance term).

Run: python examples/moe_train.py [--devices N] [--ep N]
"""
import argparse

import numpy as np


def main(num_devices=1, ep=1, epochs=2):
    import flexflow_tpu as ff

    bs = 32 * max(1, num_devices // max(1, ep))
    cfg = ff.FFConfig(
        batch_size=bs, epochs=epochs, num_devices=num_devices,
        expert_parallelism_degree=ep,
    )
    model = ff.FFModel(cfg)
    t = model.create_tensor((bs, 32), name="x")
    t = model.moe(t, num_experts=4 * max(1, ep), top_k=2, expert_hidden=64)
    t = model.dense(t, 8)
    t = model.softmax(t)
    model.compile(optimizer=ff.AdamOptimizer(lr=0.003))

    rng = np.random.default_rng(0)
    y = rng.integers(0, 8, size=1024).astype(np.int32)
    protos = rng.normal(size=(8, 32)).astype(np.float32)
    x = (protos[y] + 0.2 * rng.normal(size=(1024, 32))).astype(np.float32)
    model.fit(x, y)
    final = model.evaluate(x, y)
    print("final:", final)
    return final


if __name__ == "__main__":
    p = argparse.ArgumentParser()
    p.add_argument("--devices", type=int, default=1)
    p.add_argument("--ep", type=int, default=1)
    a = p.parse_args()
    main(a.devices, a.ep)
