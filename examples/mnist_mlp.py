"""MNIST-style MLP training — the reference's canonical smoke example
(reference ``examples/python/native/mnist_mlp.py`` +
``scripts/mnist_mlp_run.sh``). The container has no network egress, so
the data is a synthetic MNIST-shaped classification set; swap in real
MNIST arrays to reproduce the reference run exactly.

Run: python examples/mnist_mlp.py [--devices N]
"""
import argparse

import numpy as np


def synthetic_mnist(n=2048, seed=0):
    """784-dim, 10 classes, linearly-separable-ish clusters."""
    rng = np.random.default_rng(seed)
    y = rng.integers(0, 10, size=n).astype(np.int32)
    protos = rng.normal(size=(10, 784)).astype(np.float32)
    x = protos[y] + 0.3 * rng.normal(size=(n, 784)).astype(np.float32)
    return x.astype(np.float32), y


def main(num_devices=1, epochs=2, batch_size=64, profiling=False):
    import flexflow_tpu as ff

    cfg = ff.FFConfig(
        batch_size=batch_size, epochs=epochs, num_devices=num_devices,
        profiling=profiling,
    )
    model = ff.FFModel(cfg)
    t = model.create_tensor((batch_size, 784), name="x")
    t = model.dense(t, 512, activation="relu")
    t = model.dense(t, 512, activation="relu")
    t = model.dense(t, 10)
    t = model.softmax(t)
    model.compile(
        optimizer=ff.SGDOptimizer(lr=0.05),
        loss_type="sparse_categorical_crossentropy",
        metrics=("accuracy",),
    )
    x, y = synthetic_mnist()
    perf = model.fit(x, y)
    final = model.evaluate(x, y)
    print("final:", final)
    return final


if __name__ == "__main__":
    p = argparse.ArgumentParser()
    p.add_argument("--devices", type=int, default=1)
    p.add_argument("--epochs", type=int, default=2)
    p.add_argument("--profiling", action="store_true")
    a = p.parse_args()
    main(a.devices, a.epochs, profiling=a.profiling)
