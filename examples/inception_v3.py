"""InceptionV3-style training app (reference
``examples/cpp/InceptionV3/inception.cc:26-120``: InceptionA/B/C
multi-branch conv modules concatenated on the channel dim, built through
the FFModel API). Scaled-down defaults so the CPU mesh can smoke it;
``--full`` builds closer-to-paper widths.

Run: python examples/inception_v3.py [--devices N]
"""
import argparse

import numpy as np


def inception_a(model, t, w, pool_features):
    """Four branches: 1x1 / 1x1+5x5 / 1x1+3x3+3x3 / avgpool+1x1
    (reference inception.cc:26-48), widths scaled by w."""
    b1 = model.conv2d(t, 4 * w, 1, 1, 1, 1, 0, 0, activation="relu")
    b2 = model.conv2d(t, 3 * w, 1, 1, 1, 1, 0, 0, activation="relu")
    b2 = model.conv2d(b2, 4 * w, 5, 5, 1, 1, 2, 2, activation="relu")
    b3 = model.conv2d(t, 4 * w, 1, 1, 1, 1, 0, 0, activation="relu")
    b3 = model.conv2d(b3, 6 * w, 3, 3, 1, 1, 1, 1, activation="relu")
    b3 = model.conv2d(b3, 6 * w, 3, 3, 1, 1, 1, 1, activation="relu")
    b4 = model.pool2d(t, 3, 3, 1, 1, 1, 1, pool_type="avg")
    b4 = model.conv2d(b4, pool_features, 1, 1, 1, 1, 0, 0, activation="relu")
    return model.concat([b1, b2, b3, b4], axis=1)


def inception_b(model, t, w):
    """Grid-size reduction: stride-2 branches + maxpool
    (reference inception.cc:50-62)."""
    b1 = model.conv2d(t, 12 * w, 3, 3, 2, 2, 0, 0, activation="relu")
    b2 = model.conv2d(t, 4 * w, 1, 1, 1, 1, 0, 0, activation="relu")
    b2 = model.conv2d(b2, 6 * w, 3, 3, 1, 1, 1, 1, activation="relu")
    b2 = model.conv2d(b2, 6 * w, 3, 3, 2, 2, 0, 0, activation="relu")
    b3 = model.pool2d(t, 3, 3, 2, 2, 0, 0, pool_type="max")
    return model.concat([b1, b2, b3], axis=1)


def inception_c(model, t, w):
    """Factorized 7x7 branches approximated at reduced width with
    (1x7)(7x1) pairs (reference inception.cc:64-100)."""
    b1 = model.conv2d(t, 6 * w, 1, 1, 1, 1, 0, 0, activation="relu")
    b2 = model.conv2d(t, 4 * w, 1, 1, 1, 1, 0, 0, activation="relu")
    b2 = model.conv2d(b2, 4 * w, 1, 7, 1, 1, 0, 3, activation="relu")
    b2 = model.conv2d(b2, 6 * w, 7, 1, 1, 1, 3, 0, activation="relu")
    b3 = model.pool2d(t, 3, 3, 1, 1, 1, 1, pool_type="avg")
    b3 = model.conv2d(b3, 6 * w, 1, 1, 1, 1, 0, 0, activation="relu")
    return model.concat([b1, b2, b3], axis=1)


def build(model, batch_size, image_size=32, num_classes=10, w=4):
    t = model.create_tensor((batch_size, 3, image_size, image_size), name="x")
    t = model.conv2d(t, 2 * w, 3, 3, 1, 1, 1, 1, activation="relu")
    t = inception_a(model, t, w, pool_features=2 * w)
    t = inception_b(model, t, w)
    t = inception_c(model, t, w)
    t = model.mean(t, axes=(2, 3))
    t = model.dense(t, num_classes)
    return model.softmax(t)


def main(num_devices=1, epochs=2, batch_size=16, image_size=16, w=2,
         n_samples=128, num_classes=10):
    import flexflow_tpu as ff

    cfg = ff.FFConfig(
        batch_size=batch_size, epochs=epochs, num_devices=num_devices
    )
    model = ff.FFModel(cfg)
    build(model, batch_size, image_size, num_classes, w)
    model.compile(
        optimizer=ff.SGDOptimizer(lr=0.02, momentum=0.9),
        loss_type="sparse_categorical_crossentropy",
        metrics=("accuracy",),
    )
    rng = np.random.default_rng(0)
    y = rng.integers(0, num_classes, size=n_samples).astype(np.int32)
    x = rng.normal(size=(n_samples, 3, image_size, image_size)).astype(
        np.float32
    )
    x += y[:, None, None, None].astype(np.float32) / 8
    perf = model.fit(x, y)
    return perf.averages()


if __name__ == "__main__":
    p = argparse.ArgumentParser()
    p.add_argument("--devices", type=int, default=1)
    p.add_argument("--epochs", type=int, default=2)
    a = p.parse_args()
    print(main(num_devices=a.devices, epochs=a.epochs))
