"""Mixtral sparse-MoE serving demo — expert-parallel continuous
batching through the high-level ``LLM`` API (beyond the reference's
apps: its serving models are dense-only; expert parallelism here is the
serving-side analog of ``examples/moe_train.py``). Uses a tiny
randomly-initialised model so it runs anywhere; point ``--model-dir``
at a local HF Mixtral checkpoint directory to serve real weights.

Run: python examples/mixtral_serve.py [--model-dir PATH] [--ep N] [--tp N]
"""
import argparse


def main(model_dir=None, ep=1, tp=1, quantization=None):
    import jax
    import jax.numpy as jnp

    from flexflow_tpu.core.mesh import MachineSpec
    from flexflow_tpu.models import mixtral
    from flexflow_tpu.serve import ServingConfig
    from flexflow_tpu.serve.llm import LLM

    n = ep * tp
    mesh = MachineSpec.from_degrees(
        n, tensor=tp, expert=ep
    ).make_mesh(jax.devices()[:n])

    if model_dir:
        m = LLM.from_pretrained(model_dir, mesh=mesh)
        prompts = ["The capital of France is"]
    else:
        cfg = mixtral.tiny(dtype=jnp.float32)
        m = LLM(mixtral, cfg, mesh=mesh)
        prompts = [[3, 17, 91, 42, 7], [9, 8, 7]]

    sc = ServingConfig(
        max_requests_per_batch=4, max_sequence_length=128,
        prefill_chunk=16, max_spec_tree_tokens=16,
        cache_dtype=m.cfg.dtype,
    )
    m.compile(sc, quantization=quantization)
    outs = m.generate(prompts, max_new_tokens=16)
    for o in outs:
        print(f"moe ep{ep}tp{tp}:", o.output_text or o.output_tokens)
    return outs


if __name__ == "__main__":
    p = argparse.ArgumentParser()
    p.add_argument("--model-dir", default=None)
    p.add_argument("--ep", type=int, default=1)
    p.add_argument("--tp", type=int, default=1)
    p.add_argument("--quantization", default=None,
                   choices=[None, "int8", "int4"])
    a = p.parse_args()
    main(a.model_dir, a.ep, a.tp, a.quantization)
