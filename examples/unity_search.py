"""Unity-style auto-parallel compile — let the search pick the mesh
degrees and per-op shardings instead of specifying them (the
reference's headline Train capability, ``TRAIN.md:1-67``).

Run: python examples/unity_search.py [--devices N]
"""
import argparse

import numpy as np


def main(num_devices=4):
    import flexflow_tpu as ff

    bs = 8 * num_devices
    cfg = ff.FFConfig(batch_size=bs, epochs=1, num_devices=num_devices)
    model = ff.FFModel(cfg)
    t = model.create_tensor((bs, 64), name="x")
    for _ in range(3):
        t = model.dense(t, 256, activation="relu")
    t = model.dense(t, 8)
    t = model.softmax(t)
    model.compile(optimizer=ff.SGDOptimizer(lr=0.05), auto_parallel=True)
    print("searched strategy:", getattr(model, "_search_report", None))

    rng = np.random.default_rng(0)
    y = rng.integers(0, 8, size=4 * bs).astype(np.int32)
    x = rng.normal(size=(4 * bs, 64)).astype(np.float32)
    model.fit(x, y)
    return model


if __name__ == "__main__":
    p = argparse.ArgumentParser()
    p.add_argument("--devices", type=int, default=4)
    a = p.parse_args()
    main(a.devices)
