"""ResNet training — residual CNN app (reference
``examples/cpp/ResNet/resnet.cc:41-90``: BottleneckBlock built from
conv2d/batch_norm + element-binary add through the FFModel API; the
resnext50 app is the same pattern with grouped convs).

Run: python examples/resnet.py [--devices N]
"""
import argparse

import numpy as np


def basic_block(model, t, channels, stride=1):
    """conv-bn-conv-bn + skip (1x1-conv projection when shape changes),
    then ReLU — the reference block with the cheaper 2-conv variant."""
    skip = t
    out = model.conv2d(t, channels, 3, 3, stride, stride, 1, 1)
    out = model.batch_norm(out, relu=True)
    out = model.conv2d(out, channels, 3, 3, 1, 1, 1, 1)
    out = model.batch_norm(out, relu=False)
    if stride != 1 or t.shape[1] != channels:
        skip = model.conv2d(t, channels, 1, 1, stride, stride, 0, 0)
        skip = model.batch_norm(skip, relu=False)
    out = model.add(out, skip)
    return model.relu(out)


def build(model, batch_size, image_size=32, num_classes=10,
          stages=(1, 1, 1), base_width=16):
    t = model.create_tensor((batch_size, 3, image_size, image_size), name="x")
    t = model.conv2d(t, base_width, 3, 3, 1, 1, 1, 1, activation="relu")
    ch = base_width
    for i, blocks in enumerate(stages):
        for b in range(blocks):
            stride = 2 if (i > 0 and b == 0) else 1
            t = basic_block(model, t, ch, stride)
        ch *= 2
    t = model.mean(t, axes=(2, 3))  # global average pool
    t = model.dense(t, num_classes)
    return model.softmax(t)


def main(num_devices=1, epochs=2, batch_size=32, image_size=16,
         stages=(1, 1), base_width=8, n_samples=256):
    import flexflow_tpu as ff

    cfg = ff.FFConfig(
        batch_size=batch_size, epochs=epochs, num_devices=num_devices
    )
    model = ff.FFModel(cfg)
    build(model, batch_size, image_size, stages=stages, base_width=base_width)
    model.compile(
        optimizer=ff.SGDOptimizer(lr=0.02, momentum=0.9),
        loss_type="sparse_categorical_crossentropy",
        metrics=("accuracy",),
    )
    rng = np.random.default_rng(0)
    y = rng.integers(0, 10, size=n_samples).astype(np.int32)
    x = rng.normal(size=(n_samples, 3, image_size, image_size)).astype(np.float32)
    x += y[:, None, None, None].astype(np.float32) / 10
    model.fit(x, y)
    final = model.evaluate(x, y)
    print("final:", final)
    return final


if __name__ == "__main__":
    p = argparse.ArgumentParser()
    p.add_argument("--devices", type=int, default=1)
    p.add_argument("--epochs", type=int, default=2)
    a = p.parse_args()
    main(a.devices, a.epochs)
