"""split_test — exercises the split operator's forward AND gradient path
through diverging/reconverging branches (reference
``examples/cpp/split_test/split_test.cc`` + ``split_test_2``: a tensor
split into parts that flow through different layers and reconverge).

Run: python examples/split_test.py [--devices N]
"""
import argparse

import numpy as np


def build(model, batch_size, in_dim=16, num_classes=4):
    t = model.create_tensor((batch_size, in_dim), name="x")
    t = model.dense(t, 24, activation="relu")
    a, b, c = model.split(t, [8, 8, 8], axis=1)
    a = model.dense(a, 16, activation="relu")
    b = model.dense(b, 16, activation="tanh")
    # c reconverges unchanged — tests pass-through gradients
    t = model.concat([a, b, c], axis=1)
    t = model.dense(t, num_classes)
    return model.softmax(t)


def main(num_devices=1, epochs=3, batch_size=32, n_samples=256):
    import flexflow_tpu as ff

    cfg = ff.FFConfig(
        batch_size=batch_size, epochs=epochs, num_devices=num_devices
    )
    model = ff.FFModel(cfg)
    build(model, batch_size)
    model.compile(
        optimizer=ff.SGDOptimizer(lr=0.05),
        loss_type="sparse_categorical_crossentropy",
        metrics=("accuracy",),
    )
    rng = np.random.default_rng(0)
    y = rng.integers(0, 4, size=n_samples).astype(np.int32)
    x = rng.normal(size=(n_samples, 16)).astype(np.float32)
    x[:, :4] += 3.0 * np.eye(4, dtype=np.float32)[y]  # separable signal
    perf = model.fit(x, y)
    return perf.averages()


if __name__ == "__main__":
    p = argparse.ArgumentParser()
    p.add_argument("--devices", type=int, default=1)
    p.add_argument("--epochs", type=int, default=3)
    a = p.parse_args()
    print(main(num_devices=a.devices, epochs=a.epochs))
