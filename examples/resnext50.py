"""ResNeXt-style training app — grouped-convolution bottleneck blocks
(reference ``examples/cpp/resnext50/resnext.cc``: the ResNet bottleneck
with ``groups=32`` cardinality). Scaled-down defaults for the CPU mesh.

Run: python examples/resnext50.py [--devices N]
"""
import argparse

import numpy as np


def resnext_block(model, t, channels, cardinality, stride=1):
    """1x1 reduce → 3x3 grouped conv (cardinality groups) → 1x1 expand +
    projection skip (reference resnext.cc bottleneck)."""
    skip = t
    out = model.conv2d(t, channels, 1, 1, 1, 1, 0, 0)
    out = model.batch_norm(out, relu=True)
    out = model.conv2d(
        out, channels, 3, 3, stride, stride, 1, 1, groups=cardinality
    )
    out = model.batch_norm(out, relu=True)
    out = model.conv2d(out, 2 * channels, 1, 1, 1, 1, 0, 0)
    out = model.batch_norm(out, relu=False)
    if stride != 1 or t.shape[1] != 2 * channels:
        skip = model.conv2d(t, 2 * channels, 1, 1, stride, stride, 0, 0)
        skip = model.batch_norm(skip, relu=False)
    out = model.add(out, skip)
    return model.relu(out)


def build(model, batch_size, image_size=32, num_classes=10,
          stages=(1, 1, 1), base=16, cardinality=4):
    t = model.create_tensor((batch_size, 3, image_size, image_size), name="x")
    t = model.conv2d(t, base, 3, 3, 1, 1, 1, 1, activation="relu")
    ch = base
    for i, blocks in enumerate(stages):
        for b in range(blocks):
            stride = 2 if (i > 0 and b == 0) else 1
            t = resnext_block(model, t, ch, cardinality, stride)
        ch *= 2
    t = model.mean(t, axes=(2, 3))
    t = model.dense(t, num_classes)
    return model.softmax(t)


def main(num_devices=1, epochs=2, batch_size=16, image_size=16,
         stages=(1, 1), base=8, cardinality=4, n_samples=128):
    import flexflow_tpu as ff

    cfg = ff.FFConfig(
        batch_size=batch_size, epochs=epochs, num_devices=num_devices
    )
    model = ff.FFModel(cfg)
    build(model, batch_size, image_size, stages=stages, base=base,
          cardinality=cardinality)
    model.compile(
        optimizer=ff.SGDOptimizer(lr=0.02, momentum=0.9),
        loss_type="sparse_categorical_crossentropy",
        metrics=("accuracy",),
    )
    rng = np.random.default_rng(0)
    y = rng.integers(0, 10, size=n_samples).astype(np.int32)
    x = rng.normal(size=(n_samples, 3, image_size, image_size)).astype(
        np.float32
    )
    x += y[:, None, None, None].astype(np.float32) / 4
    perf = model.fit(x, y)
    return perf.averages()


if __name__ == "__main__":
    p = argparse.ArgumentParser()
    p.add_argument("--devices", type=int, default=1)
    p.add_argument("--epochs", type=int, default=2)
    a = p.parse_args()
    print(main(num_devices=a.devices, epochs=a.epochs))
