"""LLaMA serving demo — incremental decoding, SpecInfer, and beam
search through the high-level ``LLM`` API (the reference's
``inference/python/{incr_decoding,spec_infer}.py`` apps). Uses a tiny
randomly-initialised model so it runs anywhere; point ``--model-dir``
at a local HF checkpoint directory to serve real weights.

Run: python examples/llama_serve.py [--model-dir PATH] [--tp N] [--pp N]
"""
import argparse


def main(model_dir=None, tp=1, pp=1, quantization=None):
    import jax
    import jax.numpy as jnp

    from flexflow_tpu.core.mesh import MachineSpec
    from flexflow_tpu.models import llama
    from flexflow_tpu.serve import GenerationConfig, ServingConfig, SpecConfig
    from flexflow_tpu.serve.llm import LLM, SSM

    mesh = MachineSpec.from_degrees(
        tp * pp, tensor=tp, pipeline=pp
    ).make_mesh(jax.devices()[: tp * pp])

    if model_dir:
        m = LLM.from_pretrained(model_dir, mesh=mesh)
        prompts = ["The capital of France is"]
    else:
        cfg = llama.LLaMAConfig(
            vocab_size=512, hidden_size=128, intermediate_size=344,
            num_hidden_layers=4, num_attention_heads=8,
            num_key_value_heads=4, max_position_embeddings=256,
            dtype=jnp.float32,
        )
        m = LLM(llama, cfg, mesh=mesh)
        prompts = [[3, 17, 91, 42, 7], [9, 8, 7]]

    sc = ServingConfig(
        max_requests_per_batch=4, max_sequence_length=128,
        prefill_chunk=16, max_spec_tree_tokens=16,
        cache_dtype=m.cfg.dtype,
    )

    # --- incremental decoding ---
    base_params = m.params  # compile may quantize in place; drafts slice raw
    m.compile(sc, quantization=quantization)
    outs = m.generate(prompts, max_new_tokens=16)
    for o in outs:
        print("incr:", o.output_text or o.output_tokens)

    # --- beam search ---
    beam = m.generate(
        prompts[:1], gen=GenerationConfig(num_beams=3), max_new_tokens=16
    )
    print("beam3:", beam[0].output_text or beam[0].output_tokens)

    # --- SpecInfer with a layer-skip self-draft ---
    import dataclasses

    # draft depth: ~1/4 of the model, rounded up to a multiple of pp so
    # the draft's layer stack also shards over the pipe axis
    k = max(pp, pp * (m.cfg.num_hidden_layers // (4 * pp)))
    dcfg = dataclasses.replace(m.cfg, num_hidden_layers=k)
    dparams = dict(base_params)
    dparams["layers"] = {n: v[:k] for n, v in base_params["layers"].items()}
    ssm = SSM(m.family, dcfg, dparams, mesh=mesh)
    m2 = LLM(m.family, m.cfg, base_params, mesh=mesh, tokenizer=m.tokenizer)
    m2.compile(sc, ssms=[ssm], spec=SpecConfig(beam_width=2, beam_depth=3),
               quantization=quantization)
    outs2 = m2.generate(prompts, max_new_tokens=16)
    for o, o2 in zip(outs, outs2):
        assert o.output_tokens == o2.output_tokens, "spec must equal greedy"
        p = o2.profile
        print(
            f"spec: {o2.output_tokens} "
            f"(LLM steps {p.llm_decoding_steps}, accepted {p.accepted_tokens})"
        )
    return outs2


if __name__ == "__main__":
    p = argparse.ArgumentParser()
    p.add_argument("--model-dir", default=None)
    p.add_argument("--tp", type=int, default=1)
    p.add_argument("--pp", type=int, default=1)
    p.add_argument("--quantization", default=None, choices=[None, "int8", "int4"])
    a = p.parse_args()
    main(a.model_dir, a.tp, a.pp, a.quantization)
