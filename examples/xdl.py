"""XDL-style ads ranking app — many sparse embedding bags + a dense MLP,
feature interaction by concat (reference ``examples/cpp/XDL/xdl.cc:38-140``:
create_emb per sparse input, create_mlp over dense, interact_features via
concat). The DLRM example covers the dot-interaction variant; this is
the concat-interaction one.

Run: python examples/xdl.py [--devices N]
"""
import argparse

import numpy as np


def build(model, batch_size, num_sparse=4, vocab=64, embed_dim=8,
          bag_size=2, dense_dim=16, mlp=(32, 16)):
    sparse = []
    for i in range(num_sparse):
        s = model.create_tensor(
            (batch_size, bag_size), dtype="int32", name=f"sparse_{i}"
        )
        # sum-aggregated embedding bag (reference embedding AGGR_MODE_SUM)
        sparse.append(
            model.embedding(s, vocab, embed_dim, aggr="sum", name=f"emb_{i}")
        )
    dense = model.create_tensor((batch_size, dense_dim), name="dense")
    t = dense
    for i, h in enumerate(mlp):
        t = model.dense(t, h, activation="relu", name=f"mlp_{i}")
    z = model.concat(sparse + [t], axis=-1)
    z = model.dense(z, 32, activation="relu")
    z = model.dense(z, 2)
    return model.softmax(z)


def main(num_devices=1, epochs=2, batch_size=32, n_samples=256,
         num_sparse=4, vocab=64):
    import flexflow_tpu as ff

    cfg = ff.FFConfig(
        batch_size=batch_size, epochs=epochs, num_devices=num_devices
    )
    model = ff.FFModel(cfg)
    build(model, batch_size, num_sparse=num_sparse, vocab=vocab)
    model.compile(
        optimizer=ff.AdamOptimizer(lr=5e-3),
        loss_type="sparse_categorical_crossentropy",
        metrics=("accuracy",),
    )
    rng = np.random.default_rng(0)
    y = rng.integers(0, 2, size=n_samples).astype(np.int32)
    x = {
        f"sparse_{i}": rng.integers(0, vocab, size=(n_samples, 2)).astype(
            np.int32
        )
        for i in range(num_sparse)
    }
    # make the label recoverable from the first sparse feature + dense
    x["sparse_0"][:, 0] = (y * (vocab // 2) + x["sparse_0"][:, 0] % (vocab // 2)).astype(np.int32)
    x["dense"] = (
        rng.normal(size=(n_samples, 16)) + y[:, None] * 0.5
    ).astype(np.float32)
    perf = model.fit(x, y)
    return perf.averages()


if __name__ == "__main__":
    p = argparse.ArgumentParser()
    p.add_argument("--devices", type=int, default=1)
    p.add_argument("--epochs", type=int, default=2)
    a = p.parse_args()
    print(main(num_devices=a.devices, epochs=a.epochs))
