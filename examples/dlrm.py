"""DLRM recommendation model — the reference's large-scale embedding app
(reference ``examples/cpp/DLRM/dlrm.cc:38-120``: per-feature sum-bag
embeddings + bottom/top MLPs with a concat feature interaction, trained
on Criteo-format click data; ``run_summit.sh`` scales it to a cluster).

The container has no Criteo download, so the data is synthetic
click-through with planted feature-class correlation.

Run: python examples/dlrm.py [--devices N]
"""
import argparse

import numpy as np


def build(model, batch_size, num_dense=4, num_sparse=3, vocab=100,
          bag=2, embed_dim=8, bottom=(16, 8), top=(16,)):
    dense_in = model.create_tensor((batch_size, num_dense), name="dense")
    sparse_in = [
        model.create_tensor((batch_size, bag), dtype="int32", name=f"sparse_{i}")
        for i in range(num_sparse)
    ]
    # bottom MLP on dense features (dlrm.cc create_mlp)
    t = dense_in
    for h in bottom:
        t = model.dense(t, h, activation="relu")
    if bottom[-1] != embed_dim:
        t = model.dense(t, embed_dim, activation="relu")
    # per-feature sum-bag embeddings (dlrm.cc create_emb, aggr=sum)
    embs = [
        model.embedding(s, vocab, embed_dim, aggr="sum") for s in sparse_in
    ]
    # feature interaction: concat (the reference's interact_features
    # "cat" mode) — dot-product mode is batch_matmul on the same stack
    t = model.concat([t] + embs, axis=1)
    for h in top:
        t = model.dense(t, h, activation="relu")
    t = model.dense(t, 2)
    return model.softmax(t)


def synthetic_clicks(n, num_dense=4, num_sparse=3, vocab=100, bag=2, seed=0):
    rng = np.random.default_rng(seed)
    y = rng.integers(0, 2, size=n).astype(np.int32)
    dense = rng.normal(size=(n, num_dense)).astype(np.float32) + y[:, None]
    sparse = {
        f"sparse_{i}": (
            rng.integers(0, vocab // 2, size=(n, bag)) + y[:, None] * (vocab // 2)
        ).astype(np.int32)
        for i in range(num_sparse)
    }
    return {"dense": dense, **sparse}, y


def main(num_devices=1, epochs=2, batch_size=64, n_samples=512):
    import flexflow_tpu as ff

    cfg = ff.FFConfig(
        batch_size=batch_size, epochs=epochs, num_devices=num_devices
    )
    model = ff.FFModel(cfg)
    build(model, batch_size)
    model.compile(
        optimizer=ff.SGDOptimizer(lr=0.05),
        loss_type="sparse_categorical_crossentropy",
        metrics=("accuracy",),
    )
    x, y = synthetic_clicks(n_samples)
    model.fit(x, y)
    final = model.evaluate(x, y)
    print("final:", final)
    return final


if __name__ == "__main__":
    p = argparse.ArgumentParser()
    p.add_argument("--devices", type=int, default=1)
    p.add_argument("--epochs", type=int, default=2)
    a = p.parse_args()
    main(a.devices, a.epochs)
