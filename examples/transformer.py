"""Transformer encoder training, optionally through the Unity search —
the reference's attention app (reference
``examples/cpp/Transformer/transformer.cc:30-80``: N identical
attention + 2xdense blocks over (batch, seq, hidden) inputs).

Run: python examples/transformer.py [--devices N] [--auto-parallel]
"""
import argparse

import numpy as np


def encoder_block(model, t, hidden, heads, ff_dim):
    """Pre-LN encoder block out of FFModel builders (transformer.cc
    create_attention_encoder: MHA then two dense layers + residuals)."""
    a = model.layer_norm(t)
    a = model.multihead_attention(a, a, a, hidden, heads)
    t = model.add(t, a)
    f = model.layer_norm(t)
    f = model.dense(f, ff_dim, activation="relu")
    f = model.dense(f, hidden)
    return model.add(t, f)


def build(model, batch_size, seq=16, hidden=32, heads=4, ff_dim=64,
          layers=2, num_classes=8):
    t = model.create_tensor((batch_size, seq, hidden), name="x")
    for _ in range(layers):
        t = encoder_block(model, t, hidden, heads, ff_dim)
    t = model.layer_norm(t)
    t = model.mean(t, axes=(1,))
    t = model.dense(t, num_classes)
    return model.softmax(t)


def main(num_devices=1, epochs=2, batch_size=32, auto_parallel=False,
         n_samples=256, seq=16, hidden=32):
    import flexflow_tpu as ff

    cfg = ff.FFConfig(
        batch_size=batch_size, epochs=epochs, num_devices=num_devices
    )
    model = ff.FFModel(cfg)
    build(model, batch_size, seq=seq, hidden=hidden)
    model.compile(
        optimizer=ff.AdamOptimizer(lr=1e-3),
        loss_type="sparse_categorical_crossentropy",
        metrics=("accuracy",),
        auto_parallel=auto_parallel,
    )
    rng = np.random.default_rng(0)
    y = rng.integers(0, 8, size=n_samples).astype(np.int32)
    protos = rng.normal(size=(8, seq, hidden))  # per-class token patterns
    x = (protos[y] + 0.5 * rng.normal(size=(n_samples, seq, hidden))).astype(
        np.float32
    )
    model.fit(x, y)
    final = model.evaluate(x, y)
    print("final:", final)
    return final


if __name__ == "__main__":
    p = argparse.ArgumentParser()
    p.add_argument("--devices", type=int, default=1)
    p.add_argument("--epochs", type=int, default=2)
    p.add_argument("--auto-parallel", action="store_true")
    a = p.parse_args()
    main(a.devices, a.epochs, auto_parallel=a.auto_parallel)
