"""CANDLE-Uno-style multi-tower regression app (reference
``examples/cpp/candle_uno/candle_uno.cc:49-130``: per-feature dense
towers, concat, shared dense trunk, scalar regression head; a cancer
drug-response surrogate). Scaled down for the CPU mesh.

Run: python examples/candle_uno.py [--devices N]
"""
import argparse

import numpy as np


def build(model, batch_size, feature_dims=(16, 12, 8),
          tower=(32, 16), trunk=(32, 16)):
    towers = []
    for i, d in enumerate(feature_dims):
        t = model.create_tensor((batch_size, d), name=f"feature_{i}")
        for j, h in enumerate(tower):
            t = model.dense(t, h, activation="relu", use_bias=False,
                            name=f"tower_{i}_{j}")
        towers.append(t)
    out = model.concat(towers, axis=-1)
    for j, h in enumerate(trunk):
        out = model.dense(out, h, activation="relu", use_bias=False,
                          name=f"trunk_{j}")
    return model.dense(out, 1, use_bias=False, name="head")


def main(num_devices=1, epochs=3, batch_size=32, n_samples=256):
    import flexflow_tpu as ff

    dims = (16, 12, 8)
    cfg = ff.FFConfig(
        batch_size=batch_size, epochs=epochs, num_devices=num_devices
    )
    model = ff.FFModel(cfg)
    build(model, batch_size, feature_dims=dims)
    model.compile(
        optimizer=ff.AdamOptimizer(lr=5e-3),
        loss_type="mean_squared_error",
        metrics=("mean_squared_error",),
    )
    rng = np.random.default_rng(0)
    x = {
        f"feature_{i}": rng.normal(size=(n_samples, d)).astype(np.float32)
        for i, d in enumerate(dims)
    }
    # target = a fixed linear readout of the inputs (learnable exactly)
    y = sum(v.sum(axis=1) for v in x.values())
    y = ((y - y.mean()) / y.std()).astype(np.float32)[:, None]
    perf = model.fit(x, y)
    return perf.averages()


if __name__ == "__main__":
    p = argparse.ArgumentParser()
    p.add_argument("--devices", type=int, default=1)
    p.add_argument("--epochs", type=int, default=3)
    a = p.parse_args()
    print(main(num_devices=a.devices, epochs=a.epochs))
