"""Keras-frontend CNN training app (reference
``examples/python/keras/seq_cifar10_cnn.py`` /
``func_cifar10_cnn_*.py``: the same Conv-Pool-Dense stack through the
Keras Sequential API). Synthetic CIFAR-shaped blobs keep the CPU-mesh
smoke fast.

Run: python examples/keras_cnn.py [--devices N]
"""
import argparse

import numpy as np


def main(num_devices=1, epochs=2, batch_size=32, image_size=12,
         n_samples=256, num_classes=4):
    import flexflow_tpu as ff
    from flexflow_tpu.keras import layers, models, optimizers

    cfg = ff.FFConfig(batch_size=batch_size, num_devices=num_devices)
    model = models.Sequential([
        layers.Input(shape=(3, image_size, image_size)),
        layers.Conv2D(8, (3, 3), padding="same", activation="relu"),
        layers.MaxPooling2D((2, 2)),
        layers.Conv2D(16, (3, 3), padding="same", activation="relu"),
        layers.Flatten(),
        layers.Dense(32, activation="relu"),
        layers.Dense(num_classes),
        layers.Activation("softmax"),
    ], config=cfg)
    model.compile(
        optimizer=optimizers.SGD(learning_rate=0.02, momentum=0.9),
        loss="sparse_categorical_crossentropy",
        metrics=["accuracy"],
    )
    rng = np.random.default_rng(0)
    y = rng.integers(0, num_classes, size=n_samples).astype(np.int32)
    x = rng.normal(size=(n_samples, 3, image_size, image_size)).astype(
        np.float32
    )
    x += y[:, None, None, None].astype(np.float32) / 3
    hist = model.fit(x, y, epochs=epochs, batch_size=batch_size)
    return {k: v[-1] for k, v in hist.history.items()}


if __name__ == "__main__":
    p = argparse.ArgumentParser()
    p.add_argument("--devices", type=int, default=1)
    p.add_argument("--epochs", type=int, default=2)
    a = p.parse_args()
    print(main(num_devices=a.devices, epochs=a.epochs))
