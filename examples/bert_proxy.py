"""BERT-proxy training app — bidirectional transformer encoder built
through the FFModel API (reference
``examples/python/native/bert_proxy_run_script.sh`` +
``examples/python/native/bert_proxy.py`` shapes: MHA + add&norm + FFN
+ add&norm per layer, MLM-style token classification head). Tiny
defaults for the CPU mesh; raise --layers/--hidden for a real proxy.

Run: python examples/bert_proxy.py [--devices N]
"""
import argparse

import numpy as np


def encoder_layer(model, t, hidden, heads, ffn, i):
    a = model.multihead_attention(
        t, t, t, embed_dim=hidden, num_heads=heads, name=f"attn_{i}"
    )
    t = model.layer_norm(model.add(t, a), name=f"ln1_{i}")
    f = model.dense(t, ffn, activation="gelu", name=f"ffn_up_{i}")
    f = model.dense(f, hidden, name=f"ffn_down_{i}")
    return model.layer_norm(model.add(t, f), name=f"ln2_{i}")


def build(model, batch_size, seq=16, vocab=128, hidden=32, heads=4,
          ffn=64, layers=2):
    tok = model.create_tensor((batch_size, seq), dtype="int32", name="tokens")
    t = model.embedding(tok, vocab, hidden, name="embed")
    for i in range(layers):
        t = encoder_layer(model, t, hidden, heads, ffn, i)
    return model.dense(t, vocab, name="mlm_head")


def main(num_devices=1, epochs=3, batch_size=16, seq=16, vocab=64,
         hidden=32, heads=4, layers=2, n_samples=128):
    import flexflow_tpu as ff

    cfg = ff.FFConfig(
        batch_size=batch_size, epochs=epochs, num_devices=num_devices
    )
    model = ff.FFModel(cfg)
    build(model, batch_size, seq, vocab, hidden, heads, 2 * hidden, layers)
    model.compile(
        optimizer=ff.AdamOptimizer(lr=1e-2),
        loss_type="sparse_categorical_crossentropy",
        metrics=("accuracy",),
    )
    rng = np.random.default_rng(0)
    x = rng.integers(0, vocab, size=(n_samples, seq)).astype(np.int32)
    y = np.roll(x, -1, axis=1)  # predict the next token (learnable copy)
    perf = model.fit({"tokens": x}, y)
    return perf.averages()


if __name__ == "__main__":
    p = argparse.ArgumentParser()
    p.add_argument("--devices", type=int, default=1)
    p.add_argument("--epochs", type=int, default=3)
    p.add_argument("--layers", type=int, default=2)
    p.add_argument("--hidden", type=int, default=32)
    a = p.parse_args()
    print(main(num_devices=a.devices, epochs=a.epochs, layers=a.layers,
               hidden=a.hidden))
