"""Machine-model tests: 2-D torus link multiplicity + the user-editable
machine-config file (the TPU analogs of the reference's
``machine_config_example`` + ``NetworkedMachineModel``,
machine_model.cc:1-1287)."""
import math

import pytest

from flexflow_tpu.search.machine_model import (
    CollectiveModel,
    TPUChip,
    TPUTopology,
)


def test_torus_multiplicity_speeds_up_model_axis_allreduce():
    """A v5e 4x4 slice is a 2-D torus with 2 links per dimension: an
    all-reduce over a 4-wide model axis must come out ~2x faster than
    the single-ring estimate, and a whole-slice 16-wide axis ~4x."""
    chip = TPUChip.v5e()
    # latency-free so the bandwidth terms compare exactly (hop latency
    # is per-hop, not per-link, and does not shrink with striping)
    flat = CollectiveModel(
        TPUTopology(chip=chip, num_chips=16, per_hop_latency=0.0)
    )
    torus = CollectiveModel(
        TPUTopology(chip=chip, num_chips=16, torus=(4, 4),
                    per_hop_latency=0.0)
    )
    nbytes = 256e6
    t_flat = flat.all_reduce(nbytes, 4, "model")
    t_torus = torus.all_reduce(nbytes, 4, "model")
    assert t_torus == pytest.approx(t_flat / 2, rel=1e-3)

    t_flat16 = flat.all_reduce(nbytes, 16, "model")
    t_torus16 = torus.all_reduce(nbytes, 16, "model")
    assert t_torus16 == pytest.approx(t_flat16 / 4, rel=1e-3)


def test_torus_multiplicity_walks_axis_order_on_asymmetric_torus():
    """Mesh axes map onto the torus innermost-first (core.mesh
    AXIS_ORDER): on a 2x8 torus with model=2, data=8, the data axis
    rides ONLY the single size-8 torus dim (2 links) — the old
    start-at-dim-0 walk credited it with both dims (4 links)."""
    topo = TPUTopology(chip=TPUChip.v5e(), num_chips=16, torus=(2, 8))
    degrees = {"data": 8, "expert": 1, "pipe": 1, "seq": 1, "model": 2}
    assert topo.axis_link_multiplicity("model", 2, degrees) == 2
    assert topo.axis_link_multiplicity("data", 8, degrees) == 2
    # without the degree map the conservative dim-0 walk is unchanged
    assert topo.axis_link_multiplicity("data", 8) == 4
    # inner axes consuming the whole torus leave the outer axis 1 link
    topo44 = TPUTopology(chip=TPUChip.v5e(), num_chips=16, torus=(4, 4))
    d2 = {"data": 2, "expert": 1, "pipe": 1, "seq": 1, "model": 16}
    assert topo44.axis_link_multiplicity("data", 2, d2) == 1


def test_torus_multiplicity_never_applies_to_dcn_axes():
    topo = TPUTopology(
        chip=TPUChip.v5e(), num_chips=16, torus=(4, 4), dcn_axes=("data",)
    )
    assert topo.axis_link_multiplicity("data", 4) == 1
    assert topo.axis_link_multiplicity("model", 4) == 2


def test_explicit_axis_links_override_torus():
    topo = TPUTopology(
        chip=TPUChip.v5e(), num_chips=16, torus=(4, 4),
        axis_links={"model": 3},
    )
    assert topo.axis_link_multiplicity("model", 4) == 3


def test_from_file_v5e16(tmp_path):
    p = tmp_path / "machine.cfg"
    p.write_text(
        """
# v5e-16 (BASELINE.json north-star shape)
chip = v5e
num_chips = 16
torus = 4x4
dcn_axes = data
mxu_efficiency = 0.60   # calibrated override
dcn_bandwidth = 20e9
"""
    )
    topo = TPUTopology.from_file(str(p))
    assert topo.chip.name == "v5e"
    assert topo.num_chips == 16
    assert topo.torus == (4, 4)
    assert topo.dcn_axes == ("data",)
    assert topo.chip.mxu_efficiency == pytest.approx(0.60)
    assert topo.dcn_bandwidth == pytest.approx(20e9)
    # untouched preset fields survive
    assert topo.chip.bf16_flops == pytest.approx(197e12)


def test_from_file_custom_chip_and_errors(tmp_path):
    p = tmp_path / "machine.cfg"
    p.write_text(
        """
chip = custom
bf16_flops = 100e12
hbm_bandwidth = 500e9
hbm_capacity = 8e9
ici_bandwidth = 30e9
num_chips = 8
"""
    )
    topo = TPUTopology.from_file(str(p))
    assert topo.chip.bf16_flops == pytest.approx(100e12)
    assert topo.chip.hbm_capacity == pytest.approx(8e9)

    bad = tmp_path / "bad.cfg"
    bad.write_text("chip = v5e\nnot_a_key = 3\n")
    with pytest.raises(ValueError, match="unknown machine-config"):
        TPUTopology.from_file(str(bad))

    mismatch = tmp_path / "mismatch.cfg"
    mismatch.write_text("chip = v5e\nnum_chips = 16\ntorus = 4x2\n")
    with pytest.raises(ValueError, match="torus"):
        TPUTopology.from_file(str(mismatch))


def test_search_accepts_file_loaded_topology(tmp_path):
    """optimize() must run against a file-loaded topology — the
    machine-config workflow end to end (reference --machine-model-file)."""
    import flexflow_tpu as ff
    from flexflow_tpu.search import optimize

    p = tmp_path / "machine.cfg"
    p.write_text("chip = v5e\nnum_chips = 8\ntorus = 4x2\n")
    topo = TPUTopology.from_file(str(p))

    m = ff.FFModel(ff.FFConfig(batch_size=4, num_devices=8))
    t = m.create_tensor((4, 64), name="x")
    t = m.dense(t, 128)
    m.dense(t, 64)
    g2, strat, report = optimize(m.graph, num_devices=8, topo=topo, budget=4)
    assert report.best_cost > 0
    assert strat.machine.num_devices == 8


def test_calibrate_chip_measures_and_clamps():
    """calibrate_chip must return measured efficiencies within the
    documented clamp [0.05, 8.0] — the upper bound is deliberately >1
    (hardware faster than the preset, e.g. a v5p calibrated against the
    v5e numbers, legitimately measures above the assumed peak; see the
    clamp comment in machine_model.calibrate_chip). On this CPU host the
    fractions-of-TPU-peak are tiny and clamp to the 0.05 floor, proving
    the measurement actually ran. Small microbench sizes: the test only
    asserts the clamp, and the full-size default (~137 GFLOP matmul)
    costs ~20s of tier-1 budget on the 1-core CPU host."""
    from flexflow_tpu.search.machine_model import calibrate_chip

    chip = TPUChip.v5e()
    cal = calibrate_chip(chip, iters=1, n=512, stream_mb=16)
    assert 0.05 <= cal.mxu_efficiency <= 8.0
    assert 0.05 <= cal.hbm_efficiency <= 8.0
    # presets elsewhere untouched
    assert cal.bf16_flops == chip.bf16_flops


def test_compile_uses_machine_config_file(tmp_path):
    """FFConfig.machine_config_file must reach the Unity search
    (reference --machine-model-file end to end)."""
    import numpy as np

    import flexflow_tpu as ff

    p = tmp_path / "machine.cfg"
    p.write_text("chip = v5e\nnum_chips = 8\ntorus = 4x2\n")
    cfg = ff.FFConfig(
        batch_size=8, num_devices=8, search_budget=2,
        machine_config_file=str(p),
    )
    m = ff.FFModel(cfg)
    t = m.create_tensor((8, 16), name="x")
    t = m.dense(t, 32, activation="relu")
    t = m.dense(t, 4)
    m.softmax(t)
    m.compile(optimizer=ff.SGDOptimizer(lr=0.05), auto_parallel=True)
    x = np.random.default_rng(0).normal(size=(16, 16)).astype(np.float32)
    y = np.random.default_rng(0).integers(0, 4, size=(16,)).astype(np.int32)
    m.fit(x, y, epochs=1, verbose=False)
    assert m._search_report is not None
