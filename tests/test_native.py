"""Native C++ runtime components: the prefetching SingleDataLoader
(reference src/dataloader/dataloader.cc) and the GPT-2 byte-level BPE
tokenizer (reference src/runtime/gpt_tokenizer.cc), both bound via
ctypes with parity checks against Python/HF references."""
import json
import os
import time

import numpy as np
import pytest

import flexflow_tpu as ff
from flexflow_tpu.data import SingleDataLoader


@pytest.fixture(scope="module")
def data():
    rng = np.random.default_rng(0)
    x = rng.normal(size=(100, 8)).astype(np.float32)
    y = rng.integers(0, 4, size=100).astype(np.int32)
    return x, y


class TestSingleDataLoader:
    def test_native_backend_used(self, data):
        x, y = data
        dl = SingleDataLoader(x, y, 16, shuffle=False)
        assert dl.native, "g++ is in the image; the C++ path must build"

    def test_sequential_batches_match_source(self, data):
        x, y = data
        dl = SingleDataLoader(x, y, 10, shuffle=False)
        assert dl.batches_per_epoch == 10
        for s in range(10):
            xb, yb = dl.next_batch()
            np.testing.assert_array_equal(xb, x[s * 10 : (s + 1) * 10])
            np.testing.assert_array_equal(yb, y[s * 10 : (s + 1) * 10])
        # epoch 2 wraps deterministically
        xb, yb = dl.next_batch()
        np.testing.assert_array_equal(xb, x[:10])

    def test_partial_tail_wraps(self, data):
        x, y = data
        dl = SingleDataLoader(x, y, 30, shuffle=False)  # 100 = 3*30 + 10
        assert dl.batches_per_epoch == 4
        for _ in range(3):
            dl.next_batch()
        xb, yb = dl.next_batch()  # rows 90..99 then wrap 0..19
        np.testing.assert_array_equal(xb[:10], x[90:])
        np.testing.assert_array_equal(xb[10:], x[:20])

    def test_shuffle_covers_every_row_each_epoch(self, data):
        x, y = data
        dl = SingleDataLoader(x, y, 20, shuffle=True, seed=3)
        seen = []
        for _ in range(5):
            xb, _ = dl.next_batch()
            seen.append(xb)
        seen = np.concatenate(seen)
        # every source row appears exactly once (match by unique floats)
        assert sorted(seen[:, 0].tolist()) == sorted(x[:, 0].tolist())

    def test_prefetch_runs_ahead(self, data):
        x, y = data
        dl = SingleDataLoader(x, y, 10, shuffle=False, prefetch_depth=3)
        time.sleep(0.2)  # worker fills the queue while we sleep
        import ctypes

        dl._lib.ffdl_ready.restype = ctypes.c_int64
        dl._lib.ffdl_ready.argtypes = [ctypes.c_void_p]
        assert dl._lib.ffdl_ready(dl._h) >= 2

    def test_fit_accepts_loader(self, data):
        x, y = data
        cfg = ff.FFConfig(batch_size=20, epochs=2, num_devices=1)
        m = ff.FFModel(cfg)
        t = m.create_tensor((20, 8), name="x")
        t = m.dense(t, 16, activation="relu")
        t = m.dense(t, 4)
        t = m.softmax(t)
        m.compile(optimizer=ff.SGDOptimizer(lr=0.05))
        perf = m.fit(
            SingleDataLoader(x, y, 20, shuffle=False), verbose=False
        )
        assert np.isfinite(perf.averages()["loss"])

    def test_python_fallback_matches_native(self, data):
        x, y = data
        nat = SingleDataLoader(x, y, 10, shuffle=False)
        py = SingleDataLoader(x, y, 10, shuffle=False, native=False)
        assert not py.native
        for _ in range(12):  # across the epoch wrap
            nx, ny = nat.next_batch()
            px, py_ = py.next_batch()
            np.testing.assert_array_equal(nx, px)
            np.testing.assert_array_equal(ny, py_)


# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def gpt2_files(tmp_path_factory):
    """Tiny GPT-2-format vocab.json + merges.txt covering 'hello'."""
    transformers = pytest.importorskip("transformers")
    from transformers.models.gpt2.tokenization_gpt2 import bytes_to_unicode

    d = tmp_path_factory.mktemp("tok")
    units = list(bytes_to_unicode().values())
    vocab = {u: i for i, u in enumerate(units)}
    # space-prefixed merges first so (Ġ,h) outranks (h,e) and " hello"
    # becomes one Ġhello token, like real GPT-2 merge tables arrange
    merges = [
        "Ġ h", "Ġh e", "Ġhe l", "Ġhel l", "Ġhell o",
        "h e", "he l", "hel l", "hell o",
        "1 2",
    ]
    extra = ["he", "hel", "hell", "hello",
             "Ġh", "Ġhe", "Ġhel", "Ġhell", "Ġhello", "12"]
    for t in extra:
        vocab[t] = len(vocab)
    vocab_path = os.path.join(d, "vocab.json")
    merges_path = os.path.join(d, "merges.txt")
    with open(vocab_path, "w") as f:
        json.dump(vocab, f)
    with open(merges_path, "w") as f:
        f.write("#version: 0.2\n" + "\n".join(merges) + "\n")
    return vocab_path, merges_path, vocab


class TestGPTTokenizer:
    def test_merges_and_roundtrip(self, gpt2_files):
        from flexflow_tpu.tokenizer import GPTTokenizer

        vocab_path, merges_path, vocab = gpt2_files
        tok = GPTTokenizer(vocab_path, merges_path)
        assert tok.vocab_size == len(vocab)
        ids = tok.encode("hello hello")
        assert ids == [vocab["hello"], vocab["Ġhello"]]
        assert tok.decode(ids) == "hello hello"
        # digits merge; mixed word splits at the letter/digit boundary
        assert tok.encode("hello12") == [vocab["hello"], vocab["12"]]

    def test_matches_hf_gpt2_tokenizer(self, gpt2_files):
        transformers = pytest.importorskip("transformers")
        from flexflow_tpu.tokenizer import GPTTokenizer

        vocab_path, merges_path, _ = gpt2_files
        try:
            hf = transformers.GPT2TokenizerFast(
                vocab_file=vocab_path, merges_file=merges_path
            )
        except Exception as e:  # no tokenizers backend
            pytest.skip(f"HF fast tokenizer unavailable: {e}")
        tok = GPTTokenizer(vocab_path, merges_path)
        for text in [
            "hello", " hello", "hello hello", "hello12",
            "hello, hello!", "x hello  hello",
        ]:
            assert tok.encode(text) == hf.encode(text), text
            assert tok.decode(tok.encode(text)) == text, text
