"""Op numerics tests vs independent references — mirrors the reference's
FF↔PyTorch alignment suite (reference ``tests/align/align_test.py``):
run each op standalone, compare against numpy/torch formulas."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from flexflow_tpu.core.tensor import TensorSpec
from flexflow_tpu.ops import get_op
from flexflow_tpu.ops.registry import OpContext

RNG = np.random.default_rng(0)


def run_op(op_type, attrs, inputs, weights=None, training=False, state=None):
    op = get_op(op_type)
    specs = [TensorSpec(x.shape, str(x.dtype)) for x in inputs]
    if weights is None:
        weights = op.init(jax.random.PRNGKey(0), specs, attrs)
    ctx = OpContext(
        training=training,
        rng=jax.random.PRNGKey(1),
        state=state or {},
        state_updates={} if training else None,
    )
    attrs = dict(attrs)
    attrs.setdefault("_node", 0)
    outs = op.forward(weights, [jnp.asarray(x) for x in inputs], attrs, ctx)
    inferred = op.infer(specs, attrs)
    for o, spec in zip(outs, inferred):
        assert tuple(o.shape) == spec.shape, f"{op_type}: {o.shape} vs {spec.shape}"
    return [np.asarray(o) for o in outs], weights


def test_dense_matches_numpy():
    x = RNG.standard_normal((4, 8)).astype(np.float32)
    (y,), w = run_op("dense", {"out_dim": 16, "activation": "relu"}, [x])
    expect = np.maximum(x @ np.asarray(w["kernel"]) + np.asarray(w["bias"]), 0)
    np.testing.assert_allclose(y, expect, rtol=1e-5, atol=1e-5)


def test_embedding_modes():
    idx = RNG.integers(0, 50, (3, 7))
    (y,), w = run_op(
        "embedding", {"num_entries": 50, "out_dim": 12, "aggr": "none"}, [idx]
    )
    assert y.shape == (3, 7, 12)
    np.testing.assert_allclose(y, np.asarray(w["table"])[idx], rtol=1e-6)
    (ys,), _ = run_op(
        "embedding", {"num_entries": 50, "out_dim": 12, "aggr": "sum"}, [idx], weights=w
    )
    np.testing.assert_allclose(ys, np.asarray(w["table"])[idx].sum(1), rtol=1e-5)


def test_conv2d_vs_torch():
    torch = pytest.importorskip("torch")
    x = RNG.standard_normal((2, 3, 8, 8)).astype(np.float32)
    attrs = dict(
        out_channels=5, kernel_h=3, kernel_w=3, stride_h=1, stride_w=1,
        padding_h=1, padding_w=1,
    )
    (y,), w = run_op("conv2d", attrs, [x])
    with torch.no_grad():
        yt = torch.nn.functional.conv2d(
            torch.tensor(x),
            torch.tensor(np.asarray(w["kernel"])),
            torch.tensor(np.asarray(w["bias"])),
            stride=1,
            padding=1,
        ).numpy()
    np.testing.assert_allclose(y, yt, rtol=1e-4, atol=1e-4)


def test_pool2d_max_vs_torch():
    torch = pytest.importorskip("torch")
    x = RNG.standard_normal((2, 4, 8, 8)).astype(np.float32)
    attrs = dict(kernel_h=2, kernel_w=2, stride_h=2, stride_w=2)
    (y,), _ = run_op("pool2d", attrs, [x])
    with torch.no_grad():
        yt = torch.nn.functional.max_pool2d(torch.tensor(x), 2, 2).numpy()
    np.testing.assert_allclose(y, yt, rtol=1e-5)


def test_layer_norm_vs_torch():
    torch = pytest.importorskip("torch")
    x = RNG.standard_normal((4, 6, 32)).astype(np.float32)
    (y,), w = run_op("layer_norm", {}, [x])
    with torch.no_grad():
        yt = torch.nn.functional.layer_norm(
            torch.tensor(x), (32,),
            torch.tensor(np.asarray(w["gamma"])),
            torch.tensor(np.asarray(w["beta"])),
        ).numpy()
    np.testing.assert_allclose(y, yt, rtol=1e-4, atol=1e-4)


def test_rms_norm_formula():
    x = RNG.standard_normal((4, 16)).astype(np.float32)
    (y,), w = run_op("rms_norm", {"eps": 1e-6}, [x])
    rms = 1.0 / np.sqrt((x**2).mean(-1, keepdims=True) + 1e-6)
    np.testing.assert_allclose(y, x * rms * np.asarray(w["gamma"]), rtol=1e-5)


def test_residual_rms_norm_outputs():
    x = RNG.standard_normal((2, 8)).astype(np.float32)
    r = RNG.standard_normal((2, 8)).astype(np.float32)
    (s, y), w = run_op("residual_rms_norm", {"eps": 1e-6}, [x, r])
    np.testing.assert_allclose(s, x + r, rtol=1e-6)
    rms = 1.0 / np.sqrt(((x + r) ** 2).mean(-1, keepdims=True) + 1e-6)
    np.testing.assert_allclose(y, (x + r) * rms * np.asarray(w["gamma"]), rtol=1e-5)


def test_sigmoid_silu_multi():
    x1 = RNG.standard_normal((3, 5)).astype(np.float32)
    x2 = RNG.standard_normal((3, 5)).astype(np.float32)
    (y,), _ = run_op("sigmoid_silu_multi", {}, [x1, x2])
    silu = x1 / (1 + np.exp(-x1)) * x2
    np.testing.assert_allclose(y, silu, rtol=1e-5)


def test_elementwise():
    a = RNG.standard_normal((3, 4)).astype(np.float32)
    b = RNG.standard_normal((3, 4)).astype(np.float32)
    (y,), _ = run_op("element_binary", {"op": "add"}, [a, b])
    np.testing.assert_allclose(y, a + b, rtol=1e-6)
    (y,), _ = run_op("element_unary", {"op": "relu"}, [a])
    np.testing.assert_allclose(y, np.maximum(a, 0), rtol=1e-6)
    (y,), _ = run_op("element_unary", {"op": "scalar_multiply", "scalar": 2.5}, [a])
    np.testing.assert_allclose(y, a * 2.5, rtol=1e-6)


def test_shape_ops():
    x = RNG.standard_normal((2, 3, 4)).astype(np.float32)
    (y,), _ = run_op("reshape", {"shape": (6, 4)}, [x])
    assert y.shape == (6, 4)
    (y,), _ = run_op("transpose", {"perm": (2, 0, 1)}, [x])
    assert y.shape == (4, 2, 3)
    outs, _ = run_op("split", {"sizes": (1, 3), "axis": 2}, [x])
    assert outs[0].shape == (2, 3, 1) and outs[1].shape == (2, 3, 3)
    (y,), _ = run_op("concat", {"axis": 1}, [x, x])
    assert y.shape == (2, 6, 4)
    (y,), _ = run_op("flat", {}, [x])
    assert y.shape == (2, 12)


def test_softmax_and_reduce():
    x = RNG.standard_normal((5, 9)).astype(np.float32)
    (y,), _ = run_op("softmax", {"axis": -1}, [x])
    np.testing.assert_allclose(y.sum(-1), np.ones(5), rtol=1e-5)
    (y,), _ = run_op("reduce", {"op": "mean", "axes": (1,)}, [x])
    np.testing.assert_allclose(y, x.mean(1), rtol=1e-5)


def test_batch_matmul():
    a = RNG.standard_normal((2, 3, 4)).astype(np.float32)
    b = RNG.standard_normal((2, 4, 5)).astype(np.float32)
    (y,), _ = run_op("batch_matmul", {}, [a, b])
    np.testing.assert_allclose(y, a @ b, rtol=1e-5, atol=1e-5)


def test_multihead_attention_vs_torch():
    torch = pytest.importorskip("torch")
    B, L, D, H = 2, 6, 16, 4
    x = RNG.standard_normal((B, L, D)).astype(np.float32)
    attrs = {"embed_dim": D, "num_heads": H, "bias": False}
    (y,), w = run_op("multihead_attention", attrs, [x, x, x])

    mha = torch.nn.MultiheadAttention(D, H, bias=False, batch_first=True)
    with torch.no_grad():
        wq, wk, wv = [np.asarray(w[k]).T for k in ("wq", "wk", "wv")]
        mha.in_proj_weight.copy_(torch.tensor(np.concatenate([wq, wk, wv], 0)))
        mha.out_proj.weight.copy_(torch.tensor(np.asarray(w["wo"]).T))
        yt, _ = mha(torch.tensor(x), torch.tensor(x), torch.tensor(x))
    np.testing.assert_allclose(y, yt.numpy(), rtol=1e-4, atol=1e-4)


def test_causal_attention_masks_future():
    B, L, D, H = 1, 5, 8, 2
    x = RNG.standard_normal((B, L, D)).astype(np.float32)
    attrs = {"embed_dim": D, "num_heads": H, "bias": False, "causal": True}
    (y1,), w = run_op("multihead_attention", attrs, [x, x, x])
    # Perturb the last position; earlier outputs must not change.
    x2 = x.copy()
    x2[:, -1] += 10.0
    (y2,), _ = run_op("multihead_attention", attrs, [x2, x2, x2], weights=w)
    np.testing.assert_allclose(y1[:, :-1], y2[:, :-1], rtol=1e-4, atol=1e-5)


def test_batch_norm_train_and_eval():
    x = RNG.standard_normal((8, 4, 2, 2)).astype(np.float32) * 3 + 1
    state = {0: get_op("batch_norm").init_state([TensorSpec(x.shape)], {})}
    op_attrs = {"relu": False, "_node": 0}
    outs, w = run_op("batch_norm", op_attrs, [x], training=True, state=state)
    y = outs[0]
    np.testing.assert_allclose(y.mean((0, 2, 3)), np.zeros(4), atol=1e-4)
    np.testing.assert_allclose(y.std((0, 2, 3)), np.ones(4), atol=1e-2)


def test_dropout_train_vs_eval():
    x = np.ones((100, 100), np.float32)
    (y_eval,), _ = run_op("dropout", {"rate": 0.5}, [x], training=False)
    np.testing.assert_allclose(y_eval, x)
    (y_tr,), _ = run_op("dropout", {"rate": 0.5}, [x], training=True)
    frac = (y_tr == 0).mean()
    assert 0.4 < frac < 0.6
    np.testing.assert_allclose(y_tr[y_tr != 0], 2.0, rtol=1e-6)
