"""Keras frontend auxiliaries — callbacks, datasets, preprocessing
(reference ``python/flexflow/keras/{callbacks.py,datasets,preprocessing}``
— the completeness gap VERDICT r2 item 10 flagged)."""
import numpy as np
import pytest

from flexflow_tpu import keras


def _blob_data(n=256, d=16, classes=4, seed=0):
    rng = np.random.default_rng(seed)
    y = rng.integers(0, classes, size=n).astype(np.int32)
    centers = rng.normal(size=(classes, d)) * 3
    x = (centers[y] + rng.normal(size=(n, d))).astype(np.float32)
    return x, y


def _mlp(batch=32):
    inp = keras.Input(shape=(16,))
    h = keras.Dense(32, activation="relu")(inp)
    out = keras.Activation("softmax")(keras.Dense(4)(h))
    return keras.Model(inp, out, batch_size=batch)


class TestCallbacks:
    def test_history_returned_and_filled(self):
        m = _mlp()
        m.compile(loss="sparse_categorical_crossentropy")
        x, y = _blob_data()
        hist = m.fit(x, y, epochs=3, verbose=False)
        assert hist.epoch == [0, 1, 2]
        assert len(hist.history["loss"]) == 3
        assert hist.history["loss"][-1] < hist.history["loss"][0]

    def test_learning_rate_scheduler_changes_device_lr(self):
        m = _mlp()
        m.compile(loss="sparse_categorical_crossentropy")
        x, y = _blob_data()
        seen = []

        class Spy(keras.callbacks.Callback):
            def on_epoch_begin(self, epoch, logs=None):
                seen.append(float(self.model.ffmodel.opt_state["lr"]))

        sched = keras.callbacks.LearningRateScheduler(
            lambda e: 0.05 * (0.5 ** e)
        )
        m.fit(x, y, epochs=3, callbacks=[sched, Spy()], verbose=False)
        np.testing.assert_allclose(seen, [0.05, 0.025, 0.0125], rtol=1e-6)

    def test_epoch_verify_early_stop(self):
        m = _mlp()
        m.compile(loss="sparse_categorical_crossentropy")
        x, y = _blob_data()
        hist = m.fit(
            x, y, epochs=50,
            callbacks=[keras.callbacks.EpochVerifyMetrics(0.95)],
            verbose=False,
        )
        assert len(hist.epoch) < 50  # stopped once the bar cleared
        assert hist.history["accuracy"][-1] >= 0.95

    def test_verify_metrics_raises_below_bar(self):
        m = _mlp()
        m.compile(loss="sparse_categorical_crossentropy")
        x, y = _blob_data()
        with pytest.raises(AssertionError):
            m.fit(
                x, y, epochs=1,
                callbacks=[keras.callbacks.VerifyMetrics(1.01)],
                verbose=False,
            )

    def test_early_stopping_patience(self):
        m = _mlp()
        m.compile(loss="sparse_categorical_crossentropy")
        x, y = _blob_data()
        hist = m.fit(
            x, y, epochs=60,
            callbacks=[keras.callbacks.EarlyStopping(
                monitor="loss", min_delta=1e-3, patience=2
            )],
            verbose=False,
        )
        assert len(hist.epoch) < 60


class TestDatasets:
    def test_mnist_shapes(self):
        (xt, yt), (xv, yv) = keras.datasets.mnist.load_data()
        assert xt.shape[1:] == (28, 28) and xt.dtype == np.uint8
        assert set(np.unique(yt)) <= set(range(10))
        assert len(xv) < len(xt)

    def test_cifar10_shapes(self):
        (xt, yt), (xv, yv) = keras.datasets.cifar10.load_data()
        assert xt.shape[1:] == (3, 32, 32)

    def test_reuters_sequences(self):
        (xt, yt), (xv, yv) = keras.datasets.reuters.load_data(num_words=500)
        assert all(max(s) < 500 for s in xt[:20])
        assert yt.max() < 46

    def test_mnist_trains_through_keras(self):
        (xt, yt), _ = keras.datasets.mnist.load_data()
        x = (xt[:512].reshape(512, 784) / 255.0).astype(np.float32)
        y = yt[:512].astype(np.int32)
        inp = keras.Input(shape=(784,))
        h = keras.Dense(64, activation="relu")(inp)
        out = keras.Activation("softmax")(keras.Dense(10)(h))
        m = keras.Model(inp, out, batch_size=64)
        m.compile(loss="sparse_categorical_crossentropy")
        hist = m.fit(x, y, epochs=3, verbose=False)
        assert hist.history["accuracy"][-1] > 0.5


class TestPreprocessing:
    def test_pad_sequences_modes(self):
        seqs = [[1, 2, 3], [4], [5, 6, 7, 8, 9]]
        pre = keras.preprocessing.pad_sequences(seqs, maxlen=4)
        np.testing.assert_array_equal(pre[0], [0, 1, 2, 3])
        np.testing.assert_array_equal(pre[2], [6, 7, 8, 9])  # pre-truncate
        post = keras.preprocessing.pad_sequences(
            seqs, maxlen=4, padding="post", truncating="post"
        )
        np.testing.assert_array_equal(post[0], [1, 2, 3, 0])
        np.testing.assert_array_equal(post[2], [5, 6, 7, 8])

    def test_tokenizer_roundtrip(self):
        tok = keras.preprocessing.Tokenizer(oov_token="<unk>")
        tok.fit_on_texts(["the cat sat", "the dog sat down"])
        assert tok.word_index["<unk>"] == 1
        # most frequent words get the lowest indices after oov
        assert tok.word_index["the"] < tok.word_index["dog"]
        seqs = tok.texts_to_sequences(["the cat flew"])
        assert seqs[0][0] == tok.word_index["the"]
        assert seqs[0][2] == 1  # oov
        m = tok.texts_to_matrix(["the cat"], mode="count")
        assert m[0, tok.word_index["the"]] == 1

    def test_reuters_pipeline(self):
        """The reference's reuters_mlp example pipeline shape-for-shape."""
        (xt, yt), _ = keras.datasets.reuters.load_data(num_words=200)
        x = keras.preprocessing.pad_sequences(xt[:128], maxlen=50)
        assert x.shape == (128, 50)


class TestLayerKnobs:
    """Initializer-string / regularizer parity with the reference's layer
    surface (reference python/flexflow/keras/layers/core.py:26-40 +
    keras/regularizers.py L1/L2)."""

    def test_zeros_kernel_initializer_gives_zero_logits(self):
        inp = keras.Input(shape=(16,))
        out = keras.Dense(4, kernel_initializer="zeros",
                          use_bias=False)(inp)
        m = keras.Model(inp, out, batch_size=8)
        m.compile(loss="sparse_categorical_crossentropy")
        x = np.ones((8, 16), np.float32)
        np.testing.assert_allclose(np.asarray(m.predict(x)), 0.0)

    def test_unknown_initializer_rejected(self):
        with pytest.raises(ValueError, match="unknown initializer"):
            keras.Dense(4, kernel_initializer="he_normal")

    def test_unsupported_regularizers_rejected(self):
        with pytest.raises(NotImplementedError):
            keras.Dense(4, bias_regularizer=keras.regularizers.L2(0.1))

    def test_l2_regularizer_raises_training_loss_and_shrinks_weights(self):
        """The penalty must actually join the loss AND its gradient must
        reach the kernel (weight decay), not just inflate the metric."""
        x, y = _blob_data(n=64)

        def build(reg):
            inp = keras.Input(shape=(16,))
            h = keras.Dense(32, activation="relu",
                            kernel_regularizer=reg, name="reg_dense")(inp)
            out = keras.Activation("softmax")(keras.Dense(4)(h))
            m = keras.Model(inp, out, batch_size=32)
            m.compile(loss="sparse_categorical_crossentropy")
            return m

        plain = build(None)
        reg = build(keras.regularizers.L2(0.05))
        h_plain = plain.fit(x, y, epochs=1, verbose=False)
        h_reg = reg.fit(x, y, epochs=1, verbose=False)
        # same seed → same init; the regularized loss carries the Σw² term
        assert h_reg.history["loss"][0] > h_plain.history["loss"][0]
        w_plain = plain.ffmodel.get_weights("reg_dense")["kernel"]
        w_reg = reg.ffmodel.get_weights("reg_dense")["kernel"]
        assert float(np.sum(w_reg**2)) < float(np.sum(w_plain**2))

    def test_l1_penalty_value_in_graph_mode(self):
        """Exact penalty: zero-init kernel + L1 on a one-step fit keeps
        the penalty 0; constant kernel gives λ·Σ|w| — checked through
        FFModel directly for a closed-form assertion."""
        import flexflow_tpu as ff
        from flexflow_tpu.initializers import Constant

        cfg = ff.FFConfig(batch_size=4, num_devices=1)
        m = ff.FFModel(cfg)
        t = m.create_tensor((4, 8), name="x")
        t = m.dense(t, 2, use_bias=False,
                    kernel_initializer=Constant(0.5),
                    kernel_regularizer=("l1", 0.1))
        m.softmax(t)
        m.compile(optimizer=ff.SGDOptimizer(lr=0.0),
                  loss_type="sparse_categorical_crossentropy")
        x = np.zeros((4, 8), np.float32)
        y = np.zeros((4,), np.int32)
        perf = m.fit(x, y, epochs=1, verbose=False)
        # zero inputs → logits 0 → CE = log(2); penalty = 0.1 * 8*2*0.5
        expected = np.log(2.0) + 0.1 * 8 * 2 * 0.5
        assert abs(perf.averages()["loss"] - expected) < 1e-3

    def test_unknown_regularizer_kind_rejected(self):
        with pytest.raises(ValueError, match="regularizer kind"):
            keras.Dense(4, kernel_regularizer=("l3", 0.5))
        # keras-style capitalization normalizes instead of silently
        # becoming L2
        d = keras.Dense(4, kernel_regularizer="L1")
        assert d.kernel_regularizer == ("l1", 0.01)
