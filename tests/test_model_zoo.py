"""Model-zoo alignment tests vs HuggingFace transformers.

The reference validates serving correctness by diffing its greedy output
against HF transformers (reference ``tests/inference/huggingface_inference.py``
+ ``python_inference_tests.sh:111-131``). Here tiny randomly-initialised
HF models are built *locally* (no download) for every supported family,
their weights converted through each family's ``convert_hf_state_dict``,
and logits compared exactly; a second test checks the serving path
(chunked prefill + decode through the KV cache) reproduces the training
forward's logits.
"""
import numpy as np
import pytest
import jax
import jax.numpy as jnp

import flexflow_tpu.models as zoo
from flexflow_tpu.models import (
    falcon,
    gemma,
    gpt2,
    llama,
    phi,
    mistral,
    mixtral,
    qwen2_moe,
    mpt,
    opt,
    qwen2,
    starcoder,
)

transformers = pytest.importorskip("transformers")
torch = pytest.importorskip("torch")

B, S, V = 2, 17, 256


def _hf_llama():
    cfg = transformers.LlamaConfig(
        vocab_size=V, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=128,
    )
    return transformers.LlamaForCausalLM(cfg), llama.LLaMAConfig.from_hf(
        cfg.to_dict(), dtype=jnp.float32
    ), llama


def _hf_opt():
    cfg = transformers.OPTConfig(
        vocab_size=V, hidden_size=64, ffn_dim=128, num_hidden_layers=2,
        num_attention_heads=4, max_position_embeddings=128,
        word_embed_proj_dim=64, do_layer_norm_before=True,
    )
    return transformers.OPTForCausalLM(cfg), opt.from_hf(
        cfg.to_dict(), dtype=jnp.float32
    ), opt


def _hf_falcon():
    cfg = transformers.FalconConfig(
        vocab_size=V, hidden_size=64, num_hidden_layers=2,
        num_attention_heads=4, multi_query=True, parallel_attn=True,
        new_decoder_architecture=False, bias=False, alibi=False,
        max_position_embeddings=128,
    )
    return transformers.FalconForCausalLM(cfg), falcon.from_hf(
        cfg.to_dict(), dtype=jnp.float32
    ), falcon


def _hf_mpt():
    cfg = transformers.MptConfig(
        d_model=64, n_heads=4, n_layers=2, vocab_size=V, max_seq_len=128,
        expansion_ratio=4,
    )
    return transformers.MptForCausalLM(cfg), mpt.from_hf(
        cfg.to_dict(), dtype=jnp.float32
    ), mpt


def _hf_starcoder():
    cfg = transformers.GPTBigCodeConfig(
        vocab_size=V, n_embd=64, n_layer=2, n_head=4, n_positions=128,
        multi_query=True, activation_function="gelu_pytorch_tanh",
    )
    return transformers.GPTBigCodeForCausalLM(cfg), starcoder.from_hf(
        cfg.to_dict(), dtype=jnp.float32
    ), starcoder


def _hf_qwen2():
    cfg = transformers.Qwen2Config(
        vocab_size=V, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=128, rope_theta=10000.0,
    )
    return transformers.Qwen2ForCausalLM(cfg), qwen2.from_hf(
        cfg.to_dict(), dtype=jnp.float32
    ), qwen2


def _hf_mistral():
    # sliding_window=8 < S=17 so the window mask actually BINDS in the
    # alignment comparison (full-causal logits would differ)
    cfg = transformers.MistralConfig(
        vocab_size=V, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=128, sliding_window=8,
    )
    return transformers.MistralForCausalLM(cfg), mistral.from_hf(
        cfg.to_dict(), dtype=jnp.float32
    ), mistral


def _hf_gpt2():
    cfg = transformers.GPT2Config(
        vocab_size=V, n_embd=64, n_layer=2, n_head=4, n_positions=128,
    )
    return transformers.GPT2LMHeadModel(cfg), gpt2.from_hf(
        cfg.to_dict(), dtype=jnp.float32
    ), gpt2


def _hf_phi():
    # partial_rotary_factor=0.5 < 1 so the pass-through half of each
    # head actually exercises the partial-rope path
    cfg = transformers.PhiConfig(
        vocab_size=V, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=4,
        partial_rotary_factor=0.5, max_position_embeddings=128,
    )
    return transformers.PhiForCausalLM(cfg), phi.from_hf(
        cfg.to_dict(), dtype=jnp.float32
    ), phi


def _hf_gemma():
    cfg = transformers.GemmaConfig(
        vocab_size=V, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=1,
        head_dim=32, max_position_embeddings=128,
    )
    return transformers.GemmaForCausalLM(cfg), gemma.from_hf(
        cfg.to_dict(), dtype=jnp.float32
    ), gemma


def _hf_qwen2_moe():
    cfg = transformers.Qwen2MoeConfig(
        vocab_size=V, hidden_size=64, intermediate_size=128,
        moe_intermediate_size=96, shared_expert_intermediate_size=112,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        num_experts=4, num_experts_per_tok=2, norm_topk_prob=False,
        max_position_embeddings=128, decoder_sparse_step=1,
    )
    return transformers.Qwen2MoeForCausalLM(cfg), qwen2_moe.from_hf(
        cfg.to_dict(), dtype=jnp.float32
    ), qwen2_moe


def _hf_mixtral():
    cfg = transformers.MixtralConfig(
        vocab_size=V, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        num_local_experts=4, num_experts_per_tok=2,
        max_position_embeddings=128, rope_theta=10000.0,
    )
    return transformers.MixtralForCausalLM(cfg), mixtral.from_hf(
        cfg.to_dict(), dtype=jnp.float32
    ), mixtral


BUILDERS = {
    "llama": _hf_llama,
    "qwen2": _hf_qwen2,
    "mixtral": _hf_mixtral,
    "qwen2_moe": _hf_qwen2_moe,
    "gemma": _hf_gemma,
    "phi": _hf_phi,
    "gpt2": _hf_gpt2,
    "mistral": _hf_mistral,
    "opt": _hf_opt,
    "falcon": _hf_falcon,
    "mpt": _hf_mpt,
    "starcoder": _hf_starcoder,
}


@pytest.fixture(scope="module", params=sorted(BUILDERS))
def family(request):
    torch.manual_seed(0)
    hf_model, cfg, mod = BUILDERS[request.param]()
    hf_model = hf_model.eval()
    params = mod.convert_hf_state_dict(hf_model.state_dict(), cfg)
    return request.param, hf_model, cfg, mod, params


def test_hf_alignment(family):
    name, hf_model, cfg, mod, params = family
    rng = np.random.default_rng(0)
    tokens = rng.integers(0, V, size=(B, S))
    with torch.no_grad():
        ref = hf_model(torch.from_numpy(tokens)).logits.float().numpy()
    got = np.asarray(mod.forward(params, jnp.asarray(tokens), cfg))
    np.testing.assert_allclose(got, ref, atol=3e-4, rtol=3e-4)


def test_serve_matches_forward(family):
    """Chunked prefill through the KV cache must reproduce the training
    forward's logits at every chunk boundary (the reference's
    incremental-vs-full equivalence property)."""
    name, hf_model, cfg, mod, params = family
    rng = np.random.default_rng(1)
    tokens = rng.integers(0, V, size=(2, 12)).astype(np.int32)
    full = np.asarray(mod.forward(params, jnp.asarray(tokens), cfg))

    cache = mod.init_kv_cache(cfg, num_slots=2, max_len=31, dtype=jnp.float32)
    chunk = 4
    for c0 in range(0, 12, chunk):
        tk = jnp.asarray(tokens[:, c0 : c0 + chunk])
        pos = jnp.asarray(
            np.broadcast_to(np.arange(c0, c0 + chunk, dtype=np.int32), (2, chunk))
        )
        logits, cache = mod.serve_step(
            params, cache, tk, pos,
            jnp.full((2,), chunk - 1, jnp.int32), None,
            cfg=cfg, all_logits=False,
        )
        np.testing.assert_allclose(
            np.asarray(logits), full[:, c0 + chunk - 1], atol=3e-4, rtol=3e-4
        )


def test_family_registry():
    assert set(zoo.FAMILIES) >= {
        "llama", "opt", "falcon", "mpt", "starcoder", "qwen2",
    }
    # non-dense Qwen2 variants must be rejected loudly, not misrouted
    # through the substring fallback into the dense converter
    import pytest as _pytest

    from flexflow_tpu.models import qwen2 as _q

    with _pytest.raises(NotImplementedError):
        _q.from_hf({"model_type": "qwen2_moe", "hidden_size": 64,
                    "intermediate_size": 128, "num_hidden_layers": 2,
                    "num_attention_heads": 4})


def test_llm_from_pretrained_e2e(tmp_path):
    """Save a tiny HF OPT checkpoint locally, then load + generate
    through the high-level LLM API (reference serve.py flow, minus the
    hub download)."""
    from flexflow_tpu.serve import LLM, ServingConfig

    torch.manual_seed(0)
    hf_model, _, _ = _hf_opt()
    hf_model.save_pretrained(tmp_path / "opt-tiny")

    llm = LLM.from_pretrained(
        str(tmp_path / "opt-tiny"), dtype=jnp.float32, tokenizer=None
    )
    llm.compile(ServingConfig(max_requests_per_batch=2,
                              max_sequence_length=64, prefill_chunk=8))
    prompts = [[5, 6, 7], [9, 10, 11, 12]]
    out = llm.generate(prompts, max_new_tokens=5)
    assert len(out) == 2
    assert all(len(r.output_tokens) == 5 for r in out)

    # greedy serving output must match HF greedy generate
    with torch.no_grad():
        hf_out = hf_model.generate(
            torch.tensor([prompts[0]]), max_new_tokens=5, do_sample=False
        )[0, 3:].tolist()
    assert out[0].output_tokens == hf_out


def test_mixtral_guards():
    """Sliding-window configs carry the window through (the generic
    decoder enforces it since mistral landed); mlp_bias stays
    incompatible with MoE."""
    cfg = mixtral.from_hf({
        "vocab_size": 128, "hidden_size": 64, "intermediate_size": 128,
        "num_hidden_layers": 2, "num_attention_heads": 4,
        "max_position_embeddings": 4096, "sliding_window": 1024,
    })
    assert cfg.sliding_window == 1024
    assert mixtral.from_hf({
        "vocab_size": 128, "hidden_size": 64, "intermediate_size": 128,
        "num_hidden_layers": 2, "num_attention_heads": 4,
        "max_position_embeddings": 4096, "sliding_window": None,
    }).sliding_window == 0
    with pytest.raises(ValueError, match="mlp_bias"):
        mixtral.config(mlp_bias=True)


def test_qwen2_moe_norm_topk_variant():
    """norm_topk_prob=True renormalizes the selected expert weights —
    both router semantics must match HF exactly."""
    torch.manual_seed(1)
    cfg = transformers.Qwen2MoeConfig(
        vocab_size=V, hidden_size=64, intermediate_size=128,
        moe_intermediate_size=96, shared_expert_intermediate_size=112,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        num_experts=4, num_experts_per_tok=2, norm_topk_prob=True,
        max_position_embeddings=128, decoder_sparse_step=1,
    )
    hf = transformers.Qwen2MoeForCausalLM(cfg).eval()
    mcfg = qwen2_moe.from_hf(cfg.to_dict(), dtype=jnp.float32)
    assert mcfg.moe_norm_topk
    params = qwen2_moe.convert_hf_state_dict(hf.state_dict(), mcfg)
    rng = np.random.default_rng(3)
    tokens = rng.integers(0, V, size=(2, 11))
    with torch.no_grad():
        ref = hf(torch.from_numpy(tokens)).logits.float().numpy()
    got = np.asarray(qwen2_moe.forward(params, jnp.asarray(tokens), mcfg))
    np.testing.assert_allclose(got, ref, atol=3e-4, rtol=3e-4)


def test_qwen2_moe_guards():
    base = dict(vocab_size=128, hidden_size=64, intermediate_size=128,
                num_hidden_layers=2, num_attention_heads=4,
                max_position_embeddings=128)
    with pytest.raises(NotImplementedError, match="sparse_step"):
        qwen2_moe.from_hf({**base, "decoder_sparse_step": 2})
    with pytest.raises(NotImplementedError, match="sparse_step"):
        qwen2_moe.from_hf({**base, "mlp_only_layers": [0]})
    with pytest.raises(NotImplementedError, match="sliding"):
        qwen2_moe.from_hf({**base, "use_sliding_window": True})


def test_gemma_guards_and_replace_safety():
    """gemma2/gemma3 checkpoints must be rejected, not silently
    converted; and dataclasses.replace must re-derive head_dim when no
    override is set (the config-surgery pattern bench/examples use)."""
    import dataclasses

    with pytest.raises(NotImplementedError, match="gemma2"):
        gemma.from_hf({
            "model_type": "gemma2", "vocab_size": 128, "hidden_size": 64,
            "intermediate_size": 128, "num_hidden_layers": 2,
            "num_attention_heads": 4, "max_position_embeddings": 128,
        })
    from flexflow_tpu.models.transformer import DecoderConfig

    cfg = DecoderConfig(hidden_size=768, num_attention_heads=12)
    assert cfg.head_dim == 64
    assert dataclasses.replace(cfg, num_attention_heads=8).head_dim == 96
    # an explicit override survives replace (it IS the knob)
    g = gemma.tiny()
    assert dataclasses.replace(g, num_hidden_layers=1).head_dim == 32


def test_phi_guards():
    base = dict(vocab_size=128, hidden_size=64, intermediate_size=128,
                num_hidden_layers=2, num_attention_heads=4,
                max_position_embeddings=128)
    with pytest.raises(NotImplementedError, match="phi3"):
        phi.from_hf({**base, "model_type": "phi3"})
    with pytest.raises(NotImplementedError, match="qk_layernorm"):
        phi.from_hf({**base, "qk_layernorm": True})
    # odd rotary widths are a config error, not a silent one-dim drift
    with pytest.raises(ValueError, match="odd rotary"):
        phi.tiny(rotary_pct=0.45)  # head_dim 16 -> rot 7


def test_gpt2_guards_and_activation():
    base = dict(model_type="gpt2", vocab_size=128, n_embd=64, n_layer=2,
                n_head=4, n_positions=128)
    with pytest.raises(NotImplementedError, match="scale_attn_by"):
        gpt2.from_hf({**base, "scale_attn_by_inverse_layer_idx": True})
    with pytest.raises(NotImplementedError, match="scale_attn_weights"):
        gpt2.from_hf({**base, "scale_attn_weights": False})
    # activation comes from the checkpoint, not a hardcode
    assert gpt2.from_hf({**base, "activation_function": "relu"}
                        ).activation == "relu"
    assert gpt2.from_hf(base).activation == "gelu_tanh"
