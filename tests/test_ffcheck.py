"""Tier-1 wiring of scripts/ffcheck.py + unit tests for the lint rules.

The repo-wide guard is the same pattern as tests/test_family_reexports:
``flexflow_tpu/`` must lint clean (zero unsuppressed findings) so a new
JAX/TPU hazard — a host sync sneaking into a traced function, a weak
``jnp.asarray`` at a jit boundary, a cache threaded through jit without
donation — fails CI at the PR that introduces it instead of shipping as
a silent 100x TPU slowdown.
"""
import importlib.util
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from flexflow_tpu.analysis import get_rules, lint_paths, lint_source  # noqa: E402
from flexflow_tpu.analysis.lint import (  # noqa: E402
    FileContext,
    parse_suppressions,
)


def _load_ffcheck():
    path = os.path.join(REPO, "scripts", "ffcheck.py")
    spec = importlib.util.spec_from_file_location("ffcheck", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _codes(findings):
    return [f.rule for f in findings]


# ---------------------------------------------------------------------------
# the CI-style guard: the package must stay clean


def test_package_lints_clean():
    findings = lint_paths([os.path.join(REPO, "flexflow_tpu")])
    assert not findings, (
        "new ffcheck findings (fix them, or suppress with a reason: "
        "`# ffcheck: disable=RULE -- why`):\n"
        + "\n".join(f.format() for f in findings)
    )


def test_ffcheck_script_exits_zero():
    mod = _load_ffcheck()
    assert mod.main([]) == 0


def test_ffcheck_list_rules():
    mod = _load_ffcheck()
    assert mod.main(["--list-rules"]) == 0
    # the catalog in analysis/__init__ must cover every registered rule
    import flexflow_tpu.analysis as analysis

    for rule in get_rules():
        assert rule.code in analysis.__doc__, (
            f"rule {rule.code} missing from the analysis/__init__.py "
            "rule catalog"
        )
        assert rule.slug in analysis.__doc__


def test_ffcheck_diff_mode(tmp_path):
    """--diff lints only files changed vs a base ref."""
    mod = _load_ffcheck()
    # vs HEAD there may be changes or not — the call must succeed either way
    rc = mod.main(["--diff", "HEAD"])
    assert rc in (0, 1)
    files = mod.changed_files("HEAD")
    assert isinstance(files, list)
    for f in files:
        assert f.endswith(".py") and os.path.exists(f)


# ---------------------------------------------------------------------------
# FF101 host-sync


def test_host_sync_in_jitted_function():
    src = (
        "import jax\nimport numpy as np\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    return np.asarray(x)\n"
    )
    assert _codes(lint_source(src)) == ["FF101"]


def test_host_sync_item_and_device_get():
    src = (
        "import jax\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    y = x.item()\n"
        "    return jax.device_get(y)\n"
    )
    assert _codes(lint_source(src)) == ["FF101", "FF101"]


def test_host_sync_float_cast_of_traced_param():
    src = (
        "import jax\n"
        "@jax.jit\n"
        "def f(x, cfg):\n"
        "    return float(x) + float(cfg)\n"
    )
    # cfg is a conventional static — only float(x) is flagged
    assert _codes(lint_source(src)) == ["FF101"]


def test_host_sync_via_intra_file_call_graph():
    src = (
        "import jax\nimport numpy as np\n"
        "def helper(q):\n"
        "    return np.asarray(q)\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    return helper(x)\n"
    )
    assert _codes(lint_source(src)) == ["FF101"]


def test_host_sync_ok_outside_trace():
    src = (
        "import numpy as np\n"
        "def host_fetch(x):\n"
        "    return np.asarray(x)\n"
    )
    assert lint_source(src) == []


def test_serve_protocol_functions_are_trace_roots():
    src = (
        "import numpy as np\n"
        "def serve_step(params, cache, tokens):\n"
        "    return np.asarray(tokens)\n"
    )
    assert _codes(lint_source(src)) == ["FF101"]
    # ...but serve_debug_activations is eager by design
    src2 = (
        "import numpy as np\n"
        "def serve_debug_activations(params, cache, tokens):\n"
        "    return np.asarray(tokens)\n"
    )
    assert lint_source(src2) == []


def test_engine_jit_chokepoint_marks_traced():
    """Functions handed to the engine's self._jit sanitizer chokepoint
    count as traced — the refactor must not blind the lint."""
    src = (
        "import numpy as np\n"
        "class E:\n"
        "    def g(self):\n"
        "        def step(params, cache):\n"
        "            return np.asarray(params)\n"
        "        self._steps['k'] = self._jit(step, key='k',"
        " donate_argnums=(1,))\n"
    )
    assert _codes(lint_source(src)) == ["FF101"]


# ---------------------------------------------------------------------------
# FF102 tracer-control-flow


def test_tracer_control_flow_if():
    src = (
        "import jax\nimport jax.numpy as jnp\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    if jnp.any(x > 0):\n"
        "        x = x + 1\n"
        "    return x\n"
    )
    assert _codes(lint_source(src)) == ["FF102"]


def test_tracer_control_flow_static_branch_ok():
    src = (
        "import jax\n"
        "@jax.jit\n"
        "def f(x, mask=None):\n"
        "    if mask is None:\n"
        "        x = x + 1\n"
        "    return x\n"
    )
    assert lint_source(src) == []


# ---------------------------------------------------------------------------
# FF103 weak-dtype


def test_weak_dtype_flags_bare_asarray():
    src = "import jax.numpy as jnp\nx = jnp.asarray([1, 2])\n"
    assert _codes(lint_source(src)) == ["FF103"]


def test_weak_dtype_ok_with_dtype():
    src = (
        "import jax.numpy as jnp\n"
        "a = jnp.asarray([1, 2], dtype=jnp.int32)\n"
        "b = jnp.asarray([1, 2], jnp.int32)\n"   # positional dtype
        "c = jnp.asarray(jnp.zeros((2,)))\n"      # already a jax value
    )
    assert lint_source(src) == []


# ---------------------------------------------------------------------------
# FF104 unordered-iteration


def test_unordered_iteration_set_literal():
    src = (
        "import jax\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    for s in {1, 2, 3}:\n"
        "        x = x + s\n"
        "    return x\n"
    )
    assert _codes(lint_source(src)) == ["FF104"]


def test_unordered_iteration_list_ok():
    src = (
        "import jax\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    for s in [1, 2, 3]:\n"
        "        x = x + s\n"
        "    return x\n"
    )
    assert lint_source(src) == []


# ---------------------------------------------------------------------------
# FF105 missing-donation


def test_missing_donation_on_cache_param():
    src = (
        "import jax\n"
        "def step(params, cache, x):\n"
        "    return cache\n"
        "f = jax.jit(step)\n"
    )
    assert _codes(lint_source(src)) == ["FF105"]


def test_missing_donation_ok_with_donate():
    src = (
        "import jax\n"
        "def step(params, cache, x):\n"
        "    return cache\n"
        "f = jax.jit(step, donate_argnums=(1,))\n"
    )
    assert lint_source(src) == []


def test_missing_donation_cache_hook_attribute():
    src = "import jax\nf = jax.jit(model.commit_kv_paged)\n"
    assert _codes(lint_source(src)) == ["FF105"]


# ---------------------------------------------------------------------------
# FF106 static-hashability


def test_static_hashability_list_default():
    src = (
        "import jax, functools\n"
        "@functools.partial(jax.jit, static_argnames=('shape',))\n"
        "def g(x, shape=[1, 2]):\n"
        "    return x\n"
    )
    assert _codes(lint_source(src)) == ["FF106"]


def test_static_hashability_tuple_ok():
    src = (
        "import jax, functools\n"
        "@functools.partial(jax.jit, static_argnames=('shape',))\n"
        "def g(x, shape=(1, 2)):\n"
        "    return x\n"
    )
    assert lint_source(src) == []


def test_static_hashability_argnums():
    src = (
        "import jax\n"
        "def g(x, opts={}):\n"
        "    return x\n"
        "f = jax.jit(g, static_argnums=(1,))\n"
    )
    assert _codes(lint_source(src)) == ["FF106"]


# ---------------------------------------------------------------------------
# suppressions


def test_suppression_same_line():
    src = (
        "import jax.numpy as jnp\n"
        "x = jnp.asarray([1])  # ffcheck: disable=FF103 -- test fixture\n"
    )
    assert lint_source(src) == []


def test_suppression_by_slug_and_line_above():
    src = (
        "import jax.numpy as jnp\n"
        "# ffcheck: disable=weak-dtype -- dtype pinned upstream\n"
        "x = jnp.asarray([1])\n"
    )
    assert lint_source(src) == []


def test_suppression_file_level_and_all():
    src = (
        "# ffcheck: disable-file=FF103\n"
        "import jax.numpy as jnp\n"
        "x = jnp.asarray([1])\n"
        "y = jnp.asarray([2])\n"
    )
    assert lint_source(src) == []
    src_all = (
        "import jax.numpy as jnp\n"
        "x = jnp.asarray([1])  # ffcheck: disable=all\n"
    )
    assert lint_source(src_all) == []


def test_suppression_wrong_rule_does_not_hide():
    src = (
        "import jax.numpy as jnp\n"
        "x = jnp.asarray([1])  # ffcheck: disable=FF101\n"
    )
    assert _codes(lint_source(src)) == ["FF103"]


def test_suppression_reason_parsing():
    lines, file_rules = parse_suppressions(
        "x = 1  # ffcheck: disable=FF101,host-sync -- because reasons\n"
    )
    assert lines[1] == {"FF101", "host-sync"}
    assert file_rules == set()


def test_with_suppressed_reports_everything():
    src = (
        "import jax.numpy as jnp\n"
        "x = jnp.asarray([1])  # ffcheck: disable=FF103 -- hidden\n"
    )
    assert _codes(lint_source(src, with_suppressed=True)) == ["FF103"]


# ---------------------------------------------------------------------------
# meta: the analyzer must actually SEE the engine's traced surface


def test_engine_nested_steps_are_traced():
    """engine.py's nested `step` closures (jitted via self._jit under
    one shared name) must be in the traced set — otherwise the
    host-sync/control-flow rules silently stop covering the hot path."""
    path = os.path.join(REPO, "flexflow_tpu", "serve", "engine.py")
    ctx = FileContext(path, open(path).read())
    traced_names = {fn.name for fn in ctx.traced}
    assert "step" in traced_names, traced_names
    assert "speculate" in traced_names, traced_names


def test_model_serve_protocol_is_traced():
    path = os.path.join(REPO, "flexflow_tpu", "models", "llama.py")
    ctx = FileContext(path, open(path).read())
    traced_names = {fn.name for fn in ctx.traced}
    for name in ("serve_step", "serve_step_paged", "commit_kv_paged",
                 "copy_page_kv", "forward"):
        assert name in traced_names, (name, sorted(traced_names))
    assert "serve_debug_activations" not in traced_names


def test_syntax_error_reported_not_crashed(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text("def f(:\n")
    findings = lint_paths([str(bad)])
    assert [f.rule for f in findings] == ["FF000"]


# ---------------------------------------------------------------------------
# FF109 wall-clock-in-step-logic

CONTRACT_PATH = "flexflow_tpu/serve/cluster/health.py"


def test_wall_clock_flagged_in_contract_files():
    src = (
        "import time\n"
        "def decide():\n"
        "    return time.time()\n"
    )
    assert _codes(lint_source(src, path=CONTRACT_PATH)) == ["FF109"]


def test_wall_clock_sleep_and_monotonic_flagged():
    src = (
        "import time\n"
        "def f():\n"
        "    time.sleep(0.1)\n"
        "    return time.monotonic()\n"
    )
    assert _codes(lint_source(src, path=CONTRACT_PATH)) == [
        "FF109", "FF109",
    ]


def test_wall_clock_argless_datetime_now_flagged():
    src = (
        "from datetime import datetime, timezone\n"
        "def f():\n"
        "    a = datetime.now()\n"
        "    b = datetime.now(timezone.utc)\n"  # tz-carrying: not flagged
        "    return a, b\n"
    )
    assert _codes(lint_source(src, path=CONTRACT_PATH)) == ["FF109"]


def test_wall_clock_perf_counter_allowed():
    src = (
        "import time\n"
        "def measure():\n"
        "    return time.perf_counter()\n"
    )
    assert lint_source(src, path=CONTRACT_PATH) == []


def test_wall_clock_ok_outside_contract_set():
    src = (
        "import time\n"
        "def f():\n"
        "    return time.time()\n"
    )
    assert lint_source(src, path="flexflow_tpu/serve/engine.py") == []


def test_wall_clock_suppression():
    src = (
        "import time\n"
        "def f():\n"
        "    # ffcheck: disable=FF109 -- test fixture\n"
        "    time.sleep(1)\n"
    )
    assert lint_source(src, path=CONTRACT_PATH) == []


# ---------------------------------------------------------------------------
# FF110 unguarded-shared-state


def _threaded_class(init_extra="", loop_body="", read_body=""):
    return (
        "import threading\n"
        "class T:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        f"{init_extra}"
        "    def start(self):\n"
        "        threading.Thread(target=self._loop).start()\n"
        "    def _loop(self):\n"
        f"{loop_body}"
        "    def read(self):\n"
        f"{read_body}"
    )


def test_unguarded_shared_attr_flagged():
    src = _threaded_class(
        init_extra="        self._q = []\n",
        loop_body="        self._q.append(1)\n",
        read_body="        return len(self._q)\n",
    )
    assert _codes(lint_source(src)) == ["FF110"]


def test_guarded_registry_inline_clean():
    src = _threaded_class(
        init_extra="        self._q = []  # ffcheck: guarded-by=_lock\n",
        loop_body=(
            "        with self._lock:\n"
            "            self._q.append(1)\n"
        ),
        read_body=(
            "        with self._lock:\n"
            "            return len(self._q)\n"
        ),
    )
    assert lint_source(src) == []


def test_guarded_registry_bulk_form():
    src = _threaded_class(
        init_extra=(
            "        # ffcheck: guarded-by[_lock]=_q\n"
            "        self._q = []\n"
        ),
        loop_body=(
            "        with self._lock:\n"
            "            self._q.append(1)\n"
        ),
        read_body=(
            "        with self._lock:\n"
            "            return len(self._q)\n"
        ),
    )
    assert lint_source(src) == []


def test_registered_attr_scope_violation_flagged():
    src = _threaded_class(
        init_extra="        self._q = []  # ffcheck: guarded-by=_lock\n",
        loop_body=(
            "        with self._lock:\n"
            "            self._q.append(1)\n"
        ),
        read_body="        return len(self._q)\n",  # no lock held
    )
    findings = lint_source(src)
    assert _codes(findings) == ["FF110"]
    assert "outside a `with _lock:` scope" in findings[0].message


def test_locked_suffix_method_exempt():
    src = (
        "import threading\n"
        "class T:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self._q = []  # ffcheck: guarded-by=_lock\n"
        "    def start(self):\n"
        "        threading.Thread(target=self._loop).start()\n"
        "    def _loop(self):\n"
        "        with self._lock:\n"
        "            self._drain_locked()\n"
        "    def _drain_locked(self):\n"
        "        self._q.append(1)\n"
        "    def read(self):\n"
        "        with self._lock:\n"
        "            return len(self._q)\n"
    )
    assert lint_source(src) == []


def test_requires_lock_comment_exempt():
    src = _threaded_class(
        init_extra="        self._q = []  # ffcheck: guarded-by=_lock\n",
        loop_body=(
            "        with self._lock:\n"
            "            self._q.append(1)\n"
        ),
        read_body="        return len(self._q)\n",
    ).replace(
        "    def read(self):",
        "    # ffcheck: requires-lock=_lock\n    def read(self):",
    )
    assert lint_source(src) == []


def test_base_class_registry_binds_for_subclass():
    """A guarded-by comment on a BASE initializer line must register the
    attribute for subclass views too (the Transport hierarchy keeps
    counters on the base, threads on the subclass)."""
    src = (
        "import threading\n"
        "class Base:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self.n = 0  # ffcheck: guarded-by=_lock\n"
        "    def bump(self):\n"
        "        with self._lock:\n"
        "            self.n += 1\n"
        "class Sub(Base):\n"
        "    def __init__(self):\n"
        "        super().__init__()\n"
        "    def start(self):\n"
        "        threading.Thread(target=self._loop).start()\n"
        "    def _loop(self):\n"
        "        self.bump()\n"
    )
    assert lint_source(src) == []


# ---------------------------------------------------------------------------
# FF111 held-lock-blocking-call

from flexflow_tpu.analysis.rules.held_lock_blocking import (  # noqa: E402
    analyze_lock_order,
    find_order_cycles,
)


def test_blocking_call_under_lock_flagged():
    src = (
        "import threading\n"
        "class S:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "    def send(self, sock, data):\n"
        "        with self._lock:\n"
        "            sock.sendall(data)\n"
    )
    findings = lint_source(src)
    assert _codes(findings) == ["FF111"]
    assert "sendall" in findings[0].message


def test_transitively_blocking_callee_flagged():
    src = (
        "import socket\nimport threading\n"
        "class S:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "    def _dial(self):\n"
        "        return socket.create_connection(('h', 1))\n"
        "    def send(self):\n"
        "        with self._lock:\n"
        "            self._dial()\n"
    )
    findings = lint_source(src)
    assert _codes(findings) == ["FF111"]
    assert "blocks transitively" in findings[0].message


def test_blocking_outside_lock_ok():
    src = (
        "def send(sock, data):\n"
        "    sock.sendall(data)\n"
    )
    assert lint_source(src) == []


def test_non_lock_with_scope_ok():
    src = (
        "def f(path, sock):\n"
        "    with open(path) as fh:\n"
        "        sock.sendall(fh.read())\n"
    )
    assert lint_source(src) == []


def test_held_lock_suppression():
    src = (
        "import threading\n"
        "_LOCK = threading.Lock()\n"
        "def f(sock, data):\n"
        "    with _LOCK:\n"
        "        # ffcheck: disable=FF111 -- test fixture\n"
        "        sock.sendall(data)\n"
    )
    assert lint_source(src) == []


# ---------------------------------------------------------------------------
# lock-acquisition-order graph


def test_lock_order_inversion_detected():
    src = (
        "import threading\n"
        "A_LOCK = threading.Lock()\n"
        "B_LOCK = threading.Lock()\n"
        "def f():\n"
        "    with A_LOCK:\n"
        "        with B_LOCK:\n"
        "            pass\n"
        "def g():\n"
        "    with B_LOCK:\n"
        "        with A_LOCK:\n"
        "            pass\n"
    )
    edges = analyze_lock_order({"inv.py": src})
    assert ("A_LOCK", "B_LOCK") in edges and ("B_LOCK", "A_LOCK") in edges
    cycles = find_order_cycles(edges)
    assert len(cycles) == 1
    assert set(cycles[0]) == {"A_LOCK", "B_LOCK"}


def test_lock_order_cross_file_dispatch_edge():
    """A call matched by NAME across files pulls the callee's locks
    into the held scope — the loopback-dispatch → server-core pattern."""
    caller = (
        "import threading\n"
        "DISPATCH_LOCK = threading.Lock()\n"
        "def run(core, req):\n"
        "    with DISPATCH_LOCK:\n"
        "        core.dispatch(req)\n"
    )
    callee = (
        "import threading\n"
        "class Core:\n"
        "    def __init__(self):\n"
        "        self._inner_lock = threading.Lock()\n"
        "    def dispatch(self, req):\n"
        "        with self._inner_lock:\n"
        "            return req\n"
    )
    edges = analyze_lock_order({"a.py": caller, "b.py": callee})
    assert ("DISPATCH_LOCK", "Core._inner_lock") in edges
    assert find_order_cycles(edges) == []


def test_repo_lock_order_acyclic_and_expected_edges():
    """The real corpus is acyclic AND contains the two known-good
    ordering edges (writer-lock → stats, loopback-dispatch →
    server-core) — if these vanish, the analysis went blind, not clean."""
    cluster = os.path.join(REPO, "flexflow_tpu", "serve", "cluster")
    paths = [os.path.join(cluster, f)
             for f in ("transport.py", "server.py", "remote.py")]
    sources = {p: open(p).read() for p in paths}
    edges = analyze_lock_order(sources)
    assert find_order_cycles(edges) == []
    assert ("SocketTransport._lock", "_STATS_LOCK") in edges
    assert (
        "_LOOPBACK_DISPATCH_LOCK", "ReplicaServerCore._dispatch_lock"
    ) in edges


# ---------------------------------------------------------------------------
# wire-protocol drift checker

from flexflow_tpu.analysis.protocol import (  # noqa: E402
    SERVER_ONLY_METHODS,
    check_protocol_drift,
    diff_protocol,
    server_dispatch_table,
)

_DRIFT_SERVER = (
    "class ReplicaServerCore:\n"
    "    def _envelope(self, **kw):\n"
    "        return {}\n"
    "    def _m_step(self, args):\n"
    "        return self._envelope(progressed=True)\n"
    "    def _m_submit(self, args):\n"
    "        rid = args['rid']\n"
    "        return {'rid': rid}\n"
    "    def _m_hello(self, args):\n"
    "        return {}\n"
    "    def _m_orphan(self, args):\n"
    "        return {}\n"
)


def test_drift_checker_flags_skew():
    client = (
        "class RemoteReplica:\n"
        "    def a(self):\n"
        "        res = self._rpc('step', {})\n"
        "        return res['missing_key']\n"
        "    def b(self):\n"
        "        return self._rpc('submit', {'wrong': 1})\n"
        "    def c(self):\n"
        "        self._rpc('gone', {})\n"
    )
    problems = "\n".join(
        diff_protocol(_DRIFT_SERVER, {"client.py": client})
    )
    assert "no _m_gone handler" in problems
    assert "omits required arg(s) ['rid']" in problems
    assert "passes arg(s) ['wrong']" in problems
    assert "requires response key(s) ['missing_key']" in problems
    assert "_m_orphan has no client call site" in problems
    # hello is server-only by design: never reported
    assert "_m_hello" not in problems


def test_drift_checker_clean_on_matched_pair():
    client = (
        "class RemoteReplica:\n"
        "    def a(self):\n"
        "        res = self._rpc('step', {})\n"
        "        return res['progressed']\n"
        "    def b(self):\n"
        "        return self._rpc('submit', {'rid': 1})['rid']\n"
        "    def c(self):\n"
        "        self._rpc('orphan', {})\n"
    )
    assert diff_protocol(_DRIFT_SERVER, {"client.py": client}) == []


def test_repo_protocol_drift_clean():
    cluster = os.path.join(REPO, "flexflow_tpu", "serve", "cluster")
    assert check_protocol_drift(
        os.path.join(cluster, "server.py"),
        [os.path.join(cluster, "remote.py")],
    ) == []


def test_dispatch_table_covers_runtime_handlers():
    """Meta-guard for the drift checker itself: the statically scraped
    dispatch table must equal the runtime ``_m_*`` method set of
    ReplicaServerCore — if the AST scrape goes blind (class renamed,
    handlers defined dynamically), this fails before the drift check
    silently passes on an empty table."""
    from flexflow_tpu.serve.cluster.server import ReplicaServerCore

    path = os.path.join(
        REPO, "flexflow_tpu", "serve", "cluster", "server.py"
    )
    table = server_dispatch_table(open(path).read())
    runtime = {
        name[3:] for name in dir(ReplicaServerCore)
        if name.startswith("_m_")
    }
    assert set(table) == runtime and runtime, (set(table), runtime)
    assert SERVER_ONLY_METHODS <= runtime


def test_fixture_corpus_lints_clean():
    """The premerge-gate-16 fixture corpus (tests/fixtures/ffcheck/)
    exercises every FF110 registry form and FF109/FF111 suppression —
    a suppression-parser or registry regression surfaces here first."""
    fixtures = os.path.join(REPO, "tests", "fixtures", "ffcheck")
    findings = lint_paths([fixtures])
    assert not findings, "\n".join(f.format() for f in findings)
